"""Fused GEMM-ReduceScatter (tensor-parallel row-linear forward).

TPU-native redesign of the reference's GEMM-RS
(python/triton_dist/kernels/nvidia/gemm_reduce_scatter.py: producer GEMM
notifies per-tile barriers :122-285, ``gemm_rs_op`` :508; ring reduce
reduce_scatter.py:674-826) and of the fused GEMM-AllReduce
(gemm_allreduce.py, H800 path).

Math: A is column-sharded ((M, K/w) per device), B is row-sharded
((K/w, N) per device). Each device's partial ``A_local @ B_local`` must be
summed across devices; the result is row-scattered (GEMM-RS) or replicated
(GEMM-AR).

Fusion: one Pallas kernel computes the partial GEMM *chunk by chunk in ring
order* — the M-chunk a device must forward first is computed first (the
analog of the reference's rank-rotated producer tile swizzle,
gemm_rs_threadblock_swizzle.py) — and each chunk's ring hop overlaps the
next chunk's MXU work. GEMM-AR appends a ring all-gather of the reduced
chunks (two-shot AllReduce epilogue, reference gemm_allreduce.py).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.resilience import resilient
from triton_dist_tpu.ops.common import (
    DEFAULT_VMEM_BUDGET,
    HARD_FOOTPRINT_CAP,
    TUNED_VMEM_BUDGET,
    any_spec,
    cap_config_tiers,
    comm_params,
    nestable_shard_map,
    record_comm,
    record_overlap,
    resolve_interpret,
    resolve_ring_dirs,
    sync_interpret)


def _pick_block(total: int, want: int) -> int:
    for cand in (want, 512, 256, 128):
        if cand <= total and total % cand == 0:
            return cand
    return total


# Shape-keyed tuned configs (reference get_auto_triton_config,
# moe_reduce_rs.py:553 + autotuner.py).
_TUNED: dict[tuple, dict] = {}


def _hbm_nb_footprint(bm: int, bn: int, k_loc: int, itemsize: int) -> int:
    """VMEM bytes of the N-blocked hbm kernel: 2 A tiles (bm, K_loc) +
    2 B panels (K_loc, bn) + 2 recv tiles + 2 C stages (bm, bn)."""
    return itemsize * (2 * bm * k_loc + 2 * k_loc * bn + 4 * bm * bn)


def gemm_rs_configs(m: int, rows: int, k_loc: int, n: int, itemsize: int,
                    world: int,
                    vmem_budget: int = DEFAULT_VMEM_BUDGET,
                    tier_caps: bool = True) -> list[dict]:
    """Candidate config table for the fused GEMM-RS, ordered best-first.
    Every entry point (default, autotune) consults this table so an
    infeasible default can never reach the compiler (BENCH_r02).
    ``tier_caps=False`` returns the full feasible space for the
    autotune path's cost-model pruning (docs/autotuner.md)."""
    vmem_cfgs: list[dict] = []
    vmem_fp = itemsize * (m * k_loc + k_loc * n + rows * n
                          + 2 * max(world - 1, 1) * rows * n)
    if vmem_fp <= vmem_budget:
        vmem_cfgs.append({"variant": "vmem"})
    # N-blocked resident-B kernel (B read once per chunk, full-K dots).
    # Large tiles appear in both tiers; the aggressive tier is
    # concatenated LAST so defaults never pick it — see ag_gemm_configs
    # for the tier rationale and HARD_FOOTPRINT_CAP sizing.
    hbm_budget: list[dict] = []
    aggressive: list[dict] = []
    for bn in (2048, 1024, 512, 256, 128):
        if bn > n or n % bn:
            continue
        for bm in (1024, 512, 256, 128):
            if bm > rows or rows % bm:
                continue
            fp = _hbm_nb_footprint(bm, bn, k_loc, itemsize)
            if fp <= vmem_budget:
                hbm_budget.append({"variant": "hbm", "block_m": bm,
                                   "block_n": bn})
            elif fp <= HARD_FOOTPRINT_CAP:
                aggressive.append({"variant": "hbm", "block_m": bm,
                                   "block_n": bn})
    # k-tiled fallback (huge K_loc) — OUTSIDE the tier cap: entry-point
    # clamps re-filter to these, so pruning must never drop them
    # (review r5l finding 1).
    kt_cfgs: list[dict] = []
    for bm in (128, 256, 512):
        if bm > rows:
            continue
        for bk in (256, 512):
            if bk > k_loc:
                continue
            fp = (2 * bm * bk + 2 * bk * n) * itemsize \
                + bm * n * (4 + 3 * itemsize)
            if fp <= vmem_budget:
                kt_cfgs.append({"variant": "hbm_kt", "block_m": bm,
                                "block_k": bk})
    if tier_caps:
        cfgs = (vmem_cfgs
                + cap_config_tiers(hbm_budget, [], n_budget=4)
                + kt_cfgs[:2]
                + cap_config_tiers([], aggressive))
    else:
        cfgs = vmem_cfgs + hbm_budget + kt_cfgs + aggressive
    # Last resort: shape-CLAMPED k-tiled blocks (see ag_gemm_configs —
    # an unclamped literal yields k_tiles = 0 on tiny shards).
    return cfgs or [{"variant": "hbm_kt",
                     "block_m": _pick_block(rows, 128),
                     "block_k": _pick_block(k_loc, 256)}]


def _autotune_gemm_rs(a, b, ctx, key, all_gather_epilogue):
    """Candidates are the full feasible table (TUNED_VMEM_BUDGET tier
    boundary — the sweep's per-config failure isolation makes
    aggressive tiles safe to list without a global budget raise),
    cost-model pruned before any Mosaic compile is paid."""
    from triton_dist_tpu.tools.autotuner import autotune, record_prune
    from triton_dist_tpu.tools import perf_model as _pm

    m = a.shape[0]
    world = ctx.world_size
    rows = m // world
    k_loc = a.shape[1] // world
    n = b.shape[1]
    item = a.dtype.itemsize
    dirs = resolve_ring_dirs(ctx.ring_dirs)
    cfgs = gemm_rs_configs(m, rows, k_loc, n, item, world,
                           max(ctx.vmem_budget, TUNED_VMEM_BUDGET),
                           tier_caps=False)
    if all_gather_epilogue:
        # The k-tiled fallback has no AG epilogue; the N-blocked hbm
        # kernel does (VERDICT r2 weak 8).
        cfgs = [c for c in cfgs if c["variant"] != "hbm_kt"] or cfgs[:1]
    cfgs, n_before = _pm.prune_configs(
        cfgs,
        lambda c: _pm.estimate_gemm_rs_cost(
            c, m=m, rows=rows, k_loc=k_loc, n=n, itemsize=item,
            world=world, ring_dirs=dirs).total_ms,
        always_keep=(None if all_gather_epilogue
                     else lambda c: c["variant"] == "hbm_kt"))
    record_prune("gemm_ar" if all_gather_epilogue else "gemm_rs",
                 n_before, len(cfgs))
    if len(cfgs) == 1:
        _TUNED[key] = cfgs[0]
        return cfgs[0]

    entry = gemm_ar if all_gather_epilogue else gemm_rs

    def make_fn(**cfg):
        ctx2 = dataclasses.replace(ctx, autotune=False,
                                   trust_blocks=True, **cfg)
        fn = jax.jit(lambda x, w: entry(x, w, ctx2, impl="pallas"))
        # Unique input per call: the tunneled device dedupes identical
        # computations, which would void the ranking.
        from triton_dist_tpu.runtime.utils import make_perturbed_runner
        return make_perturbed_runner(fn, a, b)

    result = autotune(make_fn, cfgs, key=f"gemm_rs:{key}", iters=8,
                      warmup_iters=2,
                      vet=lambda c: _pm.vet_vmem(
                          "gemm_ar" if all_gather_epilogue else
                          "gemm_rs", c, rows=rows, m=m, k_loc=k_loc,
                          n=n, itemsize=item, world=world))
    _TUNED[key] = result.config
    return result.config


@dataclasses.dataclass
class GEMMReduceScatterContext:
    """Analog of the reference's ``create_gemm_rs_context``
    (gemm_reduce_scatter.py): config only — symmetric staging buffers become
    kernel scratch."""
    mesh: Mesh
    axis: str = "tp"
    acc_dtype: jnp.dtype = jnp.float32
    interpret: bool | None = None
    # "vmem": whole operands resident (low latency); "hbm": N-blocked
    # resident-B-panel kernel (B read once per chunk, full-K MXU dots —
    # VERDICT r2 weak 4); "hbm_kt": k-tiled tile streaming (huge K_loc
    # fallback); "auto" picks by footprint.
    variant: str = "auto"
    block_k: int = 512
    block_m: int = 256
    block_n: int = 512
    # Soft budget for the auto choice / default clamp — sizing
    # rationale on the shared constant (ops/common.py).
    vmem_budget: int = DEFAULT_VMEM_BUDGET
    # Autotune (variant, blocks) on first eager call per shape
    # (reference ContextualAutoTuner + get_auto_triton_config,
    # moe_reduce_rs.py:553).
    autotune: bool = False
    # Ring directions for the fused RS schedule: 2 = bidirectional (the
    # two column halves of every travelling partial ride opposite
    # full-duplex ICI links, halving per-link bytes), 1 = the
    # unidirectional proven-on-chip fallback, 0 = consult TDT_RING_DIRS
    # (default 2).
    ring_dirs: int = 0
    # Honor block hints past the soft budget (up to HARD_FOOTPRINT_CAP);
    # set by the sweep / tuned-winner application — see
    # AllGatherGEMMContext.trust_blocks.
    trust_blocks: bool = False

    @property
    def world_size(self) -> int:
        return self.mesh.shape[self.axis]

    def resolve_variant(self, m: int, k_loc: int, n: int,
                        itemsize: int) -> str:
        if self.variant != "auto":
            return self.variant
        w = max(self.world_size, 1)
        rows = m // w
        # vmem kernel holds x + w + out + (w-1)*2 travelling chunks
        fp = itemsize * (m * k_loc + k_loc * n + rows * n
                         + 2 * max(w - 1, 1) * rows * n)
        return "vmem" if fp <= self.vmem_budget else "hbm"


def create_gemm_rs_context(mesh: Mesh | None = None, axis: str = "tp",
                           acc_dtype=jnp.float32,
                           interpret: bool | None = None
                           ) -> GEMMReduceScatterContext:
    if mesh is None:
        from triton_dist_tpu.runtime.dist import get_mesh
        mesh = get_mesh()
    return GEMMReduceScatterContext(mesh=mesh, axis=axis,
                                    acc_dtype=acc_dtype, interpret=interpret)


def _gemm_rs_kernel(x_ref, w_ref, o_ref, send_buf, recv_buf, send_sem,
                    recv_sem, *, axis: str, world: int, rows: int,
                    acc_dtype, all_gather_epilogue: bool,
                    dirs: int = 1, ag_sems=None):
    """Producer GEMM in ring order fused with ring reduce-scatter.

    Step s computes the partial for chunk (me-s-1) — exactly the chunk this
    device must forward at step s — adds the travelling partial received at
    step s-1, and sends. The send of step s overlaps the MXU work of step
    s+1. Per-step buffers/semaphores (see ops/reduce_scatter.py for the
    FIFO-reordering race this avoids).

    ``dirs=2``: every chunk's N columns split in half — the left half
    reduces on the rightward (forward) ring as above while the right
    half reduces on the mirrored leftward ring (chunk me+s+1 at step s)
    — so both full-duplex ICI directions carry half the bytes and the
    per-link RS time halves. Each half is still summed in identical
    ring order, only narrower."""
    me = lax.axis_index(axis)
    right = lax.rem(me + 1, world)
    left = lax.rem(me - 1 + world, world)
    n = w_ref.shape[1]
    nh = n // 2 if dirs == 2 else n
    cols = ((0, n),) if dirs == 1 else ((0, nh), (nh, n))

    def partial_chunk(idx, c0=0, c1=n):
        return jnp.dot(
            x_ref[pl.ds(idx * rows, rows), :],
            w_ref[:, pl.ds(c0, c1 - c0)],
            preferred_element_type=acc_dtype).astype(o_ref.dtype)

    if world == 1:
        o_ref[:] = partial_chunk(0)
        return

    dl.barrier_all(axis)

    def rs_copy(s, d):
        c0, c1 = cols[d]
        sl = pl.ds(c0, c1 - c0)
        return dl.remote_copy(send_buf.at[s, :, sl],
                              recv_buf.at[s, :, sl],
                              right if d == 0 else left,
                              send_sem.at[d, s], recv_sem.at[d, s],
                              axis=axis)

    def rs_step(s, _):
        for d, (c0, c1) in enumerate(cols):
            send_idx = (lax.rem(me - s - 1 + world, world) if d == 0
                        else lax.rem(me + s + 1, world))
            part = partial_chunk(send_idx, c0, c1)
            sl = pl.ds(c0, c1 - c0)

            @pl.when(s == 0)
            def _(part=part, sl=sl):
                send_buf[s, :, sl] = part

            @pl.when(s > 0)
            def _(part=part, sl=sl, d=d):
                rs_copy(jnp.maximum(s - 1, 0), d).wait_recv()
                send_buf[s, :, sl] = (
                    part + recv_buf[jnp.maximum(s - 1, 0), :, sl])

            rs_copy(s, d).start()
        return _

    lax.fori_loop(0, world - 1, rs_step, None)
    row0 = me * rows if all_gather_epilogue else 0
    for d, (c0, c1) in enumerate(cols):
        sl = pl.ds(c0, c1 - c0)
        rs_copy(world - 2, d).wait_recv()
        o_ref[pl.ds(row0, rows), sl] = (recv_buf[world - 2, :, sl]
                                        + partial_chunk(me, c0, c1))

    if all_gather_epilogue:
        ag_send_sem, ag_recv_sem = ag_sems

        def ag_copy(idx):
            return dl.remote_copy(
                o_ref.at[pl.ds(idx * rows, rows), :],
                o_ref.at[pl.ds(idx * rows, rows), :],
                right, ag_send_sem.at[idx], ag_recv_sem.at[idx], axis=axis)

        def ag_step(s, _):
            ag_copy(lax.rem(me - s + world, world)).start()
            ag_copy(lax.rem(me - s - 1 + world, world)).wait_recv()
            return _

        lax.fori_loop(0, world - 1, ag_step, None)

        def ag_drain(s, _):
            ag_copy(lax.rem(me - s + world, world)).wait_send()
            return _

        lax.fori_loop(0, world - 1, ag_drain, None)

    def drain(s, _):
        for d in range(len(cols)):
            rs_copy(s, d).wait_send()
        return _

    lax.fori_loop(0, world - 1, drain, None)


def _gemm_rs_hbm_nb_kernel(x_hbm, w_hbm, o_hbm, send_hbm, recv_hbm, a_tile,
                           b_panel, r_tile, c_stage, a_sem, b_sem, r_sem,
                           c_sem, send_sem, recv_sem, ag_send_sem,
                           ag_recv_sem, *, axis: str, world: int,
                           rows: int, k_loc: int, n: int, m_blk: int,
                           n_blk: int, acc_dtype, dirs: int = 1,
                           all_gather_epilogue: bool):
    """N-blocked HBM GEMM-RS/-AR: resident B panel, full-K MXU dots.

    Ring-ordered producer schedule as ``_gemm_rs_kernel`` (chunk (me-s-1)
    computed at step s, travelling partial added, forwarded), but each
    chunk iterates (N-block, m-tile): the (K_loc, n_blk) B panel is DMA'd
    into VMEM once per (chunk, N-block) and every (m_blk, K_loc) A tile
    is one full-K ``jnp.dot`` — no k-accumulator (VERDICT r2 weak 4: the
    k-tiled kernel re-read the B panel per m-tile). With
    ``all_gather_epilogue`` the reduced chunks ride a ring AG over the
    HBM output — GEMM-AR at production N no longer needs VMEM residency
    (VERDICT r2 weak 8; reference gemm_allreduce.py).
    """
    me = lax.axis_index(axis)
    right = lax.rem(me + 1, world)
    left = lax.rem(me - 1 + world, world)
    m_tiles = rows // m_blk
    n_blocks = n // n_blk
    # Bidirectional split at N-block granularity: the forward (rightward)
    # ring reduces N-blocks [0, nbh), the backward ring [nbh, n_blocks)
    # — both full-duplex ICI directions carry about half the bytes.
    nbh = n_blocks // 2
    ranges = (((0, n_blocks),) if dirs == 1
              else ((0, nbh), (nbh, n_blocks)))

    def rs_copy(s, d):
        nb0, nb1 = ranges[d]
        sl = pl.ds(nb0 * n_blk, (nb1 - nb0) * n_blk)
        return dl.remote_copy(send_hbm.at[s, :, sl],
                              recv_hbm.at[s, :, sl],
                              right if d == 0 else left,
                              send_sem.at[d, s], recv_sem.at[d, s],
                              axis=axis)

    def chunk_gemm(chunk, s, dst, dst_row0, nb0=0, nb1=n_blocks):
        """Tiled partial for ``chunk`` over N-blocks [nb0, nb1); adds
        recv slab s-1 when s > 0; writes (rows, those columns) into
        ``dst`` starting at ``dst_row0``."""
        per = (nb1 - nb0) * m_tiles

        def mt_of(i):
            return lax.rem(i, m_tiles)

        def nb_of(i):
            return nb0 + i // m_tiles

        def a_dma(slot, i):
            return pltpu.make_async_copy(
                x_hbm.at[pl.ds(chunk * rows + mt_of(i) * m_blk, m_blk), :],
                a_tile.at[slot], a_sem.at[slot])

        def b_dma(slot, nb):
            return pltpu.make_async_copy(
                w_hbm.at[:, pl.ds(nb * n_blk, n_blk)], b_panel.at[slot],
                b_sem.at[slot])

        def r_dma(slot, i):
            return pltpu.make_async_copy(
                recv_hbm.at[jnp.maximum(s - 1, 0),
                            pl.ds(mt_of(i) * m_blk, m_blk),
                            pl.ds(nb_of(i) * n_blk, n_blk)],
                r_tile.at[slot], r_sem.at[slot])

        def c_dma(slot, i):
            return pltpu.make_async_copy(
                c_stage.at[slot],
                dst.at[pl.ds(dst_row0 + mt_of(i) * m_blk, m_blk),
                       pl.ds(nb_of(i) * n_blk, n_blk)],
                c_sem.at[slot])

        b_dma(0, nb0).start()
        a_dma(0, 0).start()

        @pl.when(s > 0)
        def _():
            r_dma(0, 0).start()

        def istep(i, _):
            slot = lax.rem(i, 2)
            nb = nb_of(i)
            bslot = lax.rem(i // m_tiles, 2)

            @pl.when(i + 1 < per)
            def _():
                a_dma(lax.rem(i + 1, 2), i + 1).start()

            @pl.when((i + 1 < per) & (s > 0))
            def _():
                r_dma(lax.rem(i + 1, 2), i + 1).start()

            @pl.when((lax.rem(i, m_tiles) == 0) & (nb + 1 < nb1))
            def _():
                b_dma(lax.rem(i // m_tiles + 1, 2), nb + 1).start()

            @pl.when(lax.rem(i, m_tiles) == 0)
            def _():
                b_dma(bslot, nb).wait()
            a_dma(slot, i).wait()

            out = jnp.dot(a_tile[slot], b_panel[bslot],
                          preferred_element_type=acc_dtype)

            @pl.when(i >= 2)
            def _():
                c_dma(slot, i - 2).wait()

            @pl.when(s > 0)
            def _():
                r_dma(slot, i).wait()
                c_stage[slot] = (out.astype(c_stage.dtype)
                                 + r_tile[slot]).astype(c_stage.dtype)

            @pl.when(s == 0)
            def _():
                c_stage[slot] = out.astype(c_stage.dtype)
            c_dma(slot, i).start()
            return _

        lax.fori_loop(0, per, istep, None)
        for i_last in range(max(0, per - 2), per):
            c_dma(i_last % 2, i_last).wait()

    if world == 1:
        chunk_gemm(jnp.int32(0), jnp.int32(0), o_hbm, 0)
        return

    dl.barrier_all(axis)

    def rs_step(s, _):
        for d, (nb0, nb1) in enumerate(ranges):
            send_idx = (lax.rem(me - s - 1 + world, world) if d == 0
                        else lax.rem(me + s + 1, world))

            @pl.when(s > 0)
            def _(d=d):
                rs_copy(jnp.maximum(s - 1, 0), d).wait_recv()
            chunk_gemm(send_idx, s, send_hbm.at[s], 0, nb0, nb1)
            rs_copy(s, d).start()
        return _

    lax.fori_loop(0, world - 1, rs_step, None)
    row0 = me * rows if all_gather_epilogue else 0
    for d, (nb0, nb1) in enumerate(ranges):
        rs_copy(world - 2, d).wait_recv()
        chunk_gemm(me, jnp.int32(world - 1), o_hbm, row0, nb0, nb1)

    if all_gather_epilogue:
        def ag_copy(idx):
            return dl.remote_copy(
                o_hbm.at[pl.ds(idx * rows, rows), :],
                o_hbm.at[pl.ds(idx * rows, rows), :],
                right, ag_send_sem.at[idx], ag_recv_sem.at[idx], axis=axis)

        def ag_step(s, _):
            ag_copy(lax.rem(me - s + world, world)).start()
            ag_copy(lax.rem(me - s - 1 + world, world)).wait_recv()
            return _

        lax.fori_loop(0, world - 1, ag_step, None)

        def ag_drain(s, _):
            ag_copy(lax.rem(me - s + world, world)).wait_send()
            return _

        lax.fori_loop(0, world - 1, ag_drain, None)

    def drain(s, _):
        for d in range(len(ranges)):
            rs_copy(s, d).wait_send()
        return _

    lax.fori_loop(0, world - 1, drain, None)


def _gemm_rs_hbm_kernel(x_hbm, w_hbm, o_hbm, send_hbm, recv_hbm, a_tile,
                        b_tile, r_tile, acc, c_stage, a_sem, b_sem, r_sem,
                        c_sem, send_sem, recv_sem, *, axis: str, world: int,
                        rows: int, k_loc: int, n: int, k_blk: int,
                        m_blk: int, acc_dtype):
    """HBM-resident GEMM-RS: operands and travelling partials never fully
    enter VMEM.

    Same ring-ordered producer schedule as ``_gemm_rs_kernel`` (chunk
    (me-s-1) computed at step s, travelling partial added, forwarded) but
    each chunk's GEMM streams (m_blk, k_blk)·(k_blk, N) tiles through
    double-buffered VMEM, and the per-step send/recv slabs live in HBM —
    the TPU shape of the reference's persistent tiled producer + staged
    reduce (gemm_reduce_scatter.py:122-285, reduce_scatter.py:285-504).
    """
    me = lax.axis_index(axis)
    right = lax.rem(me + 1, world)
    k_tiles = k_loc // k_blk
    m_tiles = rows // m_blk

    def a_dma(slot, row0, kt):
        return pltpu.make_async_copy(
            x_hbm.at[pl.ds(row0, m_blk), pl.ds(kt * k_blk, k_blk)],
            a_tile.at[slot], a_sem.at[slot])

    def b_dma(slot, kt):
        return pltpu.make_async_copy(
            w_hbm.at[pl.ds(kt * k_blk, k_blk), :], b_tile.at[slot],
            b_sem.at[slot])

    def c_dma(slot, dst, row0):
        return pltpu.make_async_copy(
            c_stage.at[slot], dst.at[pl.ds(row0, m_blk), :],
            c_sem.at[slot])

    def rs_copy(s):
        return dl.remote_copy(send_hbm.at[s], recv_hbm.at[s], right,
                              send_sem.at[s], recv_sem.at[s], axis=axis)

    def chunk_gemm(chunk, s, dst):
        """Tiled partial for ``chunk``; adds recv slab s-1 when s > 0;
        writes to dst (send slab or output)."""
        def m_step(mt, _):
            row0 = chunk * rows + mt * m_blk
            a_dma(0, row0, 0).start()
            b_dma(0, 0).start()

            @pl.when(s > 0)
            def _():
                pltpu.make_async_copy(
                    recv_hbm.at[jnp.maximum(s - 1, 0),
                                pl.ds(mt * m_blk, m_blk), :],
                    r_tile, r_sem).start()

            def k_step(kt, _):
                slot = lax.rem(kt, 2)

                @pl.when(kt + 1 < k_tiles)
                def _():
                    a_dma(lax.rem(kt + 1, 2), row0, kt + 1).start()
                    b_dma(lax.rem(kt + 1, 2), kt + 1).start()
                a_dma(slot, row0, kt).wait()
                b_dma(slot, kt).wait()
                partial = jnp.dot(a_tile[slot], b_tile[slot],
                                  preferred_element_type=acc_dtype)

                @pl.when(kt == 0)
                def _():
                    acc[:] = partial

                @pl.when(kt > 0)
                def _():
                    acc[:] = acc[:] + partial
                return _

            lax.fori_loop(0, k_tiles, k_step, None)

            cslot = lax.rem(mt, 2)

            @pl.when(mt >= 2)
            def _():
                c_dma(cslot, dst, mt * m_blk).wait()

            @pl.when(s > 0)
            def _():
                pltpu.make_async_copy(
                    recv_hbm.at[jnp.maximum(s - 1, 0),
                                pl.ds(mt * m_blk, m_blk), :],
                    r_tile, r_sem).wait()
                c_stage[cslot] = (acc[:].astype(c_stage.dtype)
                                  + r_tile[:]).astype(c_stage.dtype)

            @pl.when(s == 0)
            def _():
                c_stage[cslot] = acc[:].astype(c_stage.dtype)
            c_dma(cslot, dst, mt * m_blk).start()
            return _

        lax.fori_loop(0, m_tiles, m_step, None)
        for slot in range(min(2, m_tiles)):
            c_dma(slot, dst, 0).wait()

    if world == 1:
        chunk_gemm(jnp.int32(0), jnp.int32(0), o_hbm)
        return

    dl.barrier_all(axis)

    def rs_step(s, _):
        send_idx = lax.rem(me - s - 1 + world, world)

        @pl.when(s > 0)
        def _():
            rs_copy(jnp.maximum(s - 1, 0)).wait_recv()
        chunk_gemm(send_idx, s, send_hbm.at[s])
        rs_copy(s).start()
        return _

    lax.fori_loop(0, world - 1, rs_step, None)
    rs_copy(world - 2).wait_recv()
    chunk_gemm(me, jnp.int32(world - 1), o_hbm)

    def drain(s, _):
        rs_copy(s).wait_send()
        return _

    lax.fori_loop(0, world - 1, drain, None)


def _entry(a, b, ctx, impl, all_gather_epilogue):
    mesh, axis, world = ctx.mesh, ctx.axis, ctx.world_size
    m = a.shape[0]
    _, n = b.shape
    assert m % world == 0
    rows = m // world
    out_rows = m if all_gather_epilogue else rows
    out_spec = P() if all_gather_epilogue else P(axis)

    def run_xla():
        def body(xs, ws):
            part = jnp.dot(xs, ws, preferred_element_type=ctx.acc_dtype
                           ).astype(xs.dtype)
            if all_gather_epilogue:
                return lax.psum(part, axis)
            return lax.psum_scatter(part, axis, scatter_dimension=0,
                                    tiled=True)
        f = nestable_shard_map(body, mesh=mesh, in_specs=(P(None, axis), P(axis)),
                          out_specs=out_spec, check_vma=False)
        return f(a, b)

    if impl == "xla":
        return run_xla()

    interpret = resolve_interpret(ctx.interpret)
    k_loc = a.shape[1] // world

    if ctx.autotune:
        tune_key = (m, k_loc, n, str(a.dtype), world,
                    all_gather_epilogue)
        tuned = _TUNED.get(tune_key)
        if tuned is None and not isinstance(a, jax.core.Tracer):
            tuned = _autotune_gemm_rs(a, b, ctx, tune_key,
                                      all_gather_epilogue)
        if tuned is not None:
            ctx = dataclasses.replace(ctx, autotune=False,
                                      trust_blocks=True, **tuned)

    variant = ctx.resolve_variant(m, k_loc, n, a.dtype.itemsize)
    item = a.dtype.itemsize
    dirs = resolve_ring_dirs(ctx.ring_dirs)
    op_name = "gemm_ar" if all_gather_epilogue else "gemm_rs"

    def emit_overlap(cfg, eff_dirs):
        from triton_dist_tpu.tools import perf_model as _pm
        record_overlap(op_name, _pm.estimate_gemm_rs_cost(
            cfg, m=m, rows=rows, k_loc=k_loc, n=n, itemsize=item,
            world=world, ring_dirs=eff_dirs), world=world,
            dirs=eff_dirs)

    if variant == "hbm":
        # Clamp ctx hints to divisors + the VMEM budget; fall back to the
        # first feasible table config, then to the k-tiled kernel — an
        # infeasible default must never reach Mosaic (BENCH_r02).
        m_blk = _pick_block(rows, ctx.block_m)
        n_blk = _pick_block(n, ctx.block_n)
        clamp_at = (HARD_FOOTPRINT_CAP if ctx.trust_blocks
                    else ctx.vmem_budget)
        if _hbm_nb_footprint(m_blk, n_blk, k_loc, item) > clamp_at:
            # Re-filter to a conservative in-budget config. With
            # trust_blocks (sweep / tuned winner) the ceiling is the
            # hard COMPILE cap so the aggressive tier reaches Mosaic
            # (review r5i finding 1); defaults keep the soft budget.
            cand = [c for c in gemm_rs_configs(m, rows, k_loc, n, item,
                                               world, ctx.vmem_budget)
                    if c["variant"] == "hbm"
                    and _hbm_nb_footprint(c["block_m"], c["block_n"],
                                          k_loc, item) <= ctx.vmem_budget]
            if cand:
                m_blk, n_blk = cand[0]["block_m"], cand[0]["block_n"]
            else:
                variant = "hbm_kt"

    if variant == "hbm_kt" and all_gather_epilogue:
        # The k-tiled fallback has no AG epilogue (K_loc too large for
        # any resident B panel). Degrade to the XLA dot+psum rather than
        # fall through to the full-residency vmem kernel, whose scratch
        # would be infeasible at exactly these shapes (BENCH_r02 class:
        # an infeasible config must never reach Mosaic).
        return run_xla()

    if variant == "hbm":
        # Bidir needs >= 2 N-blocks to split between the directions.
        eff_dirs = dirs if (world > 1 and n // n_blk >= 2) else 1
        emit_overlap({"variant": "hbm", "block_m": m_blk,
                      "block_n": n_blk}, eff_dirs)
        kernel = functools.partial(
            _gemm_rs_hbm_nb_kernel, axis=axis, world=world, rows=rows,
            k_loc=k_loc, n=n, m_blk=m_blk, n_blk=n_blk,
            acc_dtype=ctx.acc_dtype, dirs=eff_dirs,
            all_gather_epilogue=all_gather_epilogue)

        def nb_body(xs, ws):
            out, *_ = pl.pallas_call(
                kernel,
                out_shape=(
                    jax.ShapeDtypeStruct((out_rows, n), a.dtype),
                    jax.ShapeDtypeStruct((max(world - 1, 1), rows, n),
                                         a.dtype),
                    jax.ShapeDtypeStruct((max(world - 1, 1), rows, n),
                                         a.dtype)),
                in_specs=[any_spec(), any_spec()],
                out_specs=(any_spec(),) * 3,
                scratch_shapes=[
                    pltpu.VMEM((2, m_blk, k_loc), a.dtype),
                    pltpu.VMEM((2, k_loc, n_blk), a.dtype),
                    pltpu.VMEM((2, m_blk, n_blk), a.dtype),
                    pltpu.VMEM((2, m_blk, n_blk), a.dtype),
                    pltpu.SemaphoreType.DMA((2,)),
                    pltpu.SemaphoreType.DMA((2,)),
                    pltpu.SemaphoreType.DMA((2,)),
                    pltpu.SemaphoreType.DMA((2,)),
                    pltpu.SemaphoreType.DMA((eff_dirs,
                                             max(world - 1, 1))),
                    pltpu.SemaphoreType.DMA((eff_dirs,
                                             max(world - 1, 1))),
                    pltpu.SemaphoreType.DMA((world,)),
                    pltpu.SemaphoreType.DMA((world,)),
                ],
                compiler_params=comm_params(collective_id=5, world=world),
                interpret=interpret,
            )(xs, ws)
            return out

        f = nestable_shard_map(nb_body, mesh=mesh,
                          in_specs=(P(None, axis), P(axis)),
                          out_specs=out_spec, check_vma=False)
        return sync_interpret(f(a, b), interpret)

    if variant == "hbm_kt" and not all_gather_epilogue and world >= 1:
        k_blk = _pick_block(k_loc, ctx.block_k)
        m_blk = _pick_block(rows, ctx.block_m)
        fp = (2 * m_blk * k_blk + 2 * k_blk * n) * item \
            + m_blk * n * (4 + 3 * item)
        if fp > ctx.vmem_budget:
            cand = [c for c in gemm_rs_configs(m, rows, k_loc, n, item,
                                               world, ctx.vmem_budget)
                    if c["variant"] == "hbm_kt"]
            if cand:
                m_blk, k_blk = cand[0]["block_m"], cand[0]["block_k"]
        # The k-tiled fallback keeps the proven unidirectional ring.
        emit_overlap({"variant": "hbm_kt", "block_m": m_blk,
                      "block_k": k_blk}, 1)
        kernel = functools.partial(
            _gemm_rs_hbm_kernel, axis=axis, world=world, rows=rows,
            k_loc=k_loc, n=n, k_blk=k_blk, m_blk=m_blk,
            acc_dtype=ctx.acc_dtype)

        def hbm_body(xs, ws):
            out, *_ = pl.pallas_call(
                kernel,
                out_shape=(
                    jax.ShapeDtypeStruct((rows, n), a.dtype),
                    jax.ShapeDtypeStruct((max(world - 1, 1), rows, n),
                                         a.dtype),
                    jax.ShapeDtypeStruct((max(world - 1, 1), rows, n),
                                         a.dtype)),
                in_specs=[any_spec(), any_spec()],
                out_specs=(any_spec(),) * 3,
                scratch_shapes=[
                    pltpu.VMEM((2, m_blk, k_blk), a.dtype),
                    pltpu.VMEM((2, k_blk, n), a.dtype),
                    pltpu.VMEM((m_blk, n), a.dtype),
                    pltpu.VMEM((m_blk, n), ctx.acc_dtype),
                    pltpu.VMEM((2, m_blk, n), a.dtype),
                    pltpu.SemaphoreType.DMA((2,)),
                    pltpu.SemaphoreType.DMA((2,)),
                    pltpu.SemaphoreType.DMA,
                    pltpu.SemaphoreType.DMA((2,)),
                    pltpu.SemaphoreType.DMA((max(world - 1, 1),)),
                    pltpu.SemaphoreType.DMA((max(world - 1, 1),)),
                ],
                compiler_params=comm_params(collective_id=5, world=world),
                interpret=interpret,
            )(xs, ws)
            return out

        f = nestable_shard_map(hbm_body, mesh=mesh,
                          in_specs=(P(None, axis), P(axis)),
                          out_specs=out_spec, check_vma=False)
        return sync_interpret(f(a, b), interpret)

    # vmem variant: the column split needs lane-aligned halves.
    eff_dirs = dirs if (world > 1 and n % 256 == 0) else 1
    emit_overlap({"variant": "vmem"}, eff_dirs)
    scratch = [pltpu.VMEM((world - 1, rows, n), a.dtype),
               pltpu.VMEM((world - 1, rows, n), a.dtype),
               pltpu.SemaphoreType.DMA((eff_dirs, world - 1)),
               pltpu.SemaphoreType.DMA((eff_dirs, world - 1))]
    if all_gather_epilogue:
        scratch += [pltpu.SemaphoreType.DMA((world,)),
                    pltpu.SemaphoreType.DMA((world,))]

        def kernel(x_ref, w_ref, o_ref, sb, rb, ss, rs, ags, agr):
            _gemm_rs_kernel(x_ref, w_ref, o_ref, sb, rb, ss, rs,
                            axis=axis, world=world, rows=rows,
                            acc_dtype=ctx.acc_dtype, dirs=eff_dirs,
                            all_gather_epilogue=True, ag_sems=(ags, agr))
    else:
        kernel = functools.partial(
            _gemm_rs_kernel, axis=axis, world=world, rows=rows,
            acc_dtype=ctx.acc_dtype, dirs=eff_dirs,
            all_gather_epilogue=False)

    def body(xs, ws):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((out_rows, n), a.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                      pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=scratch,
            compiler_params=comm_params(collective_id=5, world=world),
            interpret=interpret,
        )(xs, ws)

    f = nestable_shard_map(body, mesh=mesh, in_specs=(P(None, axis), P(axis)),
                      out_specs=out_spec, check_vma=False)
    return sync_interpret(f(a, b), interpret)


@resilient("gemm_rs", env_keys=("TDT_RING_DIRS",))
def gemm_rs(a: jax.Array, b: jax.Array,
            ctx: GEMMReduceScatterContext | None = None,
            impl: str = "pallas") -> jax.Array:
    """reduce_scatter(a @ b) over the axis (reference ``gemm_rs_op``
    gemm_reduce_scatter.py:508).

    a: (M, K) column-sharded; b: (K, N) row-sharded. Returns (M, N)
    row-sharded (device i holds rows [i*M/w, (i+1)*M/w))."""
    ctx = ctx or create_gemm_rs_context()
    record_comm("gemm_rs", a)   # the scattered partials' source operand
    return _entry(a, b, ctx, impl, all_gather_epilogue=False)


@resilient("gemm_ar", env_keys=("TDT_RING_DIRS",))
def gemm_ar(a: jax.Array, b: jax.Array,
            ctx: GEMMReduceScatterContext | None = None,
            impl: str = "pallas") -> jax.Array:
    """allreduce(a @ b): GEMM fused with two-shot AllReduce — the
    small-batch decode path (reference gemm_allreduce.py, e2e_dense.md
    GEMM-AR rows). Returns (M, N) replicated.

    M smaller than / not divisible by the world size (decode batches) is
    zero-padded to a ring-chunkable M and sliced back — the analog of the
    reference's tile-padded GEMM grids."""
    ctx = ctx or create_gemm_rs_context()
    record_comm("gemm_ar", a)
    m = a.shape[0]
    world = ctx.world_size
    if m % world != 0:
        pad = world - m % world
        a = jnp.concatenate(
            [a, jnp.zeros((pad, a.shape[1]), a.dtype)], axis=0)
        return _entry(a, b, ctx, impl, all_gather_epilogue=True)[:m]
    return _entry(a, b, ctx, impl, all_gather_epilogue=True)
