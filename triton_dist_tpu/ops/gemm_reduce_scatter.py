"""Fused GEMM-ReduceScatter (tensor-parallel row-linear forward).

TPU-native redesign of the reference's GEMM-RS
(python/triton_dist/kernels/nvidia/gemm_reduce_scatter.py: producer GEMM
notifies per-tile barriers :122-285, ``gemm_rs_op`` :508; ring reduce
reduce_scatter.py:674-826) and of the fused GEMM-AllReduce
(gemm_allreduce.py, H800 path).

Math: A is column-sharded ((M, K/w) per device), B is row-sharded
((K/w, N) per device). Each device's partial ``A_local @ B_local`` must be
summed across devices; the result is row-scattered (GEMM-RS) or replicated
(GEMM-AR).

Fusion: one Pallas kernel computes the partial GEMM *chunk by chunk in ring
order* — the M-chunk a device must forward first is computed first (the
analog of the reference's rank-rotated producer tile swizzle,
gemm_rs_threadblock_swizzle.py) — and each chunk's ring hop overlaps the
next chunk's MXU work. GEMM-AR appends a ring all-gather of the reduced
chunks (two-shot AllReduce epilogue, reference gemm_allreduce.py).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.ops.common import comm_params, resolve_interpret, sync_interpret


@dataclasses.dataclass
class GEMMReduceScatterContext:
    """Analog of the reference's ``create_gemm_rs_context``
    (gemm_reduce_scatter.py): config only — symmetric staging buffers become
    kernel scratch."""
    mesh: Mesh
    axis: str = "tp"
    acc_dtype: jnp.dtype = jnp.float32
    interpret: bool | None = None

    @property
    def world_size(self) -> int:
        return self.mesh.shape[self.axis]


def create_gemm_rs_context(mesh: Mesh | None = None, axis: str = "tp",
                           acc_dtype=jnp.float32,
                           interpret: bool | None = None
                           ) -> GEMMReduceScatterContext:
    if mesh is None:
        from triton_dist_tpu.runtime.dist import get_mesh
        mesh = get_mesh()
    return GEMMReduceScatterContext(mesh=mesh, axis=axis,
                                    acc_dtype=acc_dtype, interpret=interpret)


def _gemm_rs_kernel(x_ref, w_ref, o_ref, send_buf, recv_buf, send_sem,
                    recv_sem, *, axis: str, world: int, rows: int,
                    acc_dtype, all_gather_epilogue: bool,
                    ag_sems=None):
    """Producer GEMM in ring order fused with ring reduce-scatter.

    Step s computes the partial for chunk (me-s-1) — exactly the chunk this
    device must forward at step s — adds the travelling partial received at
    step s-1, and sends. The send of step s overlaps the MXU work of step
    s+1. Per-step buffers/semaphores (see ops/reduce_scatter.py for the
    FIFO-reordering race this avoids)."""
    me = lax.axis_index(axis)
    right = lax.rem(me + 1, world)

    def partial_chunk(idx):
        return jnp.dot(
            x_ref[pl.ds(idx * rows, rows), :], w_ref[:],
            preferred_element_type=acc_dtype).astype(o_ref.dtype)

    if world == 1:
        o_ref[:] = partial_chunk(0)
        return

    dl.barrier_all(axis)

    def rs_copy(s):
        return dl.remote_copy(send_buf.at[s], recv_buf.at[s], right,
                              send_sem.at[s], recv_sem.at[s], axis=axis)

    def rs_step(s, _):
        send_idx = lax.rem(me - s - 1 + world, world)
        part = partial_chunk(send_idx)

        @pl.when(s == 0)
        def _():
            send_buf[s] = part

        @pl.when(s > 0)
        def _():
            rs_copy(jnp.maximum(s - 1, 0)).wait_recv()
            send_buf[s] = part + recv_buf[jnp.maximum(s - 1, 0)]

        rs_copy(s).start()
        return _

    lax.fori_loop(0, world - 1, rs_step, None)
    rs_copy(world - 2).wait_recv()
    reduced = recv_buf[world - 2] + partial_chunk(me)

    if not all_gather_epilogue:
        o_ref[:] = reduced
    else:
        o_ref[pl.ds(me * rows, rows), :] = reduced
        ag_send_sem, ag_recv_sem = ag_sems

        def ag_copy(idx):
            return dl.remote_copy(
                o_ref.at[pl.ds(idx * rows, rows), :],
                o_ref.at[pl.ds(idx * rows, rows), :],
                right, ag_send_sem.at[idx], ag_recv_sem.at[idx], axis=axis)

        def ag_step(s, _):
            ag_copy(lax.rem(me - s + world, world)).start()
            ag_copy(lax.rem(me - s - 1 + world, world)).wait_recv()
            return _

        lax.fori_loop(0, world - 1, ag_step, None)

        def ag_drain(s, _):
            ag_copy(lax.rem(me - s + world, world)).wait_send()
            return _

        lax.fori_loop(0, world - 1, ag_drain, None)

    def drain(s, _):
        rs_copy(s).wait_send()
        return _

    lax.fori_loop(0, world - 1, drain, None)


def _entry(a, b, ctx, impl, all_gather_epilogue):
    mesh, axis, world = ctx.mesh, ctx.axis, ctx.world_size
    m = a.shape[0]
    _, n = b.shape
    assert m % world == 0
    rows = m // world
    out_rows = m if all_gather_epilogue else rows
    out_spec = P() if all_gather_epilogue else P(axis)

    if impl == "xla":
        def body(xs, ws):
            part = jnp.dot(xs, ws, preferred_element_type=ctx.acc_dtype
                           ).astype(xs.dtype)
            if all_gather_epilogue:
                return lax.psum(part, axis)
            return lax.psum_scatter(part, axis, scatter_dimension=0,
                                    tiled=True)
        f = jax.shard_map(body, mesh=mesh, in_specs=(P(None, axis), P(axis)),
                          out_specs=out_spec, check_vma=False)
        return f(a, b)

    interpret = resolve_interpret(ctx.interpret)
    scratch = [pltpu.VMEM((world - 1, rows, n), a.dtype),
               pltpu.VMEM((world - 1, rows, n), a.dtype),
               pltpu.SemaphoreType.DMA((world - 1,)),
               pltpu.SemaphoreType.DMA((world - 1,))]
    if all_gather_epilogue:
        scratch += [pltpu.SemaphoreType.DMA((world,)),
                    pltpu.SemaphoreType.DMA((world,))]

        def kernel(x_ref, w_ref, o_ref, sb, rb, ss, rs, ags, agr):
            _gemm_rs_kernel(x_ref, w_ref, o_ref, sb, rb, ss, rs,
                            axis=axis, world=world, rows=rows,
                            acc_dtype=ctx.acc_dtype,
                            all_gather_epilogue=True, ag_sems=(ags, agr))
    else:
        kernel = functools.partial(
            _gemm_rs_kernel, axis=axis, world=world, rows=rows,
            acc_dtype=ctx.acc_dtype, all_gather_epilogue=False)

    def body(xs, ws):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((out_rows, n), a.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                      pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=scratch,
            compiler_params=comm_params(collective_id=5, world=world),
            interpret=interpret,
        )(xs, ws)

    f = jax.shard_map(body, mesh=mesh, in_specs=(P(None, axis), P(axis)),
                      out_specs=out_spec, check_vma=False)
    return sync_interpret(f(a, b), interpret)


def gemm_rs(a: jax.Array, b: jax.Array,
            ctx: GEMMReduceScatterContext | None = None,
            impl: str = "pallas") -> jax.Array:
    """reduce_scatter(a @ b) over the axis (reference ``gemm_rs_op``
    gemm_reduce_scatter.py:508).

    a: (M, K) column-sharded; b: (K, N) row-sharded. Returns (M, N)
    row-sharded (device i holds rows [i*M/w, (i+1)*M/w))."""
    ctx = ctx or create_gemm_rs_context()
    return _entry(a, b, ctx, impl, all_gather_epilogue=False)


def gemm_ar(a: jax.Array, b: jax.Array,
            ctx: GEMMReduceScatterContext | None = None,
            impl: str = "pallas") -> jax.Array:
    """allreduce(a @ b): GEMM fused with two-shot AllReduce — the
    small-batch decode path (reference gemm_allreduce.py, e2e_dense.md
    GEMM-AR rows). Returns (M, N) replicated.

    M smaller than / not divisible by the world size (decode batches) is
    zero-padded to a ring-chunkable M and sliced back — the analog of the
    reference's tile-padded GEMM grids."""
    ctx = ctx or create_gemm_rs_context()
    m = a.shape[0]
    world = ctx.world_size
    if m % world != 0:
        pad = world - m % world
        a = jnp.concatenate(
            [a, jnp.zeros((pad, a.shape[1]), a.dtype)], axis=0)
        return _entry(a, b, ctx, impl, all_gather_epilogue=True)[:m]
    return _entry(a, b, ctx, impl, all_gather_epilogue=True)
