"""Fused AllGather-GEMM (tensor-parallel column-linear forward).

TPU-native redesign of the reference's flagship overlapped op
(python/triton_dist/kernels/nvidia/allgather_gemm.py: ``create_ag_gemm_context``
:489, ``ag_gemm`` :534, consumer GEMM that per-M-tile ``dl.wait``s on
per-rank ready flags :158-264, rank-rotated tile swizzle :221-229).

Math: A is row-sharded over the axis ((M/w, K) per device), B is
column-sharded ((K, N/w) per device). Every device computes
``C_local = allgather(A) @ B_local`` — full M rows of its N-columns.

The TPU design is a *collective matmul*: one Pallas kernel per device runs
the ring all-gather of A chunks and, as each chunk lands (semaphore wait —
the analog of the reference's per-rank ``dl.wait``), feeds it to the MXU.
The remote DMA of chunk s+1 overlaps the dot of chunk s. Consumption starts
with the device's own chunk, so compute order is naturally rank-rotated
(reference swizzle allgather_gemm.py:221-229).

``impl="xla"``: ``lax.all_gather`` + ``jnp.dot`` — the unfused golden
(XLA's latency-hiding scheduler may still overlap at coarse grain; it is
also the measuring stick for overlap efficiency, BASELINE.md north star).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.resilience import resilient
from triton_dist_tpu.ops.common import (
    DEFAULT_VMEM_BUDGET,
    HARD_FOOTPRINT_CAP,
    TUNED_VMEM_BUDGET,
    any_spec,
    cap_config_tiers,
    comm_params,
    maybe_noise,
    maybe_straggle,
    nestable_shard_map,
    record_comm,
    record_overlap,
    resolve_interpret,
    resolve_ring_dirs,
    ring_chunk_schedule,
    ring_hop_counts,
    sync_interpret)


@dataclasses.dataclass
class AllGatherGEMMContext:
    """Analog of ``AllGatherGEMMTensorParallelContext``
    (allgather_gemm.py:417-456): owns tuning params; the symmetric
    workspace/barrier allocation collapses into kernel buffers on TPU."""
    mesh: Mesh
    axis: str = "tp"
    # Dot accumulation dtype on the MXU.
    acc_dtype: jnp.dtype = jnp.float32
    interpret: bool | None = None
    # Return the gathered A alongside C (the reference reuses the AG
    # workspace for attention, tp_attn.py).
    return_gathered: bool = False
    # Kernel variant: "vmem" holds whole operands in VMEM (small shapes,
    # lowest latency); "hbm" keeps A/C in HBM, holds a (K, block_n) B
    # panel resident in VMEM and streams (block_m, K) A tiles — B is read
    # from HBM exactly once and every dot contracts the full K on the MXU
    # (VERDICT r2 weak 4: the round-2 k-tiled kernel re-DMA'd the whole B
    # panel per m-tile, ~16x minimal B traffic); "hbm_kt" is that k-tiled
    # kernel, kept for K too large for a resident panel; "auto" picks by
    # VMEM footprint.
    variant: str = "auto"
    # Tile sizes (auto-clamped to divisors and the VMEM budget; the entry
    # falls back to the first feasible ag_gemm_configs entry otherwise).
    block_k: int = 512
    block_m: int = 256
    block_n: int = 512
    # Soft VMEM budget for the auto choice and the default-path block
    # clamp (bytes) — sizing rationale on the shared constant.
    vmem_budget: int = DEFAULT_VMEM_BUDGET
    # Honor block hints past the soft budget (up to HARD_FOOTPRINT_CAP).
    # Set by the autotune sweep and tuned-winner application so the
    # config table's aggressive tier reaches Mosaic (review r5i finding
    # 1); the DEFAULT path keeps the conservative soft-budget clamp.
    trust_blocks: bool = False
    # Autotune (variant, block_m, block_k) on first *eager* call per
    # shape via tools.autotuner (reference ContextualAutoTuner +
    # matmul_get_configs, allgather_gemm.py:396); jitted calls reuse the
    # shape-keyed cache.
    autotune: bool = False
    # Ring directions for the fused AG schedule: 2 = bidirectional
    # (chunks travel the shorter way round, both full-duplex ICI links
    # active — the ops/allgather.py RING_BIDIR win the fused ops never
    # had), 1 = the unidirectional proven-on-chip fallback, 0 = consult
    # TDT_RING_DIRS (default 2).
    ring_dirs: int = 0
    # Correctness-debug injection (reference for_correctness sleeps
    # allgather_gemm.py:507-508 and straggler_option): see ops/common.py.
    straggler_option: tuple[int, int] | None = None
    for_correctness: bool = False

    @property
    def world_size(self) -> int:
        return self.mesh.shape[self.axis]

    def resolve_variant(self, m: int, k: int, n_tot: int,
                        itemsize: int) -> str:
        if self.variant != "auto":
            return self.variant
        # vmem kernel holds ag(M,K) + Bs(K,N) + Cs(M,N) + x(M/w,K)
        footprint = itemsize * (m * k + k * n_tot + m * n_tot
                                + (m // max(self.world_size, 1)) * k)
        return "vmem" if footprint <= self.vmem_budget else "hbm"


def create_ag_gemm_context(mesh: Mesh | None = None, axis: str = "tp",
                           acc_dtype=jnp.float32,
                           interpret: bool | None = None,
                           return_gathered: bool = False
                           ) -> AllGatherGEMMContext:
    if mesh is None:
        from triton_dist_tpu.runtime.dist import get_mesh
        mesh = get_mesh()
    return AllGatherGEMMContext(mesh=mesh, axis=axis, acc_dtype=acc_dtype,
                                interpret=interpret,
                                return_gathered=return_gathered)


def _make_ring(chunk_ref, me, axis: str, world: int, dirs: int,
               send_sem, recv_sem):
    """Ring bookkeeping for the rank-rotated AG consumption schedule,
    shared by every fused AG-GEMM kernel.

    ``chunk_ref(idx)`` returns the workspace slice of chunk ``idx``;
    semaphores are per (direction, chunk) — delivery is not FIFO, and a
    fast neighbor may run several hops ahead (same hazard note as
    ``ops/allgather._ring_ag_kernel``). With ``dirs=2`` the forward
    ring (rightward sends) carries chunks me-1..me-n_fwd and the
    backward ring (leftward) me+1..me+n_bwd, halving the hop count on
    the full-duplex ICI links; ``dirs=1`` reproduces the round-5
    proven unidirectional schedule exactly.

    Returns ``(chunk_of, advance, drain)``: ``chunk_of(s)`` is the
    chunk consumed at schedule position s; ``advance(s)`` waits for
    position s's arrival and keeps it travelling onward (position 0
    launches the local chunk both ways — each later hop then overlaps
    a whole chunk's compute); ``drain()`` waits out the send
    semaphores before the kernel retires.
    """
    right = lax.rem(me + 1, world)
    left = lax.rem(me - 1 + world, world)
    n_fwd, n_bwd = ring_hop_counts(world, dirs)

    def chunk_copy(idx, d):
        peer = jnp.where(jnp.asarray(d) == 1, left, right)
        ref = chunk_ref(idx)
        return dl.remote_copy(ref, ref, peer, send_sem.at[d, idx],
                              recv_sem.at[d, idx], axis=axis)

    def chunk_of(s):
        return ring_chunk_schedule(me, s, world, dirs)[0]

    def advance(s):
        if world == 1:
            return
        chunk, is_bwd, off = ring_chunk_schedule(me, s, world, dirs)
        s = jnp.asarray(s, jnp.int32)
        d = is_bwd.astype(jnp.int32)

        @pl.when(s == 0)
        def _():
            if n_fwd > 0:
                chunk_copy(me, 0).start()
            if n_bwd > 0:
                chunk_copy(me, 1).start()

        @pl.when((s > 0) & (s < world))
        def _():
            chunk_copy(chunk, d).wait_recv()   # the reference dl.wait
            onward = jnp.where(is_bwd, off < n_bwd, off < n_fwd)

            @pl.when(onward)
            def _():
                chunk_copy(chunk, d).start()

    def drain():
        if world == 1:
            return

        def wait_one(s, _):
            @pl.when(s < n_fwd)
            def _():
                chunk_copy(lax.rem(me - s + world, world), 0).wait_send()
            if n_bwd > 0:
                @pl.when(s < n_bwd)
                def _():
                    chunk_copy(lax.rem(me + s, world), 1).wait_send()
            return _

        lax.fori_loop(0, max(n_fwd, n_bwd), wait_one, None)

    return chunk_of, advance, drain


def _ag_gemm_kernel(x_ref, *rest, axis: str, world: int, rows: int,
                    acc_dtype, n_b: int, dirs: int = 1,
                    straggler_option=None,
                    for_correctness=False, interp=False):
    """Ring AG of A chunks fused with per-chunk GEMM(s).

    Per step: the chunk-boundary ``advance`` waits for the chunk's
    arrival and immediately keeps it travelling (DMA on ICI), then the
    MXU runs on it (overlap) — the wait is the reference's
    ``dl.wait(ready_ptr + rank, ...)`` (allgather_gemm.py:236). With
    ``dirs=2`` chunks ride both ICI directions (``_make_ring``).

    Supports ``n_b`` weight matrices sharing the gathered A (one AG feeding
    several GEMMs — the QKV / gate+up projections of a TP transformer
    layer, reference tp_attn.py wqkv concat / tp_mlp.py gate_up concat).
    On TPU separate B operands beat a concatenated one because each B keeps
    a clean column sharding."""
    w_refs = rest[:n_b]
    ag_ref = rest[n_b]
    c_refs = rest[n_b + 1:2 * n_b + 1]
    send_sem, recv_sem = rest[2 * n_b + 1:2 * n_b + 3]
    me = lax.axis_index(axis)

    ag_ref[pl.ds(me * rows, rows), :] = x_ref[:]
    if world > 1:
        dl.barrier_all(axis)
        maybe_straggle(straggler_option, axis, interp)
        maybe_noise(for_correctness, axis, world, salt=3, interpret=interp)

    def gemm_chunk(idx):
        for w_ref, c_ref in zip(w_refs, c_refs):
            c_ref[pl.ds(idx * rows, rows), :] = jnp.dot(
                ag_ref[pl.ds(idx * rows, rows), :], w_ref[:],
                preferred_element_type=acc_dtype).astype(c_ref.dtype)

    if world == 1:
        gemm_chunk(me)
        return

    chunk_of, advance, drain = _make_ring(
        lambda idx: ag_ref.at[pl.ds(idx * rows, rows), :], me, axis,
        world, dirs, send_sem, recv_sem)

    advance(0)

    def step(s, _):
        gemm_chunk(chunk_of(s))           # MXU on current chunk
        advance(s + 1)                    # next chunk: wait + forward
        return _

    lax.fori_loop(0, world, step, None)
    drain()


def _ag_gemm_hbm_nb_kernel(x_hbm, b_hbm, ag_hbm, c_hbm, a_tile, b_panel,
                           c_stage, copy_sem, a_sem, b_sem, c_sem,
                           send_sem, recv_sem, *, axis: str, world: int,
                           rows: int, k: int, n_loc: int, m_blk: int,
                           n_blk: int, acc_dtype, dirs: int = 1,
                           straggler_option=None,
                           for_correctness=False, interp=False):
    """N-blocked HBM AG-GEMM: resident B panel, full-K MXU dots.

    Per N-block: the (K, n_blk) B panel is DMA'd into VMEM ONCE (B total
    traffic = K·N — round 2's k-tiled kernel re-read it per m-tile,
    VERDICT r2 weak 4), then (m_blk, K) A tiles stream through a double
    buffer and each tile is one full-K ``jnp.dot`` — no k-accumulator,
    no per-k-tile writeback. The ring AG of A chunks runs during the
    FIRST N-block only (its chunk-boundary ``wait_recv`` is the
    reference's per-rank ``dl.wait``, allgather_gemm.py:236); by the
    time panel 0's compute drains, every chunk has landed, so later
    panels read the workspace freely. Rank-rotated consumption order is
    preserved (reference swizzle allgather_gemm.py:221-229).
    """
    me = lax.axis_index(axis)
    m_tiles = rows // m_blk
    n_blocks = n_loc // n_blk
    per_nb = world * m_tiles       # iterations per N-block
    total = n_blocks * per_nb

    # local shard → ag[me] (HBM→HBM DMA)
    cp = pltpu.make_async_copy(x_hbm, ag_hbm.at[pl.ds(me * rows, rows), :],
                               copy_sem)
    cp.start()
    cp.wait()
    if world > 1:
        dl.barrier_all(axis)
        maybe_straggle(straggler_option, axis, interp)
        maybe_noise(for_correctness, axis, world, salt=4, interpret=interp)

    chunk_of, advance, ring_drain = _make_ring(
        lambda idx: ag_hbm.at[pl.ds(idx * rows, rows), :], me, axis,
        world, dirs, send_sem, recv_sem)

    def chunk_idx(i):
        return chunk_of(lax.rem(i, per_nb) // m_tiles)

    def row_of(i):
        mt = lax.rem(i, m_tiles)
        return chunk_idx(i) * rows + mt * m_blk

    def a_dma(slot, i):
        return pltpu.make_async_copy(
            ag_hbm.at[pl.ds(row_of(i), m_blk), :], a_tile.at[slot],
            a_sem.at[slot])

    def b_dma(slot, nb):
        return pltpu.make_async_copy(
            b_hbm.at[:, pl.ds(nb * n_blk, n_blk)], b_panel.at[slot],
            b_sem.at[slot])

    def c_dma(slot, i):
        return pltpu.make_async_copy(
            c_stage.at[slot],
            c_hbm.at[pl.ds(row_of(i), m_blk),
                     pl.ds((i // per_nb) * n_blk, n_blk)],
            c_sem.at[slot])

    def ring_advance(i):
        """Chunk-boundary ring bookkeeping — N-block 0 only."""
        if world == 1:
            return

        @pl.when((i < per_nb) & (lax.rem(i, m_tiles) == 0))
        def _():
            advance(i // m_tiles)

    ring_advance(0)
    b_dma(0, 0).start()
    a_dma(0, 0).start()

    def step(i, _):
        slot = lax.rem(i, 2)
        nb = i // per_nb
        bslot = lax.rem(nb, 2)
        ring_advance(i + 1)

        @pl.when(i + 1 < total)
        def _():
            a_dma(lax.rem(i + 1, 2), i + 1).start()

        @pl.when((lax.rem(i, per_nb) == 0) & (nb + 1 < n_blocks))
        def _():
            b_dma(lax.rem(nb + 1, 2), nb + 1).start()  # prefetch panel

        @pl.when(lax.rem(i, per_nb) == 0)
        def _():
            b_dma(bslot, nb).wait()
        a_dma(slot, i).wait()

        out = jnp.dot(a_tile[slot], b_panel[bslot],
                      preferred_element_type=acc_dtype)

        @pl.when(i >= 2)
        def _():
            c_dma(slot, i - 2).wait()   # this slot's previous writeback
        c_stage[slot] = out.astype(c_stage.dtype)
        c_dma(slot, i).start()
        return _

    lax.fori_loop(0, total, step, None)

    for i_last in range(max(0, total - 2), total):
        c_dma(i_last % 2, i_last).wait()

    ring_drain()


def _ag_gemm_hbm_kernel(x_hbm, b_hbm, ag_hbm, c_hbm, a_tile, b_tile, acc,
                        c_stage, copy_sem, a_sem, b_sem, c_sem, send_sem,
                        recv_sem, *, axis: str, world: int, rows: int,
                        k: int, k_blk: int, m_blk: int, acc_dtype,
                        dirs: int = 1, straggler_option=None,
                        for_correctness=False, interp=False):
    """HBM-resident ring AG-GEMM: operands never fully enter VMEM.

    Ring protocol identical to ``_ag_gemm_kernel`` (per-chunk DMA
    semaphores, barrier before first remote write) but the AG workspace
    lives in HBM and each chunk's GEMM streams (m_blk, k_blk)·(k_blk, N)
    tiles through double-buffered VMEM — the TPU shape of the reference's
    persistent tiled consumer (kernel_consumer_gemm_persistent,
    allgather_gemm.py:158-264): its ``dl.wait`` per M-tile becomes the
    chunk-boundary ``wait_recv``; its BLOCK_M/BLOCK_K loops become the
    tile DMA pipeline; rank-rotated consumption order is preserved.
    """
    me = lax.axis_index(axis)
    k_tiles = k // k_blk
    m_tiles = rows // m_blk
    per_chunk = m_tiles * k_tiles
    total = world * per_chunk

    # local shard → ag[me] (HBM→HBM DMA)
    cp = pltpu.make_async_copy(x_hbm, ag_hbm.at[pl.ds(me * rows, rows), :],
                               copy_sem)
    cp.start()
    cp.wait()
    if world > 1:
        dl.barrier_all(axis)
        maybe_straggle(straggler_option, axis, interp)
        maybe_noise(for_correctness, axis, world, salt=5, interpret=interp)

    chunk_pos, advance, ring_drain = _make_ring(
        lambda idx: ag_hbm.at[pl.ds(idx * rows, rows), :], me, axis,
        world, dirs, send_sem, recv_sem)

    def chunk_of(i):
        return chunk_pos(i // per_chunk)

    def row_of(i):
        """First AG row of iteration i's (chunk, m-tile)."""
        mt = lax.rem(i, per_chunk) // k_tiles
        return chunk_of(i) * rows + mt * m_blk

    def a_dma(slot, i):
        return pltpu.make_async_copy(
            ag_hbm.at[pl.ds(row_of(i), m_blk),
                      pl.ds(lax.rem(i, k_tiles) * k_blk, k_blk)],
            a_tile.at[slot], a_sem.at[slot])

    def b_dma(slot, i):
        return pltpu.make_async_copy(
            b_hbm.at[pl.ds(lax.rem(i, k_tiles) * k_blk, k_blk), :],
            b_tile.at[slot], b_sem.at[slot])

    def c_dma(slot, row):
        return pltpu.make_async_copy(
            c_stage.at[slot], c_hbm.at[pl.ds(row, m_blk), :], c_sem.at[slot])

    def ring_advance(j):
        """At chunk boundary j: ensure the chunk has arrived, then keep it
        moving round the ring — the forward overlaps this whole chunk's
        tile compute."""
        if world == 1:
            return

        @pl.when((j < total) & (lax.rem(j, per_chunk) == 0))
        def _():
            advance(j // per_chunk)

    ring_advance(0)
    a_dma(0, 0).start()
    b_dma(0, 0).start()

    def step(i, _):
        slot = lax.rem(i, 2)
        nxt = lax.rem(i + 1, 2)
        ring_advance(i + 1)

        @pl.when(i + 1 < total)
        def _():
            a_dma(nxt, i + 1).start()
            b_dma(nxt, i + 1).start()

        a_dma(slot, i).wait()
        b_dma(slot, i).wait()
        kt = lax.rem(i, k_tiles)

        partial = jnp.dot(a_tile[slot], b_tile[slot],
                          preferred_element_type=acc_dtype)

        @pl.when(kt == 0)
        def _():
            acc[:] = partial

        @pl.when(kt > 0)
        def _():
            acc[:] = acc[:] + partial

        @pl.when(kt == k_tiles - 1)
        def _():
            # Double-buffered writeback: stage into the alternate slot and
            # let the DMA drain while the next m-tile computes; only wait
            # for this slot's *previous* writeback (2 m-tiles ago).
            mi = i // k_tiles
            cslot = lax.rem(mi, 2)

            @pl.when(mi >= 2)
            def _():
                c_dma(cslot, row_of(i)).wait()
            c_stage[cslot] = acc[:].astype(c_stage.dtype)
            c_dma(cslot, row_of(i)).start()
        return _

    lax.fori_loop(0, total, step, None)

    # Drain the outstanding C writebacks (one per slot in flight).
    for s in range(min(2, world * m_tiles)):
        c_dma(s, 0).wait()

    ring_drain()


def _pick_block_k(k: int, want: int) -> int:
    for cand in (want, 512, 256, 128):
        if cand <= k and k % cand == 0:
            return cand
    return k


def _hbm_footprint(bm: int, bn: int, k: int, itemsize: int) -> int:
    """VMEM bytes of the N-blocked hbm kernel: 2 A tiles (bm, K) + 2 B
    panels (K, bn) + 2 C stages (bm, bn)."""
    return itemsize * (2 * bm * k + 2 * k * bn + 2 * bm * bn)


# Shape-keyed tuned configs: (m, k, n_tot_loc, dtype, world) → config dict.
# The analog of the reference's per-op static config tables + autotuner
# cache (allgather_gemm.py:396, autotuner.py:43-250).
_TUNED: dict[tuple, dict] = {}


def ag_gemm_configs(m: int, rows: int, k: int, n_tot_loc: int,
                    itemsize: int,
                    vmem_budget: int = DEFAULT_VMEM_BUDGET,
                    tier_caps: bool = True) -> list[dict]:
    """Candidate config table for the fused AG-GEMM (reference
    ``matmul_get_configs`` allgather_gemm.py:396, pruned to shapes that
    fit the hardware constraints). Ordered best-first: every entry point
    (default, autotune) consults this table, so an infeasible default can
    never reach the compiler (BENCH_r02's 16.5 MB-scratch crash).
    ``tier_caps=False`` skips the blind per-tier prefix caps and
    returns the FULL feasible space — the autotune path then prunes it
    with the perf_model cost model instead (docs/autotuner.md)."""
    vmem_cfgs: list[dict] = []
    vmem_fp = itemsize * (m * k + k * n_tot_loc + m * n_tot_loc + rows * k)
    if vmem_fp <= vmem_budget:
        vmem_cfgs.append({"variant": "vmem"})
    # N-blocked resident-B kernel: larger block_n first (A is re-read
    # n_tot_loc/block_n times; B exactly once). Large tiles are listed
    # in BOTH tiers: the budget tier when they fit (making them the
    # default where they are free), the aggressive tier when only the
    # raised compile cap admits them (review r5j finding 1).
    hbm_budget: list[dict] = []
    aggressive: list[dict] = []
    for bn in (2048, 1024, 512, 256, 128):
        if bn > n_tot_loc or n_tot_loc % bn:
            continue
        for bm in (1024, 512, 256, 128):
            if bm > rows or rows % bm:
                continue
            fp = _hbm_footprint(bm, bn, k, itemsize)
            if fp <= vmem_budget:
                hbm_budget.append({"variant": "hbm", "block_m": bm,
                                   "block_n": bn})
            elif fp <= HARD_FOOTPRINT_CAP:
                # Aggressive tier — concatenated LAST so the default
                # path (first feasible) never picks these; the
                # autotuner sweeps them under per-config failure
                # isolation (see HARD_FOOTPRINT_CAP in ops/common.py).
                aggressive.append({"variant": "hbm", "block_m": bm,
                                   "block_n": bn})
    # k-tiled fallback (huge K: no resident panel fits). Kept OUTSIDE
    # the tier cap: the entry-point clamps re-filter to these when a
    # hinted config is infeasible, so pruning must never drop them
    # (review r5l finding 1).
    kt_cfgs: list[dict] = []
    for bm in (128, 256, 512):
        if bm > rows:
            continue
        for bk in (256, 512, 1024):
            if bk > k:
                continue
            # tile footprint: 2 A-tiles + 2 B-tiles + acc + 2 C-stages
            fp = (2 * bm * bk + 2 * bk * n_tot_loc) * itemsize \
                + bm * n_tot_loc * (4 + 2 * itemsize)
            if fp <= vmem_budget:
                kt_cfgs.append({"variant": "hbm_kt", "block_m": bm,
                                "block_k": bk})
    if tier_caps:
        cfgs = (vmem_cfgs
                + cap_config_tiers(hbm_budget, [], n_budget=4)
                + kt_cfgs[:2]
                + cap_config_tiers([], aggressive))
    else:
        cfgs = vmem_cfgs + hbm_budget + kt_cfgs + aggressive
    return cfgs or [{"variant": "hbm_kt",
                     "block_m": _pick_block_k(rows, 128),
                     "block_k": _pick_block_k(k, 256)}]


def _autotune_ag_gemm(a, bs, ctx, key, n_tot_loc):
    """Eager sweep over :func:`ag_gemm_configs`; winner cached by shape
    and agreed across processes (tools/autotuner broadcast).

    The candidate space is the FULL feasible table (big tiles up to
    HARD_FOOTPRINT_CAP, generated against :data:`TUNED_VMEM_BUDGET` —
    the sweep has per-config failure isolation, so aggressive entries
    are safe to list without any global budget raise), pruned by the
    perf_model roofline cost model before any Mosaic compile is paid.
    """
    from triton_dist_tpu.tools.autotuner import autotune, record_prune
    from triton_dist_tpu.tools import perf_model as _pm

    m, k = a.shape
    rows = m // ctx.world_size
    item = a.dtype.itemsize
    world = ctx.world_size
    dirs = resolve_ring_dirs(ctx.ring_dirs)
    cfgs = ag_gemm_configs(m, rows, k, n_tot_loc, item,
                           max(ctx.vmem_budget, TUNED_VMEM_BUDGET),
                           tier_caps=False)
    cfgs, n_before = _pm.prune_configs(
        cfgs,
        lambda c: _pm.estimate_ag_gemm_cost(
            c, m=m, rows=rows, k=k, n_loc=n_tot_loc, itemsize=item,
            world=world, ring_dirs=dirs).total_ms,
        always_keep=lambda c: c["variant"] == "hbm_kt")
    record_prune("ag_gemm", n_before, len(cfgs))
    if len(cfgs) == 1:
        _TUNED[key] = cfgs[0]
        return cfgs[0]

    def make_fn(**cfg):
        ctx2 = dataclasses.replace(ctx, autotune=False,
                                   trust_blocks=True, **cfg)
        fn = jax.jit(lambda x, ws: ag_gemm_multi(x, ws, ctx2,
                                                 impl="pallas"))
        # Unique input per call: the tunneled device dedupes identical
        # computations, which would void the ranking.
        from triton_dist_tpu.runtime.utils import make_perturbed_runner
        return make_perturbed_runner(fn, a, list(bs))

    result = autotune(make_fn, cfgs, key=f"ag_gemm:{key}", iters=8,
                      warmup_iters=2,
                      vet=lambda c: _pm.vet_vmem(
                          "ag_gemm", c, rows=rows, m=m, k=k,
                          n_loc=n_tot_loc, itemsize=item, world=world))
    _TUNED[key] = result.config
    return result.config


@resilient("ag_gemm", env_keys=("TDT_RING_DIRS",))
def ag_gemm_multi(a: jax.Array, bs,
                  ctx: AllGatherGEMMContext | None = None,
                  impl: str = "pallas"):
    """[C_i = allgather(a) @ b_i] sharing one fused all-gather.

    Args:
      a: (M, K) row-sharded over ``ctx.axis``.
      bs: sequence of (K, N_i), each column-sharded over ``ctx.axis``.
    Returns:
      list of C_i (M, N_i) column-sharded; with ``ctx.return_gathered``
      also the gathered A as the last element.
    """
    ctx = ctx or create_ag_gemm_context()
    mesh, axis, world = ctx.mesh, ctx.axis, ctx.world_size
    record_comm("ag_gemm", a)   # the gathered operand is the payload
    bs = list(bs)
    n_b = len(bs)
    m, k = a.shape
    for b in bs:
        assert b.shape[0] == k and b.shape[1] % world == 0
    assert m % world == 0
    rows = m // world
    c_spec = [P(None, axis)] * n_b
    out_specs = tuple(c_spec) + ((P(axis),) if ctx.return_gathered else ())

    if impl == "xla":
        def body(xs, *ws):
            ag = lax.all_gather(xs, axis, tiled=True)
            cs = [jnp.dot(ag, w, preferred_element_type=ctx.acc_dtype
                          ).astype(xs.dtype) for w in ws]
            return tuple(cs) + ((ag,) if ctx.return_gathered else ())
        f = nestable_shard_map(body, mesh=mesh,
                          in_specs=(P(axis),) + (P(None, axis),) * n_b,
                          out_specs=out_specs, check_vma=False)
        return list(f(a, *bs))

    interpret = resolve_interpret(ctx.interpret)
    n_tot_loc = sum(b.shape[1] // world for b in bs)

    if ctx.autotune:
        tune_key = (m, k, n_tot_loc, str(a.dtype), world)
        tuned = _TUNED.get(tune_key)
        if tuned is None and not isinstance(a, jax.core.Tracer):
            tuned = _autotune_ag_gemm(a, bs, ctx, tune_key, n_tot_loc)
        if tuned is not None:
            ctx = dataclasses.replace(ctx, autotune=False,
                                      trust_blocks=True, **tuned)

    variant = ctx.resolve_variant(m, k, n_tot_loc, a.dtype.itemsize)
    item = a.dtype.itemsize
    dirs = resolve_ring_dirs(ctx.ring_dirs)
    inject = dict(straggler_option=ctx.straggler_option,
                  for_correctness=ctx.for_correctness,
                  interp=bool(interpret))

    def emit_overlap(cfg):
        from triton_dist_tpu.tools import perf_model as _pm
        record_overlap("ag_gemm", _pm.estimate_ag_gemm_cost(
            cfg, m=m, rows=rows, k=k, n_loc=n_tot_loc, itemsize=item,
            world=world, ring_dirs=dirs), world=world, dirs=dirs)

    if variant == "hbm":
        # Clamp the ctx hint to divisors + the VMEM budget; fall back to
        # the first feasible table config, then to the k-tiled kernel —
        # an infeasible default must never reach Mosaic (BENCH_r02).
        m_blk = _pick_block_k(rows, ctx.block_m)
        n_blk = _pick_block_k(n_tot_loc, ctx.block_n)
        clamp_at = (HARD_FOOTPRINT_CAP if ctx.trust_blocks
                    else ctx.vmem_budget)
        if _hbm_footprint(m_blk, n_blk, k, item) > clamp_at:
            # Re-filter to a conservative in-budget config. With
            # trust_blocks (autotune sweep / tuned winner) the ceiling
            # is the hard COMPILE cap so the table's aggressive tier
            # reaches Mosaic at all (review r5i finding 1: a
            # soft-budget clamp here silently rewrote every swept
            # aggressive config back to the budget kernel); the default
            # path keeps the soft budget.
            cand = [c for c in ag_gemm_configs(m, rows, k, n_tot_loc,
                                               item, ctx.vmem_budget)
                    if c["variant"] == "hbm"
                    and _hbm_footprint(c["block_m"], c["block_n"], k,
                                       item) <= ctx.vmem_budget]
            if cand:
                m_blk, n_blk = cand[0]["block_m"], cand[0]["block_n"]
            else:
                variant = "hbm_kt"

    if variant == "hbm":
        emit_overlap({"variant": "hbm", "block_m": m_blk,
                      "block_n": n_blk})
        nb_kernel = functools.partial(
            _ag_gemm_hbm_nb_kernel, axis=axis, world=world, rows=rows,
            k=k, n_loc=n_tot_loc, m_blk=m_blk, n_blk=n_blk,
            acc_dtype=ctx.acc_dtype, dirs=dirs, **inject)

        def body(xs, *ws):
            wcat = ws[0] if n_b == 1 else jnp.concatenate(ws, axis=1)
            ag, ccat = pl.pallas_call(
                nb_kernel,
                out_shape=(jax.ShapeDtypeStruct((m, k), a.dtype),
                           jax.ShapeDtypeStruct((m, n_tot_loc), a.dtype)),
                in_specs=[any_spec()] * 2,
                out_specs=(any_spec(),) * 2,
                scratch_shapes=[
                    pltpu.VMEM((2, m_blk, k), a.dtype),
                    pltpu.VMEM((2, k, n_blk), a.dtype),
                    pltpu.VMEM((2, m_blk, n_blk), a.dtype),
                    pltpu.SemaphoreType.DMA,
                    pltpu.SemaphoreType.DMA((2,)),
                    pltpu.SemaphoreType.DMA((2,)),
                    pltpu.SemaphoreType.DMA((2,)),
                    pltpu.SemaphoreType.DMA((dirs, world)),
                    pltpu.SemaphoreType.DMA((dirs, world)),
                ],
                compiler_params=comm_params(collective_id=4, world=world),
                interpret=interpret,
            )(xs, wcat)
            widths = [b.shape[1] // world for b in bs]
            cs, off = [], 0
            for wdt in widths:
                cs.append(lax.slice_in_dim(ccat, off, off + wdt, axis=1))
                off += wdt
            return tuple(cs) + ((ag,) if ctx.return_gathered else ())

        f = nestable_shard_map(body, mesh=mesh,
                          in_specs=(P(axis),) + (P(None, axis),) * n_b,
                          out_specs=out_specs, check_vma=False)
        return list(sync_interpret(f(a, *bs), interpret))

    if variant == "hbm_kt":
        k_blk = _pick_block_k(k, ctx.block_k)
        m_blk = _pick_block_k(rows, ctx.block_m)
        fp = (2 * m_blk * k_blk + 2 * k_blk * n_tot_loc) * item \
            + m_blk * n_tot_loc * (4 + 2 * item)
        if fp > ctx.vmem_budget:
            cand = [c for c in ag_gemm_configs(m, rows, k, n_tot_loc,
                                               item, ctx.vmem_budget)
                    if c["variant"] == "hbm_kt"]
            if cand:
                m_blk, k_blk = cand[0]["block_m"], cand[0]["block_k"]
        emit_overlap({"variant": "hbm_kt", "block_m": m_blk,
                      "block_k": k_blk})
        hbm_kernel = functools.partial(
            _ag_gemm_hbm_kernel, axis=axis, world=world, rows=rows, k=k,
            k_blk=k_blk, m_blk=m_blk, acc_dtype=ctx.acc_dtype, dirs=dirs,
            **inject)

        def body(xs, *ws):
            wcat = ws[0] if n_b == 1 else jnp.concatenate(ws, axis=1)
            ag, ccat = pl.pallas_call(
                hbm_kernel,
                out_shape=(jax.ShapeDtypeStruct((m, k), a.dtype),
                           jax.ShapeDtypeStruct((m, n_tot_loc), a.dtype)),
                in_specs=[any_spec()] * 2,
                out_specs=(any_spec(),) * 2,
                scratch_shapes=[
                    pltpu.VMEM((2, m_blk, k_blk), a.dtype),
                    pltpu.VMEM((2, k_blk, n_tot_loc), a.dtype),
                    pltpu.VMEM((m_blk, n_tot_loc), ctx.acc_dtype),
                    pltpu.VMEM((2, m_blk, n_tot_loc), a.dtype),
                    pltpu.SemaphoreType.DMA,
                    pltpu.SemaphoreType.DMA((2,)),
                    pltpu.SemaphoreType.DMA((2,)),
                    pltpu.SemaphoreType.DMA((2,)),
                    pltpu.SemaphoreType.DMA((dirs, world)),
                    pltpu.SemaphoreType.DMA((dirs, world)),
                ],
                compiler_params=comm_params(collective_id=4, world=world),
                interpret=interpret,
            )(xs, wcat)
            widths = [b.shape[1] // world for b in bs]
            cs, off = [], 0
            for wdt in widths:
                cs.append(lax.slice_in_dim(ccat, off, off + wdt, axis=1))
                off += wdt
            return tuple(cs) + ((ag,) if ctx.return_gathered else ())

        f = nestable_shard_map(body, mesh=mesh,
                          in_specs=(P(axis),) + (P(None, axis),) * n_b,
                          out_specs=out_specs, check_vma=False)
        return list(sync_interpret(f(a, *bs), interpret))

    emit_overlap({"variant": "vmem"})
    kernel = functools.partial(_ag_gemm_kernel, axis=axis, world=world,
                               rows=rows, acc_dtype=ctx.acc_dtype, n_b=n_b,
                               dirs=dirs, **inject)

    def body(xs, *ws):
        out = pl.pallas_call(
            kernel,
            out_shape=tuple(
                [jax.ShapeDtypeStruct((m, k), a.dtype)] +
                [jax.ShapeDtypeStruct((m, b.shape[1] // world), a.dtype)
                 for b in bs]),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * (1 + n_b),
            out_specs=tuple([pl.BlockSpec(memory_space=pltpu.VMEM)]
                            * (1 + n_b)),
            scratch_shapes=[pltpu.SemaphoreType.DMA((dirs, world)),
                            pltpu.SemaphoreType.DMA((dirs, world))],
            compiler_params=comm_params(collective_id=4, world=world),
            interpret=interpret,
        )(xs, *ws)
        ag, cs = out[0], out[1:]
        return tuple(cs) + ((ag,) if ctx.return_gathered else ())

    f = nestable_shard_map(body, mesh=mesh,
                      in_specs=(P(axis),) + (P(None, axis),) * n_b,
                      out_specs=out_specs, check_vma=False)
    return list(sync_interpret(f(a, *bs), interpret))


def ag_gemm(a: jax.Array, b: jax.Array,
            ctx: AllGatherGEMMContext | None = None,
            impl: str = "pallas"):
    """C = allgather(a) @ b (functional entry, reference ``ag_gemm``
    allgather_gemm.py:534).

    Args:
      a: (M, K) row-sharded over ``ctx.axis``.
      b: (K, N) column-sharded over ``ctx.axis``.
    Returns:
      C: (M, N) column-sharded; with ``ctx.return_gathered`` also the
      gathered A (stacked per device: (w*M, K) sharded).
    """
    out = ag_gemm_multi(a, [b], ctx, impl)
    if len(out) == 2:
        return out[0], out[1]
    return out[0]


def _swiglu_footprint(bm: int, bn: int, k: int, itemsize: int) -> int:
    """VMEM bytes of the SwiGLU hbm kernel: 2 A tiles (bm, K) + 2x2 B
    panels (K, bn) (gate AND up resident) + 2 act stages (bm, bn)."""
    return itemsize * (2 * bm * k + 4 * k * bn + 2 * bm * bn)


def ag_swiglu_configs(rows: int, k: int, n_loc: int,
                      itemsize: int,
                      vmem_budget: int = DEFAULT_VMEM_BUDGET,
                      tier_caps: bool = True) -> list[dict]:
    """Candidate (block_m, block_n) table for the fused SwiGLU kernel,
    ordered best-first; same two-tier structure as
    :func:`ag_gemm_configs` (budget tier, then an aggressive tier up to
    HARD_FOOTPRINT_CAP for the autotuner — the dual gate+up panel
    doubles B residency, so feasible tiles are smaller than the plain
    AG-GEMM's at equal budget). ``tier_caps=False`` returns the full
    feasible space for cost-model pruning."""
    budget: list[dict] = []
    aggressive: list[dict] = []
    for bn in (2048, 1024, 512, 256, 128):
        if bn > n_loc or n_loc % bn:
            continue
        for bm in (1024, 512, 256, 128):
            if bm > rows or rows % bm:
                continue
            fp = _swiglu_footprint(bm, bn, k, itemsize)
            if fp <= vmem_budget:
                budget.append({"block_m": bm, "block_n": bn})
            elif fp <= HARD_FOOTPRINT_CAP:
                aggressive.append({"block_m": bm, "block_n": bn})
    if not tier_caps:
        return budget + aggressive
    return cap_config_tiers(budget, aggressive)


def _autotune_ag_swiglu(a, w_gate, w_up, ctx, key):
    """Eager sweep over :func:`ag_swiglu_configs`; winner cached by
    shape alongside the ag_gemm winners (same _TUNED map, distinct
    key tag). Candidates are the full feasible table (generated
    against TUNED_VMEM_BUDGET; the sweep's per-config isolation makes
    aggressive tiles safe), cost-model pruned before any compile."""
    from triton_dist_tpu.tools.autotuner import autotune, record_prune
    from triton_dist_tpu.tools import perf_model as _pm

    m, k = a.shape
    rows = m // ctx.world_size
    item = a.dtype.itemsize
    n_loc = w_gate.shape[1] // ctx.world_size
    dirs = resolve_ring_dirs(ctx.ring_dirs)
    cfgs = ag_swiglu_configs(rows, k, n_loc, item,
                             max(ctx.vmem_budget, TUNED_VMEM_BUDGET),
                             tier_caps=False)
    if not cfgs:
        return None
    cfgs, n_before = _pm.prune_configs(
        cfgs,
        lambda c: _pm.estimate_ag_swiglu_cost(
            c, m=m, rows=rows, k=k, n_loc=n_loc, itemsize=item,
            world=ctx.world_size, ring_dirs=dirs).total_ms)
    record_prune("ag_swiglu", n_before, len(cfgs))
    if len(cfgs) == 1:
        _TUNED[key] = cfgs[0]
        return cfgs[0]

    def make_fn(**cfg):
        ctx2 = dataclasses.replace(ctx, autotune=False,
                                   trust_blocks=True, **cfg)
        fn = jax.jit(lambda x, wg, wu: ag_swiglu(x, wg, wu, ctx2,
                                                 impl="pallas"))
        from triton_dist_tpu.runtime.utils import make_perturbed_runner
        return make_perturbed_runner(fn, a, w_gate, w_up)

    result = autotune(make_fn, cfgs, key=f"ag_swiglu:{key}", iters=8,
                      warmup_iters=2,
                      vet=lambda c: _pm.vet_vmem(
                          "ag_swiglu", c, rows=rows, k=k,
                          itemsize=item))
    _TUNED[key] = result.config
    return result.config


def _ag_swiglu_hbm_kernel(x_hbm, wg_hbm, wu_hbm, *rest, axis: str,
                          world: int, rows: int, k: int, n_loc: int,
                          m_blk: int, n_blk: int, acc_dtype,
                          dirs: int = 1, has_bias: bool = False,
                          straggler_option=None,
                          for_correctness=False, interp=False):
    """AG + dual GEMM + bias + SwiGLU epilogue in ONE kernel.

    Same ring/double-buffer structure as :func:`_ag_gemm_hbm_nb_kernel`
    (incl. the bidirectional schedule via ``_make_ring``), but each
    N-block holds BOTH the gate and up B panels (separate HBM inputs —
    no concatenated copy) and writes
    ``silu(A@Wg + bg) * (A@Wu + bu)`` directly — the (M, 2*n_loc)
    gate/up intermediate never exists in HBM and the whole TP-MLP front
    epilogue (bias add + SwiGLU gate) needs no separate XLA kernel.
    This is what XLA's fusion does for the unsharded MLP; the round-3
    chip bench measured the 3-dispatch fused path at 0.77x of XLA's
    single fused program at world=1, and this kernel removes exactly
    that overhead (reference TP_MLP runs AG-GEMM then a separate
    silu-mul, tp_mlp.py:147-270 — fusing past it is a TPU-side win,
    not a parity requirement). Biases are tiny (1, n_loc) VMEM
    residents; ``has_bias=False`` omits the operands entirely.
    """
    n_bias = 2 if has_bias else 0
    bg_ref = rest[0] if has_bias else None
    bu_ref = rest[1] if has_bias else None
    ag_hbm, act_hbm = rest[n_bias], rest[n_bias + 1]
    (a_tile, b_panel, c_stage, copy_sem, a_sem, b_sem, c_sem,
     send_sem, recv_sem) = rest[n_bias + 2:]
    me = lax.axis_index(axis)
    m_tiles = rows // m_blk
    n_blocks = n_loc // n_blk
    per_nb = world * m_tiles
    total = n_blocks * per_nb

    cp = pltpu.make_async_copy(x_hbm, ag_hbm.at[pl.ds(me * rows, rows), :],
                               copy_sem)
    cp.start()
    cp.wait()
    if world > 1:
        dl.barrier_all(axis)
        maybe_straggle(straggler_option, axis, interp)
        maybe_noise(for_correctness, axis, world, salt=4, interpret=interp)

    chunk_of, advance, ring_drain = _make_ring(
        lambda idx: ag_hbm.at[pl.ds(idx * rows, rows), :], me, axis,
        world, dirs, send_sem, recv_sem)

    def chunk_idx(i):
        return chunk_of(lax.rem(i, per_nb) // m_tiles)

    def row_of(i):
        mt = lax.rem(i, m_tiles)
        return chunk_idx(i) * rows + mt * m_blk

    def a_dma(slot, i):
        return pltpu.make_async_copy(
            ag_hbm.at[pl.ds(row_of(i), m_blk), :], a_tile.at[slot],
            a_sem.at[slot])

    def b_dma(slot, half, nb):
        """half 0 = gate panel, half 1 = up panel (static Python int)."""
        src = wg_hbm if half == 0 else wu_hbm
        return pltpu.make_async_copy(
            src.at[:, pl.ds(nb * n_blk, n_blk)],
            b_panel.at[slot, half], b_sem.at[slot, half])

    def c_dma(slot, i):
        return pltpu.make_async_copy(
            c_stage.at[slot],
            act_hbm.at[pl.ds(row_of(i), m_blk),
                       pl.ds((i // per_nb) * n_blk, n_blk)],
            c_sem.at[slot])

    def ring_advance(i):
        if world == 1:
            return

        @pl.when((i < per_nb) & (lax.rem(i, m_tiles) == 0))
        def _():
            advance(i // m_tiles)

    ring_advance(0)
    b_dma(0, 0, 0).start()
    b_dma(0, 1, 0).start()
    a_dma(0, 0).start()

    def step(i, _):
        slot = lax.rem(i, 2)
        nb = i // per_nb
        bslot = lax.rem(nb, 2)
        ring_advance(i + 1)

        @pl.when(i + 1 < total)
        def _():
            a_dma(lax.rem(i + 1, 2), i + 1).start()

        @pl.when((lax.rem(i, per_nb) == 0) & (nb + 1 < n_blocks))
        def _():
            b_dma(lax.rem(nb + 1, 2), 0, nb + 1).start()
            b_dma(lax.rem(nb + 1, 2), 1, nb + 1).start()

        @pl.when(lax.rem(i, per_nb) == 0)
        def _():
            b_dma(bslot, 0, nb).wait()
            b_dma(bslot, 1, nb).wait()
        a_dma(slot, i).wait()

        gate = jnp.dot(a_tile[slot], b_panel[bslot, 0],
                       preferred_element_type=acc_dtype)
        up = jnp.dot(a_tile[slot], b_panel[bslot, 1],
                     preferred_element_type=acc_dtype)
        if has_bias:
            col = pl.ds(nb * n_blk, n_blk)
            gate = gate + bg_ref[0:1, col].astype(acc_dtype)
            up = up + bu_ref[0:1, col].astype(acc_dtype)
        act = gate * jax.nn.sigmoid(gate) * up      # SwiGLU in acc dtype

        @pl.when(i >= 2)
        def _():
            c_dma(slot, i - 2).wait()
        c_stage[slot] = act.astype(c_stage.dtype)
        c_dma(slot, i).start()
        return _

    lax.fori_loop(0, total, step, None)

    for i_last in range(max(0, total - 2), total):
        c_dma(i_last % 2, i_last).wait()

    ring_drain()


@resilient("ag_swiglu", env_keys=("TDT_RING_DIRS",))
def ag_swiglu(a: jax.Array, w_gate: jax.Array, w_up: jax.Array,
              ctx: AllGatherGEMMContext | None = None,
              impl: str = "pallas",
              b_gate: jax.Array | None = None,
              b_up: jax.Array | None = None) -> jax.Array:
    """``silu(allgather(a) @ w_gate + b_gate) * (allgather(a) @ w_up +
    b_up)`` fused.

    The MLP front half as ONE kernel (AG + both GEMMs + bias +
    activation — the whole TP-MLP epilogue lives in the consumer tile
    loop, so the activation never makes an extra HBM round trip).
    Not differentiable directly — training wraps it in
    :func:`triton_dist_tpu.ops.autodiff.ag_swiglu`, whose backward
    recomputes gate/up through the differentiable composition (bias-free
    form; the biased epilogue is the inference path).

    Args:
      a: (M, K) row-sharded over ``ctx.axis``.
      w_gate/w_up: (K, N) column-sharded over ``ctx.axis``.
      b_gate/b_up: optional (N,) biases, column-sharded like the
        weights; pass both or neither.
    Returns:
      act: (M, N_loc-per-shard) column-sharded, a.dtype.
    """
    ctx = ctx or create_ag_gemm_context()
    if ctx.return_gathered:  # same convention as autodiff.ag_gemm_multi
        raise ValueError("ag_swiglu does not support return_gathered "
                         "(the gathered A is a workspace, not an output)")
    if (b_gate is None) != (b_up is None):
        raise ValueError("pass both biases or neither")
    mesh, axis, world = ctx.mesh, ctx.axis, ctx.world_size
    record_comm("ag_swiglu", a)
    m, k = a.shape
    assert w_gate.shape == w_up.shape and w_gate.shape[0] == k
    assert w_gate.shape[1] % world == 0 and m % world == 0
    n_loc = w_gate.shape[1] // world
    rows = m // world
    has_bias = b_gate is not None
    if has_bias:
        assert b_gate.shape[-1] == w_gate.shape[1], (b_gate.shape,
                                                     w_gate.shape)
        # (1, N) keeps the lane-major layout; sharded like the weights.
        biases = (jnp.reshape(b_gate, (1, -1)),
                  jnp.reshape(b_up, (1, -1)))
    else:
        biases = ()

    if impl == "xla":
        def body(xs, wg, wu, *bs):
            ag = lax.all_gather(xs, axis, tiled=True)
            gate = jnp.dot(ag, wg, preferred_element_type=ctx.acc_dtype)
            up = jnp.dot(ag, wu, preferred_element_type=ctx.acc_dtype)
            if bs:
                gate = gate + bs[0].astype(ctx.acc_dtype)
                up = up + bs[1].astype(ctx.acc_dtype)
            return (jax.nn.silu(gate) * up).astype(xs.dtype)
        f = nestable_shard_map(body, mesh=mesh,
                               in_specs=(P(axis), P(None, axis),
                                         P(None, axis))
                               + (P(None, axis),) * len(biases),
                               out_specs=P(None, axis), check_vma=False)
        return f(a, w_gate, w_up, *biases)

    interpret = resolve_interpret(ctx.interpret)
    item = a.dtype.itemsize
    dirs = resolve_ring_dirs(ctx.ring_dirs)

    if ctx.autotune:
        tune_key = (m, k, n_loc, str(a.dtype), world, "swiglu")
        tuned = _TUNED.get(tune_key)
        if tuned is None and not isinstance(a, jax.core.Tracer):
            tuned = _autotune_ag_swiglu(a, w_gate, w_up, ctx, tune_key)
        if tuned is not None:
            ctx = dataclasses.replace(ctx, autotune=False,
                                      trust_blocks=True, **tuned)

    # trust_blocks (sweep / tuned winner) honors the HINT blocks up to
    # the hard compile cap — only the hint: the descending fallbacks
    # below stay under the soft budget, so an infeasible trusted hint
    # degrades to a conservative config rather than to an unswept
    # aggressive one (review r5k finding 1; same contract as the
    # ag_gemm entry's re-filter).
    choice = None
    if ctx.trust_blocks:
        bm_h = _pick_block_k(rows, ctx.block_m)
        bn_h = _pick_block_k(n_loc, ctx.block_n)
        if (bn_h <= n_loc and n_loc % bn_h == 0 and bm_h <= rows
                and rows % bm_h == 0
                and _swiglu_footprint(bm_h, bn_h, k,
                                      item) <= HARD_FOOTPRINT_CAP):
            choice = (bm_h, bn_h)
    # First feasible (m_blk, n_blk) under the soft budget; the gate+up
    # dual panel doubles B residency vs the plain hbm kernel.
    if choice is None:
        for bn in (_pick_block_k(n_loc, ctx.block_n), 512, 256, 128):
            if bn > n_loc or n_loc % bn:
                continue
            for bm in (_pick_block_k(rows, ctx.block_m), 256, 128):
                if bm > rows or rows % bm:
                    continue
                if _swiglu_footprint(bm, bn, k, item) <= ctx.vmem_budget:
                    choice = (bm, bn)
                    break
            if choice:
                break
    if choice is None or rows % 128 or n_loc % 128:
        # No feasible single-kernel tiling (huge K or tiny shards):
        # compose from the proven pieces — still fused AG, unfused act.
        gate, up = ag_gemm_multi(a, [w_gate, w_up], ctx, impl=impl)
        if has_bias:
            # gate/up are (M, N) column-sharded globals; the (1, N)
            # biases broadcast — XLA inserts the matching sharding.
            gate = (gate.astype(jnp.float32)
                    + biases[0].astype(jnp.float32)).astype(a.dtype)
            up = (up.astype(jnp.float32)
                  + biases[1].astype(jnp.float32)).astype(a.dtype)
        return (jax.nn.silu(gate.astype(jnp.float32))
                ).astype(a.dtype) * up
    m_blk, n_blk = choice

    from triton_dist_tpu.tools import perf_model as _pm
    record_overlap("ag_swiglu", _pm.estimate_ag_swiglu_cost(
        {"block_m": m_blk, "block_n": n_blk}, m=m, rows=rows, k=k,
        n_loc=n_loc, itemsize=item, world=world, ring_dirs=dirs),
        world=world, dirs=dirs)

    kernel = functools.partial(
        _ag_swiglu_hbm_kernel, axis=axis, world=world, rows=rows, k=k,
        n_loc=n_loc, m_blk=m_blk, n_blk=n_blk, acc_dtype=ctx.acc_dtype,
        dirs=dirs, has_bias=has_bias,
        straggler_option=ctx.straggler_option,
        for_correctness=ctx.for_correctness, interp=bool(interpret))

    def body(xs, wg, wu, *bs):
        out = pl.pallas_call(
            kernel,
            out_shape=(jax.ShapeDtypeStruct((m, k), a.dtype),
                       jax.ShapeDtypeStruct((m, n_loc), a.dtype)),
            in_specs=[any_spec()] * 3
            + [pl.BlockSpec(memory_space=pltpu.VMEM)] * len(bs),
            out_specs=(any_spec(),) * 2,
            scratch_shapes=[
                pltpu.VMEM((2, m_blk, k), a.dtype),
                pltpu.VMEM((2, 2, k, n_blk), a.dtype),
                pltpu.VMEM((2, m_blk, n_blk), a.dtype),
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2, 2)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((dirs, world)),
                pltpu.SemaphoreType.DMA((dirs, world)),
            ],
            compiler_params=comm_params(collective_id=4, world=world),
            interpret=interpret,
        )(xs, wg, wu, *bs)
        return out[1]

    f = nestable_shard_map(body, mesh=mesh,
                           in_specs=(P(axis), P(None, axis),
                                     P(None, axis))
                           + (P(None, axis),) * len(biases),
                           out_specs=P(None, axis), check_vma=False)
    return sync_interpret(f(a, w_gate, w_up, *biases), interpret)
