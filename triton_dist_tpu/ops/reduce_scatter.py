"""ReduceScatter over the ICI mesh.

TPU-native redesign of the reference's ReduceScatter
(python/triton_dist/kernels/nvidia/reduce_scatter.py: ctx :47-146, ring push
variants :285-504, ``ring_reduce`` :674-826, 2-D intra+inter op :857).

Methods:

- ``RING``      — classic ring reduce-scatter: w-1 hops, each device
  accumulates a travelling partial and forwards it; bandwidth-optimal.
  The reference's ``ring_reduce`` on a torus axis.
- ``ONE_SHOT``  — every device pushes each peer's chunk directly to that
  peer's staging slots, then each peer reduces w partials locally. One hop
  (latency-optimal, small payloads) — analog of the reference's
  scatter-then-local-reduce consumer (gemm_reduce_scatter.py scatter path).

The 2-D (intra-node × inter-node) hierarchy of the reference maps to
composing this op over two mesh axes ("tp" within a pod slice, "dcn"
across) — see ops/hierarchical.py.
"""

from __future__ import annotations

import dataclasses
import enum
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.resilience import resilient
from triton_dist_tpu.ops.common import (
    comm_params,
    nestable_shard_map,
    record_comm,
    resolve_interpret,
    sync_interpret)


class ReduceScatterMethod(enum.Enum):
    AUTO = "auto"
    RING = "ring"
    ONE_SHOT = "one_shot"


@dataclasses.dataclass
class ReduceScatterContext:
    mesh: Mesh
    axis: str = "tp"
    method: ReduceScatterMethod = ReduceScatterMethod.AUTO
    interpret: bool | None = None

    @property
    def world_size(self) -> int:
        return self.mesh.shape[self.axis]

    def resolve_method(self, nbytes_per_chunk: int) -> ReduceScatterMethod:
        """Perf-model crossover (reference comm_perf_model.py:116):
        one-shot's single push round wins at small chunks; the ring wins
        once its per-step fixed costs are amortized."""
        if self.method is not ReduceScatterMethod.AUTO:
            return self.method
        if self.world_size <= 2:
            return ReduceScatterMethod.ONE_SHOT
        from triton_dist_tpu.tools.perf_model import (
            estimate_one_shot_reduce_time_ms,
            estimate_reduce_scatter_time_ms)
        t_one = estimate_one_shot_reduce_time_ms(nbytes_per_chunk,
                                                 self.world_size)
        t_ring = estimate_reduce_scatter_time_ms(nbytes_per_chunk,
                                                 self.world_size)
        return (ReduceScatterMethod.ONE_SHOT if t_one <= t_ring
                else ReduceScatterMethod.RING)


def create_reduce_scatter_context(
        mesh: Mesh | None = None, axis: str = "tp",
        method: ReduceScatterMethod = ReduceScatterMethod.AUTO,
        interpret: bool | None = None) -> ReduceScatterContext:
    if mesh is None:
        from triton_dist_tpu.runtime.dist import get_mesh
        mesh = get_mesh()
    return ReduceScatterContext(mesh=mesh, axis=axis, method=method,
                                interpret=interpret)


def _ring_rs_kernel(x_ref, o_ref, send_buf, recv_buf, send_sem, recv_sem, *,
                    axis: str, world: int, rows: int):
    """Ring reduce-scatter (reference ``ring_reduce``
    reduce_scatter.py:674-826).

    Chunk c starts at device (c+1)%w and travels right, accumulating each
    device's local contribution; after w-1 hops it lands, fully reduced, on
    device c.

    Buffers and semaphores are PER STEP (send_buf/recv_buf: (w-1, rows, N)):
    a neighbor may run ahead, and delivery is not assumed FIFO — with reused
    slots its step-(s+2) payload could clobber an unconsumed step-s payload
    (the reference serializes with per-segment flags instead,
    reduce_scatter.py ring push protocol).
    """
    me = lax.axis_index(axis)
    right = lax.rem(me + 1, world)

    if world == 1:
        o_ref[:] = x_ref[pl.ds(me * rows, rows), :]
        return

    dl.barrier_all(axis)

    def step_copy(s):
        return dl.remote_copy(send_buf.at[s], recv_buf.at[s], right,
                              send_sem.at[s], recv_sem.at[s], axis=axis)

    def step(s, _):
        send_idx = lax.rem(me - s - 1 + world, world)

        # Partial to forward: my contribution + the travelling partial
        # received last step (if any).
        @pl.when(s == 0)
        def _():
            send_buf[s] = x_ref[pl.ds(send_idx * rows, rows), :]

        @pl.when(s > 0)
        def _():
            send_buf[s] = (recv_buf[jnp.maximum(s - 1, 0)] +
                           x_ref[pl.ds(send_idx * rows, rows), :])

        step_copy(s).start()
        # Wait for the incoming step-s partial from the left neighbor
        # (it feeds next step's send).
        step_copy(s).wait_recv()
        return _

    lax.fori_loop(0, world - 1, step, None)
    o_ref[:] = recv_buf[world - 2] + x_ref[pl.ds(me * rows, rows), :]

    def drain(s, _):
        step_copy(s).wait_send()
        return _

    lax.fori_loop(0, world - 1, drain, None)


def _one_shot_rs_kernel(x_ref, o_ref, stage_ref, send_sem, recv_sem, *,
                        axis: str, world: int, rows: int):
    """Scatter-then-reduce: push chunk p to peer p's staging slot [me], then
    locally sum the w staged partials (analog of the reference's
    scatter+local-reduce path, reduce_scatter.py:285-360)."""
    me = lax.axis_index(axis)
    stage_ref[me] = x_ref[pl.ds(me * rows, rows), :]
    if world == 1:
        o_ref[:] = stage_ref[me]
        return
    dl.barrier_all(axis)

    def send(p, _):
        peer = lax.rem(me + p, world)
        dl.remote_copy(
            x_ref.at[pl.ds(peer * rows, rows), :],
            stage_ref.at[me],
            peer, send_sem.at[peer], recv_sem.at[me], axis=axis).start()
        return _

    lax.fori_loop(1, world, send, None)

    def wait_recv(p, _):
        src = lax.rem(me - p + world, world)
        dl.remote_copy(
            x_ref.at[pl.ds(me * rows, rows), :],
            stage_ref.at[src],
            me, send_sem.at[src], recv_sem.at[src], axis=axis).wait_recv()
        return _

    lax.fori_loop(1, world, wait_recv, None)

    acc = stage_ref[0]
    for p in range(1, world):
        acc = acc + stage_ref[p]
    o_ref[:] = acc

    def wait_send(p, _):
        peer = lax.rem(me + p, world)
        dl.remote_copy(
            x_ref.at[pl.ds(peer * rows, rows), :],
            stage_ref.at[me],
            peer, send_sem.at[peer], recv_sem.at[me], axis=axis).wait_send()
        return _

    lax.fori_loop(1, world, wait_send, None)


@resilient("reduce_scatter")
def reduce_scatter(x: jax.Array, ctx: ReduceScatterContext | None = None,
                   impl: str = "pallas") -> jax.Array:
    """Reduce-scatter ``x`` along dim 0: every device holds the full (M, N)
    partial; device i receives the fully-reduced rows [i*M/w, (i+1)*M/w).

    Input: replicated-shape partials (each device's local (M, N)); passed as
    a global (w*M_chunkful...)? No — input is the per-device partial
    expressed as a global array of shape (w, M, N) sharded on dim 0 (one
    partial per device). Output: (M, N) sharded on dim 0 over the axis.
    """
    ctx = ctx or create_reduce_scatter_context()
    mesh, axis, world = ctx.mesh, ctx.axis, ctx.world_size
    record_comm("reduce_scatter", x)
    assert x.shape[0] == world, (x.shape, world)
    m, n = x.shape[1], x.shape[2]
    assert m % world == 0
    rows = m // world
    method = ctx.resolve_method(rows * n * x.dtype.itemsize)

    if impl == "xla":
        def body(xs):
            local = xs[0]  # (M, N) partial
            return lax.psum_scatter(local, axis, scatter_dimension=0,
                                    tiled=True)[None]
        f = nestable_shard_map(body, mesh=mesh, in_specs=P(axis),
                          out_specs=P(axis), check_vma=False)
        return f(x).reshape(m, n)

    interpret = resolve_interpret(ctx.interpret)

    if method is ReduceScatterMethod.RING:
        kernel = functools.partial(_ring_rs_kernel, axis=axis, world=world,
                                   rows=rows)
        scratch = [pltpu.VMEM((world - 1, rows, n), x.dtype),
                   pltpu.VMEM((world - 1, rows, n), x.dtype),
                   pltpu.SemaphoreType.DMA((world - 1,)),
                   pltpu.SemaphoreType.DMA((world - 1,))]
    else:
        kernel = functools.partial(_one_shot_rs_kernel, axis=axis,
                                   world=world, rows=rows)
        scratch = [pltpu.VMEM((world, rows, n), x.dtype),
                   pltpu.SemaphoreType.DMA((world,)),
                   pltpu.SemaphoreType.DMA((world,))]

    def body(xs):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((rows, n), x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=scratch,
            compiler_params=comm_params(collective_id=2, world=world),
            interpret=interpret,
        )(xs[0])

    f = nestable_shard_map(body, mesh=mesh, in_specs=P(axis),
                      out_specs=P(axis), check_vma=False)
    return sync_interpret(f(x), interpret)
