/* LD_PRELOAD shim: report N schedulable CPUs (default 16) regardless of the
 * container's cpuset. XLA's CPU PJRT client sizes its thread pools from
 * sched_getaffinity; on 1-core CI boxes a pool of one thread deadlocks
 * Pallas TPU interpret mode, whose kernels issue blocking host callbacks
 * (semaphore waits) that occupy pool threads while other devices' compute
 * feeds their callbacks. Oversizing the pools costs only timesharing. */
#define _GNU_SOURCE
#include <sched.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

static int shim_ncpus(void) {
    const char *s = getenv("TDT_FAKE_NCPUS");
    int n = s ? atoi(s) : 16;
    return n > 0 && n <= CPU_SETSIZE ? n : 16;
}

int sched_getaffinity(pid_t pid, size_t cpusetsize, cpu_set_t *mask) {
    (void)pid;
    int n = shim_ncpus();
    CPU_ZERO_S(cpusetsize, mask);
    for (int i = 0; i < n; i++)
        CPU_SET_S(i, cpusetsize, mask);
    return 0;
}

long sysconf(int name);  /* glibc prototype */

/* std::thread::hardware_concurrency and some TSL paths use sysconf. */
static long (*real_sysconf)(int) = 0;
long sysconf(int name) {
    if (name == _SC_NPROCESSORS_ONLN || name == _SC_NPROCESSORS_CONF)
        return shim_ncpus();
    if (!real_sysconf) {
        extern long __sysconf(int);
        real_sysconf = __sysconf;
    }
    return real_sysconf(name);
}
