// Token-shard data loader: deterministic shuffled epochs + batch gather.
//
// Training IO for the finetune path (tools/data.py): the corpus is a
// flat int32 token file (memory-mapped on the Python side); an epoch is
// a seeded Fisher-Yates permutation of its fixed-size chunks, and a
// batch is a strided gather of chunk rows. Native like the reference's
// csrc host utilities, with a bit-identical Python fallback (parity
// asserted in tests/test_data.py).
//
// Build: g++ -shared -fPIC -O2 -o libtdtdata.so dataio.cc

#include <cstdint>

extern "C" {

// splitmix64 — tiny, seedable, reproducible across platforms (and
// trivially re-implementable in the Python fallback).
static inline uint64_t mix(uint64_t* s) {
  uint64_t z = (*s += 0x9e3779b97f4a7c15ull);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
  return z ^ (z >> 31);
}

// Seeded Fisher-Yates permutation of [0, n) into out.
int32_t tdt_data_epoch_perm(int64_t n, uint64_t seed, int32_t* out) {
  if (n <= 0 || n > INT32_MAX) return -1;
  for (int64_t i = 0; i < n; ++i) out[i] = (int32_t)i;
  uint64_t s = seed;
  for (int64_t i = n - 1; i > 0; --i) {
    int64_t j = (int64_t)(mix(&s) % (uint64_t)(i + 1));
    int32_t t = out[i];
    out[i] = out[j];
    out[j] = t;
  }
  return 0;
}

// Gather `count` chunks of `chunk_len` tokens into out[count][chunk_len].
// Chunk c covers data[c*chunk_len : (c+1)*chunk_len).
int32_t tdt_data_gather(const int32_t* data, int64_t n_tokens,
                        int64_t chunk_len, const int32_t* chunk_ids,
                        int64_t count, int32_t* out) {
  if (chunk_len <= 0) return -1;
  const int64_t n_chunks = n_tokens / chunk_len;
  for (int64_t i = 0; i < count; ++i) {
    const int64_t c = chunk_ids[i];
    if (c < 0 || c >= n_chunks) return -2;
    const int32_t* src = data + c * chunk_len;
    int32_t* dst = out + i * chunk_len;
    for (int64_t t = 0; t < chunk_len; ++t) dst[t] = src[t];
  }
  return 0;
}

}  // extern "C"
