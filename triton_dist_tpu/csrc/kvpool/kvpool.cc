// Paged-KV slot allocator: per-device free stacks + block tables.
//
// TPU-native serving keeps the KV pool as sharded device arrays
// (models/kv_cache.py PagedKVCacheManager); the ALLOCATOR is pure host
// bookkeeping on the serving hot path (admit/evict per request), which
// the reference keeps native alongside its runtime (csrc/, SURVEY §2.1)
// — so it is native here too, ctypes-bound with a bit-identical Python
// fallback (tests assert parity on randomized alloc/free traces).
//
// State (caller-owned numpy buffers, int32 unless noted):
//   stack[world][slots]  per-device free stacks; valid entries [0, top)
//   top[world]           stack depths
//   table[world][batch][pages]  block tables (device-local slot ids)
//   owned[batch] (uint8) rows currently holding an allocation
//
// All-or-nothing semantics: a request that cannot be satisfied on EVERY
// device changes nothing (the first Python implementation leaked the
// already-popped devices' pages on mid-loop exhaustion).
//
// Build: g++ -shared -fPIC -O2 -o libtdtkv.so kvpool.cc

#include <cstdint>

extern "C" {

// Fill the stacks: slot ids ascending so pops hand out slots-1 first
// (matches the Python list.pop() order for replay parity).
int32_t tdt_kv_init(int32_t world, int32_t slots, int32_t* stack,
                    int32_t* top) {
  if (world <= 0 || slots <= 0) return -1;
  for (int32_t r = 0; r < world; ++r) {
    top[r] = slots;
    for (int32_t i = 0; i < slots; ++i) stack[r * slots + i] = i;
  }
  return 0;
}

// Reserve `pages` slots on every device for row b.
// Returns 0, -1 (bad row / already owned), -2 (some device exhausted;
// nothing popped).
int32_t tdt_kv_alloc_seq(int32_t world, int32_t batch, int32_t pages,
                         int32_t slots, int32_t* stack, int32_t* top,
                         int32_t* table, uint8_t* owned, int32_t b) {
  if (b < 0 || b >= batch || owned[b]) return -1;
  for (int32_t r = 0; r < world; ++r)
    if (top[r] < pages) return -2;
  for (int32_t r = 0; r < world; ++r)
    for (int32_t i = 0; i < pages; ++i)
      table[(r * batch + b) * pages + i] = stack[r * slots + --top[r]];
  owned[b] = 1;
  return 0;
}

// Release row b's slots (pushed back in table order, matching the
// Python fallback so later pops replay identically).
int32_t tdt_kv_free_seq(int32_t world, int32_t batch, int32_t pages,
                        int32_t slots, int32_t* stack, int32_t* top,
                        int32_t* table, uint8_t* owned, int32_t b) {
  if (b < 0 || b >= batch || !owned[b]) return -1;
  for (int32_t r = 0; r < world; ++r)
    for (int32_t i = 0; i < pages; ++i)
      stack[r * slots + top[r]++] = table[(r * batch + b) * pages + i];
  owned[b] = 0;
  return 0;
}

// Admission control: all-or-nothing over a REQUEST of n rows — if any
// row fails, every row allocated by this call is rolled back.
// Returns 0 or the failing row's error (-1/-2).
int32_t tdt_kv_alloc_many(int32_t world, int32_t batch, int32_t pages,
                          int32_t slots, int32_t* stack, int32_t* top,
                          int32_t* table, uint8_t* owned,
                          const int32_t* rows, int32_t n) {
  for (int32_t j = 0; j < n; ++j) {
    int32_t rc = tdt_kv_alloc_seq(world, batch, pages, slots, stack, top,
                                  table, owned, rows[j]);
    if (rc != 0) {
      for (int32_t k = 0; k < j; ++k)
        tdt_kv_free_seq(world, batch, pages, slots, stack, top, table,
                        owned, rows[k]);
      return rc;
    }
  }
  return 0;
}

}  // extern "C"
