// MoE token→block alignment for grouped-GEMM tile scheduling.
//
// TPU-native equivalent of the reference's CUDA host util
// `moe_ag_scatter_align_block_size_kernel` (csrc/lib/moe_utils.cu:61) and
// the CPU threadblock swizzle reference
// (kernels/nvidia/threadblock_swizzle_ag_moe.cc): given per-pair expert
// ids, produce (a) a stable expert-sorted row order, (b) per-expert row
// segments padded up to the GEMM tile size, and (c) the block→expert map
// a tiled grouped-GEMM kernel iterates over. Used for host-side schedule
// planning of Pallas grouped-GEMM kernels (the XLA ragged_dot path does
// this internally; explicit kernels need the plan). C++ like the
// reference's; ctypes-bound (no pybind11 in this image).
//
// Build: g++ -shared -fPIC -O2 -o libtdtmoe.so moe_align.cc

#include <cstdint>
#include <numeric>
#include <vector>

extern "C" {

// Inputs: n_pairs expert ids in [0, n_experts) (id == n_experts allowed =
// invalid sentinel, sorted last, not padded).
// Outputs:
//   sorted_order[n_pairs]    — stable expert-ascending permutation
//   expert_counts[n_experts] — rows per expert
//   padded_offsets[n_experts+1] — cumulative tile-aligned row offsets
//   block_expert[cap_blocks] — expert id per GEMM row-block (filled up to
//                              return value; caller sizes it with
//                              sum(ceil(count/block)) <= n_pairs +
//                              n_experts extra blocks worst case)
// Returns the number of blocks, -1 if cap_blocks is too small, or -2 if
// any expert id is outside [0, n_experts] (matching the numpy fallback,
// which never indexes out of range).
int32_t tdt_moe_align_block_size(int32_t n_pairs, const int32_t* expert_ids,
                                 int32_t n_experts, int32_t block_size,
                                 int32_t* sorted_order,
                                 int32_t* expert_counts,
                                 int32_t* padded_offsets,
                                 int32_t* block_expert,
                                 int32_t cap_blocks) {
  std::vector<int32_t> counts(n_experts + 1, 0);
  for (int32_t i = 0; i < n_pairs; ++i) {
    if (expert_ids[i] < 0 || expert_ids[i] > n_experts) return -2;
    counts[expert_ids[i]]++;
  }

  // stable counting sort by expert id
  std::vector<int32_t> pos(n_experts + 2, 0);
  for (int32_t e = 0; e <= n_experts; ++e) pos[e + 1] = pos[e] + counts[e];
  std::vector<int32_t> cursor(pos.begin(), pos.end() - 1);
  for (int32_t i = 0; i < n_pairs; ++i)
    sorted_order[cursor[expert_ids[i]]++] = i;

  int32_t n_blocks = 0;
  int32_t off = 0;
  for (int32_t e = 0; e < n_experts; ++e) {
    expert_counts[e] = counts[e];
    padded_offsets[e] = off;
    int32_t blocks = (counts[e] + block_size - 1) / block_size;
    if (n_blocks + blocks > cap_blocks) return -1;
    for (int32_t b = 0; b < blocks; ++b) block_expert[n_blocks++] = e;
    off += blocks * block_size;
  }
  padded_offsets[n_experts] = off;
  return n_blocks;
}

}  // extern "C"
