// Task scheduler for the mega (fused decode step) runtime.
//
// TPU-native equivalent of the reference's scheduler
// (python/triton_dist/mega_triton_kernel/core/scheduler.py:40-95:
// round-robin / zig-zag static assignment of tasks to per-SM work queues)
// plus the dependency resolution the reference does in ModelBuilder
// (models/model_builder.py). C++ because it runs per model-(re)build on
// the host and the reference keeps its scheduling/graph machinery native
// (csrc/, SURVEY.md §2.1); exposed to Python via ctypes (no pybind11 in
// this image).
//
// Build: gcc -shared -fPIC -O2 -o libtdtsched.so scheduler.cc

#include <cstdint>
#include <algorithm>
#include <cstring>
#include <queue>
#include <vector>

extern "C" {

// Round-robin assignment of n_tasks to n_queues. out[i] = queue of task i.
void tdt_schedule_round_robin(int32_t n_tasks, int32_t n_queues,
                              int32_t* out) {
  for (int32_t i = 0; i < n_tasks; ++i) out[i] = i % n_queues;
}

// Zig-zag: 0,1,..,q-1,q-1,..,1,0,0,1,.. — balances queue tail lengths the
// way the reference's ZIG_ZAG policy does for uneven task costs.
void tdt_schedule_zigzag(int32_t n_tasks, int32_t n_queues, int32_t* out) {
  int32_t period = 2 * n_queues;
  for (int32_t i = 0; i < n_tasks; ++i) {
    int32_t r = i % period;
    out[i] = r < n_queues ? r : period - 1 - r;
  }
}

// Cost-aware list scheduling: assign each task (in order) to the queue
// with the least accumulated cost. costs may be null (unit costs).
void tdt_schedule_least_loaded(int32_t n_tasks, int32_t n_queues,
                               const int64_t* costs, int32_t* out) {
  std::vector<int64_t> load(n_queues, 0);
  for (int32_t i = 0; i < n_tasks; ++i) {
    int32_t best = 0;
    for (int32_t q = 1; q < n_queues; ++q)
      if (load[q] < load[best]) best = q;
    out[i] = best;
    load[best] += costs ? costs[i] : 1;
  }
}

// HEFT-style critical-path list scheduling: tasks are prioritized by
// upward rank (longest cost-weighted path to a sink) and placed on the
// queue giving the earliest dependency-respecting start time. Returns the
// resulting makespan (or -1 on a cycle); out[i] = queue of task i. The
// makespan doubles as a speed-of-light estimate for the fused step given
// n_queues-way parallel hardware.
int64_t tdt_schedule_critical_path(int32_t n_tasks, int32_t n_edges,
                                   const int32_t* edges, int32_t n_queues,
                                   const int64_t* costs, int32_t* out) {
  std::vector<std::vector<int32_t>> children(n_tasks), parents(n_tasks);
  std::vector<int32_t> outdeg(n_tasks, 0);
  for (int32_t e = 0; e < n_edges; ++e) {
    int32_t src = edges[2 * e], dst = edges[2 * e + 1];
    children[src].push_back(dst);
    parents[dst].push_back(src);
    outdeg[src]++;
  }
  auto cost = [&](int32_t i) -> int64_t { return costs ? costs[i] : 1; };
  // upward ranks via reverse topological order (Kahn on the transpose)
  std::vector<int64_t> rank(n_tasks, 0);
  std::vector<int32_t> od = outdeg;
  std::queue<int32_t> q;
  int32_t seen = 0;
  for (int32_t i = 0; i < n_tasks; ++i)
    if (od[i] == 0) q.push(i);
  while (!q.empty()) {
    int32_t t = q.front();
    q.pop();
    seen++;
    int64_t best = 0;
    for (int32_t c : children[t])
      if (rank[c] > best) best = rank[c];
    rank[t] = cost(t) + best;
    for (int32_t p : parents[t])
      if (--od[p] == 0) q.push(p);
  }
  if (seen != n_tasks) return -1;
  // priority order: descending rank, ties broken by topological
  // position — raw-id ties could schedule a zero-cost parent's child
  // first (rank equality), violating dependencies.
  std::vector<int32_t> topo(n_tasks), pos(n_tasks);
  {
    std::vector<int32_t> indeg(n_tasks, 0);
    for (int32_t i = 0; i < n_tasks; ++i)
      for (int32_t c2 : children[i]) indeg[c2]++;
    std::priority_queue<int32_t, std::vector<int32_t>,
                        std::greater<int32_t>> rq;
    for (int32_t i = 0; i < n_tasks; ++i)
      if (indeg[i] == 0) rq.push(i);
    int32_t n2 = 0;
    while (!rq.empty()) {
      int32_t t = rq.top();
      rq.pop();
      topo[n2] = t;
      pos[t] = n2++;
      for (int32_t c2 : children[t])
        if (--indeg[c2] == 0) rq.push(c2);
    }
  }
  std::vector<int32_t> order(n_tasks);
  for (int32_t i = 0; i < n_tasks; ++i) order[i] = i;
  std::sort(order.begin(), order.end(), [&](int32_t a, int32_t b) {
    if (rank[a] != rank[b]) return rank[a] > rank[b];
    return pos[a] < pos[b];
  });
  std::vector<int64_t> queue_free(n_queues, 0), finish(n_tasks, 0);
  int64_t makespan = 0;
  for (int32_t t : order) {
    int64_t ready = 0;
    for (int32_t p : parents[t])
      if (finish[p] > ready) ready = finish[p];
    int32_t best_q = 0;
    int64_t best_start = -1;
    for (int32_t qi = 0; qi < n_queues; ++qi) {
      int64_t start = queue_free[qi] > ready ? queue_free[qi] : ready;
      if (best_start < 0 || start < best_start) {
        best_start = start;
        best_q = qi;
      }
    }
    out[t] = best_q;
    finish[t] = best_start + cost(t);
    queue_free[best_q] = finish[t];
    if (finish[t] > makespan) makespan = finish[t];
  }
  return makespan;
}

// HEFT priority linearization: the order tdt_schedule_critical_path
// visits tasks in (descending upward rank, ties by topological
// position). It is itself a valid topological order (a parent's rank is
// >= any child's by at least its own cost; zero-cost ties fall back to
// topo position), so the mega executor can EMIT tasks in this order —
// which biases XLA's buffer-liveness and latency-hiding scheduling
// toward the critical path (measured: bench.py mega part compares peak
// temp memory of topo- vs heft-emitted programs). Returns 0, or -1 on
// a cycle. out receives the task ids in priority order.
int32_t tdt_priority_order(int32_t n_tasks, int32_t n_edges,
                           const int32_t* edges, const int64_t* costs,
                           int32_t* out) {
  std::vector<std::vector<int32_t>> children(n_tasks), parents(n_tasks);
  std::vector<int32_t> outdeg(n_tasks, 0);
  for (int32_t e = 0; e < n_edges; ++e) {
    int32_t src = edges[2 * e], dst = edges[2 * e + 1];
    children[src].push_back(dst);
    parents[dst].push_back(src);
    outdeg[src]++;
  }
  auto cost = [&](int32_t i) -> int64_t { return costs ? costs[i] : 1; };
  std::vector<int64_t> rank(n_tasks, 0);
  std::vector<int32_t> od = outdeg;
  std::queue<int32_t> q;
  int32_t seen = 0;
  for (int32_t i = 0; i < n_tasks; ++i)
    if (od[i] == 0) q.push(i);
  while (!q.empty()) {
    int32_t t = q.front();
    q.pop();
    seen++;
    int64_t best = 0;
    for (int32_t c : children[t])
      if (rank[c] > best) best = rank[c];
    rank[t] = cost(t) + best;
    for (int32_t p : parents[t])
      if (--od[p] == 0) q.push(p);
  }
  if (seen != n_tasks) return -1;
  std::vector<int32_t> topo(n_tasks), pos(n_tasks);
  {
    std::vector<int32_t> indeg(n_tasks, 0);
    for (int32_t i = 0; i < n_tasks; ++i)
      for (int32_t c2 : children[i]) indeg[c2]++;
    std::priority_queue<int32_t, std::vector<int32_t>,
                        std::greater<int32_t>> rq;
    for (int32_t i = 0; i < n_tasks; ++i)
      if (indeg[i] == 0) rq.push(i);
    int32_t n2 = 0;
    while (!rq.empty()) {
      int32_t t = rq.top();
      rq.pop();
      topo[n2] = t;
      pos[t] = n2++;
      for (int32_t c2 : children[t])
        if (--indeg[c2] == 0) rq.push(c2);
    }
  }
  for (int32_t i = 0; i < n_tasks; ++i) out[i] = i;
  std::sort(out, out + n_tasks, [&](int32_t a, int32_t b) {
    if (rank[a] != rank[b]) return rank[a] > rank[b];
    return pos[a] < pos[b];
  });
  return 0;
}

// Kahn topological sort with stable tie-break by task id (the dependency
// resolution of the reference's ModelBuilder). edges: n_edges pairs
// (src, dst) meaning dst depends on src. Returns 0 on success, -1 on a
// cycle. out receives the execution order (task ids).
int32_t tdt_toposort(int32_t n_tasks, int32_t n_edges, const int32_t* edges,
                     int32_t* out) {
  std::vector<std::vector<int32_t>> adj(n_tasks);
  std::vector<int32_t> indeg(n_tasks, 0);
  for (int32_t e = 0; e < n_edges; ++e) {
    int32_t src = edges[2 * e], dst = edges[2 * e + 1];
    adj[src].push_back(dst);
    indeg[dst]++;
  }
  std::priority_queue<int32_t, std::vector<int32_t>,
                      std::greater<int32_t>> ready;
  for (int32_t i = 0; i < n_tasks; ++i)
    if (indeg[i] == 0) ready.push(i);
  int32_t n = 0;
  while (!ready.empty()) {
    int32_t t = ready.top();
    ready.pop();
    out[n++] = t;
    for (int32_t d : adj[t])
      if (--indeg[d] == 0) ready.push(d);
  }
  return n == n_tasks ? 0 : -1;
}

// Dependency-aware wavefront partition: tasks with equal depth (longest
// path from a source) share a wave — the analog of the reference's
// scoreboard-separated phases; waves become fusion groups for the jit
// executor. Returns the number of waves; out_wave[i] = wave of task i.
int32_t tdt_wavefronts(int32_t n_tasks, int32_t n_edges,
                       const int32_t* edges, int32_t* out_wave) {
  std::vector<std::vector<int32_t>> adj(n_tasks);
  std::vector<int32_t> indeg(n_tasks, 0);
  for (int32_t e = 0; e < n_edges; ++e) {
    adj[edges[2 * e]].push_back(edges[2 * e + 1]);
    indeg[edges[2 * e + 1]]++;
  }
  std::vector<int32_t> depth(n_tasks, 0);
  std::queue<int32_t> ready;
  for (int32_t i = 0; i < n_tasks; ++i)
    if (indeg[i] == 0) ready.push(i);
  int32_t max_depth = -1, seen = 0;
  while (!ready.empty()) {
    int32_t t = ready.front();
    ready.pop();
    seen++;
    if (depth[t] > max_depth) max_depth = depth[t];
    for (int32_t d : adj[t]) {
      if (depth[t] + 1 > depth[d]) depth[d] = depth[t] + 1;
      if (--indeg[d] == 0) ready.push(d);
    }
  }
  if (seen != n_tasks) return -1;
  std::memcpy(out_wave, depth.data(), n_tasks * sizeof(int32_t));
  return max_depth + 1;
}

}  // extern "C"
