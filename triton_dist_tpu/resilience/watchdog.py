"""Compile watchdog: bound the first compile of every fused op.

A Mosaic compile hang is the one failure class that neither raises nor
returns — round 3 and round 5 both lost hours of hardware time to a
single kernel build that never came back (BENCH_NOTES_r3.md wedges
#2-#4; the r5 paged-``direct`` hang froze the ``hw_watch`` queue). The
watchdog runs a suspect thunk in a daemon worker thread and gives it
``TDT_COMPILE_TIMEOUT_S`` to produce a result; on expiry the caller
gets :class:`CompileTimeout` and moves on, and the worker thread is
ABANDONED, never killed — SIGKILLing a client mid-compile is the known
tunnel-wedge trigger (tpu_smoke.py ``run_subproc`` docstring), and a
Python thread cannot be killed anyway. The abandoned thread finishes
(or hangs) in the background; its result is discarded.

The router only routes first-time (op, config) keys through the
watchdog — a key that has compiled once cannot hang on compile again
in this process, so steady-state calls pay nothing. Timeouts default
ON on TPU (where the hang class lives) and OFF on CPU test meshes,
where interpret-mode kernels are slow-but-finite and a worker thread
per op would only add scheduling noise; ``TDT_COMPILE_TIMEOUT_S``
overrides either way (``0`` disables).
"""

from __future__ import annotations

import os
import threading

__all__ = ["CompileTimeout", "compile_timeout_s", "run_with_timeout"]

#: Default first-compile budget on TPU backends. Cold Mosaic compiles
#: of the budget-shape kernels measure ~30 s through the tunnel
#: (docs/autotuner.md); 600 s is an order of magnitude of headroom —
#: anything past it is the hang class, not a slow compile.
DEFAULT_TPU_TIMEOUT_S = 600.0


class CompileTimeout(TimeoutError):
    """A guarded thunk exceeded its compile budget (or a
    ``compile_timeout`` fault was injected)."""

    def __init__(self, op: str, key: str = "", timeout_s: float = 0.0):
        self.op = op
        self.key = key
        self.timeout_s = timeout_s
        super().__init__(
            f"compile watchdog tripped for op {op!r} after "
            f"{timeout_s:g}s (config {key or '?'})")


def _on_tpu() -> bool:
    try:
        import jax
        return jax.default_backend() == "tpu"
    except Exception:  # noqa: BLE001 — no backend ⇒ no TPU hang class
        return False


def compile_timeout_s() -> float:
    """Effective watchdog budget in seconds; ``<= 0`` disables."""
    env = os.environ.get("TDT_COMPILE_TIMEOUT_S")
    if env is not None and env.strip():
        try:
            return float(env)
        except ValueError:
            raise ValueError(
                f"TDT_COMPILE_TIMEOUT_S must be a number: {env!r}"
            ) from None
    return DEFAULT_TPU_TIMEOUT_S if _on_tpu() else 0.0


def run_with_timeout(thunk, timeout_s: float, *, op: str = "?",
                     key: str = ""):
    """Run ``thunk()`` with a deadline; raise :class:`CompileTimeout`
    on expiry (the worker thread is abandoned, never killed).

    ``timeout_s <= 0`` calls the thunk inline. Exceptions from the
    thunk re-raise in the caller."""
    if timeout_s <= 0:
        return thunk()
    box: dict = {}
    done = threading.Event()

    def worker():
        try:
            box["out"] = thunk()
        except BaseException as e:  # noqa: BLE001 — relayed to caller
            box["exc"] = e
        finally:
            done.set()

    t = threading.Thread(target=worker, daemon=True,
                         name=f"tdt-watchdog-{op}")
    t.start()
    if not done.wait(timeout_s):
        raise CompileTimeout(op, key, timeout_s)
    if "exc" in box:
        raise box["exc"]
    return box.get("out")
