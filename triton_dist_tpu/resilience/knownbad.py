"""On-disk known-bad config cache: never re-enter a compile hang.

Round 5's paged flash-decode ``direct`` kernel hung Mosaic and wedged
the hardware queue for the rest of the round; nothing recorded the
(op, config, device_kind) that did it, so the next session was one
env-var typo away from re-entering the same hang. This cache is that
record: the compile watchdog writes the exact tuple on every trip, the
fallback router checks it before dispatching a fused kernel, and the
file persists across processes so a hang discovered by ``tpu_smoke``
protects the serving process that starts an hour later.

File format (``docs/resilience.md``): a single JSON object mapping
``"<op>|<device_kind>|<config>"`` →

    {"op": ..., "device_kind": ..., "config": ...,
     "reason": ..., "ts": <unix seconds>}

Writes are atomic (tmp + ``os.replace``) and merge with the on-disk
state first, so concurrent processes can both record trips without
losing entries. A corrupt or unreadable file reads as empty — the
resilience layer must degrade the cache, never the op path.

Path resolution: ``TDT_KNOWN_BAD_CACHE`` env var, else
``~/.cache/triton_dist_tpu/known_bad.json`` (tests isolate via the
env var, like ``TDT_AUTOTUNE_CACHE``).
"""

from __future__ import annotations

import json
import os
import threading
import time

from triton_dist_tpu import obs

__all__ = ["KnownBadCache", "cache_path", "get_cache", "make_key",
           "reset_cache"]


def cache_path() -> str:
    env = os.environ.get("TDT_KNOWN_BAD_CACHE")
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "triton_dist_tpu", "known_bad.json")


def make_key(op: str, config: str, device_kind: str) -> str:
    """The cache key for one (op, config, device_kind) tuple. ``|`` is
    the field separator; embedded pipes in config are tolerated (the
    key is only ever compared whole)."""
    return f"{op}|{device_kind}|{config}"


class KnownBadCache:
    """Lazy-loading view of one known-bad cache file."""

    def __init__(self, path: str | None = None):
        self.path = path or cache_path()
        self._lock = threading.Lock()
        self._entries: dict[str, dict] | None = None

    def _read_disk(self) -> dict[str, dict]:
        try:
            with open(self.path) as f:
                data = json.load(f)
            if isinstance(data, dict):
                return {k: v for k, v in data.items()
                        if isinstance(v, dict)}
        except (OSError, ValueError):
            pass
        return {}

    def _loaded(self) -> dict[str, dict]:
        """The live entry dict (lazy first load). Callers treat it as
        read-only; mutation happens only in :meth:`record` under the
        lock, so lock-free membership reads are race-benign."""
        with self._lock:
            if self._entries is None:
                self._entries = self._read_disk()
                self._emit_size()
            return self._entries

    @staticmethod
    def _expired(entry: dict) -> bool:
        """TDT_KNOWN_BAD_TTL_S (seconds; 0/unset = never expire) ages
        entries out of every view — routing, entries(), len, and the
        size gauge agree — for environments where a trip may have been
        slow-that-day rather than hung."""
        ttl = float(os.environ.get("TDT_KNOWN_BAD_TTL_S", "0") or 0)
        return ttl > 0 and time.time() - entry.get("ts", 0.0) > ttl

    def entries(self) -> dict[str, dict]:
        return {k: v for k, v in self._loaded().items()
                if not self._expired(v)}

    def _emit_size(self) -> None:
        live = sum(1 for v in (self._entries or {}).values()
                   if not self._expired(v))
        obs.gauge("resilience.known_bad.size").set(live)

    def __contains__(self, key: str) -> bool:
        # Hot path: router.decide() calls this per eager guarded op —
        # membership on the live dict, no copy.
        entry = self._loaded().get(key)
        return entry is not None and not self._expired(entry)

    def __len__(self) -> int:
        return sum(1 for v in self._loaded().values()
                   if not self._expired(v))

    def refresh(self) -> None:
        """Drop the in-memory view; the next read reloads from disk
        (pick up another process's trips without restarting)."""
        with self._lock:
            self._entries = None

    def record(self, op: str, config: str, device_kind: str,
               reason: str) -> str:
        """Persist one known-bad tuple; returns its key. Merges with
        the current on-disk state under the lock so concurrent
        recorders do not drop each other's entries."""
        key = make_key(op, config, device_kind)
        entry = {"op": op, "device_kind": device_kind, "config": config,
                 "reason": reason, "ts": time.time()}
        with self._lock:
            merged = self._read_disk()
            if self._entries:
                merged.update(self._entries)
            merged[key] = entry
            self._entries = merged
            try:
                d = os.path.dirname(self.path)
                if d:
                    os.makedirs(d, exist_ok=True)
                tmp = f"{self.path}.tmp.{os.getpid()}"
                with open(tmp, "w") as f:
                    json.dump(merged, f, indent=1, sort_keys=True)
                os.replace(tmp, self.path)
            except OSError:
                # Disk trouble must not mask the failure being
                # recorded; the in-memory entry still routes this
                # process away from the bad config.
                pass
            self._emit_size()
        return key


_CACHE: KnownBadCache | None = None
_CACHE_LOCK = threading.Lock()


def get_cache() -> KnownBadCache:
    """Process-wide cache singleton, rebuilt if the configured path
    changed (tests repoint ``TDT_KNOWN_BAD_CACHE`` per test)."""
    global _CACHE
    path = cache_path()
    with _CACHE_LOCK:
        if _CACHE is None or _CACHE.path != path:
            _CACHE = KnownBadCache(path)
        return _CACHE


def reset_cache() -> None:
    global _CACHE
    with _CACHE_LOCK:
        _CACHE = None
