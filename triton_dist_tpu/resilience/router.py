"""Fallback router: every fused op keeps an always-available escape
hatch to its XLA reference path.

Triton-distributed itself treats the hand-written overlapped kernel as
one routing choice among several per shape/topology (arXiv:2504.19442
§5), and T3-style transparent overlap (arXiv:2401.16677) presumes a
safe non-fused path always exists. This module makes that stance
structural: the :func:`resilient` decorator wraps every public op
entry in ``ops/`` and, per call, chooses between the fused
implementation and the op's ``impl="xla"`` reference branch — the same
function, same arguments, different ``impl`` — so a fallback is
bit-identical to calling the reference path directly.

Routing order (first match wins), per (op, config, device_kind):

1. ``TDT_FORCE_FUSED=1``    → fused, always (bench / smoke / manual
   revalidation; the watchdog still guards the compile).
2. known-bad cache hit      → XLA (``resilience.knownbad`` — a config
   that ever hung Mosaic is never re-entered, across processes).
3. BASELINE policy          → XLA for regimes where the measured
   ``<op>_vs_xla`` ratio says the fused kernel is slower
   (``BASELINE.json`` ``regression_floors``; see :func:`policy_reason`).
4. open circuit breaker     → XLA until the cooldown's half-open probe
   (``resilience.breaker``).
5. otherwise                → fused, guarded: first-compile runs under
   the watchdog (``resilience.watchdog``), infra failures (Mosaic /
   XLA runtime errors, injected faults, watchdog trips, optional
   non-finite-output guard) record into the breaker + known-bad cache
   and the call retries on the XLA path. User errors (bad shapes,
   unsupported compositions: ``ValueError`` / ``AssertionError`` /
   ``NotImplementedError`` / ``TypeError``) propagate unchanged.

Everything here is Python-side and works at trace time too — under
``jax.jit`` the routing decision is baked into the traced program
(like the ``comms.*`` counters, it is per program build; a breaker
that opens later does not rewrite already-compiled programs).

Metric surface (docs/observability.md): ``resilience.fallbacks_total``,
``resilience.<op>.fallbacks_total`` / ``.fallback.<reason>`` /
``.fused_total``, ``resilience.watchdog.trips`` /
``resilience.<op>.watchdog_trips``, breaker + known-bad gauges.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import inspect
import json
import os
import threading
import time

from triton_dist_tpu import obs
from triton_dist_tpu.resilience import knownbad
from triton_dist_tpu.resilience.breaker import get_breaker
from triton_dist_tpu.resilience.watchdog import (CompileTimeout,
                                                 compile_timeout_s,
                                                 run_with_timeout)

__all__ = ["FallbackSpec", "NonFiniteOutput", "decide", "device_kind",
           "force_fused", "policy_reason", "registered_fallbacks",
           "resilient", "reset_router"]


class NonFiniteOutput(RuntimeError):
    """The numeric guard (``TDT_NUMERIC_GUARD=1``) found NaN/inf in a
    fused op's eager output. Infra-class: the call is retried on the
    XLA reference path and the breaker records the failure."""

    def __init__(self, op: str):
        self.op = op
        super().__init__(
            f"fused op {op!r} produced non-finite outputs")


# ---------------------------------------------------------------------------
# Registry: which entries have an escape hatch (tools/fallback_lint.py
# cross-checks this against the public surface of ops/).
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class FallbackSpec:
    op: str
    entry: str                      # "module.qualname" of the entry fn
    fused_impls: tuple[str, ...]
    fallback_impl: str


_REGISTRY: dict[str, FallbackSpec] = {}


def registered_fallbacks() -> dict[str, FallbackSpec]:
    return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# Environment / platform probes (read per call so tests can monkeypatch).
# ---------------------------------------------------------------------------

def force_fused() -> bool:
    """``TDT_FORCE_FUSED=1``: bypass all routing, always run fused
    (bench.py and tpu_smoke.py set this — a measurement or smoke run
    that silently measured XLA would be worse than one that fails)."""
    return os.environ.get("TDT_FORCE_FUSED", "").strip() in (
        "1", "true", "yes")


def _numeric_guard_enabled() -> bool:
    return os.environ.get("TDT_NUMERIC_GUARD", "").strip() in (
        "1", "true", "yes")


_DEVICE_KIND: str | None = None


def device_kind() -> str:
    """``device_kind`` of device 0 (the known-bad cache's third key
    field — a config that hangs v5e Mosaic may be fine on v5p)."""
    global _DEVICE_KIND
    if _DEVICE_KIND is None:
        try:
            import jax
            d = jax.devices()[0]
            _DEVICE_KIND = str(getattr(d, "device_kind", d.platform))
        except Exception:  # noqa: BLE001 — no backend yet
            return "unknown"
    return _DEVICE_KIND


def _platform_tier() -> str:
    try:
        import jax
        return "tpu" if jax.default_backend() == "tpu" else "cpu"
    except Exception:  # noqa: BLE001
        return "cpu"


# ---------------------------------------------------------------------------
# BASELINE-driven policy.
# ---------------------------------------------------------------------------

_BASELINE_CACHE: dict[str, dict] = {}


def _baseline_path() -> str:
    env = os.environ.get("TDT_BASELINE_PATH")
    if env:
        return env
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.join(os.path.dirname(os.path.dirname(here)),
                        "BASELINE.json")


def _baseline_ratios(tier: str) -> dict:
    path = _baseline_path()
    key = f"{path}|{tier}"
    cached = _BASELINE_CACHE.get(key)
    if cached is None:
        ratios = {}
        try:
            with open(path) as f:
                floors = json.load(f).get("regression_floors", {})
            tbl = floors.get(tier, {})
            ratios = {k: float(v) for k, v in tbl.items()
                      if not k.startswith("_")
                      and isinstance(v, (int, float))}
        except (OSError, ValueError):
            pass
        cached = _BASELINE_CACHE[key] = ratios
    return cached


def _routing_tier() -> str | None:
    """Which BASELINE tier drives policy routing, or None for off.

    Default: the ``tpu`` table on TPU backends only. The ``cpu`` table
    explicitly prices the interpret-mode simulator, not the kernels
    (BASELINE.json ``_comment``), and the CPU mesh is the test tier —
    auto-routing there would silently turn every fused-path test into
    an XLA test. ``TDT_BASELINE_ROUTING`` overrides: ``off``/``0``
    disables everywhere, ``tpu``/``cpu`` forces that table (the test
    hook for exercising the policy on the CPU mesh)."""
    env = os.environ.get("TDT_BASELINE_ROUTING", "").strip().lower()
    if env in ("off", "0", "none"):
        return None
    if env in ("tpu", "cpu"):
        return env
    tier = _platform_tier()
    return "tpu" if tier == "tpu" else None


def policy_reason(op: str) -> str | None:
    """Non-None iff the active perf data says this op's fused kernel
    is clearly slower than XLA in the active tier.

    Two data sources, freshest first (docs/resilience.md "Live ratios
    vs BASELINE floors"):

    1. **Live measured ratio** (``obs.perfwatch``): rolling medians of
       the wall times the ``@resilient`` entries themselves recorded,
       consulted once BOTH branches carry
       ``TDT_PERFWATCH_MIN_SAMPLES`` samples — a chip run
       self-corrects a stale floor without a redeploy.
       ``TDT_PERFWATCH_ROUTING=0`` opts out.
    2. **Static BASELINE floor**: the ``regression_floors`` table is a
       CI gate that deliberately sits just UNDER the measured ratios
       (BASELINE.json ``_comment``), so a floor slightly below 1.0 can
       belong to an op that actually measures faster than XLA (r5
       gemm_ar: floor 0.95, measured 1.065×).

    Both compare against ``TDT_POLICY_THRESHOLD`` (default 0.9): route
    to XLA only below it, treat [threshold, ∞) as parity-or-better —
    the parity margin floors need because they understate measured
    ratios (live medians don't, but one threshold keeps the policy
    legible). Every decision's provenance counts into
    ``resilience.policy_source.{live,floor}`` (plus per-op twins), so
    the floor→live switchover is observable."""
    tier = _routing_tier()
    if tier is None:
        return None
    thr = float(os.environ.get("TDT_POLICY_THRESHOLD", "0.9"))
    from triton_dist_tpu.obs import perfwatch
    if perfwatch.routing_enabled():
        live = perfwatch.ratio(op)
        if live is not None:
            obs.counter("resilience.policy_source.live").inc()
            obs.counter(f"resilience.{op}.policy_source.live").inc()
            if live < thr:
                return (f"live {op}_vs_xla={round(live, 4)} < {thr} "
                        f"(perfwatch median)")
            return None
    ratio = _baseline_ratios(tier).get(f"{op}_vs_xla")
    if ratio is None:
        return None
    obs.counter("resilience.policy_source.floor").inc()
    obs.counter(f"resilience.{op}.policy_source.floor").inc()
    if ratio < thr:
        return f"{op}_vs_xla={ratio} < {thr} ({tier})"
    return None


# ---------------------------------------------------------------------------
# The routing decision.
# ---------------------------------------------------------------------------

def decide(op: str, key: str) -> str | None:
    """None → run fused; otherwise the fallback reason string."""
    if force_fused():
        return None
    if key in knownbad.get_cache():
        return "known_bad"
    if policy_reason(op) is not None:
        return "policy"
    if not get_breaker(op).allow():
        return "breaker"
    return None


def _count_fallback(op: str, reason: str) -> None:
    obs.counter("resilience.fallbacks_total").inc()
    obs.counter(f"resilience.{op}.fallbacks_total").inc()
    obs.counter(f"resilience.{op}.fallback.{reason}").inc()
    obs.trace.instant(f"resilience.{op}.fallback", "resilience",
                      args={"op": op, "reason": reason})


def _record_failure(op: str, key: str, config: str, exc) -> None:
    get_breaker(op).record_failure()
    obs.trace.instant(f"resilience.{op}.failure", "resilience",
                      args={"op": op, "type": type(exc).__name__,
                            "config": config[:200]})
    if isinstance(exc, CompileTimeout):
        obs.counter("resilience.watchdog.trips").inc()
        obs.counter(f"resilience.{op}.watchdog_trips").inc()
        knownbad.get_cache().record(op, config, device_kind(),
                                    reason=f"compile_timeout: {exc}")
        # A hang postmortem: dump the trailing event window — what ran
        # in the seconds before this compile wedged — to disk
        # (docs/observability.md "Flight recorder"; rate-limited,
        # never raises, no-op when tracing is off).
        obs.flight.maybe_dump(f"watchdog_{op}")
    elif _is_compile_error(exc):
        # Deterministic compiler breaks (Mosaic rejection, Pallas
        # lowering failure) re-break on every process restart — record
        # them like hangs so no process re-enters the compile, instead
        # of each one burning breaker-threshold attempts rediscovering
        # it (runtime errors stay out: they may be transient).
        knownbad.get_cache().record(
            op, config, device_kind(),
            reason=f"compile_error: {type(exc).__name__}: "
                   f"{str(exc)[:200]}")


#: Exception type names treated as infra failures when raised from a
#: fused path. Matched by name: the concrete classes live in jaxlib /
#: Mosaic modules whose import paths move between jax versions.
_INFRA_EXC_NAMES = frozenset({
    "XlaRuntimeError", "JaxRuntimeError", "InternalError",
    "MosaicError", "LoweringError", "LoweringException",
    "VerificationError",
})

#: The deterministic-compiler-break subset of the infra classes: these
#: reproduce on every compile of the config, so they join watchdog
#: trips in the known-bad cache.
_COMPILE_EXC_NAMES = frozenset({
    "MosaicError", "LoweringError", "LoweringException",
    "VerificationError",
})


def _is_compile_error(e: BaseException) -> bool:
    t = type(e)
    return (t.__name__ in _COMPILE_EXC_NAMES
            or "mosaic" in (t.__module__ or "").lower())


def _is_infra_error(e: BaseException) -> bool:
    from triton_dist_tpu.testing.faults import InjectedFault
    if isinstance(e, (CompileTimeout, InjectedFault, NonFiniteOutput)):
        return True
    t = type(e)
    if t.__name__ in _INFRA_EXC_NAMES:
        return True
    mod = (t.__module__ or "").lower()
    return "mosaic" in mod


# ---------------------------------------------------------------------------
# The @resilient decorator.
# ---------------------------------------------------------------------------

_TLS = threading.local()

#: (op, config, device_kind) keys that have completed a fused run in
#: this process — later calls skip the watchdog thread (a key that
#: compiled once cannot hang on compile again).
_COMPILED: set[str] = set()


def _in_resilient() -> bool:
    return getattr(_TLS, "depth", 0) > 0


class _Reentrant:
    """Nested op entries (ag_gemm → ag_gemm_multi, paged → gathered
    decode, autotune sweeps) run under the outer guard only."""

    def __enter__(self):
        _TLS.depth = getattr(_TLS, "depth", 0) + 1
        return self

    def __exit__(self, *exc):
        _TLS.depth -= 1
        return False


#: Context fields worth distinguishing in a config key: the knobs that
#: select a kernel variant / tile schedule (the things a compile hang
#: depends on).
_CTX_KEY_FIELDS = ("variant", "paged_variant", "method", "block_m",
                   "block_n", "block_k", "t_blk", "ring_dirs",
                   "vmem_budget")


def _default_config(bound: inspect.BoundArguments,
                    env_keys: tuple[str, ...] = ()) -> str:
    parts = []
    for name, v in bound.arguments.items():
        if hasattr(v, "shape") and hasattr(v, "dtype"):
            parts.append(f"{name}={tuple(v.shape)}:{v.dtype}")
        elif (isinstance(v, (list, tuple)) and v
              and all(hasattr(e, "shape") and hasattr(e, "dtype")
                      for e in v)):
            # ag_gemm_multi-style operand lists.
            parts.append(name + "=[" + ";".join(
                f"{tuple(e.shape)}:{e.dtype}" for e in v) + "]")
        elif dataclasses.is_dataclass(v) and not isinstance(v, type):
            for fld in _CTX_KEY_FIELDS:
                if hasattr(v, fld):
                    fv = getattr(v, fld)
                    if isinstance(fv, (int, str, bool, type(None))):
                        parts.append(f"{fld}={fv}")
        elif isinstance(v, (int, str, bool)) or v is None:
            parts.append(f"{name}={v}")
    for k in env_keys:
        # Variant-selecting env overrides (TDT_PAGED_VARIANT,
        # TDT_RING_DIRS): when ctx is None the entry builds a default
        # context AFTER this key is computed, so the env override is
        # the only visible variant selector — without it a hang in one
        # variant would share a key with (and wrongly route) the other.
        ev = os.environ.get(k)
        if ev:
            parts.append(f"{k}={ev}")
    return ",".join(parts)


def _has_tracer(bound: inspect.BoundArguments) -> bool:
    import jax
    for v in bound.arguments.values():
        for leaf in jax.tree_util.tree_leaves(v):
            if isinstance(leaf, jax.core.Tracer):
                return True
    return False


def _shape_bucket(bound: inspect.BoundArguments) -> str:
    """Perfwatch pooling key for this call: the pow2-rounded shape
    signature of its array operands (``ops.common.shape_bucket``) —
    coarser than the resilience config key on purpose."""
    from triton_dist_tpu.ops.common import shape_bucket
    arrays = []
    for v in bound.arguments.values():
        if hasattr(v, "shape") and hasattr(v, "dtype"):
            arrays.append(v)
        elif (isinstance(v, (list, tuple)) and v
              and all(hasattr(e, "shape") and hasattr(e, "dtype")
                      for e in v)):
            arrays.extend(v)
    return shape_bucket(*arrays)


def _elapsed_ms(t0: float, out) -> float | None:
    """Wall time since ``t0`` with ``out`` materialized first (so the
    sample is device time, not async-dispatch time — the same
    observer cost the engine spans document); None when blocking
    fails. Observation only: never raises."""
    try:
        import jax
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) * 1e3
    except Exception:  # noqa: BLE001 — observation only
        return None


def _record_sample(op: str, branch: str, bound, t0: float, out) -> None:
    """One live perf sample for an EAGER op call, into
    ``obs.perfwatch``. Telemetry must never break the call it
    measures."""
    ms = _elapsed_ms(t0, out)
    if ms is None:
        return
    try:
        from triton_dist_tpu.obs import perfwatch
        perfwatch.record(op, branch, _shape_bucket(bound), ms)
    except Exception:  # noqa: BLE001 — observation only
        pass


def _op_annotation(op: str, impl, fallback_impl):
    """xprof ``TraceAnnotation`` labeling this invocation's branch —
    ``device.<op>.fused`` / ``device.<op>.xla`` — the label
    ``obs.devprof`` attributes measured device time by (an eager call
    brackets real execution; under jit it brackets trace time, like
    the ``comms.*`` counters). Must never break the call: degrades to
    a null context when the profiler side is unavailable. The
    annotation-coverage pass (``tdt-check``) statically verifies this
    wrapper stays on the invocation path — without it the parser
    silently books every op's device time as ``device.unlabeled_ms``."""
    try:
        from triton_dist_tpu.tools.profiler import annotate
        branch = "xla" if impl == fallback_impl else "fused"
        return annotate(f"device.{op}.{branch}")
    except Exception:  # noqa: BLE001 — labeling is observation only
        return contextlib.nullcontext()


def _all_finite(out) -> bool:
    from triton_dist_tpu.runtime.utils import tree_all_finite
    return tree_all_finite(out)


def _nan_fill(out):
    import jax
    import jax.numpy as jnp

    def fill(leaf):
        if isinstance(leaf, jax.Array) and jnp.issubdtype(
                leaf.dtype, jnp.floating):
            return jnp.full_like(leaf, jnp.nan)
        return leaf

    return jax.tree_util.tree_map(fill, out)


def resilient(op: str, *, fused_impls: tuple[str, ...] = ("pallas",),
              fallback_impl: str = "xla", config_fn=None,
              env_keys: tuple[str, ...] = ()):
    """Wrap an op entry with watchdog + breaker + fallback routing.

    The entry must take an ``impl`` parameter whose ``fallback_impl``
    value selects the jax.lax/XLA reference path. Calls whose ``impl``
    is not in ``fused_impls`` (already on the reference path, or on a
    collective-composition impl like sp_attention's ``ring``) pass
    through untouched. ``config_fn(bound_arguments) -> str`` overrides
    the default shape/dtype/ctx-field config key; ``env_keys`` folds
    the named env vars into the default key (variant selectors that
    bypass the ctx object)."""

    def deco(fn):
        sig = inspect.signature(fn)
        _REGISTRY[op] = FallbackSpec(
            op=op, entry=f"{fn.__module__}.{fn.__qualname__}",
            fused_impls=tuple(fused_impls), fallback_impl=fallback_impl)

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if _in_resilient():
                return fn(*args, **kwargs)
            try:
                bound = sig.bind(*args, **kwargs)
                bound.apply_defaults()
            except TypeError:
                # Let the entry raise its own signature error.
                return fn(*args, **kwargs)
            if bound.arguments.get("impl") not in fused_impls:
                # Untouched by routing — but an explicit eager call of
                # the reference path is a live "xla" sample for the
                # perf watch (tests, benches, and direct users are the
                # main source of reference-branch wall times).
                if (bound.arguments.get("impl") == fallback_impl
                        and obs.enabled() and not _has_tracer(bound)):
                    t0 = time.perf_counter()
                    out = fn(*args, **kwargs)
                    _record_sample(op, "xla", bound, t0, out)
                    return out
                return fn(*args, **kwargs)
            config = (config_fn(bound) if config_fn
                      else _default_config(bound, env_keys))
            key = knownbad.make_key(op, config, device_kind())

            def call(impl):
                # Fresh binding per invocation: an abandoned watchdog
                # worker still running the fused call must not share
                # mutable argument state with the main thread's
                # fallback re-invocation (a shared impl slot could
                # race the fallback back onto the fused path).
                b = sig.bind(*args, **kwargs)
                b.apply_defaults()
                b.arguments["impl"] = impl
                with _Reentrant(), \
                        _op_annotation(op, impl, fallback_impl):
                    return fn(*b.args, **b.kwargs)

            reason = decide(op, key)
            if reason is not None:
                if reason == "policy":
                    from triton_dist_tpu.obs import perfwatch
                    from triton_dist_tpu.resilience.breaker import (
                        CLOSED)
                    # Exploration probe (the policy-route analog of
                    # the breaker's half-open): every Nth
                    # policy-routed call runs the fused branch anyway,
                    # so fused medians stay fresh and a recovered
                    # kernel can route back in — never for known-bad
                    # routes (decide() ordered them first), and only
                    # while the breaker is fully CLOSED: decide()
                    # checks policy before the breaker, so "policy"
                    # can mask a breaker that is open over real infra
                    # failures, and a probe must not re-enter those
                    # (nor steal the half-open state's single-probe
                    # slot).
                    if (perfwatch.routing_enabled()
                            and get_breaker(op).state == CLOSED
                            and perfwatch.take_probe(op)):
                        obs.counter(
                            f"resilience.{op}.policy_probes").inc()
                        return _guarded(op, key, config, call,
                                        bound, fallback_impl)
                _count_fallback(op, reason)
                if obs.enabled() and not _has_tracer(bound):
                    t0 = time.perf_counter()
                    out = call(fallback_impl)
                    _record_sample(op, "xla", bound, t0, out)
                    return out
                return call(fallback_impl)
            return _guarded(op, key, config, call,
                            bound, fallback_impl)

        wrapper.__tdt_resilient_op__ = op
        return wrapper

    return deco


def _guarded(op, key, config, call, bound, fallback_impl):
    """Run the fused path with watchdog + fault hooks; on an infra
    failure, record it and retry on the reference path."""
    from triton_dist_tpu.testing import faults

    fused_impl = bound.arguments["impl"]
    obs.counter(f"resilience.{op}.fused_total").inc()
    tracing = _has_tracer(bound)
    timeout = compile_timeout_s()
    rec = not tracing and obs.enabled()
    t0 = time.perf_counter() if rec else 0.0
    try:
        f = faults.take("comm_error", op) if faults.active() else None
        if f is not None:
            raise faults.InjectedFault(f"{f.message} (op {op})")
        f = (faults.take("compile_timeout", op)
             if faults.active() else None)
        if f is not None:
            raise CompileTimeout(op, key, 0.0)
        if not tracing and timeout > 0 and key not in _COMPILED:

            def thunk():
                # Runs in the watchdog worker thread; call() re-enters
                # the reentrancy guard on that thread's own stack.
                hang = (faults.take("compile_hang", op)
                        if faults.active() else None)
                if hang is not None:
                    import time
                    time.sleep(hang.hang_s)
                return call(fused_impl)

            out = run_with_timeout(thunk, timeout, op=op, key=key)
        else:
            out = call(fused_impl)
        # Stop the fused clock HERE: the numeric guard below is
        # measurement overhead the xla branch never pays — timing it
        # into the fused median would bias live ratios low and route
        # ops to XLA on observer cost, not kernel performance.
        fused_ms = _elapsed_ms(t0, out) if rec else None
        if not tracing:
            f = (faults.take("nan_payload", op)
                 if faults.active() else None)
            if f is not None:
                out = _nan_fill(out)
            if _numeric_guard_enabled() and not _all_finite(out):
                raise NonFiniteOutput(op)
    except Exception as e:  # noqa: BLE001 — classified below
        if not _is_infra_error(e):
            raise
        _record_failure(op, key, config, e)
        if force_fused():
            # Bench/smoke set TDT_FORCE_FUSED precisely so a run can
            # never silently measure the XLA fallback while claiming
            # to measure the fused kernel — the failure is recorded
            # (breaker, known-bad, counters) and then SURFACES.
            raise
        reason = ("watchdog" if isinstance(e, CompileTimeout)
                  else "nonfinite" if isinstance(e, NonFiniteOutput)
                  else "error")
        _count_fallback(op, reason)
        if rec:
            t1 = time.perf_counter()
            out = call(fallback_impl)
            _record_sample(op, "xla", bound, t1, out)
            return out
        return call(fallback_impl)
    if not tracing:
        # Only a real execution proves anything: a successful TRACE
        # must neither mark the key compiled (the genuine first Mosaic
        # compile — the hang class — comes later and must stay under
        # the watchdog) nor close a half-open breaker.
        _COMPILED.add(key)
        get_breaker(op).record_success()
        if rec and fused_ms is not None:
            try:
                from triton_dist_tpu.obs import perfwatch
                perfwatch.record(op, "fused", _shape_bucket(bound),
                                 fused_ms)
            except Exception:  # noqa: BLE001 — observation only
                pass
    return out


def reset_router() -> None:
    """Drop router process state (tests): compiled-key set, baseline
    cache, breakers, known-bad singleton, live perf-ratio windows. The
    fallback registry is code-derived and survives."""
    from triton_dist_tpu.obs import perfwatch
    from triton_dist_tpu.resilience.breaker import reset_breakers
    _COMPILED.clear()
    _BASELINE_CACHE.clear()
    reset_breakers()
    knownbad.reset_cache()
    perfwatch.reset()
