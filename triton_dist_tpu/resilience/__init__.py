"""Resilience subsystem: compile watchdog, circuit-breaker fallback
routing, and the known-bad config cache.

Round 5 proved the stack can reach the chip but not survive it: one
Mosaic compile hang (the paged flash-decode ``direct`` kernel) wedged
the hardware queue for the rest of the round, and the fused ops that
measure slower than XLA had no automatic escape hatch. This package
makes a bad kernel config degrade a *request*, never the process:

- ``resilience.watchdog`` — bounded first-compile of every fused op
  (``TDT_COMPILE_TIMEOUT_S``); a trip lands the exact (op, config,
  device_kind) tuple in the on-disk known-bad cache.
- ``resilience.knownbad`` — cross-process cache of configs that ever
  hung or broke the compiler; the router never re-enters them.
- ``resilience.breaker``  — per-op circuit breakers
  (closed → open → half-open → closed).
- ``resilience.router``   — the ``@resilient`` decorator on every
  public op entry in ``ops/``: routes to each op's ``impl="xla"``
  reference path on known-bad hits, BASELINE-measured slow regimes,
  or an open breaker, and converts fused infra failures into recorded
  fallbacks. ``TDT_FORCE_FUSED=1`` bypasses routing (bench / smoke).

Fault injection for all of the above lives in
``triton_dist_tpu.testing.faults``; policies and env knobs are
documented in docs/resilience.md, metrics in docs/observability.md.
"""

from triton_dist_tpu.resilience.breaker import (  # noqa: F401
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
    all_breakers,
    get_breaker,
    reset_breakers,
)
from triton_dist_tpu.resilience.knownbad import (  # noqa: F401
    KnownBadCache,
    get_cache as known_bad_cache,
    make_key as known_bad_key,
)
from triton_dist_tpu.resilience.router import (  # noqa: F401
    FallbackSpec,
    NonFiniteOutput,
    decide,
    device_kind,
    force_fused,
    policy_reason,
    registered_fallbacks,
    resilient,
    reset_router,
)
from triton_dist_tpu.resilience.watchdog import (  # noqa: F401
    CompileTimeout,
    compile_timeout_s,
    run_with_timeout,
)


def reset_for_tests() -> None:
    """Reset every piece of process-local resilience state (breakers,
    compiled-key set, baseline cache, known-bad singleton)."""
    reset_router()
