"""Per-op circuit breakers for the fused kernel paths.

Classic three-state breaker (closed → open → half-open → closed),
scoped per op family: ``TDT_BREAKER_THRESHOLD`` consecutive infra
failures of an op's fused path open its breaker, routing every call to
the XLA reference path for ``TDT_BREAKER_COOLDOWN_S`` seconds; the
first call after the cooldown runs fused as a half-open probe, and its
outcome decides between re-closing and re-opening. A bad kernel config
thus degrades at most N requests, never the process — the ROADMAP
"serves heavy traffic" posture.

State changes emit ``resilience.<op>.breaker_state`` (0 closed /
1 open / 2 half-open), ``resilience.<op>.breaker_opens``, and the
aggregate ``resilience.breakers_open`` gauge through ``obs``.

The clock is injectable (``clock=``) so the full state machine is
testable without sleeping.
"""

from __future__ import annotations

import os
import threading
import time

from triton_dist_tpu import obs

__all__ = ["CLOSED", "OPEN", "HALF_OPEN", "CircuitBreaker",
           "get_breaker", "all_breakers", "reset_breakers"]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

#: Gauge encoding of the states (docs/observability.md).
STATE_GAUGE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}

DEFAULT_THRESHOLD = 3
DEFAULT_COOLDOWN_S = 30.0


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    return int(v) if v else default


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    return float(v) if v else default


class CircuitBreaker:
    def __init__(self, op: str, threshold: int | None = None,
                 cooldown_s: float | None = None, clock=time.monotonic):
        self.op = op
        self.threshold = (threshold if threshold is not None else
                          _env_int("TDT_BREAKER_THRESHOLD",
                                   DEFAULT_THRESHOLD))
        self.cooldown_s = (cooldown_s if cooldown_s is not None else
                           _env_float("TDT_BREAKER_COOLDOWN_S",
                                      DEFAULT_COOLDOWN_S))
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_at: float | None = None
        self._emit()

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _emit(self) -> None:
        obs.gauge(f"resilience.{self.op}.breaker_state").set(
            STATE_GAUGE[self._state])
        _emit_open_count()

    def allow(self) -> bool:
        """May the fused path run right now? An expired cooldown
        transitions open → half-open and admits ONE probe call; other
        callers keep getting the fallback until the probe reports. A
        probe that never reports (its outcome lost — e.g. a trace that
        never executes, or a crashed worker) self-heals: after another
        cooldown interval the next caller becomes the new probe."""
        with self._lock:
            now = self._clock()
            if self._state == OPEN:
                if now - self._opened_at >= self.cooldown_s:
                    self._state = HALF_OPEN
                    self._probe_at = now
                    self._emit()
                    return True
                return False
            if self._state == HALF_OPEN:
                if (self._probe_at is None
                        or now - self._probe_at >= self.cooldown_s):
                    self._probe_at = now
                    return True
                return False
            return True

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_at = None
            if self._state != CLOSED:
                self._state = CLOSED
                self._emit()

    def record_failure(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                self._open()
                return
            self._failures += 1
            if self._state == CLOSED and self._failures >= self.threshold:
                self._open()

    def _open(self) -> None:
        # Caller holds the lock.
        self._state = OPEN
        self._failures = 0
        self._probe_at = None
        self._opened_at = self._clock()
        obs.counter(f"resilience.{self.op}.breaker_opens").inc()
        obs.trace.instant(f"resilience.{self.op}.breaker_open",
                          "resilience", args={"op": self.op})
        # An open breaker means N consecutive infra failures just
        # happened: leave the timeline of the window that opened it
        # (rate-limited; no-op when tracing is off).
        obs.flight.maybe_dump(f"breaker_{self.op}")
        self._emit()


_BREAKERS: dict[str, CircuitBreaker] = {}
# RLock: get_breaker holds it while CircuitBreaker.__init__ emits the
# initial state, which re-enters here for the aggregate gauge.
_REG_LOCK = threading.RLock()


def _emit_open_count() -> None:
    with _REG_LOCK:
        open_count = sum(1 for b in _BREAKERS.values()
                         if b._state != CLOSED)
    obs.gauge("resilience.breakers_open").set(open_count)


def get_breaker(op: str) -> CircuitBreaker:
    with _REG_LOCK:
        b = _BREAKERS.get(op)
        if b is None:
            b = _BREAKERS[op] = CircuitBreaker(op)
        return b


def all_breakers() -> dict[str, CircuitBreaker]:
    with _REG_LOCK:
        return dict(_BREAKERS)


def reset_breakers() -> None:
    """Drop every breaker (tests; thresholds re-read env on rebuild)."""
    with _REG_LOCK:
        _BREAKERS.clear()
