"""Disaggregated prefill/decode over the KV-stream protocol (ISSUE 18).

The subsystem that specializes the fleet: a PREFILL replica runs the
chunked prefill of a request into its paged pools, samples the first
token, then streams the finished KV blocks to a DECODE replica —
content-addressed by the prefix cache's sha1 block-hash chain
(models/prefix_cache.py), so the ``kv_offer``/``kv_need`` negotiation
ships ONLY the blocks the decode side's prefix cache does not already
hold (serving/kv_stream.py carries the wire protocol, the schedule
helpers the model checker executes, and the one-sided symm-mem tier).
The decode replica verifies the chain and admits the row DECODE-ONLY
(:meth:`StreamSession.adopt_row` — no re-prefill), so one long prompt
never stalls TPOT for the decoders co-scheduled on that replica.

One :class:`DisaggEndpoint` hangs off every scheduler-path
``ModelServer`` and serves both roles on the existing JSON-lines
protocol:

- as the DECODE side: ``kv_offer`` (answers ``need_from`` — the
  longest hash-chain prefix its cache holds), ``kv_ship``
  (sequence-numbered block payloads into a staging table), and
  ``kv_commit`` (verify chain → ``Scheduler.submit_preloaded`` →
  generated tokens back to the prefill side);
- as the PREFILL side: ``disagg_prefill`` (the verb a tiered router
  dispatches) — prefill locally with a ``kv_export`` capture, then
  negotiate/ship/commit against ``decode_endpoint``.

Transport is TIERED per handoff: a decode endpoint registered in this
process (:func:`find_inproc` — the bench and tests run whole fleets in
one process) is driven by direct calls with each block pushed through
the one-sided :func:`~triton_dist_tpu.serving.kv_stream.symm_ship`
path (``disagg.ship_inproc``); anything else speaks the
length-prefixed wire verbs via
:class:`~triton_dist_tpu.serving.kv_stream.KVStreamSender`
(``disagg.ship_wire``).

The FALLBACK CONTRACT (docs/serving.md "Disaggregated
prefill/decode"): ANY handoff failure — export miss, dead decode
peer, chain-verify reject, decode-side eviction between offer and
commit — counts ``disagg.fallbacks`` and re-serves the request
locally in full. The prompt's blocks are still warm in the prefill
replica's prefix cache, so the re-prefill is near-free, and the
client sees tokens, never an error. One trace ID spans prefill admit
→ stream → decode admit (``disagg.*`` instants).
"""

from __future__ import annotations

import itertools
import threading
import time

from triton_dist_tpu import obs
from triton_dist_tpu.obs import trace
from triton_dist_tpu.serving import kv_stream

__all__ = ["DisaggEndpoint", "find_inproc", "register_inproc",
           "unregister_inproc"]

# In-process endpoint registry: "host:port" → DisaggEndpoint. The
# same-host transport tier — a fleet bench or test running N replicas
# in one process hands block payloads over directly (through the
# symm-mem ship path) instead of re-entering its own TCP stack.
_INPROC_LOCK = threading.Lock()
_INPROC: dict = {}


def register_inproc(label: str, endpoint: "DisaggEndpoint") -> None:
    with _INPROC_LOCK:
        _INPROC[label] = endpoint


def unregister_inproc(label: str) -> None:
    with _INPROC_LOCK:
        _INPROC.pop(label, None)


def find_inproc(label: str):
    with _INPROC_LOCK:
        return _INPROC.get(label)


def _hash_chain(kv, prompt):
    """The prompt's full-block sha1 chain, independent of whether this
    replica enabled the prefix-cache INDEX (verification must work on
    any paged decode replica; dedup simply finds nothing without the
    index)."""
    if kv.prefix is not None:
        return kv.prefix.block_hashes(prompt)
    from triton_dist_tpu.models.prefix_cache import PrefixCache
    return PrefixCache(1, kv.page_size).block_hashes(prompt)


class DisaggEndpoint:
    """Both halves of the disaggregated handoff for one ModelServer."""

    #: Verbs ``ModelServer._serve_command`` delegates here.
    VERBS = frozenset({"kv_offer", "kv_ship", "kv_commit",
                       "disagg_prefill"})

    def __init__(self, server):
        self.server = server
        self.staging = kv_stream.HandoffStaging()
        self._hid = itertools.count(1)
        #: Injectable post-ship callback ``(handoff_id, block, seq)``,
        #: called after every block leaves this PREFILL side (both
        #: transport tiers) — the chaos harness's sever point
        #: (testing/chaos.py ``sever_stream``).
        self.ship_hook = None

    def handle(self, cmd: str, req: dict) -> dict:
        if cmd == "kv_offer":
            return self._serve_offer(req)
        if cmd == "kv_ship":
            return self._serve_ship(req)
        if cmd == "kv_commit":
            return self._serve_commit(req)
        return self._serve_disagg_prefill(req)

    # -- decode side (receiver verbs) --------------------------------------
    def _serve_offer(self, req: dict) -> dict:
        severed = self.staging.purge_stale()
        if severed:
            # Half-received handoffs whose sender died (sever_stream):
            # the staging table never leaks for a prefill replica's
            # death.
            obs.counter("disagg.streams_severed").inc(severed)
        kv = self.server.engine.kv
        hashes_hex = [str(h) for h in (req.get("hashes") or [])]
        n_blocks = int(req["n_blocks"])
        need_from = 0
        if kv.prefix is not None and hashes_hex:
            need_from = kv.prefix.chain_prefix_match(
                [bytes.fromhex(h) for h in hashes_hex])
        self.staging.open(str(req["handoff_id"]), hashes_hex, n_blocks,
                          need_from, req.get("meta") or {})
        obs.counter("disagg.offers").inc()
        obs.counter("disagg.blocks_offered").inc(n_blocks)
        if need_from:
            obs.counter("disagg.blocks_deduped").inc(need_from)
        trace.emit("i", "disagg.offer", "serving",
                   args={"handoff_id": req["handoff_id"],
                         "n_blocks": n_blocks, "need_from": need_from},
                   trace_id=req.get("trace_id"))
        return {"need_from": need_from}

    def _serve_ship(self, req: dict) -> dict:
        payload = req.get("_payload")
        if payload is None:
            raise ValueError("kv_ship carried no framed payload "
                             "(nbytes + raw bytes after the line)")
        seq = int(req["seq"])
        self.staging.put(str(req["handoff_id"]), int(req["block"]),
                         seq, payload)
        obs.counter("disagg.stream_bytes").inc(len(payload))
        return {"ok": True, "seq": seq}

    def _serve_commit(self, req: dict) -> dict:
        try:
            return self._commit(req)
        except Exception:
            # Every reject — unknown/stale handoff, chain mismatch,
            # broken signal sequence, admission failure (including a
            # block the cache EVICTED between offer and commit) —
            # reaches the prefill side as a structured error reply,
            # whose fallback re-prefills locally. Never a wrong decode.
            obs.counter("disagg.commit_rejects").inc()
            raise

    def _commit(self, req: dict) -> dict:
        entry = self.staging.take(str(req["handoff_id"]))
        prompt = [int(t) for t in req["prompt_ids"]]
        kv = self.server.engine.kv
        self.staging.verify(entry, prompt, kv.page_size,
                            _hash_chain(kv, prompt))
        trace.emit("i", "disagg.decode_admit", "serving",
                   args={"handoff_id": req["handoff_id"],
                         "shipped": len(entry["blocks"]),
                         "need_from": entry["need_from"]},
                   trace_id=req.get("trace_id"))
        fut = self.server.scheduler.submit_preloaded(
            prompt, int(req["gen_len"]), int(req["first"]),
            entry["blocks"], stop_tokens=req.get("stop_tokens"),
            trace_id=req.get("trace_id"))
        tokens = fut.result()
        obs.counter("disagg.decode_admits").inc()
        return {"tokens": [int(t) for t in tokens]}

    # -- prefill side (the verb a tiered router dispatches) ----------------
    def _serve_disagg_prefill(self, req: dict) -> dict:
        t0 = time.perf_counter()
        sched = self.server.scheduler
        prompt = [int(t) for t in req["prompt_ids"]]
        gen_len = int(req.get("gen_len", 16))
        stop = req.get("stop_tokens")
        trace_id = str(req.get("trace_id") or trace.new_trace_id())

        # Prefill-only pass: one generated token, with the finished KV
        # chain captured at retirement (the scheduler runs kv_export
        # just before retire_row, while the row still owns its
        # blocks). A failed export leaves `box` empty and the fallback
        # serves the whole request locally.
        if gen_len <= 0:
            return {"tokens": [[]], "gen_len": gen_len,
                    "trace_id": trace_id}
        box: dict = {}

        def kv_export(sess, row, _req):
            box["export"] = sess.export_row(row, prompt)

        first = int(sched.submit(prompt, 1, stop_tokens=stop,
                                 trace_id=trace_id,
                                 kv_export=kv_export).result()[0])
        trace.emit("i", "disagg.prefill_done", "serving",
                   args={"prompt_len": len(prompt), "first": first},
                   trace_id=trace_id)

        if stop is None:
            eos = getattr(self.server.engine.model.config,
                          "eos_token_id", -1)
            stop_set = {eos} if eos >= 0 else set()
        else:
            stop_set = {int(t) for t in stop}
        if gen_len <= 1 or first in stop_set:
            # Nothing left to decode: the prefill replica IS the
            # answer, no handoff.
            return {"tokens": [[first]], "gen_len": gen_len,
                    "trace_id": trace_id}

        export = box.get("export")
        endpoint = req.get("decode_endpoint")
        if export is not None and endpoint:
            try:
                tokens = self._stream_to_decode(
                    str(endpoint), export, prompt, first, gen_len,
                    stop, trace_id)
                obs.counter("disagg.handoffs").inc()
                obs.histogram("disagg.handoff_ms").observe(
                    (time.perf_counter() - t0) * 1e3)
                return {"tokens": [tokens], "gen_len": gen_len,
                        "trace_id": trace_id,
                        "disagg": {"decode": str(endpoint),
                                   "shipped": export["n_blocks"]}}
            except Exception as e:  # noqa: BLE001 — fallback contract
                trace.emit("i", "disagg.fallback", "serving",
                           args={"error": str(e)[:120]},
                           trace_id=trace_id)
        # Fallback: serve the FULL request locally. The prompt's
        # blocks are still indexed in this replica's prefix cache, so
        # the re-prefill is near-free; the client sees tokens, never
        # the handoff's failure.
        obs.counter("disagg.fallbacks").inc()
        tokens = sched.submit(prompt, gen_len, stop_tokens=stop,
                              trace_id=trace_id).result()
        return {"tokens": [[int(t) for t in tokens]],
                "gen_len": gen_len, "trace_id": trace_id,
                "disagg": {"fallback": True}}

    def _stream_to_decode(self, endpoint: str, export: dict, prompt,
                          first: int, gen_len: int, stop,
                          trace_id: str) -> list:
        handoff_id = (f"{self.server.replica_id}"
                      f"#{next(self._hid)}")
        peer = find_inproc(endpoint)
        if peer is not None:
            return self._handoff_inproc(peer, handoff_id, export,
                                        prompt, first, gen_len, stop,
                                        trace_id)
        host, _, port = endpoint.rpartition(":")
        with kv_stream.KVStreamSender(host, int(port)) as tx:
            need_from = tx.offer(handoff_id, export["hashes"],
                                 export["n_blocks"], export["meta"],
                                 trace_id=trace_id)
            for j, s in kv_stream.ship_schedule(export["n_blocks"],
                                                need_from):
                tx.ship(handoff_id, j, s, export["blocks"][j])
                obs.counter("disagg.blocks_shipped").inc()
                obs.counter("disagg.ship_wire").inc()
                if self.ship_hook is not None:
                    self.ship_hook(handoff_id, j, s)
            resp = tx.commit(handoff_id, prompt, first, gen_len,
                             stop_tokens=stop, trace_id=trace_id)
        return [int(t) for t in resp["tokens"]]

    def _handoff_inproc(self, peer: "DisaggEndpoint", handoff_id: str,
                        export: dict, prompt, first: int, gen_len: int,
                        stop, trace_id: str) -> list:
        """Same-process tier: the peer's verbs are called directly
        (under ITS registry scope, so its disagg.* counters land on
        the right replica) and every shipped payload rides the
        one-sided symm-mem path — at world 1 the identity handover,
        on a real mesh axis the remote-DMA shift protocol
        (kv_stream.symm_ship)."""

        def on_peer(fn, *a):
            with obs.scoped_registry(peer.server.registry):
                return fn(*a)

        need_from = int(on_peer(peer._serve_offer, {
            "handoff_id": handoff_id, "hashes": export["hashes"],
            "n_blocks": export["n_blocks"], "meta": export["meta"],
            "trace_id": trace_id})["need_from"])
        mesh = getattr(self.server.engine.model, "mesh", None)
        for j, s in kv_stream.ship_schedule(export["n_blocks"],
                                            need_from):
            payload = export["blocks"][j]
            if mesh is not None:
                import numpy as np
                staged = np.frombuffer(payload, np.uint8)
                moved = kv_stream.symm_ship(
                    staged, mesh=mesh, axis=mesh.axis_names[0])
                payload = np.asarray(moved, np.uint8).tobytes()
            on_peer(peer._serve_ship, {
                "handoff_id": handoff_id, "block": j, "seq": s,
                "nbytes": len(payload), "_payload": payload})
            obs.counter("disagg.blocks_shipped").inc()
            obs.counter("disagg.ship_inproc").inc()
            if self.ship_hook is not None:
                self.ship_hook(handoff_id, j, s)
        resp = on_peer(peer._serve_commit, {
            "handoff_id": handoff_id, "prompt_ids": prompt,
            "first": first, "gen_len": gen_len, "stop_tokens": stop,
            "trace_id": trace_id})
        return [int(t) for t in resp["tokens"]]
