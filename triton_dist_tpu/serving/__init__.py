"""Serving demo (reference: mega_triton_kernel/test/models/model_server.py
socket server, chat.py client, bench_qwen3.py; SURVEY.md §2.7)."""

from triton_dist_tpu.serving.server import ModelServer  # noqa: F401
from triton_dist_tpu.serving.client import ChatClient  # noqa: F401
