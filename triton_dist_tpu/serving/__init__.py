"""Serving stack: continuous-batching scheduler, TCP server, client
(reference: mega_triton_kernel/test/models/model_server.py socket
server, chat.py client, bench_qwen3.py; SURVEY.md §2.7 — extended with
cross-request continuous batching, docs/serving.md)."""

from triton_dist_tpu.serving.server import ModelServer  # noqa: F401
from triton_dist_tpu.serving.client import ChatClient, fanout  # noqa: F401
from triton_dist_tpu.serving.scheduler import (  # noqa: F401
    Draining, QueueFull, Request, Scheduler)
from triton_dist_tpu.serving.router import RouterServer  # noqa: F401
