"""Cross-request continuous batching: one shared decode loop for every
connection.

The reference's model server — and our ``ModelServer`` before this
module — holds a global lock for an entire generation: concurrent
clients queue head-of-line behind whichever generation got there first,
even though the engine's continuous-batching machinery
(``Engine.serve_stream`` / ``StreamSession``) already knows how to
admit a new prompt into a freed decode row mid-flight. This module
closes that gap at the REQUEST level: a single scheduler thread owns
the engine's fixed decode batch and pumps one shared decode loop, while
handler threads enqueue requests into a bounded FIFO admission queue
and block on per-request futures. A 4-token request submitted while a
4096-token generation is mid-decode completes in milliseconds, not
minutes — T3's fine-grained-interleaving lesson (PAPERS.md) applied at
the request level: throughput under load is gated by the scheduler,
not the kernels.

Design:

- **One engine thread.** Only the pump thread touches the Engine's
  ``StreamSession``; handler threads interact through the queue and
  per-request done-events, so no generation lock exists at all.
- **Fair FIFO admission with backpressure.** :meth:`Scheduler.submit`
  appends to a bounded queue (``max_waiting`` / ``TDT_MAX_WAITING``,
  default 64); a full queue raises :class:`QueueFull`, which the
  server answers with a structured ``queue_full`` reply instead of
  stalling the connection. Admission order is strictly
  first-come-first-served.
- **Chunked prefill.** With ``prefill_chunk`` (``TDT_PREFILL_CHUNK``)
  set, long prompts prefill ``chunk`` tokens at a time — one slice per
  pump iteration, interleaved with the shared decode step — so
  admitting a long prompt cannot stall the token cadence of the rows
  already decoding (``StreamSession.prefill_step``).
- **Block-granular paged admission** (ISSUE 6). On paged engines the
  head of the queue additionally waits for enough free KV BLOCKS for
  its worst case (``StreamSession.can_admit``) — still strictly FIFO —
  and passes its ``gen_len`` budget through so the pool commits the
  decode tail. Oversubscribed pools therefore stream through the
  shared batch instead of falling back to the serialized path; a
  request that could never fit fails at ``submit()`` as ``ValueError``
  (docs/serving.md "Block-granular admission").
- **Decode-path agnostic** (ISSUE 11). The pump drives whatever decode
  step the session resolves — the plain jitted step or the mega
  one-program task-graph step (``Engine(use_mega=True)`` /
  ``decode_path="auto"``) — through the same
  :meth:`StreamSession.decode_burst` verb; greedy outputs are
  bit-identical either way (docs/serving.md "Decode-path selection").
- **Variable tokens per step** (ISSUE 13). A row emits 0..k+1 tokens
  per pump iteration: with ``Engine(spec=SpecConfig(...))`` each
  iteration drafts up to k tokens per row, verifies them in one
  widened step, and commits the accepted prefix atomically — a row
  whose burst contains its stop token retires MID-burst (the tail is
  discarded), and greedy outputs stay bit-identical to spec-off
  (docs/serving.md "Speculative decoding"). Fairness is unchanged:
  admission is still strictly FIFO per iteration, and a burst never
  exceeds the row's remaining ``gen_len`` budget.
- **Drain + in-flight accounting** (ISSUE 15). :meth:`Scheduler.drain`
  flips the scheduler to admit-nothing-new (``submit`` raises
  :class:`Draining`, the server answers a structured ``draining``
  reply, ``serving.draining`` advertises it through the health verb)
  while everything already in flight finishes; :meth:`inflight` counts
  the requests still owed an answer and :meth:`wait_idle` blocks until
  it reaches zero — the wait a graceful replica removal
  (``RouterServer.remove_replica``) rides. ``retry_after_ms_hint``
  turns rolling TPOT × queue depth into the backpressure hint both
  the single-server ``queue_full`` reply and the router's fleet-level
  shed carry.
- **Observability** (docs/observability.md): ``serving.queue_depth``
  and ``serving.batch_occupancy`` gauges, per-request
  ``serving.ttft_ms`` and ``serving.queue_wait_ms`` histograms,
  ``serving.admitted`` / ``serving.retired`` /
  ``serving.rejected_queue_full`` counters, and ``serving.admit`` /
  ``serving.retire`` instants on the trace timeline carrying each
  request's trace ID — a Perfetto dump of a loaded server shows rows
  churning through the batch.
- **SLO observatory** (ISSUE 8, docs/observability.md "SLOs and burn
  rates"). The pump feeds an :class:`obs.slo.SLOTracker`: every
  request's TTFT, queue wait, and per-output-token time (TPOT), plus
  each pump iteration's duration, land in rolling-window histograms
  (``serving.rolling.*`` gauges), and declarative SLO targets
  (``Engine(slo=...)`` / ``TDT_SLO_*`` env) are burn-rate-evaluated
  Google-SRE style each iteration — a breach arms the flight recorder
  so a latency regression leaves a Perfetto postmortem before
  anything crashes. Each retired request also gets a latency
  waterfall (``obs.attrib``: queue_wait → prefill → decode, prefix
  savings, per-token share) attached to its future (the server
  returns it under ``"timing"``) and pushed to the last-K ring behind
  ``{"cmd": "request_stats"}``.

Greedy results are bit-identical to per-request ``Engine.serve()``
(tests/test_scheduler.py): the scheduler drives the same
admission/decode programs ``serve_stream`` is proven on
(tests/test_engine_stream.py).
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time
import warnings

from triton_dist_tpu import obs
from triton_dist_tpu.obs import attrib, devprof, history, slo, trace

__all__ = ["DEFAULT_MAX_WAITING", "Draining", "QueueFull", "Request",
           "RETRY_AFTER_MAX_MS", "RETRY_AFTER_MIN_MS", "Scheduler",
           "retry_after_ms_hint"]

DEFAULT_MAX_WAITING = 64

#: Bounds on the ``retry_after_ms`` backpressure hint (ISSUE 15): the
#: floor keeps a quiet server from telling clients to hammer at 0 ms,
#: the cap keeps one deep queue from parking clients for minutes.
RETRY_AFTER_MIN_MS = 25
RETRY_AFTER_MAX_MS = 5000
#: The hint when no TPOT signal exists yet (cold server): one modest
#: beat, not zero.
RETRY_AFTER_DEFAULT_MS = 100


def retry_after_ms_hint(tpot_p50_ms, queue_depth) -> int:
    """Backpressure hint for ``queue_full`` / ``draining`` replies:
    how long a shed client should wait before retrying, derived from
    the rolling per-output-token time times the queue depth (a crude
    but honest estimate of when a queued slot frees up), clamped to
    ``[RETRY_AFTER_MIN_MS, RETRY_AFTER_MAX_MS]``. With no TPOT signal
    (cold server, SLO engine off) the hint is
    ``RETRY_AFTER_DEFAULT_MS`` — the one home for the formula shared
    by the single-server reply and the router's fleet-level shed
    (serving/router.py)."""
    try:
        tpot = float(tpot_p50_ms) if tpot_p50_ms is not None else 0.0
    except (TypeError, ValueError):
        tpot = 0.0
    if tpot <= 0.0:
        return RETRY_AFTER_DEFAULT_MS
    est = tpot * max(float(queue_depth or 0.0), 1.0)
    return int(min(max(est, RETRY_AFTER_MIN_MS), RETRY_AFTER_MAX_MS))


class QueueFull(RuntimeError):
    """Admission queue is at ``max_waiting`` — backpressure; the caller
    should retry later (the server turns this into a structured
    ``queue_full`` reply)."""


class Draining(QueueFull):
    """The scheduler is draining (ISSUE 15): it finishes what is in
    flight but admits nothing new — the server answers a structured
    ``draining`` reply so a router stops placing here and clients
    retry elsewhere."""


class Request:
    """One prompt's life through the shared batch: queued → admitted →
    decoding → done. Handler threads block on :meth:`result`; only the
    pump thread mutates the other fields."""

    __slots__ = ("prompt", "gen_len", "stop_set", "trace_id", "rid",
                 "t_submit", "t_admit", "t_first", "tokens", "error",
                 "done", "cached", "chunks", "timing", "draft_ms",
                 "verify_ms", "kv_export", "preloaded")

    def __init__(self, prompt, gen_len: int, stop_set, trace_id, rid):
        self.prompt = prompt
        self.gen_len = gen_len
        self.stop_set = stop_set
        self.trace_id = trace_id
        self.rid = rid
        self.t_submit = time.perf_counter()
        self.t_admit = None
        self.t_first = None
        self.tokens: list[int] = []     # generated tokens (no prompt)
        self.error: BaseException | None = None
        self.done = threading.Event()
        self.cached = 0            # prefix-cache-hit prompt tokens
        self.chunks = 0            # prefill slices dispatched
        self.timing: dict | None = None   # attribution waterfall
        self.draft_ms = 0.0        # spec draft time this request rode
        self.verify_ms = 0.0       # spec verify time this request rode
        # Disaggregated handoff hooks (ISSUE 18, serving/disagg.py):
        # ``kv_export`` is called by the pump as fn(session, row,
        # request) just BEFORE the row retires — while its KV blocks
        # are still mapped — so a prefill replica can extract the
        # finished chain for streaming; ``preloaded`` =
        # {"first": tok, "blocks": {j: payload}} admits the row
        # DECODE-ONLY through StreamSession.adopt_row instead of
        # running a prefill program.
        self.kv_export = None
        self.preloaded: dict | None = None

    def result(self, timeout: float | None = None) -> list[int]:
        """Block until the request finishes; returns the generated
        tokens (ending at, and including, the first stop token).
        Raises the scheduler-side failure if the request degraded, or
        ``TimeoutError`` if ``timeout`` elapses first."""
        if not self.done.wait(timeout):
            raise TimeoutError(
                f"request {self.rid} not done within {timeout}s")
        if self.error is not None:
            raise self.error
        return self.tokens


class Scheduler:
    """Continuous-batching serving scheduler over one Engine.

    ``submit()`` from any thread; a single pump thread drives the
    engine's :class:`~triton_dist_tpu.models.engine.StreamSession` so
    prompts from different connections coexist in one decode batch.
    """

    def __init__(self, engine, params, max_waiting: int | None = None,
                 prefill_chunk: int | None = None, slo_tracker=None,
                 devprof_sampler=None, history_sampler=None,
                 replica_id: str | None = None, registry=None):
        self.engine = engine
        self.params = params
        # Fleet identity (ISSUE 14): stamped into this scheduler's
        # admit/retire trace instants so two same-host replicas'
        # merged Perfetto streams cannot alias, and — via
        # ``registry`` + obs.scoped_registry on the pump thread —
        # into a per-replica metrics registry when the server runs
        # several replicas in one process.
        self.replica_id = replica_id
        self._registry = registry
        if max_waiting is None:
            max_waiting = obs.env_int("TDT_MAX_WAITING",
                                      DEFAULT_MAX_WAITING)
        if max_waiting <= 0:
            raise ValueError(f"max_waiting must be positive: {max_waiting}")
        self.max_waiting = max_waiting
        if prefill_chunk is None:
            # minimum=1 keeps "0" an error (like any non-positive
            # chunk); the unset default never hits the minimum check.
            prefill_chunk = obs.env_int("TDT_PREFILL_CHUNK", 0,
                                        minimum=1) or None
        if prefill_chunk is not None and prefill_chunk <= 0:
            raise ValueError(
                f"prefill_chunk must be positive: {prefill_chunk}")
        self.prefill_chunk = prefill_chunk
        # The SLO observatory for this scheduler: rolling TTFT / TPOT /
        # queue-wait / pump-time windows + burn-rate targets. Targets
        # come from Engine(slo=...), falling back to the env-overridable
        # defaults; pass an SLOTracker (tests: injectable clock) or a
        # target list to override, False to disable (TDT_SLO=0 does too).
        self.slo: slo.SLOTracker | None = None
        if slo_tracker is not False and slo.enabled():
            if isinstance(slo_tracker, slo.SLOTracker):
                self.slo = slo_tracker
            else:
                targets = (slo_tracker if slo_tracker is not None
                           else getattr(engine, "slo", None))
                self.slo = slo.SLOTracker(targets=targets)
        # Device-profile sampling of pump iterations (obs.devprof,
        # docs/observability.md "Device-time truth"): continuous
        # (TDT_DEVPROF_EVERY) and/or breach-armed
        # (TDT_DEVPROF_ON_BREACH via the flight recorder). None when
        # both knobs are off — the pump then pays nothing. Pass a
        # PumpSampler to override (tests: sync parse), False to
        # disable regardless of env.
        if devprof_sampler is False:
            self.devprof = None
        elif devprof_sampler is not None:
            self.devprof = devprof_sampler
        else:
            self.devprof = devprof.PumpSampler.from_env()
        # Sampled signal history (obs.history, docs/observability.md
        # "History plane"): an opt-in background sampler recording
        # this replica's gauges (values) and counters (rates) into
        # ring-buffered series behind the {"cmd": "history"} verb,
        # plus the early-warning detector pass. None unless
        # TDT_HISTORY=1 — no sampler, no thread, no cost. Pass a
        # HistorySampler to override (tests: thread=False + explicit
        # sample_once timestamps), False to disable regardless of env.
        if history_sampler is False:
            self.history = None
        elif history_sampler is not None:
            self.history = history_sampler
        else:
            self.history = history.HistorySampler.from_env(
                registry=self._registry)
        self._cond = threading.Condition()
        self._queue: collections.deque[Request] = collections.deque()
        self._rid = 0
        self._running = False
        self._thread: threading.Thread | None = None
        self._session = None
        self._inflight = 0          # live requests queued or in rows
        self._draining = False
        #: Injectable per-iteration hook (testing.chaos.wedge_pump):
        #: called by the pump thread at the top of every work
        #: iteration, OUTSIDE the scheduler lock — a hook that blocks
        #: wedges the pump exactly the way a stuck device step would,
        #: while handler threads (health, metrics) keep answering.
        self.pump_hook = None

    # -- client side -------------------------------------------------------
    def queue_depth(self) -> int:
        return len(self._queue)

    def inflight(self) -> int:
        """Live requests the scheduler currently owes an answer —
        queued plus admitted (in a decode row or mid-prefill). The
        in-flight accounting a graceful drain waits on (ISSUE 15)."""
        with self._cond:
            return self._inflight

    @property
    def draining(self) -> bool:
        return self._draining

    def drain(self) -> None:
        """Stop admitting NEW requests (``submit`` raises
        :class:`Draining`); everything already queued or in flight
        finishes normally. Publishes ``serving.draining`` so the
        replica's health verb advertises it and a router stops placing
        here (docs/serving.md "Drain")."""
        with self._cond:
            self._draining = True
        with obs.scoped_registry(self._registry):
            obs.gauge("serving.draining").set(1)

    def resume(self) -> None:
        """Cancel a drain: the scheduler admits again."""
        with self._cond:
            self._draining = False
        with obs.scoped_registry(self._registry):
            obs.gauge("serving.draining").set(0)

    def wait_idle(self, timeout: float | None = None) -> bool:
        """Block until no request is in flight (the drain wait);
        True when idle, False if ``timeout`` elapsed first."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cond:
            while self._inflight > 0:
                left = (None if deadline is None
                        else deadline - time.monotonic())
                if left is not None and left <= 0:
                    return False
                self._cond.wait(0.05 if left is None
                                else min(left, 0.05))
            return True

    def retry_after_ms(self) -> int:
        """This scheduler's backpressure hint (rolling TPOT p50 ×
        queue depth, clamped — :func:`retry_after_ms_hint`), read
        lock-free from the replica's own registry like the health
        verb."""
        from triton_dist_tpu.obs import fleet as _fleet
        g = _fleet.peek_gauges(self._registry
                               or obs.get_registry())
        return retry_after_ms_hint(
            g.get("serving.rolling.tpot_p50_ms"),
            g.get("serving.queue_depth", len(self._queue)))

    def _make_request(self, prompt, gen_len, stop_tokens, trace_id):
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("prompts must be non-empty")
        gen_len = int(gen_len)
        if len(prompt) + max(gen_len, 0) > self.engine.kv.max_seq:
            raise ValueError(
                f"prompt ({len(prompt)}) + gen_len ({gen_len}) must fit "
                f"max_seq ({self.engine.kv.max_seq})")
        if gen_len > 0 and getattr(self.engine, "paged", False):
            # Never-fitting requests must fail HERE, not queue: the
            # pump admits strictly FIFO, so an unadmittable head would
            # deadlock everything behind it.
            kv = self.engine.kv
            if not kv.fits_pool(len(prompt), gen_len):
                raise ValueError(
                    f"prompt ({len(prompt)}) + gen_len ({gen_len}) can "
                    f"never fit the block pool "
                    f"({kv.slots_per_dev} slots/device, page "
                    f"{kv.page_size}) — shrink the request or size the "
                    f"pool up")
        if stop_tokens is None:
            eos = getattr(self.engine.model.config, "eos_token_id", -1)
            stop_set = {eos} if eos >= 0 else set()
        else:
            stop_set = {int(t) for t in stop_tokens}
        self._rid += 1
        return Request(prompt, gen_len, stop_set, trace_id, self._rid)

    def submit(self, prompt, gen_len: int, stop_tokens=None,
               trace_id: str | None = None, kv_export=None) -> Request:
        """Enqueue one prompt; returns its :class:`Request` future.
        Raises :class:`QueueFull` when ``max_waiting`` requests are
        already queued, ``ValueError`` on an unservable request.
        ``kv_export`` (ISSUE 18): per-request retirement hook — see
        :class:`Request`; attached atomically with the enqueue so the
        pump can never retire the row before the hook exists."""
        return self.submit_many([prompt], gen_len, stop_tokens=stop_tokens,
                                trace_id=trace_id, kv_export=kv_export)[0]

    def submit_many(self, prompts, gen_len: int, stop_tokens=None,
                    trace_id: str | None = None,
                    kv_export=None) -> list[Request]:
        """Atomically enqueue several prompts (one client request's
        batch): either every prompt is queued or none is — a
        half-admitted batch is worse than a clean ``queue_full``
        reply."""
        with self._cond:
            if not self._running:
                raise RuntimeError("scheduler is not running")
            if self._draining:
                raise Draining(
                    "scheduler is draining — this replica admits "
                    "nothing new; retry on another replica")
            reqs = [self._make_request(p, gen_len, stop_tokens, trace_id)
                    for p in prompts]
            if kv_export is not None:
                for r in reqs:
                    r.kv_export = kv_export
            live = [r for r in reqs if r.gen_len > 0]
            for r in reqs:
                if r.gen_len <= 0:      # nothing to generate
                    r.done.set()
            if len(live) > self.max_waiting:
                # NOT QueueFull: retrying can never help — the batch
                # exceeds queue capacity even when idle. The server
                # turns ValueError into a non-retryable structured
                # error instead of a "retry later" reply.
                raise ValueError(
                    f"request batches {len(live)} prompts but the "
                    f"admission queue holds max_waiting="
                    f"{self.max_waiting} — split the batch")
            if live:
                if len(self._queue) + len(live) > self.max_waiting:
                    obs.counter("serving.rejected_queue_full").inc(
                        len(live))
                    raise QueueFull(
                        f"admission queue full "
                        f"({len(self._queue)} waiting, "
                        f"max_waiting {self.max_waiting})")
                self._queue.extend(live)
                self._inflight += len(live)
                obs.gauge("serving.queue_depth").set(len(self._queue))
                self._cond.notify()
        return reqs

    def submit_preloaded(self, prompt, gen_len: int, first: int,
                         blocks: dict, stop_tokens=None,
                         trace_id: str | None = None) -> Request:
        """Enqueue one DECODE-ONLY request from a verified
        disaggregated handoff (ISSUE 18, serving/disagg.py): the KV
        chain for ``prompt`` was streamed in (``blocks``: block index
        → packed payload) and ``first`` is the prefill side's sampled
        token, so admission runs :meth:`StreamSession.adopt_row`
        instead of a prefill program. Same FIFO queue, backpressure,
        and drain semantics as :meth:`submit`; the request's tokens
        include ``first``."""
        with self._cond:
            if not self._running:
                raise RuntimeError("scheduler is not running")
            if self._draining:
                raise Draining(
                    "scheduler is draining — this replica admits "
                    "nothing new; retry on another replica")
            req = self._make_request(prompt, gen_len, stop_tokens,
                                     trace_id)
            req.preloaded = {"first": int(first), "blocks": blocks}
            if req.gen_len <= 0:
                req.done.set()
                return req
            if len(self._queue) + 1 > self.max_waiting:
                obs.counter("serving.rejected_queue_full").inc()
                raise QueueFull(
                    f"admission queue full ({len(self._queue)} "
                    f"waiting, max_waiting {self.max_waiting})")
            self._queue.append(req)
            self._inflight += 1
            obs.gauge("serving.queue_depth").set(len(self._queue))
            self._cond.notify()
        return req

    def generate(self, prompt, gen_len: int, stop_tokens=None,
                 trace_id: str | None = None,
                 timeout: float | None = None) -> list[int]:
        """submit() + result(): the generated tokens for one prompt."""
        return self.submit(prompt, gen_len, stop_tokens=stop_tokens,
                           trace_id=trace_id).result(timeout)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "Scheduler":
        with self._cond:
            if self._running:
                return self
            self._running = True
        self._thread = threading.Thread(target=self._pump,
                                        name="tdt-scheduler", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 30.0) -> None:
        """Stop the pump thread; queued and in-flight requests fail
        with a "scheduler stopped" error (their handlers unblock)."""
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    # -- the pump ----------------------------------------------------------
    def _bind(self, req: Request):
        """Per-request trace binding around that request's OWN engine
        work (admission prefill): its stream_admission instant — and,
        on the first compile, the op instants the programs emit — land
        under the request's trace ID. The shared decode step serves
        many requests at once and stays unbound."""
        return (trace.bind(req.trace_id) if req.trace_id
                else contextlib.nullcontext())

    def _targs(self, args: dict) -> dict:
        """Stamp this scheduler's replica identity into a trace-event
        args dict (ISSUE 14): two same-host replicas' admit/retire
        streams stay distinguishable in a merged Perfetto view."""
        if self.replica_id:
            args["replica"] = self.replica_id
        return args

    def _fail(self, req: Request, exc: BaseException) -> None:
        req.error = exc
        self._finish(req)

    def _finish(self, req: Request) -> None:
        """Mark one live request done and release its in-flight slot
        (idempotent — the pump-death drain may revisit an already
        failed request). Wakes :meth:`wait_idle` when the count hits
        zero."""
        if req.done.is_set():
            return
        with self._cond:
            if self._inflight > 0:
                self._inflight -= 1
            if self._inflight == 0:
                self._cond.notify_all()
        req.done.set()

    def _pump(self) -> None:
        """Pump-thread entry: however the loop exits — clean stop, a
        session that cannot even be CONSTRUCTED (e.g. an oversubscribed
        paged pool, legal for plain serve()), or an unexpected crash —
        every queued and in-flight waiter is unblocked with an error
        and the scheduler stops accepting work. A dead pump with
        ``_running`` still True would otherwise hang every
        ``result()`` caller forever."""
        rows: dict[int, Request] = {}        # occupied rows (any state)
        # The pump's emissions (and everything the engine work it
        # drives emits on this thread — loop, failure accounting, and
        # shutdown drain alike) land in the replica's own registry
        # when one was given; scoped_registry(None) is a no-op (the
        # process-global registry keeps receiving).
        with obs.scoped_registry(self._registry):
            exc = self._pump_guarded(rows)
        if exc is not None:
            # The waiters already carry the exception; re-raising from
            # a daemon thread would only add unhandled-thread noise.
            warnings.warn(f"scheduler pump died: {exc!r}",
                          RuntimeWarning, stacklevel=2)

    def _pump_guarded(self, rows: dict) -> BaseException | None:
        exc: BaseException | None = None
        try:
            self._pump_loop(rows)
        except BaseException as e:  # noqa: BLE001 — drain, then surface
            exc = e
            obs.counter("serving.pump_errors").inc()
        finally:
            with self._cond:
                self._running = False
                leftovers = list(self._queue)
                self._queue.clear()
                obs.gauge("serving.queue_depth").set(0)
            err = RuntimeError("scheduler stopped" if exc is None
                               else f"scheduler died: {exc!r}")
            for req in leftovers + list(rows.values()):
                self._fail(req, err)
            obs.gauge("serving.batch_occupancy").set(0)
            sess, self._session = self._session, None
            if sess is not None:
                try:
                    # Release what in-flight rows still hold (paged
                    # block pools): a stop mid-generation must not
                    # strand their blocks.
                    sess.close()
                except Exception:  # noqa: BLE001 — shutdown best-effort
                    pass
            if self.devprof is not None:
                try:
                    # A stop mid-capture must still end the profiler
                    # session (and parse what it got).
                    self.devprof.close()
                except Exception:  # noqa: BLE001 — shutdown best-effort
                    pass
            if self.history is not None:
                try:
                    # Stop the sampler thread and release the flight
                    # recorder's history-provider slot.
                    self.history.close()
                except Exception:  # noqa: BLE001 — shutdown best-effort
                    pass
        return exc

    def _pump_loop(self, rows: dict) -> None:
        sess = self.engine.stream_session(self.params)
        self._session = sess
        budgets: dict[int, int] = {}
        prefilling: set[int] = set()         # rows mid-chunked-prefill
        occupancy = obs.gauge("serving.batch_occupancy")

        def record(row: int, req: Request, tok: int) -> None:
            req.tokens.append(tok)
            if req.t_first is None:
                req.t_first = time.perf_counter()
                ttft_ms = (req.t_first - req.t_submit) * 1e3
                obs.histogram("serving.ttft_ms").observe(ttft_ms)
                if self.slo is not None:
                    self.slo.observe("ttft", ttft_ms)
            budgets[row] -= 1
            if budgets[row] <= 0 or tok in req.stop_set:
                if req.kv_export is not None:
                    # Disaggregated handoff (ISSUE 18): extract the
                    # row's finished KV chain while its blocks are
                    # still mapped — retire_row releases them eagerly.
                    # Export failure degrades the HANDOFF (the caller
                    # falls back to a local re-prefill), never the
                    # request itself.
                    try:
                        req.kv_export(sess, row, req)
                    except Exception:  # noqa: BLE001 — handoff-scoped
                        obs.counter("disagg.export_errors").inc()
                sess.retire_row(row)
                rows.pop(row)
                budgets.pop(row)
                obs.counter("serving.retired").inc()
                t_done = time.perf_counter()
                # The request's latency waterfall (obs.attrib): same
                # clock readings as the trace instants, partitioned
                # queue_wait → prefill → decode so the segments sum to
                # the request's wall time by construction.
                req.timing = attrib.build(
                    rid=req.rid, trace_id=req.trace_id,
                    t_submit=req.t_submit, t_admit=req.t_admit,
                    t_first=req.t_first, t_done=t_done,
                    prompt_tokens=len(req.prompt),
                    tokens=len(req.tokens), cached_tokens=req.cached,
                    prefill_chunks=req.chunks,
                    draft_ms=req.draft_ms, verify_ms=req.verify_ms)
                attrib.push(req.timing)
                if req.timing["tpot_ms"] is not None:
                    # Cumulative TPOT histogram next to the rolling
                    # window: per-replica snapshots of it merge
                    # BUCKET-WISE into the fleet TPOT percentiles
                    # (obs.fleet.merge_fleet_snapshots — a fleet p99
                    # must come from summed buckets, never from
                    # averaging per-replica percentiles).
                    obs.histogram("serving.tpot_ms").observe(
                        req.timing["tpot_ms"])
                    if self.slo is not None:
                        self.slo.observe("tpot", req.timing["tpot_ms"])
                trace.emit("i", "serving.retire", "serving",
                           args=self._targs({"row": row, "rid": req.rid,
                                             "tokens": len(req.tokens)}),
                           trace_id=req.trace_id)
                self._finish(req)

        def admit(row: int, req: Request) -> None:
            req.t_admit = time.perf_counter()
            qw_ms = (req.t_admit - req.t_submit) * 1e3
            obs.histogram("serving.queue_wait_ms").observe(qw_ms)
            if self.slo is not None:
                self.slo.observe("queue_wait", qw_ms)
            obs.counter("serving.admitted").inc()
            trace.emit("i", "serving.admit", "serving",
                       args=self._targs({
                           "row": row, "rid": req.rid,
                           "prompt_len": len(req.prompt),
                           "queued_ms": round(
                               (req.t_admit - req.t_submit) * 1e3, 3)}),
                       trace_id=req.trace_id)
            try:
                with self._bind(req):
                    if req.preloaded is not None:
                        # Decode-only admission from a verified
                        # disaggregated handoff (ISSUE 18): the KV
                        # chain was streamed in, no prefill runs.
                        first = sess.adopt_row(
                            row, req.prompt,
                            req.preloaded["first"],
                            req.gen_len, req.preloaded["blocks"])
                    else:
                        first = sess.prefill_into_row(
                            row, req.prompt, chunk=self.prefill_chunk,
                            gen_budget=req.gen_len)
            except Exception as e:  # noqa: BLE001 — degrade THIS request
                sess.cancel_prefill(row)
                obs.counter("serving.admit_errors").inc()
                self._fail(req, e)
                return
            req.chunks = 1          # one-shot, or the first slice
            rows[row] = req
            budgets[row] = req.gen_len
            if first is None:
                prefilling.add(row)
            else:
                req.cached = (getattr(sess, "admit_info", None)
                              or {}).get("cached", 0)
                record(row, req, first)

        while True:
            if self.devprof is not None and not rows and not self._queue:
                # Going idle with a multi-iteration capture open would
                # leave the jax.profiler session running until the
                # next request (maybe hours: a breach often precedes a
                # traffic drain). End it here — BEFORE the cond lock,
                # session teardown is file I/O — and parse what it
                # got: a short postmortem beats a never-closing one.
                self.devprof.close()
            admits = []
            with self._cond:
                while self._running and not self._queue and not rows:
                    self._cond.wait()
                if not self._running:
                    break
                free = sess.free_rows()
                # Block-granular admission (paged engines): the head
                # of the queue waits until enough blocks are free for
                # its worst case — strictly FIFO, no skip-ahead.
                # ``pending`` accumulates the demand of this batch's
                # earlier admits (they run outside the lock, so the
                # pool hasn't seen them yet).
                pending = None
                while self._queue and free:
                    head = self._queue[0]
                    if not sess.can_admit(len(head.prompt),
                                          head.gen_len, extra=pending):
                        break
                    need = sess.admission_need(len(head.prompt),
                                               head.gen_len)
                    if need is not None:
                        pending = need if pending is None \
                            else pending + need
                    admits.append((free.pop(0), self._queue.popleft()))
                obs.gauge("serving.queue_depth").set(len(self._queue))
            # Engine work happens OUTSIDE the lock: submitters only ever
            # wait on queue capacity, never on device time. The devprof
            # sampler wraps exactly this lock-free region — a capture
            # can span it but never a held scheduler lock.
            hook = self.pump_hook
            if hook is not None:
                # Chaos/test hook (testing.chaos.wedge_pump): runs in
                # the lock-free work region, so a blocking hook wedges
                # engine progress — in-flight rows stall, admissions
                # stop — while handler threads stay responsive (the
                # wedged-replica failure class the router's dispatch
                # deadline exists for).
                hook()
            t_iter0 = time.perf_counter()
            prof = (self.devprof.iteration()
                    if self.devprof is not None and (admits or rows)
                    else contextlib.nullcontext())
            with prof:
                for row, req in admits:
                    admit(row, req)
                for row in sorted(prefilling):  # one slice each, FIFO-ish
                    req = rows[row]
                    try:
                        with self._bind(req):
                            first = sess.prefill_step(row)
                    except Exception as e:  # noqa: BLE001
                        sess.cancel_prefill(row)
                        prefilling.discard(row)
                        rows.pop(row)
                        budgets.pop(row, None)
                        obs.counter("serving.admit_errors").inc()
                        self._fail(req, e)
                        continue
                    req.chunks += 1
                    if first is not None:
                        prefilling.discard(row)
                        req.cached = (getattr(sess, "admit_info", None)
                                      or {}).get("cached", 0)
                        record(row, req, first)
                occupancy.set(len(rows))
                live = [(r, rows[r]) for r in sorted(rows)
                        if r not in prefilling]
                if live:
                    # Resolve the decode path for THIS step, and — only
                    # while a device capture is open — bracket the
                    # shared step alone with the per-path label
                    # (devprof.step_label: device.step.mega vs .plain),
                    # nested inside the whole-iteration device.step
                    # window. Admission/prefill work stays OUTSIDE the
                    # per-path window, so the device.step.<kind>.*
                    # gauges hold pure decode-step time — what the auto
                    # policy (Engine(decode_path="auto")) arbitrates
                    # on; labeling the whole iteration would book
                    # prefill compiles as decode cost.
                    kind_fn = getattr(sess, "decode_kind", None)
                    kind = kind_fn() if kind_fn is not None else None
                    ann = contextlib.nullcontext()
                    if kind and self.devprof is not None \
                            and self.devprof.capturing:
                        from triton_dist_tpu.tools.profiler import \
                            annotate
                        ann = annotate(devprof.step_label(kind))
                    try:
                        with ann:
                            # Variable tokens per row per iteration
                            # (ISSUE 13): one token on the base paths,
                            # 1..k+1 from a speculative verify step.
                            bursts = sess.decode_burst()
                    except Exception as e:  # noqa: BLE001
                        # The SHARED step died: every occupant degrades
                        # (the cache state is suspect) and the session
                        # restarts fresh; the scheduler keeps serving.
                        obs.counter("serving.pump_errors").inc()
                        for _, req in list(rows.items()):
                            self._fail(req, e)
                        rows.clear()
                        budgets.clear()
                        prefilling.clear()
                        sess = self.engine.stream_session(self.params)
                        self._session = sess
                        occupancy.set(0)
                        continue
                    bt = sess.last_burst_timing
                    for row, req in live:
                        if rows.get(row) is not req:   # failed above
                            continue
                        if bt is not None:
                            # Draft/verify sub-attribution: shared step
                            # time booked to every rider, like the
                            # decode wall-clock itself (obs.attrib).
                            req.draft_ms += bt["draft_ms"]
                            req.verify_ms += bt["verify_ms"]
                        for tok in bursts.get(row, ()):
                            if rows.get(row) is not req:
                                break   # retired mid-burst (stop/EOS)
                            record(row, req, int(tok))
            occupancy.set(len(rows))
            if admits or live or prefilling:
                # Iteration time = this pump turn's engine work (the
                # cond wait above is idleness, not work). Evaluation is
                # rate-limited inside the tracker; a breach arms the
                # flight recorder (obs.slo).
                it_ms = (time.perf_counter() - t_iter0) * 1e3
                obs.histogram("serving.pump_iteration_ms").observe(
                    it_ms)
                if self.slo is not None:
                    self.slo.observe("pump", it_ms)
                    self.slo.evaluate()
