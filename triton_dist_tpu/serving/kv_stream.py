"""Content-addressed KV-block streaming (ISSUE 18).

The transfer substrate of disaggregated prefill/decode
(serving/disagg.py, docs/serving.md "Disaggregated prefill/decode"):
a prefill replica ships the finished KV blocks of one admission to a
decode replica, keyed by the prefix cache's sha1 block-hash chain
(models/prefix_cache.py) so the negotiation is content-addressed —

    kv_offer(hash chain)  →  need_from = chain_prefix_match(hashes)
    kv_ship(block j, seq s, payload)   for each needed block, in order
    kv_commit(prompt, first token)     once every signal has landed

Only blocks the decode side's prefix cache does NOT already hold are
shipped (a warm replica receives a near-zero-byte handoff); every
shipped block carries a SEQUENCE NUMBER, and the receiver refuses to
admit until the sequence is contiguous and the recomputed hash chain
matches the offer — the "no signal before its block" discipline of the
one-sided protocols, carried at the wire layer.

Two transport tiers:

- **in-process / same-host** — blocks move through the one-sided
  symm-mem path: :func:`symm_ship` pushes a staged block buffer one
  hop along a mesh axis with the same remote-DMA protocol as
  ``ops.p2p.pp_shift`` (per-block completion = the DMA recv semaphore;
  world 1, the in-process case, is the identity hop and the payload is
  handed over by reference). The schedule the kernel follows is
  :func:`ship_schedule` — the SAME helper the ``kvstream-protocol``
  model checker executes symbolically (analysis/kvstream_model.py), so
  kernel and verifier cannot drift.
- **cross-process** — a length-prefixed wire verb on the existing
  JSON-lines protocol: the ``kv_ship`` line carries ``nbytes`` and the
  raw block payload follows the newline (:class:`KVStreamSender`, with
  the server side's framing in serving/server.py).

Payloads are packed per-block, all layers, as float32 bytes
(:func:`pack_block` / :func:`unpack_block`) — lossless for the fp32
and bf16 pool dtypes — so a block's bytes are a pure function of its
content and the hash chain really is an address.

Knobs (docs/observability.md "Knobs"): ``TDT_KVSTREAM_TIMEOUT_S``
bounds each wire round trip; ``TDT_KVSTREAM_STALE_S`` bounds how long
a half-received handoff may sit in the receiver's staging table before
it is purged (the severed-stream path — testing/chaos.py
``sever_stream``).
"""

from __future__ import annotations

import functools
import json
import socket
import threading
import time

from triton_dist_tpu import obs

__all__ = ["DEFAULT_STALE_S", "DEFAULT_TIMEOUT_S", "HandoffStaging",
           "KVStreamSender", "block_span", "needed_blocks",
           "pack_block", "ship_schedule", "symm_ship", "unpack_block"]

#: Wire round-trip budget per offer/ship/commit exchange, seconds.
DEFAULT_TIMEOUT_S = 30
#: A half-received handoff older than this is purged from the
#: receiver's staging table (the severed-stream cleanup), seconds.
DEFAULT_STALE_S = 30


def timeout_s() -> int:
    return obs.env_int("TDT_KVSTREAM_TIMEOUT_S", DEFAULT_TIMEOUT_S,
                       minimum=1)


def stale_s() -> int:
    return obs.env_int("TDT_KVSTREAM_STALE_S", DEFAULT_STALE_S,
                       minimum=1)


# -- schedule helpers (executed by the kvstream-protocol model) ------------
def needed_blocks(n_blocks: int, held_prefix: int) -> list:
    """Blocks the receiver still needs: the suffix past its
    locally-held hash-chain prefix. ``held_prefix`` is clamped into
    [0, n_blocks] — a receiver can never "hold" more than was offered,
    and dedup must never drop a block past the held prefix (the
    ``kvstream.coverage`` oracle)."""
    held = max(0, min(int(held_prefix), int(n_blocks)))
    return list(range(held, int(n_blocks)))


def ship_schedule(n_blocks: int, held_prefix: int) -> list:
    """``[(block_j, seq_s), ...]`` in ship order: the needed suffix,
    sequence-numbered from 0 with no gaps. THE one spelling of the
    ship order — the sender's loop, the receiver's contiguity check,
    and the model checker (analysis/kvstream_model.py) all execute
    this same function, so the protocol and its verifier cannot
    drift."""
    return [(j, s) for s, j in enumerate(needed_blocks(n_blocks,
                                                       held_prefix))]


def block_span(prompt_len: int, page_size: int) -> int:
    """Blocks covering one prompt's written positions [0, L):
    ``ceil(L / page)`` — the handoff's unit count."""
    return -(-int(prompt_len) // int(page_size))


# -- payload packing -------------------------------------------------------
def pack_block(layers) -> bytes:
    """Pack one block's per-layer (k, v) pages into wire bytes:
    float32, layer-major, k before v. float32 is lossless for the
    fp32 and bf16 pool dtypes, so the bytes are a pure function of
    the block's content (content-addressing holds end to end)."""
    import numpy as np
    parts = []
    for k, v in layers:
        parts.append(np.ascontiguousarray(
            np.asarray(k), dtype=np.float32).tobytes())
        parts.append(np.ascontiguousarray(
            np.asarray(v), dtype=np.float32).tobytes())
    return b"".join(parts)


def unpack_block(data: bytes, num_layers: int, shape) -> list:
    """Inverse of :func:`pack_block`: ``[(k, v), ...]`` float32 numpy
    arrays of ``shape`` (page, Hkv, D) per layer. Raises ``ValueError``
    on a size mismatch (a torn or mis-framed payload must fail the
    handoff, never admit garbage K/V)."""
    import numpy as np
    n = 1
    for d in shape:
        n *= int(d)
    per = n * 4
    if len(data) != num_layers * 2 * per:
        raise ValueError(
            f"kv block payload is {len(data)} bytes, expected "
            f"{num_layers * 2 * per} ({num_layers} layers x 2 x "
            f"{tuple(shape)} float32)")
    out, off = [], 0
    for _ in range(num_layers):
        k = np.frombuffer(data, np.float32, count=n,
                          offset=off).reshape(shape)
        off += per
        v = np.frombuffer(data, np.float32, count=n,
                          offset=off).reshape(shape)
        off += per
        out.append((k, v))
    return out


# -- in-process / same-host tier (one-sided symm-mem path) -----------------
def _ship_kernel(x_ref, o_ref, send_sem, recv_sem, *, axis: str,
                 world: int, delta: int):
    """Push the staged block buffer one hop along ``axis`` — the PP
    shift-hop protocol (ops/p2p.py ``_shift_kernel``) applied to a KV
    staging buffer: barrier, start the outgoing DMA, wait the incoming
    DMA's recv semaphore (the per-block completion SIGNAL — a block is
    only ever consumed after this wait), drain the send semaphore."""
    from jax import lax
    import triton_dist_tpu.language as dl
    from triton_dist_tpu.ops.p2p import shift_partners
    me = lax.axis_index(axis)
    dst, _src = shift_partners(me, delta, world)
    dl.barrier_all(axis)
    dl.remote_copy(x_ref.at[:], o_ref.at[:], dst, send_sem, recv_sem,
                   axis=axis).start()
    dl.remote_copy(x_ref.at[:], o_ref.at[:], me, send_sem, recv_sem,
                   axis=axis).wait_recv()
    dl.remote_copy(x_ref.at[:], o_ref.at[:], dst, send_sem, recv_sem,
                   axis=axis).wait_send()


def symm_ship(x, mesh=None, axis: str = "tp", delta: int = 1,
              interpret=None):
    """One-sided push of a staged block buffer one hop along ``axis``.

    ``world == 1`` — the in-process same-host tier every CPU test and
    single-host deployment runs — is the identity hop: the "transfer"
    is the handover of the staging buffer itself, and the per-block
    sequence number (:func:`ship_schedule`) is the completion signal.
    With a real multi-device axis the staged buffer moves through the
    remote-DMA shift protocol above (collective_id 9 — ops/p2p.py owns
    8)."""
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from jax.sharding import PartitionSpec as P
    from triton_dist_tpu.ops.common import (
        comm_params, nestable_shard_map, resolve_interpret,
        sync_interpret)
    if mesh is None:
        from triton_dist_tpu.runtime.dist import get_mesh
        mesh = get_mesh()
    world = mesh.shape[axis]
    if world == 1:
        return x
    interpret = resolve_interpret(interpret)
    kernel = functools.partial(_ship_kernel, axis=axis, world=world,
                               delta=delta)

    def body(xs):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(xs.shape, xs.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.SemaphoreType.DMA,
                            pltpu.SemaphoreType.DMA],
            compiler_params=comm_params(collective_id=9, world=world),
            interpret=interpret,
        )(xs)

    out = nestable_shard_map(body, mesh=mesh, in_specs=P(axis),
                             out_specs=P(axis), check_vma=False)(x)
    return sync_interpret(out, interpret)


# -- wire tier (length-prefixed verbs on the JSON-lines protocol) ----------
class KVStreamSender:
    """One handoff's connection to the decode replica.

    Speaks the three stream verbs over a single persistent connection
    (a handoff is a conversation, not N independent round trips):
    :meth:`offer` → the receiver's ``need_from``; :meth:`ship` → one
    sequence-numbered block with its raw payload framed after the JSON
    line (``nbytes``); :meth:`commit` → the receiver verifies the
    chain, admits decode-only, and replies with the generated tokens.
    Any transport or protocol failure raises — the caller's fallback
    contract (serve locally) handles it."""

    def __init__(self, host: str, port: int,
                 timeout: float | None = None):
        self._timeout = timeout if timeout is not None else timeout_s()
        self._sock = socket.create_connection((host, port),
                                              timeout=self._timeout)
        self._rfile = self._sock.makefile("rb")

    def _round_trip(self, obj: dict, payload: bytes | None = None) -> dict:
        wire = (json.dumps(obj) + "\n").encode()
        if payload is not None:
            wire += payload
        self._sock.sendall(wire)
        line = self._rfile.readline()
        if not line:
            raise ConnectionError("kv stream peer closed mid-handoff")
        resp = json.loads(line)
        if isinstance(resp, dict) and resp.get("error"):
            raise RuntimeError(
                f"kv stream peer error: {resp.get('type')}: "
                f"{resp['error']}")
        return resp

    def offer(self, handoff_id: str, hashes_hex: list,
              n_blocks: int, meta: dict,
              trace_id: str | None = None) -> int:
        """``kv_offer``: the dedup-eligible hash chain + handoff
        geometry. Returns the receiver's ``need_from`` — the longest
        chain prefix its prefix cache already holds."""
        resp = self._round_trip({
            "cmd": "kv_offer", "handoff_id": handoff_id,
            "hashes": list(hashes_hex), "n_blocks": int(n_blocks),
            "meta": meta, "trace_id": trace_id})
        return int(resp["need_from"])

    def ship(self, handoff_id: str, block: int, seq: int,
             payload: bytes) -> None:
        """``kv_ship``: one block, sequence-numbered; the receiver's
        ack is the completion signal."""
        resp = self._round_trip(
            {"cmd": "kv_ship", "handoff_id": handoff_id,
             "block": int(block), "seq": int(seq),
             "nbytes": len(payload)}, payload)
        if int(resp.get("seq", -1)) != int(seq):
            raise RuntimeError(
                f"kv stream signal mismatch: shipped seq {seq}, "
                f"peer acked {resp.get('seq')}")

    def commit(self, handoff_id: str, prompt_ids: list, first: int,
               gen_len: int, stop_tokens=None,
               trace_id: str | None = None,
               timeout: float | None = None) -> dict:
        """``kv_commit``: the receiver verifies the chain against the
        prompt, admits the row decode-only, runs the generation, and
        replies ``{"tokens": [...]}``. The commit round trip waits on
        the whole decode, so it takes its own (longer) timeout."""
        self._sock.settimeout(timeout if timeout is not None
                              else max(self._timeout, 120.0))
        return self._round_trip({
            "cmd": "kv_commit", "handoff_id": handoff_id,
            "prompt_ids": [int(t) for t in prompt_ids],
            "first": int(first), "gen_len": int(gen_len),
            "stop_tokens": (None if stop_tokens is None
                            else [int(t) for t in stop_tokens]),
            "trace_id": trace_id})

    def close(self) -> None:
        try:
            self._rfile.close()
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class HandoffStaging:
    """Receiver-side staging table: handoff_id → the blocks received
    so far. Entries live here between ``kv_offer`` and ``kv_commit``;
    a sender that dies mid-stream (the ``sever_stream`` chaos
    scenario) simply stops shipping, so :meth:`purge_stale` drops
    half-received entries older than ``TDT_KVSTREAM_STALE_S`` and
    counts them into ``disagg.streams_severed`` — the decode replica's
    pool never leaks for a prefill replica's death."""

    def __init__(self, stale_after_s: float | None = None):
        self._lock = threading.Lock()
        self._entries: dict = {}
        self.stale_after_s = (stale_after_s if stale_after_s is not None
                              else stale_s())

    def open(self, handoff_id: str, hashes_hex: list, n_blocks: int,
             need_from: int, meta: dict) -> None:
        with self._lock:
            self._entries[handoff_id] = {
                "hashes": list(hashes_hex), "n_blocks": int(n_blocks),
                "need_from": int(need_from), "meta": dict(meta),
                "blocks": {}, "seqs": [], "t0": time.monotonic()}

    def put(self, handoff_id: str, block: int, seq: int,
            payload: bytes) -> None:
        with self._lock:
            e = self._entries.get(handoff_id)
            if e is None:
                raise KeyError(
                    f"unknown or expired handoff {handoff_id!r} "
                    f"(offer first, or the entry went stale)")
            e["blocks"][int(block)] = payload
            e["seqs"].append(int(seq))

    def take(self, handoff_id: str) -> dict:
        """Claim a completed entry for admission (removes it)."""
        with self._lock:
            e = self._entries.pop(handoff_id, None)
        if e is None:
            raise KeyError(
                f"unknown or expired handoff {handoff_id!r}")
        return e

    def drop(self, handoff_id: str) -> None:
        with self._lock:
            self._entries.pop(handoff_id, None)

    def purge_stale(self, now: float | None = None) -> int:
        """Drop entries older than the staleness budget; returns how
        many were severed (counted by the caller into
        ``disagg.streams_severed``)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            dead = [h for h, e in self._entries.items()
                    if now - e["t0"] > self.stale_after_s]
            for h in dead:
                del self._entries[h]
        return len(dead)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def verify(self, entry: dict, prompt_ids, page_size: int,
               hash_chain) -> None:
        """The admission gate: the decode row may be admitted
        decode-only ONLY when (1) the recomputed hash chain of the
        prompt matches the offered chain, (2) every shipped block's
        sequence is contiguous from 0 (no signal before its block, no
        double-ship), and (3) blocks ``need_from .. n_blocks-1`` are
        all present. Raises ``ValueError`` otherwise — the caller
        falls back to a local re-prefill, never a wrong decode."""
        offered = entry["hashes"]
        local = [h.hex() for h in hash_chain]
        if local[:len(offered)] != list(offered):
            raise ValueError(
                "kv handoff chain mismatch: offered hash chain does "
                "not match the committed prompt's recomputed chain")
        n_blocks = entry["n_blocks"]
        if n_blocks != block_span(len(prompt_ids), page_size):
            raise ValueError(
                f"kv handoff geometry mismatch: offered {n_blocks} "
                f"blocks, prompt spans "
                f"{block_span(len(prompt_ids), page_size)}")
        sched = ship_schedule(n_blocks, entry["need_from"])
        want_seqs = [s for _, s in sched]
        if sorted(entry["seqs"]) != want_seqs:
            raise ValueError(
                f"kv handoff signal sequence broken: got "
                f"{sorted(entry['seqs'])}, expected {want_seqs} "
                f"(severed stream, double-ship, or dropped signal)")
        missing = [j for j, _ in sched if j not in entry["blocks"]]
        if missing:
            raise ValueError(
                f"kv handoff incomplete: needed blocks {missing} "
                f"never arrived")
