"""Generation server with cross-request continuous batching.

TPU-native analog of the reference's demo server
(python/triton_dist/mega_triton_kernel/test/models/model_server.py: a
socket server feeding the megakernel model, with chat.py as the
client) — extended past it: generation routes through the
continuous-batching scheduler (serving/scheduler.py) by default, so
prompts from DIFFERENT connections coexist in one decode batch
instead of queueing whole generations behind a lock
(docs/serving.md "Scheduler").
Protocol: newline-delimited JSON over TCP —

    → {"prompt_ids": [[...]], "gen_len": 16, "stop_tokens": [151645]}
    ← {"tokens": [[...]], "gen_len": 16, "latency_ms": 12.3}

``stop_tokens`` is optional (default: the model config's eos). The
response's ``gen_len`` echoes the EFFECTIVE value — requests past the
protocol cap (4096) or the engine's room (max_seq − longest prompt)
are clamped, counted into ``server.gen_len_clamped``, never silent.
A full admission queue answers a structured backpressure reply
instead of stalling the connection, with a ``retry_after_ms`` hint
(rolling TPOT × queue depth, clamped — ISSUE 15) that ``ChatClient``
honors instead of immediately hammering again —

    ← {"error": ..., "type": "queue_full", "queue_depth": N,
       "max_waiting": M, "retry_after_ms": T}

``{"cmd": "drain"}`` starts a graceful drain (nothing new admitted —
generation requests answer ``{"type": "draining", ...}`` — while
everything in flight finishes; ``"wait_s"`` blocks until idle,
``"resume": true`` cancels): the verb a router's graceful replica
removal speaks (docs/serving.md "Drain").

Telemetry (docs/observability.md): a metrics request on the same
protocol returns the server's registry snapshot, stamped with this
replica's identity —

    → {"cmd": "metrics"}
    ← {"metrics": {"counters": ..., "gauges": ..., "histograms": ...,
                   "replica_id": "host:port"}}

with ``"format": "prometheus"`` adding a ``prometheus`` text-exposition
field for scrapers; a metrics scrape first forces a fresh SLO
evaluation, so the ``serving.rolling.*`` / ``serving.slo_burn.*``
gauges are current as of the reply (``"evaluate": false`` skips that —
the last-evaluated gauges are returned as-is, which is what a 1 Hz
dashboard over N replicas should ask for).
Constructing a ModelServer enables the telemetry registry
(``telemetry=False`` opts out).

The fleet control surface (ISSUE 14, docs/observability.md "Fleet
view"): every server carries a stable ``replica_id`` (ctor >
``TDT_REPLICA_ID`` > ``host:port``) stamped into its metrics
snapshot, its scheduler's trace instants, and its flight-dump
filenames, and answers the CHEAP health verb —

    → {"cmd": "health"}
    ← {"health": {"replica_id": ..., "seq": N, "uptime_s": ...,
                  "rolling": ..., "slo": ..., "queue_depth": ...,
                  "batch_occupancy": ..., "breakers": ..., ...}}

``health`` never force-evaluates SLOs and reads gauges lock-free
(``obs.fleet.replica_health``): monitoring N replicas at 1 Hz
perturbs no pump loop. ``seq`` is a monotonic per-server snapshot
number. ``registry="private"`` gives the server its own metrics
registry (``obs.scoped_registry`` routes its handler threads and
scheduler pump there), so several replicas in ONE process — the
``serving_fleet`` bench, the fleet tests — keep distinct,
correctly-fleet-summable metrics.

Per-request latency attribution (ISSUE 8): scheduler-served responses
carry a ``"timing"`` waterfall per prompt (queue_wait → prefill →
decode segments summing to the request's wall time, plus prefix-cache
savings and per-token share — ``obs.attrib``), and the last-K ring is
queryable —

    → {"cmd": "request_stats", "last": 8}
    ← {"requests": [waterfall, ...]}        # newest first

Tracing (docs/observability.md "Tracing"): the server also runs the
event tracer / flight recorder by default (``TDT_TRACE=0`` opts out).
Every generation request gets a trace ID — the client's own
``"trace_id"`` if it sent one, a fresh one otherwise — carried by its
``serving.request`` span (handler thread) and by its scheduler-side
``serving.admit`` / ``serving.retire`` instants and admission events
(pump thread, re-bound per admission), so the request's
queue → admit → retire story filters to one ID in an exported
timeline; the shared decode-step spans serve many requests at once
and stay unbound. The ID is echoed back in the response. The flight recorder dumps the last
``TDT_FLIGHT_SECONDS`` of events on demand —

    → {"cmd": "dump_trace"}
    ← {"dumped": "/tmp/tdt_trace/flight_cmd_....trace.json", ...}

— and automatically on unhandled per-request failures, watchdog
trips, breaker opens, and SIGTERM.

Text in/out (tokenizer round trip) is the client's job when a HF
tokenizer is available; the server moves token ids only, like the
reference's server.
"""

from __future__ import annotations

import itertools
import json
import os
import socket
import socketserver
import threading
import time

import jax.numpy as jnp
import numpy as np

from triton_dist_tpu import obs
from triton_dist_tpu.obs import fleet as _fleet
from triton_dist_tpu.obs import flight, trace


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        # One scope per connection: this handler thread's emissions
        # (request counters, error accounting, everything the request
        # path records) land in the owning server's registry — the
        # per-replica isolation that keeps fleet counter sums correct
        # when several servers share a process (no-op when the server
        # uses the process-global registry). The connection registers
        # with the owner so a chaos-harness kill can SEVER live
        # connections (testing/chaos.py: a killed replica's clients
        # must see a dead socket, never a polite error reply).
        owner = self.server.model_server
        track = getattr(owner, "_track_connection", None)
        if track is not None:
            track(self.connection)
        try:
            with obs.scoped_registry(owner.registry):
                self._handle_scoped()
        finally:
            untrack = getattr(owner, "_untrack_connection", None)
            if untrack is not None:
                untrack(self.connection)

    def _handle_scoped(self):
        try:
            self._serve_lines()
        except OSError:
            # The peer vanished mid-read (reset/abort): routers
            # abandon dispatch connections at their per-attempt
            # deadline BY DESIGN (serving/router.py), and a chaos
            # sever does the same — connection-scoped, the server
            # keeps serving every other client.
            return

    def _serve_lines(self):
        for line in self.rfile:
            line = line.strip()
            if not line:
                continue
            # A per-request failure — malformed JSON, bad arguments, or
            # the engine/fused-kernel path blowing up — answers THIS
            # request with a structured error and keeps both the
            # connection and the serve loop alive: a bad request must
            # degrade a request, never the process (docs/resilience.md).
            try:
                req = json.loads(line)
            except Exception as e:
                obs.counter("server.errors").inc()
                resp = {"error": f"malformed request: {e}",
                        "type": type(e).__name__}
            else:
                # Length-prefixed binary framing (ISSUE 18): a request
                # carrying "nbytes" is followed by exactly that many
                # raw bytes (the kv_ship block payload) — read them
                # off the SAME buffered stream before the next JSON
                # line. A short read means the peer died mid-frame:
                # connection-scoped, like any other sever.
                nbytes = req.get("nbytes") if isinstance(req, dict) \
                    else None
                if nbytes is not None:
                    payload = self.rfile.read(int(nbytes))
                    if len(payload) != int(nbytes):
                        return
                    req["_payload"] = payload
                try:
                    resp = self.server.model_server._serve_request(req)
                except Exception as e:  # report, keep serving
                    obs.counter("server.errors").inc()
                    # The request died past parsing — an engine/kernel
                    # failure, not client garbage: leave a postmortem
                    # of what the process was doing (rate-limited,
                    # never raises; no-op when tracing is off).
                    flight.maybe_dump("serve_error")
                    resp = {"error": str(e) or repr(e),
                            "type": type(e).__name__}
            try:
                wire = json.dumps(resp)
            except (TypeError, ValueError) as e:
                obs.counter("server.errors").inc()
                wire = json.dumps({"error": f"unserializable response: "
                                            f"{e}",
                                   "type": type(e).__name__})
            try:
                self.wfile.write((wire + "\n").encode())
                self.wfile.flush()
            except OSError:
                # Client hung up mid-response: connection-scoped —
                # the ThreadingTCPServer keeps serving other clients.
                break


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class ModelServer:
    """Wraps an Engine behind a TCP JSON-lines protocol.

    By default generation runs through the continuous-batching
    :class:`~triton_dist_tpu.serving.scheduler.Scheduler`: every
    connection's prompts share ONE decode batch, so a short request
    admitted while a long generation is mid-decode completes without
    queueing behind it (docs/serving.md "Scheduler"). ``scheduler=False``
    restores the serialized-lock path (one generation at a time).
    Every decode path is schedulable — the mega one-program step takes
    per-row offsets and paged tables like the plain step (ISSUE 11) —
    so ``use_mega`` / ``decode_path`` engines stream through the
    shared batch like any other.
    """

    def __init__(self, engine, params, host: str = "127.0.0.1",
                 port: int = 0, telemetry: bool = True,
                 scheduler: bool | None = None,
                 max_waiting: int | None = None,
                 prefill_chunk: int | None = None,
                 replica_id: str | None = None, registry=None,
                 tier: str = "unified"):
        """``replica_id``: this server's stable fleet identity
        (explicit > ``TDT_REPLICA_ID`` > ``host:port`` after bind).
        ``registry``: ``"private"`` gives the server its own metrics
        registry (or pass a ``obs.Registry``) — REQUIRED for distinct
        per-replica metrics when several servers share one process;
        the default (None) keeps the historical process-global
        registry. ``tier`` (ISSUE 18): this replica's advertised role
        in a disaggregated fleet — ``"prefill"``, ``"decode"``, or the
        default ``"unified"``; it rides the health verb so a tiered
        router (``TDT_ROUTER_TIERS``) can pool replicas without extra
        config, and any scheduler-path paged server answers the
        ``kv_*``/``disagg_prefill`` verbs regardless of tier (the
        tier is placement policy, not capability)."""
        self.engine = engine
        self.params = params
        self.registry = None
        if registry == "private":
            self.registry = obs.Registry()
        elif registry is not None:
            self.registry = registry
        if telemetry:
            # A serving process wants its numbers scrapeable; direct
            # Engine users keep the zero-overhead no-op default.
            obs.enable()
            # ... and its flight recorder armed: the bounded ring
            # buffer is the whole cost, and a hang with no recorder is
            # the round-5 postmortem-less failure class. TDT_TRACE=0
            # opts out (docs/observability.md "Tracing").
            if trace.env_enabled(default=True):
                trace.enable()
                flight.install_signal_handlers()
        # Live connection registry (chaos harness: kill_replica severs
        # these; see _Handler.handle).
        self._conn_lock = threading.Lock()
        self._active_conns: set = set()
        # Bind FIRST so the default replica_id can be host:port — but
        # close the listening socket if the REST of construction
        # raises (e.g. a malformed TDT_MAX_WAITING inside the
        # Scheduler ctor): pre-ISSUE-14 the bind happened last, so a
        # ctor failure never left a bound fd behind.
        self._lock = threading.Lock()  # serialized path only
        self._srv = _TCPServer((host, port), _Handler)
        try:
            self._srv.model_server = self
            self.host, self.port = self._srv.server_address
            self.replica_id = str(
                replica_id
                or os.environ.get("TDT_REPLICA_ID", "").strip()
                or f"{self.host}:{self.port}")
            self._started_monotonic = time.monotonic()
            self._health_seq = itertools.count(1)  # thread-safe counter
            if telemetry:
                # Flight dumps (filename + metadata) carry the replica
                # identity so two same-host replicas' postmortems
                # cannot alias (in-process multi-server shares one
                # tracer — the last server's id wins there,
                # documented). Unconditional on tracing state: a
                # cheap global write now means dumps stay stamped
                # even when tracing is enabled AFTER server start.
                flight.set_replica_id(self.replica_id)
            if scheduler is None:
                # Auto: on for engines a stream session can actually
                # serve (test doubles without a kv keep the serialized
                # path). Oversubscribed paged pools stream via
                # block-granular admission (ISSUE 6), and mega engines
                # stream via the per-row mega step (ISSUE 11) —
                # neither is a special case anymore.
                # ``scheduler=False`` stays as the explicit
                # serialized-path override.
                scheduler = getattr(engine, "kv", None) is not None
            self.scheduler = None
            if scheduler:
                from triton_dist_tpu.serving.scheduler import Scheduler
                self.scheduler = Scheduler(
                    engine, params, max_waiting=max_waiting,
                    prefill_chunk=prefill_chunk,
                    replica_id=self.replica_id,
                    registry=self.registry).start()
            self.tier = str(tier)
            self.disagg = None
            if self.scheduler is not None \
                    and getattr(engine, "paged", False):
                # Disaggregated handoff endpoint (ISSUE 18,
                # serving/disagg.py): decode-only admission needs the
                # paged pools; non-paged or serialized servers simply
                # don't answer the kv verbs.
                from triton_dist_tpu.serving.disagg import \
                    DisaggEndpoint
                self.disagg = DisaggEndpoint(self)
        except BaseException:
            self._srv.server_close()
            raise
        self._thread: threading.Thread | None = None

    def _track_connection(self, conn) -> None:
        with self._conn_lock:
            self._active_conns.add(conn)

    def _untrack_connection(self, conn) -> None:
        with self._conn_lock:
            self._active_conns.discard(conn)

    def _serve_request(self, req: dict) -> dict:
        # Handler threads route their emissions into this replica's
        # registry (no-op scope when registry=None — the historical
        # process-global path).
        with obs.scoped_registry(self.registry):
            return self._serve_request_scoped(req)

    def _serve_request_scoped(self, req: dict) -> dict:
        if "cmd" in req:
            return self._serve_command(req)
        obs.counter("server.requests").inc()
        obs.gauge("server.inflight").inc()
        # One trace ID per request, bound to the handling thread: the
        # serving span below plus every engine/op/resilience event the
        # generation emits (same thread — generation runs under the
        # lock in this handler) carries it, and the client gets it
        # back for cross-referencing a later dump.
        trace_id = str(req.get("trace_id") or trace.new_trace_id())
        try:
            with trace.bind(trace_id), \
                    trace.span("serving.request", "serving",
                               args={"gen_len": req.get("gen_len"),
                                     "batch": len(req.get(
                                         "prompt_ids", []) or [])}):
                resp = self._serve_generate(req)
        finally:
            obs.gauge("server.inflight").dec()
        if trace.enabled():
            resp.setdefault("trace_id", trace_id)
        return resp

    def _serve_command(self, req: dict) -> dict:
        """Control-plane requests on the same JSON-lines protocol."""
        cmd = req["cmd"]
        if cmd == "health":
            # The CHEAP control verb (ISSUE 14): lock-free gauge/
            # counter peeks, NO SLO force-evaluation — the pump
            # refreshes the gauges every working iteration, and the
            # monotonic ``seq`` + ``uptime_s`` let the fleet view
            # judge freshness. Monitoring N replicas at 1 Hz through
            # this perturbs no pump loop (obs.fleet.replica_health).
            obs.counter("serving.replica_health_requests").inc()
            seq = next(self._health_seq)
            obs.gauge("serving.replica_health_seq").set(seq)
            health = _fleet.replica_health(
                self.replica_id, seq, self._started_monotonic,
                registry=self.registry or obs.get_registry(),
                engine=self.engine, scheduler=self.scheduler,
                tier=self.tier)
            obs.gauge("serving.replica_uptime_s").set(
                health["uptime_s"])
            return {"health": health}
        if cmd == "metrics":
            # Snapshot under the generation lock is NOT needed: the
            # registry is internally locked, and a scraper must not
            # queue behind a multi-second generation.
            if req.get("evaluate", True) \
                    and self.scheduler is not None \
                    and self.scheduler.slo is not None:
                # Rolling/burn gauges current as of THIS scrape (the
                # pump only evaluates while it is doing work).
                # ``"evaluate": false`` opts out — dashboards polling
                # N replicas read the last-evaluated gauges instead
                # of forcing N quantile merges per tick.
                self.scheduler.slo.evaluate(force=True)
            snap = obs.snapshot()
            snap["replica_id"] = self.replica_id
            if trace.enabled():
                # Tracing counts + last flight record ride inside the
                # snapshot (tools/report.py renders them as the
                # Tracing section; merge_snapshots ignores the key).
                snap["trace"] = trace.stats()
            from triton_dist_tpu.obs import devprof
            if devprof.last_profile() is not None \
                    or devprof.armed_reason() is not None:
                # Device-profile state (last parsed capture path,
                # armed reason) rides the same way — tools/report.py
                # and tools/top.py render it as the device-time
                # section.
                snap["devprof"] = devprof.stats()
            resp = {"metrics": snap}
            if req.get("format") == "prometheus":
                resp["prometheus"] = obs.render_prometheus(snap)
            return resp
        if cmd == "drain":
            # Graceful drain (ISSUE 15, docs/serving.md "Drain"): stop
            # admitting, finish what is in flight. ``"resume": true``
            # cancels; ``"wait_s": N`` blocks until idle (or the
            # deadline). The reply always carries the live in-flight
            # count so a router can poll the drain to completion.
            if self.scheduler is None:
                obs.counter("server.errors").inc()
                return {"error": "drain needs the scheduler path "
                                 "(scheduler=False serializes whole "
                                 "generations — stop the server "
                                 "instead)"}
            if req.get("resume"):
                self.scheduler.resume()
                return {"draining": False,
                        "inflight": self.scheduler.inflight()}
            self.scheduler.drain()
            drained = None
            if req.get("wait_s") is not None:
                drained = self.scheduler.wait_idle(
                    float(req["wait_s"]))
            resp = {"draining": True,
                    "inflight": self.scheduler.inflight()}
            if drained is not None:
                resp["drained"] = drained
            return resp
        if cmd == "dump_trace":
            if not trace.enabled():
                obs.counter("server.errors").inc()
                return {"error": "tracing is disabled (TDT_TRACE)"}
            path = flight.dump("cmd", last_s=req.get("seconds"))
            return {"dumped": path, "trace": trace.stats()}
        if cmd == "request_stats":
            # The attribution ring (obs.attrib): the newest `last`
            # finished requests' waterfalls, newest first.
            from triton_dist_tpu.obs import attrib
            return {"requests": attrib.last(req.get("last"))}
        if cmd == "history":
            # Sampled series (ISSUE 16, docs/serving.md "History"):
            # downsampled ring-buffer points from the scheduler's
            # opt-in sampler — ``{"history": null}`` when no sampler
            # runs (TDT_HISTORY unset), so dashboards degrade instead
            # of erroring. ``last_s`` trims the window, ``series``
            # filters names, ``max_points`` bounds the reply size
            # (sparkline scrapes need ~32 points, not the whole ring).
            sampler = getattr(self.scheduler, "history", None)
            if sampler is None:
                return {"history": None}
            series = req.get("series")
            return {"history": sampler.snapshot(
                last_s=req.get("last_s"),
                series=list(series) if series else None,
                max_points=req.get("max_points"))}
        if self.disagg is not None and cmd in self.disagg.VERBS:
            # Disaggregated handoff verbs (ISSUE 18): kv_offer /
            # kv_ship / kv_commit (decode side) and disagg_prefill
            # (prefill side). A verb failure answers THIS request with
            # the structured error the sender's fallback contract
            # expects (_serve_lines wraps it).
            return self.disagg.handle(cmd, req)
        obs.counter("server.errors").inc()
        return {"error": f"unknown cmd {cmd!r} (known: metrics, "
                         f"health, drain, dump_trace, request_stats, "
                         f"history, kv_offer, kv_ship, kv_commit, "
                         f"disagg_prefill)"}

    def _effective_gen_len(self, req: dict, prompts) -> int:
        """Clamp the requested gen_len to the protocol cap (4096) AND
        the engine's room (max_seq − longest prompt). The clamp is no
        longer silent: the response echoes the effective value under
        ``"gen_len"`` and every clamped request counts into
        ``server.gen_len_clamped``, so clients can tell they asked for
        more than they got."""
        requested = int(req.get("gen_len", 16))
        room = self.engine.kv.max_seq - max(
            (len(p) for p in prompts), default=0)
        gen_len = max(0, min(requested, 4096, room))
        if gen_len != requested:
            obs.counter("server.gen_len_clamped").inc()
        return gen_len

    def _serve_generate(self, req: dict) -> dict:
        t_req0 = time.perf_counter()
        prompts = req["prompt_ids"]
        gen_len = self._effective_gen_len(req, prompts)
        stop = req.get("stop_tokens")  # None → engine default (eos)
        if self.scheduler is not None:
            from triton_dist_tpu.serving.scheduler import (
                Draining, QueueFull)
            try:
                futures = self.scheduler.submit_many(
                    prompts, gen_len, stop_tokens=stop,
                    trace_id=trace.current_trace_id())
            except Draining:
                # Graceful drain in progress: structurally like
                # queue_full (retry elsewhere / later) but with its
                # own type so a router knows this replica is LEAVING,
                # not merely busy.
                obs.counter("server.backpressure_replies").inc()
                return {"error": "replica is draining — retry on "
                                 "another replica",
                        "type": "draining",
                        "inflight": self.scheduler.inflight(),
                        "retry_after_ms":
                            self.scheduler.retry_after_ms()}
            except QueueFull:
                # Structured backpressure, not an exception page: the
                # client sees WHY and can retry; the connection (and
                # every other request in flight) is untouched. The
                # retry_after_ms hint (rolling TPOT × queue depth,
                # clamped) tells it WHEN — ChatClient honors it
                # instead of hammering (docs/serving.md).
                obs.counter("server.backpressure_replies").inc()
                return {"error": "admission queue full — retry later",
                        "type": "queue_full",
                        "queue_depth": self.scheduler.queue_depth(),
                        "max_waiting": self.scheduler.max_waiting,
                        "retry_after_ms":
                            self.scheduler.retry_after_ms()}
            # Rows retire exactly at their first stop token, so the
            # uniform client contract (tokens end at and include the
            # first stop token) needs no trimming here.
            tokens = [f.result() for f in futures]
            ms = (time.perf_counter() - t_req0) * 1e3
            obs.histogram("server.request_ms").observe(ms)
            resp = {"tokens": tokens, "gen_len": gen_len,
                    "latency_ms": round(ms, 3)}
            # Per-prompt latency attribution (obs.attrib): where this
            # request's time went, segment sums matching latency_ms
            # up to handler↔pump handoff (docs/observability.md).
            timing = [f.timing for f in futures]
            if any(t is not None for t in timing):
                resp["timing"] = timing
            return resp
        return self._serve_generate_serialized(req, prompts, gen_len,
                                               stop, t_req0)

    def _serve_generate_serialized(self, req, prompts, gen_len, stop,
                                   t_req0) -> dict:
        # The pre-scheduler path (scheduler=False): a global lock
        # serializes whole generations. The request clock
        # starts BEFORE the lock: under load, queue wait is the
        # dominant latency component and server.request_ms must show
        # it (client-facing latency_ms keeps its original
        # generation-only meaning here).
        lens = [len(p) for p in prompts]
        ragged = len(set(lens)) > 1
        batch = self.engine.kv.batch
        # Uniform client contract across all three engine routes: each
        # row's tokens end at (and include) the first stop token.
        # serve()/serve_ragged() pad stopped rows to a rectangle with
        # the stop token; serve_stream() retires exactly — normalize to
        # the latter (the server branch taken is an internal engine
        # dimension the client cannot see).
        if stop is None:
            eos = getattr(self.engine.model.config, "eos_token_id", -1)
            stop_set = {eos} if eos >= 0 else set()
        else:
            stop_set = set(int(t) for t in stop)

        def trim(row):
            row = list(row)
            for i, t in enumerate(row):
                if t in stop_set:
                    return row[:i + 1]
            return row

        with self._lock:
            t0 = time.perf_counter()
            if len(prompts) > batch:
                # More requests than decode rows: continuous batching
                # pumps the stream through the fixed window
                # (Engine.serve_stream).
                rows = self.engine.serve_stream(self.params, prompts,
                                                gen_len, stop_tokens=stop)
                tokens = [r[ln:] for r, ln in zip(rows, lens)]
            elif ragged:
                rows = self.engine.serve_ragged(self.params, prompts,
                                                gen_len, stop_tokens=stop)
                tokens = [r[ln:].tolist() for r, ln in zip(rows, lens)]
            else:
                ids = np.asarray(prompts, np.int32)
                out = np.asarray(self.engine.serve(
                    self.params, jnp.asarray(ids), gen_len,
                    stop_tokens=stop))
                tokens = out[:, ids.shape[1]:].tolist()
            ms = (time.perf_counter() - t0) * 1e3
        obs.histogram("server.request_ms").observe(
            (time.perf_counter() - t_req0) * 1e3)
        return {"tokens": [trim(r) for r in tokens], "gen_len": gen_len,
                "latency_ms": round(ms, 3)}

    def start(self):
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        if self.disagg is not None:
            # Same-process transport tier (ISSUE 18): a sibling
            # prefill replica in this process hands blocks over
            # directly instead of re-entering the TCP stack.
            from triton_dist_tpu.serving import disagg as _disagg
            _disagg.register_inproc(f"{self.host}:{self.port}",
                                    self.disagg)
        return self

    def stop(self):
        if self.disagg is not None:
            from triton_dist_tpu.serving import disagg as _disagg
            _disagg.unregister_inproc(f"{self.host}:{self.port}")
        self._srv.shutdown()
        self._srv.server_close()
        if self.scheduler is not None:
            self.scheduler.stop()


def main():  # pragma: no cover - manual demo
    import argparse
    import jax
    from jax.sharding import Mesh
    from triton_dist_tpu.models import AutoLLM, Engine, ModelConfig

    ap = argparse.ArgumentParser()
    ap.add_argument("--model-dir", default=None,
                    help="HF checkpoint dir (random tiny model if unset)")
    ap.add_argument("--port", type=int, default=8777)
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--max-seq", type=int, default=1024)
    args = ap.parse_args()

    mesh = Mesh(np.array(jax.devices()), ("tp",))
    if args.model_dir:
        model, params = AutoLLM.from_pretrained(args.model_dir, mesh=mesh)
    else:
        cfg = ModelConfig(num_hidden_layers=2, hidden_size=256,
                          intermediate_size=512, num_attention_heads=8,
                          num_key_value_heads=8, head_dim=32,
                          vocab_size=1024)
        model = AutoLLM.build(cfg, mesh=mesh)
        params = model.init(jax.random.PRNGKey(0))
    eng = Engine(model, batch=args.batch, max_seq=args.max_seq)
    srv = ModelServer(eng, params, port=args.port).start()
    print(f"serving on {srv.host}:{srv.port}")
    threading.Event().wait()


if __name__ == "__main__":  # pragma: no cover
    main()
