"""Fault-tolerant replica router: the fleet's front door (ISSUE 15).

One :class:`RouterServer` speaks the existing JSON-lines wire protocol
in front of N ``ModelServer`` replicas, so a client talks to ONE
endpoint and the fleet's failures stay the fleet's problem — a dead
replica costs the fleet its capacity share, never a client-visible
failure or a head-of-line stall (the T3 interleaving thesis applied at
the fleet level; the one-sided-progress posture of the NVSHMEM paper
in PAPERS.md applied to replicas instead of peers):

- **Health-gated placement.** The router owns an
  :class:`~triton_dist_tpu.obs.fleet.FleetView` over the replicas
  (background health polls every ``TDT_ROUTER_POLL_S``) and places
  each generation request on the best-scoring replica via the ISSUE-14
  ``placement_score`` ranking — ``down`` replicas are excluded,
  ``stale`` ones penalized, ``draining`` ones (router-side or
  advertised through the health verb) skipped outright.
- **Per-replica circuit breakers.** Each replica carries its own
  :class:`~triton_dist_tpu.resilience.breaker.CircuitBreaker`
  (op ``replica.<host:port>`` — the same machinery, gauges and
  half-open probe semantics the fused-op paths use): dispatch
  failures open it, an open breaker removes the replica from
  placement until the cooldown admits one half-open probe dispatch,
  and that probe's outcome re-closes or re-opens it.
- **Failover re-dispatch.** Generation requests are RE-ISSUABLE: the
  router holds the prompt, so when a replica dies or wedges mid-flight
  (connection refused/reset, per-attempt timeout, torn reply, or any
  error reply that is a REPLICA fault — engine/device failure, a
  dying scheduler's farewell; the request's own errors like a
  malformed prompt pass through, replaying them elsewhere would fail
  identically) the router replays the
  request on the next healthy replica — bounded by
  ``TDT_ROUTER_RETRIES`` re-dispatches with ``TDT_ROUTER_BACKOFF_MS``
  exponential backoff, all inside the request's
  ``TDT_ROUTER_DEADLINE_S`` budget — and the client sees ONE response,
  annotated ``"failovers": n``. Greedy decode replays are
  idempotent-by-construction (same prompt → same tokens on any
  replica); docs/resilience.md "Replica failover" carries the full
  argument.
- **Structured load-shed.** When every placeable replica sheds
  (``queue_full`` / ``draining``) the router answers a FLEET-level
  ``{"type": "queue_full", "scope": "fleet"}`` with a
  ``retry_after_ms`` hint derived from the replicas' rolling TPOT ×
  queue depth (``serving.scheduler.retry_after_ms_hint`` — the
  soonest replica's estimate); when nothing is placeable at all (or
  the retry/deadline budget runs out) the reply is
  ``{"type": "no_healthy_replicas"}``, still with a hint.
- **Live add/remove with graceful drain.** ``router_add`` attaches a
  replica (it joins placement after its first health poll);
  ``router_remove`` stops placing, waits for the router's in-flight
  dispatches to that replica to finish (optionally asking the replica
  itself to ``drain``), then detaches — in-flight accounting rides
  the per-replica dispatch counters, the replica side rides
  ``Scheduler.inflight()``.
- **Observability.** ``router.*`` counters/gauges (docs/observability
  .md), ``router.request`` spans + ``router.failover`` /
  ``router.shed`` / ``router.replica_down`` instants carrying the
  request's trace ID (the router forwards the SAME ID to every
  dispatch attempt, so one Perfetto story spans the failed replica,
  the failover hop, and the replica that answered), and flight dumps
  on a replica going down and on failover storms
  (``TDT_ROUTER_STORM`` failovers within 10 s).

Protocol verbs (docs/serving.md "Router"): generation requests and
``dump_trace``/``metrics``/``health`` behave like a single server's
(metrics/health are the ROUTER's own — scrape replicas directly, or
through ``router_status``, for theirs); plus

    → {"cmd": "router_status"}
    ← {"router": {"replicas": [...], "counters": ...,
                  "uptime_s": ...}}
    → {"cmd": "router_add", "endpoint": "host:port"}
    → {"cmd": "router_remove", "endpoint": "host:port",
       "drain": true, "wait_s": 10}

Tested end to end by the chaos harness (testing/chaos.py +
tests/test_router.py): kill one of three replicas mid-traffic-window →
zero failed client requests, every in-flight request re-dispatched
(``failovers ≥ 1``), the replica marked down within the configured
age, and a validated flight dump with the trace-ID-stitched failover
story. The ``serving_router`` bench part measures the same scenario
(`serving_router_vs_direct`, gated by ``check_router_wellformed``).
"""

from __future__ import annotations

import collections
import itertools
import os
import threading
import time

from triton_dist_tpu import obs
from triton_dist_tpu.obs import flight, trace
from triton_dist_tpu.obs.fleet import (
    FleetView, _env_float, parse_endpoint)
from triton_dist_tpu.resilience.breaker import CircuitBreaker
from triton_dist_tpu.serving.scheduler import retry_after_ms_hint
from triton_dist_tpu.serving.server import _Handler, _TCPServer

__all__ = ["DEFAULT_BACKOFF_MS", "DEFAULT_DEADLINE_S",
           "DEFAULT_POLL_S", "DEFAULT_RETRIES", "DEFAULT_STORM",
           "DEFAULT_TRY_TIMEOUT_S", "RouterServer", "parse_tiers"]

DEFAULT_RETRIES = 3           # max re-dispatches per request
DEFAULT_BACKOFF_MS = 50       # base failover backoff (exponential)
DEFAULT_DEADLINE_S = 120.0    # whole-request re-dispatch budget
DEFAULT_TRY_TIMEOUT_S = 30.0  # per-dispatch-attempt cap
DEFAULT_POLL_S = 1.0          # background health-poll cadence
DEFAULT_STORM = 5             # failovers in STORM_WINDOW_S → dump
STORM_WINDOW_S = 10.0
#: Placement penalty per ROUTER-SIDE in-flight dispatch to a replica.
#: Health-derived scores only refresh per poll; between polls every
#: identical replica ties and the sort is stable, so without a live
#: term EVERY concurrent request would land on the same replica until
#: the next poll. The router's own dispatch counter is the real-time
#: signal placement_score cannot see (same scale as its QUEUE_WEIGHT
#: family — obs/fleet.py).
INFLIGHT_WEIGHT = 0.25
#: Replies whose type means "this replica is shedding, place
#: elsewhere" — liveness evidence, NOT a breaker failure.
_SHED_TYPES = ("queue_full", "draining")
#: Error-reply types that are the REQUEST's own fault (malformed
#: prompt, over-budget batch — the scheduler/server raise these for
#: client mistakes): passed through unchanged, since replaying the
#: same bad request elsewhere would fail identically. Every OTHER
#: error reply is a REPLICA fault (engine/device failure, a dying
#: scheduler's farewell) — re-dispatchable like a connection failure,
#: and a breaker count against the replica that produced it.
_CLIENT_FAULT_TYPES = ("ValueError", "TypeError", "KeyError")


class _Replica:
    """Router-side state for one replica endpoint."""

    __slots__ = ("endpoint", "label", "breaker", "inflight",
                 "draining", "last_status", "tier")

    def __init__(self, endpoint, breaker: CircuitBreaker,
                 tier: str = "unified"):
        self.endpoint = endpoint
        self.label = f"{endpoint[0]}:{endpoint[1]}"
        self.breaker = breaker
        self.inflight = 0          # router-side dispatches in flight
        self.draining = False      # router-side: stop placing
        self.last_status = None    # last observed FleetView status
        self.tier = tier           # prefill / decode / unified


def parse_tiers(spec: str) -> dict:
    """Parse ``TDT_ROUTER_TIERS`` — semicolon-separated
    ``tier=host:port`` entries, e.g.
    ``prefill=10.0.0.1:8777;decode=10.0.0.2:8777;decode=10.0.0.3:8777``
    — into ``{(host, port): tier}``. Unlisted replicas stay
    ``unified``; a replica's OWN health-advertised tier (ModelServer
    ``tier=...``) overrides this static map at every poll, so the env
    knob is only needed for replicas that don't advertise."""
    out: dict = {}
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        tier, sep, ep = part.partition("=")
        tier = tier.strip().lower()
        if not sep or tier not in ("prefill", "decode", "unified"):
            raise ValueError(
                f"TDT_ROUTER_TIERS entry {part!r} is not "
                f"tier=host:port with tier in prefill/decode/unified")
        out[parse_endpoint(ep.strip())] = tier
    return out


class RouterServer:
    """Front-end replica router over N ``ModelServer`` endpoints.

    Same construction surface as ``ModelServer`` where it makes sense:
    ``port=0`` picks a free port, ``registry="private"`` scopes the
    router's own metrics (REQUIRED when router and replicas share a
    process, e.g. the bench/tests), ``telemetry=True`` arms the
    tracer/flight recorder. The fault knobs are ctor-overridable for
    tests (``retries``, ``backoff_ms``, ``deadline_s``,
    ``try_timeout_s``, ``poll_s``, ``breaker_threshold``,
    ``breaker_cooldown_s``) and env-tunable in production
    (``TDT_ROUTER_*`` — docs/serving.md "Router")."""

    def __init__(self, endpoints, host: str = "127.0.0.1",
                 port: int = 0, telemetry: bool = True, registry=None,
                 retries: int | None = None,
                 backoff_ms: int | None = None,
                 deadline_s: float | None = None,
                 try_timeout_s: float | None = None,
                 poll_s: float | None = None,
                 breaker_threshold: int | None = None,
                 breaker_cooldown_s: float | None = None,
                 fleet: FleetView | None = None,
                 fleet_kwargs: dict | None = None,
                 tiers: dict | None = None):
        if not endpoints:
            raise ValueError("RouterServer needs at least one replica "
                             "endpoint")
        self.registry = None
        if registry == "private":
            self.registry = obs.Registry()
        elif registry is not None:
            self.registry = registry
        if telemetry:
            obs.enable()
            if trace.env_enabled(default=True):
                trace.enable()
                flight.install_signal_handlers()
        self.retries = (retries if retries is not None else
                        obs.env_int("TDT_ROUTER_RETRIES",
                                    DEFAULT_RETRIES))
        self.backoff_ms = (backoff_ms if backoff_ms is not None else
                           obs.env_int("TDT_ROUTER_BACKOFF_MS",
                                       DEFAULT_BACKOFF_MS))
        self.deadline_s = (deadline_s if deadline_s is not None else
                           _env_float("TDT_ROUTER_DEADLINE_S",
                                      DEFAULT_DEADLINE_S))
        self.try_timeout_s = (
            try_timeout_s if try_timeout_s is not None else
            _env_float("TDT_ROUTER_TRY_TIMEOUT_S",
                       DEFAULT_TRY_TIMEOUT_S))
        self.poll_s = (poll_s if poll_s is not None else
                       _env_float("TDT_ROUTER_POLL_S", DEFAULT_POLL_S))
        self.storm = obs.env_int("TDT_ROUTER_STORM", DEFAULT_STORM)
        self._breaker_threshold = breaker_threshold
        self._breaker_cooldown_s = breaker_cooldown_s
        self.fleet = (fleet if fleet is not None
                      else FleetView(endpoints, **(fleet_kwargs or {})))
        # Two-tier placement config (ISSUE 18): static endpoint→tier
        # map (ctor > TDT_ROUTER_TIERS), refined live by each
        # replica's health-advertised tier and by router_retier.
        self._tiers = ({parse_endpoint(k): str(v)
                        for k, v in tiers.items()}
                       if tiers is not None else parse_tiers(
                           os.environ.get("TDT_ROUTER_TIERS", "")))
        self._retiered: set = set()     # router_retier overrides
        self._lock = threading.Lock()   # replica dict + inflight
        self._replicas: dict = {}
        for ep in self.fleet.endpoints:
            self._replicas[ep] = self._make_replica(ep)
        self._failover_times: collections.deque = collections.deque()
        self._health_seq = itertools.count(1)
        self._started_monotonic = time.monotonic()
        self._stop = threading.Event()
        self._srv = _TCPServer((host, port), _Handler)
        try:
            self._srv.model_server = self   # duck-typed for _Handler
            self.host, self.port = self._srv.server_address
        except BaseException:
            self._srv.server_close()
            raise
        self._thread: threading.Thread | None = None
        self._poll_thread: threading.Thread | None = None
        # One synchronous poll so placement works from request one
        # (an unpolled FleetView scores every replica -inf).
        with obs.scoped_registry(self.registry):
            self._poll_once()

    # -- replica bookkeeping ----------------------------------------------
    def _make_replica(self, ep) -> _Replica:
        # Private breaker instances (not the global per-op registry):
        # the router's breakers are per-ENDPOINT infra state, reset
        # with the router, and their gauges still emit through the
        # shared resilience.<op>.* names for dashboards. Construction
        # emits the initial state gauge, so it must run under the
        # router's registry scope like every later state change — an
        # unscoped ctor would write resilience.* gauges into the
        # process-global registry an in-process sibling replica
        # scrapes (review finding).
        with obs.scoped_registry(self.registry):
            return _Replica(ep, CircuitBreaker(
                f"replica.{ep[0]}:{ep[1]}",
                threshold=self._breaker_threshold,
                cooldown_s=self._breaker_cooldown_s),
                tier=self._tiers.get(ep, "unified"))

    def add_replica(self, endpoint) -> dict:
        """Attach a replica live: it joins the fleet view now and
        placement as soon as a health poll sees it (one runs
        immediately)."""
        ep = self.fleet.add_endpoint(endpoint)
        with self._lock:
            self._replicas[ep] = self._make_replica(ep)
        self._poll_once()
        obs.counter("router.replicas_added").inc()
        return {"added": f"{ep[0]}:{ep[1]}",
                "replicas": len(self._replicas)}

    def remove_replica(self, endpoint, drain: bool = True,
                       wait_s: float | None = None,
                       replica_drain: bool = False) -> dict:
        """Detach a replica — gracefully by default: stop placing
        (router-side draining flag), wait up to ``wait_s`` (default
        10 s) for this router's in-flight dispatches to it to finish,
        then drop it from placement and the fleet view.
        ``replica_drain=True`` additionally sends the replica itself
        the ``drain`` verb first (it stops admitting from EVERY
        client, not just this router)."""
        ep = parse_endpoint(endpoint)
        with self._lock:
            st = self._replicas.get(ep)
        if st is None:
            return {"error": f"unknown replica {endpoint!r}"}
        st.draining = True
        self._publish_draining()
        if replica_drain:
            try:
                self._dispatch(ep, {"cmd": "drain"},
                               self.try_timeout_s)
            except Exception:  # noqa: BLE001 — replica may be dead
                pass
        drained = True
        if drain:
            deadline = time.monotonic() + (10.0 if wait_s is None
                                           else float(wait_s))
            while st.inflight > 0 and time.monotonic() < deadline:
                time.sleep(0.02)
            drained = st.inflight == 0
        with self._lock:
            self._replicas.pop(ep, None)
        try:
            self.fleet.remove_endpoint(ep)
        except ValueError:
            pass
        self._publish_draining()
        obs.counter("router.replicas_removed").inc()
        return {"removed": st.label, "drained": drained,
                "inflight": st.inflight}

    def retier(self, endpoint, tier: str,
               wait_s: float | None = None) -> dict:
        """Live re-specialization (ISSUE 18, ``router_retier``):
        drain the replica ROUTER-SIDE (stop placing, wait up to
        ``wait_s`` — default 10 s — for this router's in-flight
        dispatches to it to finish), flip its tier, undrain. The
        override outlives later health polls (a replica advertising
        its boot-time tier must not flap the operator's decision
        back)."""
        tier = str(tier).lower()
        if tier not in ("prefill", "decode", "unified"):
            obs.counter("router.errors").inc()
            return {"error": f"unknown tier {tier!r} (prefill / "
                             f"decode / unified)", "type": "ValueError"}
        ep = parse_endpoint(endpoint)
        with self._lock:
            st = self._replicas.get(ep)
        if st is None:
            obs.counter("router.errors").inc()
            return {"error": f"unknown replica {endpoint!r}"}
        st.draining = True
        self._publish_draining()
        deadline = time.monotonic() + (10.0 if wait_s is None
                                       else float(wait_s))
        while st.inflight > 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        drained = st.inflight == 0
        st.tier = tier
        self._tiers[ep] = tier
        self._retiered.add(ep)
        st.draining = False
        self._publish_draining()
        obs.counter("router.retiers").inc()
        trace.instant("router.retier", "serving",
                      args={"replica": st.label, "tier": tier})
        return {"retiered": st.label, "tier": tier,
                "drained": drained}

    def _publish_draining(self) -> None:
        with self._lock:
            n = sum(1 for st in self._replicas.values() if st.draining)
        obs.gauge("router.replicas_draining").set(n)

    # -- health polling ----------------------------------------------------
    def _poll_once(self) -> None:
        rows = self.fleet.poll()
        for r in rows:
            ep = parse_endpoint(r["endpoint"])
            with self._lock:
                st = self._replicas.get(ep)
            if st is None:
                continue
            adv = (r["health"] or {}).get("tier")
            if adv and ep not in self._retiered:
                # Health-advertised tier (ModelServer tier=...) wins
                # over the static TDT_ROUTER_TIERS map — the replica
                # knows its own role. A live router_retier is the one
                # exception: the operator's re-specialization must not
                # flap back on the next poll.
                st.tier = str(adv)
            prev, st.last_status = st.last_status, r["status"]
            if r["status"] == "down" and prev not in (None, "down"):
                # A replica just went dark: leave the postmortem NOW,
                # while the ring still holds its last requests'
                # events (rate-limited; no-op when tracing is off).
                obs.counter("router.replicas_down_seen").inc()
                trace.instant("router.replica_down", "resilience",
                              args={"replica": st.label,
                                    "age_s": r["age_s"]})
                flight.maybe_dump("replica_down")

    def _poll_loop(self) -> None:
        with obs.scoped_registry(self.registry):
            while not self._stop.wait(self.poll_s):
                try:
                    self._poll_once()
                except Exception:  # noqa: BLE001 — polling must survive
                    obs.counter("router.poll_errors").inc()

    # -- placement ---------------------------------------------------------
    def _candidates(self, excluded: set) -> list:
        """Placeable replicas best-first: attached, not draining
        (router-side or health-advertised), not ``down``, not already
        tried/saturated for this request. Breaker gating happens at
        selection time (``_place``) because ``allow()`` consumes the
        half-open probe slot."""
        out = []
        for r in self.fleet.replicas():
            ep = parse_endpoint(r["endpoint"])
            if ep in excluded or r["status"] == "down":
                continue
            with self._lock:
                st = self._replicas.get(ep)
            if st is None or st.draining:
                continue
            if (r["health"] or {}).get("draining"):
                continue
            score = r["score"]
            score = float("-inf") if score is None else score
            out.append((score - INFLIGHT_WEIGHT * st.inflight,
                        ep, st))
        out.sort(key=lambda t: -t[0])
        return [(ep, st) for _, ep, st in out]

    def _place(self, excluded: set):
        """The best placeable replica whose breaker admits a call
        right now (an open breaker's replica is skipped until its
        cooldown admits the single half-open probe — which this
        dispatch then IS)."""
        for ep, st in self._candidates(excluded):
            if st.breaker.allow():
                return ep, st
        return None, None

    def _tier_pools(self):
        """Two-tier placement pools (ISSUE 18): placeable prefill
        replicas ranked by TTFT burn and decode replicas by TPOT burn
        — each tier is scored by the SLO its phase owns, lower burn
        first, the router's live in-flight count as the tiebreak
        (same real-time term as ``_candidates``)."""
        prefill, decode = [], []
        for r in self.fleet.replicas():
            ep = parse_endpoint(r["endpoint"])
            if r["status"] == "down":
                continue
            with self._lock:
                st = self._replicas.get(ep)
            if st is None or st.draining:
                continue
            h = r["health"] or {}
            if h.get("draining"):
                continue
            slo = h.get("slo") or {}

            def burn(name):
                return float((slo.get(name) or {}).get("burn") or 0.0)

            if st.tier == "prefill":
                prefill.append((burn("ttft")
                                + INFLIGHT_WEIGHT * st.inflight,
                                ep, st))
            elif st.tier == "decode":
                decode.append((burn("tpot")
                               + INFLIGHT_WEIGHT * st.inflight,
                               ep, st))
        prefill.sort(key=lambda t: t[0])
        decode.sort(key=lambda t: t[0])
        return ([(ep, st) for _, ep, st in prefill],
                [(ep, st) for _, ep, st in decode])

    def _try_disagg(self, req: dict, payload: dict,
                    deadline: float):
        """Disaggregated dispatch, preference-with-fallback: when the
        fleet has BOTH a prefill and a decode pool, a single-prompt
        generation goes to the best prefill replica as a
        ``disagg_prefill`` verb naming the best decode replica; ANY
        failure (shed, transport death, replica-fault reply) returns
        ``None`` and the caller's ordinary placement loop serves the
        request unified — the handoff is an optimization, never a new
        way to fail a client."""
        prompts = req.get("prompt_ids") or []
        if len(prompts) != 1:
            # The handoff verb moves one row's KV chain; batched
            # requests keep the unified path.
            return None
        prefill, decode = self._tier_pools()
        if not prefill or not decode:
            return None
        d_ep, d_st = decode[0]
        for p_ep, p_st in prefill:
            if not p_st.breaker.allow():
                continue
            budget = deadline - time.perf_counter()
            if budget <= 0:
                return None
            body = dict(payload)
            body.update({"cmd": "disagg_prefill",
                         "prompt_ids": list(prompts[0]),
                         "decode_endpoint": d_st.label})
            obs.counter(f"router.placements.{p_st.label}").inc()
            with self._lock:
                p_st.inflight += 1
                d_st.inflight += 1
            try:
                resp = self._dispatch(p_ep, body,
                                      min(self.try_timeout_s, budget))
            except (OSError, ValueError):
                p_st.breaker.record_failure()
                obs.counter("router.disagg_errors").inc()
                return None
            finally:
                with self._lock:
                    p_st.inflight -= 1
                    d_st.inflight -= 1
            err = (resp.get("error")
                   if isinstance(resp, dict) else "torn reply")
            if isinstance(resp, dict) \
                    and resp.get("type") in _SHED_TYPES:
                p_st.breaker.record_success()
                return None
            if err is None or (isinstance(resp, dict) and
                               resp.get("type") in _CLIENT_FAULT_TYPES):
                p_st.breaker.record_success()
                obs.counter("router.disagg_dispatches").inc()
                if isinstance(resp, dict):
                    resp.setdefault("replica", p_st.label)
                    resp.setdefault("disagg_route",
                                    {"prefill": p_st.label,
                                     "decode": d_st.label})
                return resp
            p_st.breaker.record_failure()
            obs.counter("router.disagg_errors").inc()
            return None
        return None

    # -- dispatch ----------------------------------------------------------
    def _dispatch(self, ep, payload: dict, timeout: float) -> dict:
        """One fresh-connection round trip to a replica. Raises
        ``OSError``/``TimeoutError``/``ValueError`` on transport or
        framing failure — the failure classes the breaker counts
        (``serving.client.request_once`` is the one home for the
        wire framing)."""
        from triton_dist_tpu.serving.client import request_once
        return request_once(ep, payload, timeout=timeout)

    def _fleet_retry_after_ms(self) -> int:
        """The fleet-level backpressure hint: the SOONEST replica's
        rolling-TPOT × queue-depth estimate (the client should retry
        when the least-loaded replica is likely to have a free slot),
        through the same clamped formula the single-server reply uses
        (``serving.scheduler.retry_after_ms_hint``)."""
        hints = []
        for r in self.fleet.replicas():
            h = r["health"]
            if r["status"] == "down" or not h:
                continue
            hints.append(retry_after_ms_hint(
                (h.get("rolling") or {}).get("tpot_p50_ms"),
                h.get("queue_depth")))
        return min(hints) if hints else retry_after_ms_hint(None, 0)

    def _note_failover(self) -> None:
        obs.counter("router.failovers").inc()
        now = time.monotonic()
        self._failover_times.append(now)
        while self._failover_times and \
                now - self._failover_times[0] > STORM_WINDOW_S:
            self._failover_times.popleft()
        if len(self._failover_times) >= self.storm:
            # A failover STORM means the fleet is churning (several
            # replicas failing, or one flapping fast): dump the
            # window while it still shows the churn (rate-limited).
            obs.counter("router.failover_storms").inc()
            flight.maybe_dump("failover_storm")

    def _serve_generate(self, req: dict) -> dict:
        obs.counter("router.requests").inc()
        obs.gauge("router.inflight").inc()
        t0 = time.perf_counter()
        try:
            resp = self._serve_generate_placed(req, t0)
        finally:
            obs.gauge("router.inflight").dec()
        obs.histogram("router.request_ms").observe(
            (time.perf_counter() - t0) * 1e3)
        return resp

    def _serve_generate_placed(self, req: dict, t0: float) -> dict:
        trace_id = str(req.get("trace_id") or trace.new_trace_id())
        payload = dict(req)
        # One trace ID across EVERY dispatch attempt: the failed
        # replica's admission events, the router's failover instant,
        # and the answering replica's retire all tell one story.
        payload["trace_id"] = trace_id
        deadline = t0 + self.deadline_s
        failed = 0                     # failed dispatch attempts
        excluded: set = set()          # endpoints tried this request
        saturated = False              # saw >= 1 shed reply
        last_err = None
        cleared_at = -1                # last `failed` a re-round ran at
        with trace.bind(trace_id), \
                trace.span("router.request", "serving",
                           args={"gen_len": req.get("gen_len"),
                                 "batch": len(req.get("prompt_ids")
                                              or [])}):
            resp = self._try_disagg(req, payload, deadline)
            if resp is not None:
                resp.setdefault("trace_id", trace_id)
                return resp
            while True:
                ep, st = self._place(excluded)
                if ep is None and excluded and failed \
                        and failed != cleared_at \
                        and failed <= self.retries \
                        and time.perf_counter() < deadline:
                    # Every candidate was consumed by THIS request's
                    # failures/sheds but retry budget remains: one
                    # more round (covers the single-replica transient
                    # blip — with nothing else to fail over to, the
                    # bounded retry goes back to the same replica
                    # after the backoff). At most one re-round per
                    # FAILURE — shed replies alone never re-round,
                    # they answer with retry_after_ms instead.
                    cleared_at = failed
                    excluded = set()
                    ep, st = self._place(excluded)
                if ep is None:
                    return self._shed_reply(saturated, failed,
                                            last_err, trace_id)
                budget = deadline - time.perf_counter()
                if budget <= 0:
                    obs.counter("router.deadline_exhausted").inc()
                    return self._shed_reply(saturated, failed,
                                            last_err, trace_id)
                timeout = min(self.try_timeout_s, budget)
                obs.counter(f"router.placements.{st.label}").inc()
                with self._lock:
                    st.inflight += 1
                try:
                    resp = self._dispatch(ep, payload, timeout)
                except (OSError, ValueError) as e:
                    # Transport death: refused/reset/timeout/garbage.
                    failure, resp = e, None
                finally:
                    with self._lock:
                        st.inflight -= 1
                if resp is not None:
                    err = resp.get("error") if isinstance(resp, dict) \
                        else None
                    if isinstance(resp, dict) \
                            and resp.get("type") in _SHED_TYPES:
                        # The replica answered "busy/leaving" — alive
                        # (close a half-open probe), just not
                        # placeable for THIS request.
                        st.breaker.record_success()
                        excluded.add(ep)
                        saturated = True
                        obs.counter("router.replica_sheds").inc()
                        continue
                    if err is None or resp.get("type") \
                            in _CLIENT_FAULT_TYPES:
                        # Success — or the REQUEST's own error
                        # (malformed prompt: replaying it elsewhere
                        # fails identically): passthrough unchanged,
                        # the replica did its job. Any other error
                        # reply is a replica fault and takes the
                        # failover path below — a replica whose
                        # engine is broken must open its breaker and
                        # lose placements, not keep erroring at
                        # clients while healthy siblings idle.
                        st.breaker.record_success()
                        if failed:
                            resp["failovers"] = failed
                        resp.setdefault("trace_id", trace_id)
                        resp.setdefault("replica", st.label)
                        return resp
                    failure = RuntimeError(
                        f"{resp.get('type')}: {err}")
                # A replica failure: count it, open the breaker path,
                # back off, re-dispatch elsewhere (the prompt is right
                # here — generation requests are re-issuable).
                last_err = failure
                failed += 1
                excluded.add(ep)
                st.breaker.record_failure()
                obs.counter("router.dispatch_errors").inc()
                trace.instant("router.failover", "resilience",
                              args={"replica": st.label,
                                    "attempt": failed,
                                    "error": str(failure)[:120]})
                if failed > self.retries:
                    obs.counter("router.retries_exhausted").inc()
                    return self._shed_reply(saturated, failed,
                                            last_err, trace_id)
                self._note_failover()
                backoff = (self.backoff_ms / 1e3) * (2 ** (failed - 1))
                backoff = min(backoff,
                              max(deadline - time.perf_counter(), 0.0))
                if backoff > 0:
                    time.sleep(backoff)

    def _shed_reply(self, saturated: bool, failed: int, last_err,
                    trace_id: str) -> dict:
        hint = self._fleet_retry_after_ms()
        if saturated and last_err is None:
            # Every placeable replica answered queue_full/draining:
            # the fleet is SATURATED, not broken — same structured
            # shape as a single server's shed, scoped to the fleet.
            obs.counter("router.shed").inc()
            trace.instant("router.shed", "serving",
                          args={"retry_after_ms": hint})
            return {"error": "every replica is saturated — retry "
                             "later", "type": "queue_full",
                    "scope": "fleet", "retry_after_ms": hint,
                    "trace_id": trace_id}
        obs.counter("router.no_replicas").inc()
        resp = {"error": "no healthy replica could serve the request"
                         + (f" (last failure: {last_err})"
                            if last_err else ""),
                "type": "no_healthy_replicas",
                "retry_after_ms": hint, "trace_id": trace_id}
        if failed:
            resp["failovers"] = failed
        return resp

    # -- protocol ----------------------------------------------------------
    def _serve_request(self, req: dict) -> dict:
        with obs.scoped_registry(self.registry):
            return self._serve_request_scoped(req)

    def _serve_request_scoped(self, req: dict) -> dict:
        if "cmd" in req:
            return self._serve_command(req)
        if "prompt_ids" not in req:
            obs.counter("router.errors").inc()
            return {"error": "request needs prompt_ids or cmd",
                    "type": "ValueError"}
        return self._serve_generate(req)

    def status(self) -> dict:
        """The ``router_status`` payload: per-replica placement rows —
        fleet status/age/score joined with the router's OWN dimension
        (breaker state, in-flight dispatches, draining flag) — plus
        the router counters a postmortem reads first."""
        rows = []
        for r in self.fleet.replicas():
            ep = parse_endpoint(r["endpoint"])
            with self._lock:
                st = self._replicas.get(ep)
            if st is None:
                continue
            rows.append({
                "endpoint": r["endpoint"],
                "replica_id": r["replica_id"],
                "status": r["status"],
                "age_s": r["age_s"],
                "score": r["score"],
                "tier": st.tier,
                "breaker": st.breaker.state,
                "inflight": st.inflight,
                "draining": bool(
                    st.draining
                    or (r["health"] or {}).get("draining")),
            })
        from triton_dist_tpu.obs.fleet import peek_counters
        c = peek_counters(self.registry or obs.get_registry())
        counters = {k: v for k, v in c.items()
                    if k.startswith("router.")
                    and not k.startswith("router.placements.")}
        placements = {k[len("router.placements."):]: v
                      for k, v in c.items()
                      if k.startswith("router.placements.")}
        return {"replicas": rows, "counters": counters,
                "placements": placements,
                "uptime_s": round(
                    time.monotonic() - self._started_monotonic, 3)}

    def _serve_command(self, req: dict) -> dict:
        cmd = req["cmd"]
        if cmd == "router_status":
            return {"router": self.status()}
        if cmd == "router_add":
            if "endpoint" not in req:
                obs.counter("router.errors").inc()
                return {"error": "router_add needs endpoint"}
            try:
                return self.add_replica(req["endpoint"])
            except ValueError as e:
                obs.counter("router.errors").inc()
                return {"error": str(e), "type": "ValueError"}
        if cmd == "router_remove":
            if "endpoint" not in req:
                obs.counter("router.errors").inc()
                return {"error": "router_remove needs endpoint"}
            wait_s = req.get("wait_s")
            return self.remove_replica(
                req["endpoint"], drain=bool(req.get("drain", True)),
                wait_s=float(wait_s) if wait_s is not None else None,
                replica_drain=bool(req.get("replica_drain")))
        if cmd == "router_retier":
            if "endpoint" not in req or "tier" not in req:
                obs.counter("router.errors").inc()
                return {"error": "router_retier needs endpoint and "
                                 "tier"}
            wait_s = req.get("wait_s")
            try:
                return self.retier(
                    req["endpoint"], req["tier"],
                    wait_s=float(wait_s) if wait_s is not None
                    else None)
            except ValueError as e:
                obs.counter("router.errors").inc()
                return {"error": str(e), "type": "ValueError"}
        if cmd == "health":
            # The router's OWN health (a router is not a replica —
            # point FleetView at the replicas, or use router_status,
            # for theirs): enough for a watchdog to gate on.
            seq = next(self._health_seq)
            rows = self.fleet.replicas()
            return {"health": {
                "router": True,
                "replica_id": f"router@{self.host}:{self.port}",
                "seq": seq,
                "uptime_s": round(
                    time.monotonic() - self._started_monotonic, 3),
                "replicas": {
                    st: sum(1 for r in rows if r["status"] == st)
                    for st in ("live", "stale", "down")},
            }}
        if cmd == "metrics":
            snap = obs.snapshot()
            snap["replica_id"] = f"router@{self.host}:{self.port}"
            snap["router"] = self.status()
            if trace.enabled():
                snap["trace"] = trace.stats()
            resp = {"metrics": snap}
            if req.get("format") == "prometheus":
                resp["prometheus"] = obs.render_prometheus(snap)
            return resp
        if cmd == "dump_trace":
            if not trace.enabled():
                obs.counter("router.errors").inc()
                return {"error": "tracing is disabled (TDT_TRACE)"}
            path = flight.dump("cmd", last_s=req.get("seconds"))
            return {"dumped": path, "trace": trace.stats()}
        obs.counter("router.errors").inc()
        return {"error": f"unknown cmd {cmd!r} (known: router_status, "
                         f"router_add, router_remove, router_retier, "
                         f"health, metrics, dump_trace)"}

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "RouterServer":
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        self._poll_thread = threading.Thread(target=self._poll_loop,
                                             name="tdt-router-poll",
                                             daemon=True)
        self._poll_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._srv.shutdown()
        self._srv.server_close()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=5.0)
            self._poll_thread = None


def main():  # pragma: no cover - manual entry
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--endpoints", required=True,
                    help="comma-separated host:port replica list")
    ap.add_argument("--port", type=int, default=8700)
    args = ap.parse_args()
    eps = [e.strip() for e in args.endpoints.split(",") if e.strip()]
    srv = RouterServer(eps, port=args.port).start()
    print(f"routing {len(eps)} replica(s) on {srv.host}:{srv.port}")
    threading.Event().wait()


if __name__ == "__main__":  # pragma: no cover
    main()
