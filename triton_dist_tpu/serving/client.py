"""Chat client for the ModelServer (reference chat.py,
mega_triton_kernel/test/models/chat.py). Token-id protocol; plugs a HF
tokenizer in when available for text chat.

``timeout=`` (constructor or per call) bounds every protocol round
trip — a wedged server raises ``TimeoutError`` instead of blocking the
client forever. :func:`fanout` is the small concurrent-client helper
the serving bench and the scheduler load tests drive their traffic
through: one connection + thread per request, responses in request
order.

Multi-endpoint mode (ISSUE 14): ``ChatClient(endpoints=[...])`` holds
one lazy connection per replica and round-robins generation/control
requests across them (per-endpoint timeouts — the same ``timeout=``
machinery, applied per connection); ``health()`` speaks the server's
cheap ``{"cmd": "health"}`` verb; ``fanout(endpoints=[...])``
round-robins a request list across replicas — the client-side fanout
behind ``bench.py``'s ``serving_fleet`` part and
``obs.fleet.FleetView``'s concurrent scrapes.

Fault awareness (ISSUE 15): multi-endpoint round-robin skips
endpoints whose last round trip died at the socket level and retries
the failed request once on the next endpoint (``fanout`` does the
same per slot, sharing one dead-set per call), so a replica death
costs a failover, not a client-visible error; and a ``queue_full`` /
``draining`` reply's ``retry_after_ms`` hint earns one
sleep-and-retry when the timeout budget allows
(``retry_shed=False`` opts out). For health-gated placement and
deadline-budgeted re-dispatch, front the fleet with
``serving.router.RouterServer`` instead — these client-side paths
are the router-less fallback.
"""

from __future__ import annotations

import json
import socket
import threading
import time

#: Sentinel distinguishing "no per-call timeout given" from an explicit
#: ``timeout=None`` (= block forever).
_UNSET = object()


def _parse_endpoint(ep) -> tuple:
    """``(host, port)`` from ``"host:port"`` / ``(host, port)`` —
    one parser for every multi-endpoint surface (the fleet view's
    ``obs.fleet.parse_endpoint``; obs never imports serving, so no
    cycle)."""
    from triton_dist_tpu.obs.fleet import parse_endpoint
    return parse_endpoint(ep)


class ChatClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 8777,
                 tokenizer=None, timeout: float | None = None,
                 endpoints=None, retry_shed: bool = True):
        """``timeout``: seconds each protocol round trip may take
        (connect included) before ``TimeoutError``; ``None`` blocks
        indefinitely (the historical behavior). ``endpoints``: a list
        of ``"host:port"`` / ``(host, port)`` replicas — requests
        round-robin across them over one lazy persistent connection
        each (``host``/``port`` are ignored then); the single-endpoint
        form keeps its eager connect, so a refused connection still
        fails at construction. Endpoints whose last round trip died at
        the socket level are SKIPPED by the round-robin (and the
        failed request retried ONCE on the next endpoint) until a
        later success clears them — a dead replica degrades to one
        client-side retry, never a per-request error (ISSUE 15).
        ``retry_shed``: honor a ``queue_full`` / ``draining`` reply's
        ``retry_after_ms`` hint on generation requests — sleep that
        long and retry once when the timeout budget allows
        (``False`` returns the raw shed reply)."""
        self.tokenizer = tokenizer
        self.timeout = timeout
        self.retry_shed = retry_shed
        if endpoints:
            self.endpoints = [_parse_endpoint(e) for e in endpoints]
        else:
            self.endpoints = [(host, int(port))]
        self.addr = self.endpoints[0]
        self._conns: dict = {}          # endpoint -> (sock, file)
        self._rr = 0
        self._lock = threading.Lock()   # rr index + conn/lock creation
        self._ep_locks: dict = {}       # endpoint -> round-trip lock
        self._bad: set = set()          # endpoints whose last try died
        if not endpoints:
            self._conn(self.endpoints[0])   # eager: historical contract

    def _conn(self, ep, connect_timeout=_UNSET):
        """The endpoint's persistent connection, created lazily. The
        blocking connect runs OUTSIDE the client-wide lock (the lock
        only publishes the result) — a wedged replica must not stall
        requests to the healthy ones — and honors the caller's
        per-call timeout: in multi-endpoint mode first contact with a
        replica happens inside request(), so the override has to
        cover the connect, not just the round trip."""
        with self._lock:
            c = self._conns.get(ep)
        if c is not None:
            return c
        to = self.timeout if connect_timeout is _UNSET else connect_timeout
        s = socket.create_connection(ep, timeout=to)
        s.settimeout(self.timeout)
        with self._lock:
            raced = self._conns.get(ep)
            if raced is not None:
                c = raced            # another thread won; drop ours
            else:
                c = self._conns[ep] = (s, s.makefile("rwb"))
        if c[0] is not s:
            try:
                s.close()
            except OSError:
                pass
        return c

    def _ep_lock(self, ep):
        with self._lock:
            lk = self._ep_locks.get(ep)
            if lk is None:
                lk = self._ep_locks[ep] = threading.Lock()
        return lk

    def _next_endpoint(self) -> tuple:
        """Round-robin, skipping endpoints whose last round trip died
        at the socket level (all-bad falls back to plain round-robin —
        somebody has to probe them back to life)."""
        with self._lock:
            n = len(self.endpoints)
            for _ in range(n):
                ep = self.endpoints[self._rr % n]
                self._rr += 1
                if ep not in self._bad:
                    return ep
            ep = self.endpoints[self._rr % n]
            self._rr += 1
        return ep

    def _mark_bad(self, ep) -> None:
        """Remember a socket-level failure and drop the endpoint's
        (now protocol-undefined) cached connection."""
        with self._lock:
            self._bad.add(ep)
            conn = self._conns.pop(ep, None)
        if conn is not None:
            for c in conn[::-1]:
                try:
                    c.close()
                except OSError:
                    pass

    def _roundtrip(self, ep, req: dict, timeout=_UNSET) -> dict:
        with self._ep_lock(ep):
            sock, file = self._conn(ep, connect_timeout=timeout)
            if timeout is not _UNSET:
                sock.settimeout(timeout)
            try:
                file.write((json.dumps(req) + "\n").encode())
                file.flush()
                line = file.readline()
            finally:
                if timeout is not _UNSET:
                    sock.settimeout(self.timeout)
        if not line:
            raise ConnectionError("server closed the connection")
        return json.loads(line)

    def request(self, req: dict, timeout=_UNSET, endpoint=None) -> dict:
        """One protocol round trip with an arbitrary request object
        (generation or control-plane, e.g. ``{"cmd": "metrics"}``).
        ``timeout`` overrides the client default for this call only
        (``socket.timeout`` is a ``TimeoutError``; that endpoint's
        connection is left in an undefined protocol state after one —
        reconnect). ``endpoint`` pins the replica; otherwise
        multi-endpoint clients round-robin, skip endpoints whose last
        round trip died, and retry a socket-level failure ONCE on the
        next endpoint (so a replica death surfaces as a failover, not
        a client error — ISSUE 15); a pinned or single-endpoint call
        keeps the historical raise. Thread-safe: each endpoint's
        write→read round trip runs under a per-endpoint lock, so
        concurrent callers sharing one client serialize per connection
        instead of interleaving protocol bytes (use :func:`fanout`
        for genuinely concurrent traffic — one fresh connection per
        request)."""
        pinned = endpoint is not None
        ep = _parse_endpoint(endpoint) if pinned else None
        resp = self._request_failover(ep, req, timeout, pinned)
        # Shed backpressure with a hint (docs/serving.md): a
        # queue_full / draining reply carrying retry_after_ms earns
        # ONE sleep-and-retry on a generation request — when the
        # timeout budget covers the sleep — instead of bouncing the
        # shed straight back to a caller who will immediately hammer.
        if (self.retry_shed and "prompt_ids" in req
                and isinstance(resp, dict)
                and resp.get("type") in ("queue_full", "draining")
                and resp.get("retry_after_ms")):
            delay_s = float(resp["retry_after_ms"]) / 1e3
            budget = self.timeout if timeout is _UNSET else timeout
            if budget is None or delay_s < float(budget):
                time.sleep(delay_s)
                # Same failover contract as the first attempt: an
                # endpoint dying DURING the backpressure sleep must
                # cost the one retry, not a raw socket error.
                resp = self._request_failover(ep, req, timeout,
                                              pinned)
        return resp

    def _request_failover(self, ep, req: dict, timeout,
                          pinned: bool) -> dict:
        """One round trip with the dead-endpoint contract: a failure
        at the socket OR framing level (``OSError``; ``ValueError``
        covers a torn/garbled reply line from a connection severed
        mid-write — the same classes the router's dispatch counts)
        marks the endpoint bad and retries ONCE on the next endpoint;
        pinned/single-endpoint calls keep the historical raise."""
        if ep is None:
            ep = self._next_endpoint()
        try:
            resp = self._roundtrip(ep, req, timeout)
        except (OSError, ValueError):
            self._mark_bad(ep)
            if pinned or len(self.endpoints) < 2:
                raise
            nxt = self._next_endpoint()
            if nxt == ep:
                raise
            resp = self._roundtrip(nxt, req, timeout)  # single retry
            ep = nxt
        with self._lock:
            self._bad.discard(ep)
        return resp

    def generate_ids(self, prompt_ids, gen_len: int = 16,
                     trace_id: str | None = None,
                     timeout=_UNSET) -> dict:
        """Generate; with tracing on server-side the response carries
        ``trace_id`` (yours if given) for cross-referencing a later
        flight record (docs/observability.md "Tracing"), and
        ``gen_len`` echoes the server's effective (possibly clamped)
        value."""
        req = {"prompt_ids": prompt_ids, "gen_len": gen_len}
        if trace_id is not None:
            req["trace_id"] = trace_id
        return self.request(req, timeout=timeout)

    def dump_trace(self, seconds: float | None = None) -> dict:
        """Ask the server to dump its flight record
        (``{"cmd": "dump_trace"}``); returns the dump path + stats."""
        req: dict = {"cmd": "dump_trace"}
        if seconds is not None:
            req["seconds"] = seconds
        return self.request(req)

    def request_stats(self, last: int | None = None) -> list:
        """The newest ``last`` finished requests' latency-attribution
        waterfalls (``{"cmd": "request_stats"}`` — queue_wait →
        prefill → decode segments, prefix savings, per-token share;
        docs/observability.md "Request attribution"), newest first."""
        req: dict = {"cmd": "request_stats"}
        if last is not None:
            req["last"] = last
        return self.request(req).get("requests", [])

    def health(self, endpoint=None, timeout=_UNSET) -> dict:
        """One replica's compact ``ReplicaHealth`` snapshot via the
        cheap ``{"cmd": "health"}`` verb — lock-free server-side reads,
        no SLO force-evaluation (docs/observability.md "Fleet view").
        Round-robins like any request; pin a replica with
        ``endpoint=``. Raises ``RuntimeError`` on an error reply (an
        old server without the verb)."""
        resp = self.request({"cmd": "health"}, timeout=timeout,
                            endpoint=endpoint)
        if "health" not in resp:
            raise RuntimeError(resp.get("error", f"bad reply {resp!r}"))
        return resp["health"]

    def chat(self, text: str, gen_len: int = 64) -> str:
        assert self.tokenizer is not None, "text chat needs a tokenizer"
        ids = self.tokenizer(text, return_tensors="np")["input_ids"]
        resp = self.generate_ids(ids.tolist(), gen_len)
        if "error" in resp:
            raise RuntimeError(resp["error"])
        return self.tokenizer.decode(resp["tokens"][0])

    def close(self):
        with self._lock:
            conns, self._conns = list(self._conns.values()), {}
        for sock, file in conns:
            try:
                file.close()
                sock.close()
            except OSError:
                pass


def request_once(endpoint, req: dict,
                 timeout: float | None = None) -> dict:
    """One fresh-connection protocol round trip — the raw JSON-lines
    framing primitive, shared with ``RouterServer``'s dispatch
    attempts (serving/router.py) so the wire contract has ONE home.
    Raises ``OSError`` on transport failure (connect/timeout/reset),
    ``ConnectionError`` when the server closes without a reply line,
    and ``ValueError`` on a torn/garbled reply — the failure classes
    breakers and failover count. No retries, no endpoint skipping:
    callers that want the fault-aware behavior use
    :class:`ChatClient` / :func:`fanout`."""
    ep = _parse_endpoint(endpoint)
    with socket.create_connection(ep, timeout=timeout) as s:
        s.settimeout(timeout)
        with s.makefile("rwb") as f:
            f.write((json.dumps(req) + "\n").encode())
            f.flush()
            line = f.readline()
    if not line:
        raise ConnectionError("server closed the connection")
    return json.loads(line)


def fanout(host: str | None = None, port: int | None = None,
           requests: list | None = None,
           timeout: float | None = None, endpoints=None,
           retry_next: bool = True) -> list:
    """Issue ``requests`` (protocol dicts) CONCURRENTLY — one fresh
    connection and thread per request — and return the responses in
    request order. A request that fails client-side (timeout, refused
    connection) yields an ``{"error", "type"}`` dict in its slot, so
    the caller can count failures without unwinding the others. This
    is the concurrent-client helper behind bench.py's
    ``serving_throughput`` probe and the scheduler load tests.

    ``endpoints=[...]`` replaces ``host``/``port`` with a replica
    list: request ``i`` goes to ``endpoints[i % len(endpoints)]`` —
    the client-side round-robin the ``serving_fleet`` bench and
    ``obs.fleet.FleetView`` ride (per-request timeout, so one wedged
    replica cannot stall the other slots). A slot whose endpoint
    fails client-side is retried ONCE on the next endpoint that no
    sibling slot has seen die (ISSUE 15): a replica death mid-fanout
    costs one retry, and cannot be mis-attributed as a client
    failure; only a retry that ALSO fails records the error dict.
    ``retry_next=False`` pins slot ``i`` to ``endpoints[i % n]``
    exactly — what a health/metrics scrape needs: replica A's probe
    answered by replica B would corrupt per-replica records
    (``obs.fleet.FleetView`` passes it)."""
    if endpoints:
        eps = [_parse_endpoint(e) for e in endpoints]
    else:
        if host is None or port is None:
            raise ValueError("fanout needs host+port or endpoints=")
        eps = [(host, int(port))]
    if requests is None:
        raise ValueError("fanout needs requests")
    results: list = [None] * len(requests)
    dead: set = set()       # endpoints some slot saw die (GIL-safe)

    def one_shot(ep, payload: dict) -> dict:
        c = ChatClient(ep[0], ep[1], timeout=timeout)
        try:
            return c.request(payload)
        finally:
            c.close()

    def pick(start: int):
        """The first not-known-dead endpoint from ``start``; falls
        back to the start slot when every endpoint is dead."""
        n = len(eps)
        for j in range(n):
            ep = eps[(start + j) % n]
            if ep not in dead:
                return ep
        return eps[start % n]

    def worker(i: int, payload: dict) -> None:
        ep = pick(i) if retry_next else eps[i % len(eps)]
        try:
            results[i] = one_shot(ep, payload)
            return
        except Exception as e:  # noqa: BLE001 — per-slot isolation
            dead.add(ep)
            err = e
        if retry_next and len(eps) > 1:
            nxt = pick(i + 1)
            if nxt != ep:
                try:
                    results[i] = one_shot(nxt, payload)
                    return
                except Exception as e:  # noqa: BLE001
                    dead.add(nxt)
                    err = e
        results[i] = {"error": str(err) or repr(err),
                      "type": type(err).__name__}

    threads = [threading.Thread(target=worker, args=(i, r), daemon=True)
               for i, r in enumerate(requests)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results


def main():  # pragma: no cover - manual demo
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8777)
    ap.add_argument("--tokenizer-dir", default=None)
    ap.add_argument("--timeout", type=float, default=None)
    args = ap.parse_args()
    tok = None
    if args.tokenizer_dir:
        from transformers import AutoTokenizer
        tok = AutoTokenizer.from_pretrained(args.tokenizer_dir)
    client = ChatClient(args.host, args.port, tok, timeout=args.timeout)
    try:
        while True:
            text = input("you> ")
            if tok:
                print("model>", client.chat(text))
            else:
                ids = [[int(t) for t in text.split()]]
                print("model>", client.generate_ids(ids))
    except (EOFError, KeyboardInterrupt):
        client.close()


if __name__ == "__main__":  # pragma: no cover
    main()
