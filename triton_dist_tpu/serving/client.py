"""Chat client for the ModelServer (reference chat.py,
mega_triton_kernel/test/models/chat.py). Token-id protocol; plugs a HF
tokenizer in when available for text chat."""

from __future__ import annotations

import json
import socket


class ChatClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 8777,
                 tokenizer=None):
        self.addr = (host, port)
        self.tokenizer = tokenizer
        self._sock = socket.create_connection(self.addr)
        self._file = self._sock.makefile("rwb")

    def request(self, req: dict) -> dict:
        """One protocol round trip with an arbitrary request object
        (generation or control-plane, e.g. ``{"cmd": "metrics"}``)."""
        self._file.write((json.dumps(req) + "\n").encode())
        self._file.flush()
        return json.loads(self._file.readline())

    def generate_ids(self, prompt_ids, gen_len: int = 16,
                     trace_id: str | None = None) -> dict:
        """Generate; with tracing on server-side the response carries
        ``trace_id`` (yours if given) for cross-referencing a later
        flight record (docs/observability.md "Tracing")."""
        req = {"prompt_ids": prompt_ids, "gen_len": gen_len}
        if trace_id is not None:
            req["trace_id"] = trace_id
        return self.request(req)

    def dump_trace(self, seconds: float | None = None) -> dict:
        """Ask the server to dump its flight record
        (``{"cmd": "dump_trace"}``); returns the dump path + stats."""
        req: dict = {"cmd": "dump_trace"}
        if seconds is not None:
            req["seconds"] = seconds
        return self.request(req)

    def chat(self, text: str, gen_len: int = 64) -> str:
        assert self.tokenizer is not None, "text chat needs a tokenizer"
        ids = self.tokenizer(text, return_tensors="np")["input_ids"]
        resp = self.generate_ids(ids.tolist(), gen_len)
        if "error" in resp:
            raise RuntimeError(resp["error"])
        return self.tokenizer.decode(resp["tokens"][0])

    def close(self):
        self._file.close()
        self._sock.close()


def main():  # pragma: no cover - manual demo
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8777)
    ap.add_argument("--tokenizer-dir", default=None)
    args = ap.parse_args()
    tok = None
    if args.tokenizer_dir:
        from transformers import AutoTokenizer
        tok = AutoTokenizer.from_pretrained(args.tokenizer_dir)
    client = ChatClient(args.host, args.port, tok)
    try:
        while True:
            text = input("you> ")
            if tok:
                print("model>", client.chat(text))
            else:
                ids = [[int(t) for t in text.split()]]
                print("model>", client.generate_ids(ids))
    except (EOFError, KeyboardInterrupt):
        client.close()


if __name__ == "__main__":  # pragma: no cover
    main()
