"""Chat client for the ModelServer (reference chat.py,
mega_triton_kernel/test/models/chat.py). Token-id protocol; plugs a HF
tokenizer in when available for text chat.

``timeout=`` (constructor or per call) bounds every protocol round
trip — a wedged server raises ``TimeoutError`` instead of blocking the
client forever. :func:`fanout` is the small concurrent-client helper
the serving bench and the scheduler load tests drive their traffic
through: one connection + thread per request, responses in request
order.

Multi-endpoint mode (ISSUE 14): ``ChatClient(endpoints=[...])`` holds
one lazy connection per replica and round-robins generation/control
requests across them (per-endpoint timeouts — the same ``timeout=``
machinery, applied per connection); ``health()`` speaks the server's
cheap ``{"cmd": "health"}`` verb; ``fanout(endpoints=[...])``
round-robins a request list across replicas — the client-side fanout
behind ``bench.py``'s ``serving_fleet`` part and
``obs.fleet.FleetView``'s concurrent scrapes.
"""

from __future__ import annotations

import json
import socket
import threading

#: Sentinel distinguishing "no per-call timeout given" from an explicit
#: ``timeout=None`` (= block forever).
_UNSET = object()


def _parse_endpoint(ep) -> tuple:
    """``(host, port)`` from ``"host:port"`` / ``(host, port)`` —
    one parser for every multi-endpoint surface (the fleet view's
    ``obs.fleet.parse_endpoint``; obs never imports serving, so no
    cycle)."""
    from triton_dist_tpu.obs.fleet import parse_endpoint
    return parse_endpoint(ep)


class ChatClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 8777,
                 tokenizer=None, timeout: float | None = None,
                 endpoints=None):
        """``timeout``: seconds each protocol round trip may take
        (connect included) before ``TimeoutError``; ``None`` blocks
        indefinitely (the historical behavior). ``endpoints``: a list
        of ``"host:port"`` / ``(host, port)`` replicas — requests
        round-robin across them over one lazy persistent connection
        each (``host``/``port`` are ignored then); the single-endpoint
        form keeps its eager connect, so a refused connection still
        fails at construction."""
        self.tokenizer = tokenizer
        self.timeout = timeout
        if endpoints:
            self.endpoints = [_parse_endpoint(e) for e in endpoints]
        else:
            self.endpoints = [(host, int(port))]
        self.addr = self.endpoints[0]
        self._conns: dict = {}          # endpoint -> (sock, file)
        self._rr = 0
        self._lock = threading.Lock()   # rr index + conn/lock creation
        self._ep_locks: dict = {}       # endpoint -> round-trip lock
        if not endpoints:
            self._conn(self.endpoints[0])   # eager: historical contract

    def _conn(self, ep, connect_timeout=_UNSET):
        """The endpoint's persistent connection, created lazily. The
        blocking connect runs OUTSIDE the client-wide lock (the lock
        only publishes the result) — a wedged replica must not stall
        requests to the healthy ones — and honors the caller's
        per-call timeout: in multi-endpoint mode first contact with a
        replica happens inside request(), so the override has to
        cover the connect, not just the round trip."""
        with self._lock:
            c = self._conns.get(ep)
        if c is not None:
            return c
        to = self.timeout if connect_timeout is _UNSET else connect_timeout
        s = socket.create_connection(ep, timeout=to)
        s.settimeout(self.timeout)
        with self._lock:
            raced = self._conns.get(ep)
            if raced is not None:
                c = raced            # another thread won; drop ours
            else:
                c = self._conns[ep] = (s, s.makefile("rwb"))
        if c[0] is not s:
            try:
                s.close()
            except OSError:
                pass
        return c

    def _ep_lock(self, ep):
        with self._lock:
            lk = self._ep_locks.get(ep)
            if lk is None:
                lk = self._ep_locks[ep] = threading.Lock()
        return lk

    def _next_endpoint(self) -> tuple:
        with self._lock:
            ep = self.endpoints[self._rr % len(self.endpoints)]
            self._rr += 1
        return ep

    def request(self, req: dict, timeout=_UNSET, endpoint=None) -> dict:
        """One protocol round trip with an arbitrary request object
        (generation or control-plane, e.g. ``{"cmd": "metrics"}``).
        ``timeout`` overrides the client default for this call only
        (``socket.timeout`` is a ``TimeoutError``; that endpoint's
        connection is left in an undefined protocol state after one —
        reconnect). ``endpoint`` pins the replica; otherwise
        multi-endpoint clients round-robin. Thread-safe: each
        endpoint's write→read round trip runs under a per-endpoint
        lock, so concurrent callers sharing one client serialize per
        connection instead of interleaving protocol bytes (use
        :func:`fanout` for genuinely concurrent traffic — one fresh
        connection per request)."""
        ep = (_parse_endpoint(endpoint) if endpoint is not None
              else self._next_endpoint())
        with self._ep_lock(ep):
            sock, file = self._conn(ep, connect_timeout=timeout)
            if timeout is not _UNSET:
                sock.settimeout(timeout)
            try:
                file.write((json.dumps(req) + "\n").encode())
                file.flush()
                line = file.readline()
            finally:
                if timeout is not _UNSET:
                    sock.settimeout(self.timeout)
        if not line:
            raise ConnectionError("server closed the connection")
        return json.loads(line)

    def generate_ids(self, prompt_ids, gen_len: int = 16,
                     trace_id: str | None = None,
                     timeout=_UNSET) -> dict:
        """Generate; with tracing on server-side the response carries
        ``trace_id`` (yours if given) for cross-referencing a later
        flight record (docs/observability.md "Tracing"), and
        ``gen_len`` echoes the server's effective (possibly clamped)
        value."""
        req = {"prompt_ids": prompt_ids, "gen_len": gen_len}
        if trace_id is not None:
            req["trace_id"] = trace_id
        return self.request(req, timeout=timeout)

    def dump_trace(self, seconds: float | None = None) -> dict:
        """Ask the server to dump its flight record
        (``{"cmd": "dump_trace"}``); returns the dump path + stats."""
        req: dict = {"cmd": "dump_trace"}
        if seconds is not None:
            req["seconds"] = seconds
        return self.request(req)

    def request_stats(self, last: int | None = None) -> list:
        """The newest ``last`` finished requests' latency-attribution
        waterfalls (``{"cmd": "request_stats"}`` — queue_wait →
        prefill → decode segments, prefix savings, per-token share;
        docs/observability.md "Request attribution"), newest first."""
        req: dict = {"cmd": "request_stats"}
        if last is not None:
            req["last"] = last
        return self.request(req).get("requests", [])

    def health(self, endpoint=None, timeout=_UNSET) -> dict:
        """One replica's compact ``ReplicaHealth`` snapshot via the
        cheap ``{"cmd": "health"}`` verb — lock-free server-side reads,
        no SLO force-evaluation (docs/observability.md "Fleet view").
        Round-robins like any request; pin a replica with
        ``endpoint=``. Raises ``RuntimeError`` on an error reply (an
        old server without the verb)."""
        resp = self.request({"cmd": "health"}, timeout=timeout,
                            endpoint=endpoint)
        if "health" not in resp:
            raise RuntimeError(resp.get("error", f"bad reply {resp!r}"))
        return resp["health"]

    def chat(self, text: str, gen_len: int = 64) -> str:
        assert self.tokenizer is not None, "text chat needs a tokenizer"
        ids = self.tokenizer(text, return_tensors="np")["input_ids"]
        resp = self.generate_ids(ids.tolist(), gen_len)
        if "error" in resp:
            raise RuntimeError(resp["error"])
        return self.tokenizer.decode(resp["tokens"][0])

    def close(self):
        with self._lock:
            conns, self._conns = list(self._conns.values()), {}
        for sock, file in conns:
            try:
                file.close()
                sock.close()
            except OSError:
                pass


def fanout(host: str | None = None, port: int | None = None,
           requests: list | None = None,
           timeout: float | None = None, endpoints=None) -> list:
    """Issue ``requests`` (protocol dicts) CONCURRENTLY — one fresh
    connection and thread per request — and return the responses in
    request order. A request that fails client-side (timeout, refused
    connection) yields an ``{"error", "type"}`` dict in its slot, so
    the caller can count failures without unwinding the others. This
    is the concurrent-client helper behind bench.py's
    ``serving_throughput`` probe and the scheduler load tests.

    ``endpoints=[...]`` replaces ``host``/``port`` with a replica
    list: request ``i`` goes to ``endpoints[i % len(endpoints)]`` —
    the client-side round-robin the ``serving_fleet`` bench and
    ``obs.fleet.FleetView`` ride (per-request timeout, so one wedged
    replica cannot stall the other slots)."""
    if endpoints:
        eps = [_parse_endpoint(e) for e in endpoints]
    else:
        if host is None or port is None:
            raise ValueError("fanout needs host+port or endpoints=")
        eps = [(host, int(port))]
    if requests is None:
        raise ValueError("fanout needs requests")
    results: list = [None] * len(requests)

    def worker(i: int, payload: dict) -> None:
        h, p = eps[i % len(eps)]
        try:
            c = ChatClient(h, p, timeout=timeout)
            try:
                results[i] = c.request(payload)
            finally:
                c.close()
        except Exception as e:  # noqa: BLE001 — per-slot isolation
            results[i] = {"error": str(e) or repr(e),
                          "type": type(e).__name__}

    threads = [threading.Thread(target=worker, args=(i, r), daemon=True)
               for i, r in enumerate(requests)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results


def main():  # pragma: no cover - manual demo
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8777)
    ap.add_argument("--tokenizer-dir", default=None)
    ap.add_argument("--timeout", type=float, default=None)
    args = ap.parse_args()
    tok = None
    if args.tokenizer_dir:
        from transformers import AutoTokenizer
        tok = AutoTokenizer.from_pretrained(args.tokenizer_dir)
    client = ChatClient(args.host, args.port, tok, timeout=args.timeout)
    try:
        while True:
            text = input("you> ")
            if tok:
                print("model>", client.chat(text))
            else:
                ids = [[int(t) for t in text.split()]]
                print("model>", client.generate_ids(ids))
    except (EOFError, KeyboardInterrupt):
        client.close()


if __name__ == "__main__":  # pragma: no cover
    main()
