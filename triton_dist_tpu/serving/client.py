"""Chat client for the ModelServer (reference chat.py,
mega_triton_kernel/test/models/chat.py). Token-id protocol; plugs a HF
tokenizer in when available for text chat.

``timeout=`` (constructor or per call) bounds every protocol round
trip — a wedged server raises ``TimeoutError`` instead of blocking the
client forever. :func:`fanout` is the small concurrent-client helper
the serving bench and the scheduler load tests drive their traffic
through: one connection + thread per request, responses in request
order.
"""

from __future__ import annotations

import json
import socket
import threading

#: Sentinel distinguishing "no per-call timeout given" from an explicit
#: ``timeout=None`` (= block forever).
_UNSET = object()


class ChatClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 8777,
                 tokenizer=None, timeout: float | None = None):
        """``timeout``: seconds each protocol round trip may take
        (connect included) before ``TimeoutError``; ``None`` blocks
        indefinitely (the historical behavior)."""
        self.addr = (host, port)
        self.tokenizer = tokenizer
        self.timeout = timeout
        self._sock = socket.create_connection(self.addr, timeout=timeout)
        self._file = self._sock.makefile("rwb")

    def request(self, req: dict, timeout=_UNSET) -> dict:
        """One protocol round trip with an arbitrary request object
        (generation or control-plane, e.g. ``{"cmd": "metrics"}``).
        ``timeout`` overrides the client default for this call only
        (``socket.timeout`` is a ``TimeoutError``; the connection is
        left in an undefined protocol state after one — reconnect)."""
        if timeout is not _UNSET:
            self._sock.settimeout(timeout)
        try:
            self._file.write((json.dumps(req) + "\n").encode())
            self._file.flush()
            line = self._file.readline()
        finally:
            if timeout is not _UNSET:
                self._sock.settimeout(self.timeout)
        if not line:
            raise ConnectionError("server closed the connection")
        return json.loads(line)

    def generate_ids(self, prompt_ids, gen_len: int = 16,
                     trace_id: str | None = None,
                     timeout=_UNSET) -> dict:
        """Generate; with tracing on server-side the response carries
        ``trace_id`` (yours if given) for cross-referencing a later
        flight record (docs/observability.md "Tracing"), and
        ``gen_len`` echoes the server's effective (possibly clamped)
        value."""
        req = {"prompt_ids": prompt_ids, "gen_len": gen_len}
        if trace_id is not None:
            req["trace_id"] = trace_id
        return self.request(req, timeout=timeout)

    def dump_trace(self, seconds: float | None = None) -> dict:
        """Ask the server to dump its flight record
        (``{"cmd": "dump_trace"}``); returns the dump path + stats."""
        req: dict = {"cmd": "dump_trace"}
        if seconds is not None:
            req["seconds"] = seconds
        return self.request(req)

    def request_stats(self, last: int | None = None) -> list:
        """The newest ``last`` finished requests' latency-attribution
        waterfalls (``{"cmd": "request_stats"}`` — queue_wait →
        prefill → decode segments, prefix savings, per-token share;
        docs/observability.md "Request attribution"), newest first."""
        req: dict = {"cmd": "request_stats"}
        if last is not None:
            req["last"] = last
        return self.request(req).get("requests", [])

    def chat(self, text: str, gen_len: int = 64) -> str:
        assert self.tokenizer is not None, "text chat needs a tokenizer"
        ids = self.tokenizer(text, return_tensors="np")["input_ids"]
        resp = self.generate_ids(ids.tolist(), gen_len)
        if "error" in resp:
            raise RuntimeError(resp["error"])
        return self.tokenizer.decode(resp["tokens"][0])

    def close(self):
        self._file.close()
        self._sock.close()


def fanout(host: str, port: int, requests: list,
           timeout: float | None = None) -> list:
    """Issue ``requests`` (protocol dicts) CONCURRENTLY — one fresh
    connection and thread per request — and return the responses in
    request order. A request that fails client-side (timeout, refused
    connection) yields an ``{"error", "type"}`` dict in its slot, so
    the caller can count failures without unwinding the others. This
    is the concurrent-client helper behind bench.py's
    ``serving_throughput`` probe and the scheduler load tests."""
    results: list = [None] * len(requests)

    def worker(i: int, payload: dict) -> None:
        try:
            c = ChatClient(host, port, timeout=timeout)
            try:
                results[i] = c.request(payload)
            finally:
                c.close()
        except Exception as e:  # noqa: BLE001 — per-slot isolation
            results[i] = {"error": str(e) or repr(e),
                          "type": type(e).__name__}

    threads = [threading.Thread(target=worker, args=(i, r), daemon=True)
               for i, r in enumerate(requests)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results


def main():  # pragma: no cover - manual demo
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8777)
    ap.add_argument("--tokenizer-dir", default=None)
    ap.add_argument("--timeout", type=float, default=None)
    args = ap.parse_args()
    tok = None
    if args.tokenizer_dir:
        from transformers import AutoTokenizer
        tok = AutoTokenizer.from_pretrained(args.tokenizer_dir)
    client = ChatClient(args.host, args.port, tok, timeout=args.timeout)
    try:
        while True:
            text = input("you> ")
            if tok:
                print("model>", client.chat(text))
            else:
                ids = [[int(t) for t in text.split()]]
                print("model>", client.generate_ids(ids))
    except (EOFError, KeyboardInterrupt):
        client.close()


if __name__ == "__main__":  # pragma: no cover
    main()
