"""Race detector for distributed Pallas kernels (CPU interpret mode).

The framework's answer to SURVEY.md §5 "Race detection/sanitizers": the
reference has **no** custom sanitizer (compute-sanitizer hooks are
commented out; logical races are hunted with sleep-injection + stress
runs). Here, the Pallas TPU interpreter carries a vector-clock race
detector across simulated devices, DMAs, and semaphores — a missing
``wait`` in a kernel's signal protocol is reported as a concrete
read/write race, not a flaky numeric mismatch.

Usage (tests)::

    with race_check():
        ag_gemm(a, b, ctx, impl="pallas")   # raises if a race is found

.. warning:: **Private-API dependency (JAX-pin canary).** This module
   reaches into ``jax._src.pallas.mosaic.interpret.interpret_pallas_call
   .races`` — a private attribute with no stability guarantee. A JAX
   upgrade can silently remove or rename it, turning every
   ``race_check()`` into a no-op. ``tests/test_race.py`` plants a real
   race and asserts it is DETECTED; that test is the canary — if it
   starts failing after a JAX bump, update the hook below before
   trusting any race-clean run.
"""

from __future__ import annotations

import contextlib
import os


@contextlib.contextmanager
def race_check(raise_on_race: bool = True):
    """Enable vector-clock race detection for interpreted kernels run in
    the body; verify none were found on exit."""
    from jax._src.pallas.mosaic.interpret import interpret_pallas_call as ipc

    prev = os.environ.get("TDT_DETECT_RACES")
    os.environ["TDT_DETECT_RACES"] = "1"
    try:
        yield
        races = ipc.races
        if raise_on_race and races is not None and races.races_found:
            raise AssertionError(
                "data race detected in interpreted Pallas kernel "
                "(see stderr for the racing accesses)")
    finally:
        if prev is None:
            os.environ.pop("TDT_DETECT_RACES", None)
        else:
            os.environ["TDT_DETECT_RACES"] = prev


def races_were_found() -> bool:
    """Inspect the last interpreted run's race state (debug helper)."""
    from jax._src.pallas.mosaic.interpret import interpret_pallas_call as ipc
    return ipc.races is not None and bool(ipc.races.races_found)
