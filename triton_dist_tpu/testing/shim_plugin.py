"""pytest plugin (loaded via addopts `-p`) that re-execs the test process
with the CPU-affinity shim preloaded BEFORE pytest's output capture starts.

Must be a plugin, not conftest logic: initial conftests are imported inside
pytest's global capture, so an exec there inherits redirected fds and the
run's output vanishes. `-p` plugins import during config setup, earlier.
"""

from triton_dist_tpu.runtime.cpu_shim import maybe_reexec_with_shim

maybe_reexec_with_shim()
