"""Static VMEM-footprint assertion for Pallas kernels.

BENCH_r02 died because a default GEMM config allocated 16.5 MB of VMEM
scratch against the v5e's 16 MB limit — and nothing between the config
table and the hardware compiler checked the budget (VERDICT r2 weak 1 /
next 10: "a static VMEM-footprint assertion helper so config bugs fail
in CI instead of on the chip"). The reference has no analog (its
configs are validated by running on the GPU); on TPU the budget is
statically computable from the ``pallas_call`` signature.

Usage::

    with assert_vmem_within():          # HARD_FOOTPRINT_CAP default
        jax.eval_shape(entry, *bench_shaped_args)

Every ``pl.pallas_call`` traced inside the context has its VMEM-resident
bytes summed — whole-array VMEM operands/outputs (the library's kernels
use whole-array specs or ``pl.ANY``) plus VMEM scratch buffers — and a
``VmemBudgetError`` is raised when a kernel exceeds the limit.
``jax.eval_shape`` makes the check trace-only: bench-shaped kernels are
checked in milliseconds on any host, no TPU (and no interpret-mode
execution) required.

The bound is approximate in the compiler's favor: Mosaic additionally
allocates stack for live intermediates, so a kernel passing this check
can still OOM — but a kernel failing it is guaranteed dead on hardware.
"""

from __future__ import annotations

import contextlib
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Ceiling on the DECLARED footprint that still compiles: the library's
# comm kernels request a 64 MB Mosaic scoped-VMEM limit (a v5e core has
# 128 MB physical VMEM) and Mosaic's scoped accounting carries ~2.2x of
# window/staging overhead over the declared buffers (measured round-5:
# 16.14 MB scoped for ~7.4 MB declared) — see the constants in
# ops/common.py.
from triton_dist_tpu.ops.common import HARD_FOOTPRINT_CAP

__all__ = ["DECLARED_FOOTPRINT_CAP", "HARD_FOOTPRINT_CAP",
           "VmemBudgetError", "assert_vmem_within", "check_entry_vmem"]

#: This module's name for the 26 MB declared-footprint cap. The old
#: alias ``VMEM_LIMIT_BYTES`` collided with ``ops.common``'s UNRELATED
#: 64 MB Mosaic scoped limit of the same name (2.5x apart — ADVICE r5);
#: it survives only as a deprecation shim below.
DECLARED_FOOTPRINT_CAP = HARD_FOOTPRINT_CAP


def __getattr__(name):
    if name == "VMEM_LIMIT_BYTES":
        import warnings
        warnings.warn(
            "triton_dist_tpu.testing.vmem.VMEM_LIMIT_BYTES is "
            "deprecated: it is the 26 MB DECLARED-footprint cap, NOT "
            "ops.common.VMEM_LIMIT_BYTES (the 64 MB Mosaic scoped "
            "limit). Use testing.vmem.DECLARED_FOOTPRINT_CAP (or "
            "HARD_FOOTPRINT_CAP) for the former, ops.common."
            "VMEM_LIMIT_BYTES for the latter.",
            DeprecationWarning, stacklevel=2)
        return DECLARED_FOOTPRINT_CAP
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


class VmemBudgetError(AssertionError):
    pass


def _nbytes(shape, dtype) -> int:
    return math.prod(shape) * jnp.dtype(dtype).itemsize


def _is_vmem_space(space) -> bool:
    # pltpu.VMEM (MemorySpace enum member) or an unset spec (Pallas
    # defaults unset memory space to VMEM on TPU).
    if space is None:
        return True
    return "VMEM" in str(space).upper() and "SMEM" not in str(space).upper()


def _spec_bytes(spec, shape_struct) -> int:
    """VMEM bytes one operand/output contributes: its block if blocked,
    the whole array otherwise; 0 for ANY/SMEM/semaphore spaces."""
    space = getattr(spec, "memory_space", None) if spec is not None else None
    if space is not None and not _is_vmem_space(space):
        return 0
    block = getattr(spec, "block_shape", None) if spec is not None else None
    shape = tuple(block) if block is not None else tuple(shape_struct.shape)
    return _nbytes(shape, shape_struct.dtype)


def _scratch_bytes(scratch) -> int:
    """VMEM bytes of one scratch entry (semaphores cost no VMEM)."""
    shape = getattr(scratch, "shape", None)
    dtype = getattr(scratch, "dtype", None)
    if shape is None or dtype is None:
        return 0
    if "semaphore" in str(dtype).lower():
        return 0
    space = getattr(scratch, "memory_space", None)
    if space is not None and not _is_vmem_space(space):
        return 0
    try:
        return _nbytes(tuple(shape), dtype)
    except TypeError:
        return 0


@contextlib.contextmanager
def assert_vmem_within(limit: int = HARD_FOOTPRINT_CAP):
    """Patch ``pl.pallas_call`` so every kernel traced in the context has
    its static VMEM footprint checked against ``limit``."""
    orig = pl.pallas_call

    def checked(kernel, *call_args, **kw):
        inner = orig(kernel, *call_args, **kw)

        def run(*args):
            total = 0
            in_specs = kw.get("in_specs") or [None] * len(args)
            for spec, arg in zip(in_specs, args):
                total += _spec_bytes(spec, arg)
            out_shape = kw.get("out_shape")
            outs = (out_shape if isinstance(out_shape, (tuple, list))
                    else [out_shape])
            out_specs = kw.get("out_specs")
            if not isinstance(out_specs, (tuple, list)):
                out_specs = [out_specs] * len(outs)
            for spec, o in zip(out_specs, outs):
                total += _spec_bytes(spec, o)
            for s in kw.get("scratch_shapes") or ():
                total += _scratch_bytes(s)
            if total > limit:
                raise VmemBudgetError(
                    f"pallas_call static VMEM footprint {total / 2**20:.2f}"
                    f" MB exceeds {limit / 2**20:.2f} MB "
                    f"(kernel={getattr(kernel, 'func', kernel)})")
            return inner(*args)
        return run

    pl.pallas_call = checked
    try:
        yield
    finally:
        pl.pallas_call = orig


def check_entry_vmem(fn, *args, limit: int = HARD_FOOTPRINT_CAP):
    """Trace ``fn(*args)`` shape-only with the budget check active.

    ``args`` may be ``jax.ShapeDtypeStruct``s — nothing executes, so
    bench-shaped configs are validated on any host in milliseconds."""
    with assert_vmem_within(limit):
        return jax.eval_shape(fn, *args)
