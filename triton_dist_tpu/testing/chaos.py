"""Fleet-level fault injection: the chaos harness behind the router's
fault-tolerance proofs (ISSUE 15).

``testing/faults.py`` injects OP-level failures (compile timeouts,
comm errors) inside one process; this module injects REPLICA-level
failures against a live fleet, each injector producing exactly the
failure signature its real-world counterpart would — so the router
tests and the ``serving_router`` bench exercise the same transitions
production would see (tests/test_chaos.py pins each injector to the
FleetView/breaker transition it claims):

- :func:`kill_replica` — the in-process analog of ``SIGKILL`` on a
  replica: live connections are SEVERED (clients and routers see a
  dead socket mid-request, never a polite error reply), the listening
  socket closes (new connections refuse), the pump stops. FleetView:
  scrapes fail immediately → ``stale`` → ``down`` by age.
- :func:`wedge_pump` — blocks the scheduler pump via the injectable
  ``Scheduler.pump_hook``: in-flight requests STALL while the replica
  keeps answering health from its handler threads. The nastiest
  failure class — liveness checks pass while the replica serves
  nothing; only a dispatch deadline (the router's per-attempt
  timeout → breaker) catches it. FleetView: stays ``live``.
- :func:`sever_stream` — kills a PREFILL replica mid-KV-stream
  (ISSUE 18): the disagg endpoint's injectable ``ship_hook`` fires
  after N shipped blocks, kill-9s the replica and aborts the stream —
  the decode side is left holding a half-received handoff (its
  staging entry goes stale and counts ``disagg.streams_severed``),
  the router sees a dead socket and re-places the request on a
  healthy replica, and the client sees tokens, never an error.
- :class:`ChaosProxy` — a TCP proxy fronting a replica with
  switchable connection faults, for failure classes that live in the
  NETWORK rather than the replica: ``blackhole`` (accepts, swallows
  bytes, never answers — scrapes/dispatches hang to their timeout),
  ``drop`` (accepts then immediately closes — instant connection
  death), ``delay`` (forwards with added latency on the reply path —
  drives health responses past the stale/down thresholds without
  touching the replica), and :meth:`ChaosProxy.sever` (cut every live
  link mid-request). Point the FleetView/router at
  ``proxy.endpoint`` instead of the replica.

All injectors are deterministic, wall-clock-free where possible
(FleetView transitions are asserted with injected clocks), and
reversible — ``forward`` mode / ``resume`` / ``release`` restore
service so recovery paths are testable too.
"""

from __future__ import annotations

import contextlib
import socket
import threading

__all__ = ["ChaosProxy", "SeveredStream", "Wedge", "kill_replica",
           "sever_stream", "wedge_pump"]

_BUF = 65536


def kill_replica(server) -> None:
    """Abruptly kill an in-process ``ModelServer`` — the deterministic
    stand-in for ``kill -9`` on a replica process:

    1. the listening socket closes (new connections are refused),
    2. every live connection is severed at the socket level (a client
       or router blocked on a reply gets EOF/reset — crucially NOT a
       structured error reply: a dead process sends nothing),
    3. the scheduler pump stops (in-flight rows die; their handler
       threads' farewell writes land on the already-dead sockets).

    Idempotent; ``server.stop()`` afterwards stays safe (test
    teardown)."""
    srv = server._srv
    srv.shutdown()
    srv.server_close()
    with server._conn_lock:
        conns = list(server._active_conns)
    for conn in conns:
        try:
            conn.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            conn.close()
        except OSError:
            pass
    if server.scheduler is not None:
        server.scheduler.stop(timeout=5.0)


class Wedge:
    """Handle for a wedged pump: ``fired`` is set once the pump hit
    the wedge (it is provably stuck, not merely idle); ``release()``
    lets it continue."""

    def __init__(self):
        self.fired = threading.Event()
        self._release = threading.Event()

    def release(self) -> None:
        self._release.set()

    def _hook(self) -> None:
        self.fired.set()
        self._release.wait()


@contextlib.contextmanager
def wedge_pump(scheduler):
    """Wedge a scheduler's pump thread for the duration of the block:
    the next work iteration blocks inside the injectable
    ``Scheduler.pump_hook`` (the stand-in for a stuck device step or
    a hung collective), so in-flight requests stall and nothing
    admits — while handler threads keep answering health/metrics.
    Yields a :class:`Wedge`; the wedge always releases on exit (and
    the hook is removed), so a test failure cannot leak a stuck
    pump."""
    w = Wedge()
    prev = scheduler.pump_hook
    scheduler.pump_hook = w._hook
    try:
        yield w
    finally:
        scheduler.pump_hook = prev
        w.release()


class SeveredStream:
    """Handle for a severed KV stream: ``fired`` is set once the
    prefill replica was killed mid-stream; ``blocks`` counts how many
    blocks actually shipped before the cut."""

    def __init__(self):
        self.fired = threading.Event()
        self.blocks = 0


@contextlib.contextmanager
def sever_stream(prefill_server, after_blocks: int = 1):
    """Kill a prefill replica in the middle of a KV-block stream.

    Arms the server's :class:`~triton_dist_tpu.serving.disagg.
    DisaggEndpoint` ``ship_hook``: once ``after_blocks`` blocks have
    left for the decode side, the hook :func:`kill_replica`-s the
    prefill server (sockets severed, pump stopped — so even the local
    re-prefill fallback dies with it, exactly like a crashed process)
    and aborts the stream. The decode side never sees ``kv_commit``:
    its half-received staging entry goes stale and is purged on the
    next offer (``disagg.streams_severed``). Yields a
    :class:`SeveredStream`; the hook is restored on exit."""
    dis = prefill_server.disagg
    if dis is None:
        raise ValueError("server has no disagg endpoint "
                         "(scheduler-path paged engines only)")
    handle = SeveredStream()
    prev = dis.ship_hook

    def hook(handoff_id, block, seq):
        del handoff_id, block, seq
        handle.blocks += 1
        if handle.blocks >= after_blocks and not handle.fired.is_set():
            handle.fired.set()
            kill_replica(prefill_server)
            raise ConnectionError(
                f"chaos: prefill killed mid-stream after "
                f"{handle.blocks} block(s)")
        if handle.fired.is_set():
            raise ConnectionError("chaos: prefill is dead")

    dis.ship_hook = hook
    try:
        yield handle
    finally:
        dis.ship_hook = prev


class ChaosProxy:
    """TCP proxy fronting one replica endpoint with switchable
    connection-level faults.

    Modes (``set_mode``; applied to connections ACCEPTED after the
    switch — use :meth:`sever` to also cut the live ones):

    - ``"forward"`` — transparent byte pump both ways (default);
      ``delay_s > 0`` adds that much latency before each reply-side
      chunk (replica → client), which is how health responses are
      pushed past the fleet's stale/down thresholds without touching
      the replica.
    - ``"blackhole"`` — accept, read and discard, never reply and
      never contact the replica: the peer hangs until its own
      timeout (the dropped-connection/partition class).
    - ``"drop"`` — accept then immediately close: instant connection
      death (the fast-failing variant).

    ``stop()`` closes the listener and severs everything."""

    MODES = ("forward", "blackhole", "drop")

    def __init__(self, target, host: str = "127.0.0.1", port: int = 0):
        from triton_dist_tpu.obs.fleet import parse_endpoint
        self.target = parse_endpoint(target)
        self._mode = "forward"
        self.delay_s = 0.0
        self._lock = threading.Lock()
        self._links: set = set()
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(64)
        self.host, self.port = self._listener.getsockname()
        self._stopped = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="tdt-chaos-proxy",
            daemon=True)
        self._accept_thread.start()

    @property
    def endpoint(self) -> tuple:
        """The ``(host, port)`` clients/FleetViews should target."""
        return (self.host, self.port)

    @property
    def mode(self) -> str:
        return self._mode

    def set_mode(self, mode: str, delay_s: float = 0.0) -> None:
        if mode not in self.MODES:
            raise ValueError(
                f"unknown chaos mode {mode!r} (known: {self.MODES})")
        self._mode = mode
        self.delay_s = float(delay_s)

    # -- plumbing ----------------------------------------------------------
    def _register(self, sock) -> None:
        with self._lock:
            self._links.add(sock)

    def _close(self, sock) -> None:
        with self._lock:
            self._links.discard(sock)
        try:
            sock.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return              # listener closed
            mode, delay = self._mode, self.delay_s
            if mode == "drop":
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            self._register(conn)
            if mode == "blackhole":
                threading.Thread(target=self._swallow, args=(conn,),
                                 daemon=True).start()
                continue
            try:
                up = socket.create_connection(self.target, timeout=10)
            except OSError:
                self._close(conn)
                continue
            self._register(up)
            threading.Thread(target=self._pump,
                             args=(conn, up, 0.0), daemon=True).start()
            threading.Thread(target=self._pump,
                             args=(up, conn, delay),
                             daemon=True).start()

    def _swallow(self, conn) -> None:
        try:
            while conn.recv(_BUF):
                pass
        except OSError:
            pass
        finally:
            self._close(conn)

    def _pump(self, src, dst, delay_s: float) -> None:
        try:
            while True:
                data = src.recv(_BUF)
                if not data:
                    break
                if delay_s > 0:
                    # Latency injection on this direction (reply path
                    # when src is the replica side).
                    self._stopped.wait(delay_s)
                dst.sendall(data)
        except OSError:
            pass
        finally:
            for s in (src, dst):
                self._close(s)

    # -- faults ------------------------------------------------------------
    def sever(self) -> int:
        """Cut every LIVE proxied connection (both sides) — a
        mid-request connection kill; new connections still follow the
        current mode. Returns how many sockets were cut."""
        with self._lock:
            links = list(self._links)
        for s in links:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            self._close(s)
        return len(links)

    def stop(self) -> None:
        self._stopped.set()
        try:
            self._listener.close()
        except OSError:
            pass
        self.sever()
