"""Test-support utilities (single-process multi-device simulation)."""
