"""Deterministic fault injection for the resilience subsystem.

The reference exercises failure handling only by what real hardware
happens to do to it (SURVEY.md §4: no fault harness); round 5 showed
what that costs — one Mosaic compile hang wedged the whole smoke queue.
This module is the controlled stand-in for the chip misbehaving: tests
plant faults here and the resilience layer (``triton_dist_tpu
.resilience``) and ``runtime.dist`` poll for them at the exact points
where the real failure classes bite, so every breaker / fallback /
retry transition is exercised in tier-1 CPU tests with zero wall-clock
dependence.

Fault kinds and where they fire:

- ``"compile_timeout"`` — the guarded fused-op call raises
  :class:`~triton_dist_tpu.resilience.CompileTimeout` immediately, as
  if the compile watchdog had tripped (no wall clock involved;
  deterministic stand-in for the paged-``direct`` Mosaic hang class).
- ``"compile_hang"``    — the fused thunk sleeps ``hang_s`` inside the
  watchdog worker thread, driving the REAL thread-timeout path (pair
  with a small ``TDT_COMPILE_TIMEOUT_S``).
- ``"comm_error"``      — the fused op raises :class:`InjectedFault`
  (the runtime-failure class: a remote DMA / collective blowing up).
- ``"nan_payload"``     — the fused op's outputs are replaced with NaN
  before the numeric guard sees them (``TDT_NUMERIC_GUARD=1``).
- ``"dist_init"``       — ``runtime.dist``'s coordinator bootstrap
  raises before calling ``jax.distributed.initialize`` (the
  coordinator-not-yet-up multi-host race found in r5).

Usage::

    from triton_dist_tpu.testing import faults
    with faults.inject("compile_timeout", op="gemm_rs", times=2):
        gemm_rs(a, b, ctx)          # trips the watchdog, falls back

Faults are process-local, thread-safe, and consumed atomically
(``times`` decrements per activation); ``inject`` removes its fault on
exit, ``clear()`` wipes the plan between tests.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading

__all__ = ["Fault", "InjectedFault", "KINDS", "active", "clear",
           "inject", "take"]

#: The recognized fault kinds (see module docstring for semantics).
KINDS = ("compile_timeout", "compile_hang", "comm_error", "nan_payload",
         "dist_init")


class InjectedFault(RuntimeError):
    """Raised by instrumented code for ``comm_error`` / ``dist_init``
    faults. Classified as an infra error by the resilience router, so
    it takes the same fallback path a real runtime failure would."""


@dataclasses.dataclass
class Fault:
    kind: str
    op: str | None = None       # None matches any op
    times: int = 1              # remaining activations
    hang_s: float = 60.0        # compile_hang sleep
    message: str = "injected fault"
    fired: int = 0              # activations so far (test assertions)


_LOCK = threading.Lock()
_PLAN: list[Fault] = []


def active() -> bool:
    """Cheap gate for hot paths: any fault currently planted?"""
    return bool(_PLAN)


def take(kind: str, op: str | None) -> Fault | None:
    """Consume one activation of a matching fault, or None.

    A fault with ``op=None`` matches every op; an op-specific fault
    only its own. Matching is first-planted-first-served."""
    if not _PLAN:
        return None
    with _LOCK:
        for f in _PLAN:
            if (f.kind == kind and f.times > 0
                    and (f.op is None or f.op == op)):
                f.times -= 1
                f.fired += 1
                return f
    return None


@contextlib.contextmanager
def inject(kind: str, op: str | None = None, times: int = 1,
           hang_s: float = 60.0, message: str = "injected fault"):
    """Plant a fault for the duration of the ``with`` block."""
    if kind not in KINDS:
        raise ValueError(f"unknown fault kind {kind!r} (known: {KINDS})")
    f = Fault(kind=kind, op=op, times=times, hang_s=hang_s,
              message=message)
    with _LOCK:
        _PLAN.append(f)
    try:
        yield f
    finally:
        with _LOCK:
            if f in _PLAN:
                _PLAN.remove(f)


def clear() -> None:
    """Remove every planted fault (test teardown)."""
    with _LOCK:
        _PLAN.clear()
