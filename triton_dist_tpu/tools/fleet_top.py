"""Fleet dashboard: ``tools/top.py`` lifted across N replicas.

One refresh-loop screen over a replica fleet (ISSUE 14,
docs/observability.md "Fleet view"): a row per replica — status
(live/stale/down), last-good-snapshot age, queue depth, occupancy,
rolling TTFT/TPOT, breach flags, placement score — plus a fleet
rollup line with the bucket-merged TTFT/TPOT percentiles
(``obs.fleet.merge_fleet_snapshots`` — summed buckets through
``histogram_quantile``, never averaged per-replica percentiles).

The scrapes are the CHEAP path on purpose: ``{"cmd": "health"}``
(lock-free server-side reads, no SLO force-evaluation) for the rows
and ``{"cmd": "metrics", "evaluate": false}`` for the merged
histograms — watching a fleet at 1 Hz perturbs no pump loop. A dead
or wedged replica renders as ``stale``/``down`` with its age; the
screen never raises.

Usage:
    python -m triton_dist_tpu.tools.fleet_top \\
        --endpoints 127.0.0.1:8777,127.0.0.1:8778 [--interval 2]
        [--once]
    python -m triton_dist_tpu.tools.fleet_top --router 127.0.0.1:8700

``--router`` watches a :class:`~triton_dist_tpu.serving.router
.RouterServer` instead (ISSUE 15): one ``{"cmd": "router_status"}``
scrape per tick renders the ROUTER's per-replica placement rows —
status/age/score joined with breaker state, router-side in-flight
dispatches and draining flags — plus the failover / shed / placement
counters, so a failover postmortem reads from the same dashboard as
single-replica serving.

``render()`` / ``render_router()`` are pure (state dict → string) so
both screens are testable without servers (tests/test_fleet.py,
tests/test_router.py).
"""

from __future__ import annotations

import argparse
import sys
import time


#: Full-metrics scrape cadence: the per-replica rows come from the
#: cheap health verb EVERY tick, the bucket-merged fleet percentiles
#: only every Nth (a full snapshot ships every histogram — at 1 Hz
#: over N replicas that is exactly the monitoring load the health
#: verb exists to avoid; the stale merge is rendered from cache in
#: between).
METRICS_EVERY = 5


def fetch(view, with_metrics: bool = True) -> dict:
    """One refresh: a concurrent health poll through a persistent
    :class:`~triton_dist_tpu.obs.fleet.FleetView` (persistent so
    staleness ages survive across refresh ticks), plus — only when
    ``with_metrics`` (every :data:`METRICS_EVERY` ticks in the loop)
    — a full-snapshot scrape for the bucket-merged fleet percentiles;
    otherwise the last merge is rendered from the view's cache.
    Returns the dict :func:`render` consumes."""
    rows = view.poll()
    merged = (view.scrape_metrics(evaluate=False) if with_metrics
              else view.merged())
    # History (ISSUE 16) rides the SAME cadence: the poll above just
    # fed the view's poll-sampled health history for free, and the
    # remote {"cmd": "history"} bulk read only goes out on the sparse
    # metrics ticks — off-tick renders read the cached copy, issuing
    # zero extra scrapes.
    remote = (view.scrape_history(max_points=32) if with_metrics
              else view.remote_history())
    return {"replicas": rows, "merged": merged,
            "history": view.history(max_points=32),
            "remote_history": remote}


def _fmt(v) -> str:
    if v is None:
        return "-"
    f = float(v)
    return str(int(f)) if f == int(f) else f"{f:.3f}"


def _row_cells(r: dict) -> list:
    h = r.get("health") or {}
    rolling = h.get("rolling") or {}
    breaches = sum(1 for t in (h.get("slo") or {}).values()
                   if t.get("breached"))
    occ = _fmt(h.get("batch_occupancy"))
    batch = h.get("batch")
    return [
        r.get("replica_id") or r.get("endpoint") or "?",
        r.get("status", "?"),
        # Disagg placement tier (ISSUE 18): which pool this replica
        # serves — advertised in health, "-" pre-disagg.
        str(h.get("tier") or "-"),
        f"{_fmt(r.get('age_s'))}s",
        _fmt(h.get("queue_depth")),
        f"{occ}/{_fmt(batch)}" if batch is not None else occ,
        f"{_fmt(rolling.get('ttft_p50_ms'))}/"
        f"{_fmt(rolling.get('ttft_p99_ms'))}",
        f"{_fmt(rolling.get('tpot_p50_ms'))}/"
        f"{_fmt(rolling.get('tpot_p99_ms'))}",
        str(breaches) if breaches else "-",
        _fmt(r.get("score")),
    ]


_HEADER = ["replica", "st", "tier", "age", "q", "occ", "ttft p50/p99",
           "tpot p50/p99", "brch", "score"]


def render(state: dict) -> str:
    """One fleet screen from ``{"replicas": [...], "merged": {...}}``
    (the :func:`fetch` shape — per-replica rows are
    ``FleetView.replicas()`` dicts, ``merged`` a
    ``merge_fleet_snapshots`` result or None)."""
    from triton_dist_tpu.obs.fleet import merged_percentiles
    rows = state.get("replicas") or []
    counts = {"live": 0, "stale": 0, "down": 0}
    for r in rows:
        counts[r.get("status", "down")] = counts.get(
            r.get("status", "down"), 0) + 1
    lines = [f"tdt fleet — {time.strftime('%H:%M:%S')} — "
             f"{len(rows)} replica(s) ({counts['live']} live / "
             f"{counts['stale']} stale / {counts['down']} down)", ""]
    if not rows:
        lines.append("(no replicas)")
        return "\n".join(lines)

    table = [_HEADER] + [_row_cells(r) for r in rows]
    widths = [max(len(row[i]) for row in table)
              for i in range(len(_HEADER))]
    for row in table:
        lines.append("  ".join(c.ljust(w)
                               for c, w in zip(row, widths)).rstrip())

    merged = state.get("merged")
    fleet_bits = []
    healths = [r.get("health") or {} for r in rows
               if r.get("status") != "down"]
    if healths:
        q = sum(float(h.get("queue_depth") or 0) for h in healths)
        occ = sum(float(h.get("batch_occupancy") or 0) for h in healths)
        fleet_bits.append(f"queue {_fmt(q)}   occupancy {_fmt(occ)}")
    if merged:
        for label, p in merged_percentiles(
                merged.get("histograms")).items():
            fleet_bits.append(
                f"{label} p50 {_fmt(p['p50'])} / p99 {_fmt(p['p99'])} "
                f"ms (bucket-merged, n {p['n']})")
        c = merged.get("counters", {})
        if "serving.retired" in c:
            fleet_bits.append(f"retired {_fmt(c['serving.retired'])}")
    if fleet_bits:
        lines += ["", "fleet: " + "   ".join(fleet_bits)]

    # Poll-fed health history (ISSUE 16): fleet-rollup sparklines plus
    # one compact line per replica — the trend the instantaneous table
    # above cannot show. Additive: absent until a view has polled.
    hist = state.get("history") or {}
    fseries = (hist.get("fleet") or {}).get("series") or {}
    if any((s.get("points") or []) for s in fseries.values()):
        from triton_dist_tpu.obs.history import sparkline

        def _spark(series, name):
            pts = (series.get(name) or {}).get("points") or []
            return sparkline([v for _, v in pts], width=16) or "-"

        lines += ["", "history: "
                  f"queue {_spark(fseries, 'queue_depth')}   "
                  f"occ {_spark(fseries, 'batch_occupancy')}   "
                  f"reporting {_spark(fseries, 'replicas_reporting')}"]
        for rid in sorted(hist.get("replicas") or {}):
            rs = (hist["replicas"][rid] or {}).get("series") or {}
            lines.append(
                f"  {rid}: q {_spark(rs, 'queue_depth')}  "
                f"ttft99 {_spark(rs, 'ttft_p99_ms')}")
    # Remote samplers' early warnings (scrape_history cache): surface
    # the newest one per replica — the fleet screen is exactly where a
    # pre-breach warning must show up.
    for rid in sorted(state.get("remote_history") or {}):
        rh = state["remote_history"][rid] or {}
        for w in (rh.get("warnings") or [])[:1]:
            lines.append(
                f"  ! {rid}: history.warning {w.get('detector', '?')} "
                f"{w.get('metric', '?')}")

    errs = [r for r in rows if r.get("error")]
    for r in errs[:4]:
        lines.append(f"  ! {r.get('endpoint')}: "
                     f"{str(r['error'])[:70]}")
    return "\n".join(lines)


_ROUTER_HEADER = ["replica", "st", "tier", "age", "breaker", "infl",
                  "drain", "score", "placed"]


def render_router(status: dict) -> str:
    """One router screen from a ``{"cmd": "router_status"}``
    ``router`` payload (``RouterServer.status()`` shape): per-replica
    placement rows (fleet status joined with the router's breaker /
    in-flight / draining dimension) and the router counters."""
    rows = status.get("replicas") or []
    placements = status.get("placements") or {}
    lines = [f"tdt router — {time.strftime('%H:%M:%S')} — "
             f"{len(rows)} replica(s), uptime "
             f"{_fmt(status.get('uptime_s'))}s", ""]
    if not rows:
        lines.append("(no replicas)")
    else:
        table = [_ROUTER_HEADER]
        for r in rows:
            rid = r.get("replica_id") or r.get("endpoint") or "?"
            table.append([
                rid,
                r.get("status", "?"),
                str(r.get("tier") or "-"),
                f"{_fmt(r.get('age_s'))}s",
                r.get("breaker", "?"),
                _fmt(r.get("inflight")),
                "yes" if r.get("draining") else "-",
                _fmt(r.get("score")),
                _fmt(placements.get(r.get("endpoint"))
                     or placements.get(rid)),
            ])
        widths = [max(len(row[i]) for row in table)
                  for i in range(len(_ROUTER_HEADER))]
        for row in table:
            lines.append("  ".join(
                c.ljust(w) for c, w in zip(row, widths)).rstrip())
    c = status.get("counters") or {}
    bits = []
    for key, label in (("router.requests", "requests"),
                       ("router.failovers", "failovers"),
                       ("router.shed", "shed"),
                       ("router.no_replicas", "no-replica"),
                       ("router.dispatch_errors", "dispatch-err"),
                       ("router.failover_storms", "storms"),
                       ("router.disagg_dispatches", "disagg"),
                       ("router.disagg_errors", "disagg-err"),
                       ("router.retiers", "retiers")):
        if key in c:
            bits.append(f"{label} {_fmt(c[key])}")
    if bits:
        lines += ["", "router: " + "   ".join(bits)]
    return "\n".join(lines)


def fetch_router(endpoint, timeout: float | None = None) -> dict:
    """One ``router_status`` scrape (degrades to an error screen
    payload, never raises — dashboard contract)."""
    from triton_dist_tpu.serving.client import ChatClient
    try:
        c = ChatClient(*_parse(endpoint), timeout=timeout or 5.0)
        try:
            return c.request({"cmd": "router_status"}).get("router", {})
        finally:
            c.close()
    except Exception as e:  # noqa: BLE001 — screen must render
        return {"replicas": [], "counters": {},
                "error": str(e) or repr(e)}


def _parse(endpoint):
    from triton_dist_tpu.obs.fleet import parse_endpoint
    return parse_endpoint(endpoint)


def main(argv=None) -> int:
    from triton_dist_tpu.obs.fleet import FleetView
    ap = argparse.ArgumentParser()
    ap.add_argument("--endpoints", default=None,
                    help="comma-separated host:port replica list")
    ap.add_argument("--router", default=None,
                    help="host:port of a RouterServer — render its "
                         "router_status instead of direct scrapes")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--iterations", type=int, default=None,
                    help="stop after N refreshes (default: forever)")
    ap.add_argument("--once", action="store_true",
                    help="print one screen and exit (no ANSI clear)")
    ap.add_argument("--timeout", type=float, default=None,
                    help="per-replica scrape timeout "
                         "(default TDT_FLEET_TIMEOUT_S)")
    args = ap.parse_args(argv)
    if not args.endpoints and not args.router:
        ap.error("need --endpoints or --router")
    view = None
    if args.endpoints:
        eps = [e.strip() for e in args.endpoints.split(",")
               if e.strip()]
        view = FleetView(eps, timeout_s=args.timeout)
    n = 1 if args.once else args.iterations
    i = 0
    try:
        while n is None or i < n:
            if args.router:
                screen = render_router(
                    fetch_router(args.router, timeout=args.timeout))
                if view is not None:
                    screen += "\n\n" + render(fetch(
                        view, with_metrics=args.once
                        or i % METRICS_EVERY == 0))
            else:
                screen = render(fetch(
                    view,
                    with_metrics=args.once or i % METRICS_EVERY == 0))
            if not args.once:
                sys.stdout.write("\x1b[2J\x1b[H")
            print(screen)
            sys.stdout.flush()
            i += 1
            if n is not None and i >= n:
                break
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
