"""Export, merge, and validate Chrome trace-event / Perfetto dumps.

The reference's tracing pipeline writes per-rank chrome traces and
merges them on rank 0 (``group_profile`` + ``gather_object`` +
``_merge_json``, python/triton_dist/utils.py:505-592). This module is
that pipeline for ``obs.trace``'s structured events:

- :func:`to_chrome` — tracer snapshot → Chrome trace-event JSON dict
  (the ``{"traceEvents": [...]}`` object format Perfetto loads).
- :func:`gather_to_chrome` — every host contributes its events through
  a byte-padded ``process_allgather`` (the ``gather_object`` analog;
  same transport as ``obs.exposition.aggregate_across_hosts``) and the
  merge runs on every rank; single-process returns the local trace.
- :func:`validate` — schema check for dumps: balanced B/E pairs per
  track (unclosed begins are *warnings* — a hang postmortem
  legitimately ends mid-span), monotonic timestamps per track,
  well-formed X/instant events.
- :func:`compute_overlap` — reconstruct per-op comm/compute overlap
  from the ring-schedule chunk events (``comms.<op>.compute`` /
  ``comms.<op>.comm`` tracks) by interval arithmetic over the trace,
  instead of trusting the dispatch-time ``comms.<op>.overlap_pct``
  gauge.

- :func:`merge_profile` — overlay a parsed device-profile capture
  (``obs.devprof`` / ``tools/profile_export.py``) into a host dump on
  ONE clock: capture timestamps are profile-session-relative and the
  ``tdt_capture.json`` anchor shifts them onto the same wall-anchored
  micros the tracer stamps, so a single Perfetto view shows dispatch,
  the ring-chunk schedule, and what the chip actually did.

CLI::

    python -m triton_dist_tpu.tools.trace_export --validate dump.json
    python -m triton_dist_tpu.tools.trace_export --overlap  dump.json
    python -m triton_dist_tpu.tools.trace_export a.json b.json --out merged.json
    python -m triton_dist_tpu.tools.trace_export dump.json \
        --merge-profile /tmp/tdt_devprof --out overlaid.json

Load any output at https://ui.perfetto.dev (or chrome://tracing); the
"reading a Perfetto dump" walkthrough lives in docs/observability.md.
"""

from __future__ import annotations

import argparse
import json
import re
import sys

__all__ = ["compute_overlap", "gather_to_chrome",
           "history_counter_events", "merge_chrome", "merge_profile",
           "to_chrome", "validate", "write_trace"]


def history_counter_events(hist: dict, pid: int = 0) -> list[dict]:
    """Render an ``obs.history`` snapshot (``{"epoch", "series":
    {name: {"points": [[t, v], ...]}}}``) as Chrome ``"C"`` counter
    events — Perfetto draws each name as a counter track, so sampled
    series (queue depth, burn rates, KV occupancy) overlay the event
    timeline on ONE clock. Timestamps are the store's perf-counter
    seconds shifted by its wall ``epoch`` anchor onto the same
    wall-anchored micros ``obs.trace`` stamps (ISSUE 16)."""
    epoch = float(hist.get("epoch") or 0.0)
    events: list[dict] = []
    for name in sorted(hist.get("series") or {}):
        for t, v in hist["series"][name].get("points") or []:
            events.append({"ph": "C", "pid": pid, "tid": 0,
                           "name": name, "cat": "history",
                           "ts": (float(t) + epoch) * 1e6,
                           "args": {"value": float(v)}})
    return events


def to_chrome(collected: dict, pid: int | None = None,
              process_name: str = "tdt",
              metadata: dict | None = None) -> dict:
    """Convert an ``obs.trace.collect()`` snapshot into a Chrome
    trace-event object. Tracks become tids (named via ``M`` metadata
    events); event args carry the trace ID under ``args.trace_id`` so
    Perfetto's query/filter box isolates one request's story."""
    if pid is None:
        pid = _host_index()
    events: list[dict] = [
        {"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
         "args": {"name": f"{process_name} host{pid}"}},
    ]
    for tid, track in enumerate(sorted(collected.get("tracks", {})),
                                start=1):
        events.append({"ph": "M", "pid": pid, "tid": tid,
                       "name": "thread_name", "args": {"name": track}})
        for ph, ts_us, dur_us, name, cat, trace_id, args in \
                collected["tracks"][track]:
            ev: dict = {"ph": ph, "ts": ts_us, "pid": pid, "tid": tid,
                        "name": name, "cat": cat}
            if ph == "X":
                ev["dur"] = 0.0 if dur_us is None else dur_us
            elif ph == "i":
                ev["s"] = "t"   # thread-scoped instant
            if args or trace_id:
                a = dict(args or {})
                if trace_id:
                    a["trace_id"] = trace_id
                ev["args"] = a
            events.append(ev)
    meta = {"events_total": collected.get("events_total", 0),
            "dropped_total": collected.get("dropped_total", 0),
            "ring_capacity": collected.get("ring_capacity", 0)}
    if metadata:
        meta.update(metadata)
    # A flight dump with attached history (obs.flight's provider)
    # carries the raw series in metadata AND as counter tracks, so
    # the Perfetto view shows the lead-up without a second tool pass.
    hist = meta.get("history")
    if hist:
        events.extend(history_counter_events(hist, pid=pid))
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "metadata": meta}


def write_trace(chrome: dict, path: str) -> str:
    with open(path, "w") as f:
        json.dump(chrome, f)
    return path


def merge_chrome(traces: list[dict]) -> dict:
    """Merge per-host trace objects into one (the reference's rank-0
    ``_merge_json``). Colliding pids across sources are re-based so
    two hosts that both called themselves pid 0 stay distinct rows."""
    traces = [t for t in traces if t]
    events: list[dict] = []
    metadata: dict = {"hosts": len(traces)}
    used_pids: set = set()
    for i, t in enumerate(traces):
        pids = {e.get("pid", 0) for e in t.get("traceEvents", [])}
        remap = {}
        for p in sorted(pids, key=str):
            q = p
            while q in used_pids:
                q = (q if isinstance(q, int) else 0) + 1000 * (i + 1)
            remap[p] = q
            used_pids.add(q)
        for e in t.get("traceEvents", []):
            e = dict(e)
            e["pid"] = remap.get(e.get("pid", 0), e.get("pid", 0))
            events.append(e)
        for k, v in (t.get("metadata") or {}).items():
            metadata.setdefault(k, v)
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "metadata": metadata}


def _host_index() -> int:
    try:
        import jax
        return jax.process_index()
    except Exception:  # noqa: BLE001 — no backend
        return 0


def gather_to_chrome(last_s: float | None = None,
                     process_name: str = "tdt") -> dict:
    """Every host's buffered events, merged into one trace object.

    The transport mirrors ``obs.exposition.aggregate_across_hosts``
    (JSON bytes through a padded ``process_allgather`` — the
    ``gather_object`` chrome-trace merge of the reference); every rank
    returns the same merged trace. Single-process: the local trace."""
    from triton_dist_tpu.obs import trace as _trace
    from triton_dist_tpu.obs.exposition import allgather_json
    local = to_chrome(_trace.collect(last_s=last_s),
                      process_name=process_name)
    gathered = allgather_json(local)
    return local if len(gathered) == 1 else merge_chrome(gathered)


def merge_profile(chrome: dict, capture_path: str) -> dict:
    """Overlay a device-profile capture into a host trace dump.

    The capture's label windows, device-plane events, and host
    execution/comm events land as extra process rows (pid 900+host —
    ``tools/profile_export.DEVICE_PID_BASE``), timestamp-shifted onto
    the host dump's wall-anchored clock via the capture's
    ``tdt_capture.json`` anchor. The host events are untouched, so the
    result stays ``--validate``-clean."""
    from triton_dist_tpu.tools import profile_export as _pexp
    caps = _pexp.capture_paths(capture_path)
    if not caps:
        raise ValueError(
            f"no profile capture found under {capture_path!r}")
    merged = dict(chrome)
    merged["traceEvents"] = list(chrome.get("traceEvents", []))
    for cap in caps:
        merged["traceEvents"].extend(_pexp.to_chrome_events(cap))
    meta = dict(chrome.get("metadata") or {})
    meta["merged_profiles"] = meta.get("merged_profiles", 0) + len(caps)
    meta["profile_sources"] = (meta.get("profile_sources") or []) + [
        str(c) for c in caps]
    merged["metadata"] = meta
    return merged


# ---------------------------------------------------------------------------
# Validation.
# ---------------------------------------------------------------------------

_KNOWN_PH = frozenset("BEXiMC")


def validate(chrome: dict) -> tuple[list[str], list[str]]:
    """Check a dump against the trace-event schema this pipeline emits.

    Returns ``(errors, warnings)``. Errors: malformed events, an ``E``
    whose name differs from the open ``B`` it closes, non-monotonic
    begin/end/instant timestamps within a track, X events with
    negative duration. Warnings — the truncation modes a flight
    record produces BY DESIGN and must not be rejected for: begins
    left unclosed at the end of the dump (a hang record legitimately
    ends mid-span; the unclosed span IS the postmortem's answer), an
    ``E`` with no open begin (its ``B`` fell before the
    ``TDT_FLIGHT_SECONDS`` window or was ring-overwritten), and
    unknown phases. ``C`` (counter) events — the history-plane series
    tracks — are validated for numeric ts/args but exempt from the
    monotonic check (several series interleave on one tid).
    """
    errors: list[str] = []
    warnings: list[str] = []
    evs = chrome.get("traceEvents")
    if not isinstance(evs, list):
        return (["traceEvents missing or not a list"], [])
    stacks: dict[tuple, list] = {}
    last_ts: dict[tuple, float] = {}
    for i, e in enumerate(evs):
        ph = e.get("ph")
        if ph == "M":
            continue
        if ph not in _KNOWN_PH:
            warnings.append(f"event {i}: unknown phase {ph!r}")
            continue
        ts = e.get("ts")
        if not isinstance(ts, (int, float)):
            errors.append(f"event {i}: non-numeric ts {ts!r}")
            continue
        key = (e.get("pid", 0), e.get("tid", 0))
        if ph == "C":
            # Counter samples (history series): args must carry at
            # least one numeric value. Several series interleave on
            # one tid by design, so C events are exempt from the
            # per-track monotonic check (like back-dated X events).
            args = e.get("args")
            if not isinstance(args, dict) or not args or not all(
                    isinstance(v, (int, float))
                    and not isinstance(v, bool)
                    for v in args.values()):
                errors.append(
                    f"event {i}: C with non-numeric args {args!r}")
            continue
        if ph == "X":
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append(f"event {i}: X with bad dur {dur!r}")
            continue   # X may be emitted retrospectively (back-dated)
        prev = last_ts.get(key)
        if prev is not None and ts < prev:
            errors.append(
                f"event {i}: ts went backwards on track {key} "
                f"({ts} < {prev})")
        last_ts[key] = ts
        if ph == "B":
            stacks.setdefault(key, []).append((e.get("name"), i))
        elif ph == "E":
            stack = stacks.get(key)
            if not stack:
                warnings.append(
                    f"event {i}: E {e.get('name')!r} with no open B "
                    f"on track {key} — begin fell outside the "
                    f"recorded window")
                continue
            b_name, b_i = stack.pop()
            name = e.get("name")
            if name is not None and b_name is not None \
                    and name != b_name:
                errors.append(
                    f"event {i}: E {name!r} closes B {b_name!r} "
                    f"(event {b_i}) on track {key}")
    for key, stack in stacks.items():
        for name, i in stack:
            warnings.append(
                f"unclosed B {name!r} (event {i}) on track {key} — "
                f"in-flight when the dump was taken")
    return errors, warnings


# ---------------------------------------------------------------------------
# Overlap reconstruction from ring-schedule chunk events.
# ---------------------------------------------------------------------------

_SCHED_TRACK = re.compile(r"^comms\.(?P<op>[\w.]+)\.(?P<kind>comm|compute)$")


def _union(intervals: list[tuple[float, float]]) \
        -> list[tuple[float, float]]:
    """Merge intervals into a disjoint sorted union (events on one
    track may overlap each other; double-counting would overstate
    coverage)."""
    merged: list[list[float]] = []
    for a, b in sorted(intervals):
        if merged and a <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], b)
        else:
            merged.append([a, b])
    return [(a, b) for a, b in merged]


def _union_len(intervals: list[tuple[float, float]]) -> float:
    return sum(b - a for a, b in _union(intervals))


def _intersect(comm: list[tuple[float, float]],
               compute: list[tuple[float, float]]) -> float:
    """Length of union(comm) ∩ union(compute)."""
    covered = 0.0
    compute = _union(compute)
    for a, b in _union(comm):
        for c, d in compute:
            if d <= a:
                continue
            if c >= b:
                break
            covered += min(b, d) - max(a, c)
    return covered


def compute_overlap(chrome: dict) -> dict:
    """Reconstruct per-op overlap from the ``comms.<op>.{comm,compute}``
    chunk tracks: ``exposed_comm_ms`` is comm-interval time not covered
    by any compute interval of the same (host, op); ``overlap_pct`` is
    ``100 * (1 - exposed / comm)`` — measured over the trace's
    geometry, independent of the dispatch-time gauge.

    The interval arithmetic runs per (pid, op) — a merged multi-host
    trace has each host's schedule on its own pid, and SPMD hosts run
    near-simultaneously on wall-anchored clocks, so pooling them would
    let host B's compute slices mask host A's exposed comm. Per-op
    numbers are the SUM of the per-host terms (worst case surfaces in
    the total rather than averaging away)."""
    track_of: dict[tuple, str] = {}
    for e in chrome.get("traceEvents", []):
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            track_of[(e.get("pid", 0), e.get("tid", 0))] = \
                e.get("args", {}).get("name", "")
    per_host_op: dict[tuple, dict[str, list]] = {}
    for e in chrome.get("traceEvents", []):
        if e.get("ph") != "X":
            continue
        pid = e.get("pid", 0)
        m = _SCHED_TRACK.match(track_of.get((pid, e.get("tid", 0)), ""))
        if not m:
            continue
        iv = (float(e["ts"]), float(e["ts"]) + float(e.get("dur", 0)))
        per_host_op.setdefault((pid, m["op"]),
                               {"comm": [], "compute": []})[
            m["kind"]].append(iv)
    agg: dict[str, dict] = {}
    for (pid, op), kinds in sorted(per_host_op.items(), key=str):
        comm_us = _union_len(kinds["comm"])
        covered_us = _intersect(kinds["comm"], kinds["compute"])
        a = agg.setdefault(op, {"comm_us": 0.0, "exposed_us": 0.0,
                                "n_chunks": 0, "n_hosts": 0})
        a["comm_us"] += comm_us
        a["exposed_us"] += max(comm_us - covered_us, 0.0)
        a["n_chunks"] += len(kinds["compute"])
        a["n_hosts"] += 1
    out = {}
    for op, a in sorted(agg.items()):
        comm_us, exposed_us = a["comm_us"], a["exposed_us"]
        out[op] = {
            "comm_ms": round(comm_us / 1e3, 6),
            "exposed_comm_ms": round(exposed_us / 1e3, 6),
            "overlap_pct": round(100.0 * (1 - exposed_us / comm_us), 2)
            if comm_us > 0 else 100.0,
            "n_chunks": a["n_chunks"],
            "n_hosts": a["n_hosts"],
        }
    return out


# ---------------------------------------------------------------------------
# CLI.
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Validate / analyze / merge tdt trace dumps")
    ap.add_argument("paths", nargs="+", help="trace JSON file(s)")
    ap.add_argument("--validate", action="store_true",
                    help="schema-check each dump; rc!=0 on errors "
                         "(unclosed begins are warnings, not errors)")
    ap.add_argument("--overlap", action="store_true",
                    help="reconstruct per-op comm/compute overlap "
                         "from ring-schedule chunk events")
    ap.add_argument("--out", default=None,
                    help="merge the inputs into this file")
    ap.add_argument("--merge-profile", default=None, metavar="CAPTURE",
                    help="overlay a jax.profiler capture (file / run "
                         "dir / TDT_DEVPROF_DIR root) into the merged "
                         "dump on one wall clock; requires --out")
    ap.add_argument("--history", default=None, metavar="SERIES",
                    help="overlay an obs.history snapshot JSON (a "
                         "saved {'cmd': 'history'} reply or a raw "
                         "store snapshot) into the merged dump as "
                         "Perfetto counter tracks; requires --out")
    args = ap.parse_args(argv)
    if args.merge_profile and not args.out:
        ap.error("--merge-profile needs --out for the overlaid dump")
    if args.history and not args.out:
        ap.error("--history needs --out for the overlaid dump")
    traces = []
    for p in args.paths:
        with open(p) as f:
            traces.append(json.load(f))
    rc = 0
    if args.validate:
        for p, t in zip(args.paths, traces):
            errors, warns = validate(t)
            for w in warns:
                print(f"{p}: WARN {w}")
            for e in errors:
                print(f"{p}: ERROR {e}")
            n = len(t.get("traceEvents", []))
            print(f"{p}: {'INVALID' if errors else 'valid'} "
                  f"({n} events, {len(errors)} errors, "
                  f"{len(warns)} warnings)")
            rc = rc or (1 if errors else 0)
    if args.overlap:
        merged = merge_chrome(traces) if len(traces) > 1 else traces[0]
        print(json.dumps(compute_overlap(merged), indent=2))
    if args.out:
        merged = merge_chrome(traces) if len(traces) > 1 else traces[0]
        if args.merge_profile:
            merged = merge_profile(merged, args.merge_profile)
        if args.history:
            with open(args.history) as f:
                hist = json.load(f)
            if isinstance(hist, dict) and "history" in hist:
                hist = hist["history"]      # a saved verb reply
            if not isinstance(hist, dict) or not hist.get("series"):
                ap.error(f"--history {args.history}: no series found")
            merged = dict(merged)
            merged["traceEvents"] = (list(merged.get("traceEvents", []))
                                     + history_counter_events(hist))
            meta = dict(merged.get("metadata") or {})
            meta["history_series"] = len(hist["series"])
            merged["metadata"] = meta
        write_trace(merged, args.out)
        print(f"wrote {args.out} "
              f"({len(merged['traceEvents'])} events)")
    if not (args.validate or args.overlap or args.out):
        ap.error("nothing to do: pass --validate, --overlap, or --out")
    return rc


if __name__ == "__main__":
    sys.exit(main())
