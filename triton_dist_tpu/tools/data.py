"""Token-shard dataset: pack → memory-map → shuffled epoch batches.

The training-side IO pipeline (beyond-reference: the reference has no
training, hence no loader). The corpus lives as a flat int32 ``.bin``
token shard; reading memory-maps it (no copy of the corpus into RAM),
and batching runs through the native loader (csrc/dataio: seeded
Fisher-Yates epoch permutation + chunk gather) with a bit-identical
Python fallback. Epochs are deterministic in (seed, epoch) — a resumed
finetune run re-derives the exact batch order.

    pack_tokens(ids, "corpus.bin")
    ds = TokenDataset("corpus.bin", batch=4, seq=512)
    for step, batch in zip(range(100), ds.batches(seed=0)):
        ...  # batch: (4, 512) int32 numpy

``tdt-finetune --data corpus.bin`` uses this path automatically.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from triton_dist_tpu.runtime.native_lib import load_native

_SRC = os.path.join(os.path.dirname(__file__), "..", "csrc", "dataio",
                    "dataio.cc")
_SO = os.path.join(os.path.dirname(_SRC), "libtdtdata.so")
_LIB = None
_TRIED = False

_I32P = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")


def _configure(lib):
    lib.tdt_data_epoch_perm.restype = ctypes.c_int32
    lib.tdt_data_epoch_perm.argtypes = [ctypes.c_int64, ctypes.c_uint64,
                                        _I32P]
    lib.tdt_data_gather.restype = ctypes.c_int32
    lib.tdt_data_gather.argtypes = [_I32P, ctypes.c_int64, ctypes.c_int64,
                                    _I32P, ctypes.c_int64, _I32P]


def _load():
    global _LIB, _TRIED
    if not _TRIED:
        _TRIED = True
        _LIB = load_native(_SRC, _SO, _configure)
    return _LIB


def have_native() -> bool:
    return _load() is not None


def _mix(state: int) -> tuple[int, int]:
    """splitmix64 step — mirrors csrc/dataio exactly (parity-tested)."""
    m = (1 << 64) - 1
    state = (state + 0x9E3779B97F4A7C15) & m
    z = state
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & m
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & m
    return state, z ^ (z >> 31)


def _py_epoch_perm(n: int, seed: int) -> np.ndarray:
    out = np.arange(n, dtype=np.int32)
    s = seed & ((1 << 64) - 1)
    for i in range(n - 1, 0, -1):
        s, r = _mix(s)
        j = r % (i + 1)
        out[i], out[j] = out[j], out[i]
    return out


def pack_tokens(ids, path: str) -> str:
    """Write a flat int32 token shard."""
    np.asarray(ids, np.int32).tofile(path)
    return path


class TokenDataset:
    """Memory-mapped int32 token shard, chunked into (seq)-token rows."""

    def __init__(self, path: str, batch: int, seq: int):
        self.data = np.memmap(path, np.int32, mode="r")
        self.batch, self.seq = batch, seq
        self.n_chunks = len(self.data) // seq
        if self.n_chunks < 1:
            raise ValueError(
                f"{path}: {len(self.data)} tokens < one {seq}-token chunk")
        self._lib = _load()

    def epoch_perm(self, seed: int, epoch: int) -> np.ndarray:
        """Deterministic chunk order for (seed, epoch)."""
        mixed = (seed * 0x100000001B3 + epoch) & ((1 << 64) - 1)
        if self._lib is not None:
            out = np.empty(self.n_chunks, np.int32)
            rc = self._lib.tdt_data_epoch_perm(self.n_chunks, mixed, out)
            assert rc == 0
            return out
        return _py_epoch_perm(self.n_chunks, mixed)

    def gather(self, chunk_ids: np.ndarray) -> np.ndarray:
        """(len(chunk_ids), seq) int32 rows."""
        chunk_ids = np.ascontiguousarray(chunk_ids, np.int32)
        if self._lib is not None:
            out = np.empty((len(chunk_ids), self.seq), np.int32)
            # the memmap is already a C-contiguous ndarray — passing it
            # straight through keeps the corpus on disk (no copy)
            rc = self._lib.tdt_data_gather(
                self.data, len(self.data), self.seq,
                chunk_ids, len(chunk_ids), out)
            if rc != 0:
                raise IndexError(f"chunk id out of range (rc={rc})")
            return out
        n = self.n_chunks
        if (chunk_ids < 0).any() or (chunk_ids >= n).any():
            raise IndexError("chunk id out of range (rc=-2)")
        usable = self.data[:n * self.seq].reshape(n, self.seq)
        return np.asarray(usable[chunk_ids])

    def batches(self, seed: int = 0, start_batch: int = 0):
        """Infinite deterministic batch stream: shuffled epochs of
        (batch, seq) rows; a partial final batch rolls into the next
        epoch's order.

        ``start_batch`` fast-forwards the stream (permutation-index
        math only, no gathers) so a resumed run continues with exactly
        the batches the interrupted run never saw.
        """
        epoch, queue = 0, np.empty(0, np.int32)
        skip = start_batch
        while True:
            while len(queue) < self.batch:
                queue = np.concatenate(
                    [queue, self.epoch_perm(seed, epoch)])
                epoch += 1
            if skip > 0:
                skip -= 1
            else:
                yield self.gather(queue[:self.batch])
            queue = queue[self.batch:]
