"""Escape-hatch lint: no public op entry ships without a fallback.

The resilience contract (docs/resilience.md) is that EVERY public op
entry in ``ops/`` — every module-level function with an ``impl``
parameter — carries the ``@resilient`` decorator registering its XLA
reference path with the fallback router, so a new op cannot merge
without an escape hatch. This lint enforces that statically: it walks
the AST of every ``ops/*.py``, collects the public ``impl``-taking
functions, and fails unless each is either resilient-decorated or a
documented delegate of one that is.

Wired into the quick tier via tests/test_fallback_lint.py; also
runnable standalone::

    python -m triton_dist_tpu.tools.fallback_lint
"""

from __future__ import annotations

import ast
import importlib
import sys
from pathlib import Path

__all__ = ["DELEGATES", "EXCLUDED_MODULES", "missing_fallbacks", "main"]

#: Entries that intentionally carry no decorator of their own because
#: they are thin forwards into a decorated entry (the registered op
#: name on the right). The lint verifies the target op IS registered.
DELEGATES = {
    # ag_gemm(a, b) == ag_gemm_multi(a, [b]) — single-b sugar.
    "allgather_gemm.ag_gemm": "ag_gemm",
    # fp8 wire wrapper: quantize → fast_all_to_all → dequantize; the
    # custom_vjp object cannot wear the wrapper, and routing happens
    # at the inner (decorated) exchange anyway.
    "all_to_all.fast_all_to_all_fp8": "all_to_all",
}

#: Modules exempt wholesale: ``autodiff`` re-exports forward-identical
#: custom_vjp wrappers that CALL the decorated entries (double-routing
#: them would re-run the router inside its own fallback).
EXCLUDED_MODULES = {"autodiff"}


def _impl_functions(tree: ast.Module):
    """(name, has_resilient_decorator) for public module-level defs
    taking an ``impl`` parameter."""
    for node in tree.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        if node.name.startswith("_"):
            continue
        argnames = [a.arg for a in (node.args.args
                                    + node.args.kwonlyargs)]
        if "impl" not in argnames:
            continue
        decorated = False
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            name = (target.attr if isinstance(target, ast.Attribute)
                    else getattr(target, "id", None))
            if name == "resilient":
                decorated = True
        yield node.name, decorated


def missing_fallbacks() -> list[str]:
    """Entries violating the contract (empty list == clean)."""
    import triton_dist_tpu.ops as ops_pkg
    from triton_dist_tpu.resilience import registered_fallbacks

    ops_dir = Path(ops_pkg.__file__).parent
    problems: list[str] = []
    candidates: list[tuple[str, str, bool]] = []
    for py in sorted(ops_dir.glob("*.py")):
        if py.stem.startswith("_") or py.stem in EXCLUDED_MODULES:
            continue
        tree = ast.parse(py.read_text(), filename=str(py))
        for name, decorated in _impl_functions(tree):
            candidates.append((py.stem, name, decorated))

    # Import the modules so the decorators have run and the router
    # registry is populated, then cross-check both directions.
    for mod in sorted({m for m, _, _ in candidates}):
        importlib.import_module(f"triton_dist_tpu.ops.{mod}")
    registered = registered_fallbacks()
    entry_to_op = {spec.entry.rsplit("triton_dist_tpu.ops.", 1)[-1]: op
                   for op, spec in registered.items()}

    for mod, name, decorated in candidates:
        qual = f"{mod}.{name}"
        if decorated:
            if qual not in entry_to_op:
                problems.append(
                    f"{qual}: @resilient present in source but no "
                    f"registration reached the router (import-order "
                    f"or decorator bug?)")
            continue
        delegate_op = DELEGATES.get(qual)
        if delegate_op is None:
            problems.append(
                f"{qual}: public op entry with an impl= parameter but "
                f"no @resilient decorator and no DELEGATES entry — "
                f"every op needs an XLA escape hatch "
                f"(docs/resilience.md)")
        elif delegate_op not in registered:
            problems.append(
                f"{qual}: delegates to op {delegate_op!r}, which is "
                f"not registered with the fallback router")
    return problems


def main(argv=None) -> int:
    problems = missing_fallbacks()
    if problems:
        print("fallback lint FAILED:")
        for p in problems:
            print(f"  - {p}")
        return 1
    from triton_dist_tpu.resilience import registered_fallbacks
    n = len(registered_fallbacks())
    print(f"fallback lint OK: {n} ops registered, no uncovered entries")
    return 0


if __name__ == "__main__":
    sys.exit(main())
