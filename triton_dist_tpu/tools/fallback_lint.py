"""Escape-hatch lint — DEPRECATION SHIM.

The check lives in the static-analysis framework now
(``triton_dist_tpu.analysis.lint_fallback``, run by
``python -m triton_dist_tpu.tools.tdt_check`` as the
``fallback-coverage`` pass, with ``file:line``-anchored findings).
This module keeps the original entry points working::

    python -m triton_dist_tpu.tools.fallback_lint

``missing_fallbacks()`` returns the same message strings it always
did; prefer the pass API (findings with anchors) in new code.
"""

from __future__ import annotations

import sys
import warnings

from triton_dist_tpu.analysis.lint_fallback import (  # noqa: F401
    DELEGATES, EXCLUDED_MODULES, collect_findings)

__all__ = ["DELEGATES", "EXCLUDED_MODULES", "missing_fallbacks", "main"]


def _deprecation():
    warnings.warn(
        "tools.fallback_lint is a deprecation shim: the check lives "
        "in the static-analysis framework — run `tdt-check --pass "
        "fallback-coverage` (python -m triton_dist_tpu.tools."
        "tdt_check) for file:line-anchored findings",
        DeprecationWarning, stacklevel=3)


def missing_fallbacks() -> list:
    """Entries violating the contract (empty list == clean)."""
    _deprecation()
    return [f.message for f in collect_findings()]


def main(argv=None) -> int:
    problems = missing_fallbacks()
    if problems:
        print("fallback lint FAILED:")
        for p in problems:
            print(f"  - {p}")
        return 1
    from triton_dist_tpu.resilience import registered_fallbacks
    n = len(registered_fallbacks())
    print(f"fallback lint OK: {n} ops registered, no uncovered entries")
    return 0


if __name__ == "__main__":
    sys.exit(main())
