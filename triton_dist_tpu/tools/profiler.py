"""Profiling helpers: per-host traces with rank-0 collection.

TPU-native redesign of the reference's tracing subsystem
(python/triton_dist/utils.py: ``group_profile`` context manager :505-592
writing per-rank chrome traces and merging them on rank 0 via
``gather_object`` + ``_merge_json``; ``get_torch_prof_ctx`` :262). On TPU
the tracer is ``jax.profiler`` (XPlane/TensorBoard): each host writes its
own trace under ``<dir>/<name>/host<idx>/``; the merge step of the
reference collapses to pointing TensorBoard/xprof at the shared
directory, which overlays all hosts' timelines.
"""

from __future__ import annotations

import contextlib
import glob
import os

import jax


@contextlib.contextmanager
def group_profile(name: str = "trace", out_dir: str = "/tmp/tdt_profile",
                  enabled: bool = True):
    """Profile the enclosed region on every host (reference
    ``group_profile`` utils.py:505)."""
    if not enabled:
        yield None
        return
    path = os.path.join(out_dir, name, f"host{jax.process_index()}")
    os.makedirs(path, exist_ok=True)
    with jax.profiler.trace(path):
        yield path


def trace_files(name: str = "trace",
                out_dir: str = "/tmp/tdt_profile") -> list[str]:
    """List the collected per-host trace artifacts (the reference merges
    into one JSON; xprof reads the directory tree directly)."""
    pattern = os.path.join(out_dir, name, "host*", "**", "*")
    return sorted(p for p in glob.glob(pattern, recursive=True)
                  if os.path.isfile(p))


@contextlib.contextmanager
def annotate(label: str):
    """Named region inside a trace (reference launch_metadata hooks,
    allgather_gemm.py:145-155)."""
    with jax.profiler.TraceAnnotation(label):
        yield


# The Engine's decode-loop profile window (reference engine.py:153-179)
# lives in models/engine.py: construct Engine(profile_dir=...,
# profile_steps=...) and the first N decode steps of each serve() are
# traced per-host via group_profile("engine_decode", profile_dir).
