"""Profiling helpers: per-host traces with rank-0 collection.

TPU-native redesign of the reference's tracing subsystem
(python/triton_dist/utils.py: ``group_profile`` context manager :505-592
writing per-rank chrome traces and merging them on rank 0 via
``gather_object`` + ``_merge_json``; ``get_torch_prof_ctx`` :262). On TPU
the tracer is ``jax.profiler`` (XPlane/TensorBoard): each host writes its
own trace under ``<dir>/<name>/host<idx>/``; the merge step of the
reference collapses to pointing TensorBoard/xprof at the shared
directory, which overlays all hosts' timelines.
"""

from __future__ import annotations

import contextlib
import glob
import os

import jax


@contextlib.contextmanager
def group_profile(name: str = "trace", out_dir: str = "/tmp/tdt_profile",
                  enabled: bool = True):
    """Profile the enclosed region on every host (reference
    ``group_profile`` utils.py:505)."""
    if not enabled:
        yield None
        return
    path = os.path.join(out_dir, name, f"host{jax.process_index()}")
    os.makedirs(path, exist_ok=True)
    with jax.profiler.trace(path):
        yield path


def trace_files(name: str = "trace",
                out_dir: str = "/tmp/tdt_profile") -> list[str]:
    """List the collected per-host trace artifacts (the reference merges
    into one JSON; xprof reads the directory tree directly)."""
    pattern = os.path.join(out_dir, name, "host*", "**", "*")
    return sorted(p for p in glob.glob(pattern, recursive=True)
                  if os.path.isfile(p))


@contextlib.contextmanager
def annotate(label: str):
    """Named region inside a trace (reference launch_metadata hooks,
    allgather_gemm.py:145-155)."""
    with jax.profiler.TraceAnnotation(label):
        yield


def decode_profile_hook(engine, steps: int = 64, name: str = "decode",
                        out_dir: str = "/tmp/tdt_profile"):
    """Profile N decode steps of an Engine (reference engine.py:153-179
    64-step decode profile). Returns the trace dir."""
    import jax.numpy as jnp

    with group_profile(name, out_dir) as path:
        params = getattr(engine, "_profile_params")
        caches = engine.kv.init()
        token = jnp.zeros((engine.kv.batch,), jnp.int32)
        if engine._decode_step is None:
            engine._decode_step = engine._build_decode_step()
        key = jax.random.PRNGKey(0)
        for s in range(steps):
            token, caches = engine._decode_step(
                params, caches, token, jnp.int32(s), key)
        jax.block_until_ready(token)
    return path
