"""Profiling helpers: per-host traces with rank-0 collection.

TPU-native redesign of the reference's tracing subsystem
(python/triton_dist/utils.py: ``group_profile`` context manager :505-592
writing per-rank chrome traces and merging them on rank 0 via
``gather_object`` + ``_merge_json``; ``get_torch_prof_ctx`` :262). On TPU
the tracer is ``jax.profiler`` (XPlane/TensorBoard): each host writes its
own trace under ``<dir>/<name>/host<idx>/``; the merge step of the
reference collapses to pointing TensorBoard/xprof at the shared
directory, which overlays all hosts' timelines — or, since the
device-time truth layer (``obs.devprof``), to parsing the capture back
into measured per-op metrics (``tools/profile_export.py``) and
overlaying it into the host Perfetto dump
(``tools/trace_export.py --merge-profile``).

Each capture is counted through obs (``profile.captures`` /
``profile.capture_ms``) and leaves a ``tdt_capture.json`` anchor in its
artifact dir — the wall-clock instant the profiler session started, so
the capture's session-relative timestamps can be placed on the same
clock as ``obs.trace``'s wall-anchored events.
"""

from __future__ import annotations

import contextlib
import glob
import json
import os
import time

import jax

from triton_dist_tpu import obs


class Capture(str):
    """A ``group_profile`` artifact handle: the capture DIRECTORY as a
    plain string (back-compatible with every ``os.path`` consumer),
    plus the structured fields callers previously had to re-derive.

    Attributes: ``path`` (== str(self)), ``name`` (the capture name),
    ``host`` (process index), ``t0_unix`` (wall clock at session
    start — the overlay anchor, also persisted as
    ``tdt_capture.json``)."""

    path: str
    name: str
    host: int
    t0_unix: float

    def __new__(cls, path: str, name: str, host: int, t0_unix: float):
        self = super().__new__(cls, path)
        self.path = str(path)
        self.name = name
        self.host = host
        self.t0_unix = t0_unix
        return self


@contextlib.contextmanager
def group_profile(name: str = "trace", out_dir: str = "/tmp/tdt_profile",
                  enabled: bool = True):
    """Profile the enclosed region on every host (reference
    ``group_profile`` utils.py:505). Yields a :class:`Capture` (the
    artifact dir, str-compatible); counts ``profile.captures`` and the
    capture wall time into ``profile.capture_ms``."""
    if not enabled:
        yield None
        return
    host = jax.process_index()
    path = os.path.join(out_dir, name, f"host{host}")
    os.makedirs(path, exist_ok=True)
    t0p = time.perf_counter()
    with jax.profiler.trace(path):
        # Anchor INSIDE the session: capture timestamps are relative
        # to profiler start, so the closest wall-clock reading wins.
        t0 = time.time()
        cap = Capture(path, name, host, t0)
        try:
            with open(os.path.join(path, "tdt_capture.json"), "w") as f:
                json.dump({"name": name, "host": host, "t0_unix": t0,
                           "pid": os.getpid()}, f)
        except OSError:
            pass       # the anchor is an overlay nicety, not a gate
        try:
            yield cap
        finally:
            obs.counter("profile.captures").inc()
            obs.histogram("profile.capture_ms").observe(
                (time.perf_counter() - t0p) * 1e3)


def trace_files(name: str = "trace",
                out_dir: str = "/tmp/tdt_profile") -> list[str]:
    """List the collected per-host trace artifacts (the reference merges
    into one JSON; xprof reads the directory tree directly)."""
    pattern = os.path.join(out_dir, name, "host*", "**", "*")
    return sorted(p for p in glob.glob(pattern, recursive=True)
                  if os.path.isfile(p))


@contextlib.contextmanager
def annotate(label: str):
    """Named region inside a trace (reference launch_metadata hooks,
    allgather_gemm.py:145-155). The ``device.<op>.<branch>`` /
    ``device.step`` labels the router and pump sampler plant this way
    are what ``obs.devprof`` keys its measured attribution on."""
    with jax.profiler.TraceAnnotation(label):
        yield


# The Engine's decode-loop profile window (reference engine.py:153-179)
# lives in models/engine.py: construct Engine(profile_dir=...,
# profile_steps=...) and the first N decode steps of each serve() are
# traced per-host via group_profile("engine_decode", profile_dir).
