"""Render bench / sweep JSON into markdown tables.

Consumes ``bench.py``'s one-line JSON (``--bench``) and/or
``tools/bench_ops.py`` JSONL (``--sweep``); the reference publishes its
numbers as rendered tables (README.md:96-205) — this is the generator
for ours.

Usage:
    python -m triton_dist_tpu.tools.report --bench BENCH_r03.json
    python -m triton_dist_tpu.tools.report --sweep sweep.jsonl
"""

from __future__ import annotations

import argparse
import json
import sys


def render_bench(d: dict) -> str:
    ex = dict(d.get("extras", {}))
    telemetry = ex.pop("telemetry", None)
    if d.get("prior_value") is not None:
        # Probe-failure lines keep value null and carry the last good
        # run under explicitly-prior fields — render those, not a
        # "None ... (vs_baseline None)" head.
        prov = d.get("from_prior_run", {})
        head = (f"**{d.get('metric')}** = (this run measured nothing) "
                f"— prior_value {d['prior_value']} {d.get('unit', '')} "
                f"(prior_vs_baseline {d.get('prior_vs_baseline')}, "
                f"age {prov.get('age_s', '?')}s, "
                f"{prov.get('path', '?')})")
    else:
        head = (f"**{d.get('metric')}** = {d.get('value')} "
                f"{d.get('unit', '')} "
                f"(vs_baseline {d.get('vs_baseline')})")
    lines = [head, ""]
    groups: dict[str, dict] = {}
    for k, v in ex.items():
        op = k.split("_")[0] if "_" in k else k
        groups.setdefault(op, {})[k] = v
    lines.append("| key | value |")
    lines.append("|---|---|")
    for op in sorted(groups):
        for k in sorted(groups[op]):
            lines.append(f"| {k} | {groups[op][k]} |")
    if telemetry:
        lines += ["", render_telemetry(telemetry)]
    return "\n".join(lines)


_BREAKER_STATES = {0: "closed", 1: "OPEN", 2: "half-open"}


def render_resilience(snap: dict) -> str:
    """Summarize the ``resilience.*`` metrics (docs/resilience.md):
    breaker states decoded to words, fallback totals by op and reason,
    watchdog trips, known-bad cache size. Empty string when the
    snapshot carries no resilience metrics."""
    counters = {k: v for k, v in snap.get("counters", {}).items()
                if k.startswith("resilience.")}
    gauges = {k: v for k, v in snap.get("gauges", {}).items()
              if k.startswith("resilience.")}
    if not counters and not gauges:
        return ""
    lines = ["#### resilience", "| metric | value |", "|---|---|"]
    for k in sorted(gauges):
        v = gauges[k]
        if k.endswith(".breaker_state"):
            v = _BREAKER_STATES.get(int(v), v)
        else:
            v = int(v) if float(v) == int(v) else round(float(v), 4)
        lines.append(f"| {k} | {v} |")
    for k in sorted(counters):
        v = counters[k]
        lines.append(f"| {k} | {int(v) if float(v) == int(v) else v} |")
    return "\n".join(lines)


def render_serving(snap: dict) -> str:
    """Summarize the continuous-batching scheduler's ``serving.*``
    metrics (docs/serving.md "Scheduler"): queue depth / batch
    occupancy gauges, admitted / retired / backpressure counters, and
    TTFT + queue-wait percentiles interpolated from the snapshot
    histograms. Empty string when the snapshot carries no serving
    metrics (a scheduler-less process)."""
    counters = {k: v for k, v in snap.get("counters", {}).items()
                if k.startswith("serving.")}
    gauges = {k: v for k, v in snap.get("gauges", {}).items()
              if k.startswith("serving.")}
    hists = {k: h for k, h in snap.get("histograms", {}).items()
             if k.startswith("serving.")}
    if not counters and not gauges and not hists:
        return ""
    from triton_dist_tpu.obs import histogram_quantile
    lines = ["#### serving", "| metric | value |", "|---|---|"]
    for k in sorted(gauges):
        v = gauges[k]
        lines.append(f"| {k} | "
                     f"{int(v) if float(v) == int(v) else round(v, 4)} |")
    for k in sorted(counters):
        v = counters[k]
        lines.append(f"| {k} | "
                     f"{int(v) if float(v) == int(v) else v} |")
    for k in sorted(hists):
        h = hists[k]
        p50 = histogram_quantile(h, 0.50)
        p99 = histogram_quantile(h, 0.99)
        lines.append(
            f"| {k} | n={h.get('count', 0)} "
            f"p50={round(p50, 3) if p50 is not None else '-'} "
            f"p99={round(p99, 3) if p99 is not None else '-'} "
            f"max={h.get('max')} |")
    return "\n".join(lines)


def render_kv(snap: dict) -> str:
    """Summarize the paged block pool + prefix cache (``kv.*`` metrics,
    docs/observability.md "KV block pool"): occupancy gauges
    (free / cached / active / utilization) and the eviction counter.
    The prefix-cache hit metrics live under ``serving.*`` and render in
    that section. Empty string for processes without a paged pool."""
    counters = {k: v for k, v in snap.get("counters", {}).items()
                if k.startswith("kv.")}
    gauges = {k: v for k, v in snap.get("gauges", {}).items()
              if k.startswith("kv.")}
    if not counters and not gauges:
        return ""
    lines = ["#### kv block pool", "| metric | value |", "|---|---|"]
    for k in sorted(gauges):
        v = gauges[k]
        lines.append(f"| {k} | "
                     f"{int(v) if float(v) == int(v) else round(v, 4)} |")
    for k in sorted(counters):
        v = counters[k]
        lines.append(f"| {k} | "
                     f"{int(v) if float(v) == int(v) else v} |")
    return "\n".join(lines)


def render_disagg(snap: dict) -> str:
    """Summarize the disaggregated prefill/decode plane (``disagg.*``
    metrics, docs/serving.md "Disaggregated prefill/decode"): stream
    counters — handoffs, blocks shipped/deduped by transport tier,
    fallbacks, severed streams — plus handoff-latency percentiles
    interpolated from the ``disagg.handoff_ms`` histogram and the
    end-to-end dedup ratio. Empty string when the snapshot carries no
    disagg metrics (a unified fleet)."""
    counters = {k: v for k, v in snap.get("counters", {}).items()
                if k.startswith("disagg.")}
    hists = {k: h for k, h in snap.get("histograms", {}).items()
             if k.startswith("disagg.")}
    if not counters and not hists:
        return ""
    from triton_dist_tpu.obs import histogram_quantile
    lines = ["#### disagg", "| metric | value |", "|---|---|"]
    for k in sorted(counters):
        v = counters[k]
        lines.append(f"| {k} | "
                     f"{int(v) if float(v) == int(v) else v} |")
    for k in sorted(hists):
        h = hists[k]
        p50 = histogram_quantile(h, 0.50)
        p99 = histogram_quantile(h, 0.99)
        lines.append(
            f"| {k} | n={h.get('count', 0)} "
            f"p50={round(p50, 3) if p50 is not None else '-'} "
            f"p99={round(p99, 3) if p99 is not None else '-'} "
            f"max={h.get('max')} |")
    offered = counters.get("disagg.blocks_offered")
    if offered:
        lines.append(
            f"| dedup ratio | "
            f"{round(counters.get('disagg.blocks_deduped', 0) / offered, 4)} |")
    return "\n".join(lines)


def render_fleet(merged: dict | None) -> str:
    """Summarize a fleet-merged snapshot (``obs.fleet.
    merge_fleet_snapshots`` — bench.py's ``serving_fleet`` part embeds
    one under ``extras.telemetry.fleet``; docs/observability.md
    "Fleet view"): the replica roster, per-replica queue/occupancy/
    rolling-p99 rows, and the fleet rollup with BUCKET-MERGED TTFT/
    TPOT percentiles. Empty string when no merged snapshot is
    present."""
    if not merged or not merged.get("replicas"):
        return ""
    per = merged.get("per_replica", {})
    lines = ["#### fleet",
             f"replicas: {', '.join(merged['replicas'])}", "",
             "| replica | queue | occupancy | rolling ttft p99 | "
             "rolling tpot p99 | admitted | retired |",
             "|---|---|---|---|---|---|---|"]
    for rid in merged["replicas"]:
        g = per.get(rid, {}).get("gauges", {})
        c = per.get(rid, {}).get("counters", {})

        def _v(x):
            return "-" if x is None else (
                int(x) if float(x) == int(x) else round(float(x), 3))

        lines.append(
            f"| {rid} | {_v(g.get('serving.queue_depth'))} | "
            f"{_v(g.get('serving.batch_occupancy'))} | "
            f"{_v(g.get('serving.rolling.ttft_p99_ms'))} | "
            f"{_v(g.get('serving.rolling.tpot_p99_ms'))} | "
            f"{_v(c.get('serving.admitted'))} | "
            f"{_v(c.get('serving.retired'))} |")
    from triton_dist_tpu.obs.fleet import merged_percentiles
    fleet_bits = []
    for label, p in merged_percentiles(merged.get("histograms")).items():
        p50, p99 = p["p50"], p["p99"]
        fleet_bits.append(
            f"{label} p50={round(p50, 3) if p50 is not None else '-'}"
            f" p99={round(p99, 3) if p99 is not None else '-'}"
            f" (n={p['n']}, bucket-merged)")
    c = merged.get("counters", {})
    if "serving.retired" in c:
        fleet_bits.append(f"retired={int(c['serving.retired'])}")
    if fleet_bits:
        lines += ["", "fleet rollup: " + "  ".join(fleet_bits)]
    return "\n".join(lines)


def render_router(status: dict | None) -> str:
    """Summarize a router-status payload (``RouterServer.status()`` —
    the ``serving_router`` bench embeds one under
    ``extras.telemetry.router``; a router's ``{"cmd": "metrics"}``
    snapshot carries it under ``router``): per-replica placement rows
    with the router's breaker / in-flight / draining dimension, plus
    the failover and shed counters a failover postmortem reads first
    (docs/serving.md "Router"). Empty string when absent."""
    if not status or not status.get("replicas"):
        return ""
    placements = status.get("placements") or {}
    lines = ["#### router",
             "| replica | status | breaker | inflight | draining | "
             "score | placed |", "|---|---|---|---|---|---|---|"]
    for r in status["replicas"]:
        rid = r.get("replica_id") or r.get("endpoint") or "?"
        placed = (placements.get(r.get("endpoint"))
                  or placements.get(rid) or 0)
        lines.append(
            f"| {rid} | {r.get('status')} | {r.get('breaker')} | "
            f"{r.get('inflight')} | "
            f"{'yes' if r.get('draining') else '-'} | "
            f"{r.get('score')} | {int(placed)} |")
    # EVERY router counter renders here: render_telemetry suppresses
    # router.* from the generic table when this section exists, so a
    # counter skipped here (retries_exhausted, poll_errors, ...)
    # would be invisible in the postmortem — the opposite of what the
    # section is for (review finding).
    c = status.get("counters") or {}
    bits = [f"{k.split('.', 1)[1]}={int(c[k])}" for k in sorted(c)]
    if bits:
        lines += ["", "router counters: " + "  ".join(bits)]
    hop = status.get("failover_sample")
    if hop:
        # One stitched failover: this trace ID spans the dead
        # replica's admit, the router's failover instant, and the
        # answering replica's retire in the flight record.
        lines += ["", f"failover sample: trace_id={hop.get('trace_id')}"
                      f"  failovers={hop.get('failovers')}"
                      f"  answered_by={hop.get('replica')}"]
    return "\n".join(lines)


def render_tracing(stats: dict | None) -> str:
    """Summarize the event-tracing / flight-recorder state
    (``obs.trace.stats()``, carried under the snapshot's ``trace`` key
    by the server metrics command and bench extras —
    docs/observability.md "Tracing"): events captured, events dropped
    to ring overwrites, and the last flight-record path so a
    postmortem reader knows which file to open in Perfetto. Empty
    string when the payload carries no tracing stats."""
    if not stats:
        return ""
    lines = ["#### tracing", "| metric | value |", "|---|---|"]
    for k in ("events_total", "dropped_total", "tracks",
              "ring_capacity", "ring_high_water", "flight_dumps"):
        if k in stats:
            lines.append(f"| {k} | {stats[k]} |")
    if stats.get("last_flight_record"):
        lines.append(
            f"| last_flight_record | {stats['last_flight_record']} |")
    if stats.get("dropped_total"):
        # An undersized TDT_TRACE_RING silently truncates every flight
        # record's window; surface it where the numbers are read
        # instead of only inside a dump.
        lines.append(
            f"\n⚠ {int(stats['dropped_total'])} trace events were "
            f"overwritten before export — the flight-recorder window "
            f"is truncated; raise TDT_TRACE_RING "
            f"(capacity {stats.get('ring_capacity', '?')}, high water "
            f"{stats.get('ring_high_water', '?')}).")
    return "\n".join(lines)


def render_waterfalls(wf: dict | None) -> str:
    """Render sampled request-attribution waterfalls (``obs.attrib``
    records bench.py embeds under ``extras.telemetry.waterfalls``):
    where one request's TTFT went — queue vs prefill vs decode — next
    to the aggregate numbers."""
    if not wf:
        return ""
    lines = ["#### request waterfalls",
             "| part | total_ms | queue_wait | prefill | decode | "
             "tokens | cached |", "|---|---|---|---|---|---|---|"]
    for part in sorted(wf):
        r = wf[part] or {}
        seg = r.get("segments", {})
        lines.append(
            f"| {part} | {r.get('total_ms')} | "
            f"{seg.get('queue_wait_ms')} | {seg.get('prefill_ms')} | "
            f"{seg.get('decode_ms')} | {r.get('tokens')} | "
            f"{r.get('cached_tokens')} |")
    return "\n".join(lines)


def render_history(hist: dict | None) -> str:
    """Summarize a sampled-history snapshot (``obs.history`` — the
    ``serving_history`` bench embeds one under
    ``extras.telemetry.history``; docs/observability.md "History
    plane"): per-series stats with a unicode sparkline, plus every
    retained early-warning excerpt. Empty string when no series were
    sampled."""
    if not hist or not hist.get("series"):
        return ""
    from triton_dist_tpu.obs.history import sparkline, window_stats
    lines = ["#### history",
             "| series | n | last | min | max | avg | trend |",
             "|---|---|---|---|---|---|---|"]

    def _v(x):
        return "-" if x is None else (
            int(x) if float(x) == int(x) else round(float(x), 4))

    for name in sorted(hist["series"]):
        s = hist["series"][name] or {}
        pts = s.get("points") or []
        st = window_stats(pts)
        if not st.get("n"):
            continue
        lines.append(
            f"| {name} | {s.get('n', st['n'])} | {_v(st['last'])} | "
            f"{_v(st['min'])} | {_v(st['max'])} | {_v(st['avg'])} | "
            f"{sparkline([v for _, v in pts], width=20)} |")
    for w in hist.get("warnings") or []:
        lines.append(
            f"\n⚠ history.warning: {w.get('detector', '?')} detector "
            f"on `{w.get('metric', '?')}` "
            f"({w.get('op', '?')} {_v(w.get('threshold'))} over "
            f"{_v(w.get('window_s'))} s).")
    return "\n".join(lines)


def render_devprof(snap: dict, stats: dict | None = None) -> str:
    """Summarize the device-time truth layer (``obs.devprof``,
    docs/observability.md "Device-time truth"): measured per-op
    compute/comm attribution and overlap, drift vs the dispatch-time
    model gauge, unlabeled device time, capture counts, and the last
    parsed profile artifact path. Empty string when the snapshot holds
    no ``device.*`` gauges and no devprof stats."""
    gauges = snap.get("gauges", {})
    counters = snap.get("counters", {})
    dev = {k: v for k, v in gauges.items() if k.startswith("device.")}
    meas = {k: v for k, v in gauges.items()
            if k.startswith("comms.") and ("_measured" in k
                                           or k.endswith("_drift_pct"))}
    prof = {k: v for k, v in counters.items()
            if k.startswith("profile.")}
    if not dev and not meas and not prof and not stats:
        return ""
    lines = ["#### device time (measured)", "| metric | value |",
             "|---|---|"]
    for k in sorted(dev) + sorted(meas):
        v = gauges[k]
        lines.append(f"| {k} | "
                     f"{int(v) if float(v) == int(v) else round(v, 4)} |")
    for k in sorted(prof):
        v = counters[k]
        lines.append(f"| {k} | {int(v) if float(v) == int(v) else v} |")
    if stats:
        if stats.get("last_profile"):
            lines.append(f"| last_profile | {stats['last_profile']} "
                         f"({stats.get('last_reason', '?')}) |")
        if stats.get("armed"):
            lines.append(f"| armed | {stats['armed']} |")
    if dev.get("device.unlabeled_ms"):
        # Nonzero unlabeled time means execution ran outside every
        # device.<op> window — the annotation-coverage pass guards the
        # label plumbing; surface it where the numbers are read.
        lines.append(
            f"\n⚠ {round(float(dev['device.unlabeled_ms']), 3)} ms of "
            f"device/runtime execution was attributed to NO "
            f"device.<op> label (see tdt-check annotation-coverage).")
    return "\n".join(lines)


def render_telemetry(snap: dict) -> str:
    """Render an obs snapshot (bench ``extras.telemetry`` / server
    ``{"cmd": "metrics"}`` payload — docs/observability.md) as
    markdown: one counters/gauges table, one histogram summary table,
    plus dedicated resilience and tracing sections when those exist."""
    lines = ["### telemetry"]
    resil = render_resilience(snap)
    serving = render_serving(snap)
    kv = render_kv(snap)
    disagg = render_disagg(snap)
    fleet = render_fleet(snap.get("fleet"))
    router = render_router(snap.get("router"))
    tracing = render_tracing(snap.get("trace"))
    devprof = render_devprof(snap, snap.get("devprof"))
    waterfalls = render_waterfalls(snap.get("waterfalls"))
    history = render_history(snap.get("history"))
    # trace.* gauges mirror what the tracing section already shows
    # (they exist for the Prometheus exposition path) — don't render
    # the same numbers twice when that section is present; ditto the
    # serving.* / kv.* metrics and their dedicated sections.
    skip = lambda k: (k.startswith("resilience.")  # noqa: E731
                      or (bool(serving) and k.startswith("serving."))
                      or (bool(kv) and k.startswith("kv."))
                      or (bool(disagg) and k.startswith("disagg."))
                      or (bool(tracing) and k.startswith("trace."))
                      or (bool(devprof)
                          and (k.startswith("device.")
                               or k.startswith("profile.")
                               or (k.startswith("comms.")
                                   and ("_measured" in k
                                        or k.endswith("_drift_pct"))))))
    # The router section renders every router.* COUNTER itself;
    # router gauges/histograms are not in its payload and stay in the
    # generic tables below.
    scalars = [("counter", k, v)
               for k, v in sorted(snap.get("counters", {}).items())
               if not skip(k)
               and not (bool(router) and k.startswith("router."))]
    scalars += [("gauge", k, v)
                for k, v in sorted(snap.get("gauges", {}).items())
                if not skip(k)]
    if resil:
        lines += [resil, ""]
    if serving:
        lines += [serving, ""]
    if kv:
        lines += [kv, ""]
    if disagg:
        lines += [disagg, ""]
    if fleet:
        lines += [fleet, ""]
    if router:
        lines += [router, ""]
    if tracing:
        lines += [tracing, ""]
    if devprof:
        lines += [devprof, ""]
    if waterfalls:
        lines += [waterfalls, ""]
    if history:
        lines += [history, ""]
    if scalars:
        lines += ["| metric | type | value |", "|---|---|---|"]
        for kind, k, v in scalars:
            vv = int(v) if float(v) == int(v) else round(float(v), 4)
            lines.append(f"| {k} | {kind} | {vv} |")
    hists = {k: h for k, h in snap.get("histograms", {}).items()
             if not skip(k)}
    if hists:
        lines += ["", "| histogram | count | mean | min | max |",
                  "|---|---|---|---|---|"]
        for k in sorted(hists):
            h = hists[k]
            n = h.get("count", 0)
            mean = round(h["sum"] / n, 4) if n else None
            lines.append(
                f"| {k} | {n} | {mean} | {h.get('min')} | "
                f"{h.get('max')} |")
    if len(lines) == 1:
        lines.append("(empty)")
    return "\n".join(lines)


def render_sweep(rows: list[dict]) -> str:
    if not rows:
        return "(no rows)"
    out = []
    by_op: dict[str, list] = {}
    for r in rows:
        by_op.setdefault(r.get("op", "?"), []).append(r)
    for op, rs in sorted(by_op.items()):
        cols = [c for c in rs[0] if c != "op"]
        out.append(f"### {op}")
        out.append("| " + " | ".join(cols) + " |")
        out.append("|" + "---|" * len(cols))
        for r in rs:
            out.append("| " + " | ".join(str(r.get(c, "")) for c in cols)
                       + " |")
        out.append("")
    return "\n".join(out)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--bench", default=None)
    ap.add_argument("--sweep", default=None)
    args = ap.parse_args(argv)
    if not (args.bench or args.sweep):
        ap.error("need --bench and/or --sweep")
    if args.bench:
        with open(args.bench) as f:
            d = json.load(f)
        if "metric" not in d and "tail" in d:
            # driver BENCH_r{N}.json wraps the emitted line in `tail`
            line = [ln for ln in d["tail"].splitlines()
                    if ln.startswith("{")]
            d = json.loads(line[-1]) if line else d
        print(render_bench(d))
    if args.sweep:
        rows = []
        with open(args.sweep) as f:
            for line in f:
                line = line.strip()
                if line:
                    rows.append(json.loads(line))
        print(render_sweep(rows))
    return 0


if __name__ == "__main__":
    sys.exit(main())
