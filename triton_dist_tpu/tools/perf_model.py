"""Speed-of-light performance models for TPU compute and ICI collectives.

TPU-native redesign of the reference's perf models
(python/triton_dist/kernels/nvidia/gemm_perf_model.py:232
``estimate_gemm_sol_time_ms`` and comm_perf_model.py:94-116
``estimate_all_gather_time_ms`` / ``estimate_reduce_scatter_time_ms``
against probed NVLink/PCIe bandwidth). The reference budgets SMs between
GEMM and comm with these; on TPU the analog decision is whether overlap
is compute- or bandwidth-bound per shape (``overlap_efficiency``), which
drives method choice (e.g. ring vs one-shot, ops/allgather.py).

Chip tables are public-spec numbers; ``probe_*`` measure the live system
(the analog of the reference's topology probes utils.py:823-967).
"""

from __future__ import annotations

import dataclasses

import jax


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    name: str
    bf16_tflops: float          # MXU peak, bf16
    hbm_gbps: float             # HBM bandwidth GB/s
    ici_gbps_per_link: float    # per-direction per-link ICI GB/s
    ici_links: int              # torus links per chip


# Public-spec table (order matters: first matching substring wins).
# "lite" keys first: real device_kind strings are e.g. "TPU v5 lite" /
# "TPU v6 lite", which no bare "v5e"/"v6e" substring matches — missing
# them would silently select the cpu-sim spec on the bench chip.
CHIP_SPECS = {
    "v5 lite": ChipSpec("v5e", 197.0, 819.0, 50.0, 4),
    "v6 lite": ChipSpec("v6e", 918.0, 1640.0, 100.0, 4),
    "v6": ChipSpec("v6e", 918.0, 1640.0, 100.0, 4),
    "v5p": ChipSpec("v5p", 459.0, 2765.0, 100.0, 6),
    "v5e": ChipSpec("v5e", 197.0, 819.0, 50.0, 4),
    "v4": ChipSpec("v4", 275.0, 1228.0, 50.0, 6),
    "cpu": ChipSpec("cpu-sim", 1.0, 50.0, 10.0, 2),
}


def get_chip_spec(device=None) -> ChipSpec:
    """Identify the local chip (reference topology probes)."""
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "cpu").lower()
    for key, spec in CHIP_SPECS.items():
        if key in kind:
            return spec
    return CHIP_SPECS["cpu"]


def estimate_gemm_sol_time_ms(m: int, n: int, k: int,
                              spec: ChipSpec | None = None,
                              dtype_bytes: int = 2) -> float:
    """max(FLOP-bound, HBM-bound) GEMM time (reference
    gemm_perf_model.py:232)."""
    spec = spec or get_chip_spec()
    flops = 2.0 * m * n * k
    t_flops = flops / (spec.bf16_tflops * 1e12)
    bytes_moved = dtype_bytes * (m * k + k * n + m * n)
    t_mem = bytes_moved / (spec.hbm_gbps * 1e9)
    return max(t_flops, t_mem) * 1e3


# Fixed costs per DMA/step (ICI hop launch + semaphore signalling). These
# are what make small payloads latency-bound and large ones
# bandwidth-bound — the axis every AUTO crossover below turns on (the
# reference's analog constants live in its probed bandwidth tables,
# comm_perf_model.py:94-116).
DMA_STARTUP_US = 2.0
ICI_HOP_LATENCY_US = 1.0


def _ring_time_s(nbytes_per_rank: int, world: int, link_gbps: float,
                 n_hops: int) -> float:
    return (nbytes_per_rank * n_hops) / (link_gbps * 1e9)


def estimate_all_gather_time_ms(nbytes_per_rank: int, world: int,
                                spec: ChipSpec | None = None,
                                bidir: bool = True) -> float:
    """Ring AG over ICI: (w-1) hops of the shard per direction plus
    per-step fixed costs (reference comm_perf_model.py:94)."""
    spec = spec or get_chip_spec()
    if world <= 1:
        return 0.0
    hops = (world - 1 + 1) // 2 if bidir else world - 1
    bw = _ring_time_s(nbytes_per_rank, world, spec.ici_gbps_per_link, hops)
    fixed = hops * (DMA_STARTUP_US + ICI_HOP_LATENCY_US) * 1e-6
    return (bw + fixed) * 1e3


def estimate_full_mesh_push_time_ms(nbytes_per_rank: int, world: int,
                                    spec: ChipSpec | None = None) -> float:
    """Full-mesh push AG: one logical hop (all w-1 puts launch at once),
    but non-neighbor puts traverse the torus (mean distance ~w/4 on a
    ring), consuming through-bandwidth on intermediate links."""
    spec = spec or get_chip_spec()
    if world <= 1:
        return 0.0
    avg_hops = max(world / 4.0, 1.0)
    # A 1-D gather axis owns 2 of the chip's links (one per direction);
    # every put occupies avg_hops link-segments of that capacity.
    bw = 2.0 * spec.ici_gbps_per_link
    t = nbytes_per_rank * (world - 1) * avg_hops / (bw * 1e9)
    fixed = (DMA_STARTUP_US + avg_hops * ICI_HOP_LATENCY_US) * 1e-6
    return (t + fixed) * 1e3


def estimate_reduce_scatter_time_ms(nbytes_per_rank: int, world: int,
                                    spec: ChipSpec | None = None,
                                    bidir: bool = True) -> float:
    """Ring RS ≙ AG mirror (reference comm_perf_model.py:116)."""
    return estimate_all_gather_time_ms(nbytes_per_rank, world, spec, bidir)


def estimate_one_shot_reduce_time_ms(nbytes_per_chunk: int, world: int,
                                     spec: ChipSpec | None = None) -> float:
    """One-shot RS/AR gather phase: every peer pushes its contribution
    directly (full-mesh), then a local w-way sum (HBM-bound)."""
    spec = spec or get_chip_spec()
    if world <= 1:
        return 0.0
    push = estimate_full_mesh_push_time_ms(nbytes_per_chunk, world, spec)
    reduce_ms = world * nbytes_per_chunk / (spec.hbm_gbps * 1e9) * 1e3
    return push + reduce_ms


def estimate_all_reduce_time_ms(nbytes: int, world: int,
                                spec: ChipSpec | None = None,
                                method: str = "two_shot") -> float:
    """two_shot: RS + AG decomposition; one_shot: full-buffer full-mesh
    exchange + local sum (reference allreduce.py:1101-1127 budgets the
    same trade)."""
    if world <= 1:
        return 0.0
    if method == "one_shot":
        return estimate_one_shot_reduce_time_ms(nbytes, world, spec)
    per = nbytes // max(world, 1)
    return (estimate_all_gather_time_ms(per, world, spec)
            + estimate_reduce_scatter_time_ms(per, world, spec))


def overlap_efficiency(gemm_ms: float, comm_ms: float) -> float:
    """Upper bound on fused-op gain: serial/(overlapped) time ratio. 1.0 =
    no win, 2.0 = perfect hiding of the shorter phase (the BASELINE.md
    ≥90% overlap-efficiency north star divides measured by this bound)."""
    serial = gemm_ms + comm_ms
    overlapped = max(gemm_ms, comm_ms)
    return serial / overlapped


def probe_matmul_tflops(m: int = 4096, n: int = 4096, k: int = 4096,
                        dtype=None, iters: int = 10) -> float:
    """Measured MXU throughput (the live analog of the spec table)."""
    import jax.numpy as jnp
    from triton_dist_tpu.runtime.utils import perf_func
    dtype = dtype or jnp.bfloat16
    a = jnp.ones((m, k), dtype)
    b = jnp.ones((k, n), dtype)
    f = jax.jit(lambda: a @ b)
    _, ms = perf_func(f, iters=iters, warmup_iters=3, return_output=False)
    return 2.0 * m * n * k / (ms * 1e-3) / 1e12
