"""Speed-of-light performance models for TPU compute and ICI collectives.

TPU-native redesign of the reference's perf models
(python/triton_dist/kernels/nvidia/gemm_perf_model.py:232
``estimate_gemm_sol_time_ms`` and comm_perf_model.py:94-116
``estimate_all_gather_time_ms`` / ``estimate_reduce_scatter_time_ms``
against probed NVLink/PCIe bandwidth). The reference budgets SMs between
GEMM and comm with these; on TPU the analog decision is whether overlap
is compute- or bandwidth-bound per shape (``overlap_efficiency``), which
drives method choice (e.g. ring vs one-shot, ops/allgather.py).

Chip tables are public-spec numbers; ``probe_*`` measure the live system
(the analog of the reference's topology probes utils.py:823-967).
"""

from __future__ import annotations

import dataclasses

import jax


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    name: str
    bf16_tflops: float          # MXU peak, bf16
    hbm_gbps: float             # HBM bandwidth GB/s
    ici_gbps_per_link: float    # per-direction per-link ICI GB/s
    ici_links: int              # torus links per chip


# Public-spec table (order matters: first matching substring wins).
# "lite" keys first: real device_kind strings are e.g. "TPU v5 lite" /
# "TPU v6 lite", which no bare "v5e"/"v6e" substring matches — missing
# them would silently select the cpu-sim spec on the bench chip.
CHIP_SPECS = {
    "v5 lite": ChipSpec("v5e", 197.0, 819.0, 50.0, 4),
    "v6 lite": ChipSpec("v6e", 918.0, 1640.0, 100.0, 4),
    "v6": ChipSpec("v6e", 918.0, 1640.0, 100.0, 4),
    "v5p": ChipSpec("v5p", 459.0, 2765.0, 100.0, 6),
    "v5e": ChipSpec("v5e", 197.0, 819.0, 50.0, 4),
    "v4": ChipSpec("v4", 275.0, 1228.0, 50.0, 6),
    "cpu": ChipSpec("cpu-sim", 1.0, 50.0, 10.0, 2),
}


def get_chip_spec(device=None) -> ChipSpec:
    """Identify the local chip (reference topology probes)."""
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "cpu").lower()
    for key, spec in CHIP_SPECS.items():
        if key in kind:
            return spec
    return CHIP_SPECS["cpu"]


def estimate_gemm_sol_time_ms(m: int, n: int, k: int,
                              spec: ChipSpec | None = None,
                              dtype_bytes: int = 2) -> float:
    """max(FLOP-bound, HBM-bound) GEMM time (reference
    gemm_perf_model.py:232)."""
    spec = spec or get_chip_spec()
    flops = 2.0 * m * n * k
    t_flops = flops / (spec.bf16_tflops * 1e12)
    bytes_moved = dtype_bytes * (m * k + k * n + m * n)
    t_mem = bytes_moved / (spec.hbm_gbps * 1e9)
    return max(t_flops, t_mem) * 1e3


# Fixed costs per DMA/step (ICI hop launch + semaphore signalling). These
# are what make small payloads latency-bound and large ones
# bandwidth-bound — the axis every AUTO crossover below turns on (the
# reference's analog constants live in its probed bandwidth tables,
# comm_perf_model.py:94-116).
DMA_STARTUP_US = 2.0
ICI_HOP_LATENCY_US = 1.0


def _ring_time_s(nbytes_per_rank: int, world: int, link_gbps: float,
                 n_hops: int) -> float:
    return (nbytes_per_rank * n_hops) / (link_gbps * 1e9)


def estimate_all_gather_time_ms(nbytes_per_rank: int, world: int,
                                spec: ChipSpec | None = None,
                                bidir: bool = True) -> float:
    """Ring AG over ICI: (w-1) hops of the shard per direction plus
    per-step fixed costs (reference comm_perf_model.py:94)."""
    spec = spec or get_chip_spec()
    if world <= 1:
        return 0.0
    hops = (world - 1 + 1) // 2 if bidir else world - 1
    bw = _ring_time_s(nbytes_per_rank, world, spec.ici_gbps_per_link, hops)
    fixed = hops * (DMA_STARTUP_US + ICI_HOP_LATENCY_US) * 1e-6
    return (bw + fixed) * 1e3


def estimate_full_mesh_push_time_ms(nbytes_per_rank: int, world: int,
                                    spec: ChipSpec | None = None) -> float:
    """Full-mesh push AG: one logical hop (all w-1 puts launch at once),
    but non-neighbor puts traverse the torus (mean distance ~w/4 on a
    ring), consuming through-bandwidth on intermediate links."""
    spec = spec or get_chip_spec()
    if world <= 1:
        return 0.0
    avg_hops = max(world / 4.0, 1.0)
    # A 1-D gather axis owns 2 of the chip's links (one per direction);
    # every put occupies avg_hops link-segments of that capacity.
    bw = 2.0 * spec.ici_gbps_per_link
    t = nbytes_per_rank * (world - 1) * avg_hops / (bw * 1e9)
    fixed = (DMA_STARTUP_US + avg_hops * ICI_HOP_LATENCY_US) * 1e-6
    return (t + fixed) * 1e3


def estimate_reduce_scatter_time_ms(nbytes_per_rank: int, world: int,
                                    spec: ChipSpec | None = None,
                                    bidir: bool = True) -> float:
    """Ring RS ≙ AG mirror (reference comm_perf_model.py:116)."""
    return estimate_all_gather_time_ms(nbytes_per_rank, world, spec, bidir)


def estimate_one_shot_reduce_time_ms(nbytes_per_chunk: int, world: int,
                                     spec: ChipSpec | None = None) -> float:
    """One-shot RS/AR gather phase: every peer pushes its contribution
    directly (full-mesh), then a local w-way sum (HBM-bound)."""
    spec = spec or get_chip_spec()
    if world <= 1:
        return 0.0
    push = estimate_full_mesh_push_time_ms(nbytes_per_chunk, world, spec)
    reduce_ms = world * nbytes_per_chunk / (spec.hbm_gbps * 1e9) * 1e3
    return push + reduce_ms


def estimate_all_reduce_time_ms(nbytes: int, world: int,
                                spec: ChipSpec | None = None,
                                method: str = "two_shot") -> float:
    """two_shot: RS + AG decomposition; one_shot: full-buffer full-mesh
    exchange + local sum (reference allreduce.py:1101-1127 budgets the
    same trade)."""
    if world <= 1:
        return 0.0
    if method == "one_shot":
        return estimate_one_shot_reduce_time_ms(nbytes, world, spec)
    per = nbytes // max(world, 1)
    return (estimate_all_gather_time_ms(per, world, spec)
            + estimate_reduce_scatter_time_ms(per, world, spec))


# ---------------------------------------------------------------------------
# Fused-kernel config cost model (VMEM/ICI/MXU roofline)
# ---------------------------------------------------------------------------

#: Per-MXU-dispatch fixed cost inside a Mosaic tile loop (loop
#: bookkeeping, semaphore ops, the VMEM C-stage copy). Measured round 5
#: on v5e: at block_m=128/block_n=512 each ~1.4 us dot carried ~0.5 us
#: of overhead — the gap between the kernel's 135 TFLOPS and the 167
#: TFLOPS calibration dot (docs/perf.md "Why 135 TFLOPS"). This term is
#: what makes the model prefer big tiles: halving the tile count halves
#: the overhead while the roofline terms stay put.
TILE_OVERHEAD_US = 0.5


@dataclasses.dataclass(frozen=True)
class FusedGemmCost:
    """Roofline breakdown of one fused comm-GEMM config.

    ``total_ms`` ranks autotune candidates (tile loop + the comm the
    schedule could not hide); ``overlap_pct`` is the hidden fraction of
    the ring communication — the per-op ``comms.<op>.overlap_pct``
    gauge the ops emit (docs/perf.md "Overlap accounting")."""
    total_ms: float
    compute_ms: float          # max(mxu, hbm) + tile overhead
    mxu_ms: float              # FLOP-bound term
    hbm_ms: float              # HBM-traffic term (tile re-reads incl.)
    tile_overhead_ms: float    # n_tiles * TILE_OVERHEAD_US
    comm_ms: float             # ring ICI time for the full payload
    exposed_comm_ms: float     # comm the tile loop cannot hide
    overlap_pct: float         # 100 * (1 - exposed/comm); 100 if no comm
    n_tiles: int


def _ring_hops(world: int, ring_dirs: int) -> int:
    """Critical-path hop count of the AG ring schedule — derived from
    the kernels' own ``ops.common.ring_hop_counts`` (single source of
    truth: a future change to the direction split must reprice the
    cost model automatically). Lazy import: ops.common imports nothing
    from tools at module scope, but keeping tools → ops edges lazy
    mirrors the ops → tools convention."""
    if world <= 1:
        return 0
    from triton_dist_tpu.ops.common import ring_hop_counts
    return max(ring_hop_counts(world, ring_dirs))


def _fused_cost(flops: float, hbm_bytes: float, n_tiles: int,
                comm_ms: float, world: int, hops: int,
                spec: ChipSpec) -> FusedGemmCost:
    """Combine the roofline terms with the ring schedule's per-step
    overlap structure: the hop moving chunk s+1 overlaps the tile loop
    of chunk s, so each hop hides up to one chunk's compute; the rest
    is exposed."""
    mxu_ms = flops / (spec.bf16_tflops * 1e12) * 1e3
    hbm_ms = hbm_bytes / (spec.hbm_gbps * 1e9) * 1e3
    tile_ms = n_tiles * TILE_OVERHEAD_US * 1e-3
    compute_ms = max(mxu_ms, hbm_ms) + tile_ms
    if world <= 1 or comm_ms <= 0.0 or hops <= 0:
        exposed_ms, pct = 0.0, 100.0
    else:
        t_hop = comm_ms / hops
        per_chunk = compute_ms / world
        exposed_ms = hops * max(0.0, t_hop - per_chunk)
        pct = 100.0 * (1.0 - exposed_ms / comm_ms)
    return FusedGemmCost(
        total_ms=compute_ms + exposed_ms, compute_ms=compute_ms,
        mxu_ms=mxu_ms, hbm_ms=hbm_ms, tile_overhead_ms=tile_ms,
        comm_ms=comm_ms, exposed_comm_ms=exposed_ms,
        overlap_pct=round(max(0.0, min(100.0, pct)), 1),
        n_tiles=n_tiles)


def estimate_ag_gemm_cost(cfg: dict, *, m: int, rows: int, k: int,
                          n_loc: int, itemsize: int, world: int,
                          spec: ChipSpec | None = None,
                          ring_dirs: int = 2) -> FusedGemmCost:
    """Cost of one ``ag_gemm_configs`` entry at (M, K) x (K, N_loc).

    Traffic model per variant (mirrors the kernels' DMA structure):
    ``vmem`` — operands once, one dot per chunk; ``hbm`` (N-blocked) —
    B panel once, A re-read once per N-block, C once; ``hbm_kt`` — A
    once but the B panel re-read per (chunk, m-tile) — the re-read that
    makes it the huge-K fallback, priced here instead of hidden."""
    spec = spec or get_chip_spec()
    variant = cfg.get("variant", "hbm")
    flops = 2.0 * m * k * n_loc
    if variant == "vmem":
        hbm_bytes = itemsize * (rows * k + k * n_loc + m * n_loc + m * k)
        n_tiles = max(world, 1)
    elif variant == "hbm":
        bm = cfg.get("block_m", 256)
        bn = cfg.get("block_n", 512)
        n_blocks = max(n_loc // max(bn, 1), 1)
        m_tiles = max(rows // max(bm, 1), 1)
        hbm_bytes = itemsize * (m * k * (n_blocks + 1) + k * n_loc
                                + m * n_loc)
        n_tiles = world * m_tiles * n_blocks
    else:  # hbm_kt
        bm = cfg.get("block_m", 128)
        bk = cfg.get("block_k", 256)
        m_tiles = max(rows // max(bm, 1), 1)
        k_tiles = max(k // max(bk, 1), 1)
        hbm_bytes = itemsize * (2 * m * k + world * m_tiles * k * n_loc
                                + m * n_loc)
        n_tiles = world * m_tiles * k_tiles
    comm_ms = estimate_all_gather_time_ms(
        rows * k * itemsize, world, spec,
        bidir=(ring_dirs == 2 and world > 2))
    return _fused_cost(flops, hbm_bytes, n_tiles, comm_ms, world,
                       _ring_hops(world, ring_dirs), spec)


def estimate_ag_swiglu_cost(cfg: dict, *, m: int, rows: int, k: int,
                            n_loc: int, itemsize: int, world: int,
                            spec: ChipSpec | None = None,
                            ring_dirs: int = 2) -> FusedGemmCost:
    """Cost of one ``ag_swiglu_configs`` entry: the N-blocked dual-GEMM
    kernel (gate AND up panels resident, two dots + the activation per
    tile, one fused C write)."""
    spec = spec or get_chip_spec()
    bm = cfg.get("block_m", 256)
    bn = cfg.get("block_n", 512)
    n_blocks = max(n_loc // max(bn, 1), 1)
    m_tiles = max(rows // max(bm, 1), 1)
    flops = 2.0 * 2.0 * m * k * n_loc
    hbm_bytes = itemsize * (m * k * (n_blocks + 1) + 2 * k * n_loc
                            + m * n_loc)
    n_tiles = 2 * world * m_tiles * n_blocks   # two dots per tile
    comm_ms = estimate_all_gather_time_ms(
        rows * k * itemsize, world, spec,
        bidir=(ring_dirs == 2 and world > 2))
    return _fused_cost(flops, hbm_bytes, n_tiles, comm_ms, world,
                       _ring_hops(world, ring_dirs), spec)


def estimate_gemm_rs_cost(cfg: dict, *, m: int, rows: int, k_loc: int,
                          n: int, itemsize: int, world: int,
                          spec: ChipSpec | None = None,
                          ring_dirs: int = 2) -> FusedGemmCost:
    """Cost of one ``gemm_rs_configs`` entry at (M, K_loc) x (K_loc, N).

    The bidirectional RS halves per-link traffic by sending the two
    column halves opposite ways, which ``estimate_reduce_scatter_time_ms
    (bidir=True)`` already prices as half the hops of a full payload."""
    spec = spec or get_chip_spec()
    variant = cfg.get("variant", "hbm")
    flops = 2.0 * m * k_loc * n
    slab_bytes = 2 * max(world - 1, 0) * rows * n * itemsize
    if variant == "vmem":
        hbm_bytes = itemsize * (m * k_loc + k_loc * n + rows * n)
        n_tiles = max(world, 1)
    elif variant == "hbm":
        bm = cfg.get("block_m", 256)
        bn = cfg.get("block_n", 512)
        n_blocks = max(n // max(bn, 1), 1)
        m_tiles = max(rows // max(bm, 1), 1)
        hbm_bytes = (itemsize * (m * k_loc * n_blocks
                                 + world * k_loc * n + m * n)
                     + slab_bytes)
        n_tiles = world * m_tiles * n_blocks
    else:  # hbm_kt
        bm = cfg.get("block_m", 128)
        bk = cfg.get("block_k", 256)
        m_tiles = max(rows // max(bm, 1), 1)
        k_tiles = max(k_loc // max(bk, 1), 1)
        hbm_bytes = (itemsize * (m * k_loc
                                 + world * m_tiles * k_loc * n + m * n)
                     + slab_bytes)
        n_tiles = world * m_tiles * k_tiles
    comm_ms = estimate_reduce_scatter_time_ms(
        rows * n * itemsize, world, spec,
        bidir=(ring_dirs == 2))
    return _fused_cost(flops, hbm_bytes, n_tiles, comm_ms, world,
                       world - 1 if world > 1 else 0, spec)


def prune_configs(cfgs, cost_ms_fn, *, factor: int = 4,
                  keep_min: int = 2, always_keep=None):
    """Cost-model pruning of an autotune candidate table.

    Keeps ``max(keep_min, len(cfgs) // factor)`` entries: first the
    best-cost config matching ``always_keep`` (the downstream-clamp
    fallback variants pruning must never drop — review r5l finding 1),
    then the best-ranked remainder. Every kept entry still runs under
    the sweep's per-config compile-failure isolation; pruning trims the
    ~30 s-per-candidate Mosaic compile bill, it does not relax safety.

    Returns ``(pruned, n_before)`` so callers can log the counts
    (``tools.autotuner.record_prune``).
    """
    cfgs = list(cfgs)
    n_before = len(cfgs)
    if n_before <= keep_min:
        return cfgs, n_before
    costs = [float(cost_ms_fn(c)) for c in cfgs]
    order = sorted(range(n_before), key=lambda i: costs[i])
    n_keep = max(keep_min, n_before // factor)
    picked: list[int] = []
    if always_keep is not None:
        musts = [i for i in order if always_keep(cfgs[i])]
        if musts:
            picked.append(musts[0])
    for i in order:
        if len(picked) >= n_keep:
            break
        if i not in picked:
            picked.append(i)
    picked.sort(key=lambda i: costs[i])
    return [cfgs[i] for i in picked], n_before


def declared_footprint(op: str, cfg: dict, *, rows: int,
                       itemsize: int = 2, world: int = 1,
                       m: int | None = None, k: int | None = None,
                       k_loc: int | None = None, n: int | None = None,
                       n_loc: int | None = None) -> int:
    """Declared VMEM bytes of one fused-family candidate config — the
    number the per-op clamps compare against ``DEFAULT_VMEM_BUDGET`` /
    ``HARD_FOOTPRINT_CAP`` (ops/common.py). Delegates to the kernels'
    own footprint helpers where they exist so this stays a single
    source of truth; the inline vmem/k-tiled formulas mirror the
    config generators (``ag_gemm_configs`` / ``gemm_rs_configs``).

    Used by the static analysis vet (``triton_dist_tpu.analysis.vmem``)
    and the autotuner's pre-compile candidate gate — an over-budget
    config is rejected from Python, before Mosaic ever sees it."""
    variant = cfg.get("variant", "hbm")
    bm = cfg.get("block_m", 256)
    bn = cfg.get("block_n", 512)
    bk = cfg.get("block_k", 256)
    if op in ("ag_gemm", "ag_swiglu"):
        from triton_dist_tpu.ops.allgather_gemm import (
            _hbm_footprint, _swiglu_footprint)
        if op == "ag_swiglu":
            return _swiglu_footprint(bm, bn, k, itemsize)
        if variant == "vmem":
            return itemsize * (m * k + k * n_loc + m * n_loc + rows * k)
        if variant == "hbm":
            return _hbm_footprint(bm, bn, k, itemsize)
        return (2 * bm * bk + 2 * bk * n_loc) * itemsize \
            + bm * n_loc * (4 + 2 * itemsize)
    if op in ("gemm_rs", "gemm_ar"):
        from triton_dist_tpu.ops.gemm_reduce_scatter import (
            _hbm_nb_footprint)
        if variant == "vmem":
            return itemsize * (m * k_loc + k_loc * n + rows * n
                               + 2 * max(world - 1, 1) * rows * n)
        if variant == "hbm":
            return _hbm_nb_footprint(bm, bn, k_loc, itemsize)
        return (2 * bm * bk + 2 * bk * n) * itemsize \
            + bm * n * (4 + 3 * itemsize)
    if op == "all_to_all":
        # send slab input + recv output, both whole in VMEM — the
        # op's own formula (ops/all_to_all.py a2a_footprint).
        from triton_dist_tpu.ops.all_to_all import a2a_footprint
        return a2a_footprint(world, cfg["capacity"], cfg["h"], itemsize)
    if op == "moe_reduce_rs":
        # The fused kernel's scratch at the h-block it will actually
        # run: delegate BOTH the clamp and the formula to the kernel's
        # own helpers so the vet prices the real tiling.
        from triton_dist_tpu.ops.moe_reduce_rs import (
            moe_rs_fused_footprint, moe_rs_resolve_h_blk)
        h_blk = moe_rs_resolve_h_blk(
            cfg["h"], cfg.get("block_h", 512), cfg.get("block_m", 128),
            cfg["i_loc"], rows, itemsize, cfg["vmem_budget"])
        return moe_rs_fused_footprint(
            cfg.get("block_m", 128), cfg["i_loc"], h_blk, rows,
            itemsize)
    raise ValueError(f"no footprint model for op {op!r}")


def vet_vmem(op: str, cfg: dict, *, cap: int | None = None,
             **dims) -> str | None:
    """Static VMEM gate for one autotune candidate: a rejection reason
    when the declared footprint exceeds ``cap`` (default
    ``HARD_FOOTPRINT_CAP``), else ``None``. Pure Python — no compile
    is invoked, so a config that would wedge a Mosaic compile (the
    BENCH_r02 / smoke-queue class) is refused up front."""
    if cap is None:
        from triton_dist_tpu.ops.common import HARD_FOOTPRINT_CAP
        cap = HARD_FOOTPRINT_CAP
    fp = declared_footprint(op, cfg, **dims)
    if fp > cap:
        return (f"{op} config {cfg} declares {fp / 2**20:.1f} MB VMEM "
                f"> {cap / 2**20:.1f} MB cap")
    return None


def overlap_efficiency(gemm_ms: float, comm_ms: float) -> float:
    """Upper bound on fused-op gain: serial/(overlapped) time ratio. 1.0 =
    no win, 2.0 = perfect hiding of the shorter phase (the BASELINE.md
    ≥90% overlap-efficiency north star divides measured by this bound)."""
    serial = gemm_ms + comm_ms
    overlapped = max(gemm_ms, comm_ms)
    return serial / overlapped


def probe_matmul_tflops(m: int = 4096, n: int = 4096, k: int = 4096,
                        dtype=None, iters: int = 10) -> float:
    """Measured MXU throughput (the live analog of the spec table)."""
    import jax.numpy as jnp
    from triton_dist_tpu.runtime.utils import perf_func
    dtype = dtype or jnp.bfloat16
    a = jnp.ones((m, k), dtype)
    b = jnp.ones((k, n), dtype)
    f = jax.jit(lambda: a @ b)
    _, ms = perf_func(f, iters=iters, warmup_iters=3, return_output=False)
    return 2.0 * m * n * k / (ms * 1e-3) / 1e12
