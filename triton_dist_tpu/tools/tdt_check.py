"""``tdt-check`` driver: run the static-analysis passes over the repo.

Usage::

    python -m triton_dist_tpu.tools.tdt_check            # all passes
    python -m triton_dist_tpu.tools.tdt_check --list
    python -m triton_dist_tpu.tools.tdt_check --json
    python -m triton_dist_tpu.tools.tdt_check --pass ring-protocol \
        --pass vmem-budget

Exits nonzero when any error-severity finding survives suppression
(``# tdt: ignore[...]`` pragmas, docs/analysis.md). The quick tier
runs this over the repo (tests/test_tdt_check.py) and ``tpu_smoke.py``
calls :func:`preflight` before queuing any case, so a ring-protocol or
VMEM-budget regression is rejected before a compile can wedge a smoke
queue.
"""

from __future__ import annotations

import argparse
import sys

from triton_dist_tpu.analysis import (
    PASSES, exit_code, render_human, render_json, run_passes)

__all__ = ["main", "preflight"]


def preflight(names=None, out=None) -> int:
    """Smoke-queue preflight: run the passes, print findings, return
    the would-be exit code. Cheap (pure Python, no compile) — a
    protocol violation or an over-budget candidate table stops the
    queue before the first Mosaic compile."""
    out = out or sys.stdout
    findings = run_passes(names=names)
    print(render_human(findings, n_passes=len(names or PASSES)),
          file=out)
    return exit_code(findings)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tdt_check",
        description="static ring-protocol verifier + repo contract "
                    "lints (docs/analysis.md)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    ap.add_argument("--list", action="store_true",
                    help="list registered passes and exit")
    ap.add_argument("--pass", dest="passes", action="append",
                    metavar="NAME",
                    help="run only this pass (repeatable)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: derived from the "
                         "installed package)")
    args = ap.parse_args(argv)

    if args.list:
        for p in PASSES.values():
            print(f"{p.name}: {p.description}")
        return 0

    findings = run_passes(root=args.root, names=args.passes)
    if args.json:
        print(render_json(findings))
    else:
        print(render_human(
            findings, n_passes=len(args.passes or PASSES)))
    return exit_code(findings)


if __name__ == "__main__":
    sys.exit(main())
