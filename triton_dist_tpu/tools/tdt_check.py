"""``tdt-check`` driver: run the static-analysis passes over the repo.

Usage::

    python -m triton_dist_tpu.tools.tdt_check            # all passes
    python -m triton_dist_tpu.tools.tdt_check --list
    python -m triton_dist_tpu.tools.tdt_check --json
    python -m triton_dist_tpu.tools.tdt_check --pass ring-protocol \
        --pass a2a-protocol,p2p-protocol
    python -m triton_dist_tpu.tools.tdt_check --changed   # diff-scoped

``--pass`` repeats and accepts comma-separated lists. ``--changed``
asks git for the working-tree diff (staged + unstaged + untracked)
and runs only the passes whose declared watch files changed
(``analysis.Pass.watches``) — the fast pre-commit loop; passes with
no declared watches always run. ``--md-summary PATH`` appends a
markdown findings table (the GitHub Actions step-summary renderer —
CI passes ``$GITHUB_STEP_SUMMARY``).

Exits nonzero when any error-severity finding survives suppression
(``# tdt: ignore[...]`` pragmas, docs/analysis.md). The quick tier
runs this over the repo (tests/test_tdt_check.py) and ``tpu_smoke.py``
calls :func:`preflight` before queuing any case, so a ring-protocol or
VMEM-budget regression is rejected before a compile can wedge a smoke
queue.
"""

from __future__ import annotations

import argparse
import subprocess
import sys

from triton_dist_tpu.analysis import (
    PASSES, exit_code, render_human, render_json, repo_root,
    run_passes, select_passes_for)

__all__ = ["main", "preflight", "changed_files", "render_md"]


def preflight(names=None, out=None) -> int:
    """Smoke-queue preflight: run the passes, print findings, return
    the would-be exit code. Cheap (pure Python, no compile) — a
    protocol violation or an over-budget candidate table stops the
    queue before the first Mosaic compile."""
    out = out or sys.stdout
    findings = run_passes(names=names)
    print(render_human(findings, n_passes=len(names or PASSES)),
          file=out)
    return exit_code(findings)


def changed_files(root=None) -> list:
    """Repo-relative paths the working tree changed vs HEAD: staged,
    unstaged, and untracked (one ``git status --porcelain`` walk;
    renames contribute both sides)."""
    root = str(root or repo_root())
    out = subprocess.run(
        ["git", "status", "--porcelain"], cwd=root,
        capture_output=True, text=True, check=True).stdout
    paths = []
    for line in out.splitlines():
        if len(line) < 4:
            continue
        body = line[3:]
        for part in body.split(" -> "):
            part = part.strip().strip('"')
            if part:
                paths.append(part)
    return paths


def render_md(findings, n_passes: int | None = None) -> str:
    """Markdown findings table for CI step summaries."""
    n_err = sum(1 for f in findings if f.severity == "error")
    suffix = f" across {n_passes} passes" if n_passes is not None \
        else ""
    lines = ["## tdt-check", ""]
    if not findings:
        lines.append(f"**OK** — no findings{suffix}")
    else:
        lines.append(f"**{n_err} error(s), "
                     f"{len(findings) - n_err} warning(s)**{suffix}")
        lines += ["", "| code | severity | anchor | message |",
                  "|---|---|---|---|"]
        for f in findings:
            msg = f.message.replace("|", "\\|")
            lines.append(f"| `{f.code}` | {f.severity} | "
                         f"`{f.anchor}` | {msg} |")
    return "\n".join(lines) + "\n"


def _expand_passes(raw) -> list | None:
    if not raw:
        return None
    names = []
    for item in raw:
        names.extend(n.strip() for n in item.split(",") if n.strip())
    return names or None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tdt_check",
        description="static protocol verifiers + repo contract "
                    "lints (docs/analysis.md)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    ap.add_argument("--list", action="store_true",
                    help="list registered passes and exit")
    ap.add_argument("--pass", dest="passes", action="append",
                    metavar="NAME[,NAME...]",
                    help="run only these passes (repeatable and/or "
                         "comma-separated)")
    ap.add_argument("--changed", action="store_true",
                    help="run only passes whose watched files the "
                         "git working tree changed (fast pre-commit "
                         "loop)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: derived from the "
                         "installed package)")
    ap.add_argument("--md-summary", metavar="PATH", default=None,
                    help="append a markdown findings table to PATH "
                         "(CI: pass $GITHUB_STEP_SUMMARY)")
    args = ap.parse_args(argv)

    if args.list:
        for p in PASSES.values():
            print(f"{p.name}: {p.description}")
        return 0

    names = _expand_passes(args.passes)
    if args.changed:
        if names is not None:
            ap.error("--changed and --pass are mutually exclusive")
        changed = changed_files(args.root)
        names = select_passes_for(changed)
        skipped = sorted(set(PASSES) - set(names))
        # Status prose goes to stderr so `--changed --json > f.json`
        # stays machine-parseable; an empty selection falls through to
        # the normal render path (empty findings JSON / summary), it
        # does not short-circuit the output contract.
        print(f"tdt-check --changed: {len(changed)} changed file(s) "
              f"-> running {len(names)}/{len(PASSES)} passes"
              + (f" (skipped: {', '.join(skipped)})" if skipped
                 else ""), file=sys.stderr)
        if not names:
            print("tdt-check --changed: no watched files changed",
                  file=sys.stderr)

    findings = run_passes(root=args.root, names=names)
    n_passes = len(PASSES) if names is None else len(names)
    if args.json:
        print(render_json(findings))
    else:
        print(render_human(findings, n_passes=n_passes))
    if args.md_summary:
        with open(args.md_summary, "a", encoding="utf-8") as f:
            f.write(render_md(findings, n_passes=n_passes))
    return exit_code(findings)


if __name__ == "__main__":
    sys.exit(main())
