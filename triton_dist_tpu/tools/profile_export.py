"""Validate, summarize, and export parsed ``jax.profiler`` captures.

The read-back CLI over ``obs.devprof`` (docs/observability.md
"Device-time truth"): where ``tools/trace_export.py`` owns the
host-side structured-event dumps, this tool owns the DEVICE-side
captures ``tools/profiler.group_profile`` writes.

CLI::

    python -m triton_dist_tpu.tools.profile_export PATH... --validate
    python -m triton_dist_tpu.tools.profile_export PATH... --summary
    python -m triton_dist_tpu.tools.profile_export PATH --chrome out.json

``PATH`` may be a capture file (``*.trace.json[.gz]`` /
``*.xplane.pb``), a profile run directory, a ``group_profile``
artifact dir, or a root holding several captures (every run found is
processed; the hardware watcher points it at the bench's
``TDT_DEVPROF_DIR`` after each bench step).

- ``--validate`` — parse every capture; rc!=0 on an unparseable one
  (the same contract as ``trace_export --validate``: the queue stops
  before an unreadable artifact masquerades as evidence). A path with
  NO captures is a warning by default (a CPU part may legitimately
  skip profiling); ``--require`` upgrades that to a failure.
- ``--summary`` — the parsed attribution as JSON: per-op
  total/compute/comm ms, measured overlap, unlabeled time.
- ``--chrome`` — convert the device timeline to Chrome trace events
  (wall-clock shifted via the capture's ``tdt_capture.json`` anchor),
  the form ``trace_export --merge-profile`` overlays into a host dump.
"""

from __future__ import annotations

import argparse
import json
import sys

from triton_dist_tpu.obs import devprof

__all__ = ["capture_paths", "main", "to_chrome_events",
           "validate_capture"]

#: pid base for overlaid device-profile rows in a merged Perfetto dump
#: — far from host pids (0..n_hosts) and trace_export's collision
#: remapping (1000·host steps).
DEVICE_PID_BASE = 900


def capture_paths(path: str) -> list[str]:
    """Every capture under ``path`` (see module docstring for accepted
    forms): run directories newest-last, or the file itself."""
    import os
    if os.path.isfile(path):
        return [path]
    return devprof.find_captures(path)


def validate_capture(path: str) -> tuple[dict | None, str | None]:
    """(summary, error): parse one capture; error string when it is
    unparseable or empty."""
    try:
        summary = devprof.parse_capture(path)
    except Exception as e:  # noqa: BLE001 — the rc is the contract
        return None, f"{type(e).__name__}: {e}"
    if not summary.get("n_events") and not summary.get("ops"):
        return summary, "capture parsed but holds no execution events"
    return summary, None


def to_chrome_events(path: str, pid: int | None = None) -> list[dict]:
    """The capture's events as Chrome trace events on one wall clock.

    Capture timestamps are profile-session-relative; the
    ``tdt_capture.json`` anchor (``t0_unix``) shifts them onto the
    same epoch-micros clock ``obs.trace`` stamps host events with, so
    a merged dump shows dispatch and device work in one Perfetto view.
    Un-anchored (foreign) captures keep their relative clock."""
    events = [e for e in devprof.load_capture(path)
              # The overlay carries the MEANINGFUL timeline — label
              # windows, device-plane work, host-side execution /
              # comm events — not the thousands of python-frame
              # events a capture also holds (Perfetto chokes and the
              # merged dump stops being readable).
              if e["device"]
              or e["name"].startswith(devprof.LABEL_PREFIX)
              or devprof._EXEC_PAT.search(e["name"])
              or devprof._COMM_PAT.search(e["name"])]
    meta = devprof.capture_meta(path)
    shift_us = float(meta.get("t0_unix", 0.0)) * 1e6
    if pid is None:
        pid = DEVICE_PID_BASE + int(meta.get("host", 0))
    out: list[dict] = [
        {"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
         "args": {"name": f"devprof host{meta.get('host', '?')}"
                          + ("" if meta else " (unanchored)")}},
    ]
    tids: dict[tuple, int] = {}
    for e in events:
        key = (e["pid"], e["tid"])
        tid = tids.get(key)
        if tid is None:
            tid = tids[key] = len(tids) + 1
            kind = "device" if e["device"] else "host"
            out.append({"ph": "M", "pid": pid, "tid": tid,
                        "name": "thread_name",
                        "args": {"name": f"devprof.{kind}.{e['pid']}"
                                         f".{e['tid']}"}})
        out.append({"ph": "X", "pid": pid, "tid": tid,
                    "ts": e["ts_us"] + shift_us, "dur": e["dur_us"],
                    "name": e["name"], "cat": "devprof"})
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Parse / validate jax.profiler captures "
                    "(obs.devprof)")
    ap.add_argument("paths", nargs="+",
                    help="capture file(s) / run dir(s) / capture roots")
    ap.add_argument("--validate", action="store_true",
                    help="rc!=0 on any unparseable capture")
    ap.add_argument("--require", action="store_true",
                    help="with --validate: a path holding NO captures "
                         "is a failure, not a warning")
    ap.add_argument("--summary", action="store_true",
                    help="print the parsed per-op attribution as JSON")
    ap.add_argument("--chrome", default=None,
                    help="write the newest capture's device timeline "
                         "as Chrome trace JSON (wall-clock anchored)")
    args = ap.parse_args(argv)
    if not (args.validate or args.summary or args.chrome):
        ap.error("nothing to do: pass --validate, --summary, "
                 "and/or --chrome")
    rc = 0
    all_caps: list[str] = []
    for p in args.paths:
        caps = capture_paths(p)
        if not caps:
            msg = f"{p}: no profile captures found"
            if args.require:
                print(f"{msg} (--require)")
                rc = 1
            else:
                print(f"{msg} (skipped)")
            continue
        all_caps.extend(caps)
        for c in caps:
            summary, err = validate_capture(c)
            if args.validate or err:
                ops = sorted((summary or {}).get("ops", {}))
                print(f"{c}: "
                      + (f"INVALID {err}" if err else
                         f"valid ({summary['n_events']} exec events, "
                         f"ops: {', '.join(ops) if ops else '-'}, "
                         f"unlabeled {summary['unlabeled_ms']} ms)"))
                rc = rc or (1 if err else 0)
            if args.summary and summary is not None:
                print(json.dumps(summary, indent=1, sort_keys=True))
    if args.chrome:
        if not all_caps:
            print("--chrome: no capture to convert")
            return 1
        events = to_chrome_events(all_caps[-1])
        with open(args.chrome, "w") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms"}, f)
        print(f"wrote {args.chrome} ({len(events)} events from "
              f"{all_caps[-1]})")
    return rc


if __name__ == "__main__":
    sys.exit(main())
