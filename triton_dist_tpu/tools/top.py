"""Live serving dashboard: `top` for the SLO observatory.

Polls a running ModelServer's ``{"cmd": "metrics", "evaluate":
false}`` (read-only — a render tick must not force SLO evaluations;
the pump keeps the gauges fresh while it works, and the ``health``
verb's seq/uptime header says how fresh) plus ``{"cmd":
"request_stats"}`` and renders one refresh-loop screen: rolling
p50/p99 latencies, per-target burn rates with breach flags, batch
occupancy / queue depth, KV block-pool utilization, per-op live
fused-vs-XLA ratios (``obs.perfwatch``), and the freshest request
waterfalls (``obs.attrib``) — the terminal answer to "is serving
healthy right now and where is the latency going", no Perfetto dump
required (docs/observability.md "SLOs and burn rates").

Usage:
    python -m triton_dist_tpu.tools.top --port 8777 [--interval 2]
        [--once]

``render()`` is pure (snapshot dict → string) so the screen is
testable without a server (tests/test_tools.py).
"""

from __future__ import annotations

import argparse
import sys
import time


def fetch(host: str, port: int, timeout: float = 10.0) -> dict:
    """One scrape: the metrics snapshot plus the newest request
    waterfalls, as the dict :func:`render` consumes.

    The read path is CHEAP on purpose (ISSUE 14 bugfix): the metrics
    request passes ``"evaluate": false`` — rendering a dashboard must
    not force an SLO evaluation per tick, or monitoring N replicas at
    1 Hz perturbs N pump loops — and the replica header comes from the
    lock-free ``{"cmd": "health"}`` verb (its ``seq``/``uptime_s``
    tell the reader how fresh the last-evaluated gauges are; the pump
    re-evaluates every working iteration, so an ACTIVE server's
    gauges are at most ~1 s old anyway)."""
    from triton_dist_tpu.serving.client import ChatClient
    c = ChatClient(host, port, timeout=timeout)
    try:
        snap = c.request({"cmd": "metrics",
                          "evaluate": False})["metrics"]
        try:
            snap["health"] = c.health()
        except Exception:  # noqa: BLE001 — pre-ISSUE-14 servers
            snap["health"] = None
        try:
            # Sampled series (ISSUE 16): None unless the server runs
            # with TDT_HISTORY=1; downsampled server-side so a screen's
            # worth of sparklines costs one small reply.
            snap["history"] = c.request(
                {"cmd": "history", "max_points": 32}).get("history")
        except Exception:  # noqa: BLE001 — pre-ISSUE-16 servers
            snap["history"] = None
        snap["requests"] = c.request_stats(last=5)
    finally:
        c.close()
    return snap


def _fmt(v) -> str:
    if v is None:
        return "-"
    f = float(v)
    return str(int(f)) if f == int(f) else f"{f:.3f}"


def _rows(lines: list, title: str, rows: list) -> None:
    if not rows:
        return
    lines.append(title)
    width = max(len(r[0]) for r in rows)
    for name, val in rows:
        lines.append(f"  {name:<{width}}  {val}")
    lines.append("")


def render(snap: dict) -> str:
    """One dashboard screen from a metrics snapshot (plus an optional
    ``requests`` waterfall list)."""
    g = snap.get("gauges", {})
    c = snap.get("counters", {})
    lines = [f"tdt top — {time.strftime('%H:%M:%S')}", ""]

    h = snap.get("health")
    rid = snap.get("replica_id") or (h or {}).get("replica_id")
    if rid:
        parts = [f"replica {rid}"]
        if h:
            parts.append(f"up {_fmt(h.get('uptime_s'))}s")
            parts.append(f"seq {_fmt(h.get('seq'))}")
        lines[0] += "   [" + "   ".join(parts) + "]"

    slo_rows = []
    for m in ("ttft", "tpot", "queue_wait", "pump"):
        p50 = g.get(f"serving.rolling.{m}_p50_ms")
        p99 = g.get(f"serving.rolling.{m}_p99_ms")
        n = g.get(f"serving.rolling.{m}_n")
        if p50 is None and p99 is None and not n:
            continue
        slo_rows.append((m, f"p50 {_fmt(p50)} ms   p99 {_fmt(p99)} ms"
                            f"   n {_fmt(n)}"))
    if "serving.spec_accept_rate" in g:
        # Speculative decoding rides the same panel: accept rate and
        # emitted tokens per verify step are the knobs that move TPOT
        # (docs/serving.md "Speculative decoding").
        slo_rows.append(
            ("spec", f"accept {_fmt(g['serving.spec_accept_rate'])}   "
                     f"tok/step "
                     f"{_fmt(g.get('serving.spec_tokens_per_step'))}"))
    _rows(lines, "rolling latency (window)", slo_rows)

    burn_rows = []
    for k in sorted(g):
        if k.startswith("serving.slo_burn.") and not k.endswith("_slow"):
            name = k[len("serving.slo_burn."):]
            slow = g.get(f"{k}_slow")
            breached = g.get(f"serving.slo_breached.{name}")
            flag = "  ** BREACH **" if breached else ""
            burn_rows.append(
                (name, f"fast {_fmt(g[k])}   slow {_fmt(slow)}{flag}"))
    _rows(lines, "slo burn rates", burn_rows)

    batch_rows = []
    for label, key in (("batch occupancy", "serving.batch_occupancy"),
                       ("queue depth", "serving.queue_depth"),
                       ("block utilization", "kv.block_utilization"),
                       ("blocks free", "kv.blocks_free"),
                       ("prefix hit rate", "serving.prefix_hit_rate")):
        if key in g:
            batch_rows.append((label, _fmt(g[key])))
    for label, key in (("admitted", "serving.admitted"),
                       ("retired", "serving.retired"),
                       ("slo breaches", "serving.slo_breaches")):
        if key in c:
            batch_rows.append((label, _fmt(c[key])))
    if g.get("trace.dropped_total"):
        batch_rows.append(("trace drops",
                           f"{_fmt(g['trace.dropped_total'])} "
                           f"(raise TDT_TRACE_RING)"))
    _rows(lines, "batch / pool", batch_rows)

    ratio_rows = []
    for k in sorted(g):
        if k.startswith("resilience.perfwatch.") \
                and k.endswith(".live_ratio"):
            op = k[len("resilience.perfwatch."):-len(".live_ratio")]
            ratio_rows.append((op, f"{_fmt(g[k])}x vs xla (live)"))
    _rows(lines, "live op ratios", ratio_rows)

    # Device-time truth (obs.devprof): measured per-op attribution
    # from parsed jax.profiler captures, drift vs the modeled gauge,
    # and the last profile artifact a postmortem reader should open.
    dev_rows = []
    ops = sorted({k.split(".")[1] for k in g
                  if k.startswith("device.") and k.count(".") == 2})
    for op in ops:
        comp = g.get(f"device.{op}.compute_ms")
        comm = g.get(f"device.{op}.comm_ms")
        ov = g.get(f"comms.{op}.overlap_pct_measured")
        drift = g.get(f"comms.{op}.overlap_drift_pct")
        val = (f"compute {_fmt(comp)} ms   comm {_fmt(comm)} ms"
               + (f"   overlap {_fmt(ov)}%" if ov is not None else "")
               + (f"   drift {_fmt(drift)}%" if drift is not None
                  else ""))
        dev_rows.append((op, val))
    if g.get("device.unlabeled_ms"):
        dev_rows.append(("(unlabeled)",
                         f"{_fmt(g['device.unlabeled_ms'])} ms "
                         f"(see tdt-check annotation-coverage)"))
    dp = snap.get("devprof") or {}
    if dp.get("last_profile"):
        dev_rows.append(("last profile",
                         f"{dp['last_profile']} "
                         f"({dp.get('last_reason', '?')})"))
    _rows(lines, "device time (measured)", dev_rows)

    # Sampled history (ISSUE 16): one sparkline per recorded series —
    # the time dimension every panel above lacks — plus the newest
    # early-warning excerpts. Only present when the server samples
    # (TDT_HISTORY=1); rendering is additive so old snapshots are fine.
    hist = snap.get("history") or {}
    hist_rows = []
    if hist.get("series"):
        from triton_dist_tpu.obs.history import sparkline, window_stats
        for name in sorted(hist["series"]):
            s = hist["series"][name]
            pts = s.get("points") or []
            st = window_stats(pts)
            if not st.get("n"):
                continue
            hist_rows.append(
                (name, f"{sparkline([v for _, v in pts], width=24)} "
                       f"last {_fmt(st['last'])}   "
                       f"min {_fmt(st['min'])}   max {_fmt(st['max'])}"))
        for w in (hist.get("warnings") or [])[:3]:
            hist_rows.append(
                (f"! {w.get('detector', '?')}",
                 f"{w.get('metric', '?')} {w.get('op', '')} "
                 f"{_fmt(w.get('threshold'))} "
                 f"(window {_fmt(w.get('window_s'))}s)"))
    _rows(lines, "history (sampled)", hist_rows)

    req_rows = []
    for r in snap.get("requests", [])[:5]:
        seg = r.get("segments", {})
        req_rows.append(
            (f"rid {r.get('rid')}",
             f"total {_fmt(r.get('total_ms'))} ms = queue "
             f"{_fmt(seg.get('queue_wait_ms'))} + prefill "
             f"{_fmt(seg.get('prefill_ms'))} + decode "
             f"{_fmt(seg.get('decode_ms'))}   "
             f"[{r.get('tokens')} tok, {r.get('cached_tokens')} "
             f"cached]"))
    _rows(lines, "latest requests", req_rows)

    if len(lines) == 2:
        lines.append("(no serving metrics yet)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8777)
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--iterations", type=int, default=None,
                    help="stop after N refreshes (default: forever)")
    ap.add_argument("--once", action="store_true",
                    help="print one screen and exit (no ANSI clear)")
    args = ap.parse_args(argv)
    n = 1 if args.once else args.iterations
    i = 0
    try:
        while n is None or i < n:
            screen = render(fetch(args.host, args.port))
            if not args.once:
                sys.stdout.write("\x1b[2J\x1b[H")
            print(screen)
            sys.stdout.flush()
            i += 1
            if n is not None and i >= n:
                break
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
