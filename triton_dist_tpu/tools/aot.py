"""AOT compilation/export of jitted programs.

TPU-native redesign of the reference's AOT toolchain (L9:
python/triton_dist/tools/compile_aot.py ``@aot_compile_spaces`` generating
C sources per (kernel × config) + a CUDA-driver runtime
triton_aot_runtime.cc, used to launch flash-decode from C++ without
Python, flash_decode.py:979-1130).

The XLA-native equivalent is ``jax.export``: a jitted function lowers to
a serialized StableHLO artifact that any PJRT runtime (C++, Python, TF)
can load and run without re-tracing. ``aot_compile_spaces`` maps to
exporting one artifact per declared signature (symbolic shapes cover the
reference's dynamic ``M`` dimension spaces).
"""

from __future__ import annotations

import os
from collections.abc import Callable, Sequence

import jax
from jax import export as jax_export


def aot_export(fn: Callable, example_args: Sequence,
               platforms: Sequence[str] | None = None) -> bytes:
    """Trace + lower ``fn`` for ``example_args`` and serialize (reference
    per-signature C source generation, compile_aot.py:61-115)."""
    exp = jax_export.export(
        jax.jit(fn),
        platforms=list(platforms) if platforms else None,
    )(*jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
        if hasattr(a, "shape") else a, tuple(example_args)))
    return bytes(exp.serialize())


def aot_export_symbolic(fn: Callable, args_spec: Sequence,
                        platforms: Sequence[str] | None = None) -> bytes:
    """Export with symbolic dimensions — ONE artifact serving every size
    of the dynamic axes (the reference instead enumerates a C source per
    (kernel x config) signature; its flash-decode AOT spaces over M,
    compile_aot.py:61-115, collapse into a single symbolic export here).

    Args:
      args_spec: one ``(shape_str, dtype)`` per argument; ``shape_str``
        is a jax.export symbolic shape, e.g. ``("m, 4096", jnp.bfloat16)``
        — the same symbol name means the same size across arguments.
    """
    scope = jax_export.SymbolicScope()
    avals = tuple(
        jax.ShapeDtypeStruct(
            jax_export.symbolic_shape(s, scope=scope), dtype)
        for s, dtype in args_spec)
    return aot_export(fn, avals, platforms=platforms)


def aot_load(blob: bytes) -> Callable:
    """Deserialize an exported artifact into a callable (reference
    registry.cc lookup + triton_aot_runtime launch)."""
    exp = jax_export.deserialize(blob)
    return exp.call


def aot_compile_spaces(spaces: dict):
    """Decorator declaring named export spaces (API parity with the
    reference's ``@aot_compile_spaces``, compile_aot.py:61): each entry
    maps a space name to example args. ``fn.aot_artifacts()`` exports
    them all."""
    def wrap(fn):
        def aot_artifacts(platforms=None) -> dict[str, bytes]:
            return {name: aot_export(fn, args, platforms=platforms)
                    for name, args in spaces.items()}
        fn.aot_artifacts = aot_artifacts
        fn.aot_spaces = spaces
        return fn
    return wrap


def save_artifacts(artifacts: dict[str, bytes], out_dir: str) -> list[str]:
    """Write artifacts to ``<out_dir>/<name>.jaxexport`` (reference
    gen_aot_code.sh output tree)."""
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    for name, blob in artifacts.items():
        p = os.path.join(out_dir, f"{name}.jaxexport")
        with open(p, "wb") as f:
            f.write(blob)
        paths.append(p)
    return paths


def load_artifact(path: str) -> Callable:
    with open(path, "rb") as f:
        return aot_load(f.read())
