"""Tooling (reference L9: python/triton_dist/tools/ + kernel-side aids):
AOT export (``aot.py`` ≙ compile_aot.py), distributed-synchronized
autotuner (``autotuner.py`` ≙ kernels/nvidia/autotuner.py), SOL perf
models (``perf_model.py`` ≙ gemm_perf_model.py / comm_perf_model.py),
profiling (``profiler.py`` ≙ utils.py group_profile).
"""

from triton_dist_tpu.tools.autotuner import autotune, TuneResult  # noqa: F401
from triton_dist_tpu.tools.perf_model import (  # noqa: F401
    ChipSpec, get_chip_spec, estimate_gemm_sol_time_ms,
    estimate_all_gather_time_ms, estimate_reduce_scatter_time_ms,
    estimate_all_reduce_time_ms, overlap_efficiency)
from triton_dist_tpu.tools.profiler import (  # noqa: F401
    group_profile, annotate, trace_files)
from triton_dist_tpu.tools.aot import (  # noqa: F401
    aot_export, aot_load, aot_compile_spaces, save_artifacts,
    load_artifact)
