"""Finetune CLI: HF checkpoint → sharded training loop → orbax save.

End-to-end glue for the training stack (beyond-reference — the
reference is inference-only): ``AutoLLM.from_pretrained`` loads and
TP-shards the safetensors weights, ``models.train.make_train_step``
runs the loss/grad/optax step in any differentiable mode (including
the fused ``ag_rs`` path), and ``models.checkpoint`` saves a resumable
{params, opt_state} orbax checkpoint.

    tdt-finetune --model ./Qwen3-0.6B --data corpus.txt --steps 100 \
        --mode ag_rs --out ./ckpt

Tokenization uses the checkpoint's HF tokenizer when present, else
falls back to UTF-8 bytes (mod vocab) so weight-only dirs still work.
"""

from __future__ import annotations

import argparse
import os
import time


def _tokenize(model_dir: str, text: str, vocab_size: int):
    """HF tokenizer if the dir ships one, else UTF-8 bytes mod vocab."""
    import numpy as np
    try:
        from transformers import AutoTokenizer
        tok = AutoTokenizer.from_pretrained(model_dir)
        ids = tok(text, return_tensors="np")["input_ids"][0]
        source = "hf"
    except Exception:  # noqa: BLE001 — weight-only dir / no tokenizer
        ids = np.frombuffer(text.encode("utf-8"), np.uint8)
        source = "bytes"
    return np.asarray(ids, np.int32) % vocab_size, source


def _batches(ids, batch: int, seq: int):
    """Cycle (B, S) next-token batches over the token stream."""
    import numpy as np
    n = batch * seq
    if len(ids) < n:
        reps = -(-n // max(len(ids), 1))
        ids = np.tile(ids, reps)
    usable = len(ids) - len(ids) % n
    chunks = ids[:usable].reshape(-1, batch, seq)
    i = 0
    while True:
        yield chunks[i % len(chunks)]
        i += 1


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="tdt-finetune",
        description="finetune an HF checkpoint with the fused TP stack")
    ap.add_argument("--model", required=True, help="HF checkpoint dir")
    ap.add_argument("--data", required=True, help="UTF-8 text file")
    ap.add_argument("--out", required=True, help="orbax checkpoint dir")
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--lr", type=float, default=2e-5)
    ap.add_argument("--mode", default="ag_rs",
                    help="xla | xla_ar | ag_rs | gemm_ar")
    ap.add_argument("--impl", default="pallas")
    ap.add_argument("--remat", action="store_true",
                    help="per-layer activation checkpointing")
    ap.add_argument("--resume", default=None,
                    help="orbax dir to resume params+opt_state from")
    ap.add_argument("--log-every", type=int, default=10)
    args = ap.parse_args(argv)

    import jax
    import optax

    from triton_dist_tpu.models import AutoLLM, make_train_step
    from triton_dist_tpu.models.checkpoint import load_params, save_params
    from triton_dist_tpu.runtime.dist import initialize_distributed

    initialize_distributed({"tp": len(jax.devices())})
    model, params = AutoLLM.from_pretrained(args.model, fwd_mode=args.mode,
                                            impl=args.impl)
    with open(args.data, encoding="utf-8") as f:
        text = f.read()
    ids, source = _tokenize(args.model, text, model.config.vocab_size)
    if len(ids) == 0:
        raise SystemExit(f"--data {args.data} produced no tokens")
    print(f"[finetune] {len(ids)} tokens ({source}), "
          f"{args.batch}x{args.seq} batches, mode={args.mode}")

    step, init_opt = make_train_step(
        model, optax.adamw(args.lr, mu_dtype=jax.numpy.float32),
        mode=args.mode, remat=args.remat)
    opt_state = init_opt(params)
    if args.resume:
        restored = load_params(args.resume, like={"params": params,
                                                  "opt_state": opt_state})
        params, opt_state = restored["params"], restored["opt_state"]
        print(f"[finetune] resumed from {args.resume}")

    t0 = time.perf_counter()
    last = None
    for i, chunk in zip(range(args.steps), _batches(ids, args.batch,
                                                    args.seq)):
        params, opt_state, m = step(params, opt_state,
                                    {"input_ids": jax.numpy.asarray(chunk)})
        last = float(m["loss"])
        if i % args.log_every == 0 or i == args.steps - 1:
            dt = time.perf_counter() - t0
            tps = (i + 1) * args.batch * args.seq / max(dt, 1e-9)
            print(f"[finetune] step {i:>5} loss {last:.4f} "
                  f"grad_norm {float(m['grad_norm']):.3f} "
                  f"({tps:,.0f} tok/s)", flush=True)

    save_params(os.path.abspath(args.out),
                {"params": params, "opt_state": opt_state})
    print(f"[finetune] saved {args.out} (final loss {last:.4f})")
    return last


if __name__ == "__main__":
    main()
