"""Finetune CLI: HF checkpoint → sharded training loop → orbax save.

End-to-end glue for the training stack (beyond-reference — the
reference is inference-only): ``AutoLLM.from_pretrained`` loads and
TP-shards the safetensors weights, ``models.train.make_train_step``
runs the loss/grad/optax step in any differentiable mode (including
the fused ``ag_rs`` path), and ``models.checkpoint`` saves a resumable
{params, opt_state} orbax checkpoint.

    tdt-finetune --model ./Qwen3-0.6B --data corpus.txt --steps 100 \
        --mode ag_rs --out ./ckpt

Tokenization uses the checkpoint's HF tokenizer when present, else
falls back to UTF-8 bytes (mod vocab) so weight-only dirs still work.
"""

from __future__ import annotations

import argparse
import os
import time


def _tokenize(model_dir: str, text: str, vocab_size: int):
    """HF tokenizer if the dir ships one, else UTF-8 bytes mod vocab."""
    import numpy as np
    try:
        from transformers import AutoTokenizer
        tok = AutoTokenizer.from_pretrained(model_dir)
        ids = tok(text, return_tensors="np")["input_ids"][0]
        source = "hf"
    except Exception:  # noqa: BLE001 — weight-only dir / no tokenizer
        ids = np.frombuffer(text.encode("utf-8"), np.uint8)
        source = "bytes"
    return np.asarray(ids, np.int32) % vocab_size, source


def _batches(ids, batch: int, seq: int, start: int = 0):
    """Cycle (B, S) next-token batches over the token stream;
    ``start`` fast-forwards the cycle for deterministic resume."""
    import numpy as np
    n = batch * seq
    if len(ids) < n:
        reps = -(-n // max(len(ids), 1))
        ids = np.tile(ids, reps)
    usable = len(ids) - len(ids) % n
    chunks = ids[:usable].reshape(-1, batch, seq)
    i = start
    while True:
        yield chunks[i % len(chunks)]
        i += 1


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="tdt-finetune",
        description="finetune an HF checkpoint with the fused TP stack")
    ap.add_argument("--model", required=True, help="HF checkpoint dir")
    ap.add_argument("--data", required=True,
                    help="UTF-8 text file, or a pre-packed int32 token "
                         "shard (*.bin — memory-mapped, native shuffled "
                         "epochs; see tools.data.pack_tokens)")
    ap.add_argument("--out", required=True, help="orbax checkpoint dir")
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--lr", type=float, default=2e-5)
    ap.add_argument("--mode", default="ag_rs",
                    help="xla | xla_ar | ag_rs | gemm_ar")
    ap.add_argument("--impl", default="pallas")
    ap.add_argument("--remat", action="store_true",
                    help="per-layer activation checkpointing")
    ap.add_argument("--resume", default=None,
                    help="orbax dir to resume params+opt_state from")
    ap.add_argument("--log-every", type=int, default=10)
    args = ap.parse_args(argv)

    import jax
    import optax

    from triton_dist_tpu.models import AutoLLM, make_train_step
    from triton_dist_tpu.models.checkpoint import load_params, save_params
    from triton_dist_tpu.runtime.dist import initialize_distributed

    import numpy as np

    initialize_distributed({"tp": len(jax.devices())})
    model, params = AutoLLM.from_pretrained(args.model, fwd_mode=args.mode,
                                            impl=args.impl)
    vocab = model.config.vocab_size

    step, init_opt = make_train_step(
        model, optax.adamw(args.lr, mu_dtype=jax.numpy.float32),
        mode=args.mode, remat=args.remat)
    opt_state = init_opt(params)
    step0 = 0
    if args.resume:
        like = {"params": params, "opt_state": opt_state,
                "step": np.zeros((), np.int32)}  # 0-d array: orbax
        # rejects bare numpy scalars
        restored = load_params(args.resume, like=like)
        params, opt_state = restored["params"], restored["opt_state"]
        step0 = int(restored["step"])
        print(f"[finetune] resumed from {args.resume} at step {step0}")

    if args.data.endswith(".bin"):
        # Pre-packed int32 token shard: memory-mapped, batched by the
        # native loader (tools/data.py; seeded shuffled epochs). The
        # resumed step count fast-forwards the deterministic stream so
        # the run continues with batches the saved run never saw.
        from triton_dist_tpu.tools.data import TokenDataset
        ds = TokenDataset(args.data, args.batch, args.seq)
        batch_iter = ds.batches(seed=0, start_batch=step0)
        n_tokens, source = len(ds.data), "bin"
    else:
        with open(args.data, encoding="utf-8") as f:
            text = f.read()
        ids, source = _tokenize(args.model, text, vocab)
        if len(ids) == 0:
            raise SystemExit(f"--data {args.data} produced no tokens")
        batch_iter = _batches(ids, args.batch, args.seq, start=step0)
        n_tokens = len(ids)
    print(f"[finetune] {n_tokens} tokens ({source}), "
          f"{args.batch}x{args.seq} batches, mode={args.mode}")

    t0 = time.perf_counter()
    last = None
    for i, chunk in zip(range(args.steps), batch_iter):
        chunk = np.asarray(chunk)
        if chunk.min() < 0 or chunk.max() >= vocab:
            # XLA clamps out-of-range gather ids silently — training on
            # a mis-packed shard must fail loudly instead.
            raise SystemExit(
                f"--data token ids outside [0, {vocab}) at step {i} "
                f"(min {chunk.min()}, max {chunk.max()}): shard packed "
                "with an incompatible tokenizer?")
        params, opt_state, m = step(params, opt_state,
                                    {"input_ids": jax.numpy.asarray(chunk)})
        last = float(m["loss"])
        if i % args.log_every == 0 or i == args.steps - 1:
            dt = time.perf_counter() - t0
            tps = (i + 1) * args.batch * args.seq / max(dt, 1e-9)
            print(f"[finetune] step {step0 + i:>5} loss {last:.4f} "
                  f"grad_norm {float(m['grad_norm']):.3f} "
                  f"({tps:,.0f} tok/s)", flush=True)

    save_params(os.path.abspath(args.out),
                {"params": params, "opt_state": opt_state,
                 "step": np.asarray(step0 + args.steps, np.int32)})
    print(f"[finetune] saved {args.out} (final loss {last:.4f})")
    return last


if __name__ == "__main__":
    main()
