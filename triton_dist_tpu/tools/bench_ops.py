"""Per-op shape-sweep microbenchmarks.

The reference ends every op test with a perf loop over shapes
(test/nvidia/test_ag_gemm.py:72-197: correctness then `perf_func` +
`group_profile` per (M, N, K)); this is that harness as a standalone
tool. Each case checks correctness against the op's XLA golden first —
a wrong kernel's throughput is meaningless — then times both paths with
the tunnel-safe chained-slope method (docs/perf.md). Every shape is
failure-isolated (a VMEM/compile failure emits an error row and the
sweep continues) and rows are written as they finish, so a crash late
in an expensive TPU session cannot discard earlier results.

Usage:
    python -m triton_dist_tpu.tools.bench_ops [--op ag_gemm]
        [--json out.jsonl]

On CPU hosts the sweep runs interpret-mode (tiny shapes, correctness
spot-check of the harness itself); real numbers need the TPU.
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def _init_mesh(timeout_s: float = 240.0):
    """Backend init with the wedged-tunnel guard (subprocess probe +
    deadline, like bench.py's `_init_backend`)."""
    import jax
    from jax.sharding import Mesh
    import os
    if os.environ.get("JAX_PLATFORMS", "") != "cpu":
        import subprocess
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            capture_output=True, timeout=timeout_s)
        if probe.returncode != 0:
            raise RuntimeError(
                f"backend probe failed: {probe.stderr.decode()[-200:]}")
    devices = jax.devices()
    return Mesh(np.array(devices), ("tp",)), len(devices)


def _is_tpu():
    from triton_dist_tpu.runtime.platform import is_tpu
    return is_tpu()


def _time(step, x0):
    from triton_dist_tpu.runtime.utils import perf_func_chained
    # CPU interpret-mode exists only to prove the harness runs; keep the
    # chains short there (each step re-runs the Pallas interpreter).
    iters = (8, 24) if _is_tpu() else (1, 3)
    return perf_func_chained(step, x0, iters)


def _emit(row, out):
    out.write(json.dumps(row) + "\n")
    out.flush()


def _sweep_gemm_family(op_name, mesh, world, shapes, out):
    """Shared sweep for the collective-matmul ops: ag_gemm (row-sharded
    A, column-sharded B) and gemm_rs (col-sharded A, row-sharded B).
    The chain fold is CHEAP (scaled slice tiled back to the input
    shape) so the timed step is dominated by the op under test, and the
    (M/w, N) gemm_rs output is tiled back up so `x = step(x)` chains at
    any world size."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from triton_dist_tpu.runtime.utils import assert_allclose

    if op_name == "ag_gemm":
        from triton_dist_tpu.ops.allgather_gemm import (
            ag_gemm as op, create_ag_gemm_context as mk_ctx)
        a_spec, b_spec = P("tp"), P(None, "tp")
    else:
        from triton_dist_tpu.ops.gemm_reduce_scatter import (
            create_gemm_rs_context as mk_ctx, gemm_rs as op)
        a_spec, b_spec = P(None, "tp"), P("tp")

    for (m, k, n) in shapes:
        row = {"op": op_name, "m": m, "k": k, "n": n}
        try:
            ctx = mk_ctx(mesh, "tp")
            a0 = jax.device_put(
                jax.random.normal(jax.random.PRNGKey(0), (m, k),
                                  jnp.float32).astype(jnp.bfloat16),
                NamedSharding(mesh, a_spec))
            b = jax.device_put(
                (jax.random.normal(jax.random.PRNGKey(1), (k, n),
                                   jnp.float32) / 8).astype(jnp.bfloat16),
                NamedSharding(mesh, b_spec))
            assert_allclose(op(a0, b, ctx, impl="pallas"),
                            op(a0, b, ctx, impl="xla"),
                            rtol=3e-2, atol=3e-2)

            def mk(impl):
                @jax.jit
                def step(a):
                    c = op(a, b, ctx, impl=impl)
                    # cheap fold back to (m, k): scaled slice, tiled up
                    sl = (c[:, :k] if c.shape[1] >= k else
                          jnp.tile(c, (1, -(-k // c.shape[1])))[:, :k])
                    reps = -(-m // sl.shape[0])
                    return (jnp.tile(sl, (reps, 1))[:m]
                            * jnp.asarray(2 ** -4, jnp.bfloat16))
                return step

            ms_p, ms_x = _time(mk("pallas"), a0), _time(mk("xla"), a0)
            flops = 2 * m * k * n
            row.update({
                "pallas_ms": round(ms_p, 4), "xla_ms": round(ms_x, 4),
                "tflops_per_chip": round(
                    flops / world / (ms_p * 1e-3) / 1e12, 2),
                "vs_xla": round(ms_x / ms_p, 4)})
        except Exception as e:  # noqa: BLE001 — per-shape isolation
            row["error"] = repr(e)[:200]
        _emit(row, out)


def sweep_ag_gemm(mesh, world, shapes, out):
    _sweep_gemm_family("ag_gemm", mesh, world, shapes, out)


def sweep_gemm_rs(mesh, world, shapes, out):
    _sweep_gemm_family("gemm_rs", mesh, world, shapes, out)


def sweep_flash_decode(mesh, world, shapes, out):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from triton_dist_tpu.ops.flash_decode import (
        create_flash_decode_context, gqa_fwd_batch_decode)
    from triton_dist_tpu.runtime.utils import assert_allclose

    for (b, hq, hkv, d, t) in shapes:
        row = {"op": "flash_decode", "b": b, "hq": hq, "hkv": hkv,
               "d": d, "t": t}
        try:
            ctx = create_flash_decode_context(mesh, "tp", variant="tiled",
                                              t_blk=min(512, t // world))
            q0 = jax.random.normal(jax.random.PRNGKey(0), (b, hq, d),
                                   jnp.float32).astype(jnp.bfloat16)
            sh = NamedSharding(mesh, P(None, "tp"))
            kc = jax.device_put(jax.random.normal(
                jax.random.PRNGKey(1), (b, t, hkv, d), jnp.float32
            ).astype(jnp.bfloat16), sh)
            vc = jax.device_put(jax.random.normal(
                jax.random.PRNGKey(2), (b, t, hkv, d), jnp.float32
            ).astype(jnp.bfloat16), sh)
            n = jnp.int32(t - 1)
            assert_allclose(
                gqa_fwd_batch_decode(q0, kc, vc, n, ctx, impl="pallas"),
                gqa_fwd_batch_decode(q0, kc, vc, n, ctx, impl="xla"),
                rtol=3e-2, atol=3e-2)

            def mk(impl):
                @jax.jit
                def step(q):
                    o = gqa_fwd_batch_decode(q, kc, vc, n, ctx, impl=impl)
                    return (o.astype(jnp.float32) * 0.5 + 0.25
                            ).astype(q.dtype)
                return step

            ms_p, ms_x = _time(mk("pallas"), q0), _time(mk("xla"), q0)
            row.update({"pallas_ms": round(ms_p, 4),
                        "xla_ms": round(ms_x, 4),
                        "vs_xla": round(ms_x / ms_p, 4)})
        except Exception as e:  # noqa: BLE001 — per-shape isolation
            row["error"] = repr(e)[:200]
        _emit(row, out)


# ---------------------------------------------------------------------------
# Regression gate (--regress): compare *_vs_xla ratios against the
# checked-in floors in BASELINE.json and exit nonzero on a drop.
# ---------------------------------------------------------------------------

def _default_baseline_path() -> str:
    import os
    return os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "BASELINE.json")


def load_floors(baseline_path: str, tier: str) -> dict:
    """Floor dict for ``tier`` ("tpu" | "cpu") from BASELINE.json's
    ``regression_floors``. The cpu tier is deliberately lax (near-zero
    floors): a CPU smoke asserts the harness runs end to end and the
    keys exist, not interpret-mode throughput."""
    with open(baseline_path) as f:
        floors = json.load(f).get("regression_floors", {})
    if tier not in floors:
        raise SystemExit(
            f"BASELINE.json regression_floors has no {tier!r} tier "
            f"(found {sorted(floors)})")
    return {k: v for k, v in floors[tier].items()
            if not k.startswith("_")}


def check_regression(extras: dict, floors: dict) -> list[str]:
    """Machine-check a bench run's ratios against the floors.

    Returns failure strings (empty = pass). A missing or non-numeric
    key fails — that is how the CPU smoke asserts the harness produced
    every metric end to end — and a non-null ``baseline_anomaly``
    fails outright: when the same-matmul XLA baselines disagree, every
    vs_xla ratio in the run is untrustworthy (docs/perf.md), so a
    "pass" against floors would be meaningless.
    """
    fails = []
    for key, floor in sorted(floors.items()):
        val = extras.get(key)
        if not isinstance(val, (int, float)):
            fails.append(f"{key}: missing (floor {floor})")
        elif float(val) < float(floor):
            fails.append(f"{key}: {val} < floor {floor}")
    anom = extras.get("baseline_anomaly")
    if anom:
        fails.append(f"baseline_anomaly is set - ratios untrustworthy: "
                     f"{anom}")
    return fails


#: Rolling-window serving percentiles a bench run's extras must carry
#: once it produced serving numbers (ISSUE 8): lifetime-histogram
#: percentiles hide a fresh regression under hours of good samples, so
#: the gate pins the extras to the WINDOWED gauges.
SERVING_ROLLING_KEYS = (
    "serving_rolling_ttft_p50_ms", "serving_rolling_ttft_p99_ms",
    "serving_rolling_tpot_p50_ms", "serving_rolling_tpot_p99_ms",
)


#: Fused-family bench parts that must publish MEASURED overlap
#: evidence (ISSUE 10): once a part ran (its `<part>_pallas_ms` /
#: fused-ms key exists), its extras must carry either a numeric
#: `<part>_overlap_pct_measured` (chip, world>1) or an explicit
#: marker — `<part>_overlap_requires_chip` (no comm events in the
#: profiled window) or `<part>_profile_error` / `_profile_unattributed`
#: (the capture path failed, recorded rather than silently absent).
#: (part, ran-sentinel-key) pairs.
OVERLAP_MEASURED_PARTS = (
    ("ag_gemm", "ag_gemm_pallas_ms"),
    ("gemm_rs", "gemm_rs_pallas_ms"),
    ("gemm_ar", "gemm_ar_pallas_ms"),
    ("tp_mlp", "tp_mlp_fused_ms"),
)


def check_overlap_measured_wellformed(extras: dict) -> list[str]:
    """Failure strings when a fused-family part ran without leaving
    measured-overlap evidence, or left a malformed value. The measured
    number is the device-timeline tier of the overlap accounting
    (docs/perf.md): a part publishing neither the number nor an
    explicit marker would let the next chip window report modeled
    numbers as if they were measured again."""
    fails = []
    for part, ran_key in OVERLAP_MEASURED_PARTS:
        if ran_key not in extras:
            continue          # part did not run this time
        val = extras.get(f"{part}_overlap_pct_measured")
        if val is not None:
            if not isinstance(val, (int, float)) \
                    or isinstance(val, bool) \
                    or not 0.0 <= float(val) <= 100.0:
                fails.append(f"{part}_overlap_pct_measured: malformed "
                             f"value {val!r} (want 0..100)")
            continue
        if not (extras.get(f"{part}_overlap_requires_chip")
                or extras.get(f"{part}_profile_error")
                or extras.get(f"{part}_profile_unattributed")):
            fails.append(
                f"{part}: ran but published neither "
                f"{part}_overlap_pct_measured nor an explicit "
                f"overlap_requires_chip / profile_error marker")
    return fails


def load_measured_overlap_floors(baseline_path: str, tier: str) -> dict:
    """Per-tier floors for `*_overlap_pct_measured` from BASELINE.json
    ``measured_overlap_floors`` (absent → empty). Deliberately
    generous: the hook exists so the NEXT chip window's measured
    numbers are machine-compared, not so today's 0% chip evidence
    fails retroactively."""
    with open(baseline_path) as f:
        floors = json.load(f).get("measured_overlap_floors", {})
    return {k: v for k, v in floors.get(tier, {}).items()
            if not k.startswith("_")}


def check_measured_overlap_floors(extras: dict, floors: dict) \
        -> list[str]:
    """Compare `*_overlap_pct_measured` values that EXIST against the
    tier floors (a CPU run's explicit requires-chip marker passes the
    wellformedness check instead; a present-but-below value fails)."""
    fails = []
    for key, floor in sorted(floors.items()):
        val = extras.get(key)
        if isinstance(val, (int, float)) and not isinstance(val, bool) \
                and float(val) < float(floor):
            fails.append(f"{key}: {val} < measured-overlap floor "
                         f"{floor}")
    return fails


def check_serving_wellformed(extras: dict) -> list[str]:
    """Failure strings when a run that measured serving throughput is
    missing its rolling-window TTFT/TPOT percentiles (empty when the
    serving part did not run — kernel-only sweeps pass untouched — or
    when the run recorded the explicit ``TDT_SLO=0`` opt-out)."""
    if "serving_tokens_per_s" not in extras:
        return []
    if extras.get("serving_rolling_disabled"):
        return []
    return [f"{k}: missing/non-numeric (serving extras must carry "
            f"rolling-window percentiles)"
            for k in SERVING_ROLLING_KEYS
            if not isinstance(extras.get(k), (int, float))
            or isinstance(extras.get(k), bool)]


def check_mega_serving_wellformed(extras: dict) -> list[str]:
    """Failure strings when the serving_mega part ran (its tokens/s
    key exists) without publishing a well-formed
    ``serving_mega_vs_plain`` ratio (ISSUE 11): the mega-in-scheduler
    number is the composition evidence ROADMAP item 1 asks for, and a
    run that silently dropped it would let the next chip window claim
    the two subsystems compose without a machine-readable ratio.
    Empty when the part did not run."""
    if "serving_mega_tokens_per_s" not in extras:
        return []
    v = extras.get("serving_mega_vs_plain")
    if not isinstance(v, (int, float)) or isinstance(v, bool) \
            or float(v) <= 0.0:
        return [f"serving_mega_vs_plain: missing/malformed ({v!r}) — "
                f"the serving_mega part ran but published no "
                f"mega-vs-plain scheduler ratio"]
    return []


def check_spec_serving_wellformed(extras: dict) -> list[str]:
    """Failure strings when the serving_spec part ran (its tokens/s
    key exists) without publishing a well-formed
    ``serving_spec_vs_plain`` ratio and accept-rate evidence
    (ISSUE 13): the spec-on-vs-off scheduler ratio is the acceptance
    bar, and the accept rate is what explains it — a run that
    silently dropped either would let a drafter regression hide
    behind a stale floor pass. Empty when the part did not run."""
    if "serving_spec_tokens_per_s" not in extras:
        return []
    fails = []
    v = extras.get("serving_spec_vs_plain")
    if not isinstance(v, (int, float)) or isinstance(v, bool) \
            or float(v) <= 0.0:
        fails.append(
            f"serving_spec_vs_plain: missing/malformed ({v!r}) — the "
            f"serving_spec part ran but published no spec-vs-plain "
            f"scheduler ratio")
    r = extras.get("serving_spec_accept_rate")
    if not isinstance(r, (int, float)) or isinstance(r, bool) \
            or not 0.0 <= float(r) <= 1.0:
        fails.append(
            f"serving_spec_accept_rate: missing/malformed ({r!r}) — "
            f"want a rate in [0, 1]")
    return fails


def check_fleet_wellformed(extras: dict) -> list[str]:
    """Failure strings when the serving_fleet part ran (its tokens/s
    key exists) without leaving well-formed fleet evidence
    (ISSUE 14): the two-replica-vs-one ratio must be present and
    positive, the per-replica rows must exist (at least two replica
    ids — a "fleet" of one would fake the scale-out number), no
    replica may have been ``down`` after the timed window, EVERY
    replica must have retired rows during the window (a replica whose
    pump died mid-window still answers health from its handler
    threads, so liveness alone cannot catch it — its retired-delta
    can), and no request in either timed leg may have errored (a
    fanout half-landing on a dead replica would otherwise publish a
    fleet tokens/s that is really a single-replica number). Empty
    when the part did not run."""
    if "serving_fleet_tokens_per_s" not in extras:
        return []
    fails = []
    v = extras.get("serving_fleet_vs_single")
    if not isinstance(v, (int, float)) or isinstance(v, bool) \
            or float(v) <= 0.0:
        fails.append(
            f"serving_fleet_vs_single: missing/malformed ({v!r}) — "
            f"the serving_fleet part ran but published no "
            f"fleet-vs-single ratio")
    ids = extras.get("serving_fleet_replica_ids")
    if not isinstance(ids, (list, tuple)) or len(ids) < 2 \
            or len(set(ids)) != len(ids):
        fails.append(
            f"serving_fleet_replica_ids: want >= 2 distinct replica "
            f"rows, got {ids!r}")
    down = extras.get("serving_fleet_down_replicas")
    if not isinstance(down, (int, float)) or isinstance(down, bool):
        fails.append(
            f"serving_fleet_down_replicas: missing/malformed "
            f"({down!r})")
    elif down:
        fails.append(
            f"serving_fleet_down_replicas: {down} replica(s) were not "
            f"live during the timed window — the fleet tokens/s is "
            f"not a 2-replica number")
    retired = extras.get("serving_fleet_replica_retired")
    if not isinstance(retired, (list, tuple)) or len(retired) < 2:
        fails.append(
            f"serving_fleet_replica_retired: want >= 2 per-replica "
            f"retired-deltas, got {retired!r}")
    elif not all(isinstance(r, (int, float))
                 and not isinstance(r, bool) and r > 0
                 for r in retired):
        fails.append(
            f"serving_fleet_replica_retired: every replica must have "
            f"retired rows in the timed window, got {retired!r} — a "
            f"dead-pump replica served nothing")
    for key in ("serving_fleet_error_count",
                "serving_fleet_single_error_count"):
        n = extras.get(key)
        if not isinstance(n, (int, float)) or isinstance(n, bool):
            fails.append(f"{key}: missing/malformed ({n!r})")
        elif n:
            fails.append(
                f"{key}: {n} request(s) errored in the timed window — "
                f"the tokens/s numbers are not comparable")
    return fails


#: Slack on the down-detection deadline: "down" is DEFINED as
#: last-good-scrape age exceeding the down threshold, so detection can
#: never land meaningfully under it — what the gate must catch is a
#: router that missed the death by a poll period or more, not the
#: sub-second scrape/poll lag inherent to the mechanism.
DOWN_DETECT_SLACK_S = 2.0


def check_router_wellformed(extras: dict) -> list[str]:
    """Failure strings when the serving_router part ran (its tokens/s
    key exists) without leaving well-formed fault-tolerance evidence
    (ISSUE 15). The kill window is the part's whole point, so when
    the part ran its kill keys are REQUIRED:

    - ``serving_router_vs_direct`` present and positive (router
      overhead vs client-side round-robin on the same fleet);
    - ``serving_router_kill_client_errors`` == 0 — killing one of
      three replicas mid-window must cost ZERO client-visible
      failures (the acceptance bar);
    - ``serving_router_failovers`` ≥ 1 — at least one request was
      actually re-dispatched (zero would mean the kill window missed
      every in-flight request and proved nothing);
    - ``serving_router_down_detect_s`` ≤ ``serving_router_down_s`` +
      :data:`DOWN_DETECT_SLACK_S` (the configured
      TDT_FLEET_DOWN_S-style age, plus the scrape/poll lag the
      mechanism cannot avoid) — the router noticed the death within
      its own threshold.

    Empty when the part did not run."""
    if "serving_router_tokens_per_s" not in extras:
        return []
    fails = []
    v = extras.get("serving_router_vs_direct")
    if not isinstance(v, (int, float)) or isinstance(v, bool) \
            or float(v) <= 0.0:
        fails.append(
            f"serving_router_vs_direct: missing/malformed ({v!r}) — "
            f"the serving_router part ran but published no "
            f"router-vs-direct ratio")
    errs = extras.get("serving_router_kill_client_errors")
    if not isinstance(errs, (int, float)) or isinstance(errs, bool):
        fails.append(f"serving_router_kill_client_errors: "
                     f"missing/malformed ({errs!r})")
    elif errs:
        fails.append(
            f"serving_router_kill_client_errors: {errs} client-"
            f"visible failure(s) during the kill window — the router "
            f"did not absorb the replica death")
    fo = extras.get("serving_router_failovers")
    if not isinstance(fo, (int, float)) or isinstance(fo, bool) \
            or fo < 1:
        fails.append(
            f"serving_router_failovers: want >= 1 recorded failover "
            f"in the kill window, got {fo!r} — zero means no request "
            f"was in flight on the victim and the window proved "
            f"nothing")
    det = extras.get("serving_router_down_detect_s")
    down_s = extras.get("serving_router_down_s")
    if not isinstance(det, (int, float)) or isinstance(det, bool) \
            or not isinstance(down_s, (int, float)) \
            or isinstance(down_s, bool):
        fails.append(
            f"serving_router_down_detect_s/serving_router_down_s: "
            f"missing/malformed ({det!r}/{down_s!r})")
    elif det > down_s + DOWN_DETECT_SLACK_S:
        fails.append(
            f"serving_router_down_detect_s: {det} > configured down "
            f"age {down_s} + {DOWN_DETECT_SLACK_S}s slack — the "
            f"router missed its detection deadline")
    return fails


def check_history_wellformed(extras: dict) -> list[str]:
    """Failure strings when the serving_history part ran (its
    tokens/s key exists) without leaving well-formed history-plane
    evidence (ISSUE 16):

    - ``serving_history_on_vs_off`` present and positive (the
      sampler-on vs sampler-off throughput ratio the BASELINE.json
      cpu floor gates — this check guards SHAPE, the floor guards
      magnitude);
    - ``serving_history_ticks`` ≥ 1 — the 20 Hz sampler must have
      actually ticked during the on-leg (zero would mean the ratio
      priced nothing);
    - ``serving_history_series`` ≥ 1 — at least one series was
      recorded and shipped back through ``{"cmd": "history"}`` (the
      pump publishes queue/occupancy gauges every working iteration,
      so an empty snapshot means the verb or the sampler is broken).

    Empty when the part did not run."""
    if "serving_history_tokens_per_s" not in extras:
        return []
    fails = []
    v = extras.get("serving_history_on_vs_off")
    if not isinstance(v, (int, float)) or isinstance(v, bool) \
            or float(v) <= 0.0:
        fails.append(
            f"serving_history_on_vs_off: missing/malformed ({v!r}) — "
            f"the serving_history part ran but published no "
            f"on-vs-off ratio")
    ticks = extras.get("serving_history_ticks")
    if not isinstance(ticks, (int, float)) or isinstance(ticks, bool) \
            or ticks < 1:
        fails.append(
            f"serving_history_ticks: want >= 1 sampler tick in the "
            f"on-leg, got {ticks!r} — the overhead ratio priced a "
            f"sampler that never ran")
    series = extras.get("serving_history_series")
    if not isinstance(series, (int, float)) \
            or isinstance(series, bool) or series < 1:
        fails.append(
            f"serving_history_series: want >= 1 recorded series in "
            f"the on-leg history snapshot, got {series!r}")
    return fails


def check_disagg_wellformed(extras: dict) -> list[str]:
    """Failure strings when the serving_disagg part ran (its tokens/s
    key exists) without leaving well-formed disaggregation evidence
    (ISSUE 18):

    - ``serving_disagg_vs_unified`` present and positive (the 1
      prefill + 2 decode fleet vs 3 unified replicas on the same
      workload — the BASELINE.json cpu floor gates magnitude, this
      check guards shape);
    - ``serving_disagg_handoffs`` ≥ 1 — at least one prefill→decode
      KV stream actually completed (zero would mean every request
      fell back and the ratio compared nothing);
    - ``serving_disagg_dedup_ratio`` in [0, 1] — blocks deduped over
      blocks offered: the content-addressed negotiation's yield is a
      RATIO by construction, anything outside the interval means the
      counters are wrong, not the workload.

    Empty when the part did not run."""
    if "serving_disagg_tokens_per_s" not in extras:
        return []
    fails = []
    v = extras.get("serving_disagg_vs_unified")
    if not isinstance(v, (int, float)) or isinstance(v, bool) \
            or float(v) <= 0.0:
        fails.append(
            f"serving_disagg_vs_unified: missing/malformed ({v!r}) — "
            f"the serving_disagg part ran but published no "
            f"disagg-vs-unified ratio")
    ho = extras.get("serving_disagg_handoffs")
    if not isinstance(ho, (int, float)) or isinstance(ho, bool) \
            or ho < 1:
        fails.append(
            f"serving_disagg_handoffs: want >= 1 completed KV "
            f"handoff, got {ho!r} — the disagg leg fell back to "
            f"unified serving throughout")
    dr = extras.get("serving_disagg_dedup_ratio")
    if not isinstance(dr, (int, float)) or isinstance(dr, bool) \
            or not 0.0 <= float(dr) <= 1.0:
        fails.append(
            f"serving_disagg_dedup_ratio: want a ratio in [0, 1], "
            f"got {dr!r} — blocks_deduped/blocks_offered accounting "
            f"is broken")
    return fails


def _extras_from_file(path: str) -> dict:
    """Extras dict from any bench artifact: a bench.py checkpoint
    ({"extras": ...}), a bench.py result line ({"metric", "extras"}),
    or a plain extras dict."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict) and isinstance(data.get("extras"), dict):
        return data["extras"]
    return data


def _extras_from_sweep(mesh, world, on_tpu) -> dict:
    """Run the standard sweeps and fold rows into bench-style extras:
    per op the WORST (min) vs_xla across shapes, so a single regressed
    shape cannot hide behind a good one."""
    import io
    buf = io.StringIO()
    for name, (fn, tpu_shapes, cpu_shapes) in sorted(SWEEPS.items()):
        fn(mesh, world, tpu_shapes if on_tpu else cpu_shapes, buf)
    extras: dict = {}
    for line in buf.getvalue().splitlines():
        row = json.loads(line)
        key = f"{row['op']}_vs_xla"
        if "vs_xla" in row:
            extras[key] = min(extras.get(key, float("inf")),
                              row["vs_xla"])
        elif "error" in row:
            extras.setdefault(f"{row['op']}_errors", []).append(
                row["error"])
    extras["baseline_anomaly"] = None   # sweep shares one timing path
    return extras


def run_regress(baseline_path: str, from_file: str | None,
                tier: str | None) -> int:
    skipped: list = []
    if from_file:
        extras = _extras_from_file(from_file)
        if tier is None:
            tier = ("tpu" if "tpu" in str(extras.get("device_kind", "")
                                          ).lower() else "cpu")
    else:
        mesh, world = _init_mesh()
        on_tpu = _is_tpu()
        if tier is None:
            tier = "tpu" if on_tpu else "cpu"
        extras = _extras_from_sweep(mesh, world, on_tpu)
    floors = load_floors(baseline_path, tier)
    if not from_file:
        # The live sweep covers the SWEEPS ops only; floors for
        # bench.py-only metrics (gemm_ar, tp_mlp, ...) apply to --from
        # checkpoints. Without this filter the missing-key-fails
        # contract would make the live TPU gate structurally unpassable.
        sweep_keys = {f"{op}_vs_xla" for op in SWEEPS}
        skipped = sorted(set(floors) - sweep_keys)
        floors = {k: v for k, v in floors.items() if k in sweep_keys}
    fails = check_regression(extras, floors)
    fails += check_serving_wellformed(extras)
    fails += check_mega_serving_wellformed(extras)
    fails += check_spec_serving_wellformed(extras)
    fails += check_fleet_wellformed(extras)
    fails += check_router_wellformed(extras)
    fails += check_history_wellformed(extras)
    fails += check_disagg_wellformed(extras)
    fails += check_overlap_measured_wellformed(extras)
    fails += check_measured_overlap_floors(
        extras, load_measured_overlap_floors(baseline_path, tier))
    report = {"tier": tier, "floors": floors, "failures": fails,
              "floors_skipped_not_swept": skipped,
              "checked": {k: extras.get(k) for k in sorted(floors)}}
    print(json.dumps(report, indent=1))
    if fails:
        print(f"REGRESSION: {len(fails)} metric(s) below floor",
              file=sys.stderr)
        return 1
    print("regression gate: PASS", file=sys.stderr)
    return 0


SWEEPS = {
    "ag_gemm": (sweep_ag_gemm,
                [(2048, 4096, 4096), (4096, 4096, 4096),
                 (1024, 8192, 4096)],
                [(64, 64, 64)]),
    "gemm_rs": (sweep_gemm_rs,
                [(2048, 4096, 4096), (4096, 4096, 4096)],
                [(64, 64, 64)]),
    "flash_decode": (sweep_flash_decode,
                     [(8, 32, 8, 128, 8192), (1, 32, 8, 128, 32768),
                      (32, 32, 8, 128, 2048)],
                     [(2, 8, 2, 32, 64)]),
}


def main(argv=None):
    # 1-core CPU hosts deadlock interpret-mode semaphore waits unless the
    # affinity shim re-execs us first (runtime/cpu_shim.py; same call
    # every user-style script makes).
    from triton_dist_tpu.runtime.cpu_shim import maybe_reexec_with_shim
    maybe_reexec_with_shim()
    # The axon sitecustomize pins platforms to "axon,cpu" regardless of
    # the JAX_PLATFORMS env var; honoring a cpu request needs the config
    # set BEFORE backend init (otherwise a wedged tunnel hangs us here).
    import os
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")

    ap = argparse.ArgumentParser()
    ap.add_argument("--op", choices=sorted(SWEEPS) + ["all"],
                    default="all")
    ap.add_argument("--json", default=None,
                    help="append JSON lines here (default stdout)")
    ap.add_argument("--regress", action="store_true",
                    help="compare *_vs_xla ratios against BASELINE.json "
                         "regression_floors; exit 1 on a drop")
    ap.add_argument("--baseline", default=None,
                    help="floor file (default: repo BASELINE.json)")
    ap.add_argument("--from", dest="from_file", default=None,
                    help="take ratios from a bench checkpoint/result "
                         "JSON instead of running the sweep")
    ap.add_argument("--tier", choices=["tpu", "cpu"], default=None,
                    help="floor tier (default: by device_kind/backend)")
    args = ap.parse_args(argv)

    if args.regress:
        return run_regress(args.baseline or _default_baseline_path(),
                           args.from_file, args.tier)

    mesh, world = _init_mesh()
    on_tpu = _is_tpu()
    out = open(args.json, "a") if args.json else sys.stdout
    try:
        for name, (fn, tpu_shapes, cpu_shapes) in sorted(SWEEPS.items()):
            if args.op not in ("all", name):
                continue
            fn(mesh, world, tpu_shapes if on_tpu else cpu_shapes, out)
    finally:
        if args.json:
            out.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
