"""Distributed-synchronized autotuner.

TPU-native redesign of the reference's ``ContextualAutoTuner``
(python/triton_dist/kernels/nvidia/autotuner.py:43-250: sweeps configs
with barriers interleaved so ALL ranks pick the same config — a rank
divergence would deadlock the fused kernels' signal protocols).

Same hazard here: shard_map programs with different tuning params on
different hosts would compile different collectives. The sweep is
SPMD-deterministic (every process times the same candidates in the same
order) and the winner is broadcast from process 0
(``multihost_utils.broadcast_one_to_all``) so divergent clocks can't
split the decision.
"""

from __future__ import annotations

import dataclasses
from collections.abc import Callable, Sequence

import jax
import numpy as np

from triton_dist_tpu.runtime.utils import perf_func

_CACHE: dict = {}


@dataclasses.dataclass(frozen=True)
class TuneResult:
    config: dict
    avg_ms: float
    all_ms: tuple


def clear_cache():
    _CACHE.clear()


def autotune(make_fn: Callable[..., Callable], configs: Sequence[dict],
             key: str | None = None, iters: int = 20,
             warmup_iters: int = 5) -> TuneResult:
    """Pick the fastest config.

    Args:
      make_fn: config-kwargs → zero-arg callable running the op (the
        analog of re-launching the Triton kernel per config).
      configs: candidate dicts (reference per-op config tables, e.g.
        ``matmul_get_configs`` allgather_gemm.py:396).
      key: cache key — one sweep per key per process (reference caches on
        the Autotuner instance).
    Returns the winning TuneResult (same on every process).

    Failure isolation: a config that raises scores inf (skipped, like
    the reference's OutOfResources handling). On multi-host sweeps the
    per-config scores are agreed as the WORST rank's time, so a config
    failing anywhere loses everywhere; note that a non-SPMD-deterministic
    failure (raising on only some ranks mid-collective) can still desync
    the sweep itself — only configs whose failures are deterministic
    across ranks are fully safe to list.
    """
    if key is not None and key in _CACHE:
        return _CACHE[key]

    times = []
    errors = []
    for cfg in configs:
        # A config that fails to compile/run (e.g. VMEM overflow on this
        # chip generation) scores inf instead of killing the sweep — the
        # reference's Triton autotuner likewise skips OutOfResources
        # configs. This keeps aggressive candidates safe to list.
        try:
            fn = make_fn(**cfg)
            _, ms = perf_func(fn, iters=iters, warmup_iters=warmup_iters,
                              return_output=False)
        except Exception as e:  # noqa: BLE001 — per-config isolation
            ms = float("inf")
            errors.append((cfg, repr(e)[:200]))
        times.append(ms)

    if jax.process_count() > 1:
        # Agree on scores BEFORE picking: a config that failed on ANY
        # rank must lose everywhere (worst-rank time), and the cached
        # avg_ms must be the agreed number, not this rank's local inf
        # (code-review r3d findings 1/4). Residual hazard documented
        # above: a config failing on only SOME ranks may already have
        # desynced the sweep itself — per-config isolation is fully safe
        # only where failures are SPMD-deterministic.
        from jax.experimental import multihost_utils
        allt = np.asarray(multihost_utils.process_allgather(
            np.asarray(times, np.float64)))
        times = list(allt.reshape(jax.process_count(), -1).max(axis=0))
    if not np.isfinite(times).any():
        raise RuntimeError(f"every autotune config failed: {errors}")
    best = int(np.argmin(times))
    result = TuneResult(config=dict(configs[best]), avg_ms=times[best],
                        all_ms=tuple(times))
    if key is not None:
        _CACHE[key] = result
    return result
