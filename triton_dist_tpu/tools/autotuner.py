"""Distributed-synchronized autotuner.

TPU-native redesign of the reference's ``ContextualAutoTuner``
(python/triton_dist/kernels/nvidia/autotuner.py:43-250: sweeps configs
with barriers interleaved so ALL ranks pick the same config — a rank
divergence would deadlock the fused kernels' signal protocols).

Same hazard here: shard_map programs with different tuning params on
different hosts would compile different collectives. The sweep is
SPMD-deterministic (every process times the same candidates in the same
order) and the winner is broadcast from process 0
(``multihost_utils.broadcast_one_to_all``) so divergent clocks can't
split the decision.
"""

from __future__ import annotations

import dataclasses
from collections.abc import Callable, Sequence

import jax
import numpy as np

from triton_dist_tpu.runtime.utils import perf_func

_CACHE: dict = {}


@dataclasses.dataclass(frozen=True)
class TuneResult:
    config: dict
    avg_ms: float
    all_ms: tuple


def clear_cache():
    _CACHE.clear()


def _disk_cache_path() -> str | None:
    """Persistent sweep cache (off unless ``TDT_AUTOTUNE_CACHE`` is set —
    a path, or ``1`` for the default location). Worth it on TPU, where
    each candidate costs a 20-40 s Mosaic compile; the reference's
    autotuner caches only per Autotuner instance."""
    import os
    val = os.environ.get("TDT_AUTOTUNE_CACHE")
    if not val:
        return None
    if val == "1":
        return os.path.expanduser("~/.cache/triton_dist_tpu/autotune.json")
    return os.path.expanduser(val)


#: Bump when the sweep's TIMING methodology changes materially: every
#: persisted winner under an older version must miss (a fresh sweep is
#: cheaper than serving a winner ranked by a measurement now known to
#: be wrong). v2: the round-5 chained-runner fix — pre-fix on-chip
#: sweeps paid one readback roundtrip per iteration and ranked sub-ms
#: kernels by tunnel jitter (cached "winners" carried avg_ms of
#: 136-297 ms for a 0.5 ms kernel).
_CACHE_VERSION = "v2"


def _disk_key(key: str) -> str:
    kind = getattr(jax.devices()[0], "device_kind", "cpu")
    return f"{_CACHE_VERSION}::{kind}::{key}"


def _disk_load(key: str) -> TuneResult | None:
    path = _disk_cache_path()
    if path is None:
        return None
    import json
    import os
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            data = json.load(f)
        ent = data.get(_disk_key(key))
        if ent is None:
            return None
        return TuneResult(
            config=dict(ent["config"]), avg_ms=float(ent["avg_ms"]),
            all_ms=tuple(float("inf") if t is None else float(t)
                         for t in ent["all_ms"]))
    except (OSError, ValueError, KeyError, TypeError):
        return None


def _disk_store(key: str, result: TuneResult) -> None:
    path = _disk_cache_path()
    if path is None or jax.process_index() != 0:
        return
    import json
    import os
    try:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        data = {}
        if os.path.exists(path):
            try:
                with open(path) as f:
                    data = json.load(f)
            except (OSError, ValueError):
                data = {}
        # Evict entries from older cache versions on rewrite: a version
        # bump means their timing methodology is known-wrong, and dead
        # winners would otherwise accumulate one generation per bump.
        data = {k: v for k, v in data.items()
                if k.startswith(_CACHE_VERSION + "::")}
        data[_disk_key(key)] = {
            "config": result.config, "avg_ms": result.avg_ms,
            "all_ms": [t if np.isfinite(t) else None
                       for t in result.all_ms]}
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(data, f, indent=1)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    except (OSError, TypeError, ValueError):
        # Persistence is best-effort and, on multi-host, runs on process
        # 0 only — raising here (e.g. a non-JSON config value) would
        # desync ranks after an otherwise successful sweep.
        pass


#: Last cost-model prune per op family: op -> (n_before, n_after).
#: Introspectable record of the search-space reduction (the acceptance
#: log for "prunes >= 4x"); the same pair lands in the obs gauges
#: ``autotune.<op>.candidates_before/after``.
LAST_PRUNE: dict[str, tuple[int, int]] = {}


def record_prune(op: str, n_before: int, n_after: int) -> None:
    """Log a cost-model candidate-table prune (perf_model.prune_configs):
    keeps the before/after counts visible in telemetry and in
    :data:`LAST_PRUNE` so sweeps can show their search-space reduction."""
    LAST_PRUNE[op] = (int(n_before), int(n_after))
    from triton_dist_tpu import obs
    if obs.enabled():
        obs.gauge(f"autotune.{op}.candidates_before").set(n_before)
        obs.gauge(f"autotune.{op}.candidates_after").set(n_after)
    import logging
    logging.getLogger("triton_dist_tpu.autotuner").info(
        "autotune %s: cost model pruned %d candidates -> %d",
        op, n_before, n_after)


_TRACE_FALLBACK_WARNED: set = set()


def consult_disk_for_trace(key: str) -> "TuneResult | None":
    """Disk-cache consult for an ``impl="auto"`` call first hit under
    jit TRACING (no eager sweep possible there).

    Two deliberate restrictions (ADVICE r4 items 1 and 4):

    - **Multi-process: always None.** The cache file may exist on only
      some hosts, and a winner applied on some ranks but not others
      would bake MISMATCHED collective programs across the deployment —
      a hang, not a slowdown. Eager ``autotune`` sweeps are rank-agreed
      (worst-rank scores + process-0 hit broadcast); this traced
      shortcut has no agreement step, so it is single-controller-only.
    - **One-time warning on a miss**, so users know the traced program
      baked the default impl for its lifetime and a later eager tune
      will not update it.
    """
    if jax.process_count() > 1:
        if key not in _TRACE_FALLBACK_WARNED:
            _TRACE_FALLBACK_WARNED.add(key)
            import warnings
            warnings.warn(
                f"impl='auto' for {key!r} hit under jit tracing in a "
                "multi-process deployment: using the default impl on "
                "every rank (the per-host disk cache is not consulted "
                "— divergent winners would hang collectives). Tune "
                "eagerly once before jit to pick a measured winner.",
                stacklevel=3)
        return None
    hit = _disk_load(key)
    if hit is None and key not in _TRACE_FALLBACK_WARNED:
        _TRACE_FALLBACK_WARNED.add(key)
        import warnings
        warnings.warn(
            f"impl='auto' for {key!r} was first reached under jit "
            "tracing with no cached winner: the traced program bakes "
            "the default impl for its LIFETIME (a later eager tune "
            "cannot update it). Run one eager call first to tune.",
            stacklevel=3)
    return hit


def autotune(make_fn: Callable[..., Callable], configs: Sequence[dict],
             key: str | None = None, iters: int = 20,
             warmup_iters: int = 5,
             vet: Callable[[dict], "str | None"] | None = None
             ) -> TuneResult:
    """Pick the fastest config.

    Args:
      make_fn: config-kwargs → zero-arg callable running the op (the
        analog of re-launching the Triton kernel per config).
      configs: candidate dicts (reference per-op config tables, e.g.
        ``matmul_get_configs`` allgather_gemm.py:396).
      key: cache key — one sweep per key per process (reference caches on
        the Autotuner instance).
      vet: optional static candidate gate (config → rejection reason or
        None), e.g. ``perf_model.vet_vmem`` bound to the sweep shape.
        Rejected candidates never reach ``make_fn`` — no compile is
        invoked for them (``autotune.candidates_rejected_static``;
        docs/analysis.md "vmem-budget"). Deterministic, so every rank
        rejects the same set and the sweep stays SPMD-agreed.
    Returns the winning TuneResult (same on every process).

    Failure isolation: a config that raises scores inf (skipped, like
    the reference's OutOfResources handling). On multi-host sweeps the
    per-config scores are agreed as the WORST rank's time, so a config
    failing anywhere loses everywhere; note that a non-SPMD-deterministic
    failure (raising on only some ranks mid-collective) can still desync
    the sweep itself — only configs whose failures are deterministic
    across ranks are fully safe to list.
    """
    from triton_dist_tpu import obs
    if vet is not None:
        # BEFORE any cache consult: a persisted winner from a sweep
        # that predates the vet (or a footprint-model fix) must fail
        # the staleness membership check below against the VETTED
        # list, not be resurrected unvetted. Deterministic, so every
        # rank rejects the same set and the sweep stays SPMD-agreed.
        kept = []
        for cfg in configs:
            reason = vet(dict(cfg))
            if reason is None:
                kept.append(cfg)
                continue
            import logging
            logging.getLogger("triton_dist_tpu.autotuner").warning(
                "autotune %s: candidate rejected statically: %s",
                key, reason)
            if obs.enabled():
                obs.counter("autotune.candidates_rejected_static").inc()
        if not kept:
            raise ValueError(
                f"autotune {key!r}: every candidate was rejected by "
                f"the static vet — the config table and the vet "
                f"disagree (docs/analysis.md)")
        configs = kept
    if key is not None and key in _CACHE:
        return _CACHE[key]
    if key is not None:
        hit = _disk_load(key)
        # A persisted winner that is no longer in the candidate list is
        # stale (the config table changed — e.g. a tightened VMEM-budget
        # filter excluded it): fall through to a fresh sweep rather than
        # resurrect a config the current filter rejects.
        if hit is not None and hit.config not in [dict(c) for c in configs]:
            hit = None
        if jax.process_count() > 1:
            # The hit/miss decision must be AGREED, not per-process: the
            # cache file may exist on only some hosts, and a partial hit
            # would leave the missing ranks blocking in the sweep's
            # process_allgather forever. Process 0 decides; the winner
            # index + time are broadcast (configs are identical and
            # identically ordered on every process by construction).
            from jax.experimental import multihost_utils
            idx = -1.0
            avg = float("nan")
            allms = [float("nan")] * len(configs)
            if hit is not None and jax.process_index() == 0:
                idx = float(next(i for i, c in enumerate(configs)
                                 if dict(c) == hit.config))
                avg = hit.avg_ms
                # keep the per-config scores (incl. inf losers) so the
                # TuneResult contract matches the single-host hit
                for i, t in enumerate(hit.all_ms[:len(configs)]):
                    allms[i] = t
            agreed = np.asarray(multihost_utils.broadcast_one_to_all(
                np.asarray([idx, avg] + allms, np.float64)))
            if agreed[0] >= 0:
                hit = TuneResult(config=dict(configs[int(agreed[0])]),
                                 avg_ms=float(agreed[1]),
                                 all_ms=tuple(float(t)
                                              for t in agreed[2:]))
            else:
                hit = None
        if hit is not None:
            _CACHE[key] = hit
            return hit

    times = []
    errors = []
    for cfg in configs:
        # A config that fails to compile/run (e.g. VMEM overflow on this
        # chip generation) scores inf instead of killing the sweep — the
        # reference's Triton autotuner likewise skips OutOfResources
        # configs. This keeps aggressive candidates safe to list.
        # Each candidate is a span: a sweep that wedges on one Mosaic
        # compile leaves that candidate's un-ended begin (with its
        # exact config) in the flight record.
        with obs.span("autotune.candidate", cat="op",
                      args={"key": key, **{k: v for k, v in cfg.items()
                                           if isinstance(v, (int, str,
                                                             bool))}}):
            try:
                fn = make_fn(**cfg)
                _, ms = perf_func(fn, iters=iters,
                                  warmup_iters=warmup_iters,
                                  return_output=False)
            except Exception as e:  # noqa: BLE001 — per-config isolation
                ms = float("inf")
                errors.append((cfg, repr(e)[:200]))
        times.append(ms)

    if jax.process_count() > 1:
        # Agree on scores BEFORE picking: a config that failed on ANY
        # rank must lose everywhere (worst-rank time), and the cached
        # avg_ms must be the agreed number, not this rank's local inf
        # (code-review r3d findings 1/4). Residual hazard documented
        # above: a config failing on only SOME ranks may already have
        # desynced the sweep itself — per-config isolation is fully safe
        # only where failures are SPMD-deterministic.
        from jax.experimental import multihost_utils
        allt = np.asarray(multihost_utils.process_allgather(
            np.asarray(times, np.float64)))
        times = list(allt.reshape(jax.process_count(), -1).max(axis=0))
    if not np.isfinite(times).any():
        raise RuntimeError(f"every autotune config failed: {errors}")
    best = int(np.argmin(times))
    result = TuneResult(config=dict(configs[best]), avg_ms=times[best],
                        all_ms=tuple(times))
    if key is not None:
        _CACHE[key] = result
        _disk_store(key, result)
    return result
