"""Distributed-synchronized autotuner.

TPU-native redesign of the reference's ``ContextualAutoTuner``
(python/triton_dist/kernels/nvidia/autotuner.py:43-250: sweeps configs
with barriers interleaved so ALL ranks pick the same config — a rank
divergence would deadlock the fused kernels' signal protocols).

Same hazard here: shard_map programs with different tuning params on
different hosts would compile different collectives. The sweep is
SPMD-deterministic (every process times the same candidates in the same
order) and the winner is broadcast from process 0
(``multihost_utils.broadcast_one_to_all``) so divergent clocks can't
split the decision.
"""

from __future__ import annotations

import dataclasses
from collections.abc import Callable, Sequence

import jax
import numpy as np

from triton_dist_tpu.runtime.utils import perf_func

_CACHE: dict = {}


@dataclasses.dataclass(frozen=True)
class TuneResult:
    config: dict
    avg_ms: float
    all_ms: tuple


def clear_cache():
    _CACHE.clear()


def autotune(make_fn: Callable[..., Callable], configs: Sequence[dict],
             key: str | None = None, iters: int = 20,
             warmup_iters: int = 5) -> TuneResult:
    """Pick the fastest config.

    Args:
      make_fn: config-kwargs → zero-arg callable running the op (the
        analog of re-launching the Triton kernel per config).
      configs: candidate dicts (reference per-op config tables, e.g.
        ``matmul_get_configs`` allgather_gemm.py:396).
      key: cache key — one sweep per key per process (reference caches on
        the Autotuner instance).
    Returns the winning TuneResult (same on every process).
    """
    if key is not None and key in _CACHE:
        return _CACHE[key]

    times = []
    for cfg in configs:
        fn = make_fn(**cfg)
        _, ms = perf_func(fn, iters=iters, warmup_iters=warmup_iters,
                          return_output=False)
        times.append(ms)

    best = int(np.argmin(times))
    if jax.process_count() > 1:
        # Rank-0's choice wins everywhere (reference: synchronized sweep +
        # identical pick; we make the agreement explicit).
        from jax.experimental import multihost_utils
        best = int(multihost_utils.broadcast_one_to_all(
            np.int32(best)))
    result = TuneResult(config=dict(configs[best]), avg_ms=times[best],
                        all_ms=tuple(times))
    if key is not None:
        _CACHE[key] = result
    return result
