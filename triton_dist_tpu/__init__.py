"""triton_dist_tpu — a TPU-native framework for compute–communication
overlapping distributed kernels.

A from-scratch re-design (JAX / XLA / Pallas / shard_map over ICI/DCN meshes)
of the capabilities of Triton-distributed (reference: ByteDance-Seed
Triton-distributed, see SURVEY.md). The layering mirrors the reference:

- ``triton_dist_tpu.language``  — device-side one-sided communication and
  signal primitives usable inside Pallas kernels (reference L3:
  python/triton_dist/language/distributed_ops.py,
  language/extra/libshmem_device.py).
- ``triton_dist_tpu.runtime``   — host distributed runtime: mesh init,
  symmetric buffers, bench/verify helpers, topology (reference L4:
  python/triton_dist/utils.py).
- ``triton_dist_tpu.ops``       — the overlapping kernel library: AG-GEMM,
  GEMM-RS, AllReduce, EP AllToAll, MoE, distributed flash-decode,
  SP attention (reference L5: python/triton_dist/kernels/nvidia/).
- ``triton_dist_tpu.parallel``  — TP/EP/SP model layers (reference L6:
  python/triton_dist/layers/nvidia/).
- ``triton_dist_tpu.models``    — Qwen3-class dense + MoE models, KV cache,
  inference engine (reference L7: python/triton_dist/models/).
- ``triton_dist_tpu.mega``      — fused whole-decoder-step runtime
  (reference L8: python/triton_dist/mega_triton_kernel/).
- ``triton_dist_tpu.tools``     — AOT export, profiling (reference L9:
  python/triton_dist/tools/).

Unlike the reference (CUDA/NVSHMEM), the hot path is Pallas kernels with
async remote DMA over ICI plus XLA collectives, composed under
``jax.shard_map`` over a ``jax.sharding.Mesh``.
"""

__version__ = "0.1.0"

# jax-version compat: the library, tests, and examples target current
# jax's ``jax.shard_map`` (kwarg ``check_vma``); jax 0.4.x spells it
# ``jax.experimental.shard_map.shard_map`` with ``check_rep``. Install
# a translating alias once, at package import, so every call site runs
# on both (the container's baked-in toolchain pins 0.4.x).
import jax as _jax

if not hasattr(_jax, "shard_map"):
    import functools as _functools

    from jax.experimental.shard_map import shard_map as _shard_map_04

    @_functools.wraps(_shard_map_04)
    def _shard_map_compat(f, *args, check_vma=None, **kwargs):
        if check_vma is not None:
            kwargs["check_rep"] = check_vma
        return _shard_map_04(f, *args, **kwargs)

    _jax.shard_map = _shard_map_compat

if not hasattr(_jax.lax, "axis_size"):
    def _axis_size_compat(axis_name, *, _psum=_jax.lax.psum):
        # 0.4.x: psum of a Python literal folds to the static size.
        return _psum(1, axis_name)

    _jax.lax.axis_size = _axis_size_compat

del _jax

from triton_dist_tpu.runtime.dist import (  # noqa: F401
    initialize_distributed,
    finalize_distributed,
    get_context,
    get_mesh,
)
