"""triton_dist_tpu — a TPU-native framework for compute–communication
overlapping distributed kernels.

A from-scratch re-design (JAX / XLA / Pallas / shard_map over ICI/DCN meshes)
of the capabilities of Triton-distributed (reference: ByteDance-Seed
Triton-distributed, see SURVEY.md). The layering mirrors the reference:

- ``triton_dist_tpu.language``  — device-side one-sided communication and
  signal primitives usable inside Pallas kernels (reference L3:
  python/triton_dist/language/distributed_ops.py,
  language/extra/libshmem_device.py).
- ``triton_dist_tpu.runtime``   — host distributed runtime: mesh init,
  symmetric buffers, bench/verify helpers, topology (reference L4:
  python/triton_dist/utils.py).
- ``triton_dist_tpu.ops``       — the overlapping kernel library: AG-GEMM,
  GEMM-RS, AllReduce, EP AllToAll, MoE, distributed flash-decode,
  SP attention (reference L5: python/triton_dist/kernels/nvidia/).
- ``triton_dist_tpu.parallel``  — TP/EP/SP model layers (reference L6:
  python/triton_dist/layers/nvidia/).
- ``triton_dist_tpu.models``    — Qwen3-class dense + MoE models, KV cache,
  inference engine (reference L7: python/triton_dist/models/).
- ``triton_dist_tpu.mega``      — fused whole-decoder-step runtime
  (reference L8: python/triton_dist/mega_triton_kernel/).
- ``triton_dist_tpu.tools``     — AOT export, profiling (reference L9:
  python/triton_dist/tools/).

Unlike the reference (CUDA/NVSHMEM), the hot path is Pallas kernels with
async remote DMA over ICI plus XLA collectives, composed under
``jax.shard_map`` over a ``jax.sharding.Mesh``.
"""

__version__ = "0.1.0"

from triton_dist_tpu.runtime.dist import (  # noqa: F401
    initialize_distributed,
    finalize_distributed,
    get_context,
    get_mesh,
)
