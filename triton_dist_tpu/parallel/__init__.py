"""Parallelism-strategy re-export surface (reference L6 layer map,
SURVEY.md §1).

The implementations live in :mod:`triton_dist_tpu.layers`; this package
groups them by parallelism strategy the way the reference's docs do
(SURVEY.md §2.9 checklist): TP (dense + MoE), EP (all-to-all
dispatch/combine), SP (AG-KV attention + distributed flash decode), and
PP (p2p buffers + pipeline schedule).
"""

from triton_dist_tpu.parallel.plan import Plan, plan_parallelism
from triton_dist_tpu.layers.ep_a2a import DispatchHandle, EPAll2AllLayer
from triton_dist_tpu.layers.ep_moe import EPMoE
from triton_dist_tpu.layers.p2p import CommOp
from triton_dist_tpu.layers.sp_flash_decode import (
    SpAttentionLayer,
    SpFlashDecodeLayer,
)
from triton_dist_tpu.layers.tp_attn import TPAttn
from triton_dist_tpu.layers.tp_mlp import TPMLP
from triton_dist_tpu.layers.tp_moe import TPMoE

# Strategy → layers index (mirrors SURVEY.md §2.9).
TP_LAYERS = (TPMLP, TPAttn, TPMoE)
EP_LAYERS = (EPAll2AllLayer, EPMoE)
SP_LAYERS = (SpFlashDecodeLayer, SpAttentionLayer)
PP_LAYERS = (CommOp,)

__all__ = [
    "Plan",
    "plan_parallelism",
    "CommOp",
    "DispatchHandle",
    "EPAll2AllLayer",
    "EPMoE",
    "SpAttentionLayer",
    "SpFlashDecodeLayer",
    "TPAttn",
    "TPMLP",
    "TPMoE",
    "TP_LAYERS",
    "EP_LAYERS",
    "SP_LAYERS",
    "PP_LAYERS",
]
