"""Parallelism planner: model config + chip count → a recommended
layout.

The reference leaves strategy choice to the user (its tests hard-code
TP=8 etc.); here the framework's divisibility rules and the round-3
measured crossovers (BENCH_NOTES_r3.md; e.g. replicated GEMM-AR wins
small-batch decode) pick a starting point:

- **tp** divides BOTH the kv-head count and the MLP intermediate
  (gcd-based cap) and grows until the per-chip parameter bytes fit
  comfortably in HBM;
- **ep** covers the expert dim when the config is MoE (experts spread
  before heads split further — expert FLOPs dominate);
- **sp** takes the remaining factor when the serving context is long
  (the sequence-sharded cache is what scales max_seq);
- anything left replicates as **dp**; chips that no legal factoring
  can use are reported in ``reasons`` rather than silently dropped.

The output is a starting point, not an oracle — the distributed
autotuner (tools/autotuner.py) refines tile configs per shape, and
``Plan.mesh()`` hands back the concrete `jax.sharding.Mesh` to build
models on.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import numpy as np
from jax.sharding import Mesh


@dataclasses.dataclass(frozen=True)
class Plan:
    """A recommended parallel layout over ``n_chips``."""
    tp: int = 1
    sp: int = 1
    ep: int = 1
    dp: int = 1
    prefill_mode: str = "ag_rs"
    decode_mode: str = "gemm_ar"
    moe_parallel: str | None = None   # None for dense configs
    reasons: tuple = ()

    @property
    def axis_names(self) -> tuple:
        names = []
        for name in ("dp", "ep", "tp", "sp"):
            if getattr(self, name) > 1 or name == "tp":
                names.append(name)
        return tuple(names)

    def mesh(self, devices=None) -> Mesh:
        devs = list(devices if devices is not None else jax.devices())
        shape = tuple(getattr(self, n) for n in self.axis_names)
        n = int(np.prod(shape))
        assert len(devs) >= n, (len(devs), shape)
        return Mesh(np.array(devs[:n]).reshape(shape), self.axis_names)


def _divisors_leq(n: int, cap: int) -> list:
    """All divisors of ``n`` that are <= cap, ascending (>= [1])."""
    return [d for d in range(1, max(1, min(n, cap)) + 1) if n % d == 0]


def plan_parallelism(config, n_chips: int, max_seq: int = 4096,
                     decode_batch: int = 8,
                     hbm_bytes: int = 16 * 2 ** 30) -> Plan:
    """Pick (dp, ep, tp, sp) for ``config`` over ``n_chips``.

    Heuristics (each recorded in ``Plan.reasons``):
      1. MoE configs give the expert dim first claim on chips.
      2. tp ∈ divisors(gcd(kv_heads, intermediate)) grows until the
         per-chip parameter bytes fit in ~half HBM (leaving room for
         activations + KV); if no legal tp fits, the largest legal one
         is taken and the shortfall is recorded.
      3. Long contexts (max_seq > 8k) spend remaining chips on sp.
      4. Anything left becomes dp; chips no legal factoring can use
         are reported, never silently idled.
    """
    c = config
    reasons = []
    remaining = n_chips
    is_moe = getattr(c, "num_experts", 0) and c.num_experts > 0

    ep = 1
    if is_moe:
        ep = _divisors_leq(c.num_experts, remaining)[-1]
        remaining //= ep
        reasons.append(f"ep={ep}: {c.num_experts} experts spread first "
                       "(EP moves routed tokens only)")

    # Parameter bytes per chip under tp (dense part + experts under
    # ep). Shared accounting with models.presets.param_count — one
    # counter, two consumers (review r5f-1; this path previously
    # overcounted tied embeddings by 2x). bf16 = 2 bytes.
    inter = getattr(c, "intermediate_size", 0) or getattr(
        c, "moe_intermediate_size", 0)
    attn_p, mlp_p, embed_p = c.param_split()
    per_layer = 2 * (attn_p + mlp_p / max(ep, 1))
    total = per_layer * c.num_hidden_layers + 2 * embed_p

    # tp must divide BOTH the kv heads and the intermediate (review
    # r3j: a min()-based cap let tp=3 through against 8 kv heads).
    cap_basis = c.num_key_value_heads
    if inter:
        cap_basis = math.gcd(cap_basis, inter)
    tp = 1
    for d in _divisors_leq(cap_basis, remaining):  # ascending
        tp = d
        if total / d <= hbm_bytes // 2:
            break
    if total / tp > hbm_bytes // 2:
        reasons.append(
            f"WARNING: even tp={tp} (largest legal) leaves "
            f"{total / tp / 2**30:.1f} GiB params/chip")
    remaining //= tp
    reasons.append(f"tp={tp}: ~{total / tp / 2**30:.1f} GiB params/chip "
                   f"(gcd cap {cap_basis})")

    sp = 1
    if max_seq > 8192 and remaining > 1:
        sp = remaining
        remaining = 1
        reasons.append(f"sp={sp}: max_seq {max_seq} wants the "
                       "sequence-sharded cache")
    dp = max(1, remaining)
    if dp > 1:
        reasons.append(f"dp={dp}: leftover chips replicate for "
                       "throughput")
    used = ep * tp * sp * dp
    if used < n_chips:
        reasons.append(f"NOTE: {n_chips - used} of {n_chips} chips "
                       "unused (no legal factoring absorbs them; "
                       "consider a chip count matching the expert/"
                       "head divisors)")

    if sp > 1:
        prefill = decode = "sp"
    else:
        prefill = "ag_rs"
        # Round-3 measured crossover (BENCH_NOTES_r3.md): replicated
        # GEMM-AR wins small decode batches; the sharded path wins once
        # the batch splits usefully across tp.
        decode = "gemm_ar" if decode_batch < 8 * tp else "ag_rs"
        reasons.append(f"decode={decode} at batch {decode_batch}")

    return Plan(tp=tp, sp=sp, ep=ep, dp=dp, prefill_mode=prefill,
                decode_mode=decode,
                moe_parallel=("ep" if ep > 1 else
                              ("tp" if is_moe else None)),
                reasons=tuple(reasons))


def main():  # pragma: no cover — thin CLI over plan_parallelism
    """``tdt-plan``: recommend a parallel layout for a model + chips."""
    import argparse
    import json
    from triton_dist_tpu.models import ModelConfig

    from triton_dist_tpu.models import presets

    ap = argparse.ArgumentParser(
        description="Recommend (dp, ep, tp, sp) for a model")
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--model-dir", default=None,
                     help="HF checkpoint dir (reads config.json)")
    src.add_argument("--preset", default=None,
                     choices=sorted(presets.PRESETS),
                     help="named architecture (models/presets.py)")
    ap.add_argument("--chips", type=int, required=True)
    ap.add_argument("--max-seq", type=int, default=4096)
    ap.add_argument("--decode-batch", type=int, default=8)
    ap.add_argument("--hbm-gib", type=float, default=16.0)
    args = ap.parse_args()
    cfg = (presets.PRESETS[args.preset]() if args.preset
           else ModelConfig.from_hf_config(args.model_dir))
    p = plan_parallelism(cfg, args.chips, max_seq=args.max_seq,
                         decode_batch=args.decode_batch,
                         hbm_bytes=int(args.hbm_gib * 2 ** 30))
    print(json.dumps({
        "mesh": {n: getattr(p, n) for n in p.axis_names},
        "prefill_mode": p.prefill_mode, "decode_mode": p.decode_mode,
        "moe_parallel": p.moe_parallel, "reasons": list(p.reasons),
    }, indent=2))
