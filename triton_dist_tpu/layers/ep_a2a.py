"""Expert-parallel AllToAll dispatch/combine layer.

TPU-native redesign of the reference's ``EPAll2AllLayer``
(python/triton_dist/layers/nvidia/ep_a2a_layer.py:40-248: preprocess
computes splits/offsets, dispatch pushes tokens to the ranks owning their
experts, combine reverses; double-buffered symmetric buffers) over our
``fast_all_to_all`` op (ops/all_to_all.py ≙ low_latency_all_to_all.py).

Static-shape contract: every (token, k) pair gets a slot in a
``(world, capacity)`` rank-major send layout (ops/moe_utils.dispatch_layout
≙ the reference's send-request generation + recv-offset computation,
ep_a2a.py:244). Payload rides the Pallas LL a2a; int32 sideband metadata
(local expert id) rides a tiny XLA all-to-all, like the reference's splits
pre-exchange.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from triton_dist_tpu.ops.common import nestable_shard_map

from triton_dist_tpu.ops.all_to_all import (
    AllToAllContext, create_all_to_all_context)
# Differentiable wrapper (forward-identical): the a2a's adjoint is the
# reverse exchange, so EP dispatch/combine train (ops/autodiff.py).
from triton_dist_tpu.ops.autodiff import fast_all_to_all
from triton_dist_tpu.ops.moe_utils import (
    dispatch_layout, live_slot_mask, scatter_to_slabs, topk_reduce)


@dataclasses.dataclass
class DispatchHandle:
    """State carried from dispatch to combine (the reference stashes it on
    the module: num_dispatch_token_cur_rank etc., ep_a2a_layer.py:100)."""
    dest: jax.Array        # (T, K) global, row-sharded
    pos: jax.Array         # (T, K)
    valid: jax.Array       # (T, K)
    recv_counts: jax.Array  # (world*world,) row-sharded


class EPAll2AllLayer:
    """dispatch(x, indices) → tokens grouped for local expert compute;
    combine(expert_out, weights, handle) → per-token outputs."""

    def __init__(self, max_tokens: int, hidden: int, topk: int,
                 num_experts: int, mesh: Mesh | None = None,
                 axis: str = "ep", capacity: int | None = None,
                 dtype=jnp.bfloat16, impl: str = "pallas",
                 wire_dtype: str | None = None):
        if mesh is None:
            from triton_dist_tpu.runtime.dist import get_mesh
            mesh = get_mesh()
        self.mesh, self.axis = mesh, axis
        self.world = mesh.shape[axis]
        assert num_experts % self.world == 0
        # wire_dtype="fp8": DISPATCH tokens travel as float8_e4m3fn with
        # per-row scales (the reference's headline LL-a2a config —
        # README.md:97); combine stays at model dtype to keep the topk
        # weighted sum accurate (DeepEP practice). Inference-only: the
        # quantizer has no useful gradient, so training uses the plain
        # wire (ops/autodiff.py).
        assert wire_dtype in (None, "fp8"), wire_dtype
        self.wire_dtype = wire_dtype
        self.max_tokens = max_tokens
        self.hidden = hidden
        self.topk = topk
        self.num_experts = num_experts
        self.experts_per_rank = num_experts // self.world
        # Worst case: every pair this rank routes lands on one peer
        # (reference sizes send_buf the same way: max_tokens * topk rows,
        # ep_a2a_layer.py:70-90).
        cap = capacity or max_tokens * topk
        # Sublane-align the slab for chunked DMA: 8 rows for >=2-byte
        # payloads, 32 for the fp8 path's int8 wire (1-byte native tile
        # is (32, 128); review r3e finding 1).
        align = 32 if wire_dtype == "fp8" else 8
        cap = max(align, -(-cap // align) * align)
        self.capacity = cap
        self.dtype = dtype
        self.impl = impl
        self.a2a_ctx: AllToAllContext = create_all_to_all_context(
            mesh, axis, capacity=cap)

    # -- helpers -----------------------------------------------------------
    def _meta_a2a(self, arr: jax.Array) -> jax.Array:
        """XLA all-to-all for small int sideband arrays (local shape
        (world, ...) → transposed slabs)."""
        from triton_dist_tpu.ops.all_to_all import _xla_a2a
        return _xla_a2a(self.mesh, self.axis, arr)

    # -- dispatch ----------------------------------------------------------
    def dispatch(self, x: jax.Array, exp_indices: jax.Array):
        """Route token rows to the ranks owning their experts.

        Args:
          x: (T, H) row-sharded over ``axis`` (T = world * tokens_per_rank).
          exp_indices: (T, topk) int32 global expert ids, row-sharded.

        Returns (tokens, local_expert, handle):
          tokens: (world*capacity, H) per device (global leading dim
            world²*capacity, sharded) — received pair rows.
          local_expert: matching (world*capacity,) int32 per device;
            invalid slots hold ``experts_per_rank`` (sentinel sorted last
            by grouped compute).
          handle: state for :meth:`combine`.
        """
        world, cap = self.world, self.capacity
        axis = self.axis

        def local_pack(xs, ids):
            meta = dispatch_layout(ids, self.num_experts, world, cap)
            buf, extras = scatter_to_slabs(
                xs, meta, world, cap,
                extra={"local_expert": meta["local_expert"]})
            return (buf, extras["local_expert"], meta["send_counts"],
                    meta["dest"], meta["pos"], meta["valid"])

        pack = nestable_shard_map(
            local_pack, mesh=self.mesh, in_specs=(P(axis), P(axis)),
            out_specs=(P(axis), P(axis), P(axis), P(axis), P(axis), P(axis)),
            check_vma=False)
        send_buf, send_exp, send_counts, dest, pos, valid = pack(
            x, exp_indices)

        if self.wire_dtype == "fp8":
            from triton_dist_tpu.ops.all_to_all import fast_all_to_all_fp8
            recv_buf, recv_counts = fast_all_to_all_fp8(
                send_buf, send_counts, self.a2a_ctx, impl=self.impl)
        else:
            recv_buf, recv_counts = fast_all_to_all(
                send_buf, send_counts, self.a2a_ctx, impl=self.impl)
        recv_exp = self._meta_a2a(send_exp)

        def local_unpack(rb, re, rc):
            # Mask slots past each slab's live count; sentinel expert id.
            live = live_slot_mask(rc, world, cap)
            exp = jnp.where(live, re, self.experts_per_rank)
            # Zero the stale payload rows too: the Pallas a2a leaves
            # them undefined, and any NaN there would poison the expert
            # FFN's *backward* (0-cotangent × NaN-primal = NaN) even
            # though combine masks them out of the forward.
            rb = jnp.where(live[..., None], rb, 0)
            return rb.reshape(world * cap, -1), exp.reshape(-1)

        unpack = nestable_shard_map(
            local_unpack, mesh=self.mesh,
            in_specs=(P(axis), P(axis), P(axis)),
            out_specs=(P(axis), P(axis)), check_vma=False)
        tokens, local_expert = unpack(recv_buf, recv_exp, recv_counts)

        handle = DispatchHandle(dest=dest, pos=pos, valid=valid,
                                recv_counts=recv_counts)
        return tokens, local_expert, handle

    # -- combine -----------------------------------------------------------
    def combine(self, expert_out: jax.Array, weights: jax.Array,
                handle: DispatchHandle) -> jax.Array:
        """Return processed pair rows to their source ranks and reduce over
        top-k (reference combine: same kernel reversed + topk reduce,
        ep_a2a_layer.py:200-248).

        Args:
          expert_out: (world*capacity, H) per device — processed rows in
            dispatch slot order (global sharded like dispatch's output).
          weights: (T, topk) routing weights, row-sharded.
        Returns:
          (T, H) row-sharded combined outputs.
        """
        world, cap = self.world, self.capacity
        axis = self.axis

        def reshape_slabs(eo):
            return eo.reshape(world, cap, -1)
        slabs = nestable_shard_map(reshape_slabs, mesh=self.mesh,
                              in_specs=P(axis), out_specs=P(axis),
                              check_vma=False)(expert_out)

        # Reverse exchange: slab j goes back to rank j (counts are what we
        # received in dispatch).
        back_buf, _ = fast_all_to_all(slabs, handle.recv_counts,
                                      self.a2a_ctx, impl=self.impl)

        def local_gather(bb, dest, pos, valid, wts):
            t, k = dest.shape
            flat = bb.reshape(world * cap, -1)
            slot = dest.reshape(-1) * cap + pos.reshape(-1)
            rows = flat[jnp.minimum(slot, world * cap - 1)]
            rows = jnp.where(valid.reshape(-1)[:, None], rows, 0)
            return topk_reduce(rows.reshape(t, k, -1), wts)

        gather = nestable_shard_map(
            local_gather, mesh=self.mesh,
            in_specs=(P(axis),) * 5, out_specs=P(axis), check_vma=False)
        return gather(back_buf, handle.dest, handle.pos, handle.valid,
                      weights)
