"""Pipeline-parallel communication layer.

TPU-native analog of the reference's ``CommOp``
(python/triton_dist/layers/nvidia/p2p.py:43-131: N symmetric buffers with
per-pp-rank set/wait signals so a producer stage can run ahead of its
consumer). On TPU the signal protocol collapses into dataflow — a
``pp_shift`` is ordered by SSA use — so ``CommOp`` keeps the *API* (ring
of in-flight buffers, send/recv pairing) while the synchronization is
compiler-managed.

A microbatched GPipe-style schedule built on this layer lives in
``pipeline_schedule`` (the reference stops at p2p + test; SURVEY.md §2.9
"PP: partial — no scheduler", so the schedule is an extension).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh
from triton_dist_tpu.ops.common import nestable_shard_map

from triton_dist_tpu.ops.p2p import P2PContext, create_p2p_context, pp_shift


class CommOp:
    """Ring of ``num_buffers`` in-flight pipeline hops (API parity with
    layers/nvidia/p2p.py:43; the buffer count bounds producer run-ahead
    in the reference — here it bounds how many shifts are outstanding)."""

    def __init__(self, num_buffers: int = 2, mesh: Mesh | None = None,
                 axis: str = "pp", impl: str = "pallas"):
        self.ctx: P2PContext = create_p2p_context(mesh, axis)
        self.num_buffers = num_buffers
        self.impl = impl
        self._in_flight: list[jax.Array] = []

    def send(self, x: jax.Array, delta: int = 1) -> None:
        """Issue a hop; blocks (joins the oldest) when the ring is full."""
        if len(self._in_flight) >= self.num_buffers:
            self._in_flight.pop(0)
        self._in_flight.append(pp_shift(x, self.ctx, delta=delta,
                                        impl=self.impl))

    def recv(self) -> jax.Array:
        """Consume the oldest outstanding hop."""
        return self._in_flight.pop(0)


def pipeline_forward(stage_fn, x: jax.Array, mesh: Mesh | None = None,
                     axis: str = "pp", impl: str = "xla") -> jax.Array:
    """Forward pass through a w-stage pipeline over the pp axis.

    ``stage_fn(stage_idx, h)`` applies stage ``stage_idx`` to block ``h``
    (SPMD: every device applies its own stage each tick). ``x``:
    (w*rows, F) sharded over pp; stage 0's shard carries the input. Each
    tick = apply + shift, so after w ticks the stage-0 block has passed
    stages 0..w-1; the result sits in stage 0's shard again (w shifts =
    full wrap). Microbatch schedulers (1F1B etc.) compose this tick —
    the reference stops at p2p + test (SURVEY.md §2.9 "PP: partial").
    """
    from jax.sharding import PartitionSpec as P

    ctx = create_p2p_context(mesh, axis)
    world = ctx.world_size

    def apply(h):
        def body(hs):
            me = lax.axis_index(axis)
            return stage_fn(me, hs)
        return nestable_shard_map(body, mesh=ctx.mesh, in_specs=P(axis),
                             out_specs=P(axis), check_vma=False)(h)

    h = x
    for _ in range(world):
        h = pp_shift(apply(h), ctx, delta=1, impl=impl)
    return h


def pipeline_schedule(stage_fn, stage_params, microbatches,
                      mesh: Mesh | None = None,
                      axis: str = "pp") -> jax.Array:
    """GPipe-style microbatched pipeline forward over the pp axis.

    The reference stops at p2p buffers + a test (SURVEY.md §2.9 "PP:
    partial — no scheduler"); this is the missing scheduler, built
    TPU-first: one ``lax.scan`` over ``m + w - 1`` ticks inside a single
    shard_map — at each tick every stage applies itself to the
    activation it holds and the results rotate one hop along the pp ring
    (``lax.ppermute`` riding ICI), so all stages are busy in steady
    state. No data-dependent control flow: fill/drain bubbles are
    masked, not branched.

    Args:
      stage_fn: ``stage_fn(params_s, h) -> h`` — one pipeline stage;
        every activation must keep the same shape/dtype.
      stage_params: pytree whose leaves are stacked per-stage on dim 0
        (length = pp world size); sharded over ``axis`` so each device
        holds its own stage's slice.
      microbatches: (m, ...) microbatch stack, replicated.
    Returns:
      (m, ...) outputs of the full stage stack, replicated.
    """
    from jax.sharding import PartitionSpec as P

    ctx = create_p2p_context(mesh, axis)
    w = ctx.world_size
    m = microbatches.shape[0]
    perm = [(i, (i + 1) % w) for i in range(w)]

    def body(params, mb):
        me = lax.axis_index(axis)
        local = jax.tree_util.tree_map(lambda p: p[0], params)
        h0 = jnp.zeros_like(mb[0])
        out0 = jnp.zeros_like(mb)

        def tick(carry, t):
            h, out = carry
            # stage 0 ingests microbatch t (clamped during drain);
            # later stages consume the hop received last tick.
            mb_t = lax.dynamic_index_in_dim(
                mb, jnp.clip(t, 0, m - 1), 0, keepdims=False)
            h_in = jnp.where(me == 0, mb_t, h)
            y = stage_fn(local, h_in)
            # the last stage finishes microbatch j = t - (w-1)
            j = t - (w - 1)
            jc = jnp.clip(j, 0, m - 1)
            valid = (me == w - 1) & (j >= 0)
            prev = lax.dynamic_index_in_dim(out, jc, 0, keepdims=False)
            out = lax.dynamic_update_index_in_dim(
                out, jnp.where(valid, y, prev), jc, 0)
            return (lax.ppermute(y, axis, perm), out), None

        (_, out), _ = lax.scan(tick, (h0, out0), jnp.arange(m + w - 1))
        # only the last stage wrote real outputs; everyone else holds
        # zeros, so a psum replicates the result.
        return lax.psum(out, axis)

    f = nestable_shard_map(body, mesh=ctx.mesh, in_specs=(P(axis), P()),
                          out_specs=P(), check_vma=False)
    return f(stage_params, microbatches)
