"""Pipeline-parallel communication layer.

TPU-native analog of the reference's ``CommOp``
(python/triton_dist/layers/nvidia/p2p.py:43-131: N symmetric buffers with
per-pp-rank set/wait signals so a producer stage can run ahead of its
consumer). On TPU the signal protocol collapses into dataflow — a
``pp_shift`` is ordered by SSA use — so ``CommOp`` keeps the *API* (ring
of in-flight buffers, send/recv pairing) while the synchronization is
compiler-managed.

A microbatched GPipe-style schedule built on this layer lives in
``pipeline_schedule`` (the reference stops at p2p + test; SURVEY.md §2.9
"PP: partial — no scheduler", so the schedule is an extension).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from triton_dist_tpu.ops.p2p import P2PContext, create_p2p_context, pp_shift


class CommOp:
    """Ring of ``num_buffers`` in-flight pipeline hops (API parity with
    layers/nvidia/p2p.py:43; the buffer count bounds producer run-ahead
    in the reference — here it bounds how many shifts are outstanding)."""

    def __init__(self, num_buffers: int = 2, mesh: Mesh | None = None,
                 axis: str = "pp", impl: str = "pallas"):
        self.ctx: P2PContext = create_p2p_context(mesh, axis)
        self.num_buffers = num_buffers
        self.impl = impl
        self._in_flight: list[jax.Array] = []

    def send(self, x: jax.Array, delta: int = 1) -> None:
        """Issue a hop; blocks (joins the oldest) when the ring is full."""
        if len(self._in_flight) >= self.num_buffers:
            self._in_flight.pop(0)
        self._in_flight.append(pp_shift(x, self.ctx, delta=delta,
                                        impl=self.impl))

    def recv(self) -> jax.Array:
        """Consume the oldest outstanding hop."""
        return self._in_flight.pop(0)


def pipeline_forward(stage_fn, x: jax.Array, mesh: Mesh | None = None,
                     axis: str = "pp", impl: str = "xla") -> jax.Array:
    """Forward pass through a w-stage pipeline over the pp axis.

    ``stage_fn(stage_idx, h)`` applies stage ``stage_idx`` to block ``h``
    (SPMD: every device applies its own stage each tick). ``x``:
    (w*rows, F) sharded over pp; stage 0's shard carries the input. Each
    tick = apply + shift, so after w ticks the stage-0 block has passed
    stages 0..w-1; the result sits in stage 0's shard again (w shifts =
    full wrap). Microbatch schedulers (1F1B etc.) compose this tick —
    the reference stops at p2p + test (SURVEY.md §2.9 "PP: partial").
    """
    from jax.sharding import PartitionSpec as P

    ctx = create_p2p_context(mesh, axis)
    world = ctx.world_size

    def apply(h):
        def body(hs):
            me = lax.axis_index(axis)
            return stage_fn(me, hs)
        return jax.shard_map(body, mesh=ctx.mesh, in_specs=P(axis),
                             out_specs=P(axis), check_vma=False)(h)

    h = x
    for _ in range(world):
        h = pp_shift(apply(h), ctx, delta=1, impl=impl)
    return h
