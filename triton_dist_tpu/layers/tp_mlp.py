"""Tensor-parallel MLP (reference ``TP_MLP``, layers/nvidia/tp_mlp.py:52).

Column-parallel gate/up projections + row-parallel down projection. The
fused ``ag_rs`` path runs the whole MLP front half as ONE Pallas kernel
(``ops.allgather_gemm.ag_swiglu``: all-gather + gate GEMM + up GEMM +
SwiGLU epilogue — the (M, 2I/w) intermediate never touches HBM) and
reduces the down projection with the fused GEMM-RS / GEMM-AR kernels.
The reference's ``dist_triton_fwd`` (tp_mlp.py:147) stops at a shared
AG with separate activation; the extra fusion is the TPU-side answer to
XLA's own epilogue fusion (the round-3 chip bench measured the
3-dispatch version at 0.77x of XLA's fused program at world=1).

Weight convention: JAX-style ``(in_features, out_features)``; gate/up are
column-sharded ``P(None, tp)``, down is row-sharded ``P(tp, None)``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from triton_dist_tpu.ops.common import nestable_shard_map

from triton_dist_tpu.layers.common import (
    col_parallel_matmul, row_parallel_matmul_ar, shard_param)
from triton_dist_tpu.ops.allgather_gemm import create_ag_gemm_context
from triton_dist_tpu.ops.gemm_reduce_scatter import create_gemm_rs_context
# Differentiable wrappers (forward-identical; backward rides the
# transpose fused kernel — ops/autodiff.py) so mode="ag_rs"/"gemm_ar"
# trains through the Pallas path.
from triton_dist_tpu.ops.autodiff import (ag_swiglu, gemm_rs, gemm_ar)


class TPMLP:
    """SwiGLU MLP: ``down( silu(x@gate) * (x@up) )`` under TP."""

    def __init__(self, hidden_size: int, intermediate_size: int,
                 mesh: Mesh | None = None, axis: str = "tp",
                 dtype=jnp.bfloat16, fwd_mode: str = "ag_rs",
                 impl: str = "pallas"):
        if mesh is None:
            from triton_dist_tpu.runtime.dist import get_mesh
            mesh = get_mesh()
        self.mesh, self.axis = mesh, axis
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.dtype = dtype
        self.fwd_mode = fwd_mode
        self.impl = impl
        world = mesh.shape[axis]
        assert intermediate_size % world == 0
        assert hidden_size % world == 0
        # Context objects (reference _init_ctx, tp_mlp.py:116): on TPU these
        # carry tuning knobs only — symmetric workspaces live in the kernel.
        self.ag_ctx = create_ag_gemm_context(mesh, axis)
        self.rs_ctx = create_gemm_rs_context(mesh, axis)

    def set_fwd(self, mode: str):
        self.fwd_mode = mode

    # -- params ------------------------------------------------------------
    def init(self, key: jax.Array) -> dict:
        kg, ku, kd = jax.random.split(key, 3)
        h, i = self.hidden_size, self.intermediate_size
        scale = h ** -0.5
        params = {
            "w_gate": jax.random.normal(kg, (h, i), self.dtype) * scale,
            "w_up": jax.random.normal(ku, (h, i), self.dtype) * scale,
            "w_down": jax.random.normal(kd, (i, h), self.dtype) * (i ** -0.5),
        }
        return self.shard_params(params)

    def shard_params(self, params: dict) -> dict:
        m, ax = self.mesh, self.axis
        return {
            "w_gate": shard_param(params["w_gate"], m, P(None, ax)),
            "w_up": shard_param(params["w_up"], m, P(None, ax)),
            "w_down": shard_param(params["w_down"], m, P(ax, None)),
        }

    # -- forwards ----------------------------------------------------------
    def __call__(self, params: dict, x: jax.Array,
                 mode: str | None = None) -> jax.Array:
        """x: (M, H). Row-sharded for {xla, ag_rs}; replicated for
        {xla_ar, gemm_ar}. Output has the same layout as the input."""
        mode = mode or self.fwd_mode
        if mode == "ag_rs":
            return self._fused_fwd(params, x, reduce="rs")
        if mode == "gemm_ar":
            return self._fused_fwd(params, x, reduce="ar")
        if mode == "xla":
            return self._xla_fwd(params, x)
        if mode == "xla_ar":
            return self._xla_ar_fwd(params, x)
        raise ValueError(f"unknown fwd mode {mode!r}")

    def _fused_fwd(self, params, x, reduce: str):
        if reduce == "rs":
            # One kernel for AG + gate/up GEMMs + SwiGLU: the (M, 2*I/w)
            # intermediate never touches HBM (chip bench r3: the
            # 3-dispatch version measured 0.77x of XLA's fused program
            # at world=1).
            act = ag_swiglu(x, params["w_gate"], params["w_up"],
                            self.ag_ctx, impl=self.impl)
            return gemm_rs(act, params["w_down"], self.rs_ctx,
                           impl=self.impl)
        gate = col_parallel_matmul(x, params["w_gate"], self.mesh,
                                   self.axis)
        up = col_parallel_matmul(x, params["w_up"], self.mesh, self.axis)
        act = jax.nn.silu(gate.astype(jnp.float32)).astype(x.dtype) * up
        return gemm_ar(act, params["w_down"], self.rs_ctx, impl=self.impl)

    def _xla_fwd(self, params, x):
        """shard_map golden with the ag_rs layout (row-sharded x)."""
        axis = self.axis

        def body(xs, wg, wu, wd):
            ag = lax.all_gather(xs, axis, tiled=True)
            gate = jnp.dot(ag, wg, preferred_element_type=jnp.float32)
            up = jnp.dot(ag, wu, preferred_element_type=jnp.float32)
            act = (jax.nn.silu(gate) * up).astype(xs.dtype)
            part = jnp.dot(act, wd, preferred_element_type=jnp.float32
                           ).astype(xs.dtype)
            return lax.psum_scatter(part, axis, scatter_dimension=0,
                                    tiled=True)
        f = nestable_shard_map(
            body, mesh=self.mesh,
            in_specs=(P(axis), P(None, axis), P(None, axis), P(axis)),
            out_specs=P(axis), check_vma=False)
        return f(x, params["w_gate"], params["w_up"], params["w_down"])

    def _xla_ar_fwd(self, params, x):
        """Replicated-activation golden (reference torch_fwd NCCL AR)."""
        gate = col_parallel_matmul(x, params["w_gate"], self.mesh, self.axis)
        up = col_parallel_matmul(x, params["w_up"], self.mesh, self.axis)
        act = jax.nn.silu(gate.astype(jnp.float32)).astype(x.dtype) * up
        return row_parallel_matmul_ar(act, params["w_down"], self.mesh,
                                      self.axis)
