"""Tensor-parallel MLP (reference ``TP_MLP``, layers/nvidia/tp_mlp.py:52).

Column-parallel gate/up projections + row-parallel down projection. The
fused ``ag_rs`` path runs the whole MLP front half as ONE Pallas kernel
(``ops.allgather_gemm.ag_swiglu``: all-gather + gate GEMM + up GEMM +
SwiGLU epilogue — the (M, 2I/w) intermediate never touches HBM) and
reduces the down projection with the fused GEMM-RS / GEMM-AR kernels.
The reference's ``dist_triton_fwd`` (tp_mlp.py:147) stops at a shared
AG with separate activation; the extra fusion is the TPU-side answer to
XLA's own epilogue fusion (the round-3 chip bench measured the
3-dispatch version at 0.77x of XLA's fused program at world=1).

Weight convention: JAX-style ``(in_features, out_features)``; gate/up are
column-sharded ``P(None, tp)``, down is row-sharded ``P(tp, None)``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from triton_dist_tpu.ops.common import nestable_shard_map

from triton_dist_tpu.layers.common import (
    col_parallel_matmul, row_parallel_matmul_ar, shard_param)
from triton_dist_tpu.ops.allgather_gemm import create_ag_gemm_context
from triton_dist_tpu.ops.gemm_reduce_scatter import create_gemm_rs_context
# Differentiable wrappers (forward-identical; backward rides the
# transpose fused kernel — ops/autodiff.py) so mode="ag_rs"/"gemm_ar"
# trains through the Pallas path.
from triton_dist_tpu.ops.autodiff import (ag_swiglu, gemm_rs, gemm_ar)


class TPMLP:
    """SwiGLU MLP: ``down( silu(x@gate + bg) * (x@up + bu) + bd )``
    under TP.

    ``use_bias=True`` adds gate/up/down biases; on the fused paths the
    gate/up biases ride INSIDE the AG-SwiGLU kernel's epilogue (the
    whole bias + activation epilogue fused into the consumer tile loop
    — no extra HBM round trip) and the down bias is one cheap add after
    the reduce. The biased fused forward goes through the raw Pallas op
    (inference path); training with biases uses the differentiable
    ``xla``/``xla_ar`` modes."""

    def __init__(self, hidden_size: int, intermediate_size: int,
                 mesh: Mesh | None = None, axis: str = "tp",
                 dtype=jnp.bfloat16, fwd_mode: str = "ag_rs",
                 impl: str = "pallas", use_bias: bool = False):
        if mesh is None:
            from triton_dist_tpu.runtime.dist import get_mesh
            mesh = get_mesh()
        self.mesh, self.axis = mesh, axis
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.dtype = dtype
        self.fwd_mode = fwd_mode
        self.impl = impl
        self.use_bias = use_bias
        world = mesh.shape[axis]
        assert intermediate_size % world == 0
        assert hidden_size % world == 0
        # Context objects (reference _init_ctx, tp_mlp.py:116): on TPU these
        # carry tuning knobs only — symmetric workspaces live in the kernel.
        self.ag_ctx = create_ag_gemm_context(mesh, axis)
        self.rs_ctx = create_gemm_rs_context(mesh, axis)

    def set_fwd(self, mode: str):
        self.fwd_mode = mode

    # -- params ------------------------------------------------------------
    def init(self, key: jax.Array) -> dict:
        kg, ku, kd = jax.random.split(key, 3)
        h, i = self.hidden_size, self.intermediate_size
        scale = h ** -0.5
        params = {
            "w_gate": jax.random.normal(kg, (h, i), self.dtype) * scale,
            "w_up": jax.random.normal(ku, (h, i), self.dtype) * scale,
            "w_down": jax.random.normal(kd, (i, h), self.dtype) * (i ** -0.5),
        }
        if self.use_bias:
            params["b_gate"] = jnp.zeros((i,), self.dtype)
            params["b_up"] = jnp.zeros((i,), self.dtype)
            params["b_down"] = jnp.zeros((h,), self.dtype)
        return self.shard_params(params)

    def shard_params(self, params: dict) -> dict:
        m, ax = self.mesh, self.axis
        out = {
            "w_gate": shard_param(params["w_gate"], m, P(None, ax)),
            "w_up": shard_param(params["w_up"], m, P(None, ax)),
            "w_down": shard_param(params["w_down"], m, P(ax, None)),
        }
        if "b_gate" in params:
            out["b_gate"] = shard_param(params["b_gate"], m, P(ax))
            out["b_up"] = shard_param(params["b_up"], m, P(ax))
            out["b_down"] = shard_param(params["b_down"], m, P())
        return out

    # -- forwards ----------------------------------------------------------
    def __call__(self, params: dict, x: jax.Array,
                 mode: str | None = None) -> jax.Array:
        """x: (M, H). Row-sharded for {xla, ag_rs}; replicated for
        {xla_ar, gemm_ar}. Output has the same layout as the input."""
        mode = mode or self.fwd_mode
        if mode == "ag_rs":
            return self._fused_fwd(params, x, reduce="rs")
        if mode == "gemm_ar":
            return self._fused_fwd(params, x, reduce="ar")
        if mode == "xla":
            return self._xla_fwd(params, x)
        if mode == "xla_ar":
            return self._xla_ar_fwd(params, x)
        raise ValueError(f"unknown fwd mode {mode!r}")

    def _has_bias(self, params) -> bool:
        return self.use_bias and "b_gate" in params

    def _add_down_bias(self, y, params):
        if not self._has_bias(params):
            return y
        return (y.astype(jnp.float32)
                + params["b_down"].astype(jnp.float32)).astype(y.dtype)

    def _fused_fwd(self, params, x, reduce: str):
        bias = self._has_bias(params)
        if reduce == "rs":
            # One kernel for AG + gate/up GEMMs + bias + SwiGLU: the
            # (M, 2*I/w) intermediate never touches HBM (chip bench r3:
            # the 3-dispatch version measured 0.77x of XLA's fused
            # program at world=1). With biases the raw fused op carries
            # the whole epilogue (inference path — the autodiff wrapper
            # stays bias-free).
            if bias:
                from triton_dist_tpu.ops.allgather_gemm import (
                    ag_swiglu as raw_ag_swiglu)
                act = raw_ag_swiglu(x, params["w_gate"], params["w_up"],
                                    self.ag_ctx, impl=self.impl,
                                    b_gate=params["b_gate"],
                                    b_up=params["b_up"])
            else:
                act = ag_swiglu(x, params["w_gate"], params["w_up"],
                                self.ag_ctx, impl=self.impl)
            return self._add_down_bias(
                gemm_rs(act, params["w_down"], self.rs_ctx,
                        impl=self.impl), params)
        gate = col_parallel_matmul(x, params["w_gate"], self.mesh,
                                   self.axis)
        up = col_parallel_matmul(x, params["w_up"], self.mesh, self.axis)
        if bias:
            gate = gate + params["b_gate"][None, :].astype(gate.dtype)
            up = up + params["b_up"][None, :].astype(up.dtype)
        act = jax.nn.silu(gate.astype(jnp.float32)).astype(x.dtype) * up
        return self._add_down_bias(
            gemm_ar(act, params["w_down"], self.rs_ctx, impl=self.impl),
            params)

    def _xla_fwd(self, params, x):
        """shard_map golden with the ag_rs layout (row-sharded x)."""
        axis = self.axis
        bias = self._has_bias(params)

        def body(xs, wg, wu, wd, *bs):
            ag = lax.all_gather(xs, axis, tiled=True)
            gate = jnp.dot(ag, wg, preferred_element_type=jnp.float32)
            up = jnp.dot(ag, wu, preferred_element_type=jnp.float32)
            if bs:
                gate = gate + bs[0][None, :].astype(jnp.float32)
                up = up + bs[1][None, :].astype(jnp.float32)
            act = (jax.nn.silu(gate) * up).astype(xs.dtype)
            part = jnp.dot(act, wd, preferred_element_type=jnp.float32)
            if bs:
                # psum_scatter sums w copies; pre-divide so the
                # replicated bias lands exactly once.
                part = part + (bs[2][None, :].astype(jnp.float32)
                               / lax.axis_size(axis))
            part = part.astype(xs.dtype)
            return lax.psum_scatter(part, axis, scatter_dimension=0,
                                    tiled=True)

        bias_args = ((params["b_gate"], params["b_up"], params["b_down"])
                     if bias else ())
        f = nestable_shard_map(
            body, mesh=self.mesh,
            in_specs=(P(axis), P(None, axis), P(None, axis), P(axis))
            + ((P(axis), P(axis), P()) if bias else ()),
            out_specs=P(axis), check_vma=False)
        return f(x, params["w_gate"], params["w_up"], params["w_down"],
                 *bias_args)

    def _xla_ar_fwd(self, params, x):
        """Replicated-activation golden (reference torch_fwd NCCL AR)."""
        gate = col_parallel_matmul(x, params["w_gate"], self.mesh, self.axis)
        up = col_parallel_matmul(x, params["w_up"], self.mesh, self.axis)
        if self._has_bias(params):
            gate = gate + params["b_gate"][None, :].astype(gate.dtype)
            up = up + params["b_up"][None, :].astype(up.dtype)
        act = jax.nn.silu(gate.astype(jnp.float32)).astype(x.dtype) * up
        return self._add_down_bias(
            row_parallel_matmul_ar(act, params["w_down"], self.mesh,
                                   self.axis), params)
