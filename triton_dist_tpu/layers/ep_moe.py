"""Expert-parallel MoE FFN layer.

TPU-native equivalent of the reference's EP inference path
(python/triton_dist/test/nvidia/test_ep_moe_inference.py, 504 LoC:
Qwen3-MoE served with experts sharded across ranks and token routing via
the LL all-to-all; models/qwen_moe.py:108): the router runs on local
rows, :class:`~triton_dist_tpu.layers.ep_a2a.EPAll2AllLayer` dispatches
each (token, expert) pair to the rank owning the expert, the rank runs
its experts at FULL intermediate size over the received rows
(``grouped_expert_ffn`` — sorted ``ragged_dot``), and combine returns +
top-k-reduces the pair rows.

Contrast with :class:`~triton_dist_tpu.layers.tp_moe.TPMoE`: TP shards
every expert's intermediate dim across ranks (all ranks touch all
experts); EP shards the expert set itself (each rank owns E/w whole
experts) — the reference offers both, selected per deployment.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from triton_dist_tpu.ops.common import nestable_shard_map

from triton_dist_tpu.layers.common import shard_param
from triton_dist_tpu.layers.ep_a2a import EPAll2AllLayer
from triton_dist_tpu.ops.group_gemm import grouped_expert_ffn
from triton_dist_tpu.ops.moe_utils import topk_routing


class EPMoE:
    """Expert-parallel sparse FFN: dispatch → local experts → combine."""

    def __init__(self, hidden_size: int, intermediate_size: int,
                 num_experts: int, topk: int, mesh: Mesh | None = None,
                 axis: str = "ep", dtype=jnp.bfloat16,
                 impl: str = "pallas", norm_topk_prob: bool = True,
                 wire_dtype: str | None = None):
        if mesh is None:
            from triton_dist_tpu.runtime.dist import get_mesh
            mesh = get_mesh()
        self.mesh, self.axis = mesh, axis
        self.world = mesh.shape[axis]
        assert num_experts % self.world == 0
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_experts = num_experts
        self.experts_per_rank = num_experts // self.world
        self.topk = topk
        self.dtype = dtype
        self.impl = impl
        self.norm_topk_prob = norm_topk_prob
        self.wire_dtype = wire_dtype  # "fp8": quantized dispatch wire
        # One a2a layer per distinct per-rank token count (prefill vs
        # decode shapes); the reference similarly sizes its symmetric
        # buffers by max_M and reuses them (ep_a2a_layer.py:70-90).
        self._a2a: dict[int, EPAll2AllLayer] = {}

    def set_fwd(self, mode: str):  # parity with TPMoE's interface
        pass

    def _a2a_for(self, t_loc: int) -> EPAll2AllLayer:
        if t_loc not in self._a2a:
            self._a2a[t_loc] = EPAll2AllLayer(
                max_tokens=t_loc, hidden=self.hidden_size, topk=self.topk,
                num_experts=self.num_experts, mesh=self.mesh,
                axis=self.axis, dtype=self.dtype, impl=self.impl,
                wire_dtype=self.wire_dtype)
        return self._a2a[t_loc]

    # -- params (same pytree as TPMoE; EP sharding) -------------------------
    def init(self, key: jax.Array) -> dict:
        kr, kg, ku, kd = jax.random.split(key, 4)
        h, i, e = self.hidden_size, self.intermediate_size, self.num_experts
        params = {
            "w_router": jax.random.normal(kr, (h, e), jnp.float32) * h**-0.5,
            "w_gate": jax.random.normal(kg, (e, h, i), self.dtype) * h**-0.5,
            "w_up": jax.random.normal(ku, (e, h, i), self.dtype) * h**-0.5,
            "w_down": jax.random.normal(kd, (e, i, h), self.dtype) * i**-0.5,
        }
        return self.shard_params(params)

    def shard_params(self, params: dict) -> dict:
        m, ax = self.mesh, self.axis
        return {
            "w_router": shard_param(params["w_router"], m, P()),
            # Expert dim sharded: each rank owns E/w whole experts.
            "w_gate": shard_param(params["w_gate"], m, P(ax)),
            "w_up": shard_param(params["w_up"], m, P(ax)),
            "w_down": shard_param(params["w_down"], m, P(ax)),
        }

    # -- forward -----------------------------------------------------------
    def __call__(self, params: dict, x: jax.Array,
                 mode: str | None = None) -> jax.Array:
        """x: (T, H) row-sharded over ``axis``; returns the same layout.

        ``mode`` accepts "ep" (default, LL a2a dispatch) or "xla"
        (dispatch/combine ride the XLA all_to_all baseline).

        Rows are padded up to a multiple of the axis size (decode-size
        batches) — pad rows carry zero weights and are sliced off."""
        t, h = x.shape
        t_pad = -(-t // self.world) * self.world
        logits = x.astype(jnp.float32) @ params["w_router"]
        weights, indices = topk_routing(logits, self.topk,
                                        self.norm_topk_prob)
        if t_pad != t:
            pad = t_pad - t
            x = jnp.concatenate([x, jnp.zeros((pad, h), x.dtype)])
            weights = jnp.concatenate(
                [weights, jnp.zeros((pad,) + weights.shape[1:],
                                    weights.dtype)])
            indices = jnp.concatenate(
                [indices, jnp.zeros((pad,) + indices.shape[1:],
                                    indices.dtype)])

        t_loc = t_pad // self.world
        a2a = self._a2a_for(t_loc)
        e_loc = self.experts_per_rank

        tokens, local_expert, handle = a2a.dispatch(x, indices)

        def local_ffn(tok, exp, wg, wu, wd):
            return grouped_expert_ffn(tok, wg, wu, wd, exp, e_loc)

        ffn = nestable_shard_map(
            local_ffn, mesh=self.mesh,
            in_specs=(P(self.axis), P(self.axis), P(self.axis),
                      P(self.axis), P(self.axis)),
            out_specs=P(self.axis), check_vma=False)
        expert_out = ffn(tokens, local_expert, params["w_gate"],
                         params["w_up"], params["w_down"])

        out = a2a.combine(expert_out, weights, handle)
        return out[:t] if t_pad != t else out
