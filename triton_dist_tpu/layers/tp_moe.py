"""Tensor-parallel MoE layer (reference ``TP_MoE``,
python/triton_dist/layers/nvidia/tp_moe.py: AG-MoE grouped GEMM +
MoE-ReduceScatter kernels around a softmax-topk router).

Sharding: every expert's gate/up weights are column-sharded over the TP
axis ((E, H, I/w)), down weights row-sharded ((E, I/w, H)) — the dense
TP_MLP recipe applied per expert. Activations stay row(M)-sharded between
layers, like the ag_rs dense path.

Fused path ("ag_rs"): Pallas all-gather of the token rows + routing ids
(ops/allgather ≙ the AG producer of allgather_group_gemm.py), pair
expansion, grouped gate/up via ``ragged_dot`` (ops/group_gemm), then the
ring-overlapped ``moe_reduce_rs`` (ops/moe_reduce_rs ≙ moe_reduce_rs.py
:546).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from triton_dist_tpu.ops.common import nestable_shard_map

from triton_dist_tpu.layers.common import shard_param
from triton_dist_tpu.ops.allgather import (
    create_allgather_context, all_gather)
from triton_dist_tpu.ops.group_gemm import grouped_matmul
from triton_dist_tpu.ops.moe_reduce_rs import (
    create_moe_rs_context, moe_reduce_rs)
from triton_dist_tpu.ops.moe_utils import topk_routing


class TPMoE:
    """Qwen3-MoE-style sparse FFN under tensor parallelism."""

    def __init__(self, hidden_size: int, intermediate_size: int,
                 num_experts: int, topk: int, mesh: Mesh | None = None,
                 axis: str = "tp", dtype=jnp.bfloat16,
                 fwd_mode: str = "ag_rs", impl: str = "pallas",
                 norm_topk_prob: bool = True):
        if mesh is None:
            from triton_dist_tpu.runtime.dist import get_mesh
            mesh = get_mesh()
        self.mesh, self.axis = mesh, axis
        self.world = mesh.shape[axis]
        assert intermediate_size % self.world == 0
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_experts = num_experts
        self.topk = topk
        self.dtype = dtype
        self.fwd_mode = fwd_mode
        self.impl = impl
        self.norm_topk_prob = norm_topk_prob
        self.ag_ctx = create_allgather_context(mesh, axis)
        self.rs_ctx = create_moe_rs_context(mesh, axis, num_experts, topk)

    def set_fwd(self, mode: str):
        self.fwd_mode = mode

    # -- params ------------------------------------------------------------
    def init(self, key: jax.Array) -> dict:
        kr, kg, ku, kd = jax.random.split(key, 4)
        h, i, e = self.hidden_size, self.intermediate_size, self.num_experts
        params = {
            "w_router": jax.random.normal(kr, (h, e), jnp.float32) * h**-0.5,
            "w_gate": jax.random.normal(kg, (e, h, i), self.dtype) * h**-0.5,
            "w_up": jax.random.normal(ku, (e, h, i), self.dtype) * h**-0.5,
            "w_down": jax.random.normal(kd, (e, i, h), self.dtype) * i**-0.5,
        }
        return self.shard_params(params)

    def shard_params(self, params: dict) -> dict:
        m, ax = self.mesh, self.axis
        return {
            "w_router": shard_param(params["w_router"], m, P()),
            "w_gate": shard_param(params["w_gate"], m, P(None, None, ax)),
            "w_up": shard_param(params["w_up"], m, P(None, None, ax)),
            "w_down": shard_param(params["w_down"], m, P(None, ax, None)),
        }

    # -- forward -----------------------------------------------------------
    def __call__(self, params: dict, x: jax.Array,
                 mode: str | None = None) -> jax.Array:
        """x: (M, H) row-sharded over the TP axis; returns the same layout."""
        mode = mode or self.fwd_mode
        if mode not in ("ag_rs", "xla"):
            raise ValueError(f"unknown fwd mode {mode!r}")
        m, h = x.shape
        k = self.topk

        # Router runs on local rows (replicated weights — reference computes
        # routing before the AG too, tp_moe.py).
        logits = x.astype(jnp.float32) @ params["w_router"]
        weights, indices = topk_routing(logits, k, self.norm_topk_prob)

        # Decode-size batches: pad rows to a multiple of the axis (pad
        # rows carry zero weights and are sliced off at the end).
        m_pad = -(-m // self.world) * self.world
        if m_pad != m:
            pad = m_pad - m
            x = jnp.concatenate([x, jnp.zeros((pad, h), x.dtype)])
            weights = jnp.concatenate(
                [weights, jnp.zeros((pad, k), weights.dtype)])
            indices = jnp.concatenate(
                [indices, jnp.zeros((pad, k), indices.dtype)])

        impl = "xla" if mode == "xla" else self.impl
        # Fused/collective all-gather of tokens and routing ids.
        ag_x = all_gather(x, self.ag_ctx, impl=impl)
        ag_idx = self._ag_meta(indices)
        ag_w = self._ag_meta(weights)

        # Pair expansion: one row per (token, expert) pair.
        pair_ids = ag_idx.reshape(-1)                       # (M_g*k,)
        pair_x = jnp.repeat(ag_x, k, axis=0)                # (M_g*k, H)

        gate = grouped_matmul(pair_x, params["w_gate"], pair_ids,
                              self.num_experts)
        up = grouped_matmul(pair_x, params["w_up"], pair_ids,
                            self.num_experts)
        act = (jax.nn.silu(gate.astype(jnp.float32)) *
               up.astype(jnp.float32)).astype(x.dtype)

        rs_impl = "xla" if mode == "xla" else "ring"
        out = moe_reduce_rs(act, params["w_down"], pair_ids, ag_w,
                            self.rs_ctx, impl=rs_impl)
        return out[:m] if m_pad != m else out

    def _ag_meta(self, arr: jax.Array) -> jax.Array:
        """All-gather small routing metadata (XLA collective)."""
        axis = self.axis

        def body(a):
            return lax.all_gather(a, axis, tiled=True)
        return nestable_shard_map(body, mesh=self.mesh, in_specs=P(axis),
                             out_specs=P(), check_vma=False)(arr)
