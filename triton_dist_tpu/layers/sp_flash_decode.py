"""Sequence-parallel attention layers.

TPU-native analogs of the reference's ``SpFlashDecodeLayer``
(python/triton_dist/layers/nvidia/sp_flash_decode_layer.py: binds the
flash-decode context + kernels to a module API over a sequence-sharded KV
cache) and the SP prefill wrapper around the AG-attention kernels.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.ops.flash_decode import (
    create_flash_decode_context, gqa_fwd_batch_decode)
from triton_dist_tpu.ops.sp_attention import (
    create_sp_attention_context, sp_ag_attention)


class SpFlashDecodeLayer:
    """Decode attention over a sequence-sharded KV cache.

    Owns the cache layout: (B, T, Hkv, D) with T sharded over the SP axis.
    ``append`` writes the new token's K/V at the decode offset (the write
    lands on the one shard owning that position); ``__call__`` runs the
    distributed flash-decode.
    """

    def __init__(self, batch: int, max_seq: int, num_kv_heads: int,
                 head_dim: int, mesh: Mesh | None = None, axis: str = "sp",
                 dtype=jnp.bfloat16, impl: str = "pallas"):
        if mesh is None:
            from triton_dist_tpu.runtime.dist import get_mesh
            mesh = get_mesh()
        self.mesh, self.axis = mesh, axis
        world = mesh.shape[axis]
        assert max_seq % world == 0
        self.batch, self.max_seq = batch, max_seq
        self.num_kv_heads, self.head_dim = num_kv_heads, head_dim
        self.dtype = dtype
        self.impl = impl
        self.ctx = create_flash_decode_context(mesh, axis)
        self._kv_sharding = NamedSharding(mesh, P(None, axis))

    def init_cache(self):
        shape = (self.batch, self.max_seq, self.num_kv_heads, self.head_dim)
        z = jnp.zeros(shape, self.dtype)
        return (jax.device_put(z, self._kv_sharding),
                jax.device_put(z, self._kv_sharding))

    def append(self, kv_cache, k_new: jax.Array, v_new: jax.Array,
               offset: jax.Array):
        """Write (B, 1, Hkv, D) new entries at ``offset``. XLA turns the
        dynamic-update-slice into a write on the owning shard."""
        ck, cv = kv_cache
        off = jnp.asarray(offset, jnp.int32)
        ck = jax.lax.dynamic_update_slice(ck, k_new.astype(ck.dtype),
                                          (0, off, 0, 0))
        cv = jax.lax.dynamic_update_slice(cv, v_new.astype(cv.dtype),
                                          (0, off, 0, 0))
        return ck, cv

    def __call__(self, q: jax.Array, kv_cache, kv_len) -> jax.Array:
        """q: (B, Hq, D) replicated; returns (B, Hq, D)."""
        ck, cv = kv_cache
        return gqa_fwd_batch_decode(q, ck, cv, kv_len, self.ctx,
                                    impl=self.impl)


class SpAttentionLayer:
    """Prefill SP attention wrapper (ring / AG-KV), sequence-sharded IO."""

    def __init__(self, mesh: Mesh | None = None, axis: str = "sp",
                 causal: bool = True, impl: str = "ring"):
        self.ctx = create_sp_attention_context(mesh, axis, causal=causal)
        self.impl = impl

    def __call__(self, q: jax.Array, k: jax.Array, v: jax.Array
                 ) -> jax.Array:
        return sp_ag_attention(q, k, v, self.ctx, impl=self.impl)
