"""Tensor-parallel attention (reference ``TP_Attn``, layers/nvidia/tp_attn.py:79).

QKV projections column-parallel (sharded over heads), output projection
row-parallel. GQA with Qwen3-style per-head q/k RMSNorm and rotary
embeddings. The fused path shares one all-gather across the three QKV
GEMMs (``ag_gemm_multi``) and fuses the output projection with the
ReduceScatter / AllReduce (reference ``dist_triton_fwd`` tp_attn.py:215).

The attention core itself is a shard_map over the head axis — heads are
fully local under TP, so no collective appears between the QKV and O
projections (same property as the reference, which calls single-GPU flash
attention on the local heads).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from triton_dist_tpu.ops.common import nestable_shard_map

from triton_dist_tpu.layers.common import (
    apply_rope, col_parallel_matmul, rms_norm, shard_param)
from triton_dist_tpu.ops.allgather_gemm import create_ag_gemm_context
from triton_dist_tpu.ops.gemm_reduce_scatter import create_gemm_rs_context
# Differentiable wrappers (forward-identical; ops/autodiff.py).
from triton_dist_tpu.ops.autodiff import ag_gemm_multi, gemm_rs, gemm_ar


class TPAttn:
    """GQA attention under TP. No QKV bias (Qwen3 dropped it)."""

    def __init__(self, hidden_size: int, num_heads: int, num_kv_heads: int,
                 head_dim: int, mesh: Mesh | None = None, axis: str = "tp",
                 dtype=jnp.bfloat16, fwd_mode: str = "ag_rs",
                 impl: str = "pallas", qk_norm: bool = True,
                 rms_eps: float = 1e-6):
        if mesh is None:
            from triton_dist_tpu.runtime.dist import get_mesh
            mesh = get_mesh()
        self.mesh, self.axis = mesh, axis
        self.hidden_size = hidden_size
        self.num_heads, self.num_kv_heads = num_heads, num_kv_heads
        self.head_dim = head_dim
        self.dtype = dtype
        self.fwd_mode = fwd_mode
        self.impl = impl
        self.qk_norm = qk_norm
        self.rms_eps = rms_eps
        world = mesh.shape[axis]
        assert num_heads % world == 0, (num_heads, world)
        assert num_kv_heads % world == 0, (num_kv_heads, world)
        self.ag_ctx = create_ag_gemm_context(mesh, axis)
        self.rs_ctx = create_gemm_rs_context(mesh, axis)

    def set_fwd(self, mode: str):
        self.fwd_mode = mode

    # -- params ------------------------------------------------------------
    def init(self, key: jax.Array) -> dict:
        kq, kk, kv, ko = jax.random.split(key, 4)
        h, d = self.hidden_size, self.head_dim
        nq, nkv = self.num_heads * d, self.num_kv_heads * d
        scale = h ** -0.5
        params = {
            "w_q": jax.random.normal(kq, (h, nq), self.dtype) * scale,
            "w_k": jax.random.normal(kk, (h, nkv), self.dtype) * scale,
            "w_v": jax.random.normal(kv, (h, nkv), self.dtype) * scale,
            "w_o": jax.random.normal(ko, (nq, h), self.dtype) * (nq ** -0.5),
        }
        if self.qk_norm:
            params["q_norm"] = jnp.ones((d,), self.dtype)
            params["k_norm"] = jnp.ones((d,), self.dtype)
        return self.shard_params(params)

    def shard_params(self, params: dict) -> dict:
        m, ax = self.mesh, self.axis
        out = {
            "w_q": shard_param(params["w_q"], m, P(None, ax)),
            "w_k": shard_param(params["w_k"], m, P(None, ax)),
            "w_v": shard_param(params["w_v"], m, P(None, ax)),
            "w_o": shard_param(params["w_o"], m, P(ax, None)),
        }
        for name in ("q_norm", "k_norm"):
            if name in params:
                out[name] = shard_param(params[name], m, P())
        return out

    # -- forward -----------------------------------------------------------
    def __call__(self, params: dict, x: jax.Array, position_ids: jax.Array,
                 rope_cache: tuple[jax.Array, jax.Array],
                 kv_cache: tuple[jax.Array, jax.Array],
                 offset: jax.Array, mode: str | None = None,
                 kv_start: jax.Array | None = None):
        """One attention block.

        Args:
          x: (M, H) activations, M = B*S. Row-sharded over tp for
            {xla, ag_rs}; replicated for {xla_ar, gemm_ar}.
          position_ids: (B, S) absolute positions.
          rope_cache: (cos, sin) tables (T_max, D/2).
          kv_cache: (k, v) each (B, T, num_kv_heads, D), head-sharded.
          offset: int32 write position into the cache — scalar, or a
            (B,) per-row vector when S == 1 (continuous batching;
            see _attention_core).
        Returns:
          (out, (k_cache, v_cache)): out has the same layout as x.
        """
        mode = mode or self.fwd_mode
        impl = "xla" if mode in ("xla", "xla_ar") else self.impl
        sharded = mode in ("xla", "ag_rs")
        b, s = position_ids.shape
        d = self.head_dim

        if sharded:
            q, k, v = ag_gemm_multi(
                x, [params["w_q"], params["w_k"], params["w_v"]],
                self.ag_ctx, impl=impl)
        else:
            q = col_parallel_matmul(x, params["w_q"], self.mesh, self.axis)
            k = col_parallel_matmul(x, params["w_k"], self.mesh, self.axis)
            v = col_parallel_matmul(x, params["w_v"], self.mesh, self.axis)

        q = q.reshape(b, s, self.num_heads, d)
        k = k.reshape(b, s, self.num_kv_heads, d)
        v = v.reshape(b, s, self.num_kv_heads, d)

        # Per-head RMSNorm before rope (Qwen3; reference tp_attn.py:196-200).
        if self.qk_norm:
            q = rms_norm(q, params["q_norm"], self.rms_eps)
            k = rms_norm(k, params["k_norm"], self.rms_eps)
        cos, sin = rope_cache
        q = apply_rope(q, cos, sin, position_ids)
        k = apply_rope(k, cos, sin, position_ids)

        attn, new_cache = self._attention(q, k, v, kv_cache, offset,
                                          kv_start)
        attn = attn.reshape(b * s, self.num_heads * d)

        if sharded:
            out = gemm_rs(attn, params["w_o"], self.rs_ctx, impl=impl)
        else:
            out = gemm_ar(attn, params["w_o"], self.rs_ctx, impl=impl)
        return out, new_cache

    def _attention(self, q, k, v, kv_cache, offset, kv_start=None):
        """Cached GQA attention, shard_mapped over the head axis.

        Equivalent role to the reference's flash-attn call on local heads
        (tp_attn.py:215 dist_triton_fwd); the Pallas flash/SP kernels
        (ops/flash_decode.py) plug in here for long-context paths."""
        axis = self.axis
        groups = self.num_heads // self.num_kv_heads
        core = functools.partial(_attention_core, groups=groups)
        spec = P(None, None, axis, None)
        if kv_start is None:
            kv_start = jnp.zeros((q.shape[0],), jnp.int32)
        f = nestable_shard_map(
            core, mesh=self.mesh,
            in_specs=(spec, spec, spec, spec, spec, P(), P()),
            out_specs=(spec, spec, spec), check_vma=False)
        out, ck, cv = f(q, k, v, kv_cache[0], kv_cache[1],
                        jnp.asarray(offset, jnp.int32),
                        jnp.asarray(kv_start, jnp.int32))
        return out, (ck, cv)


def _attention_core(q, k, v, cache_k, cache_v, offset, kv_start, *,
                    groups: int):
    """Single-device cached causal GQA (fp32 softmax).

    q: (B, S, hq, D); k/v: (B, S, hkv, D); cache: (B, T, hkv, D).
    Query i sits at absolute position offset+i and attends to cache
    positions kv_start[b] <= j <= offset+i — ``kv_start`` is the
    left-padding boundary for ragged batches (all-zeros = the plain
    causal mask). Fully-masked (pad) query rows get finite garbage (not
    NaN); their logits are never consumed.

    ``offset`` may be a PER-ROW (B,) vector (continuous batching: each
    row decodes at its own write position, Engine.serve_stream). Scalar
    offset keeps the contiguous dynamic_update_slice write; the vector
    path scatters per row — one position (S == 1, the stream decode
    step) or a burst of S positions offset[b]+[0, S) (the speculative-
    decoding verify window, Engine spec steps; out-of-range positions
    are dropped by the scatter, which only frozen rows near max_seq
    ever produce)."""
    b, s, hq, d = q.shape
    t = cache_k.shape[1]
    hkv = cache_k.shape[2]
    if offset.ndim == 0:
        cache_k = lax.dynamic_update_slice(cache_k, k, (0, offset, 0, 0))
        cache_v = lax.dynamic_update_slice(cache_v, v, (0, offset, 0, 0))
        off_b = jnp.broadcast_to(offset, (b,))
    else:
        rows = jnp.arange(b)
        if s == 1:
            cache_k = cache_k.at[rows, offset].set(k[:, 0])
            cache_v = cache_v.at[rows, offset].set(v[:, 0])
        else:
            # Burst write: row b's window lands at offset[b]+[0, S).
            # Positions past T (frozen rows at stale offsets) drop out
            # of the scatter; in-lane overshoot is overwritten before
            # any causal mask exposes it (the stream-admission pad-slot
            # safety argument, docs/serving.md "Speculative decoding").
            pos = offset[:, None] + jnp.arange(s, dtype=jnp.int32)[None]
            cache_k = cache_k.at[rows[:, None], pos].set(k)
            cache_v = cache_v.at[rows[:, None], pos].set(v)
        off_b = offset

    # Contractions run in the cache dtype when q matches it (MXU-native
    # bf16 is up to 3x an f32 matmul; f32 accumulation keeps scores
    # bit-identical to an upcast-first dot — r4, same treatment as
    # ops/flash_decode). Mismatched precision keeps the exact f32 path.
    dt = cache_k.dtype if q.dtype == cache_k.dtype else jnp.float32
    qg = q.reshape(b, s, hkv, groups, d).astype(dt)
    scores = jnp.einsum("bskgd,btkd->bkgst", qg, cache_k.astype(dt),
                        preferred_element_type=jnp.float32) * (d ** -0.5)
    q_pos = off_b[:, None, None] + jnp.arange(s)[None, :, None]  # (B,S,1)
    causal = jnp.arange(t)[None, None, :] <= q_pos  # (B, S, T)
    live = jnp.arange(t)[None, :] >= kv_start[:, None]  # (B, T)
    mask = causal & live[:, None]  # (B, S, T)
    scores = jnp.where(mask[:, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgst,btkd->bskgd", probs.astype(dt),
                     cache_v.astype(dt),
                     preferred_element_type=jnp.float32)
    return out.reshape(b, s, hq, d).astype(q.dtype), cache_k, cache_v
