"""Model layers (reference L6: python/triton_dist/layers/nvidia/).

Each layer is a thin module object owning config + op contexts, with pure
functional forwards over pytree params — the idiomatic JAX shape of the
reference's ``TP_MLP`` (tp_mlp.py:52) / ``TP_Attn`` (tp_attn.py:79)
torch modules.

Forward-mode names map to the reference's per-layer ``set_fwd`` modes
(models/dense.py:216):

- ``"xla"``      ≙ ``torch`` (NCCL): shard_map + lax collectives golden.
- ``"ag_rs"``    ≙ ``triton_dist``: fused AG-GEMM + GEMM-RS, activations
                  row(M)-sharded between layers.
- ``"gemm_ar"``  ≙ ``triton_dist_gemm_ar``: replicated activations, fused
                  GEMM-AllReduce output projection (small-batch decode).
- ``"xla_ar"``   ≙ ``torch`` golden for the replicated layout.
"""

from triton_dist_tpu.layers.common import (  # noqa: F401
    rms_norm,
    precompute_rope_cache,
    apply_rope,
    col_parallel_matmul,
    shard_param,
)
from triton_dist_tpu.layers.tp_mlp import TPMLP  # noqa: F401
from triton_dist_tpu.layers.tp_attn import TPAttn  # noqa: F401
from triton_dist_tpu.layers.tp_moe import TPMoE  # noqa: F401
from triton_dist_tpu.layers.ep_moe import EPMoE  # noqa: F401

FWD_MODES = ("xla", "ag_rs", "gemm_ar", "xla_ar")
