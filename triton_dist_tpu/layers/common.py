"""Shared layer math: RMSNorm, rotary embeddings, sharded matmul helpers.

Reference analogs: ``layer_norm`` (layers/nvidia/tp_attn.py:60, flashinfer
rmsnorm), ``_set_cos_sin_cache`` (tp_attn.py:69), ``shard_local``
(tp_mlp.py:38). On TPU the norms and rope stay as jnp ops — XLA fuses them
into neighbouring kernels; hand-writing them in Pallas would only block
fusion.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from triton_dist_tpu.ops.common import nestable_shard_map


def rms_norm(x: jax.Array, w: jax.Array, eps: float = 1e-6) -> jax.Array:
    """RMSNorm in fp32 accumulation (reference layer_norm, tp_attn.py:60)."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * lax.rsqrt(var + eps)
    return (normed * w.astype(jnp.float32)).astype(x.dtype)


def precompute_rope_cache(head_dim: int, max_len: int,
                          theta: float = 1e6) -> tuple[jax.Array, jax.Array]:
    """(cos, sin) tables of shape (max_len, head_dim//2), fp32
    (reference ``_set_cos_sin_cache`` tp_attn.py:69-75)."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                           dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array,
               position_ids: jax.Array) -> jax.Array:
    """Neox-style (rotate-half) rotary embedding.

    x: (B, S, H, D); position_ids: (B, S). Matches HF Qwen3 /
    flashinfer.apply_rope_with_cos_sin_cache (reference tp_attn.py:166)."""
    c = cos[position_ids][:, :, None, :]  # (B, S, 1, D/2)
    s = sin[position_ids][:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)


def shard_param(x: jax.Array, mesh: Mesh, spec: P) -> jax.Array:
    """Place a (host) array with a named sharding — the analog of the
    reference's ``shard_local`` (tp_mlp.py:38), except JAX slices the
    global array per device instead of each rank slicing by hand."""
    return jax.device_put(x, NamedSharding(mesh, spec))


def col_parallel_matmul(x: jax.Array, w: jax.Array, mesh: Mesh,
                        axis: str = "tp") -> jax.Array:
    """x replicated (M, K) @ w column-sharded (K, N) -> (M, N) col-sharded.

    The local GEMM of the reference's replicated-activation modes
    (tp_attn.py torch_fwd / gemm-ar path)."""
    f = nestable_shard_map(
        lambda xs, ws: jnp.dot(xs, ws, preferred_element_type=jnp.float32
                               ).astype(xs.dtype),
        mesh=mesh, in_specs=(P(), P(None, axis)),
        out_specs=P(None, axis), check_vma=False)
    return f(x, w)


def row_parallel_matmul_ar(x: jax.Array, w: jax.Array, mesh: Mesh,
                           axis: str = "tp") -> jax.Array:
    """x col-sharded (M, K) @ w row-sharded (K, N) + psum -> replicated.

    XLA golden for the fused ``gemm_ar`` path."""
    def body(xs, ws):
        part = jnp.dot(xs, ws, preferred_element_type=jnp.float32
                       ).astype(xs.dtype)
        return lax.psum(part, axis)
    f = nestable_shard_map(body, mesh=mesh, in_specs=(P(None, axis), P(axis)),
                      out_specs=P(), check_vma=False)
    return f(x, w)
