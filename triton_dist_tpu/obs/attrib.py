"""Per-request latency attribution: where did this request's time go?

The trace timeline (PR 4) already carries every request's story —
``serving.admit`` / ``serving.retire`` instants, admission prefill
events, prefix-hit instants — under its trace ID, but reading it
means exporting a dump and opening Perfetto. This module folds the
same per-request timestamps into a *waterfall* the serving path can
hand back inline:

    queue_wait → prefill (admission, chunked or one-shot, minus any
    prefix-cache hit) → decode (per-token share)

Segments are computed from one monotonic clock's readings
(``t_submit`` → ``t_admit`` → ``t_first`` → ``t_done``), so they sum
to the request's measured wall time *by construction* — the
acceptance contract (segments ≈ wall time within 5 ms on CPU) is
arithmetic, not sampling.

Consumers (docs/observability.md "Request attribution"):

- the scheduler attaches each finished request's waterfall to its
  future, and the server returns it in the response under
  ``"timing"``;
- the last ``TDT_ATTRIB_RING`` (default 256) waterfalls sit in a
  process-local ring, queryable via ``{"cmd": "request_stats"}``;
- ``tools/top.py`` renders the freshest entries in its refresh loop,
  and bench.py embeds one sampled waterfall per serving part so
  BENCH_*.json shows where TTFT went.
"""

from __future__ import annotations

import collections
import threading

from triton_dist_tpu.obs import registry as _registry

__all__ = ["DEFAULT_RING", "build", "last", "push", "reset",
           "ring_size"]

DEFAULT_RING = 256

_LOCK = threading.Lock()
_RING: collections.deque | None = None


def ring_size() -> int:
    return _registry.env_int("TDT_ATTRIB_RING", DEFAULT_RING,
                             minimum=1)


def build(*, rid: int, trace_id: str | None, t_submit: float,
          t_admit: float, t_first: float, t_done: float,
          prompt_tokens: int, tokens: int, cached_tokens: int = 0,
          prefill_chunks: int = 0, draft_ms: float = 0.0,
          verify_ms: float = 0.0) -> dict:
    """Waterfall dict from one request's monotonic-clock milestones
    (``time.perf_counter`` readings). The three segments partition
    ``[t_submit, t_done]`` exactly:

    - ``queue_wait_ms`` — submit → admission start;
    - ``prefill_ms`` — admission start → first token sampled (covers
      every chunked-prefill slice, including pump iterations it shared
      with decode steps);
    - ``decode_ms`` — first token → retirement.

    ``draft_ms``/``verify_ms`` (ISSUE 13): speculative-decoding
    sub-attribution of the decode segment — the draft and widened-
    verify wall time of every shared burst this request rode. They
    ride under ``"spec"`` and are NOT part of the exact partition
    (shared-step time is booked to every rider, like ``decode_ms``
    itself); present only when the engine speculated.
    """
    queue_wait = (t_admit - t_submit) * 1e3
    prefill = (t_first - t_admit) * 1e3
    decode = (t_done - t_first) * 1e3
    tpot = decode / (tokens - 1) if tokens > 1 else None
    out = {
        "rid": rid,
        "trace_id": trace_id,
        "total_ms": round((t_done - t_submit) * 1e3, 3),
        "segments": {
            "queue_wait_ms": round(queue_wait, 3),
            "prefill_ms": round(prefill, 3),
            "decode_ms": round(decode, 3),
        },
        "prompt_tokens": int(prompt_tokens),
        "cached_tokens": int(cached_tokens),
        "prefill_chunks": int(prefill_chunks),
        "tokens": int(tokens),
        "tpot_ms": round(tpot, 3) if tpot is not None else None,
    }
    if draft_ms or verify_ms:
        out["spec"] = {"draft_ms": round(draft_ms, 3),
                       "verify_ms": round(verify_ms, 3)}
    return out


def push(record: dict) -> None:
    """Keep ``record`` in the last-K ring (newest last)."""
    global _RING
    with _LOCK:
        if _RING is None:
            _RING = collections.deque(maxlen=ring_size())
        _RING.append(record)


def last(k: int | None = None) -> list[dict]:
    """The newest ``k`` (default: all retained) waterfalls,
    newest first."""
    with _LOCK:
        items = list(_RING) if _RING else []
    items.reverse()
    if k is not None:
        items = items[:max(int(k), 0)]
    return items


def reset() -> None:
    global _RING
    with _LOCK:
        _RING = None
