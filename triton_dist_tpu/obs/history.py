"""History plane: sampled time series, trend math, early-warning
detectors (ISSUE 16).

Every signal the repo computes — SLO burn rates, queue depth, KV-block
occupancy, ``device.step.*`` times — is point-in-time: the registry
keeps the latest value, the rolling windows forget, and a flight dump
begins at the breach instant. This module retains the lead-up, so any
number becomes comparable to itself five minutes ago:

- :class:`Series` / :class:`SeriesStore` — fixed-size ring buffers of
  ``(t, value)`` points (``TDT_HISTORY_LEN`` points per series,
  monotonic ``time.perf_counter()`` timestamps plus a wall-clock
  ``epoch`` anchor so exported points line up with ``obs.trace``'s
  micros). Appends are lock-free on the sampler thread (preallocated
  slots, GIL-atomic stores); readers snapshot without blocking it.
- :class:`HistorySampler` — an opt-in background thread that rides the
  same C-level ``peek_gauges`` / ``peek_counters`` reads the ``health``
  verb uses, every ``TDT_HISTORY_TICK_S`` seconds: gauges are stored
  as values, counters as per-second RATES (the delta between ticks).
  ``from_env`` returns None unless ``TDT_HISTORY=1`` — the
  zero-overhead-when-unused contract of ``obs.registry`` is preserved:
  no sampler, no thread, no cost.
- Trend queries as pure functions over point lists — :func:`slope`
  (least squares), :func:`ema`, :func:`window_stats`, and
  :func:`eta_to` ("queue depth crosses max_waiting in ~N s", "KV pool
  exhausted in ~N s", "burn rate crosses 1.0 in ~N s") — the forecast
  surface ISSUE 17's autoscaler will consume verbatim, the way the
  router consumed ``placement_score``.
- Early-warning **detectors** — :class:`SustainedSlope` and
  :class:`StepChange` over configurable windows
  (``TDT_HISTORY_SLOPE`` / ``TDT_HISTORY_STEP``, e.g.
  ``serving.queue_depth>0.5@30``) — that emit a ``history.warning``
  trace instant and arm the existing flight-dump +
  ``TDT_DEVPROF_ON_BREACH`` machinery *before* the SLO breach
  (``obs.flight.maybe_dump`` → ``obs.devprof.arm``), turning
  postmortems into pre-mortems. A detector latches: it fires exactly
  once per sustained excursion and re-arms only after the condition
  clears (no instant-storm).
- :func:`sparkline` — the unicode renderer ``tools/top.py`` /
  ``fleet_top.py`` / ``report.py`` share.

A live sampler installs itself as ``obs.flight``'s history provider,
so every flight dump embeds the trailing ``TDT_HISTORY_DUMP_S``
seconds of sampled series (``metadata.history``) and
``tools/trace_export.to_chrome`` renders them as Perfetto COUNTER
tracks next to the event timeline.

Knobs (docs/observability.md "History plane"): ``TDT_HISTORY``,
``TDT_HISTORY_LEN``, ``TDT_HISTORY_TICK_S``, ``TDT_HISTORY_DUMP_S``,
``TDT_HISTORY_SLOPE``, ``TDT_HISTORY_STEP``.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import threading
import time

from triton_dist_tpu.obs import flight as _flight
from triton_dist_tpu.obs import registry as _registry
from triton_dist_tpu.obs import trace as _trace

__all__ = [
    "DEFAULT_DETECTOR_WINDOW_S", "DEFAULT_DUMP_S", "DEFAULT_EMA_ALPHA",
    "DEFAULT_HISTORY_LEN", "DEFAULT_TICK_S", "DetectorSpec",
    "HistorySampler", "Series", "SeriesStore", "StepChange",
    "SustainedSlope", "downsample", "ema", "eta_to", "history_dump_s",
    "history_enabled", "history_len", "history_tick_s",
    "make_detector", "parse_detectors", "slope", "sparkline",
    "window_stats",
]

DEFAULT_HISTORY_LEN = 512
DEFAULT_TICK_S = 1.0
DEFAULT_DUMP_S = 60.0
DEFAULT_DETECTOR_WINDOW_S = 30.0
DEFAULT_EMA_ALPHA = 0.3

#: Warning records retained per store (newest-first in snapshots).
MAX_WARNINGS = 64


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name, "").strip()
    if not v:
        return default
    try:
        return float(v)
    except ValueError:
        raise ValueError(f"{name} must be a number: {v!r}") from None


def history_enabled() -> bool:
    """``TDT_HISTORY=1`` opts the scheduler's sampler in (default off:
    the zero-overhead contract)."""
    return bool(_registry.env_int("TDT_HISTORY", 0))


def history_len() -> int:
    return _registry.env_int("TDT_HISTORY_LEN", DEFAULT_HISTORY_LEN,
                             minimum=2)


def history_tick_s() -> float:
    v = _env_float("TDT_HISTORY_TICK_S", DEFAULT_TICK_S)
    if v <= 0:
        raise ValueError(f"TDT_HISTORY_TICK_S must be positive: {v}")
    return v


def history_dump_s() -> float:
    """Trailing seconds of series a flight dump embeds."""
    return _env_float("TDT_HISTORY_DUMP_S", DEFAULT_DUMP_S)


# ---------------------------------------------------------------------------
# Ring-buffered series + the store.
# ---------------------------------------------------------------------------

class Series:
    """Fixed-size ring of ``(t, value)`` points — one writer (the
    sampler), lock-free readers.

    The slots are preallocated lists written by index, so an append is
    three GIL-atomic stores and never allocates; :meth:`points` copies
    the slot lists (one C-level pass each) and reorders. With a
    concurrent append, the OLDEST returned point may belong to the
    next generation — benign for trend math over a trailing window,
    and the price of never taking a lock on the sample path."""

    __slots__ = ("name", "maxlen", "_t", "_v", "_n")

    def __init__(self, name: str, maxlen: int):
        maxlen = int(maxlen)
        if maxlen < 2:
            raise ValueError(f"series maxlen must be >= 2: {maxlen}")
        self.name = name
        self.maxlen = maxlen
        self._t = [0.0] * maxlen
        self._v = [0.0] * maxlen
        self._n = 0                    # total appends ever

    def append(self, t: float, v: float) -> None:
        i = self._n % self.maxlen
        self._t[i] = float(t)
        self._v[i] = float(v)
        self._n += 1                   # publish last

    def __len__(self) -> int:
        return min(self._n, self.maxlen)

    @property
    def total(self) -> int:
        """Total points ever appended (ring overwrites included)."""
        return self._n

    def last(self):
        """The newest ``(t, value)`` or None when empty."""
        n = self._n
        if n == 0:
            return None
        i = (n - 1) % self.maxlen
        return (self._t[i], self._v[i])

    def points(self, last_s: float | None = None,
               now: float | None = None) -> list:
        """Oldest-first ``[(t, value), ...]``; ``last_s`` trims to the
        trailing window ending at ``now`` (default: the newest
        point's timestamp)."""
        n = self._n
        k = min(n, self.maxlen)
        if k == 0:
            return []
        ts = list(self._t)
        vs = list(self._v)
        pts = []
        for j in range(n - k, n):
            i = j % self.maxlen
            pts.append((ts[i], vs[i]))
        if last_s is not None:
            anchor = pts[-1][0] if now is None else float(now)
            cutoff = anchor - float(last_s)
            pts = [p for p in pts if p[0] >= cutoff]
        return pts

    def values(self, last_s: float | None = None,
               now: float | None = None) -> list:
        return [v for _, v in self.points(last_s, now)]


class SeriesStore:
    """Named :class:`Series` rings plus a bounded warning ring.

    The lock guards only series CREATION (a dict mutation); appends
    and reads go straight to the rings. ``epoch`` is the same
    wall-minus-perf anchor ``obs.trace``'s Tracer keeps, so exported
    points convert to the trace's wall-anchored micros
    (``(t + epoch) * 1e6``) and counter tracks line up with the event
    timeline in one Perfetto view."""

    def __init__(self, maxlen: int | None = None,
                 max_warnings: int = MAX_WARNINGS):
        self.maxlen = maxlen if maxlen is not None else history_len()
        self.epoch = time.time() - time.perf_counter()
        self._series: dict[str, Series] = {}
        self._lock = threading.Lock()
        self._warnings: collections.deque = collections.deque(
            maxlen=max_warnings)

    def __len__(self) -> int:
        return len(self._series)

    def names(self) -> list:
        return sorted(self._series)

    def get(self, name: str) -> Series | None:
        return self._series.get(name)

    def series(self, name: str) -> Series:
        s = self._series.get(name)
        if s is None:
            with self._lock:
                s = self._series.get(name)
                if s is None:
                    s = self._series[name] = Series(name, self.maxlen)
        return s

    def record(self, name: str, t: float, v: float) -> None:
        self.series(name).append(t, v)

    def add_warning(self, rec: dict) -> None:
        self._warnings.append(dict(rec))

    def warnings(self) -> list:
        """Newest-first warning records (bounded ring)."""
        return list(self._warnings)[::-1]

    def snapshot(self, last_s: float | None = None, series=None,
                 max_points: int | None = None) -> dict:
        """JSON-safe view: ``{"epoch", "maxlen", "series": {name:
        {"points": [[t, v], ...], "n": total}}, "warnings": [...]}``.
        ``series`` filters by name, ``last_s`` trims to the trailing
        window, ``max_points`` downsamples (stride, newest kept)."""
        wanted = set(series) if series else None
        out: dict = {"epoch": self.epoch, "maxlen": self.maxlen,
                     "series": {}, "warnings": self.warnings()}
        for name in self.names():
            if wanted is not None and name not in wanted:
                continue
            s = self._series[name]
            pts = downsample(s.points(last_s=last_s), max_points)
            out["series"][name] = {
                "points": [[round(t, 6), v] for t, v in pts],
                "n": s.total}
        return out


def downsample(points: list, max_points: int | None) -> list:
    """Stride-downsample oldest-first points to at most
    ``max_points``, always keeping the NEWEST point (dashboards read
    the right edge)."""
    if max_points is None or len(points) <= max_points:
        return list(points)
    if max_points <= 0:
        return []
    stride = -(-len(points) // max_points)
    return list(points)[-1::-stride][::-1]


# ---------------------------------------------------------------------------
# Trend math: pure functions over [(t, v), ...] point lists.
# ---------------------------------------------------------------------------

def slope(points: list) -> float | None:
    """Least-squares slope in value-units per second, or None when
    fewer than 2 points or zero time variance make a fit meaningless
    (the len<2 degenerate case is the caller's no-data answer, not
    0.0 — a flat reading and no reading must stay distinguishable)."""
    n = len(points)
    if n < 2:
        return None
    mt = sum(t for t, _ in points) / n
    mv = sum(v for _, v in points) / n
    num = sum((t - mt) * (v - mv) for t, v in points)
    den = sum((t - mt) ** 2 for t, _ in points)
    if den <= 0.0:
        return None
    return num / den


def ema(points: list, alpha: float = DEFAULT_EMA_ALPHA) -> float | None:
    """Exponential moving average of the values, oldest-first
    (``s = alpha * v + (1 - alpha) * s``); None when empty."""
    if not points:
        return None
    if not 0.0 < alpha <= 1.0:
        raise ValueError(f"ema alpha must be in (0, 1]: {alpha}")
    s = float(points[0][1])
    for _, v in points[1:]:
        s = alpha * float(v) + (1.0 - alpha) * s
    return s


def window_stats(points: list) -> dict:
    """``{"n", "min", "max", "avg", "last", "span_s"}`` over a point
    list (``{"n": 0}`` when empty)."""
    if not points:
        return {"n": 0}
    vals = [v for _, v in points]
    return {"n": len(vals), "min": min(vals), "max": max(vals),
            "avg": sum(vals) / len(vals), "last": vals[-1],
            "span_s": points[-1][0] - points[0][0]}


def eta_to(points: list, threshold: float) -> float | None:
    """Seconds until the least-squares fit reaches ``threshold``:
    positive when the trend points at it, ``0.0`` when the last value
    already sits ON it, None when there is no crossing ahead (flat or
    moving away, including the negative-slope-below-threshold case)
    or fewer than 2 points. This is the forecast behind "queue depth
    crosses max_waiting in ~N s"."""
    if len(points) < 2:
        return None
    last = float(points[-1][1])
    threshold = float(threshold)
    if last == threshold:
        return 0.0
    s = slope(points)
    if not s:                       # None or exactly flat: never crosses
        return None
    t_cross = (threshold - last) / s
    return t_cross if t_cross > 0 else None


# ---------------------------------------------------------------------------
# Sparklines (the dashboard renderer — pure).
# ---------------------------------------------------------------------------

_SPARK_CHARS = "▁▂▃▄▅▆▇█"


def sparkline(values, width: int | None = None) -> str:
    """Unicode sparkline of a value sequence. ``width`` caps the
    output by averaging values into that many buckets; an all-equal
    (or single-value) series renders as mid-blocks so "flat" and "no
    data" (empty string) stay visually distinct."""
    vals = [float(v) for v in values if v is not None]
    if not vals:
        return ""
    if width is not None and width > 0 and len(vals) > width:
        per = len(vals) / width
        vals = [sum(vals[int(i * per):max(int((i + 1) * per),
                                          int(i * per) + 1)])
                / max(int((i + 1) * per) - int(i * per), 1)
                for i in range(width)]
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _SPARK_CHARS[3] * len(vals)
    return "".join(
        _SPARK_CHARS[min(int((v - lo) / span * 8), 7)] for v in vals)


# ---------------------------------------------------------------------------
# Early-warning detectors.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class DetectorSpec:
    """One parsed detector: ``metric OP threshold @ window_s``."""

    kind: str                       # "slope" | "step"
    metric: str
    op: str                         # ">" | "<"
    threshold: float
    window_s: float = DEFAULT_DETECTOR_WINDOW_S

    def __post_init__(self):
        if self.kind not in ("slope", "step"):
            raise ValueError(f"detector kind must be slope/step: "
                             f"{self.kind!r}")
        if self.op not in (">", "<"):
            raise ValueError(f"detector op must be > or <: {self.op!r}")
        if self.window_s <= 0:
            raise ValueError(
                f"detector window must be positive: {self.window_s}")


def parse_detectors(spec: str, kind: str) -> list:
    """Parse a ``;``-separated env spec (``TDT_HISTORY_SLOPE`` /
    ``TDT_HISTORY_STEP``) into :class:`DetectorSpec` rows. Each entry
    is ``<metric><op><threshold>[@<window_s>]`` — e.g.
    ``serving.queue_depth>0.5@30`` ("queue depth climbing faster than
    0.5/s sustained over 30 s" for the slope kind; "recent half-window
    average 0.5 above the earlier half" for the step kind)."""
    out = []
    for entry in (spec or "").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        op = ">" if ">" in entry else ("<" if "<" in entry else None)
        if op is None:
            raise ValueError(
                f"detector spec needs > or <: {entry!r} "
                f"(want metric>threshold[@window_s])")
        metric, _, rest = entry.partition(op)
        thr_s, _, win_s = rest.partition("@")
        metric = metric.strip()
        if not metric or not thr_s.strip():
            raise ValueError(f"malformed detector spec: {entry!r}")
        try:
            thr = float(thr_s)
            win = float(win_s) if win_s.strip() \
                else DEFAULT_DETECTOR_WINDOW_S
        except ValueError:
            raise ValueError(
                f"malformed detector numbers in: {entry!r}") from None
        out.append(DetectorSpec(kind, metric, op, thr, win))
    return out


class _Detector:
    """Latch wrapper shared by both detector kinds: the condition is a
    pure function of the trailing window, the latch makes a sustained
    excursion fire exactly ONCE — :meth:`check` returns details only
    on the clear → firing transition and re-arms when the condition
    clears again."""

    kind = "?"

    def __init__(self, spec: DetectorSpec):
        self.spec = spec
        self.fired = False

    def evaluate(self, points: list, now: float) -> dict | None:
        raise NotImplementedError

    def check(self, points: list, now: float) -> dict | None:
        details = self.evaluate(points, now)
        if details is None:
            self.fired = False
            return None
        if self.fired:
            return None
        self.fired = True
        return details

    def _base(self) -> dict:
        return {"detector": self.kind, "metric": self.spec.metric,
                "op": self.spec.op, "threshold": self.spec.threshold,
                "window_s": self.spec.window_s}


class SustainedSlope(_Detector):
    """Fires when the least-squares slope over the trailing window
    crosses the threshold (per second) AND the window is at least
    half covered — two points at the start of a ramp are a blip, not
    a sustained trend."""

    kind = "slope"

    def evaluate(self, points: list, now: float) -> dict | None:
        if len(points) < 3:
            return None
        if points[-1][0] - points[0][0] < 0.5 * self.spec.window_s:
            return None
        s = slope(points)
        if s is None:
            return None
        hit = s > self.spec.threshold if self.spec.op == ">" \
            else s < self.spec.threshold
        if not hit:
            return None
        d = self._base()
        d["slope_per_s"] = round(s, 6)
        d["last"] = points[-1][1]
        return d


class StepChange(_Detector):
    """Fires when the recent half-window average jumped past the
    earlier half's by more than the threshold — the level-shift
    detector (a deploy, a traffic step) that a slope fit smears out.
    Needs points in BOTH halves, so a series that appears mid-window
    cannot instant-fire on its first samples."""

    kind = "step"

    def evaluate(self, points: list, now: float) -> dict | None:
        if len(points) < 4:
            return None
        if points[-1][0] - points[0][0] < 0.5 * self.spec.window_s:
            return None
        mid = now - 0.5 * self.spec.window_s
        early = [v for t, v in points if t <= mid]
        late = [v for t, v in points if t > mid]
        if not early or not late:
            return None
        delta = sum(late) / len(late) - sum(early) / len(early)
        hit = delta > self.spec.threshold if self.spec.op == ">" \
            else delta < self.spec.threshold
        if not hit:
            return None
        d = self._base()
        d["delta"] = round(delta, 6)
        d["last"] = points[-1][1]
        return d


_DETECTOR_KINDS = {"slope": SustainedSlope, "step": StepChange}


def make_detector(spec: DetectorSpec) -> _Detector:
    return _DETECTOR_KINDS[spec.kind](spec)


# ---------------------------------------------------------------------------
# The sampler.
# ---------------------------------------------------------------------------

class HistorySampler:
    """Background sampler feeding a :class:`SeriesStore` from the
    lock-free registry peeks, plus the detector pass.

    Construction follows ``obs.devprof.PumpSampler``'s idiom: the
    Scheduler builds one via :meth:`from_env` (None unless
    ``TDT_HISTORY=1``) and closes it when the pump exits. Tests pass
    ``thread=False`` and drive :meth:`sample_once` with explicit
    timestamps — every condition is then deterministic, no sleeping.

    A live sampler registers :meth:`dump_payload` as ``obs.flight``'s
    history provider, so every flight dump — a breach, a watchdog
    trip, one of THIS module's warnings — carries the trailing
    ``TDT_HISTORY_DUMP_S`` seconds of series alongside the event ring.
    """

    def __init__(self, registry=None, store: SeriesStore | None = None,
                 tick_s: float | None = None, maxlen: int | None = None,
                 detectors=None, clock=time.perf_counter,
                 thread: bool = True, install_flight_provider=True):
        self.tick_s = tick_s if tick_s is not None else history_tick_s()
        if self.tick_s <= 0:
            raise ValueError(f"tick_s must be positive: {self.tick_s}")
        self.store = store if store is not None \
            else SeriesStore(maxlen=maxlen)
        self.detectors = list(detectors or [])
        self._registry = registry
        self._clock = clock
        self._prev_counters: dict[str, float] = {}
        self._prev_t: float | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._installed_provider = False
        if install_flight_provider:
            _flight.set_history_provider(self.dump_payload)
            self._installed_provider = True
        if thread:
            self._thread = threading.Thread(
                target=self._run, name="tdt-history", daemon=True)
            self._thread.start()

    @classmethod
    def from_env(cls, registry=None) -> "HistorySampler | None":
        """The Scheduler's constructor path: None unless
        ``TDT_HISTORY=1`` (the no-sampler-no-cost contract), else a
        running sampler with the env cadence/length and any
        ``TDT_HISTORY_SLOPE`` / ``TDT_HISTORY_STEP`` detectors."""
        if not history_enabled():
            return None
        dets = [make_detector(s) for s in
                parse_detectors(os.environ.get("TDT_HISTORY_SLOPE", ""),
                                "slope")
                + parse_detectors(os.environ.get("TDT_HISTORY_STEP", ""),
                                  "step")]
        return cls(registry=registry, detectors=dets)

    # -- sampling ----------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.wait(self.tick_s):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 — sampling must never kill serving
                try:
                    self._reg().counter("history.sample_errors").inc()
                except Exception:  # noqa: BLE001 — best-effort bookkeeping
                    pass

    def _reg(self):
        return (self._registry if self._registry is not None
                else _registry.get_registry())

    def sample_once(self, now: float | None = None) -> None:
        """One tick: peek every gauge (stored as value) and counter
        (stored as per-second rate vs the previous tick), then run the
        detector pass. ``now`` is injectable for tests."""
        from triton_dist_tpu.obs.fleet import peek_counters, peek_gauges
        now = self._clock() if now is None else float(now)
        reg = self._reg()
        for name, v in peek_gauges(reg).items():
            self.store.record(name, now, float(v))
        prev_t = self._prev_t
        for name, v in peek_counters(reg).items():
            v = float(v)
            p = self._prev_counters.get(name)
            if p is not None and prev_t is not None and now > prev_t:
                self.store.record(name, now, (v - p) / (now - prev_t))
            self._prev_counters[name] = v
        self._prev_t = now
        reg.counter("history.ticks").inc()
        reg.gauge("history.series").set(len(self.store))
        for det in self.detectors:
            s = self.store.get(det.spec.metric)
            pts = s.points(last_s=det.spec.window_s, now=now) \
                if s is not None else []
            details = det.check(pts, now)
            if details is not None:
                self._fire(det, details, now)

    def _fire(self, det: _Detector, details: dict, now: float) -> None:
        details = dict(details)
        details["t"] = round(now, 3)
        self.store.add_warning(details)
        reg = self._reg()
        reg.counter("history.warnings").inc()
        reg.counter(f"history.warning.{det.kind}").inc()
        _trace.instant("history.warning", "history", args=details)
        # maybe_dump (not devprof.arm directly): the dump carries the
        # attached series AND arms the breach-gated device profiler —
        # the full pre-mortem, rate-limited per reason.
        _flight.maybe_dump(f"history_{det.kind}_{det.spec.metric}")

    # -- reads / lifecycle -------------------------------------------------
    def snapshot(self, last_s: float | None = None, series=None,
                 max_points: int | None = None) -> dict:
        """The ``{"cmd": "history"}`` payload: the store snapshot plus
        the sampler cadence."""
        snap = self.store.snapshot(last_s=last_s, series=series,
                                   max_points=max_points)
        snap["tick_s"] = self.tick_s
        return snap

    def dump_payload(self) -> dict:
        """What a flight dump embeds: the trailing
        ``TDT_HISTORY_DUMP_S`` seconds, untrimmed point counts."""
        return self.snapshot(last_s=history_dump_s())

    def close(self) -> None:
        """Stop the thread and (if ours) uninstall the flight
        provider. Idempotent; never raises past a join timeout — the
        pump's teardown path calls this."""
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout=2.0)
        if self._installed_provider \
                and _flight.history_provider() == self.dump_payload:
            _flight.set_history_provider(None)
            self._installed_provider = False
