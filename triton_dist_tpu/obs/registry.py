"""Process-local metrics registry: counters, gauges, histograms, spans.

The reference ships tracing as its only observability surface
(``group_profile`` per-rank chrome traces, ``launch_metadata`` kernel
annotations — python/triton_dist/utils.py:505-592); answering "what is
the engine doing right now" requires attaching a profiler. This module
adds the counting substrate underneath: a process-local registry of
counters / gauges / fixed-bucket latency histograms that the engine,
server, and collective wrappers record into, snapshot-able to a plain
JSON-able dict (``snapshot``) and mergeable across hosts
(``obs.exposition.merge_snapshots`` — the rank-0 ``gather_object``
merge of the reference, collapsed to dict arithmetic).

Zero overhead by default: the module-level registry starts as the
:class:`NullRegistry`, whose metrics are shared no-op singletons and
whose spans skip the clock entirely — instrumented hot paths (the
engine decode loop) pay a couple of attribute lookups per *serve call*,
not per token, until :func:`enable` swaps in a real :class:`Registry`.

Semantics under ``jax.jit``: instrumentation runs in PYTHON, so a
counter inside a jitted function increments at trace time — once per
compilation, not per execution. Collective wrappers therefore count
*dispatched program builds* (like the reference's per-launch
``launch_metadata``), while the engine counts real wall-clock events
because its loop drives the jitted step from Python.
"""

from __future__ import annotations

import bisect
import os
import threading
import time
import warnings

from triton_dist_tpu.obs import trace as _trace

__all__ = [
    "DEFAULT_MS_BUCKETS", "Counter", "Gauge", "Histogram", "Registry",
    "NullRegistry", "enable", "disable", "enabled", "env_int",
    "get_registry", "set_registry", "counter", "gauge", "histogram",
    "scoped_registry", "snapshot", "reset", "span", "record_comm",
]

def env_int(name: str, default: int, minimum: int | None = None) -> int:
    """Validated integer env knob — the one parser the obs modules
    share (perfwatch / attrib; the ring/breaker knobs predate it)."""
    v = os.environ.get(name, "").strip()
    if not v:
        return default
    try:
        n = int(v)
    except ValueError:
        raise ValueError(f"{name} must be an integer: {v!r}") from None
    if minimum is not None and n < minimum:
        raise ValueError(f"{name} must be >= {minimum}: {n}")
    return n


#: Default latency buckets (milliseconds): sub-ms jit dispatch up to
#: multi-second prefills. Upper bounds; an implicit +Inf bucket catches
#: the tail.
DEFAULT_MS_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                      100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0,
                      10000.0)


class Counter:
    """Monotonically increasing count (Prometheus counter semantics)."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name}: inc({amount}) < 0")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Point-in-time value (can go up and down)."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Fixed-bucket histogram with sum/count/min/max.

    ``buckets`` are inclusive upper bounds; observations above the last
    bound land in the implicit +Inf bucket (``counts`` has
    ``len(buckets) + 1`` entries). Bucket *layout is fixed at creation*
    so per-host snapshots merge by plain elementwise addition.
    """

    __slots__ = ("name", "buckets", "_counts", "_sum", "_count", "_min",
                 "_max", "_lock")

    def __init__(self, name: str, lock: threading.Lock,
                 buckets=DEFAULT_MS_BUCKETS):
        if not buckets or list(buckets) != sorted(buckets):
            raise ValueError(
                f"histogram {name}: buckets must be ascending, non-empty")
        self.name = name
        self.buckets = tuple(float(b) for b in buckets)
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0
        self._min = None
        self._max = None
        self._lock = lock

    def observe(self, value: float) -> None:
        value = float(value)
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1
            if self._min is None or value < self._min:
                self._min = value
            if self._max is None or value > self._max:
                self._max = value

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def to_dict(self) -> dict:
        return {"buckets": list(self.buckets),
                "counts": list(self._counts),
                "sum": self._sum, "count": self._count,
                "min": self._min, "max": self._max}


class Registry:
    """Thread-safe store of named metrics.

    One lock serves both metric creation and updates: telemetry is
    opt-in and its hot operations (a float add under the GIL + lock)
    cost tens of nanoseconds — far below the jit-dispatch floor of the
    paths it instruments.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}

    def _check_free(self, name: str, kind: dict) -> None:
        for store in (self._counters, self._gauges, self._histograms):
            if store is not kind and name in store:
                raise ValueError(
                    f"metric {name!r} already registered as a different "
                    f"type")

    def counter(self, name: str) -> Counter:
        with self._lock:
            m = self._counters.get(name)
            if m is None:
                self._check_free(name, self._counters)
                m = self._counters[name] = Counter(name, self._lock)
        return m

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            m = self._gauges.get(name)
            if m is None:
                self._check_free(name, self._gauges)
                m = self._gauges[name] = Gauge(name, self._lock)
        return m

    def histogram(self, name: str,
                  buckets=DEFAULT_MS_BUCKETS) -> Histogram:
        with self._lock:
            m = self._histograms.get(name)
            if m is None:
                self._check_free(name, self._histograms)
                m = self._histograms[name] = Histogram(
                    name, self._lock, buckets)
        return m

    def snapshot(self) -> dict:
        """Plain JSON-able dict of every metric's current value."""
        with self._lock:
            return {
                "counters": {k: c._value
                             for k, c in self._counters.items()},
                "gauges": {k: g._value for k, g in self._gauges.items()},
                "histograms": {k: h.to_dict()
                               for k, h in self._histograms.items()},
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


class _NullMetric:
    """Shared no-op stand-in for every metric type."""

    __slots__ = ()
    name = "<null>"
    value = 0.0
    count = 0
    sum = 0.0

    def inc(self, amount: float = 1.0) -> None:
        pass

    def dec(self, amount: float = 1.0) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def observe(self, value: float) -> None:
        pass

    def to_dict(self) -> dict:
        return {}


_NULL_METRIC = _NullMetric()


class NullRegistry:
    """The disabled-telemetry registry: every lookup returns the shared
    no-op metric, snapshots are empty. This is the DEFAULT — hot paths
    instrumented against it pay attribute lookups only."""

    def counter(self, name: str) -> _NullMetric:
        return _NULL_METRIC

    def gauge(self, name: str) -> _NullMetric:
        return _NULL_METRIC

    def histogram(self, name: str, buckets=None) -> _NullMetric:
        return _NULL_METRIC

    def snapshot(self) -> dict:
        return {"counters": {}, "gauges": {}, "histograms": {}}

    def reset(self) -> None:
        pass


_NULL_REGISTRY = NullRegistry()
_REGISTRY = _NULL_REGISTRY

#: Thread-scoped registry overrides (``scoped_registry``). ``_SCOPED``
#: is a monotonic fast-path guard: until the FIRST scope is installed
#: anywhere in the process, every emission resolves the registry with
#: one module-global read — the zero-overhead-when-unused contract.
#: Once a process runs replica-scoped servers (ISSUE 14) each emission
#: additionally pays one ``threading.local`` attribute lookup.
_TLS = threading.local()
_SCOPED = False


def _current():
    if _SCOPED:
        reg = getattr(_TLS, "registry", None)
        if reg is not None:
            return reg
    return _REGISTRY


class scoped_registry:
    """Route THIS thread's module-level metric emissions
    (``obs.counter``/``gauge``/``histogram``/``span``/``snapshot``)
    into ``registry`` for the duration of the ``with`` block.

    This is how several ``ModelServer`` replicas coexist in one
    process without aliasing each other's serving metrics
    (docs/observability.md "Fleet view"): each replica's handler
    threads and scheduler pump wrap their work in its private
    registry, so per-replica snapshots stay distinct and the fleet
    merge's counter sums are correct. ``registry=None`` is a no-op
    (the global registry keeps receiving), so call sites need no
    branching. Re-entrant per thread (the previous scope is restored
    on exit); scopes never leak across threads."""

    __slots__ = ("_registry", "_prev", "_installed")

    def __init__(self, registry):
        self._registry = registry
        self._installed = False

    def __enter__(self):
        global _SCOPED
        if self._registry is not None:
            self._prev = getattr(_TLS, "registry", None)
            _TLS.registry = self._registry
            _SCOPED = True
            self._installed = True
        return self._registry

    def __exit__(self, *exc):
        if self._installed:
            _TLS.registry = self._prev
            self._installed = False
        return False


def get_registry():
    return _REGISTRY


def set_registry(registry) -> None:
    global _REGISTRY
    _REGISTRY = registry


def enable(registry: Registry | None = None) -> Registry:
    """Switch telemetry on. Idempotent: an already-active real registry
    is kept (so a second subsystem enabling telemetry does not wipe the
    first's counts); pass ``registry`` to replace it explicitly.

    ``TDT_TRACE=1`` makes this also switch event tracing on
    (``obs.trace``), so bench/smoke runs that enable metrics get the
    timeline for free."""
    global _REGISTRY
    if registry is not None:
        _REGISTRY = registry
    elif _REGISTRY is _NULL_REGISTRY:
        _REGISTRY = Registry()
    if _trace.env_enabled() and not _trace.enabled():
        _trace.enable()
    return _REGISTRY


def disable() -> None:
    """Back to the zero-overhead no-op registry (counts are dropped)."""
    global _REGISTRY
    _REGISTRY = _NULL_REGISTRY


def enabled() -> bool:
    return _REGISTRY is not _NULL_REGISTRY


def counter(name: str):
    return _current().counter(name)


def gauge(name: str):
    return _current().gauge(name)


def histogram(name: str, buckets=DEFAULT_MS_BUCKETS):
    return _current().histogram(name, buckets)


def snapshot() -> dict:
    return _current().snapshot()


def reset() -> None:
    _current().reset()


# ---------------------------------------------------------------------------
# Spans: wall-clock regions that land in a histogram AND in xprof.
# ---------------------------------------------------------------------------

class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


#: Category a span's trace events land under, by name prefix
#: (docs/observability.md "Tracing"): the part before the first dot.
_CAT_BY_PREFIX = {"engine": "engine", "server": "serving",
                  "serving": "serving", "comms": "comms",
                  "resilience": "resilience"}

_ANNOTATE_WARNED = False


def _enter_annotate(name: str):
    """Entered ``tools.profiler.annotate(name)`` context, or None when
    the xprof side is unavailable (no jax profiler in this
    environment). The span docstring promises composition with xprof —
    an import/construction failure must not be pure silence, so the
    first one warns and every one counts into
    ``obs.span.annotate_unavailable``; histograms (and trace events)
    keep recording either way."""
    global _ANNOTATE_WARNED
    try:
        from triton_dist_tpu.tools.profiler import annotate
        cm = annotate(name)
        cm.__enter__()
        return cm
    except Exception as e:  # noqa: BLE001 — degrade, never break the span
        _current().counter("obs.span.annotate_unavailable").inc()
        if not _ANNOTATE_WARNED:
            _ANNOTATE_WARNED = True
            warnings.warn(
                f"obs.span: xprof annotate unavailable "
                f"({type(e).__name__}: {e}) — spans record histograms "
                f"and trace events only", RuntimeWarning, stacklevel=4)
        return None


class _Span:
    """Times the enclosed region into ``<name>_ms``, wraps it in
    ``tools.profiler.annotate(name)`` so the SAME label shows up as a
    named region in an xprof trace when one is being collected, and —
    when event tracing is on (``obs.trace``) — emits a begin/end pair
    so the region lands on the Perfetto timeline under the thread's
    current trace ID. B/E (not one complete event) on purpose: a hang
    inside the span leaves the un-ended begin in the flight record."""

    __slots__ = ("_hist", "_name", "_cat", "_args", "_t0", "_ann",
                 "_traced")

    def __init__(self, hist, name: str, cat: str | None = None,
                 args: dict | None = None):
        self._hist = hist
        self._name = name
        self._cat = cat or _CAT_BY_PREFIX.get(
            name.split(".", 1)[0], "op")
        self._args = args
        self._ann = None

    def __enter__(self):
        self._ann = _enter_annotate(self._name)
        self._traced = _trace.enabled()
        if self._traced:
            _trace.begin(self._name, self._cat, args=self._args)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt_ms = (time.perf_counter() - self._t0) * 1e3
        if self._traced:
            _trace.end(self._name, self._cat)
        ann, self._ann = self._ann, None
        try:
            return ann.__exit__(*exc) if ann is not None else False
        finally:
            self._hist.observe(dt_ms)


def span(name: str, buckets=DEFAULT_MS_BUCKETS, cat: str | None = None,
         args: dict | None = None):
    """Context manager timing a region into histogram ``<name>_ms``
    (and onto the event timeline when tracing is enabled; ``cat``
    overrides the prefix-derived category, ``args`` attach to the
    begin event).

    Disabled telemetry AND disabled tracing return a shared no-op (no
    clock read, no annotation) — the form the engine decode loop
    relies on for its zero-overhead-when-disabled contract. With only
    tracing on, the histogram side records into the no-op registry."""
    reg = _current()
    if reg is _NULL_REGISTRY and not _trace.enabled():
        return _NULL_SPAN
    return _Span(reg.histogram(name + "_ms", buckets), name, cat, args)


def record_comm(op: str, *arrays) -> None:
    """Count one collective-wrapper invocation: ``comms.<op>.calls`` +=
    1 and ``comms.<op>.bytes`` += the summed byte size of ``arrays``
    (the global payload handed to the op).

    Called from the ops-layer functional entries (all_gather,
    reduce_scatter, all_reduce, fast_all_to_all, ag_gemm, gemm_rs,
    gemm_ar). Under ``jax.jit`` these run at trace time, so the counts
    are per program BUILD, not per device launch — see the module
    docstring. Shapes are static, so tracers report sizes fine.

    With event tracing on, the dispatch also lands on the timeline as
    an instant event (category ``op``) carrying the op name and byte
    count — the hook that puts every op entry a request touches onto
    that request's trace-ID track."""
    reg = _current()
    tracing = _trace.enabled()
    if reg is _NULL_REGISTRY and not tracing:
        return
    nbytes = 0
    for a in arrays:
        size = getattr(a, "size", None)
        dtype = getattr(a, "dtype", None)
        if size is not None and dtype is not None:
            try:
                nbytes += int(size) * dtype.itemsize
            except (TypeError, AttributeError):
                pass
    reg.counter(f"comms.{op}.calls").inc()
    reg.counter(f"comms.{op}.bytes").inc(nbytes)
    if tracing:
        _trace.instant(f"comms.{op}", "op",
                       args={"op": op, "bytes": nbytes})
