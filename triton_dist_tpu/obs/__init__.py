"""Telemetry subsystem: metrics registry + spans + exposition.

The missing fourth observability leg next to ``tools/profiler``'s
traces: process-local counters / gauges / fixed-bucket histograms
(``obs.registry``), wall-clock spans that land in both a histogram and
the xprof trace (``obs.span``), per-host snapshot merge mirroring the
reference's rank-0 ``gather_object`` trace merge, and a Prometheus
text exposition path served over the ModelServer protocol
(``obs.exposition``). Disabled by default at zero hot-path cost; flip
on with ``obs.enable()`` (the ModelServer does this at construction).

See docs/observability.md for the metric name catalog.
"""

from triton_dist_tpu.obs.registry import (  # noqa: F401
    DEFAULT_MS_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    NullRegistry,
    Registry,
    counter,
    disable,
    enable,
    enabled,
    gauge,
    get_registry,
    histogram,
    record_comm,
    reset,
    set_registry,
    snapshot,
    span,
)
from triton_dist_tpu.obs.exposition import (  # noqa: F401
    aggregate_across_hosts,
    merge_snapshots,
    render_prometheus,
)
