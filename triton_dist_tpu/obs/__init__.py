"""Telemetry subsystem: metrics, spans, event tracing, exposition.

Process-local counters / gauges / fixed-bucket histograms
(``obs.registry``), wall-clock spans that land in a histogram, the
xprof trace, AND the structured event timeline (``obs.span``),
per-host snapshot merge mirroring the reference's rank-0
``gather_object`` trace merge, and a Prometheus text exposition path
served over the ModelServer protocol (``obs.exposition``).

The timeline side (``obs.trace``) records begin/end + instant events
into per-thread ring buffers, exports Chrome trace-event / Perfetto
JSON through ``tools/trace_export.py``, and doubles as a flight
recorder (``obs.flight``): the most recent event window dumps to disk
on watchdog trips, breaker opens, serve-loop failures, SIGTERM, or an
explicit ``{"cmd": "dump_trace"}``.

The serving SLO observatory (ISSUE 8) sits on top: ``obs.slo`` keeps
rolling-window percentiles + multi-window burn rates that arm the
flight recorder on a latency-SLO breach, ``obs.perfwatch`` keeps live
fused-vs-XLA wall-time medians the resilience router consults before
its static BASELINE floors, and ``obs.attrib`` keeps per-request
latency waterfalls (queue → prefill → decode) the server returns
inline and ``tools/top.py`` renders live.

The fleet plane (ISSUE 14, ``obs.fleet``) lifts all of it across N
replicas: per-replica ``ReplicaHealth`` snapshots behind the server's
cheap ``{"cmd": "health"}`` verb, a ``FleetView`` aggregator that
scrapes endpoints concurrently, tracks staleness (live → stale →
down), and merges snapshots correctly by metric kind, plus the
``placement_score`` the multi-replica router will consume
(docs/observability.md "Fleet view"). Several replicas in one process
keep distinct metrics via ``obs.scoped_registry``.

The history plane (ISSUE 16, ``obs.history``) retains what everything
above only reads point-in-time: an opt-in sampler (``TDT_HISTORY=1``)
records every gauge (value) and counter (rate) into ring-buffered
series behind the server's ``{"cmd": "history"}`` verb, pure trend
math (``slope`` / ``ema`` / ``eta_to``) forecasts crossings, and
early-warning detectors arm the flight recorder BEFORE the SLO breach
— with the trailing series embedded in every dump as Perfetto counter
tracks (docs/observability.md "History plane").

Disabled by default at zero hot-path cost; flip metrics on with
``obs.enable()`` (the ModelServer does this at construction;
``TDT_TRACE=1`` makes that enable tracing too).

See docs/observability.md for the metric name catalog and event
schema.
"""

from triton_dist_tpu.obs.registry import (  # noqa: F401
    DEFAULT_MS_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    NullRegistry,
    Registry,
    counter,
    disable,
    enable,
    enabled,
    env_int,
    gauge,
    get_registry,
    histogram,
    record_comm,
    reset,
    scoped_registry,
    set_registry,
    snapshot,
    span,
)
from triton_dist_tpu.obs.exposition import (  # noqa: F401
    aggregate_across_hosts,
    histogram_quantile,
    merge_snapshots,
    render_prometheus,
)
from triton_dist_tpu.obs import (  # noqa: F401
    attrib, devprof, fleet, flight, history, perfwatch, slo, trace)
from triton_dist_tpu.obs.slo import (  # noqa: F401
    SLOTarget,
    SLOTracker,
    WindowedHistogram,
)
from triton_dist_tpu.obs.trace import (  # noqa: F401
    enabled as trace_enabled,
)
