"""Snapshot merging and metrics exposition.

``merge_snapshots`` is the cross-host aggregation primitive: the
reference gathers per-rank chrome traces with ``gather_object`` and
merges JSON on rank 0 (utils.py:505-592); here the artifact is a plain
metrics dict, so the merge is arithmetic — counters and histogram
buckets sum, gauges take the max (they are point-in-time readings; max
answers the capacity questions gauges exist for, e.g. peak in-flight).

``render_prometheus`` turns a snapshot into Prometheus text exposition
format (v0.0.4) so any scraper pointed at the serving host — via the
server's ``{"cmd": "metrics", "format": "prometheus"}`` request — can
ingest the numbers without a client library.
"""

from __future__ import annotations

import json
import re

from triton_dist_tpu.obs import registry as _registry

__all__ = ["allgather_json", "histogram_quantile", "merge_snapshots",
           "render_prometheus", "aggregate_across_hosts"]


def histogram_quantile(h: dict, q: float, detail: bool = False):
    """Estimate the ``q``-quantile of a snapshot histogram dict
    (fixed upper-bound ``buckets`` + per-bucket ``counts`` — the shape
    :meth:`Histogram.to_dict` emits) by linear interpolation inside
    the containing bucket. A quantile landing in the +Inf overflow
    bucket reports the recorded ``max`` when the snapshot carries one,
    and otherwise CLIPS to the top finite bucket edge — windowed
    histogram deltas (bench.py) and rolling windows (``obs.slo``)
    cannot know their extrema, and "at least the top edge" is a usable
    lower bound where ``None`` used to hide the whole percentile.
    ``detail=True`` returns ``(value, clipped)`` so callers can flag
    the clip. ``None`` (or ``(None, False)``) only on an empty or
    malformed histogram. This is how bench.py turns the server's
    ``serving.ttft_ms`` histogram into p50/p99 without shipping raw
    samples."""
    value, clipped = None, False
    counts = h.get("counts") or []
    buckets = h.get("buckets") or []
    total = h.get("count", 0)
    if total and counts:
        target = q * total
        cum = 0
        lo = 0.0
        in_overflow = True
        for i, c in enumerate(counts):
            cum += c
            if cum >= target and c:
                if i < len(buckets):
                    hi = buckets[i]
                    frac = (target - (cum - c)) / c
                    value = lo + (hi - lo) * frac
                    in_overflow = False
                break
            if i < len(buckets):
                lo = buckets[i]
        if in_overflow and buckets:
            if h.get("max") is not None:
                value = float(h["max"])
            else:
                value, clipped = float(buckets[-1]), True
    return (value, clipped) if detail else value


def allgather_json(obj) -> list:
    """Every host's ``obj`` (any JSON-able value), as a list indexed
    by process — the ``gather_object`` analog: JSON bytes through a
    byte-padded ``process_allgather``, decoded per rank. Every rank
    returns the same list; single-process returns ``[obj]``. Shared by
    the metrics merge below and the chrome-trace merge
    (``tools.trace_export.gather_to_chrome``)."""
    import jax
    if jax.process_count() == 1:
        return [obj]
    import numpy as np
    from jax.experimental import multihost_utils
    data = np.frombuffer(json.dumps(obj).encode(), np.uint8)
    sizes = np.asarray(multihost_utils.process_allgather(
        np.array([data.size], np.int64))).reshape(-1)
    padded = np.zeros(int(sizes.max()), np.uint8)
    padded[:data.size] = data
    gathered = np.asarray(multihost_utils.process_allgather(padded))
    gathered = gathered.reshape(len(sizes), -1)
    return [json.loads(bytes(gathered[i, :int(sizes[i])]).decode())
            for i in range(len(sizes))]


def merge_snapshots(snaps) -> dict:
    """Merge per-host snapshot dicts into one (rank-0 aggregation).

    Counters and histogram (counts, sum, count) add; gauges take the
    max across hosts; histogram min/max combine. Histograms must share
    bucket layouts (they do by construction — layouts are fixed at
    metric creation); a mismatch raises ``ValueError``.
    """
    snaps = [s for s in snaps if s]
    out = {"counters": {}, "gauges": {}, "histograms": {}}
    for s in snaps:
        for k, v in s.get("counters", {}).items():
            out["counters"][k] = out["counters"].get(k, 0.0) + v
        for k, v in s.get("gauges", {}).items():
            out["gauges"][k] = (v if k not in out["gauges"]
                                else max(out["gauges"][k], v))
        for k, h in s.get("histograms", {}).items():
            if k not in out["histograms"]:
                out["histograms"][k] = {
                    "buckets": list(h["buckets"]),
                    "counts": list(h["counts"]),
                    "sum": h["sum"], "count": h["count"],
                    "min": h.get("min"), "max": h.get("max")}
                continue
            acc = out["histograms"][k]
            if list(h["buckets"]) != acc["buckets"]:
                raise ValueError(
                    f"histogram {k!r}: bucket layouts differ across "
                    f"hosts — {acc['buckets']} vs {list(h['buckets'])}")
            acc["counts"] = [a + b
                             for a, b in zip(acc["counts"], h["counts"])]
            acc["sum"] += h["sum"]
            acc["count"] += h["count"]
            for key, pick in (("min", min), ("max", max)):
                vals = [v for v in (acc.get(key), h.get(key))
                        if v is not None]
                acc[key] = pick(vals) if vals else None
    return out


_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str, prefix: str) -> str:
    n = _NAME_RE.sub("_", name)
    if prefix:
        n = f"{prefix}_{n}"
    if n[:1].isdigit():
        n = "_" + n
    return n


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def render_prometheus(snap: dict | None = None,
                      prefix: str = "tdt") -> str:
    """Render a snapshot (default: the active registry's) as Prometheus
    text exposition. Counters get the ``_total`` suffix; histogram
    buckets are emitted CUMULATIVE with ``le`` labels plus the
    ``_sum`` / ``_count`` series, per the format spec."""
    if snap is None:
        snap = _registry.snapshot()
    lines = []
    for name in sorted(snap.get("counters", {})):
        pn = _prom_name(name, prefix) + "_total"
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {_fmt(snap['counters'][name])}")
    for name in sorted(snap.get("gauges", {})):
        pn = _prom_name(name, prefix)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {_fmt(snap['gauges'][name])}")
    for name in sorted(snap.get("histograms", {})):
        h = snap["histograms"][name]
        pn = _prom_name(name, prefix)
        lines.append(f"# TYPE {pn} histogram")
        cum = 0
        for ub, c in zip(h["buckets"], h["counts"]):
            cum += c
            lines.append(f'{pn}_bucket{{le="{_fmt(ub)}"}} {cum}')
        cum += h["counts"][len(h["buckets"])]
        lines.append(f'{pn}_bucket{{le="+Inf"}} {cum}')
        lines.append(f"{pn}_sum {_fmt(h['sum'])}")
        lines.append(f"{pn}_count {h['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


def aggregate_across_hosts(snap: dict | None = None) -> dict:
    """Gather every host's snapshot and return the merged dict
    (meaningful on rank 0; every rank returns the same merge).

    The multi-host transport mirrors the reference's ``gather_object``:
    each host contributes its JSON-encoded snapshot as a padded uint8
    array through ``process_allgather``, rank 0's merge being plain
    ``merge_snapshots``. Single-process (the CPU tier-1 mesh) returns
    the local snapshot unchanged.
    """
    if snap is None:
        snap = _registry.snapshot()
    return merge_snapshots(allgather_json(snap))
