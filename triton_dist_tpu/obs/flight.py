"""Flight recorder: dump the last N seconds of trace events on failure.

PR 3's resilience layer records *that* a config hung (known-bad cache,
breaker opens) but not *what the process was doing* when it did. The
flight recorder closes that gap: tracing's per-thread ring buffers
(``obs.trace``) already hold the most recent event window at all times
— bounded, overwrite-oldest — and this module dumps that window to
disk as a Chrome trace-event / Perfetto JSON file whenever something
goes wrong:

- a compile-watchdog trip (``resilience.router``),
- a circuit breaker opening (``resilience.breaker``),
- an unhandled serve-loop exception (``serving.server``),
- ``SIGTERM`` (:func:`install_signal_handlers`),
- an explicit ``{"cmd": "dump_trace"}`` server request.

Knobs (docs/observability.md): ``TDT_FLIGHT_SECONDS`` — the window
length (default 30 s); ``TDT_TRACE_DIR`` — where dumps land (default
``<tmp>/tdt_trace``). Each dump increments the
``resilience.flight_dumps`` counter and records its path for
``obs.trace.stats()`` / ``tools/report.py``'s Tracing section.

Dumps are best-effort by construction: every trigger sits on a failure
path, so :func:`maybe_dump` never raises and rate-limits per reason
(a breaker flapping open must not write a dump per request).
"""

from __future__ import annotations

import json
import os
import signal
import tempfile
import threading
import time

from triton_dist_tpu.obs import registry as _registry
from triton_dist_tpu.obs import trace as _trace

__all__ = ["dump", "flight_seconds", "history_provider",
           "install_signal_handlers", "last_record", "maybe_dump",
           "replica_id", "reset", "set_history_provider",
           "set_replica_id", "trace_dir"]

DEFAULT_FLIGHT_SECONDS = 30.0

#: Minimum spacing between dumps of the SAME reason (maybe_dump).
MIN_INTERVAL_S = 1.0

_LOCK = threading.Lock()
_LAST: dict | None = None           # {"path", "reason", "ts", "count"}
_COUNT = 0
_SEQ = 0                            # filename uniquifier (same-ms dumps)
_LAST_BY_REASON: dict[str, float] = {}
_SIGTERM_INSTALLED = False
_REPLICA_ID: str | None = None
_HISTORY_PROVIDER = None      # () -> obs.history snapshot dict, or None


def set_history_provider(fn) -> None:
    """Install the zero-arg callable whose return value (an
    ``obs.history`` snapshot dict — the trailing ``TDT_HISTORY_DUMP_S``
    seconds of sampled series) every later dump embeds under
    ``metadata.history`` (ISSUE 16): a breach dump then shows the
    LEAD-UP, not just the instant, and ``trace_export.to_chrome``
    renders the series as Perfetto counter tracks next to the event
    timeline. A live ``obs.history.HistorySampler`` installs its own
    ``dump_payload`` here and uninstalls it on close; like
    :func:`set_replica_id`, the last installer wins. ``None``
    uninstalls."""
    global _HISTORY_PROVIDER
    _HISTORY_PROVIDER = fn


def history_provider():
    return _HISTORY_PROVIDER


def set_replica_id(rid: str | None) -> None:
    """Stamp a replica identity into every later dump: the filename
    gains a ``_r<id>`` segment and the trace metadata a
    ``replica_id`` key, so flight records from two same-host replicas
    can never alias in a merged Perfetto view (ISSUE 14; the
    ``ModelServer`` calls this at construction — in a multi-server
    process the LAST server wins, which matches the shared tracer
    those servers also share)."""
    global _REPLICA_ID
    _REPLICA_ID = str(rid) if rid else None


def replica_id() -> str | None:
    return _REPLICA_ID


def flight_seconds() -> float:
    """The recorder window in seconds (``TDT_FLIGHT_SECONDS``)."""
    v = os.environ.get("TDT_FLIGHT_SECONDS", "").strip()
    if not v:
        return DEFAULT_FLIGHT_SECONDS
    try:
        return float(v)
    except ValueError:
        raise ValueError(
            f"TDT_FLIGHT_SECONDS must be a number: {v!r}") from None


def trace_dir() -> str:
    """Directory flight records land in (``TDT_TRACE_DIR``)."""
    return (os.environ.get("TDT_TRACE_DIR", "").strip()
            or os.path.join(tempfile.gettempdir(), "tdt_trace"))


def last_record() -> dict | None:
    """``{"path", "reason", "ts", "count"}`` of the newest dump, or
    None. ``count`` is the total dumps this process has written."""
    with _LOCK:
        return dict(_LAST) if _LAST else None


def dump(reason: str, last_s: float | None = None) -> str | None:
    """Write the trailing event window as a Perfetto-loadable JSON
    file; returns its path, or None when tracing is disabled.

    The filename carries the reason, host index, and a millisecond
    timestamp so repeated dumps never clobber each other."""
    global _LAST, _COUNT, _SEQ
    if not _trace.enabled():
        return None
    from triton_dist_tpu.tools import trace_export as _texp
    window = last_s if last_s is not None else flight_seconds()
    meta = {"reason": reason, "window_s": window,
            "unix_time": time.time()}
    if _REPLICA_ID:
        meta["replica_id"] = _REPLICA_ID
    prov = _HISTORY_PROVIDER
    if prov is not None:
        try:
            hist = prov()
        except Exception:  # noqa: BLE001 — history must never block a dump
            hist = None
        if hist and hist.get("series"):
            meta["history"] = hist
    chrome = _texp.to_chrome(_trace.collect(last_s=window),
                             metadata=meta)
    d = trace_dir()
    os.makedirs(d, exist_ok=True)

    def _safe(s, n=64):
        return "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in s)[:n]

    safe = _safe(reason)
    # The replica segment keeps two same-host replicas' dumps
    # filename-distinct even at identical millisecond timestamps.
    rep = f"_r{_safe(_REPLICA_ID, 48)}" if _REPLICA_ID else ""
    with _LOCK:
        # Per-process sequence number: two dumps inside the SAME
        # millisecond (fast hosts, back-to-back triggers) must not
        # share a path — the second would silently overwrite the
        # first postmortem.
        _SEQ += 1
        seq = _SEQ
    path = os.path.join(
        d, f"flight_{safe}{rep}_h{_texp._host_index()}"
           f"_{int(time.time() * 1e3)}_{os.getpid()}_{seq}.trace.json")
    with open(path, "w") as f:
        json.dump(chrome, f)
    with _LOCK:
        _COUNT += 1
        _LAST = {"path": path, "reason": reason, "ts": time.time(),
                 "count": _COUNT}
    _registry.counter("resilience.flight_dumps").inc()
    _registry.counter(f"resilience.flight_dump.{safe}").inc()
    try:
        # Every flight dump also ARMS a bounded device-profile capture
        # (obs.devprof): with TDT_DEVPROF_ON_BREACH set, the serving
        # pump profiles its next N iterations, so the postmortem pairs
        # this host-event dump with what the chip actually did. A
        # no-op (one flag write) when no sampler consumes it.
        from triton_dist_tpu.obs import devprof as _devprof
        _devprof.arm(reason)
    except Exception:  # noqa: BLE001 — arming must never worsen a failure
        pass
    return path


def maybe_dump(reason: str, last_s: float | None = None) -> str | None:
    """Best-effort :func:`dump` for failure paths: never raises, and
    skips when the same reason dumped less than :data:`MIN_INTERVAL_S`
    ago (a flapping breaker must not write a dump per request)."""
    if not _trace.enabled():
        return None
    now = time.monotonic()
    with _LOCK:
        prev = _LAST_BY_REASON.get(reason)
        if prev is not None and now - prev < MIN_INTERVAL_S:
            return None
        _LAST_BY_REASON[reason] = now
    try:
        return dump(reason, last_s)
    except Exception:  # noqa: BLE001 — the dump must never worsen a failure
        return None


def install_signal_handlers() -> bool:
    """Dump a flight record on ``SIGTERM`` before the previous handler
    (or the default die-now behavior) runs. Idempotent; only works
    from the main thread (``signal.signal``'s constraint) — returns
    False and does nothing elsewhere."""
    global _SIGTERM_INSTALLED
    if _SIGTERM_INSTALLED:
        return True
    if threading.current_thread() is not threading.main_thread():
        return False
    prev = signal.getsignal(signal.SIGTERM)

    def _on_term(signum, frame):
        maybe_dump("sigterm")
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_DFL:
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            signal.raise_signal(signal.SIGTERM)

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:  # not the main thread after all
        return False
    _SIGTERM_INSTALLED = True
    return True


def reset() -> None:
    """Drop process-local recorder state (tests). The SIGTERM handler
    is left installed — it re-checks tracing at fire time."""
    global _LAST, _COUNT, _REPLICA_ID, _HISTORY_PROVIDER
    with _LOCK:
        _LAST = None
        _COUNT = 0
        _LAST_BY_REASON.clear()
        _REPLICA_ID = None
        _HISTORY_PROVIDER = None
