"""Sliding-window SLO engine: rolling percentiles + burn-rate alerts.

The metrics registry (PR 1) keeps cumulative-since-boot histograms:
after an hour of good samples a p99 regression is arithmetically
invisible — the bad minute drowns in the good hour. This module adds
the *rolling* view serving health lives on: a ring of subwindow bucket
arrays (:class:`WindowedHistogram`) whose trailing-window merge yields
p50/p99 over the last ``TDT_SLO_WINDOW_S`` seconds (default 60 s, 12
subwindows of 5 s), for the four serving signals the scheduler feeds —
TTFT, per-output-token time (TPOT), queue wait, and pump-iteration
time.

On top sit declarative targets (:class:`SLOTarget`) evaluated
Google-SRE style with **multi-window burn rates**: the burn rate of a
window is the fraction of that window's requests violating the
threshold divided by the error budget ``1 - p`` (burn 1.0 = budget
consumed exactly at the sustainable rate). A target *breaches* when
BOTH the fast window (``window_s``, 1 min) and the slow window
(``window_s × TDT_SLO_SLOW_MULT``, 10 min) exceed the target's burn
threshold — the fast window gives detection latency, the slow window
vetoes one-off blips (a single slow request cannot page anyone).

The payoff: a breach **arms the flight recorder** — the same
``obs.flight`` dump a watchdog trip produces — so a latency regression
leaves a Perfetto postmortem of what the process was doing *before*
anything crashes. Dumps fire on the not-breached → breached
transition only (plus ``obs.flight``'s own per-reason rate limit), so
a sustained breach writes one record, not one per evaluation.

Every clock is injectable (``clock=``) so window rotation, expiry, and
burn math are testable without sleeping (tests/test_slo.py).

Metric surface (docs/observability.md "SLOs and burn rates"):
``serving.rolling.<metric>_{p50,p99}_ms`` + ``serving.rolling.<metric>_n``
gauges, ``serving.slo_burn.<name>`` / ``serving.slo_burn.<name>_slow``
/ ``serving.slo_breached.<name>`` gauges, ``serving.slo_breaches`` /
``serving.slo_breach.<name>`` counters.
"""

from __future__ import annotations

import bisect
import collections
import dataclasses
import os
import threading
import time

from triton_dist_tpu.obs import flight as _flight
from triton_dist_tpu.obs import registry as _registry
from triton_dist_tpu.obs import trace as _trace
from triton_dist_tpu.obs.exposition import histogram_quantile

__all__ = [
    "DEFAULT_BURN_THRESHOLD", "DEFAULT_SLOW_MULT", "DEFAULT_SUBWINDOWS",
    "DEFAULT_WINDOW_S", "METRICS", "SLO_MS_BUCKETS", "SLOTarget",
    "SLOTracker", "WindowedHistogram", "default_targets", "enabled",
    "gauge_catalog", "violating_fraction",
]

#: The serving signals the scheduler feeds into the tracker.
METRICS = ("ttft", "tpot", "queue_wait", "pump")

#: Default rolling window (seconds) — the FAST burn window.
DEFAULT_WINDOW_S = 60.0

#: Subwindows per window: granularity of rotation/expiry.
DEFAULT_SUBWINDOWS = 12

#: Slow burn window = ``window_s * slow_mult`` (Google-SRE multiwindow:
#: the fast window detects, the slow window vetoes blips).
DEFAULT_SLOW_MULT = 10

#: Burn rate both windows must exceed for a breach. 1.0 = the error
#: budget is being consumed exactly at the sustainable rate.
DEFAULT_BURN_THRESHOLD = 1.0

#: The SLOW window must hold at least this many samples before a
#: target can breach (``TDT_SLO_MIN_SAMPLES``). Under sparse traffic
#: the slow window may contain only the blip itself — with no good
#: traffic to dilute it, fast and slow agree trivially and the
#: multiwindow veto is void; requiring a floor of slow-window data
#: restores "a single slow request cannot page anyone".
DEFAULT_MIN_SAMPLES = 10

#: SLO histograms extend the default ms buckets past 10 s: thresholds
#: only *provably* fire on samples inside a finite bucket (the
#: overflow tail cannot be compared against a larger threshold), so
#: the buckets must reach the generous default thresholds below.
SLO_MS_BUCKETS = _registry.DEFAULT_MS_BUCKETS + (
    25_000.0, 60_000.0, 120_000.0, 300_000.0)

#: Default targets: (metric, env override, p, threshold_ms). Deliberately
#: generous — on the CPU quick tier nothing healthy ever breaches them
#: (the acceptance bar: no false positive across the suite) — and
#: per-deployment env overrides tighten them to real latency goals.
_DEFAULT_TARGET_SPECS = (
    ("ttft", "TDT_SLO_TTFT_P99_MS", 0.99, 60_000.0),
    ("tpot", "TDT_SLO_TPOT_P99_MS", 0.99, 60_000.0),
    ("queue_wait", "TDT_SLO_QUEUE_P99_MS", 0.99, 120_000.0),
)

#: Evaluations closer together than this are skipped (pump loops tick
#: every few ms; quantile merges need not run that often).
EVAL_INTERVAL_S = 1.0


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name, "").strip()
    if not v:
        return default
    try:
        return float(v)
    except ValueError:
        raise ValueError(f"{name} must be a number: {v!r}") from None


def enabled() -> bool:
    """``TDT_SLO=0`` switches the whole SLO engine off."""
    return os.environ.get("TDT_SLO", "").strip() != "0"


def window_s() -> float:
    return _env_float("TDT_SLO_WINDOW_S", DEFAULT_WINDOW_S)


def subwindows() -> int:
    return _registry.env_int("TDT_SLO_SUBWINDOWS", DEFAULT_SUBWINDOWS,
                             minimum=1)


def slow_mult() -> int:
    return _registry.env_int("TDT_SLO_SLOW_MULT", DEFAULT_SLOW_MULT,
                             minimum=1)


def burn_threshold() -> float:
    return _env_float("TDT_SLO_BURN_RATE", DEFAULT_BURN_THRESHOLD)


def min_breach_samples() -> int:
    return _registry.env_int("TDT_SLO_MIN_SAMPLES",
                             DEFAULT_MIN_SAMPLES, minimum=0)


@dataclasses.dataclass(frozen=True)
class SLOTarget:
    """Declarative target: "the ``p`` quantile of ``metric`` stays
    under ``threshold_ms``" — i.e. at most ``1 - p`` of requests may
    exceed the threshold (the error budget the burn rate is measured
    against)."""

    metric: str
    p: float
    threshold_ms: float
    burn_threshold: float = DEFAULT_BURN_THRESHOLD

    def __post_init__(self):
        if self.metric not in METRICS:
            raise ValueError(
                f"SLOTarget metric {self.metric!r} not in {METRICS}")
        if not 0.0 < self.p < 1.0:
            raise ValueError(f"SLOTarget p must be in (0, 1): {self.p}")
        if self.threshold_ms <= 0:
            raise ValueError(
                f"SLOTarget threshold_ms must be positive: "
                f"{self.threshold_ms}")

    @property
    def name(self) -> str:
        return f"{self.metric}_p{self.p * 100:g}".replace(".", "_")

    @property
    def budget(self) -> float:
        return 1.0 - self.p


def default_targets() -> list[SLOTarget]:
    """The default target set, with per-metric env overrides
    (``TDT_SLO_TTFT_P99_MS`` etc.; ``0`` or negative disables that
    target)."""
    bt = burn_threshold()
    out = []
    for metric, env, p, dflt in _DEFAULT_TARGET_SPECS:
        thr = _env_float(env, dflt)
        if thr > 0:
            out.append(SLOTarget(metric, p, thr, burn_threshold=bt))
    return out


class WindowedHistogram:
    """Ring of subwindow bucket arrays: rolling-window histograms.

    Each subwindow covers ``window_s / subwindows`` seconds and is a
    plain ``(counts, sum, count)`` triple keyed by its absolute
    subwindow index (``clock() // sub_s``); subwindows older than the
    retained span (``window_s × retain_windows`` — sized to cover the
    SLOW burn window) expire on the next observe/snapshot.
    :meth:`snapshot` merges the trailing subwindows covering a
    requested window into a registry-shaped histogram dict, so
    ``obs.histogram_quantile`` works on it unchanged. ``min``/``max``
    are reported as None — window extrema are not tracked, and the
    quantile's overflow handling clips to the top finite edge instead
    of needing them.
    """

    __slots__ = ("buckets", "window_s", "sub_s", "n_keep", "_slots",
                 "_lock", "_clock")

    def __init__(self, buckets=SLO_MS_BUCKETS, window_s_: float | None = None,
                 subwindows_: int | None = None,
                 retain_windows: int | None = None, clock=time.monotonic):
        if not buckets or list(buckets) != sorted(buckets):
            raise ValueError("buckets must be ascending, non-empty")
        self.buckets = tuple(float(b) for b in buckets)
        self.window_s = window_s_ if window_s_ is not None else window_s()
        n_sub = subwindows_ if subwindows_ is not None else subwindows()
        if self.window_s <= 0 or n_sub <= 0:
            raise ValueError(
                f"window_s/subwindows must be positive: "
                f"{self.window_s}/{n_sub}")
        retain = retain_windows if retain_windows is not None else slow_mult()
        self.sub_s = self.window_s / n_sub
        self.n_keep = n_sub * max(int(retain), 1)
        self._slots: collections.OrderedDict[int, list] = (
            collections.OrderedDict())
        self._lock = threading.Lock()
        self._clock = clock

    def _expire(self, now_idx: int) -> None:
        # Caller holds the lock. Insertion order == index order (the
        # clock is monotonic), so expiry pops from the front.
        oldest_keep = now_idx - self.n_keep + 1
        while self._slots and next(iter(self._slots)) < oldest_keep:
            self._slots.popitem(last=False)

    def observe(self, value: float) -> None:
        value = float(value)
        i = bisect.bisect_left(self.buckets, value)
        now_idx = int(self._clock() // self.sub_s)
        with self._lock:
            self._expire(now_idx)
            slot = self._slots.get(now_idx)
            if slot is None:
                slot = self._slots[now_idx] = [
                    [0] * (len(self.buckets) + 1), 0.0, 0]
            slot[0][i] += 1
            slot[1] += value
            slot[2] += 1

    def snapshot(self, over_s: float | None = None) -> dict:
        """Merged histogram dict over the trailing ``over_s`` seconds
        (default: one fast window): the current — possibly partial —
        subwindow plus enough whole ones to cover the span."""
        over_s = self.window_s if over_s is None else float(over_s)
        n = min(max(-(-over_s // self.sub_s), 1), self.n_keep)
        now_idx = int(self._clock() // self.sub_s)
        counts = [0] * (len(self.buckets) + 1)
        total, s = 0, 0.0
        with self._lock:
            self._expire(now_idx)
            for idx, (c, sm, n_obs) in self._slots.items():
                if idx > now_idx - n:
                    for i, v in enumerate(c):
                        counts[i] += v
                    s += sm
                    total += n_obs
        return {"buckets": list(self.buckets), "counts": counts,
                "sum": s, "count": total, "min": None, "max": None}

    def quantile(self, q: float, over_s: float | None = None):
        return histogram_quantile(self.snapshot(over_s), q)

    def clear(self) -> None:
        """Drop every retained subwindow (a fresh measurement epoch)."""
        with self._lock:
            self._slots.clear()


def violating_fraction(h: dict, threshold_ms: float) -> float:
    """Estimated fraction of a histogram dict's samples above
    ``threshold_ms``: whole buckets above the threshold count fully,
    the containing bucket contributes linearly, and overflow-bucket
    samples count only when the threshold sits at or under the top
    finite edge (they are provably above it there; beyond the top edge
    their position is unknowable and assuming violation would
    manufacture false positives)."""
    counts = h.get("counts") or []
    buckets = h.get("buckets") or []
    total = h.get("count", 0)
    if not total or not counts:
        return 0.0
    threshold_ms = float(threshold_ms)
    viol = 0.0
    lo = 0.0
    for i, c in enumerate(counts):
        if i >= len(buckets):
            if buckets and threshold_ms <= buckets[-1]:
                viol += c
            break
        hi = buckets[i]
        if threshold_ms <= lo:
            viol += c
        elif threshold_ms < hi:
            viol += c * (hi - threshold_ms) / (hi - lo)
        lo = hi
    return viol / total


class SLOTracker:
    """Rolling-window observatory over the serving signals + the
    burn-rate evaluator that arms the flight recorder.

    One per :class:`~triton_dist_tpu.serving.scheduler.Scheduler`; the
    pump thread observes and ticks :meth:`evaluate` (rate-limited to
    :data:`EVAL_INTERVAL_S`), the server's ``{"cmd": "metrics"}``
    forces a fresh evaluation before snapshotting. Gauges land in the
    process registry, so multiple trackers in one process (tests) last
    write wins — exactly the point-in-time semantics gauges carry."""

    def __init__(self, targets=None, window_s_: float | None = None,
                 subwindows_: int | None = None,
                 slow_mult_: int | None = None, clock=time.monotonic,
                 buckets=SLO_MS_BUCKETS):
        self.window_s = window_s_ if window_s_ is not None else window_s()
        mult = slow_mult_ if slow_mult_ is not None else slow_mult()
        self.slow_s = self.window_s * max(int(mult), 1)
        self.clock = clock
        self.targets = tuple(default_targets() if targets is None
                             else targets)
        for t in self.targets:
            if not isinstance(t, SLOTarget):
                raise TypeError(
                    f"slo targets must be SLOTarget, got {t!r}")
        self.hists = {m: WindowedHistogram(
            buckets, self.window_s, subwindows_, max(int(mult), 1),
            clock) for m in METRICS}
        self._lock = threading.Lock()
        self._breached: dict[str, bool] = {}
        self._last_eval: float | None = None

    def observe(self, metric: str, ms: float) -> None:
        self.hists[metric].observe(ms)

    def reset_windows(self) -> None:
        """Drop every rolling window (breach state stays): the start
        of a fresh measurement epoch. bench.py calls this between its
        warmup and timed passes so the windowed percentiles it reports
        cannot contain the warmup's cold-compile latencies."""
        for h in self.hists.values():
            h.clear()

    def quantile(self, metric: str, q: float,
                 over_s: float | None = None):
        return self.hists[metric].quantile(q, over_s)

    def burn_rate(self, target: SLOTarget, over_s: float) -> float:
        """Violating fraction over the window, divided by the error
        budget. 0.0 on an empty window (no data is not a breach)."""
        h = self.hists[target.metric].snapshot(over_s)
        if not h["count"]:
            return 0.0
        return (violating_fraction(h, target.threshold_ms)
                / max(target.budget, 1e-9))

    @staticmethod
    def _burn_of(snap: dict, target: SLOTarget) -> float:
        if not snap["count"]:
            return 0.0
        return (violating_fraction(snap, target.threshold_ms)
                / max(target.budget, 1e-9))

    def evaluate(self, force: bool = False) -> dict | None:
        """One evaluation pass: refresh the rolling-percentile and
        burn-rate gauges, detect breach transitions, arm the flight
        recorder on each new breach. Returns the evaluation dict, or
        None when rate-limited (``force=True`` bypasses)."""
        new_breaches: list[str] = []
        with self._lock:
            now = self.clock()
            if (not force and self._last_eval is not None
                    and now - self._last_eval < EVAL_INTERVAL_S):
                return None
            self._last_eval = now
            rolling: dict = {}
            # One window merge per (metric, span): the fast snapshots
            # serve the rolling gauges AND every target's fast burn,
            # the slow ones each target's slow burn + sample floor.
            fast_snaps = {m: self.hists[m].snapshot() for m in METRICS}
            slow_snaps: dict = {}
            for m in METRICS:
                snap = fast_snaps[m]
                _registry.gauge(f"serving.rolling.{m}_n").set(
                    snap["count"])
                for q, tag in ((0.50, "p50"), (0.99, "p99")):
                    # A drained window zeroes its gauges (with _n=0
                    # alongside): a dashboard must never read a
                    # minutes-old percentile as current.
                    v = (histogram_quantile(snap, q)
                         if snap["count"] else None)
                    _registry.gauge(
                        f"serving.rolling.{m}_{tag}_ms").set(
                        round(v, 3) if v is not None else 0.0)
                    if v is not None:
                        rolling[f"{m}_{tag}_ms"] = round(v, 3)
            burn: dict = {}
            min_n = min_breach_samples()
            for t in self.targets:
                if t.metric not in slow_snaps:
                    slow_snaps[t.metric] = self.hists[
                        t.metric].snapshot(self.slow_s)
                fast = self._burn_of(fast_snaps[t.metric], t)
                slow = self._burn_of(slow_snaps[t.metric], t)
                _registry.gauge(f"serving.slo_burn.{t.name}").set(
                    round(fast, 4))
                _registry.gauge(f"serving.slo_burn.{t.name}_slow").set(
                    round(slow, 4))
                # The slow-window sample floor keeps the multiwindow
                # veto meaningful under sparse traffic: one slow
                # request alone in both windows must not page anyone.
                breached = (fast > t.burn_threshold
                            and slow > t.burn_threshold
                            and slow_snaps[t.metric]["count"] >= min_n)
                _registry.gauge(f"serving.slo_breached.{t.name}").set(
                    1.0 if breached else 0.0)
                if breached and not self._breached.get(t.name):
                    # Transition, not level: a sustained breach arms
                    # the recorder ONCE (obs.flight's per-reason rate
                    # limit backstops a flapping target).
                    new_breaches.append(t.name)
                    _registry.counter("serving.slo_breaches").inc()
                    _registry.counter(
                        f"serving.slo_breach.{t.name}").inc()
                    _trace.instant(
                        f"serving.slo_breach.{t.name}", "serving",
                        args={"target": t.name,
                              "threshold_ms": t.threshold_ms,
                              "burn_fast": round(fast, 4),
                              "burn_slow": round(slow, 4)})
                self._breached[t.name] = breached
                burn[t.name] = {"fast": round(fast, 4),
                                "slow": round(slow, 4),
                                "breached": breached}
        # The dump serializes the whole trace ring to disk — OUTSIDE
        # the tracker lock, or a concurrent metrics scrape (and the
        # pump itself) would stall behind file I/O exactly while the
        # regression being reported is in progress.
        for name in new_breaches:
            _flight.maybe_dump(f"slo_{name}")
        return {"rolling": rolling, "burn": burn,
                "new_breaches": new_breaches}


def gauge_catalog(targets=None) -> list[str]:
    """Every gauge name the tracker maintains (the wellformedness
    contract a live ``{"cmd": "metrics"}`` snapshot is tested
    against). Percentile gauges require at least one sample in the
    window; ``_n`` gauges and the per-target burn/breach gauges exist
    after any evaluation."""
    targets = default_targets() if targets is None else targets
    names = [f"serving.rolling.{m}_n" for m in METRICS]
    names += [f"serving.rolling.{m}_{tag}_ms" for m in METRICS
              for tag in ("p50", "p99")]
    for t in targets:
        names += [f"serving.slo_burn.{t.name}",
                  f"serving.slo_burn.{t.name}_slow",
                  f"serving.slo_breached.{t.name}"]
    return names
