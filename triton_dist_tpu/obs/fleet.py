"""Fleet observability plane: per-replica health, cross-replica merge,
placement signals (ISSUE 14).

Every signal the multi-replica tier's router needs — SLO burn rates,
rolling TTFT/TPOT percentiles, breaker states, queue/occupancy gauges —
is already computed *per replica*; until now each was trapped inside
its own process behind ``{"cmd": "metrics"}``. This module is the
host-side control plane over N replicas, shipped BEFORE the router
(ROADMAP item 2) so placement can rest on tested, aggregated,
staleness-aware numbers:

- :func:`replica_health` builds the compact ``ReplicaHealth`` dict the
  server's cheap ``{"cmd": "health"}`` verb returns — lock-free gauge/
  counter peeks, NO SLO force-evaluation, no generation lock;
- :class:`FleetView` scrapes N endpoints concurrently (per-replica
  timeouts), tracks per-replica staleness (``live`` → ``stale`` →
  ``down`` by last-good-snapshot age; a dead or wedged replica
  degrades, never raises, and its last-good health is retained with
  its age reported), and merges full metric snapshots by kind;
- :func:`merge_fleet_snapshots` extends
  ``obs.exposition.merge_snapshots``: counters sum into fleet totals,
  histograms merge bucket-wise (fleet p99 comes from SUMMED buckets
  through the existing ``histogram_quantile`` — never from averaging
  per-replica percentiles), and gauges keep BOTH a fleet rollup
  (additive gauges like queue depth sum; point-in-time ones keep the
  max) and the per-replica values under ``per_replica``;
- :func:`placement_score` is the explicit, unit-tested scoring
  function ISSUE 15's router will consume verbatim: occupancy
  headroom minus queue-depth, burn-rate, breach, and breaker
  penalties (higher = better placement target);
- :func:`render_prometheus_fleet` renders the merged view as
  Prometheus text exposition with a ``replica`` label per series
  (``replica="fleet"`` for the rollup).

Knobs (docs/observability.md "Fleet view"): ``TDT_FLEET_STALE_S`` /
``TDT_FLEET_DOWN_S`` — ages past which a replica's last good snapshot
degrades its status; ``TDT_FLEET_TIMEOUT_S`` — per-replica scrape
timeout; ``TDT_REPLICA_ID`` — the server-side replica identity
(docs/serving.md "Server").
"""

from __future__ import annotations

import os
import re
import threading
import time

from triton_dist_tpu.obs import history as _history
from triton_dist_tpu.obs import registry as _registry
from triton_dist_tpu.obs.exposition import (
    _fmt, _prom_name, histogram_quantile, merge_snapshots)

__all__ = [
    "DEFAULT_DOWN_S", "DEFAULT_STALE_S", "DEFAULT_TIMEOUT_S",
    "FleetView", "PERCENTILE_HISTOGRAMS", "STATUSES",
    "merge_fleet_snapshots", "merged_percentiles", "parse_endpoint",
    "peek_counters", "peek_gauges", "placement_score",
    "render_prometheus_fleet", "replica_health",
]

#: Replica status ladder (docs/observability.md "Fleet view"): a
#: successful scrape younger than the stale age is ``live``; past it
#: (or after a failed scrape) the replica is ``stale`` — its last-good
#: snapshot is retained but must be read with its reported age — and
#: past the down age it is ``down`` (excluded from placement).
STATUSES = ("live", "stale", "down")

DEFAULT_STALE_S = 10.0
DEFAULT_DOWN_S = 30.0
DEFAULT_TIMEOUT_S = 5.0

#: placement_score weights — explicit module constants so the ISSUE 15
#: router's behavior is auditable (and tunable) in one place.
QUEUE_WEIGHT = 0.1      # per queued request
BURN_WEIGHT = 0.25      # per unit of burn rate above sustainable (1.0)
BREACH_PENALTY = 2.0    # per target currently breached
BREAKER_PENALTY = 0.5   # per circuit breaker not fully closed
STALE_PENALTY = 1.0     # stale (but not down) replicas rank below live


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name, "").strip()
    if not v:
        return default
    try:
        return float(v)
    except ValueError:
        raise ValueError(f"{name} must be a number: {v!r}") from None


def stale_s() -> float:
    return _env_float("TDT_FLEET_STALE_S", DEFAULT_STALE_S)


def down_s() -> float:
    return _env_float("TDT_FLEET_DOWN_S", DEFAULT_DOWN_S)


def scrape_timeout_s() -> float:
    return _env_float("TDT_FLEET_TIMEOUT_S", DEFAULT_TIMEOUT_S)


def parse_endpoint(ep) -> tuple:
    """``(host, port)`` from ``"host:port"``, ``(host, port)``, or a
    bare port int (localhost)."""
    if isinstance(ep, (tuple, list)) and len(ep) == 2:
        return str(ep[0]), int(ep[1])
    if isinstance(ep, int):
        return "127.0.0.1", ep
    host, _, port = str(ep).rpartition(":")
    if not host or not port:
        raise ValueError(f"endpoint must be host:port, got {ep!r}")
    return host, int(port)


# ---------------------------------------------------------------------------
# Lock-free registry peeks + the ReplicaHealth builder.
# ---------------------------------------------------------------------------

def peek_gauges(registry=None) -> dict:
    """Every gauge's current value WITHOUT taking the registry lock:
    ``list(dict.items())`` is a single C-level pass under the GIL and
    each ``_value`` read is one attribute load. This is what keeps the
    ``health`` verb cheap — a 1 Hz scrape of N replicas must not
    contend with N pump loops (ISSUE 14 satellite: ``tools/top.py``
    used to force-evaluate SLOs on every render tick)."""
    reg = registry if registry is not None else _registry.get_registry()
    store = getattr(reg, "_gauges", None) or {}
    return {k: m._value for k, m in list(store.items())}


def peek_counters(registry=None) -> dict:
    """Lock-free counter peek (see :func:`peek_gauges`)."""
    reg = registry if registry is not None else _registry.get_registry()
    store = getattr(reg, "_counters", None) or {}
    return {k: m._value for k, m in list(store.items())}


def replica_health(replica_id: str, seq: int, started_monotonic: float,
                   registry=None, engine=None, scheduler=None,
                   clock=time.monotonic, tier: str | None = None) -> dict:
    """The compact ``ReplicaHealth`` dict ``{"cmd": "health"}``
    returns (docs/serving.md "Server"): everything the fleet view and
    the placement score consume, built from lock-free reads of the
    LAST-EVALUATED gauges — the verb never forces an SLO evaluation
    (the pump refreshes them every working iteration; ``seq`` +
    ``uptime_s`` let a scraper judge freshness itself).

    Fields: ``replica_id``, ``seq`` (monotonic per-server snapshot
    number), ``uptime_s``, ``rolling`` (TTFT/TPOT/queue-wait p50/p99 +
    sample counts), ``slo`` (per-target fast/slow burn + breached
    flag), ``queue_depth`` / ``max_waiting``, ``batch_occupancy`` /
    ``batch``, ``kv`` (block utilization/free, paged engines),
    ``breakers`` (open count + not-closed ops), ``spec_accept_rate``
    (speculative engines), ``decode_path``, and the headline serving
    counters."""
    g = peek_gauges(registry)
    c = peek_counters(registry)

    rolling: dict = {}
    for m in ("ttft", "tpot", "queue_wait"):
        for tag in ("p50_ms", "p99_ms", "n"):
            v = g.get(f"serving.rolling.{m}_{tag}")
            if v is not None:
                rolling[f"{m}_{tag}"] = v

    slo: dict = {}
    for k, v in g.items():
        if not k.startswith("serving.slo_burn.") or k.endswith("_slow"):
            continue
        name = k[len("serving.slo_burn."):]
        slo[name] = {
            "burn": v,
            "burn_slow": g.get(f"{k}_slow"),
            "breached": bool(g.get(f"serving.slo_breached.{name}")),
        }

    not_closed = {k[len("resilience."):-len(".breaker_state")]: int(v)
                  for k, v in g.items()
                  if k.startswith("resilience.")
                  and k.endswith(".breaker_state") and v}

    health: dict = {
        "replica_id": replica_id,
        "seq": int(seq),
        "uptime_s": round(max(clock() - started_monotonic, 0.0), 3),
        "rolling": rolling,
        "slo": slo,
        "queue_depth": g.get("serving.queue_depth", 0.0),
        "batch_occupancy": g.get("serving.batch_occupancy", 0.0),
        "breakers": {"open": g.get("resilience.breakers_open", 0.0),
                     "not_closed": not_closed},
        "counters": {k: c[k] for k in ("serving.admitted",
                                       "serving.retired",
                                       "serving.pump_errors",
                                       "serving.slo_breaches",
                                       "server.requests",
                                       "server.errors") if k in c},
    }
    if tier is not None:
        # Disaggregated-fleet role (ISSUE 18): "prefill" / "decode" /
        # "unified" — a tiered router pools replicas by this field, so
        # it rides the cheap health verb like draining does.
        health["tier"] = str(tier)
    if engine is not None:
        kv = getattr(engine, "kv", None)
        health["batch"] = getattr(kv, "batch", None)
        health["decode_path"] = getattr(engine, "decode_path", None)
    if scheduler is not None:
        health["max_waiting"] = getattr(scheduler, "max_waiting", None)
    if g.get("serving.draining"):
        # Graceful drain in progress (ISSUE 15): the replica finishes
        # its in-flight work but admits nothing new — routers must
        # stop placing here (serving/router.py skips draining
        # replicas outright; the flag rides health so remote routers
        # see it without a full metrics scrape).
        health["draining"] = True
    if "kv.block_utilization" in g:
        health["kv"] = {"block_utilization": g["kv.block_utilization"],
                        "blocks_free": g.get("kv.blocks_free")}
    if "serving.spec_accept_rate" in g:
        health["spec_accept_rate"] = g["serving.spec_accept_rate"]
    return health


# ---------------------------------------------------------------------------
# Placement scoring — the function ISSUE 15's router consumes verbatim.
# ---------------------------------------------------------------------------

def placement_score(health: dict | None) -> float:
    """Score one replica as a placement target — HIGHER is better.

    Inputs (all from :func:`replica_health`): occupancy headroom
    (free decode rows / batch; 0 when capacity is unknown), minus
    ``QUEUE_WEIGHT`` per queued request, minus ``BURN_WEIGHT`` per
    unit of fast-window burn rate above the sustainable 1.0, minus
    ``BREACH_PENALTY`` per currently-breached SLO target, minus
    ``BREAKER_PENALTY`` per circuit breaker not fully closed. A
    replica with no health at all scores ``-inf`` (never a target).
    Staleness is the CALLER's dimension — :meth:`FleetView.placement`
    subtracts :data:`STALE_PENALTY` for stale replicas and excludes
    down ones; the score itself prices load and health only."""
    if not health:
        return float("-inf")
    occ = float(health.get("batch_occupancy") or 0.0)
    batch = health.get("batch")
    headroom = ((float(batch) - occ) / float(batch)
                if batch else 0.0)
    queue = float(health.get("queue_depth") or 0.0)
    burn = breached = 0.0
    for t in (health.get("slo") or {}).values():
        burn += max(float(t.get("burn") or 0.0) - 1.0, 0.0)
        breached += 1.0 if t.get("breached") else 0.0
    breakers = float((health.get("breakers") or {}).get("open") or 0.0)
    return (headroom - QUEUE_WEIGHT * queue - BURN_WEIGHT * burn
            - BREACH_PENALTY * breached - BREAKER_PENALTY * breakers)


# ---------------------------------------------------------------------------
# Snapshot merge by metric kind.
# ---------------------------------------------------------------------------

#: Gauges whose fleet rollup is a SUM (they count concurrent things,
#: so the fleet answer is the total across replicas); every other
#: gauge keeps ``merge_snapshots``'s max semantics (point-in-time
#: readings — max answers the capacity questions gauges exist for).
ADDITIVE_GAUGES = (
    "serving.queue_depth", "serving.batch_occupancy", "server.inflight",
    "kv.blocks_free", "kv.blocks_active", "kv.blocks_cached",
)


def merge_fleet_snapshots(by_replica: dict) -> dict:
    """Merge per-replica metric snapshots (``{replica_id: snapshot}``)
    into one fleet view, correctly BY KIND:

    - **counters** sum — fleet totals under the original names;
    - **histograms** merge bucket-wise (``merge_snapshots``), so a
      fleet percentile interpolates the SUMMED bucket counts via
      ``histogram_quantile`` — the only arithmetic that is correct
      (per-replica p99s cannot be averaged into a fleet p99);
    - **gauges** keep a fleet rollup under the original names
      (:data:`ADDITIVE_GAUGES` sum, everything else keeps the max)
      AND the raw per-replica values under ``per_replica`` —
      ``{rid: {"gauges": ..., "counters": ...}}`` — so nothing is
      lost to the rollup.

    The result carries ``replicas`` (sorted ids) and merges cleanly
    into ``tools/report.py``'s fleet section and
    :func:`render_prometheus_fleet`.
    """
    ids = sorted(by_replica)
    merged = merge_snapshots([by_replica[r] for r in ids])
    for name in ADDITIVE_GAUGES:
        vals = [by_replica[r].get("gauges", {}).get(name) for r in ids]
        vals = [v for v in vals if v is not None]
        if vals:
            merged["gauges"][name] = sum(vals)
    merged["replicas"] = ids
    merged["per_replica"] = {
        r: {"gauges": dict(by_replica[r].get("gauges", {})),
            "counters": dict(by_replica[r].get("counters", {}))}
        for r in ids}
    return merged


#: The latency histograms every fleet-percentile surface reads
#: (tools/report.py, tools/fleet_top.py, bench.py serving_fleet):
#: (snapshot histogram name, display label) pairs.
PERCENTILE_HISTOGRAMS = (("serving.ttft_ms", "ttft"),
                         ("serving.tpot_ms", "tpot"))


def merged_percentiles(histograms: dict | None,
                       names=PERCENTILE_HISTOGRAMS) -> dict:
    """``{label: {"p50": v, "p99": v, "n": count}}`` for each named
    bucket-merged histogram present and non-empty in ``histograms``
    (a merged snapshot's ``histograms`` dict, or any dict of
    registry-shaped histogram dicts) — the ONE home for the fleet
    percentile arithmetic the report/dashboard/bench surfaces share,
    always interpolating the summed buckets via
    ``histogram_quantile``."""
    out: dict = {}
    for name, label in names:
        h = (histograms or {}).get(name)
        if not h or not h.get("count"):
            continue
        out[label] = {"p50": histogram_quantile(h, 0.50),
                      "p99": histogram_quantile(h, 0.99),
                      "n": h["count"]}
    return out


_LABEL_SAFE = re.compile(r"[^A-Za-z0-9_.:\-]")


def _label(replica: str) -> str:
    return _LABEL_SAFE.sub("_", str(replica))


def render_prometheus_fleet(by_replica: dict, prefix: str = "tdt") -> str:
    """Prometheus text exposition of the fleet: every counter/gauge
    series is emitted once per replica with a ``replica="<id>"`` label
    plus the fleet rollup as ``replica="fleet"`` (samples of one
    metric grouped under one ``# TYPE`` line, per the format spec);
    histograms are emitted fleet-rollup-only (bucket-merged — the
    per-replica bucket explosion belongs in a real TSDB, not a text
    page). Same name sanitization/prefixing as
    ``obs.render_prometheus``."""
    merged = merge_fleet_snapshots(by_replica)
    per = merged["per_replica"]
    ids = merged["replicas"]
    lines: list = []

    def emit(kind, pn, fleet_v, per_kind, name):
        lines.append(f"# TYPE {pn} {kind}")
        lines.append(f'{pn}{{replica="fleet"}} {_fmt(fleet_v)}')
        for rid in ids:
            v = per[rid][per_kind].get(name)
            if v is not None:
                lines.append(
                    f'{pn}{{replica="{_label(rid)}"}} {_fmt(v)}')

    for name in sorted(merged["counters"]):
        emit("counter", _prom_name(name, prefix) + "_total",
             merged["counters"][name], "counters", name)
    for name in sorted(merged["gauges"]):
        emit("gauge", _prom_name(name, prefix), merged["gauges"][name],
             "gauges", name)
    for name in sorted(merged["histograms"]):
        h = merged["histograms"][name]
        pn = _prom_name(name, prefix)
        lines.append(f"# TYPE {pn} histogram")
        cum = 0
        for ub, cnt in zip(h["buckets"], h["counts"]):
            cum += cnt
            lines.append(
                f'{pn}_bucket{{replica="fleet",le="{_fmt(ub)}"}} {cum}')
        cum += h["counts"][len(h["buckets"])]
        lines.append(f'{pn}_bucket{{replica="fleet",le="+Inf"}} {cum}')
        lines.append(f'{pn}_sum{{replica="fleet"}} {_fmt(h["sum"])}')
        lines.append(f'{pn}_count{{replica="fleet"}} {h["count"]}')
    return "\n".join(lines) + ("\n" if lines else "")


# ---------------------------------------------------------------------------
# FleetView: concurrent scrapes + staleness tracking.
# ---------------------------------------------------------------------------

class _Rec:
    """Mutable per-replica scrape record (internal)."""

    __slots__ = ("endpoint", "replica_id", "health", "snapshot", "seq",
                 "t_ok", "t_created", "last_ok", "error", "hist",
                 "rhist")

    def __init__(self, endpoint, t_created):
        self.endpoint = endpoint
        self.replica_id = f"{endpoint[0]}:{endpoint[1]}"
        self.health = None          # last GOOD health, retained
        self.snapshot = None        # last GOOD metrics snapshot
        self.seq = None
        self.t_ok = None            # clock() of the last good scrape
        self.t_created = t_created
        self.last_ok = False        # did the latest attempt succeed?
        self.error = None
        self.hist = None            # SeriesStore fed from health polls
        self.rhist = None           # last remote {"cmd": "history"} reply


class FleetView:
    """Aggregator over N replica endpoints.

    :meth:`poll` runs one CONCURRENT ``{"cmd": "health"}`` scrape
    (per-replica timeout via the client ``fanout`` machinery — one
    wedged replica cannot stall the others) and returns the per-replica
    rows; :meth:`scrape_metrics` does the same with full
    ``{"cmd": "metrics"}`` snapshots and returns the fleet merge
    (:func:`merge_fleet_snapshots`). Scrape failures NEVER raise: the
    replica's last-good data is retained and its status degrades by
    the age of that data — ``live`` while younger than ``stale_s``
    (and the latest attempt succeeded), ``stale`` until ``down_s``,
    ``down`` past it; a later good scrape recovers it to ``live``.
    ``clock`` is injectable so the transitions are testable without
    sleeping (tests/test_fleet.py)."""

    def __init__(self, endpoints, timeout_s: float | None = None,
                 stale_s_: float | None = None,
                 down_s_: float | None = None, clock=time.monotonic,
                 scrape=None):
        if not endpoints:
            raise ValueError("FleetView needs at least one endpoint")
        self.endpoints = [parse_endpoint(e) for e in endpoints]
        if len(set(self.endpoints)) != len(self.endpoints):
            raise ValueError(
                f"duplicate endpoints: {self.endpoints}")
        self.timeout_s = (timeout_s if timeout_s is not None
                          else scrape_timeout_s())
        self.stale_s = stale_s_ if stale_s_ is not None else stale_s()
        self.down_s = down_s_ if down_s_ is not None else down_s()
        if not 0 < self.stale_s <= self.down_s:
            raise ValueError(
                f"need 0 < stale_s <= down_s, got "
                f"{self.stale_s}/{self.down_s}")
        self._clock = clock
        self._scrape = scrape       # injectable (tests): (eps, req) -> list
        now = clock()
        self._eps_lock = threading.Lock()
        self._recs = {ep: _Rec(ep, now) for ep in self.endpoints}
        self._merged = None
        # Health history (ISSUE 16): every poll() appends the headline
        # health numbers into bounded per-replica ring buffers plus a
        # fleet-level rollup store — no extra scrapes, the poll the
        # dashboard already runs IS the sampler. TDT_HISTORY_LEN bounds
        # every buffer.
        self._hist_len = _history.history_len()
        self._fleet_hist = _history.SeriesStore(maxlen=self._hist_len)

    # -- dynamic membership (ISSUE 15: live replica add/remove) ------------
    def add_endpoint(self, ep) -> tuple:
        """Start tracking a replica (it joins the next poll; its
        status starts ``stale`` until a good scrape). Returns the
        parsed ``(host, port)``; duplicate endpoints are a
        ``ValueError`` like at construction."""
        ep = parse_endpoint(ep)
        with self._eps_lock:
            if ep in self._recs:
                raise ValueError(f"endpoint already tracked: {ep}")
            self._recs[ep] = _Rec(ep, self._clock())
            self.endpoints.append(ep)
        return ep

    def remove_endpoint(self, ep) -> tuple:
        """Stop tracking a replica (its record — and its contribution
        to any future merge — is dropped; a concurrent poll that
        already snapshotted the endpoint list finishes harmlessly
        against the dropped record)."""
        ep = parse_endpoint(ep)
        with self._eps_lock:
            if ep not in self._recs:
                raise ValueError(f"endpoint not tracked: {ep}")
            self._recs.pop(ep)
            self.endpoints.remove(ep)
        return ep

    def _snapshot_eps(self) -> list:
        with self._eps_lock:
            return list(self.endpoints)

    # -- scraping ----------------------------------------------------------
    def _scrape_all(self, eps, req: dict) -> list:
        """One request to every endpoint concurrently; per-slot
        ``{"error", "type"}`` dicts on failure (client fanout
        contract)."""
        if self._scrape is not None:
            return self._scrape(eps, req)
        from triton_dist_tpu.serving.client import fanout
        # retry_next=False pins slot i to endpoint i: a probe of
        # replica A answered by replica B (the generation-path retry)
        # would corrupt A's staleness record.
        return fanout(requests=[dict(req) for _ in eps],
                      timeout=self.timeout_s, endpoints=eps,
                      retry_next=False)

    def _record(self, rec: _Rec, resp, key: str) -> None:
        now = self._clock()
        ok = isinstance(resp, dict) and key in resp
        rec.last_ok = ok
        if not ok:
            rec.error = ((resp or {}).get("error")
                         if isinstance(resp, dict) else str(resp))
            _registry.counter("fleet.scrape_errors").inc()
            return
        rec.error = None
        rec.t_ok = now
        _registry.counter("fleet.scrapes").inc()
        if key == "health":
            rec.health = resp["health"]
            rec.seq = rec.health.get("seq")
            rid = rec.health.get("replica_id")
        else:
            rec.snapshot = resp["metrics"]
            rid = rec.snapshot.get("replica_id")
        if rid:
            rec.replica_id = str(rid)

    def _status(self, rec: _Rec, now: float) -> tuple:
        """(status, age_s) from the last-good-scrape age."""
        anchor = rec.t_ok if rec.t_ok is not None else rec.t_created
        age = max(now - anchor, 0.0)
        if rec.t_ok is None:
            # Never successfully scraped: no data to be "live" on.
            return ("down" if age > self.down_s else "stale"), age
        if rec.last_ok and age <= self.stale_s:
            return "live", age
        if age <= self.down_s:
            return "stale", age
        return "down", age

    def _publish(self, rows: list) -> None:
        counts = {st: 0 for st in STATUSES}
        for r in rows:
            counts[r["status"]] += 1
        _registry.gauge("fleet.replicas").set(len(rows))
        _registry.gauge("fleet.replicas_live").set(counts["live"])
        _registry.gauge("fleet.replicas_stale").set(counts["stale"])
        _registry.gauge("fleet.replicas_down").set(counts["down"])

    def poll(self) -> list:
        """One concurrent health scrape; returns :meth:`replicas`.
        Each poll also appends the headline health numbers into the
        bounded per-replica / fleet history stores (:meth:`history`) —
        the scrape the dashboard already runs IS the history sampler,
        no extra requests (ISSUE 16)."""
        t0 = time.perf_counter()
        eps = self._snapshot_eps()
        outs = self._scrape_all(eps, {"cmd": "health"})
        _registry.histogram("fleet.scrape_ms").observe(
            (time.perf_counter() - t0) * 1e3)
        for ep, resp in zip(eps, outs):
            rec = self._recs.get(ep)   # may have been removed mid-poll
            if rec is not None:
                self._record(rec, resp, "health")
        rows = self.replicas()
        self._append_history(rows)
        self._publish(rows)
        return rows

    def _append_history(self, rows: list) -> None:
        """One history tick from the poll that just completed: per
        LIVE-answering replica the headline health numbers (queue
        depth, batch occupancy, rolling TTFT p99, per-target fast
        burn), and one fleet-level rollup (additive sums over every
        replica not ``down``, plus how many replicas reported).
        Staleness-aware by construction: a replica that failed this
        poll gets NO new point — its series simply stops advancing, so
        a sparkline gap is a staleness signal, not a zero."""
        now = self._clock()
        reporting = 0
        fleet_q = fleet_occ = 0.0
        by_ep = {r["endpoint"]: r for r in rows}
        for ep in self._snapshot_eps():
            rec = self._recs.get(ep)
            row = by_ep.get(f"{ep[0]}:{ep[1]}")
            if rec is None or row is None or rec.health is None:
                continue
            h = rec.health
            if row["status"] != "down":
                reporting += 1
                fleet_q += float(h.get("queue_depth") or 0.0)
                fleet_occ += float(h.get("batch_occupancy") or 0.0)
            if not rec.last_ok:
                continue
            if rec.hist is None:
                rec.hist = _history.SeriesStore(maxlen=self._hist_len)
            rec.hist.record("queue_depth",
                            now, float(h.get("queue_depth") or 0.0))
            rec.hist.record("batch_occupancy",
                            now, float(h.get("batch_occupancy") or 0.0))
            p99 = (h.get("rolling") or {}).get("ttft_p99_ms")
            if p99 is not None:
                rec.hist.record("ttft_p99_ms", now, float(p99))
            for name, t in (h.get("slo") or {}).items():
                burn = t.get("burn")
                if burn is not None:
                    rec.hist.record(f"slo_burn.{name}",
                                    now, float(burn))
        self._fleet_hist.record("queue_depth", now, fleet_q)
        self._fleet_hist.record("batch_occupancy", now, fleet_occ)
        self._fleet_hist.record("replicas_reporting",
                                now, float(reporting))

    def scrape_metrics(self, evaluate: bool = False) -> dict | None:
        """Concurrent full-snapshot scrape → the fleet merge (also
        liveness evidence — a good metrics scrape refreshes the same
        staleness clock as a health scrape). ``evaluate=True`` asks
        each replica to force a fresh SLO evaluation first (the bench
        does, a 1 Hz dashboard should not). Returns None when no
        replica answered; replicas that failed merge with their LAST
        GOOD snapshot only if still ``stale`` or better — a ``down``
        replica's numbers leave the merge."""
        t0 = time.perf_counter()
        eps = self._snapshot_eps()
        outs = self._scrape_all(eps, {"cmd": "metrics",
                                      "evaluate": bool(evaluate)})
        _registry.histogram("fleet.scrape_ms").observe(
            (time.perf_counter() - t0) * 1e3)
        now = self._clock()
        by_replica: dict = {}
        for ep, resp in zip(eps, outs):
            rec = self._recs.get(ep)   # may have been removed mid-poll
            if rec is None:
                continue
            self._record(rec, resp, "metrics")
            status, _ = self._status(rec, now)
            if rec.snapshot is not None and status != "down":
                rid = rec.replica_id
                if rid in by_replica:
                    # Two replicas claiming one id must not silently
                    # collapse in the merge (their counters would
                    # alias) — disambiguate by endpoint.
                    rid = f"{rid}@{ep[0]}:{ep[1]}"
                by_replica[rid] = rec.snapshot
        self._publish(self.replicas())
        if not by_replica:
            self._merged = None
            return None
        self._merged = merge_fleet_snapshots(by_replica)
        return self._merged

    # -- reads -------------------------------------------------------------
    def merged(self) -> dict | None:
        """The last :meth:`scrape_metrics` merge (None before one)."""
        return self._merged

    def replicas(self) -> list:
        """Per-replica rows, endpoint order: ``{"endpoint",
        "replica_id", "status", "age_s", "seq", "health", "error",
        "score"}``. ``health`` is the LAST GOOD snapshot whatever the
        status — with ``age_s`` saying exactly how old it is, a stale
        value is never presented as current."""
        now = self._clock()
        rows = []
        for ep in self._snapshot_eps():
            rec = self._recs.get(ep)
            if rec is None:
                continue
            status, age = self._status(rec, now)
            rows.append({
                "endpoint": f"{ep[0]}:{ep[1]}",
                "replica_id": rec.replica_id,
                "status": status,
                "age_s": round(age, 3),
                "seq": rec.seq,
                "health": rec.health,
                "error": rec.error,
                "score": (None if status == "down"
                          else round(placement_score(rec.health)
                                     - (STALE_PENALTY
                                        if status == "stale" else 0.0),
                                     4)),
            })
        return rows

    def placement(self) -> list:
        """``[(replica_id, score), ...]`` best-first over the replicas
        a router may target: ``down`` replicas are excluded, ``stale``
        ones carry :data:`STALE_PENALTY` (already folded into the row
        score). This ranking is exactly what ISSUE 15's router will
        consume."""
        ranked = [(r["replica_id"], r["score"])
                  for r in self.replicas() if r["score"] is not None]
        ranked.sort(key=lambda t: -t[1])
        return ranked

    def fleet_quantile(self, hist_name: str, q: float):
        """Fleet percentile of a merged histogram — interpolated from
        the SUMMED buckets (None before a metrics scrape or when the
        histogram is absent/empty)."""
        if self._merged is None:
            return None
        h = self._merged.get("histograms", {}).get(hist_name)
        return histogram_quantile(h, q) if h else None

    # -- health history (ISSUE 16) -----------------------------------------
    def history(self, last_s: float | None = None,
                max_points: int | None = None) -> dict:
        """The poll-fed health history: ``{"fleet": <snapshot>,
        "replicas": {replica_id: <snapshot>}}`` where each snapshot is
        ``obs.history.SeriesStore.snapshot`` shaped (per-replica
        ``queue_depth`` / ``batch_occupancy`` / ``ttft_p99_ms`` /
        ``slo_burn.<name>``; fleet-level additive sums over non-down
        replicas plus ``replicas_reporting``). Timestamps are this
        view's ``clock`` — comparable within one view, not across
        processes. Empty until the first :meth:`poll`."""
        out = {"fleet": self._fleet_hist.snapshot(
                   last_s=last_s, max_points=max_points),
               "replicas": {}}
        for ep in self._snapshot_eps():
            rec = self._recs.get(ep)
            if rec is not None and rec.hist is not None:
                out["replicas"][rec.replica_id] = rec.hist.snapshot(
                    last_s=last_s, max_points=max_points)
        return out

    def scrape_history(self, last_s: float | None = None,
                       max_points: int | None = 64) -> dict:
        """One concurrent ``{"cmd": "history"}`` scrape: each
        replica's OWN sampled series (its in-process
        ``HistorySampler``, far richer than the poll-fed health
        history) is fetched and cached per replica, then returned as
        :meth:`remote_history`. Replicas without a sampler answer
        ``{"history": None}`` and simply stay absent. Deliberately
        does NOT touch the staleness clock — history is a bulk read,
        not liveness evidence (``poll`` owns that)."""
        eps = self._snapshot_eps()
        req: dict = {"cmd": "history"}
        if last_s is not None:
            req["last_s"] = last_s
        if max_points is not None:
            req["max_points"] = max_points
        outs = self._scrape_all(eps, req)
        for ep, resp in zip(eps, outs):
            rec = self._recs.get(ep)
            if rec is None:
                continue
            if isinstance(resp, dict) and "history" in resp:
                _registry.counter("fleet.history_scrapes").inc()
                rec.rhist = resp["history"]
        return self.remote_history()

    def remote_history(self) -> dict:
        """``{replica_id: <history snapshot>}`` from the last
        :meth:`scrape_history` — cached, zero requests (the dashboard
        reads this between its sparse scrape ticks)."""
        out: dict = {}
        for ep in self._snapshot_eps():
            rec = self._recs.get(ep)
            if rec is not None and rec.rhist is not None:
                out[rec.replica_id] = rec.rhist
        return out
