"""Live fused-vs-XLA perf-ratio watch: rolling medians per op.

The resilience router's BASELINE policy (PR 3) routes "clearly slower"
fused regimes to XLA using ``BASELINE.json`` floors — numbers measured
in round 5 and frozen at deploy time. ROADMAP item 5 asks for the
loop to close: *measured* ratios feeding routing so a chip run
self-corrects a stale floor without a redeploy. This module is that
feedback path: every ``@resilient`` op entry records the wall time of
the branch it actually ran (``fused`` or ``xla``) per (op, branch,
shape-bucket) into bounded rolling windows, and :func:`ratio` answers
"what does the live data say fused-vs-XLA is *right now*" — the
median of per-bucket ``median(xla) / median(fused)`` ratios across
buckets where BOTH branches have at least ``TDT_PERFWATCH_MIN_SAMPLES``
(default 32) samples. The router consults that live ratio FIRST and
falls back to the static floor when the data is too thin
(docs/resilience.md "Live ratios vs BASELINE floors");
``TDT_PERFWATCH_ROUTING=0`` opts routing out while samples keep
accumulating.

Shape buckets are power-of-two-rounded shape signatures
(``ops.common.shape_bucket``): close-enough shapes pool their samples
(a serving process sees few distinct shapes but many calls), while a
64× size difference can never launder one regime's ratio into
another's.

Recording is eager-only (trace-time "samples" under ``jax.jit`` are
compile costs, not runtimes — the router already skips its guards for
traced calls) and gated on telemetry being enabled; recorded calls are
``block_until_ready``-materialized first so the sample is device time,
not async-dispatch time (the same documented observer cost as the
engine's decode spans).

Metric surface: ``resilience.perfwatch.<op>.live_ratio`` gauge (once
computable), ``resilience.perfwatch.samples.{fused,xla}`` counters,
and the router-side ``resilience.policy_source.{live,floor}`` decision
counters (docs/observability.md).
"""

from __future__ import annotations

import collections
import os
import statistics
import threading

from triton_dist_tpu.obs import registry as _registry

__all__ = [
    "BRANCHES", "DEFAULT_MAX_SAMPLES", "DEFAULT_MIN_SAMPLES",
    "min_samples", "ratio", "record", "reset", "routing_enabled",
    "sample_count", "stats",
]

BRANCHES = ("fused", "xla")

#: Per-(op, branch, bucket) rolling-window length. Medians over 128
#: samples shrug off one-off outliers (first-call compiles, GC pauses).
DEFAULT_MAX_SAMPLES = 128

#: Both branches of a bucket need this many samples before its ratio
#: counts (``TDT_PERFWATCH_MIN_SAMPLES``): routing on thin live data
#: would be worse than routing on the stale-but-measured floor.
DEFAULT_MIN_SAMPLES = 32

#: Every Nth policy-routed call runs the fused branch anyway (the
#: policy-route analog of the breaker's half-open probe): without it a
#: routed-out op never gathers fresh fused samples, its medians freeze,
#: and live routing is one-way sticky — a transient slowdown would pin
#: the op to XLA for the process lifetime. ``TDT_PERFWATCH_PROBE_EVERY``
#: overrides; 0 disables probing.
DEFAULT_PROBE_EVERY = 32

_LOCK = threading.Lock()
_SAMPLES: dict[tuple[str, str, str], collections.deque] = {}
_PROBE_COUNT: dict[str, int] = {}
#: Op-level ratio cache: recomputed lazily only when new samples
#: arrived since the last consult, so the router's per-call policy
#: check is a dict lookup, not a median pass. Keyed by min_samples
#: (an env change selects a different gate) and invalidated by
#: dropping ALL of an op's keys on record — a per-op dirty bit would
#: let one gate's recompute mark another gate's stale entry clean.
_RATIO_CACHE: dict[tuple[str, int], float | None] = {}


def min_samples() -> int:
    return _registry.env_int("TDT_PERFWATCH_MIN_SAMPLES",
                             DEFAULT_MIN_SAMPLES, minimum=1)


def routing_enabled() -> bool:
    """``TDT_PERFWATCH_ROUTING=0`` stops the router consulting live
    ratios (samples still accumulate for dashboards/reports)."""
    return os.environ.get("TDT_PERFWATCH_ROUTING", "").strip() != "0"


def probe_every() -> int:
    return _registry.env_int("TDT_PERFWATCH_PROBE_EVERY",
                             DEFAULT_PROBE_EVERY, minimum=0)


def take_probe(op: str) -> bool:
    """True on every :func:`probe_every`-th policy-routed call of
    ``op``: the router then runs the fused branch anyway (recording
    its wall time) so the fused medians stay fresh and a recovered
    kernel can route back in — live routing self-corrects in BOTH
    directions (docs/resilience.md "Live ratios vs BASELINE
    floors")."""
    n = probe_every()
    if n <= 0:
        return False
    with _LOCK:
        c = _PROBE_COUNT.get(op, 0) + 1
        _PROBE_COUNT[op] = c
        return c % n == 0


def record(op: str, branch: str, bucket: str, ms: float) -> None:
    """One measured wall-time sample for ``op``'s ``branch``
    ("fused" | "xla") at ``bucket`` (``ops.common.shape_bucket``
    signature). O(1): the append marks the op dirty and the median
    pass happens lazily at the next :func:`ratio` consult (router
    policy check / :func:`stats`), which also refreshes the
    ``live_ratio`` gauge — recording must stay cheap enough for every
    eager op call under telemetry."""
    if branch not in BRANCHES:
        raise ValueError(f"branch must be one of {BRANCHES}: {branch!r}")
    with _LOCK:
        dq = _SAMPLES.get((op, branch, bucket))
        if dq is None:
            dq = _SAMPLES[(op, branch, bucket)] = collections.deque(
                maxlen=DEFAULT_MAX_SAMPLES)
        dq.append(float(ms))
        for k in [k for k in _RATIO_CACHE if k[0] == op]:
            del _RATIO_CACHE[k]
    _registry.counter(f"resilience.perfwatch.samples.{branch}").inc()


def sample_count(op: str, branch: str, bucket: str | None = None) -> int:
    with _LOCK:
        return sum(len(dq) for (o, br, b), dq in _SAMPLES.items()
                   if o == op and br == branch
                   and (bucket is None or b == bucket))


def _bucket_ratios(op: str, bucket: str | None, min_n: int) -> list:
    # Caller holds _LOCK.
    buckets = sorted({b for (o, _, b) in _SAMPLES
                      if o == op and (bucket is None or b == bucket)})
    out = []
    for b in buckets:
        fused = _SAMPLES.get((op, "fused", b))
        xla = _SAMPLES.get((op, "xla", b))
        if (fused and xla and len(fused) >= min_n
                and len(xla) >= min_n):
            mf = statistics.median(fused)
            if mf > 0:
                out.append(statistics.median(xla) / mf)
    return out


def ratio(op: str, bucket: str | None = None,
          min_n: int | None = None) -> float | None:
    """Live ``<op>_vs_xla`` ratio (>1 = fused faster, matching the
    BASELINE floor convention): median over per-bucket
    ``median(xla) / median(fused)`` ratios, each bucket qualifying
    only when both branches carry ≥ ``min_n`` samples
    (default ``TDT_PERFWATCH_MIN_SAMPLES``). None when no bucket
    qualifies — the router then falls back to the static floor.

    The op-level default path is cached: a consult with no new
    samples since the last one is a dict lookup, so the router's
    per-call policy check never pays a median pass on a quiet op."""
    if bucket is not None or min_n is not None:
        with _LOCK:
            ratios = _bucket_ratios(
                op, bucket, min_n if min_n is not None else min_samples())
        return statistics.median(ratios) if ratios else None
    mn = min_samples()
    key = (op, mn)
    with _LOCK:
        if key in _RATIO_CACHE:
            return _RATIO_CACHE[key]
        ratios = _bucket_ratios(op, None, mn)
        r = statistics.median(ratios) if ratios else None
        _RATIO_CACHE[key] = r
    if r is not None:
        _registry.gauge(f"resilience.perfwatch.{op}.live_ratio").set(
            round(r, 4))
    return r


def stats() -> dict:
    """Per-op summary for reports/dashboards: qualified live ratio
    (or None), per-branch sample counts, bucket count. Goes through
    :func:`ratio`'s cache, so scraping also refreshes the
    ``live_ratio`` gauges."""
    with _LOCK:
        ops = sorted({o for (o, _, _) in _SAMPLES})
    out = {}
    for op in ops:
        r = ratio(op)
        with _LOCK:
            out[op] = {
                "live_ratio": round(r, 4) if r is not None else None,
                "buckets": len({b for (o, _, b) in _SAMPLES
                                if o == op}),
                "fused_samples": sum(
                    len(dq) for (o, br, _), dq in _SAMPLES.items()
                    if o == op and br == "fused"),
                "xla_samples": sum(
                    len(dq) for (o, br, _), dq in _SAMPLES.items()
                    if o == op and br == "xla"),
            }
    return out


def reset() -> None:
    """Drop every rolling window, probe counter, and cached ratio
    (tests)."""
    with _LOCK:
        _SAMPLES.clear()
        _PROBE_COUNT.clear()
        _RATIO_CACHE.clear()
