"""Device-time truth: parse ``jax.profiler`` captures into per-op
timelines and MEASURED overlap metrics.

Every ``comms.<op>.overlap_pct`` number the repo publishes elsewhere is
model-derived (``tools/perf_model``) or dispatch-derived (``bench.py``'s
ingredient proxy, ``obs.trace``'s host-side chunk events) — while the
only silicon measurement on record says overlap is 0.0% against a ≥90%
north star (ROADMAP item 5). T3's thesis (PAPERS.md) is that
fine-grained overlap wins are only real when read off the DEVICE
timeline, and the reference's own evaluation is built on per-rank
merged chrome traces. ``tools/profiler.py`` has long owned the capture
side (``group_profile`` wraps ``jax.profiler``); this module is the
missing read-back side:

- **Parse** a capture — the ``*.trace.json(.gz)`` trace-event dump jax
  emits AND/OR the ``*.xplane.pb`` XPlane proto (decoded with a
  self-contained protobuf wire reader; no tensorflow import) — into a
  normalized event list (:func:`load_capture`).
- **Attribute** device/runtime execution intervals to ops via the
  ``device.<op>.<branch>`` ``TraceAnnotation`` labels the resilience
  router plants around every fused-op invocation (and the
  ``device.step`` label the serving pump sampler plants around a
  profiled pump iteration): :func:`summarize`. Execution events are
  classified compute vs comm by name (collectives / DMA / copy vs
  everything else), and interval arithmetic inside each op window
  yields the MEASURED tier of the overlap accounting
  (docs/perf.md "Overlap accounting"):
  ``device.<op>.{total,compute,comm}_ms``,
  ``comms.<op>.overlap_pct_measured``,
  ``comms.<op>.exposed_comm_ms_measured``. Execution time under no
  label lands in ``unlabeled_ms`` (``device.unlabeled_ms``) — the
  annotation-coverage pass (``tdt-check``) keeps that bucket honest.
- **Publish** the summary as gauges, plus a model-vs-measured drift
  gauge ``comms.<op>.overlap_drift_pct`` against the dispatch-time
  ``comms.<op>.overlap_pct`` the cost model set (:func:`publish`).
- **Sample serving continuously** (:class:`PumpSampler`):
  ``TDT_DEVPROF_EVERY=N`` profiles one pump iteration every N, parses
  ASYNC off the pump thread, and feeds the ``device.step.*``
  attribution gauges — plus the decode-step-only sub-windows the
  scheduler brackets per decode path (``device.step.mega.*`` /
  ``device.step.plain.*``, :func:`step_label`), so the auto
  decode-path policy (models/engine.py) arbitrates on unblended,
  admission-free numbers; ``TDT_DEVPROF_ON_BREACH=N`` arms a bounded
  capture of the next N pump iterations when the flight recorder
  dumps (SLO breach, watchdog trip, breaker open) — the postmortem
  then includes what the chip actually did, not just host events.
  Captures start at iteration boundaries in the pump thread, never
  while any scheduler lock is held, and arming is rate-limited like
  flight dumps.

Labels under jit: the router's annotation wraps the PYTHON invocation,
so for a jitted call it brackets trace time (like the ``comms.*``
counters). Measured per-op attribution therefore profiles EAGER
dispatches — exactly how ``bench.py`` / ``tpu_smoke.py`` use it — while
the pump sampler attributes whole iterations (``device.step``), which
is correct for jitted programs too because the label wraps the
blocking call. docs/perf.md "Overlap accounting" spells out the tiers.

See tools/profile_export.py for the CLI (validate / summary / chrome
conversion) and ``tools/trace_export.py --merge-profile`` for the
one-clock overlay into a host Perfetto dump.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import re
import tempfile
import threading
import time
import weakref

from triton_dist_tpu.obs import registry as _registry

__all__ = [
    "PumpSampler", "STEP_LABEL", "arm", "armed_reason",
    "devprof_dir", "find_captures", "last_profile", "load_capture",
    "op_label", "parse_capture", "parse_xplane", "publish", "reset",
    "sampler_active", "stats", "step_label", "summarize", "wait_idle",
]

#: Annotation label the serving pump sampler plants around a profiled
#: pump iteration (the shared decode step + that iteration's
#: admissions) — the whole-iteration ``device.step.*`` gauges. INSIDE
#: a profiled iteration the scheduler additionally brackets the shared
#: decode step alone with the per-path variant (:func:`step_label` —
#: ``device.step.mega`` / ``device.step.plain``), so the decode paths
#: attribute separately and WITHOUT admission/prefill contamination —
#: the gauges ``Engine(decode_path="auto")`` arbitrates on.
STEP_LABEL = "device.step"

#: Label prefix every op-attribution annotation shares. The resilience
#: router plants ``device.<op>.<branch>`` around each @resilient
#: invocation; anything under no such label is "unlabeled" device time.
LABEL_PREFIX = "device."

#: Minimum spacing between consumed breach-arms (like
#: ``obs.flight.MIN_INTERVAL_S`` — a flapping breaker must not chain
#: captures back to back).
ARM_MIN_INTERVAL_S = 30.0


def op_label(op: str, branch: str = "fused") -> str:
    """The annotation label for one op invocation. The parser keys on
    the ``device.<op>`` prefix; ``branch`` (``fused``/``xla``) rides in
    the third segment so a Perfetto reader can tell a fallback's
    window from a fused one."""
    return f"{LABEL_PREFIX}{op}.{branch}"


def step_label(kind: str | None = None) -> str:
    """The step annotation label: bare :data:`STEP_LABEL` for the
    whole-iteration window, or the per-path variant
    (``device.step.mega`` / ``device.step.plain``) the scheduler
    brackets the SHARED DECODE STEP alone with — decode-step device
    time only, no admission/prefill contamination. The per-path
    segment is load-bearing: the parser keeps it (:func:`_label_op`),
    so the two decode paths attribute into separate
    ``device.step.<kind>.*`` gauges and the auto decode-path policy
    never reads a blend (annotation-coverage pass,
    docs/analysis.md)."""
    return f"{STEP_LABEL}.{kind}" if kind else STEP_LABEL


def _label_op(tail: str) -> str:
    """Attribution key for one ``device.*`` label tail. Router labels
    are ``device.<op>.<branch>`` → the key is ``<op>`` (branches
    blend into one op window); STEP labels keep their decode-path
    segment (``step.mega`` vs ``step.plain`` must NOT blend — the
    auto decode-path policy arbitrates on exactly these gauges)."""
    parts = tail.split(".")
    if parts[0] == "step" and len(parts) > 1 and parts[1]:
        return parts[0] + "." + parts[1]
    return parts[0]


def devprof_dir() -> str:
    """Where device-profile captures land (``TDT_DEVPROF_DIR``)."""
    return (os.environ.get("TDT_DEVPROF_DIR", "").strip()
            or os.path.join(tempfile.gettempdir(), "tdt_devprof"))


# ---------------------------------------------------------------------------
# Capture discovery + loading.
# ---------------------------------------------------------------------------

#: jax.profiler writes <dir>/plugins/profile/<run>/<host>.{trace.json.gz,
#: xplane.pb}; group_profile nests that under <out>/<name>/host<i>/.
_TRACE_SUFFIXES = (".trace.json.gz", ".trace.json", ".json.gz", ".json")
_XPLANE_SUFFIX = ".xplane.pb"


def find_captures(root: str) -> list[str]:
    """Profile run directories under ``root`` (newest last). ``root``
    may be a ``group_profile`` artifact dir, its parent, or already a
    ``plugins/profile/<run>`` dir."""
    root = str(root)
    if not os.path.isdir(root):
        return []
    runs = set()
    for pat in ("", "*/", "*/*/", "*/*/*/"):
        for d in glob.glob(os.path.join(root, pat + "plugins/profile/*")):
            if os.path.isdir(d):
                runs.add(os.path.abspath(d))
    if not runs and _capture_files(root):
        runs.add(os.path.abspath(root))
    return sorted(runs, key=lambda d: (os.path.getmtime(d), d))


def _capture_files(run_dir: str) -> list[str]:
    out = []
    for f in sorted(os.listdir(run_dir)):
        p = os.path.join(run_dir, f)
        if os.path.isfile(p) and (f.endswith(_TRACE_SUFFIXES)
                                  or f.endswith(_XPLANE_SUFFIX)):
            out.append(p)
    return out


def capture_meta(path: str) -> dict:
    """The ``tdt_capture.json`` anchor ``tools/profiler.group_profile``
    writes next to a capture (wall-clock start, host, name) — the
    one-clock handle ``trace_export --merge-profile`` aligns on.
    Empty dict when absent (foreign captures overlay un-anchored)."""
    d = str(path)
    if os.path.isfile(d):
        d = os.path.dirname(d)
    for _ in range(4):   # run dir → .../plugins/profile → host dir
        meta = os.path.join(d, "tdt_capture.json")
        if os.path.isfile(meta):
            try:
                with open(meta) as f:
                    return json.load(f)
            except (OSError, ValueError):
                return {}
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return {}


def load_capture(path: str) -> list[dict]:
    """Normalized events from a capture path (a run dir, a
    ``group_profile`` artifact dir, or a single trace/xplane file).

    Each event is ``{"name", "ts_us", "dur_us", "pid", "tid",
    "device": bool}`` — ``device`` marks events from a ``/device:*``
    plane/process (TPU/GPU timelines). Raises ``ValueError`` when the
    path holds no parseable capture (the ``profile_export --validate``
    rc!=0 contract)."""
    path = str(path)
    files: list[str] = []
    if os.path.isfile(path):
        files = [path]
    else:
        runs = find_captures(path)
        if runs:
            files = _capture_files(runs[-1])   # newest run
    if not files:
        raise ValueError(f"no profile capture found under {path!r}")
    # Prefer the trace-event JSON (it carries host-side python events
    # the xplane groups differently); fall back to the xplane proto.
    ordered = ([f for f in files if not f.endswith(_XPLANE_SUFFIX)]
               + [f for f in files if f.endswith(_XPLANE_SUFFIX)])
    last_exc: Exception | None = None
    for f in ordered:
        try:
            if f.endswith(_XPLANE_SUFFIX):
                with open(f, "rb") as fh:
                    return parse_xplane(fh.read())
            return _load_trace_json(f)
        except Exception as e:  # noqa: BLE001 — try the next artifact
            last_exc = e
    raise ValueError(
        f"unparseable profile capture under {path!r}: {last_exc!r}")


def _load_trace_json(path: str) -> list[dict]:
    if path.endswith(".gz"):
        with gzip.open(path) as f:
            data = json.loads(f.read().decode("utf-8", "replace"))
    else:
        with open(path) as f:
            data = json.load(f)
    evs = data.get("traceEvents")
    if not isinstance(evs, list):
        raise ValueError(f"{path}: traceEvents missing")
    device_pids = set()
    for e in evs:
        if (e.get("ph") == "M" and e.get("name") == "process_name"
                and str((e.get("args") or {}).get("name", ""))
                .startswith("/device:")):
            device_pids.add(e.get("pid"))
    out = []
    for e in evs:
        if e.get("ph") != "X":
            continue
        ts, dur = e.get("ts"), e.get("dur", 0.0)
        if not isinstance(ts, (int, float)):
            continue
        out.append({"name": str(e.get("name", "")), "ts_us": float(ts),
                    "dur_us": float(dur or 0.0),
                    "pid": e.get("pid", 0), "tid": e.get("tid", 0),
                    "device": e.get("pid") in device_pids})
    if not out:
        raise ValueError(f"{path}: no complete events")
    return out


# ---------------------------------------------------------------------------
# XPlane proto wire parser (self-contained; schema:
# tensorflow/core/profiler/protobuf/xplane.proto).
# ---------------------------------------------------------------------------

def _varint(b: bytes, i: int) -> tuple[int, int]:
    x = s = 0
    while True:
        c = b[i]
        i += 1
        x |= (c & 0x7F) << s
        if not c & 0x80:
            return x, i
        s += 7


def _fields(b: bytes):
    """(field_number, wire_type, value) triples of one message."""
    i, end = 0, len(b)
    while i < end:
        tag, i = _varint(b, i)
        fn, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _varint(b, i)
        elif wt == 2:
            ln, i = _varint(b, i)
            v = b[i:i + ln]
            i += ln
        elif wt == 5:
            v, i = b[i:i + 4], i + 4
        elif wt == 1:
            v, i = b[i:i + 8], i + 8
        else:
            raise ValueError(f"unsupported protobuf wire type {wt}")
        yield fn, wt, v


def parse_xplane(data: bytes) -> list[dict]:
    """Decode an XSpace proto into the same normalized event list as
    the trace-event JSON loader. Planes become pids (hash of name),
    lines become tids; ``ts_us`` = line ``timestamp_ns``/1e3 + event
    ``offset_ps``/1e6 — the same profile-session-relative clock the
    JSON dump uses, so both sources anchor identically."""
    out: list[dict] = []
    pid = 0
    for fn, _wt, v in _fields(data):
        if fn != 1:          # XSpace.planes
            continue
        pid += 1
        plane_name = ""
        lines = []
        event_names: dict[int, str] = {}
        for fn2, _wt2, v2 in _fields(v):
            if fn2 == 2:     # XPlane.name
                plane_name = v2.decode("utf-8", "replace")
            elif fn2 == 3:   # XPlane.lines
                lines.append(v2)
            elif fn2 == 4:   # XPlane.event_metadata (map<int64, XEventMetadata>)
                mid, meta = None, b""
                for fn3, _wt3, v3 in _fields(v2):
                    if fn3 == 1:
                        mid = v3
                    elif fn3 == 2:
                        meta = v3
                if mid is not None:
                    name = ""
                    for fn4, _wt4, v4 in _fields(meta):
                        if fn4 == 2:    # XEventMetadata.name
                            name = v4.decode("utf-8", "replace")
                    event_names[mid] = name
        device = plane_name.startswith("/device:")
        for tid, line in enumerate(lines, start=1):
            ts_ns = 0
            events = []
            for fn3, _wt3, v3 in _fields(line):
                if fn3 == 3:            # XLine.timestamp_ns
                    ts_ns = v3
                elif fn3 == 4:          # XLine.events
                    events.append(v3)
            base_us = ts_ns / 1e3
            for ev in events:
                mid = off_ps = dur_ps = 0
                for fn4, _wt4, v4 in _fields(ev):
                    if fn4 == 1:
                        mid = v4
                    elif fn4 == 2:      # offset_ps
                        off_ps = v4
                    elif fn4 == 3:      # duration_ps
                        dur_ps = v4
                out.append({"name": event_names.get(mid, f"#{mid}"),
                            "ts_us": base_us + off_ps / 1e6,
                            "dur_us": dur_ps / 1e6,
                            "pid": pid, "tid": tid, "device": device})
    if not out:
        raise ValueError("xplane capture holds no events")
    return out


# ---------------------------------------------------------------------------
# Attribution: label windows x classified execution intervals.
# ---------------------------------------------------------------------------

#: Execution events on the HOST timeline that represent program
#: execution (the CPU backend has no device plane; TfrtCpuClient
#: executes inline). Device-plane events count wholesale.
_EXEC_PAT = re.compile(
    r"TfrtCpuExecutable::Execute\b|ThunkExecutor::Execute"
    r"|ExecuteReplicated|PjRtStreamExecutor.*Execute")

#: Communication classification, by event name: XLA collective /
#: copy / DMA op families on a device plane. Everything else executed
#: on-device is compute.
_COMM_PAT = re.compile(
    r"all[-_]?gather|all[-_]?reduce|reduce[-_]?scatter"
    r"|collective[-_]?permute|all[-_]?to[-_]?all|copy[-_]?(start|done)"
    r"|\bsend\b|\brecv\b|dma|infeed|outfeed|cross[-_]?replica",
    re.IGNORECASE)


def _union(ivs: list[tuple[float, float]]) -> list[tuple[float, float]]:
    merged: list[list[float]] = []
    for a, b in sorted(ivs):
        if merged and a <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], b)
        else:
            merged.append([a, b])
    return [(a, b) for a, b in merged]


def _union_len(ivs) -> float:
    return sum(b - a for a, b in _union(ivs))


def _clip(ivs, windows) -> list[tuple[float, float]]:
    """Intervals ∩ union(windows)."""
    out = []
    windows = _union(windows)
    for a, b in _union(ivs):
        for c, d in windows:
            if d <= a:
                continue
            if c >= b:
                break
            out.append((max(a, c), min(b, d)))
    return out


def _intersect_len(xs, ys) -> float:
    return _union_len(_clip(xs, ys))


def summarize(events: list[dict]) -> dict:
    """Attribute execution intervals to op label windows.

    Returns ``{"ops": {op: {"total_ms", "compute_ms", "comm_ms",
    "exposed_comm_ms", "overlap_pct", "n_events"}}, "unlabeled_ms",
    "n_events", "window_ms"}``. ``overlap_pct`` is
    ``100·(1 − exposed/comm)`` over the MEASURED interval geometry —
    ``None`` when the window held no comm events (a world-1 / CPU run
    has nothing to overlap; callers publish an explicit
    ``overlap_requires_chip`` marker instead of a fiction)."""
    windows: dict[str, list[tuple[float, float]]] = {}
    exec_iv: list[tuple[float, float]] = []
    comm_iv: list[tuple[float, float]] = []
    n_exec = 0
    t_lo, t_hi = float("inf"), float("-inf")
    # Host-side Execute spans stand in for device work ONLY when the
    # capture holds no device plane (the CPU backend executes inline).
    # On a TPU capture they merely bracket dispatch: counting one as
    # compute would let it "cover" device comm intervals and inflate
    # the measured overlap — the exact fiction this tier exists to
    # retire.
    has_device_plane = any(e["device"] for e in events)
    for e in events:
        name, ts, dur = e["name"], e["ts_us"], e["dur_us"]
        t_lo, t_hi = min(t_lo, ts), max(t_hi, ts + dur)
        if name.startswith(LABEL_PREFIX):
            op = _label_op(name[len(LABEL_PREFIX):])
            if op:
                windows.setdefault(op, []).append((ts, ts + dur))
            continue
        is_exec = e["device"] or (not has_device_plane
                                  and _EXEC_PAT.search(name))
        if not is_exec:
            continue
        n_exec += 1
        iv = (ts, ts + dur)
        if _COMM_PAT.search(name):
            comm_iv.append(iv)
        else:
            exec_iv.append(iv)
    ops: dict[str, dict] = {}
    for op, wins in sorted(windows.items()):
        compute = _clip(exec_iv, wins)
        comm = _clip(comm_iv, wins)
        comm_us = _union_len(comm)
        covered_us = _intersect_len(comm, compute)
        exposed_us = max(comm_us - covered_us, 0.0)
        ops[op] = {
            "total_ms": round(_union_len(wins) / 1e3, 6),
            "compute_ms": round(_union_len(compute) / 1e3, 6),
            "comm_ms": round(comm_us / 1e3, 6),
            "exposed_comm_ms": round(exposed_us / 1e3, 6),
            "overlap_pct": (round(100.0 * (1 - exposed_us / comm_us), 2)
                            if comm_us > 0 else None),
            "n_events": len(compute) + len(comm),
            # Annotation windows in the capture: a multi-iteration
            # breach capture unions N step windows into total_ms, so
            # per-window consumers (the auto decode-path policy)
            # normalize by this count instead of comparing unions of
            # different spans.
            "n_windows": len(wins),
        }
    all_windows = [iv for wins in windows.values() for iv in wins]
    unlabeled_us = (_union_len(exec_iv + comm_iv)
                    - _intersect_len(exec_iv + comm_iv, all_windows)
                    if (exec_iv or comm_iv) else 0.0)
    return {"ops": ops,
            "unlabeled_ms": round(max(unlabeled_us, 0.0) / 1e3, 6),
            "n_events": n_exec,
            "window_ms": (round((t_hi - t_lo) / 1e3, 6)
                          if t_hi > t_lo else 0.0)}


def parse_capture(path: str) -> dict:
    """Load + summarize one capture; the summary additionally carries
    ``source`` (the path) and the capture's wall-clock ``meta``."""
    s = summarize(load_capture(path))
    s["source"] = str(path)
    s["meta"] = capture_meta(path)
    return s


# ---------------------------------------------------------------------------
# Publication: summary → gauges (+ model-vs-measured drift).
# ---------------------------------------------------------------------------

def publish(summary: dict) -> None:
    """Set the ``device.*`` / ``*_measured`` gauges from a parsed
    summary, and — where the dispatch-time model gauge exists — the
    ``comms.<op>.overlap_drift_pct`` drift (measured − modeled; a
    large negative drift means the cost model promises overlap the
    chip does not deliver)."""
    reg = _registry.get_registry()
    snap_gauges = reg.snapshot().get("gauges", {})
    for op, m in summary.get("ops", {}).items():
        reg.gauge(f"device.{op}.total_ms").set(m["total_ms"])
        reg.gauge(f"device.{op}.compute_ms").set(m["compute_ms"])
        reg.gauge(f"device.{op}.comm_ms").set(m["comm_ms"])
        reg.gauge(f"device.{op}.windows").set(m.get("n_windows", 1))
        if m["overlap_pct"] is not None:
            reg.gauge(f"comms.{op}.overlap_pct_measured").set(
                m["overlap_pct"])
            reg.gauge(f"comms.{op}.exposed_comm_ms_measured").set(
                m["exposed_comm_ms"])
            modeled = snap_gauges.get(f"comms.{op}.overlap_pct")
            if modeled is not None:
                reg.gauge(f"comms.{op}.overlap_drift_pct").set(
                    round(m["overlap_pct"] - modeled, 2))
    reg.gauge("device.unlabeled_ms").set(summary.get("unlabeled_ms", 0.0))
    reg.counter("profile.parsed").inc()


# ---------------------------------------------------------------------------
# Breach arming (consumed by the pump sampler).
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_ARMED: str | None = None
_LAST_ARM_CONSUMED = 0.0
_LAST_PROFILE: dict | None = None
_PARSE_THREADS: list[threading.Thread] = []

#: Live samplers configured to consume breach-arms. arm() is a no-op
#: with no consumer: otherwise a watchdog trip in a sampler-less
#: process would set an "armed" flag nothing ever clears, and every
#: later metrics scrape would advertise a capture that can never
#: happen.
_CONSUMERS = weakref.WeakSet()

#: ALL live samplers (any trigger config). The auto decode-path
#: policy's exploration probe gates on this — running the other
#: decode path "so a sampler can measure it" is pure waste in a
#: process where no sampler can ever capture (same consumer-gating
#: rationale as :func:`arm`).
_SAMPLERS = weakref.WeakSet()


def sampler_active() -> bool:
    """Is any :class:`PumpSampler` alive in this process (i.e. could a
    pump iteration ever be captured into ``device.step.*`` gauges)?"""
    return any(True for _ in _SAMPLERS)


def arm(reason: str) -> None:
    """Request a device-profile capture of the next pump iterations.
    Called by ``obs.flight`` after each flight dump (SLO breach,
    watchdog trip, breaker open, ...); consumed by a
    :class:`PumpSampler` with a breach window configured. Cheap and
    lock-light: arming happens on failure paths."""
    global _ARMED
    if not any(True for _ in _CONSUMERS):
        return
    with _LOCK:
        if _ARMED is None:
            _ARMED = reason


def armed_reason() -> str | None:
    with _LOCK:
        return _ARMED


def _consume_arm() -> str | None:
    """Take the armed reason if the rate limit allows (one capture per
    :data:`ARM_MIN_INTERVAL_S`, like flight dumps per reason)."""
    global _ARMED, _LAST_ARM_CONSUMED
    with _LOCK:
        if _ARMED is None:
            return None
        now = time.monotonic()
        if now - _LAST_ARM_CONSUMED < ARM_MIN_INTERVAL_S:
            _ARMED = None           # drop: inside the rate window
            return None
        reason, _ARMED = _ARMED, None
        _LAST_ARM_CONSUMED = now
        return reason


def last_profile() -> dict | None:
    """``{"path", "reason", "ts", "summary"}`` of the newest parsed
    capture, or None."""
    with _LOCK:
        return dict(_LAST_PROFILE) if _LAST_PROFILE else None


def _set_last_profile(rec: dict) -> None:
    global _LAST_PROFILE
    with _LOCK:
        _LAST_PROFILE = rec


def stats() -> dict:
    """Devprof state for the server metrics payload / tools/report.py
    (the ``devprof`` key next to ``trace``)."""
    out: dict = {"armed": armed_reason()}
    last = last_profile()
    if last is not None:
        out["last_profile"] = last["path"]
        out["last_reason"] = last["reason"]
        ops = (last.get("summary") or {}).get("ops", {})
        if ops:
            out["ops"] = sorted(ops)
    return out


def wait_idle(timeout: float = 10.0) -> bool:
    """Join outstanding async parse threads (tests / shutdown)."""
    deadline = time.monotonic() + timeout
    with _LOCK:
        threads = list(_PARSE_THREADS)
    for t in threads:
        t.join(max(deadline - time.monotonic(), 0.0))
    with _LOCK:
        _PARSE_THREADS[:] = [t for t in _PARSE_THREADS if t.is_alive()]
        return not _PARSE_THREADS


def reset() -> None:
    """Test isolation: drop armed/last-profile state (parse threads
    are joined best-effort first)."""
    global _ARMED, _LAST_PROFILE, _LAST_ARM_CONSUMED
    wait_idle(timeout=5.0)
    with _LOCK:
        _ARMED = None
        _LAST_PROFILE = None
        _LAST_ARM_CONSUMED = 0.0


# ---------------------------------------------------------------------------
# The serving pump sampler.
# ---------------------------------------------------------------------------

class _ActiveCapture:
    """One in-flight multi-iteration capture (sampler-internal)."""

    __slots__ = ("reason", "remaining", "stack", "path", "t0")

    def __init__(self, reason: str, remaining: int, stack, path, t0):
        self.reason = reason
        self.remaining = remaining
        self.stack = stack
        self.path = path
        self.t0 = t0


class PumpSampler:
    """Low-overhead device-profile sampling for the scheduler pump.

    The pump wraps each iteration's ENGINE WORK (admissions + prefill
    slices + the shared decode step — everything outside the condition
    lock) in :meth:`iteration`. While no capture is active that is a
    null context; when one starts, the iteration runs under the
    :data:`STEP_LABEL` annotation inside a ``group_profile`` window
    that spans ``n`` consecutive iterations, then parsing and gauge
    publication happen on a detached daemon thread (``sync=True`` in
    tests parses inline).

    Two trigger paths, both iteration-boundary only (never mid-lock):

    - **Continuous** (``TDT_DEVPROF_EVERY=N``): every Nth working
      iteration captures one iteration.
    - **Breach-armed** (``TDT_DEVPROF_ON_BREACH=N``): a flight dump
      arms the module (:func:`arm`); the next working iteration starts
      a capture of N iterations. Rate-limited
      (:data:`ARM_MIN_INTERVAL_S`).
    """

    def __init__(self, every: int = 0, on_breach: int = 0,
                 out_dir: str | None = None, sync: bool = False):
        if every < 0 or on_breach < 0:
            raise ValueError("sampler windows must be >= 0")
        self.every = every
        self.on_breach = on_breach
        self.out_dir = out_dir or devprof_dir()
        self.sync = sync
        self._iter = 0
        self._n_captures = 0
        self._cap: _ActiveCapture | None = None
        _SAMPLERS.add(self)
        if on_breach > 0:
            _CONSUMERS.add(self)

    @classmethod
    def from_env(cls) -> "PumpSampler | None":
        """Sampler per the env knobs, or None when both are off (the
        scheduler then pays nothing per iteration)."""
        every = _registry.env_int("TDT_DEVPROF_EVERY", 0, minimum=0)
        on_breach = _registry.env_int("TDT_DEVPROF_ON_BREACH", 0,
                                      minimum=0)
        if every <= 0 and on_breach <= 0:
            return None
        return cls(every=every, on_breach=on_breach)

    def _maybe_start(self) -> None:
        if self._cap is not None:       # a multi-iteration capture is open
            return
        reason: str | None = None
        n = 1
        if self.on_breach > 0:
            armed = _consume_arm()
            if armed is not None:
                reason, n = f"breach_{armed}", self.on_breach
        if reason is None and self.every > 0:
            self._iter += 1
            if self._iter % self.every == 0:
                reason, n = "sampler", 1
        if reason is None:
            return
        try:
            from triton_dist_tpu.tools.profiler import group_profile
            stack = contextlib.ExitStack()
            self._n_captures += 1
            cap_path = stack.enter_context(group_profile(
                f"pump_{self._n_captures}", self.out_dir))
            self._cap = _ActiveCapture(reason, n, stack, str(cap_path),
                                time.perf_counter())
        except Exception:  # noqa: BLE001 — sampling must never hurt serving
            self._cap = None

    def _finish(self) -> None:
        cap, self._cap = self._cap, None
        if cap is None:
            return
        try:
            cap.stack.close()       # stops the jax profiler session
        except Exception:  # noqa: BLE001
            _registry.counter("profile.parse_errors").inc()
            return
        if self.sync:
            _parse_and_publish(cap.path, cap.reason)
            return
        t = threading.Thread(target=_parse_and_publish,
                             args=(cap.path, cap.reason),
                             name="tdt-devprof-parse", daemon=True)
        with _LOCK:
            # Prune finished parse threads as we go: production never
            # calls wait_idle(), and a long-lived server sampling
            # every Nth iteration must not accumulate one dead Thread
            # object per capture forever.
            _PARSE_THREADS[:] = [x for x in _PARSE_THREADS
                                 if x.is_alive()]
            _PARSE_THREADS.append(t)
        t.start()

    @property
    def capturing(self) -> bool:
        """A capture is open right now — the scheduler consults this
        to bracket the shared decode step with the per-path
        :func:`step_label` only while it would land in a capture."""
        return self._cap is not None

    @contextlib.contextmanager
    def iteration(self):
        """Wrap one pump iteration's engine work. Starts/extends/ends
        captures at the boundaries; pump-thread only."""
        self._maybe_start()
        cap = self._cap
        if cap is None:
            yield
            return
        try:
            from triton_dist_tpu.tools.profiler import annotate
            with annotate(STEP_LABEL):
                yield
        finally:
            cap.remaining -= 1
            if cap.remaining <= 0:
                self._finish()

    def close(self) -> None:
        """End any open capture (scheduler stop mid-window)."""
        if self._cap is not None:
            self._cap.remaining = 0
            self._finish()


def _parse_and_publish(path: str, reason: str) -> None:
    """Off-pump parse: capture → summary → gauges → last-profile
    record. Never raises (counts ``profile.parse_errors``)."""
    try:
        summary = parse_capture(path)
        publish(summary)
        _set_last_profile({"path": path, "reason": reason,
                           "ts": time.time(), "summary": summary})
    except Exception:  # noqa: BLE001 — observation only
        _registry.counter("profile.parse_errors").inc()
