"""Structured event tracing: per-thread ring buffers → Perfetto timelines.

The reference treats the timeline as its primary observability artifact:
``group_profile`` writes per-rank chrome traces and merges them on
rank 0 via ``gather_object`` (python/triton_dist/utils.py:505-592), and
``launch_metadata`` annotates every kernel launch onto it. ``obs``'s
metrics layer (PR 1) aggregates *numbers*; this module restores the
*order* — who ran what, when, on which thread — as structured events
that export to Chrome trace-event / Perfetto JSON
(``tools/trace_export.py``) without attaching a profiler.

Design:

- **Events** are compact tuples ``(ph, ts_us, dur_us, name, cat,
  trace_id, args)`` with the Chrome trace-event phases ``B``/``E``
  (begin/end), ``X`` (complete), ``i`` (instant). Categories are the
  fixed set :data:`CATEGORIES` — ``op`` (kernel/op entries), ``comms``
  (per-chunk ring-schedule events), ``engine``, ``serving``,
  ``resilience``.
- **Per-thread ring buffers.** Each thread appends to its own
  fixed-capacity ring (``TDT_TRACE_RING`` events, default 32768) with
  no lock on the append path — the owning thread is the only writer,
  so the hot path is a list store + integer bump under the GIL.
  When the ring is full the OLDEST event is overwritten and
  ``dropped`` increments: the buffer always holds the most recent
  window, which is exactly what a flight recorder wants
  (``obs.flight``). Named side tracks (the ring-schedule comm/compute
  timelines) may have several writers and append under a per-ring
  lock — they are cold paths. Finished threads' rings are kept as a
  bounded tail (:data:`Tracer.MAX_DEAD_RINGS`) so a
  thread-per-connection server cannot leak one ring per request.
- **Trace IDs** propagate through a thread-local: the server binds one
  per request (:func:`bind`), and every event emitted on that thread —
  engine spans, op instants, resilience fallbacks — carries it, so one
  request's prefill→decode→reply path filters to a single story in
  the exported timeline.
- **Disabled by default at zero cost.** The module-level tracer starts
  as ``None``; every emit helper begins with an ``is None`` check.
  :func:`enable` switches it on (``TDT_TRACE=1`` makes ``obs.enable``
  do so; the ``ModelServer`` enables it by default — the flight
  recorder posture — unless ``TDT_TRACE=0``).

Timestamps are wall-clock microseconds with ``perf_counter``
precision (an epoch anchor is taken once at tracer creation), so
per-host traces from the same boot epoch line up when merged rank-0
side (``tools/trace_export.gather_to_chrome``).

See docs/observability.md for the event schema and knob catalog.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
import uuid

__all__ = [
    "CATEGORIES", "Tracer", "bind", "begin", "collect", "complete",
    "current_trace_id", "disable", "emit", "enable", "enabled", "end",
    "env_enabled", "get_tracer", "instant", "new_trace_id", "now_us",
    "perf_to_us", "reset", "ring_schedule_events", "span", "stats",
]

#: The recognized event categories (docs/observability.md "Tracing").
CATEGORIES = ("op", "comms", "engine", "serving", "resilience")

#: Default per-ring capacity (events). At ~100 B/event the default
#: bounds each thread's recorder at a few MB.
DEFAULT_RING_CAPACITY = 32768


def _env_int(name: str, default: int) -> int:
    # Lazy: registry imports this module at load, so the shared parser
    # is reached at call time, when both modules exist.
    from triton_dist_tpu.obs.registry import env_int
    return env_int(name, default)


def env_enabled(default: bool = False) -> bool:
    """``TDT_TRACE`` as a boolean; unset → ``default``."""
    v = os.environ.get("TDT_TRACE")
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


class _Ring:
    """Fixed-capacity overwrite-oldest event buffer.

    Per-thread rings have exactly ONE writer (the owning thread) and
    append with no lock — a list store plus integer bumps under the
    GIL. Named side tracks can be written from several threads (an
    abandoned watchdog worker unwedging mid-``record_overlap`` races
    the current case's thread), so they carry a ``lock`` and append
    under it — they are cold paths.

    Snapshots from other threads read the list without a lock: a read
    racing the owner on a WRAPPED ring can observe freshly-overwritten
    (newest) events in the oldest slots, i.e. out of timestamp order —
    :meth:`Tracer.collect` re-sorts each track by timestamp, restoring
    the true order (per-writer timestamps are monotonic). The backing
    list grows lazily up to ``cap`` so a thread that emits three
    events does not pay for 32768 slots.
    """

    __slots__ = ("name", "buf", "cap", "total", "dropped", "owner",
                 "lock")

    def __init__(self, name: str, cap: int, owner=None,
                 lock: threading.Lock | None = None):
        self.name = name
        self.buf: list = []
        self.cap = cap
        self.total = 0          # events ever appended
        self.dropped = 0        # oldest events overwritten
        self.owner = owner      # weakref to the owning thread, if any
        self.lock = lock        # multi-writer (named-track) rings only

    def append(self, ev) -> None:
        if self.lock is not None:
            with self.lock:
                self._append(ev)
        else:
            self._append(ev)

    def _append(self, ev) -> None:
        i = self.total
        if i < self.cap:
            self.buf.append(ev)
        else:
            self.dropped += 1
            self.buf[i % self.cap] = ev
        self.total = i + 1

    def events(self) -> list:
        """Buffered events, oldest-slot first (see class docstring for
        the torn-read caveat the caller's ts-sort absorbs)."""
        n, cap = self.total, self.cap
        if n <= cap:
            return [e for e in self.buf[:n] if e is not None]
        h = n % cap
        return [e for e in self.buf[h:] + self.buf[:h] if e is not None]

    def owner_dead(self) -> bool:
        return self.owner is not None and self.owner() is None


class Tracer:
    """Registry of per-thread (and named) event rings."""

    def __init__(self, capacity: int | None = None):
        self.capacity = capacity if capacity is not None else _env_int(
            "TDT_TRACE_RING", DEFAULT_RING_CAPACITY)
        if self.capacity <= 0:
            raise ValueError(
                f"trace ring capacity must be positive: {self.capacity}")
        self._lock = threading.Lock()
        self._rings: dict[str, _Ring] = {}
        self._tls = threading.local()
        # Wall-clock anchor for perf_counter: epoch micros with
        # monotonic precision (merged per-host traces line up).
        self._epoch = time.time() - time.perf_counter()

    # -- clocks ------------------------------------------------------------
    def now_us(self) -> float:
        return (time.perf_counter() + self._epoch) * 1e6

    def perf_to_us(self, t_perf: float) -> float:
        """Convert a ``time.perf_counter()`` reading to trace micros."""
        return (t_perf + self._epoch) * 1e6

    # -- rings -------------------------------------------------------------

    #: Dead-thread rings retained beyond this many are evicted
    #: (oldest-registered first). A server handling each connection on
    #: a fresh thread (ThreadingTCPServer) would otherwise leak one
    #: ring per connection forever; keeping a bounded tail of finished
    #: threads' rings preserves the flight-recorder window without
    #: unbounded growth.
    MAX_DEAD_RINGS = 64

    def ring(self, name: str, owner=None) -> _Ring:
        """Named track ring (cold paths: ring-schedule timelines).
        Ownerless rings may be written from several threads and get a
        per-ring append lock; per-thread rings stay lock-free."""
        with self._lock:
            r = self._rings.get(name)
            if r is None:
                r = self._rings[name] = _Ring(
                    name, self.capacity, owner,
                    lock=None if owner is not None
                    else threading.Lock())
                if owner is not None:
                    self._prune_dead_rings()
            elif owner is not None and r.owner_dead():
                # A new thread landed on a finished thread's key (the
                # OS reuses thread idents): adopt the ring so pruning
                # cannot drop a buffer that is being written to.
                r.owner = owner
            return r

    def _prune_dead_rings(self) -> None:
        # Caller holds the lock. Dict order = registration order, so
        # the oldest finished threads' rings go first.
        dead = [n for n, r in self._rings.items() if r.owner_dead()]
        for n in dead[:max(len(dead) - self.MAX_DEAD_RINGS, 0)]:
            del self._rings[n]

    def thread_ring(self) -> _Ring:
        r = getattr(self._tls, "ring", None)
        if r is None:
            import weakref
            t = threading.current_thread()
            r = self.ring(f"{t.name}-{t.ident}", owner=weakref.ref(t))
            self._tls.ring = r
        return r

    # -- emit --------------------------------------------------------------
    def emit(self, ph: str, name: str, cat: str = "op", *,
             ts_us: float | None = None, dur_us: float | None = None,
             args: dict | None = None, track: str | None = None,
             trace_id: str | None = None) -> None:
        if trace_id is None:
            trace_id = current_trace_id()
        ev = (ph, self.now_us() if ts_us is None else ts_us, dur_us,
              name, cat, trace_id, args)
        (self.ring(track) if track else self.thread_ring()).append(ev)

    # -- snapshots ---------------------------------------------------------
    def collect(self, last_s: float | None = None) -> dict:
        """All buffered events as ``{"tracks": {name: [event, ...]},
        "dropped_total": int, "events_total": int}`` — ordered by
        timestamp per track, optionally trimmed to the trailing
        ``last_s`` seconds (the flight-recorder window).

        The per-track ts sort restores true order when a snapshot
        races the owning thread on a wrapped ring (the torn read can
        surface freshly-overwritten newest events in the oldest
        slots); per-writer clocks are monotonic so the sort is a no-op
        on quiescent rings."""
        with self._lock:
            rings = list(self._rings.values())
        cutoff = self.now_us() - last_s * 1e6 if last_s else None
        tracks = {}
        for r in rings:
            evs = r.events()
            if cutoff is not None:
                evs = [e for e in evs if e[1] >= cutoff]
            if evs:
                evs.sort(key=lambda e: e[1])
                tracks[r.name] = evs
        return {"tracks": tracks,
                "events_total": sum(r.total for r in rings),
                "dropped_total": sum(r.dropped for r in rings),
                "ring_capacity": self.capacity}


_TRACER: Tracer | None = None
_TLS = threading.local()


def enabled() -> bool:
    return _TRACER is not None


def get_tracer() -> Tracer | None:
    return _TRACER


def enable(capacity: int | None = None) -> Tracer:
    """Switch tracing on. Idempotent: an active tracer (and its
    buffered events) is kept; pass ``capacity`` only on first enable."""
    global _TRACER
    if _TRACER is None:
        _TRACER = Tracer(capacity)
    return _TRACER


def disable() -> None:
    """Back to the zero-cost disabled state (buffered events dropped)."""
    global _TRACER
    _TRACER = None


def reset() -> None:
    """Full reset for tests: tracer AND thread-local trace IDs."""
    disable()
    if getattr(_TLS, "trace_id", None) is not None:
        _TLS.trace_id = None


# ---------------------------------------------------------------------------
# Trace-ID propagation (thread-local).
# ---------------------------------------------------------------------------

def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def current_trace_id() -> str | None:
    return getattr(_TLS, "trace_id", None)


class bind:
    """Context manager binding ``trace_id`` to the current thread:
    every event emitted inside carries it (the server wraps each
    request in one so the whole prefill→decode→reply path is a single
    filterable story in the exported timeline)."""

    __slots__ = ("trace_id", "_prev")

    def __init__(self, trace_id: str):
        self.trace_id = trace_id

    def __enter__(self):
        self._prev = getattr(_TLS, "trace_id", None)
        _TLS.trace_id = self.trace_id
        return self

    def __exit__(self, *exc):
        _TLS.trace_id = self._prev
        return False


# ---------------------------------------------------------------------------
# Module-level emit helpers (every one starts with the is-None gate).
# ---------------------------------------------------------------------------

def now_us() -> float:
    t = _TRACER
    return t.now_us() if t is not None else time.time() * 1e6


def perf_to_us(t_perf: float) -> float:
    t = _TRACER
    return t.perf_to_us(t_perf) if t is not None else t_perf * 1e6


def emit(ph: str, name: str, cat: str = "op", **kw) -> None:
    t = _TRACER
    if t is not None:
        t.emit(ph, name, cat, **kw)


def begin(name: str, cat: str = "op", args: dict | None = None,
          track: str | None = None) -> None:
    t = _TRACER
    if t is not None:
        t.emit("B", name, cat, args=args, track=track)


def end(name: str, cat: str = "op", track: str | None = None) -> None:
    t = _TRACER
    if t is not None:
        t.emit("E", name, cat, track=track)


def instant(name: str, cat: str = "op", args: dict | None = None,
            track: str | None = None) -> None:
    t = _TRACER
    if t is not None:
        t.emit("i", name, cat, args=args, track=track)


def complete(name: str, cat: str, ts_us: float, dur_us: float,
             args: dict | None = None, track: str | None = None) -> None:
    t = _TRACER
    if t is not None:
        t.emit("X", name, cat, ts_us=ts_us, dur_us=dur_us, args=args,
               track=track)


@contextlib.contextmanager
def span(name: str, cat: str = "op", args: dict | None = None):
    """Begin/end pair around a region. B/E (not one X) on purpose: a
    hang inside leaves the un-ended B in the flight record — the
    postmortem then SHOWS what was in flight when the watchdog tripped
    (``tools/trace_export.py --validate`` reports unclosed begins as
    warnings, not errors, for exactly this reason)."""
    t = _TRACER
    if t is None:
        yield
        return
    t.emit("B", name, cat, args=args)
    try:
        yield
    finally:
        # Re-read: disable() while the region ran must not crash it.
        t2 = _TRACER
        if t2 is not None:
            t2.emit("E", name, cat)


def collect(last_s: float | None = None) -> dict:
    t = _TRACER
    if t is None:
        return {"tracks": {}, "events_total": 0, "dropped_total": 0,
                "ring_capacity": 0}
    return t.collect(last_s)


def stats() -> dict:
    """Counts for dashboards/reports: events captured, dropped (ring
    overwrites), buffer capacity, plus the last flight record if one
    was dumped. Mirrors the counts into ``trace.*`` gauges so plain
    metric snapshots carry them too."""
    t = _TRACER
    out = {"enabled": t is not None}
    if t is not None:
        with t._lock:
            rings = list(t._rings.values())
        out["events_total"] = sum(r.total for r in rings)
        out["dropped_total"] = sum(r.dropped for r in rings)
        out["tracks"] = len(rings)
        out["ring_capacity"] = t.capacity
        # Per-ring high-water mark: the fullest any single ring ever
        # got (capped at capacity — a wrapped ring IS full). Together
        # with dropped_total this is the TDT_TRACE_RING sizing signal:
        # high water at capacity + nonzero drops = undersized ring
        # (tools/report.py warns on it).
        out["ring_high_water"] = max(
            (min(r.total, r.cap) for r in rings), default=0)
        from triton_dist_tpu.obs import registry as _registry
        _registry.gauge("trace.events_total").set(out["events_total"])
        _registry.gauge("trace.dropped_total").set(out["dropped_total"])
        _registry.gauge("trace.ring_high_water").set(
            out["ring_high_water"])
    from triton_dist_tpu.obs import flight as _flight
    last = _flight.last_record()
    if last is not None:
        out["last_flight_record"] = last["path"]
        out["flight_dumps"] = last["count"]
    return out


# ---------------------------------------------------------------------------
# Ring-schedule chunk events (the fused comm-GEMM timelines).
# ---------------------------------------------------------------------------

def ring_schedule_events(op: str, *, world: int, dirs: int,
                         compute_ms: float, comm_ms: float,
                         n_hops: int | None = None) -> None:
    """Per-chunk begin/end events for a fused ring schedule, emitted
    host-side at dispatch onto two named tracks —
    ``comms.<op>.compute`` (one slice per consumed chunk, in the
    kernel's rank-rotated order) and ``comms.<op>.comm`` (one slice
    per travelling hop, each overlapping the previous chunk's tile
    loop, per the schedule contract in docs/perf.md).

    The slice GEOMETRY (who overlaps whom) is the kernel's real
    schedule; the durations are the dispatch-time cost-model terms —
    so ``tools/trace_export.py --overlap`` reconstructs overlap from
    the trace's interval arithmetic rather than trusting the
    ``comms.<op>.overlap_pct`` gauge, and an on-chip profile overlaid
    in Perfetto shows model-vs-measured skew per chunk."""
    t = _TRACER
    if t is None or world <= 1:
        return
    from triton_dist_tpu.ops.common import (ring_chunk_schedule,
                                            ring_hop_counts)
    if n_hops is None:
        n_hops = sum(ring_hop_counts(world, dirs))
    t0 = t.now_us()
    dc = compute_ms / world * 1e3                    # us per chunk
    dh = comm_ms / max(n_hops, 1) * 1e3              # us per hop
    tid = current_trace_id()
    for s in range(world):
        chunk, is_bwd, off = ring_chunk_schedule(0, s, world, dirs)
        args = {"op": op, "step": s, "chunk": int(chunk),
                "dir": "bwd" if bool(is_bwd) else "fwd",
                "hop": int(off)}
        t.emit("X", f"chunk{int(chunk)}", "comms", ts_us=t0 + s * dc,
               dur_us=dc, args=args, track=f"comms.{op}.compute",
               trace_id=tid)
        if s + 1 < world:
            # The hop delivering the chunk consumed at step s+1 runs
            # under step s's tile loop — the overlap the schedule buys.
            t.emit("X", f"hop{s}", "comms", ts_us=t0 + s * dc,
                   dur_us=dh, args={"op": op, "step": s},
                   track=f"comms.{op}.comm", trace_id=tid)
