"""Shared compile-on-first-use loader for the csrc/ C++ components.

One definition of the build/load dance (mtime-checked g++ -shared
rebuild, ctypes load, graceful fallback to None) so fixes to it reach
every native module — mega/native.py and models/kv_native.py both had
a copy before.
"""

from __future__ import annotations

import ctypes
import os
import subprocess


def load_native(src: str, so: str, configure) -> ctypes.CDLL | None:
    """Build ``so`` from ``src`` if stale, load it, apply ``configure``
    (sets restype/argtypes; an AttributeError there means a stale
    prebuilt .so missing a newer symbol). Returns None when any step
    fails — callers fall back to their Python implementations.
    """
    src, so = os.path.abspath(src), os.path.abspath(so)
    try:
        if (not os.path.exists(so)
                or os.path.getmtime(so) < os.path.getmtime(src)):
            subprocess.run(
                ["g++", "-shared", "-fPIC", "-O2", "-o", so, src],
                check=True, capture_output=True)
        lib = ctypes.CDLL(so)
        configure(lib)
        return lib
    except (OSError, subprocess.CalledProcessError, AttributeError):
        return None
