"""Platform probing and execution-mode defaults.

The reference selects a backend (NVIDIA/AMD) at compile time
(backends/nvidia/backend/compiler.py). On TPU there is one hardware target,
but we support two execution modes for every Pallas kernel:

- compiled (Mosaic) on real TPU devices;
- interpreted (``pltpu.InterpretParams``) on a forced-multi-device CPU mesh,
  which simulates remote DMAs and semaphores. This is the single-process
  multi-"rank" test spine that the reference lacks (SURVEY.md §4 TPU
  translation note).
"""

from __future__ import annotations

import functools

import jax


@functools.cache
def backend_platform() -> str:
    platform = jax.devices()[0].platform
    # The axon PJRT plugin reports platform "axon" but is a TPU.
    if platform == "axon":
        return "tpu"
    return platform


def is_tpu() -> bool:
    return backend_platform() == "tpu"


def is_cpu() -> bool:
    return backend_platform() == "cpu"


def default_interpret() -> bool:
    """Interpret Pallas TPU kernels when not running on real TPU hardware.

    ``TDT_FORCE_COMPILED=1`` forces the compiled path regardless of the
    backend — used by the export-lint mode (tpu_smoke --export-lint),
    which lowers every kernel FOR the tpu platform on a CPU host to run
    the Pallas→Mosaic verifier without executing anything."""
    import os
    if os.environ.get("TDT_FORCE_COMPILED") == "1":
        return False
    return not is_tpu()
