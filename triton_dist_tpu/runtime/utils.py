"""Bench / verify / logging helpers.

TPU-native analogs of the reference's host utilities
(python/triton_dist/utils.py): ``perf_func`` (:274), ``dist_print`` (:289),
``assert_allclose`` (:870), ``init_seed`` (:77).
"""

from __future__ import annotations

import sys
import time
from collections.abc import Callable

import jax
import jax.numpy as jnp
import numpy as np


def init_seed(seed: int = 42) -> jax.Array:
    """Deterministic seeding (reference utils.py:77-96). Returns a JAX PRNG
    key; numpy is seeded for host-side golden generation."""
    np.random.seed(seed)
    return jax.random.PRNGKey(seed)


def tree_all_finite(tree) -> bool:
    """Every floating jax.Array leaf of ``tree`` is NaN/inf-free.

    The one shared finiteness walk (resilience numeric guard,
    tpu_smoke result scoring): blocks on the leaves, casts to f32 so
    bf16/f16 reduce without surprises."""
    import jax.numpy as jnp
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array) and jnp.issubdtype(
                leaf.dtype, jnp.floating):
            if not bool(jnp.isfinite(leaf.astype(jnp.float32)).all()):
                return False
    return True


def _block(tree) -> None:
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array):
            leaf.block_until_ready()


def _tunneled_device() -> bool:
    """True when the device is reached through a request tunnel (the
    'axon' PJRT plugin) whose ``block_until_ready`` completes before the
    device work does — wall-clock deltas without data materialization are
    meaningless there."""
    import os
    if "axon" in os.environ.get("JAX_PLATFORMS", ""):
        return True
    try:
        # The plugin registers under "axon" even though devices report
        # platform "tpu".
        from jax._src import xla_bridge
        return "axon" in xla_bridge.backends()
    except Exception:
        return False


def _materialize_small(tree) -> None:
    """Force a (tiny) host readback — the only reliable sync point on a
    tunneled device."""
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array):
            np.asarray(jax.device_get(jnp.ravel(leaf)[:8]))
            return


def _escalating_median_slope(run, n1: int, n2: int, *, n1_cap: int,
                             n2_cap: int, samples: int = 5,
                             floor_ms: float = 12.0) -> float:
    """Median of repeated ``(run(n2) - run(n1)) / (n2 - n1)`` slopes,
    escalating the window x4 until the raw delta carries at least
    ``floor_ms`` of signal. The shared tunnel-timing estimator behind
    perf_func_chained and the chained-runner path of perf_func: the
    fixed readback roundtrip cancels in the slope, and the floor keeps
    per-read jitter (several ms) from dominating sub-0.1 ms steps (a
    4 ms floor once let a selfcheck imply 264 TFLOPS on a 197-TFLOPS
    chip)."""
    while True:
        slopes = []
        for _ in range(samples):
            t1 = run(n1)
            t2 = run(n2)
            slopes.append(max(t2 - t1, 1e-9) / (n2 - n1) * 1e3)
        med = float(np.median(slopes))
        if med * (n2 - n1) >= floor_ms or n2 >= n2_cap:
            # Below-noise steps return the cap-length median; callers'
            # plausibility gates (timing_selfcheck) are the backstop.
            return med
        n1, n2 = min(n1 * 4, n1_cap), min(n2 * 4, n2_cap)


def perf_func(
    func: Callable,
    iters: int = 50,
    warmup_iters: int = 10,
    return_output: bool = True,
):
    """Time a JAX function with proper device synchronization.

    Analog of reference ``perf_func`` (utils.py:274-288, CUDA-event based).
    Returns ``(output, avg_ms)``.

    On tunneled devices the fixed readback roundtrip (~tens of ms) dwarfs
    kernel time, so the per-iteration cost is estimated by the *slope*
    between an ``iters`` run and a ``2*iters`` run, each synced by one
    tiny readback — the fixed cost cancels.
    """
    out = None
    for _ in range(max(warmup_iters, 1)):
        out = func()
    _block(out)

    if _tunneled_device():
        _materialize_small(out)
        chained = bool(getattr(func, "chained", False))

        def run(n: int) -> float:
            nonlocal out
            t0 = time.perf_counter()
            for _ in range(n):
                out = func()
                # The tunnel executes lazily and dedupes unread results.
                # An UNCHAINED func must read every iteration or the
                # slope measures dispatch overhead only — and that
                # per-read roundtrip does NOT cancel, so its jitter
                # (several ms per read, times n reads) swamps sub-ms
                # kernels: the round-5 on-chip sweep ranked a 0.89 ms
                # ag_gemm config above the 0.52 ms default this way. A
                # runner from make_perturbed_runner chains each call on
                # the previous output, so ONE read forces the whole
                # window and the fixed cost cancels in the slope.
                if not chained:
                    _materialize_small(out)
            if chained:
                _materialize_small(out)
            return time.perf_counter() - t0

        if chained:
            # Same estimator as perf_func_chained's tunnel path (shared
            # helper); smaller caps because every chained-runner
            # iteration also pays the eager perturb+tie dispatches.
            n1 = max(iters // 2, 1)
            avg_ms = _escalating_median_slope(
                run, n1, max(iters, n1 + 1), n1_cap=128, n2_cap=512)
        else:
            t1 = run(iters)
            t2 = run(2 * iters)
            avg_ms = max(t2 - t1, 1e-9) / iters * 1e3
    else:
        t0 = time.perf_counter()
        for _ in range(iters):
            out = func()
        _block(out)
        avg_ms = (time.perf_counter() - t0) / iters * 1e3
    if return_output:
        return out, avg_ms
    return None, avg_ms


def perturb_input(tree, counter: int):
    """Scale floating leaves by a factor that is DISTINCT IN THE LEAF'S
    OWN DTYPE per ``counter`` — makes a chain's computation unique per
    run so the tunnel cannot serve cached results. The step is
    dtype-aware: a fixed 1e-4 would round to exactly 1.0 in bfloat16
    (eps 2^-7) and silently reintroduce the dedup bug."""
    def f(leaf):
        if isinstance(leaf, jax.Array) and jnp.issubdtype(
                leaf.dtype, jnp.floating):
            eps = float(jnp.finfo(leaf.dtype).eps)
            return leaf * jnp.asarray(1.0 + 4.0 * eps * counter, leaf.dtype)
        return leaf
    return jax.tree_util.tree_map(f, tree)


def perf_func_chained(step: Callable, x0, iters: tuple[int, int] = (20, 60)):
    """Time ``x = step(x)`` per iteration via the slope between two chained
    runs.

    The tunneled single-chip environment (axon) executes only
    computations whose outputs are read and runs independent computations
    lazily, so unchained timing is meaningless there: chaining forces
    serial execution and the two-run slope cancels the fixed readback
    cost. The tunnel also DEDUPES identical computations — a repeated
    chain from the same x0 would be served from cache and measure only
    the readback (VERDICT r2 weak 5: the round-2 XLA "baseline" implied
    248 TFLOPS on a 197-TFLOPS chip) — so every run starts from a
    uniquely-perturbed x0. On normal backends a single chained run with a
    final block is used. Returns avg ms per step.
    """
    x = step(x0)
    _materialize_small(x)
    counter = [0]

    def run(n: int) -> float:
        counter[0] += 1
        x = perturb_input(x0, counter[0])
        _block(x)
        t0 = time.perf_counter()
        for _ in range(n):
            x = step(x)
        _materialize_small(x)
        return time.perf_counter() - t0

    n1, n2 = iters
    if _tunneled_device():
        # Median of repeated slopes via the shared estimator: the fixed
        # readback cost jitters by several ms, so one slope sample is
        # not enough, and sub-0.1ms steps need their chain escalated
        # (gemm_ar's decode GEMM once measured a "0.0 ms" XLA baseline
        # from a too-short delta).
        return _escalating_median_slope(run, n1, n2,
                                        n1_cap=500, n2_cap=2000)
    # Non-tunneled backends: min of 5 chained windows, escalating the
    # chain until one window carries >= ~20 ms of signal. A SINGLE
    # sub-ms window (the pre-r5 behavior) on a loaded 1-core host
    # spreads 3-4.4x run-to-run, which is what produced the r4
    # "2.845x same-matmul XLA baseline split" across bench parts
    # measured minutes apart (diagnosis: docs/perf.md; the unloaded
    # pair agrees within 1.05x). min() is the right estimator for
    # "cost without preemption" on a shared host.
    t = run(n2)
    while t < 0.02 and n2 < 2000:
        n2 = min(n2 * 4, 2000)
        t = run(n2)
    samples = [t / n2]
    # Re-target the chain to a ~40 ms window for the remaining samples:
    # a slow (interpret-mode) step's (8,24) window can carry seconds,
    # and four more full-size windows would multiply the CPU bench
    # wall ~5x for no extra noise rejection (review r5c-1).
    n2 = max(2, min(n2, int(round(0.04 / max(samples[0], 1e-9)))))
    for _ in range(4):
        samples.append(run(n2) / n2)
    return min(samples) * 1e3


def _chain_tie(tree, carry):
    """Scale the first floating leaf of ``tree`` by a one-valued factor
    derived from ``carry`` (a scalar from the previous call's output).
    The values are bitwise unchanged — ``x * 1.0`` is exact for every
    input including -0.0/inf/nan, and ``nan_to_num`` keeps the factor
    exactly one even for inf/nan carries — but the runtime now sees a
    data dependency on the previous output, so a lazy tunneled backend
    must execute every link of the chain to serve the final read."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = []
    tied = False
    for leaf in leaves:
        if (not tied and isinstance(leaf, jax.Array)
                and jnp.issubdtype(leaf.dtype, jnp.floating)):
            one = 1.0 + jnp.nan_to_num(carry.astype(jnp.float32)) * 0.0
            leaf = leaf * one.astype(leaf.dtype)
            tied = True
        out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


def _carry_scalar(tree):
    """First element of the first floating leaf of ``tree`` (a device
    scalar, NOT read back), or None when there is no floating leaf."""
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array) and jnp.issubdtype(
                leaf.dtype, jnp.floating):
            return jnp.ravel(leaf)[0]
    return None


def make_perturbed_runner(fn, x, *rest):
    """Closure that calls ``fn(perturb_input(x, i), *rest)`` with a fresh
    counter per call — the shared shape of every autotune/bench run loop
    on the tunneled device (which dedupes repeated identical
    computations). Consecutive calls are CHAINED: each input carries a
    zero-valued tie to the previous output (:func:`_chain_tie`), so a
    timing loop needs only one readback per window instead of one per
    iteration — per-read roundtrip jitter over the tunnel is what made
    the round-5 on-chip autotune sweeps rank configs by noise. The
    ``chained`` attribute tells :func:`perf_func` to use the
    single-readback slope estimator."""
    counter = [0]
    carry = [None]

    def run():
        counter[0] += 1
        xi = perturb_input(x, counter[0])
        if carry[0] is not None:
            xi = _chain_tie(xi, carry[0])
        out = fn(xi, *rest)
        c = _carry_scalar(out)
        if c is not None:
            carry[0] = c
        elif run.chained:
            # No floating leaf in the output to tie through: the chain
            # cannot form, and advertising one would let perf_func skip
            # the per-iteration readbacks that force execution — the
            # silent version of the exact bug this runner exists to fix.
            # perf_func reads .chained after warmup, so a first-call
            # downgrade here is always seen.
            run.chained = False
        return out

    # A tie needs a floating leaf on the input side too (dtype check
    # only — no device op at construction).
    run.chained = any(
        isinstance(leaf, jax.Array) and jnp.issubdtype(leaf.dtype,
                                                       jnp.floating)
        for leaf in jax.tree_util.tree_leaves(x))
    return run


def timing_selfcheck(iters: tuple[int, int] = (8, 24)) -> dict:
    """Calibrate :func:`perf_func_chained` against a known-FLOPs matmul.

    Runs a chained (2048x4096)@(4096x4096) bf16 dot and reports the
    implied TFLOPS; ``ok`` is False when the number exceeds the chip's
    physical bf16 peak — i.e. the timing path is broken and every other
    number from this process is suspect.
    """
    m = k = 4096
    n = 2048
    a = jax.random.normal(jax.random.PRNGKey(0), (n, m),
                          jnp.float32).astype(jnp.bfloat16)
    b = jax.random.normal(jax.random.PRNGKey(1), (m, k),
                          jnp.float32).astype(jnp.bfloat16)

    @jax.jit
    def step(x):
        y = jnp.dot(x, b, preferred_element_type=jnp.float32)
        return (y * jnp.asarray(2.0 ** -6, jnp.float32)).astype(x.dtype)

    ms = perf_func_chained(step, a, iters)
    tflops = 2.0 * n * m * k / (ms * 1e-3) / 1e12
    # Substring-matched spec table (handles "TPU v5 lite" etc.); an
    # exact-match dict here would silently disable the check on any
    # unlisted device_kind.
    from triton_dist_tpu.tools.perf_model import get_chip_spec
    spec = get_chip_spec()
    kind = getattr(jax.devices()[0], "device_kind", "cpu").lower()
    if spec.name == "cpu-sim" and "cpu" not in kind:
        # Unknown accelerator: no physical bound known — disable the
        # check explicitly rather than false-alarm against the
        # simulator spec (or silently pass against a huge default).
        return {"calib_ms": round(ms, 4),
                "calib_tflops": round(tflops, 1), "peak_tflops": None,
                "ok": True, "note": f"unknown device kind {kind!r}; "
                                    "peak check disabled"}
    peak = spec.bf16_tflops
    return {"calib_ms": round(ms, 4), "calib_tflops": round(tflops, 1),
            "peak_tflops": peak, "ok": bool(tflops <= 1.05 * peak)}


def dist_print(*args, prefix: bool = True, need_sync: bool = False,
               allowed_ranks="all", **kwargs) -> None:
    """Per-process-prefixed printing (reference ``dist_print`` utils.py:289).

    ``allowed_ranks`` filters by ``jax.process_index()`` (host granularity —
    per-device printing from inside jitted code uses ``jax.debug.print``).
    ``need_sync`` serializes output across processes: each rank prints in
    turn with a global barrier between turns (reference behavior).
    """
    rank = jax.process_index()
    world = jax.process_count()
    if allowed_ranks == "all":
        allowed = range(world)
    else:
        allowed = allowed_ranks

    def _emit():
        if rank in allowed:
            if prefix:
                print(f"[rank {rank}/{world}]", *args, **kwargs)
            else:
                print(*args, **kwargs)
            sys.stdout.flush()

    if need_sync and world > 1:
        from jax.experimental import multihost_utils
        for r in range(world):
            if rank == r:
                _emit()
            multihost_utils.sync_global_devices(f"dist_print_{r}")
    else:
        _emit()


def assert_allclose(x, y, rtol: float = 1e-2, atol: float = 1e-2,
                    verbose: bool = True) -> None:
    """Structured allclose with mismatch diagnostics (reference
    ``assert_allclose`` utils.py:870-886)."""
    x = np.asarray(jax.device_get(x), dtype=np.float64)
    y = np.asarray(jax.device_get(y), dtype=np.float64)
    if x.shape != y.shape:
        raise AssertionError(f"shape mismatch: {x.shape} vs {y.shape}")
    close = np.isclose(x, y, rtol=rtol, atol=atol)
    if not close.all():
        bad = np.argwhere(~close)
        n = bad.shape[0]
        msg = [f"allclose failed: {n}/{x.size} mismatched "
               f"(rtol={rtol}, atol={atol})"]
        if verbose:
            for idx in bad[:10]:
                i = tuple(idx)
                msg.append(f"  at {i}: {x[i]!r} vs {y[i]!r}")
            abs_err = np.abs(x - y)
            msg.append(f"  max abs err {abs_err.max():.3e}, "
                       f"mean abs err {abs_err.mean():.3e}")
        raise AssertionError("\n".join(msg))


def bitwise_equal(x, y) -> bool:
    """Bitwise comparison used to gate deterministic collectives
    (SURVEY.md §7 stage-2 gate)."""
    x = np.asarray(jax.device_get(x))
    y = np.asarray(jax.device_get(y))
    return x.shape == y.shape and bool(
        np.array_equal(x.view(np.uint8), y.view(np.uint8)))


def rand(key, shape, dtype=jnp.float32, scale: float = 1.0) -> jax.Array:
    """Test-data helper: normal data cast to ``dtype``."""
    return (jax.random.normal(key, shape) * scale).astype(dtype)
