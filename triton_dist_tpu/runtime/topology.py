"""ICI/DCN topology description (reference topology probes,
utils.py:823-967: NVLink fullmesh / NUMA / multicast detection — on TPU
the questions become torus extents, hosts, and chip generation).

The actionable consumer is mesh construction:
``initialize_distributed`` routes TPU device grids through
``jax.experimental.mesh_utils.create_device_mesh`` so the logical mesh
axes are laid onto physical ICI neighbors (a naive ``reshape`` can put
a TP ring across the torus diagonal, turning every hop into multiple
physical links). This module surfaces what that decision sees.
"""
from __future__ import annotations

import numpy as np

import jax


def describe_topology(devices=None) -> dict:
    """Best-effort physical-topology summary of ``devices``.

    Returns keys: ``n_devices``, ``platform``, ``device_kind``,
    ``n_hosts``, and — when per-device coordinates are exposed (real
    TPU backends) — ``torus_extent`` (inclusive extent per coordinate
    axis) and ``coords_contiguous`` (whether the slice fills its
    bounding box, i.e. no holes from a twisted/partial slice).
    """
    devices = list(devices if devices is not None else jax.devices())
    if not devices:
        return {"n_devices": 0, "platform": "?", "device_kind": "?",
                "n_hosts": 0}
    d0 = devices[0]
    out = {
        "n_devices": len(devices),
        "platform": getattr(d0, "platform", "?"),
        "device_kind": getattr(d0, "device_kind", "?"),
        "n_hosts": len({getattr(d, "process_index", 0) for d in devices}),
    }
    coords = [getattr(d, "coords", None) for d in devices]
    if coords and all(c is not None for c in coords):
        arr = np.asarray(coords)
        extent = arr.max(axis=0) - arr.min(axis=0) + 1
        out["torus_extent"] = tuple(int(x) for x in extent)
        out["coords_contiguous"] = bool(
            int(np.prod(extent)) == len({tuple(c) for c in coords}))
    return out


def topology_aware_grid(devices: np.ndarray, shape) -> np.ndarray:
    """Arrange ``devices`` into ``shape`` honoring physical topology.

    TPU grids go through ``mesh_utils.create_device_mesh`` (torus-aware
    axis assignment); anything else — CPU simulation meshes, explicit
    device subsets, or a mesh_utils failure — falls back to the plain
    ``reshape`` (order-preserving, what the tests' 8-virtual-device
    meshes assume).
    """
    flat = np.asarray(devices).ravel()
    shape = tuple(shape)
    if (getattr(flat[0], "platform", "?") == "tpu"
            and flat.size == len(jax.devices()) and flat.size > 1):
        try:
            from jax.experimental import mesh_utils
            return np.asarray(
                mesh_utils.create_device_mesh(shape, devices=list(flat)))
        except Exception as e:  # noqa: BLE001 — layout is an optimization
            import warnings
            warnings.warn(
                "mesh_utils.create_device_mesh failed "
                f"({type(e).__name__}: {e}); falling back to a naive "
                "device reshape — TP rings may span the torus diagonal "
                "(multiple physical ICI links per hop)", stacklevel=2)
    return np.asarray(devices).reshape(shape)
