"""Build/locate the CPU-count shim used for multi-device CPU testing.

XLA's CPU PJRT client sizes its thread pools from ``sched_getaffinity``. On
1-core hosts the compute pool has a single thread; Pallas TPU interpret mode
issues blocking host callbacks (semaphore waits) that occupy pool threads
while *other* simulated devices' compute feeds their callbacks — a hard
deadlock. ``libcpushim.so`` (csrc/cpushim/cpushim.c) LD_PRELOADs a fake
16-CPU affinity so the pools are sized for the 8-device simulation; the
threads simply timeshare the physical core.

LD_PRELOAD must be set before process start — ``maybe_reexec_with_shim()``
re-execs the current process once if needed (used by tests/conftest.py and
__graft_entry__.dryrun_multichip).
"""

from __future__ import annotations

import os
import subprocess
import sys

_SRC = os.path.join(os.path.dirname(__file__), "..", "csrc", "cpushim",
                    "cpushim.c")
_SO = os.path.join(os.path.dirname(_SRC), "libcpushim.so")


def ensure_cpu_shim() -> str | None:
    """Compile the shim if needed; return its path (None if no compiler)."""
    src = os.path.abspath(_SRC)
    so = os.path.abspath(_SO)
    if os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(src):
        return so
    try:
        subprocess.run(["gcc", "-shared", "-fPIC", "-O2", "-o", so, src],
                       check=True, capture_output=True)
        return so
    except (OSError, subprocess.CalledProcessError):
        return None


def maybe_reexec_with_shim() -> None:
    """Re-exec the current process with LD_PRELOAD=libcpushim.so (no-op when
    already loaded, on multi-core hosts, disabled via TDT_NO_CPU_SHIM=1, or
    if the shim can't be built)."""
    if os.environ.get("TDT_NO_CPU_SHIM"):
        return
    if os.cpu_count() and os.cpu_count() >= 8:
        return
    so = ensure_cpu_shim()
    if so is None or so in os.environ.get("LD_PRELOAD", ""):
        return
    env = dict(os.environ)
    env["LD_PRELOAD"] = ":".join(
        p for p in (env.get("LD_PRELOAD"), so) if p)
    with open("/proc/self/cmdline", "rb") as f:
        args = [a.decode() for a in f.read().split(b"\0") if a]
    os.execve(sys.executable, [sys.executable] + args[1:], env)
