"""Symmetric memory over a device mesh.

TPU-native analog of NVSHMEM symmetric-heap tensors
(reference ``nvshmem_create_tensor(s)`` python/triton_dist/utils.py:114-136).

On TPU, a "symmetric tensor" is a globally-shaped array sharded along a mesh
axis so that *every device holds an identically-shaped local shard at the
same logical offset*. Inside ``jax.shard_map``, each device sees its local
shard; Pallas kernels address a *peer's* shard with
``pltpu.make_async_remote_copy(..., device_id=peer)`` — the analog of
``nvshmem_ptr`` / ``symm_at`` (DistributedOps.td:120-150).

There is no separate allocator: XLA owns HBM. Persistent workspaces are
ordinary sharded arrays threaded through jitted functions (donated when
mutated in place).
"""

from __future__ import annotations

from collections.abc import Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def symm_tensor(
    local_shape: Sequence[int],
    dtype,
    mesh: Mesh,
    axis: str = "tp",
    fill: float | int = 0,
) -> jax.Array:
    """Allocate a symmetric tensor: one ``local_shape`` buffer per device.

    Returns a global array of shape ``(axis_size, *local_shape)`` sharded on
    its leading dimension over ``axis``. Device ``i``'s shard is the slice
    ``[i]`` — its symmetric buffer. Analog of ``nvshmem_create_tensor``
    (utils.py:114).
    """
    world = mesh.shape[axis]
    spec = P(axis, *([None] * len(local_shape)))
    sharding = NamedSharding(mesh, spec)
    # Allocate shard-by-shard on each device (jnp.full + device_put would
    # first materialize the full world-sized array on one device).
    return jnp.full((world, *local_shape), fill, dtype=dtype,
                    device=sharding)


def symm_like(x: jax.Array, mesh: Mesh, axis: str = "tp") -> jax.Array:
    """Symmetric tensor with per-device buffers shaped like ``x``."""
    return symm_tensor(x.shape, x.dtype, mesh, axis)


def local_shard(x: jax.Array, index: int = 0) -> jax.Array:
    """Host-side view of one device's shard (debug/test helper, analog of
    peeking a single rank's symmetric buffer)."""
    return jax.device_get(x)[index]
