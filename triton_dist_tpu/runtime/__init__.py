"""Host distributed runtime (reference L4: python/triton_dist/utils.py)."""

from triton_dist_tpu.runtime.dist import (  # noqa: F401
    DistContext,
    initialize_distributed,
    finalize_distributed,
    get_context,
    get_mesh,
)
from triton_dist_tpu.runtime.platform import (  # noqa: F401
    is_tpu,
    is_cpu,
    default_interpret,
)
from triton_dist_tpu.runtime.symm_mem import (  # noqa: F401
    symm_tensor,
    symm_like,
    local_shard,
)
from triton_dist_tpu.runtime.utils import (  # noqa: F401
    perf_func,
    dist_print,
    assert_allclose,
    init_seed,
)
