"""Distributed initialization and the global mesh context.

TPU-native analog of the reference's process-group bootstrap
(python/triton_dist/utils.py:182 ``initialize_distributed``: torchrun env →
``init_process_group`` → NVSHMEM UID broadcast). On TPU the runtime is
simpler: ``jax.distributed.initialize`` (multi-host only) plus a
``jax.sharding.Mesh`` over the devices. ICI connectivity replaces NVLink;
the mesh axes replace NVSHMEM teams (SURVEY.md §5 "Distributed communication
backend").

Axis-name conventions used across the framework:

- ``"tp"``  tensor parallel (the reference's default TP group = all ranks,
  utils.py:197)
- ``"ep"``  expert parallel
- ``"sp"``  sequence parallel
- ``"pp"``  pipeline parallel
- ``"dp"``  data parallel / replicated inference
"""

from __future__ import annotations

import dataclasses
import os
from collections.abc import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

_CONTEXT: "DistContext | None" = None

DEFAULT_TP_AXIS = "tp"


@dataclasses.dataclass(frozen=True)
class DistContext:
    """Global distributed context: the device mesh plus bookkeeping.

    Plays the role of the reference's ``TP_GROUP`` process group returned by
    ``initialize_distributed`` (utils.py:182-205).
    """

    mesh: Mesh
    seed: int = 42

    @property
    def axis_names(self) -> tuple[str, ...]:
        return tuple(self.mesh.axis_names)

    @property
    def world_size(self) -> int:
        return self.mesh.size

    @property
    def num_processes(self) -> int:
        return jax.process_count()

    @property
    def process_index(self) -> int:
        return jax.process_index()

    def axis_size(self, axis: str) -> int:
        return self.mesh.shape[axis]


def _already_initialized_error(e: RuntimeError) -> bool:
    """Is this ``jax.distributed.initialize`` failure the idempotent
    re-entry case (service already running) rather than a connect
    failure worth retrying?"""
    msg = str(e).lower()
    return ("already" in msg or "once" in msg
            or "duplicate" in msg)


def _initialize_with_retry(coord: str, nproc: int, pid: int,
                           retries: int | None = None,
                           backoff_s: float | None = None,
                           sleep=None) -> None:
    """``jax.distributed.initialize`` under bounded exponential backoff.

    The common multi-host race (found in r5): worker processes start
    before the coordinator's gRPC service is listening, and the bare
    ``initialize`` call fails hard — one slow pod member then kills the
    whole job at t=0. Retry ``TDT_DIST_INIT_RETRIES`` times (default
    5) with exponential backoff from ``TDT_DIST_INIT_BACKOFF_S``
    (default 0.5 s, doubling, capped at 30 s per wait), counting each
    retry into ``resilience.dist_init.retries``. Idempotent re-entry
    (already initialized) returns quietly at any attempt, preserving
    the previous barrier-guarded-re-init contract.

    ``sleep`` is injectable for tests; fault kind ``"dist_init"``
    (triton_dist_tpu.testing.faults) deterministically simulates the
    coordinator-not-up failure.
    """
    import time

    from triton_dist_tpu import obs
    from triton_dist_tpu.testing import faults

    if retries is None:
        retries = obs.env_int("TDT_DIST_INIT_RETRIES", 5, minimum=0)
    if backoff_s is None:
        backoff_s = float(os.environ.get("TDT_DIST_INIT_BACKOFF_S",
                                         "0.5"))
    if sleep is None:
        sleep = time.sleep
    for attempt in range(retries + 1):
        try:
            f = faults.take("dist_init", None) if faults.active() \
                else None
            if f is not None:
                raise faults.InjectedFault(
                    f"{f.message} (coordinator {coord} not up)")
            # Passed explicitly: bare ``initialize()`` only auto-detects
            # under recognized cluster launchers (Slurm/MPI/K8s), NOT
            # from these env vars — found by tests/test_multihost.py
            # (the r4 path raised "Number of processes must be
            # defined" on any pod launched this way).
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=nproc,
                process_id=pid)
            return
        except RuntimeError as e:
            if _already_initialized_error(e):
                return
            if attempt >= retries:
                raise
            obs.counter("resilience.dist_init.retries").inc()
            sleep(min(backoff_s * (2 ** attempt), 30.0))


#: Set once this process's ``jax.distributed.initialize`` succeeded —
#: the in-process idempotence guard. The error-message matching in
#: ``_already_initialized_error`` cannot cover re-entry on every jax
#: version: after the first init plus any computation, this jax raises
#: the generic "must be called before any JAX computations" message,
#: which looks like (and must not be confused with) a genuine
#: too-late-init failure from a process that never initialized.
_MULTIHOST_INITED = False


def _maybe_multihost_init() -> None:
    """Call ``jax.distributed.initialize`` iff a coordinator is configured.

    Mirrors the reference reading RANK/WORLD_SIZE from torchrun env
    (utils.py:183-186); JAX's equivalent env is set by the TPU pod launcher
    or explicitly via JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
    JAX_PROCESS_ID. Re-entry (a second ``initialize_distributed`` to
    reshape the mesh) is a no-op once this process has initialized.
    """
    global _MULTIHOST_INITED
    if _MULTIHOST_INITED:
        return
    coord = os.environ.get("JAX_COORDINATOR_ADDRESS")
    nproc = os.environ.get("JAX_NUM_PROCESSES")
    pid = os.environ.get("JAX_PROCESS_ID")
    if coord and nproc:
        try:
            nproc_i, pid_i = int(nproc), int(pid)  # type: ignore[arg-type]
        except (TypeError, ValueError):
            raise RuntimeError(
                "multi-host init needs all three of "
                "JAX_COORDINATOR_ADDRESS, JAX_NUM_PROCESSES and "
                "JAX_PROCESS_ID set to valid values; got "
                f"num_processes={nproc!r}, process_id={pid!r}") from None
        _initialize_with_retry(coord, nproc_i, pid_i)
        _MULTIHOST_INITED = True


def initialize_distributed(
    mesh_shape: dict[str, int] | Sequence[int] | None = None,
    axis_names: Sequence[str] | None = None,
    seed: int = 42,
    devices: Sequence[jax.Device] | None = None,
) -> DistContext:
    """Create (and globally register) the device mesh context.

    Args:
      mesh_shape: either a dict ``{"tp": 8}`` / ``{"dp": 2, "tp": 4}`` or a
        plain shape tuple matched with ``axis_names``. Default: 1-D mesh of
        all devices on axis ``"tp"`` — the reference's default TP group of
        all ranks (utils.py:197).
      axis_names: names for a tuple ``mesh_shape``.
      seed: base RNG seed (reference ``init_seed`` utils.py:77).
      devices: explicit device list (tests may pass a subset).

    Returns:
      The registered ``DistContext``.
    """
    global _CONTEXT
    _maybe_multihost_init()
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices)

    if mesh_shape is None:
        mesh_shape = {DEFAULT_TP_AXIS: devices.size}
    if isinstance(mesh_shape, dict):
        names = tuple(mesh_shape.keys())
        shape = tuple(mesh_shape.values())
    else:
        shape = tuple(mesh_shape)
        if axis_names is None:
            raise ValueError("axis_names required when mesh_shape is a tuple")
        names = tuple(axis_names)
    if int(np.prod(shape)) != devices.size:
        raise ValueError(
            f"mesh shape {shape} does not cover {devices.size} devices")

    from triton_dist_tpu.runtime.topology import topology_aware_grid
    mesh = Mesh(topology_aware_grid(devices, shape), names)
    _CONTEXT = DistContext(mesh=mesh, seed=seed)
    return _CONTEXT


def get_context() -> DistContext:
    if _CONTEXT is None:
        raise RuntimeError(
            "initialize_distributed() has not been called")
    return _CONTEXT


def get_mesh() -> Mesh:
    return get_context().mesh


def finalize_distributed() -> None:
    """Drop the global context (reference ``finalize_distributed``
    utils.py:145)."""
    global _CONTEXT
    _CONTEXT = None
