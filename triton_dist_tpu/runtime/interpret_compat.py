"""Workaround for a busy-spin in Pallas TPU interpret mode.

``jax._src.pallas.mosaic.interpret.shared_memory.Semaphore.wait`` busy-spins
(``while True: ... continue``) when waiting on a DMA semaphore whose
matching DMA has not been issued yet. On low-core-count hosts (CI boxes,
this image has 1 CPU), the spinning waiter threads starve the device threads
that would issue those DMAs — GIL + lock-convoy on the shared-memory lock —
so multi-device kernels hang nondeterministically.

This module monkeypatches (in-process only) the spin loop to sleep briefly
between polls, yielding the GIL so sender devices make progress. Applied
lazily the first time an interpreted kernel is requested
(ops.common.resolve_interpret).
"""

from __future__ import annotations

import time

_PATCHED = False

_SPIN_SLEEP_S = 2e-4


def patch_interpreter_spin() -> None:
    """Idempotently patch Semaphore.wait to yield while polling."""
    global _PATCHED
    if _PATCHED:
        return
    try:
        from jax._src.pallas.mosaic.interpret import shared_memory
        from jax._src.pallas.mosaic.interpret import vector_clock as vc
    except ImportError:  # interpreter layout changed; leave upstream as-is
        _PATCHED = True
        return

    def wait(self, value, global_core_id, *, has_tasks=False):
        global_core_id = int(global_core_id)
        clock = None
        if not has_tasks:
            with self.cv:
                while self.count_by_core[global_core_id] < value:
                    self.cv.wait()
                self.count_by_core[global_core_id] -= value
                if self.detect_races:
                    clock = vc.copy_vector_clock(
                        self.clocks[global_core_id])
            if self.detect_races:
                with self.shared_memory.lock:
                    vc.update_vector_clock(
                        self.shared_memory.clocks[global_core_id], clock)
            return

        while True:
            clock = None
            with self.cv:
                if self.count_by_core[global_core_id] >= value:
                    self.count_by_core[global_core_id] -= value
                    if self.detect_races:
                        clock = vc.copy_vector_clock(
                            self.clocks[global_core_id])
                    else:
                        return
            if clock is not None:
                with self.shared_memory.lock:
                    vc.update_vector_clock(
                        self.shared_memory.clocks[global_core_id], clock)
                return

            with self.shared_memory.lock:
                task_queue = self.shared_memory.tasks_by_sem[
                    (self.id, global_core_id)]
                task = task_queue.pop() if len(task_queue) > 0 else None
            if task is None:
                # Upstream `continue`s here without yielding, starving the
                # device thread that would issue the DMA we are waiting for.
                time.sleep(_SPIN_SLEEP_S)
                continue
            task()

    shared_memory.Semaphore.wait = wait
    _PATCHED = True
