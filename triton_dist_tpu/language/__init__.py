"""Device-side distributed primitives for Pallas TPU kernels.

TPU-native analog of the reference's device language layer (L3):
``triton_dist.language`` builtins ``wait / consume_token / rank / num_ranks /
symm_at / notify`` (python/triton_dist/language/distributed_ops.py:56-111)
and the ``libshmem_device`` stub API
(python/triton_dist/language/extra/libshmem_device.py).

Mapping (SURVEY.md §5 "Distributed communication backend"):

=====================  =========================================
reference primitive    TPU-native primitive
=====================  =========================================
symmetric heap ptr     peer shard of a mesh-sharded array,
                       addressed by ``device_id`` on a remote DMA
``putmem(_signal)``    ``pltpu.make_async_remote_copy`` (the recv
                       semaphore *is* the signal)
``dl.notify``          ``pltpu.semaphore_signal(device_id=peer)``
``dl.wait``            ``pltpu.semaphore_wait``
``dl.consume_token``   data dependence (Pallas orders by SSA use;
                       provided as an identity for API parity)
``barrier_all``        all-peer signal + wait on the global
                       barrier semaphore
teams / scopes         mesh axis names ("tp", "ep", ...)
=====================  =========================================

Import convention mirrors the reference::

    import triton_dist_tpu.language as dl
    ...
    dl.wait(sem, 1)
"""

from __future__ import annotations

import jax
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


# ---------------------------------------------------------------------------
# Identity / topology (reference distributed_ops.py:70-83 rank/num_ranks)
# ---------------------------------------------------------------------------

def rank(axis: str = "tp") -> jax.Array:
    """This device's index along ``axis`` (reference ``dl.rank``)."""
    return lax.axis_index(axis)


def num_ranks(axis: str = "tp") -> jax.Array:
    """World size along ``axis`` (reference ``dl.num_ranks``)."""
    return lax.axis_size(axis)


def _current_mesh_axes() -> tuple[str, ...] | None:
    """Axis names of the mesh enclosing the current trace (shard_map body),
    in mesh order. Lets primitives compute global logical device ids without
    the caller having to plumb mesh_axes through."""
    try:
        mesh = jax.sharding.get_abstract_mesh()
        names = tuple(mesh.axis_names)
        return names if names else None
    except Exception:
        return None


def logical_device_id(peer: jax.Array, axis: str,
                      mesh_axes: tuple[str, ...] | None = None):
    """Flattened logical device id of the device at ``peer`` on ``axis``,
    keeping this device's coordinates on every other mesh axis.

    For a 1-D mesh this is just ``peer``. For multi-axis meshes, remote DMA
    ``device_id`` must be the *logical* id over the full mesh
    (``pltpu.DeviceIdType.LOGICAL``); this computes it from mesh coordinates
    — the analog of NVSHMEM team-relative→global PE translation
    (``nvshmem_team_translate_pe``). ``mesh_axes`` defaults to the axes of
    the mesh enclosing the current trace.
    """
    if mesh_axes is None:
        mesh_axes = _current_mesh_axes()
    if mesh_axes is None or tuple(mesh_axes) == (axis,):
        return peer
    did = 0
    for name in mesh_axes:
        idx = peer if name == axis else lax.axis_index(name)
        did = did * lax.axis_size(name) + idx
    return did


# ---------------------------------------------------------------------------
# Signal / wait (reference distributed_ops.py:56-68 wait, :95-111 notify;
# lowering DistributedOpToLLVM.cpp:187-342)
# ---------------------------------------------------------------------------

def wait(sem, value: int | jax.Array = 1) -> None:
    """Block until ``sem`` has accumulated ``value`` signals, consuming them.

    Analog of ``dl.wait(barrier_ptr, n, scope, "acquire")`` — the PTX spin
    loop (DistributedOpToLLVM.cpp:187-206) becomes a hardware semaphore
    wait; acquire ordering is implied by the TPU DMA/semaphore model.
    """
    pltpu.semaphore_wait(sem, value)


def notify(sem, peer=None, inc: int = 1, axis: str | None = None,
           mesh_axes: tuple[str, ...] | None = None) -> None:
    """Signal ``sem`` (optionally on a remote device) — analog of
    ``dl.notify(ptr, rank, signal="add", comm_scope=...)``
    (distributed_ops.py:95-111).

    ``peer``: target rank along ``axis`` (None = local). CommScope GPU vs
    INTRA_NODE vs INTER_NODE collapses on TPU: ICI remote signal is one
    mechanism.
    """
    if peer is None:
        pltpu.semaphore_signal(sem, inc=inc)
    else:
        did = logical_device_id(peer, axis, mesh_axes) if axis else peer
        pltpu.semaphore_signal(
            sem, inc=inc, device_id=did,
            device_id_type=pltpu.DeviceIdType.LOGICAL)


# Signaling without ``axis`` treats ``peer`` as an already-global logical id;
# pass ``axis=`` whenever the peer index is axis-relative.


def consume_token(value, token=None):
    """API-parity identity (reference ``dl.consume_token``,
    distributed_ops.py:85-93; lowering is identity too,
    DistributedOpToLLVM.cpp:228). Pallas orders memory ops by data/effect
    dependence, so no token plumbing is needed."""
    del token
    return value


def semaphore_read(sem) -> jax.Array:
    """Non-blocking semaphore read (debug; reference has no direct analog —
    closest is reading the uint64 flag with ``ld.acquire``)."""
    return pltpu.semaphore_read(sem)


# ---------------------------------------------------------------------------
# One-sided data movement (reference libshmem_device putmem family)
# ---------------------------------------------------------------------------

def remote_copy(src_ref, dst_ref, peer, send_sem, recv_sem,
                axis: str | None = None,
                mesh_axes: tuple[str, ...] | None = None):
    """Build (don't start) an async remote copy ``src_ref → dst_ref@peer``.

    The analog of ``libshmem_device.putmem_nbi_block`` + signal: on TPU the
    receiver's ``recv_sem`` is signalled by the transport on delivery, which
    subsumes ``putmem_signal`` (libshmem_device.py:139-219). Returns the
    descriptor: call ``.start()`` / ``.wait()`` / ``.wait_send()`` /
    ``.wait_recv()``.
    """
    did = logical_device_id(peer, axis, mesh_axes) if axis else peer
    return pltpu.make_async_remote_copy(
        src_ref=src_ref, dst_ref=dst_ref,
        send_sem=send_sem, recv_sem=recv_sem,
        device_id=did, device_id_type=pltpu.DeviceIdType.LOGICAL)


def local_copy(src_ref, dst_ref, sem):
    """Async same-chip DMA (HBM↔VMEM) — the analog of the reference's
    cudaMemcpyAsync copy-engine path (allgather.py:158-230)."""
    return pltpu.make_async_copy(src_ref, dst_ref, sem)


# ---------------------------------------------------------------------------
# Barriers (reference barrier_all_intra_node_* common_ops.py:57-392,
# nvshmem_barrier_all_on_stream utils.py:162)
# ---------------------------------------------------------------------------

def barrier_all(axis: str = "tp",
                mesh_axes: tuple[str, ...] | None = None) -> None:
    """Full barrier across ``axis`` from inside a kernel.

    Signals every peer on the global barrier semaphore and waits for
    world-many signals (including self, keeping the count uniform).
    Requires ``collective_id`` in ``pltpu.CompilerParams``. Analog of
    ``barrier_all_intra_node_atomic_cas_block`` (common_ops.py).

    NOTE (jax 0.4.x): ``get_barrier_semaphore`` has no cpu-platform
    lowering there, so interpret-mode multi-device kernels cannot trace
    this on that jax generation (the TPU lowering is fine).
    ``tests/test_ring_bidir.py`` shows the test-side stub pattern for
    kernels whose data ordering rides per-copy DMA semaphores; a
    LIBRARY-level no-op is deliberately not provided — protocols like
    fast_all_to_all rely on the barrier to keep all interpreted devices
    live until every peer has arrived (a no-op deadlocks them).
    """
    sem = pltpu.get_barrier_semaphore()
    world = lax.axis_size(axis)

    def signal_one(i, _):
        did = logical_device_id(i, axis, mesh_axes)
        pltpu.semaphore_signal(
            sem, inc=1, device_id=did,
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        return _

    lax.fori_loop(0, world, signal_one, None)
    pltpu.semaphore_wait(sem, world)


def barrier_neighbors(axis: str = "tp",
                      mesh_axes: tuple[str, ...] | None = None) -> None:
    """Ring-neighbor barrier (cheaper than ``barrier_all``): sync with the
    left and right neighbors only — sufficient between ring steps."""
    sem = pltpu.get_barrier_semaphore()
    world = lax.axis_size(axis)
    me = lax.axis_index(axis)
    left = lax.rem(me - 1 + world, world)
    right = lax.rem(me + 1, world)
    for peer in (left, right):
        pltpu.semaphore_signal(
            sem, inc=1,
            device_id=logical_device_id(peer, axis, mesh_axes),
            device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_wait(sem, 2)


# Re-exports so kernels can use one namespace.
ds = pl.ds
when = pl.when
program_id = pl.program_id
num_programs = pl.num_programs
