"""SHMEM-style façade over the Pallas primitives.

Name-for-name analog of the reference's vendor-neutral ``libshmem_device``
stub API (python/triton_dist/language/extra/libshmem_device.py:28-341) so
kernels translated from SHMEM-style pseudocode read naturally. Everything
here delegates to :mod:`triton_dist_tpu.language`.

Semantic notes vs NVSHMEM:

- ``putmem_signal*`` collapses into one op: a TPU remote DMA signals the
  receiver's ``recv_sem`` on delivery.
- ``fence``/``quiet`` (ordering/completion of outstanding puts) map to
  waiting on the relevant send semaphores — puts are tracked per-descriptor,
  so completion is explicit rather than global.
- Teams are mesh axes; ``team_my_pe``/``team_n_pes`` take an axis name.
"""

from __future__ import annotations

import triton_dist_tpu.language as dl

# Comparison constants (libshmem_device.py CMP_* — only EQ/GE are used by the
# reference kernels; TPU semaphore_wait is >= with decrement).
CMP_EQ = 0
CMP_NE = 1
CMP_GT = 2
CMP_LE = 3
CMP_LT = 4
CMP_GE = 5

SIGNAL_SET = 9
SIGNAL_ADD = 10


def my_pe(axis: str = "tp"):
    return dl.rank(axis)


def n_pes(axis: str = "tp"):
    return dl.num_ranks(axis)


def team_my_pe(axis: str):
    return dl.rank(axis)


def team_n_pes(axis: str):
    return dl.num_ranks(axis)


def putmem_nbi_block(dst_ref, src_ref, peer, send_sem, recv_sem,
                     axis: str | None = None, mesh_axes=None):
    """Non-blocking put; returns the descriptor (call ``.wait()`` for
    completion). Reference: libshmem_device.putmem_nbi_block."""
    copy = dl.remote_copy(src_ref, dst_ref, peer, send_sem, recv_sem,
                          axis=axis, mesh_axes=mesh_axes)
    copy.start()
    return copy


def putmem_block(dst_ref, src_ref, peer, send_sem, recv_sem,
                 axis: str | None = None, mesh_axes=None):
    """Blocking put (reference libshmem_device.putmem_block)."""
    copy = putmem_nbi_block(dst_ref, src_ref, peer, send_sem, recv_sem,
                            axis=axis, mesh_axes=mesh_axes)
    copy.wait_send()
    return copy


def putmem_signal_nbi_block(dst_ref, src_ref, peer, send_sem, recv_sem,
                            axis: str | None = None, mesh_axes=None):
    """Put + signal-on-delivery. On TPU the recv semaphore *is* the signal,
    so this is identical to ``putmem_nbi_block``
    (reference libshmem_device.putmem_signal_nbi_block)."""
    return putmem_nbi_block(dst_ref, src_ref, peer, send_sem, recv_sem,
                            axis=axis, mesh_axes=mesh_axes)


def signal_op(sem, peer, inc: int = 1, axis: str | None = None,
              mesh_axes=None):
    """Remote signal (reference libshmem_device.signal_op with SIGNAL_ADD)."""
    dl.notify(sem, peer=peer, inc=inc, axis=axis, mesh_axes=mesh_axes)


def signal_wait_until(sem, cmp: int, value):
    """Wait until the local signal reaches ``value``
    (reference libshmem_device.signal_wait_until).

    TPU semaphores implement *wait-for-at-least-value-then-decrement*;
    CMP_GE maps exactly. CMP_EQ is accepted because the reference kernels
    use it on monotonic flags where EQ and GE coincide (e.g.
    low_latency_all_to_all.py signal_wait_until(EQ, call_count)) — true
    exact-equality gating on an over-signaled semaphore is NOT expressible.
    """
    assert cmp in (CMP_EQ, CMP_GE), "TPU semaphores support GE-style waits"
    dl.wait(sem, value)


def fence(*copies):
    """Order prior puts before subsequent ones (reference
    libshmem_device.fence): wait for the given descriptors' local sends to
    complete. ICI delivers a single put's data in order, so send-completion
    is sufficient for producer-side ordering."""
    for c in copies:
        c.wait_send()


def quiet(*copies):
    """Complete the *send side* of all given puts (reference
    libshmem_device.quiet).

    Note: a put's delivery is observed by the RECEIVER via its recv
    semaphore (which the transport signals); the sender cannot wait on it.
    Receivers must ``signal_wait_until``/``dl.wait`` their recv semaphore
    before reading — same contract as NVSHMEM putmem_signal + wait.
    """
    for c in copies:
        c.wait_send()


def barrier_all(axis: str = "tp", mesh_axes=None):
    dl.barrier_all(axis, mesh_axes)
