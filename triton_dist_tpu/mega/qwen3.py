"""Qwen3 decode step as a mega task graph.

TPU-native redesign of the reference's mega-kernel Qwen3 integration
(python/triton_dist/mega_triton_kernel/models/qwen3.py:201: records the
whole decoder step op-by-op through ModelBuilder, then launches the
persistent kernel each step). Here the recorded graph jits into one XLA
program replayed per decode step; numerics match
``DenseLLM.forward(mode="gemm_ar")`` exactly (test_mega.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from triton_dist_tpu.mega.builder import ModelBuilder
from triton_dist_tpu.models.dense import DenseLLM


class MegaQwen3:
    """One-program decode step for a DenseLLM (reference bench target:
    mega_triton_kernel.md decode latencies, SURVEY.md §6)."""

    def __init__(self, model: DenseLLM, decode_mode: str = "gemm_ar",
                 order_policy: str = "topo"):
        self.model = model
        self.decode_mode = decode_mode
        self.order_policy = order_policy
        c = model.config
        model.attn.set_fwd(decode_mode)
        b = ModelBuilder(model.mesh, model.axis, impl=model.attn.impl,
                         rms_eps=c.rms_norm_eps)
        self.builder = b

        inputs = ["ids", "pos", "offset", "rope", "embed", "final_norm",
                  "lm_head"]
        outputs = []
        b.make_embedding("embed", "ids", "x0")
        x = "x0"
        for i in range(c.num_hidden_layers):
            p = f"l{i}."
            inputs += [p + "attn", p + "ln_attn", p + "w_gate", p + "w_up",
                       p + "w_down", p + "ln_mlp", p + "ck", p + "cv"]
            b.make_rms_norm(x, p + "ln_attn", p + "h_attn")
            b.make_attention(model.attn, p + "h_attn", p + "attn", "pos",
                             "rope", p + "ck", p + "cv", "offset",
                             p + "a", p + "nk", p + "nv",
                             name=f"attn{i}")
            outputs += [p + "nk", p + "nv"]
            b.make_add(x, p + "a", p + "x_mid")
            b.make_rms_norm(p + "x_mid", p + "ln_mlp", p + "h_mlp")
            b.make_linear_col(p + "h_mlp", p + "w_gate", p + "gate",
                              name=f"gate{i}")
            b.make_linear_col(p + "h_mlp", p + "w_up", p + "up",
                              name=f"up{i}")
            b.make_silu_mul(p + "gate", p + "up", p + "act")
            b.make_linear_ar(p + "act", p + "w_down", p + "down",
                             name=f"down{i}")
            b.make_add(p + "x_mid", p + "down", p + "x_out")
            x = p + "x_out"
        b.make_rms_norm(x, "final_norm", "x_final")
        b.make_lm_head("x_final", "lm_head", "logits")
        self._input_names = inputs
        self._output_names = ["logits"] + outputs
        self._step = b.compile(inputs, self._output_names,
                               order_policy=order_policy)

    @property
    def graph(self):
        return self.builder.graph

    def flat_args(self, params: dict, token: jax.Array, kv_caches,
                  offset) -> list:
        """The executor's positional argument list (also used by
        bench.py to lower the program for memory analysis)."""
        bsz, s = token.shape
        offset = jnp.asarray(offset, jnp.int32)
        pos = offset + jnp.tile(jnp.arange(s, dtype=jnp.int32)[None],
                                (bsz, 1))
        args = {
            "ids": token, "pos": pos, "offset": offset,
            "rope": self.model.rope_cache,
            "embed": params["embed"], "final_norm": params["final_norm"],
            "lm_head": params["lm_head"],
        }
        for i, (lp, (ck, cv)) in enumerate(zip(params["layers"],
                                               kv_caches)):
            p = f"l{i}."
            args[p + "attn"] = lp["attn"]
            args[p + "ln_attn"] = lp["ln_attn"]
            args[p + "ln_mlp"] = lp["ln_mlp"]
            args[p + "w_gate"] = lp["mlp"]["w_gate"]
            args[p + "w_up"] = lp["mlp"]["w_up"]
            args[p + "w_down"] = lp["mlp"]["w_down"]
            args[p + "ck"], args[p + "cv"] = ck, cv
        return [args[n] for n in self._input_names]

    def step(self, params: dict, token: jax.Array, kv_caches, offset):
        """token: (B, 1) int32 → (logits (B, 1, V), new_caches)."""
        c = self.model.config
        bsz, s = token.shape
        out = self._step(*self.flat_args(params, token, kv_caches,
                                         offset))
        logits, flat = out[0], out[1:]
        caches = [(flat[2 * i], flat[2 * i + 1])
                  for i in range(c.num_hidden_layers)]
        return logits.reshape(bsz, s, c.vocab_size), caches
