"""Qwen3 decode step as a mega task graph.

TPU-native redesign of the reference's mega-kernel Qwen3 integration
(python/triton_dist/mega_triton_kernel/models/qwen3.py:201: records the
whole decoder step op-by-op through ModelBuilder, then launches the
persistent kernel each step). Here the recorded graph jits into one XLA
program replayed per decode step; numerics match the plain forward
exactly (test_mega.py, tests/test_scheduler.py).

Two graph families, selected by ``decode_mode`` (ISSUE 11):

* dense tp (``gemm_ar``/``xla_ar``/...): the TP fused-op tasks over
  contiguous (B, T, Hkv, D) caches, matching
  ``DenseLLM.forward(mode=decode_mode)``;
* ``"sp"`` (± ``paged``): forward_sp's decode ops over the seq-sharded
  cache or the paged pools, matching
  ``DenseLLM.forward_sp`` — the continuous-batching scheduler's
  native substrate.

Both take ``offset`` as a scalar OR a (B,) per-row vector (every row
decodes at its own cache position — the shared-batch stream step), the
dense family additionally takes ragged ``kv_start`` boundaries, and the
paged family takes the block table. That is what lets ``Engine``'s
scheduler pump the mega step like any other decode forward instead of
refusing paged/ragged configurations.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from triton_dist_tpu.mega.builder import ModelBuilder
from triton_dist_tpu.models.dense import DenseLLM


class MegaQwen3:
    """One-program decode step for a DenseLLM (reference bench target:
    mega_triton_kernel.md decode latencies, SURVEY.md §6)."""

    def __init__(self, model: DenseLLM, decode_mode: str = "gemm_ar",
                 order_policy: str = "topo", paged: bool = False):
        self.model = model
        self.decode_mode = decode_mode
        self.order_policy = order_policy
        self.sp = decode_mode == "sp"
        self.paged = bool(paged)
        if self.paged and not self.sp:
            raise ValueError("paged mega decode rides the sp cache "
                             "layout — pass decode_mode='sp'")
        c = model.config
        if self.sp:
            # ValueError, not assert: user-facing configuration
            # validation must survive ``python -O`` (same contract as
            # Engine's decode_path checks).
            if not getattr(model, "sp_axis", None):
                raise ValueError(
                    "mega sp decode needs a model built with sp_axis=...")
        else:
            model.attn.set_fwd(decode_mode)
        b = ModelBuilder(model.mesh, model.axis, impl=model.attn.impl,
                         rms_eps=c.rms_norm_eps)
        self.builder = b

        inputs = ["ids", "pos", "offset", "rope", "embed", "final_norm",
                  "lm_head"]
        if self.sp:
            if self.paged:
                inputs.append("table")
        else:
            inputs.append("kv_start")
        outputs = []
        if self.sp:
            b.make_embedding_sp("embed", "ids", "x0")
        else:
            b.make_embedding("embed", "ids", "x0")
        x = "x0"
        for i in range(c.num_hidden_layers):
            p = f"l{i}."
            inputs += [p + "attn", p + "ln_attn", p + "w_gate", p + "w_up",
                       p + "w_down", p + "ln_mlp", p + "ck", p + "cv"]
            b.make_rms_norm(x, p + "ln_attn", p + "h_attn")
            if self.sp:
                b.make_attention_sp(
                    model, p + "h_attn", p + "attn", "pos", "rope",
                    p + "ck", p + "cv", "offset", p + "a", p + "nk",
                    p + "nv", table="table" if self.paged else None,
                    name=f"attn{i}")
            else:
                b.make_attention(model.attn, p + "h_attn", p + "attn",
                                 "pos", "rope", p + "ck", p + "cv",
                                 "offset", "kv_start",
                                 p + "a", p + "nk", p + "nv",
                                 name=f"attn{i}")
            outputs += [p + "nk", p + "nv"]
            b.make_add(x, p + "a", p + "x_mid")
            b.make_rms_norm(p + "x_mid", p + "ln_mlp", p + "h_mlp")
            if self.sp:
                b.make_linear_sp(p + "h_mlp", p + "w_gate", p + "gate",
                                 name=f"gate{i}")
                b.make_linear_sp(p + "h_mlp", p + "w_up", p + "up",
                                 name=f"up{i}")
                b.make_silu_mul_sp(p + "gate", p + "up", p + "act")
                b.make_linear_down_sp(p + "act", p + "w_down", p + "down",
                                      name=f"down{i}")
            else:
                b.make_linear_col(p + "h_mlp", p + "w_gate", p + "gate",
                                  name=f"gate{i}")
                b.make_linear_col(p + "h_mlp", p + "w_up", p + "up",
                                  name=f"up{i}")
                b.make_silu_mul(p + "gate", p + "up", p + "act")
                b.make_linear_ar(p + "act", p + "w_down", p + "down",
                                 name=f"down{i}")
            b.make_add(p + "x_mid", p + "down", p + "x_out")
            x = p + "x_out"
        b.make_rms_norm(x, "final_norm", "x_final")
        if self.sp:
            b.make_lm_head_sp("x_final", "lm_head", "logits")
        else:
            b.make_lm_head("x_final", "lm_head", "logits")
        self._input_names = inputs
        self._output_names = ["logits"] + outputs
        self._step = b.compile(inputs, self._output_names,
                               order_policy=order_policy)

    @property
    def graph(self):
        return self.builder.graph

    def flat_args(self, params: dict, token: jax.Array, kv_caches,
                  offset, kv_start=None, table=None) -> list:
        """The executor's positional argument list (also used by
        bench.py to lower the program for memory analysis).

        ``offset``: scalar or (B,) per-row decode positions.
        ``kv_start`` (dense family): (B,) ragged left-pad boundaries;
        ``None`` means the uniform batch (zeros — bit-identical to the
        plain forward called without kv_start). ``table`` (paged
        family): the (w, B, n_pages) device block table."""
        bsz, s = token.shape
        offset = jnp.asarray(offset, jnp.int32)
        off2d = offset[:, None] if offset.ndim else offset
        pos = off2d + jnp.tile(jnp.arange(s, dtype=jnp.int32)[None],
                               (bsz, 1))
        args = {
            "ids": token, "offset": offset,
            "rope": self.model.rope_cache,
            "embed": params["embed"], "final_norm": params["final_norm"],
            "lm_head": params["lm_head"],
        }
        # ValueErrors, not asserts: these are caller-facing contract
        # checks (they fire at trace time) and must survive python -O.
        if self.sp:
            if kv_start is not None:
                raise ValueError("mode='sp' has no ragged support yet")
            if self.paged:
                if table is None:
                    raise ValueError(
                        "paged mega step needs the block table")
                args["table"] = table
            elif table is not None:
                raise ValueError(
                    "block tables need MegaQwen3(paged=True)")
        else:
            if table is not None:
                raise ValueError("paged tables ride the sp mega graph")
            ks = (jnp.zeros((bsz,), jnp.int32) if kv_start is None
                  else jnp.asarray(kv_start, jnp.int32))
            # Same clamp the plain forward applies for ragged batches
            # (zeros leave pos untouched — the uniform case stays
            # bit-identical).
            pos = jnp.maximum(pos - ks[:, None], 0)
            args["kv_start"] = ks
        args["pos"] = pos
        for i, (lp, (ck, cv)) in enumerate(zip(params["layers"],
                                               kv_caches)):
            p = f"l{i}."
            args[p + "attn"] = lp["attn"]
            args[p + "ln_attn"] = lp["ln_attn"]
            args[p + "ln_mlp"] = lp["ln_mlp"]
            args[p + "w_gate"] = lp["mlp"]["w_gate"]
            args[p + "w_up"] = lp["mlp"]["w_up"]
            args[p + "w_down"] = lp["mlp"]["w_down"]
            args[p + "ck"], args[p + "cv"] = ck, cv
        return [args[n] for n in self._input_names]

    def step(self, params: dict, token: jax.Array, kv_caches, offset,
             kv_start=None, table=None):
        """token: (B, 1) int32 → (logits (B, 1, V), new_caches)."""
        c = self.model.config
        bsz, s = token.shape
        out = self._step(*self.flat_args(params, token, kv_caches, offset,
                                         kv_start=kv_start, table=table))
        logits, flat = out[0], out[1:]
        caches = [(flat[2 * i], flat[2 * i + 1])
                  for i in range(c.num_hidden_layers)]
        return logits.reshape(bsz, s, c.vocab_size), caches
