"""Mega runtime: whole-decoder-step fusion (reference L8:
python/triton_dist/mega_triton_kernel/ — task graph + scheduler +
persistent MEGA_TRITON_KERNEL). On TPU the task graph compiles into one
jitted XLA program (see mega/task_graph.py for the design translation);
scheduling/dependency resolution is native C++ (csrc/scheduler).
"""

from triton_dist_tpu.mega.task_graph import Task, TaskGraph  # noqa: F401
from triton_dist_tpu.mega.builder import ModelBuilder  # noqa: F401
from triton_dist_tpu.mega.qwen3 import MegaQwen3  # noqa: F401
