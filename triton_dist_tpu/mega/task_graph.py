"""Task graph IR for the mega (fused decode step) runtime.

TPU-native redesign of the reference's MegaTritonKernel task machinery
(python/triton_dist/mega_triton_kernel/core/task_base.py:150-220:
``TaskBase`` encoding (task_type, layer_id, task_id, tiles, deps, io
tensors) into int32 structs; core/builder.py:62 ``TaskBuilder``).

Key design translation (SURVEY.md §7 stage 8): the reference needs the
task encoding because its persistent kernel *interprets* task structs at
runtime and a device scoreboard orders producers/consumers
(kernels/task_context.py). Under XLA the whole decode step compiles into
one program, so ordering is SSA dataflow and the "scoreboard" is the
compiler's dependence graph — the task graph here exists at *build* time:
it records ops + buffers, resolves dependencies (native toposort /
wavefronts, mega/native.py), and the executor emits one fused jit
program. Launch-overhead parity with the persistent megakernel comes from
replaying that single compiled program per step.
"""

from __future__ import annotations

import dataclasses
from collections.abc import Callable, Sequence

import numpy as np

from triton_dist_tpu.mega import native


@dataclasses.dataclass
class Task:
    """One node (reference TaskBase: task_type ≙ op, layer_id/tag in name)."""
    id: int
    op: str
    name: str
    fn: Callable
    inputs: tuple[str, ...]
    outputs: tuple[str, ...]
    meta: dict


class TaskGraph:
    """Append-only op recorder + dependency resolver."""

    def __init__(self):
        self.tasks: list[Task] = []
        self._producer: dict[str, int] = {}

    def add(self, op: str, fn: Callable, inputs: Sequence[str],
            outputs: Sequence[str], name: str | None = None,
            **meta) -> tuple[str, ...]:
        tid = len(self.tasks)
        task = Task(id=tid, op=op, name=name or f"{op}_{tid}", fn=fn,
                    inputs=tuple(inputs), outputs=tuple(outputs), meta=meta)
        for o in task.outputs:
            if o in self._producer:
                raise ValueError(f"buffer {o!r} written twice (SSA only)")
            self._producer[o] = tid
        self.tasks.append(task)
        return task.outputs

    # -- dependency resolution (reference ModelBuilder dep resolution) -----
    def edges(self) -> np.ndarray:
        """(E, 2) producer→consumer edges via buffer names."""
        es = []
        for t in self.tasks:
            for i in t.inputs:
                p = self._producer.get(i)
                if p is not None and p != t.id:
                    es.append((p, t.id))
        return np.asarray(sorted(set(es)), np.int32).reshape(-1, 2)

    def order(self) -> np.ndarray:
        return native.toposort(len(self.tasks), self.edges())

    def waves(self) -> tuple[int, np.ndarray]:
        return native.wavefronts(len(self.tasks), self.edges())

    def priority_order(self) -> np.ndarray:
        """HEFT priority linearization of the graph (descending upward
        rank) — a valid topological order that :meth:`make_executor`
        can EMIT in (``order_policy="heft"``). NOTE (r5): emission
        order does NOT change the compiled program — XLA schedules the
        dataflow graph and normalizes instruction order away (measured:
        identical temp bytes and step times across orders; experiments
        in docs/architecture.md "Mega scheduler", pinned by
        tests/test_mega.py::test_heft_emission_inert_under_xla). The
        order's value is observability: it documents the critical path
        and feeds :meth:`makespan`'s perf model."""
        costs = [t.meta.get("cost", 1) for t in self.tasks]
        return native.priority_order(len(self.tasks), self.edges(),
                                     costs=costs)

    def queue_assignment(self, n_queues: int,
                         policy: str = "zigzag") -> np.ndarray:
        """Static queue assignment in execution order (reference
        ``enque_tasks`` core/scheduler.py:86). The queue ids are
        observability/parity metadata on TPU — XLA owns placement, and
        emission order is inert too (see :meth:`priority_order`).
        ``policy="critical_path"`` is dependency-aware (HEFT list
        scheduling over this graph's edges; see :meth:`makespan`)."""
        if policy == "critical_path":
            return self.critical_path_schedule(n_queues)[0]
        costs = [t.meta.get("cost", 1) for t in self.tasks]
        return native.schedule(len(self.tasks), n_queues, policy,
                               costs=costs)

    def critical_path_schedule(self, n_queues: int):
        """(queue_of_task, makespan) from one HEFT run — use this when
        both are wanted (each wrapper below re-runs the scheduler)."""
        costs = [t.meta.get("cost", 1) for t in self.tasks]
        return native.schedule_critical_path(
            len(self.tasks), self.edges(), n_queues, costs=costs)

    def makespan(self, n_queues: int) -> int:
        """Critical-path makespan on ``n_queues``-way hardware — a
        speed-of-light perf model of this graph (cost units = task
        ``meta["cost"]``)."""
        return self.critical_path_schedule(n_queues)[1]

    # -- execution ---------------------------------------------------------
    def make_executor(self, input_names: Sequence[str],
                      output_names: Sequence[str],
                      order_policy: str = "topo") -> Callable:
        """Build ``run(*inputs) -> outputs`` executing tasks in a valid
        linear order — trace it under ``jax.jit`` to get the single
        fused program (the MEGA kernel analog,
        core/code_generator.py:31-92). ``order_policy``: "topo" (stable
        Kahn) or "heft" (:meth:`priority_order`). The two compile to
        the same program under XLA (see :meth:`priority_order`)."""
        ids = (self.priority_order() if order_policy == "heft"
               else self.order())
        order = [self.tasks[i] for i in ids]
        input_names = tuple(input_names)
        output_names = tuple(output_names)

        def run(*args):
            env = dict(zip(input_names, args, strict=True))
            for t in order:
                res = t.fn(*[env[i] for i in t.inputs])
                if not isinstance(res, tuple):
                    res = (res,)
                env.update(zip(t.outputs, res, strict=True))
            outs = tuple(env[o] for o in output_names)
            return outs if len(outs) > 1 else outs[0]

        return run

    def summary(self) -> str:
        n_waves, wave = self.waves()
        lines = [f"TaskGraph: {len(self.tasks)} tasks, {n_waves} waves"]
        for t in self.tasks:
            lines.append(
                f"  [{t.id:3d}] w{wave[t.id]:<3d} {t.op:<12s} {t.name} "
                f"{list(t.inputs)} -> {list(t.outputs)}")
        return "\n".join(lines)
