"""ctypes bindings for the native scheduler (csrc/scheduler/scheduler.cc).

Reference analog: the mega runtime's scheduler + ModelBuilder dependency
resolution (mega_triton_kernel/core/scheduler.py:40-95,
models/model_builder.py) — kept native like the reference's csrc/
components. Falls back to pure-Python implementations when no compiler is
available (results are bit-identical; tests assert so).
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "..", "csrc", "scheduler",
                    "scheduler.cc")
_SO = os.path.join(os.path.dirname(_SRC), "libtdtsched.so")
_LIB = None
_TRIED = False


def _configure(lib):
    lib.tdt_toposort.restype = ctypes.c_int32
    lib.tdt_wavefronts.restype = ctypes.c_int32
    lib.tdt_schedule_critical_path.restype = ctypes.c_int64
    lib.tdt_priority_order.restype = ctypes.c_int32


def _load():
    global _LIB, _TRIED
    if not _TRIED:
        _TRIED = True
        from triton_dist_tpu.runtime.native_lib import load_native
        _LIB = load_native(_SRC, _SO, _configure)
    return _LIB


def have_native() -> bool:
    return _load() is not None


def _i32(a):
    return np.ascontiguousarray(a, np.int32)


def schedule(n_tasks: int, n_queues: int, policy: str = "round_robin",
             costs=None) -> np.ndarray:
    """Assign tasks to queues. Policies: round_robin | zigzag |
    least_loaded (reference ROUND_ROBIN / ZIG_ZAG, scheduler.py:86)."""
    lib = _load()
    out = np.empty(n_tasks, np.int32)
    if lib is not None:
        p = out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        if policy == "round_robin":
            lib.tdt_schedule_round_robin(n_tasks, n_queues, p)
        elif policy == "zigzag":
            lib.tdt_schedule_zigzag(n_tasks, n_queues, p)
        elif policy == "least_loaded":
            c = (np.ascontiguousarray(costs, np.int64)
                 .ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
                 if costs is not None else None)
            lib.tdt_schedule_least_loaded(n_tasks, n_queues, c, p)
        else:
            raise ValueError(policy)
        return out
    return _schedule_py(n_tasks, n_queues, policy, costs)


def _schedule_py(n_tasks, n_queues, policy, costs=None) -> np.ndarray:
    out = np.empty(n_tasks, np.int32)
    if policy == "round_robin":
        out[:] = np.arange(n_tasks) % n_queues
    elif policy == "zigzag":
        r = np.arange(n_tasks) % (2 * n_queues)
        out[:] = np.where(r < n_queues, r, 2 * n_queues - 1 - r)
    elif policy == "least_loaded":
        load = np.zeros(n_queues, np.int64)
        c = (np.asarray(costs, np.int64) if costs is not None
             else np.ones(n_tasks, np.int64))
        for i in range(n_tasks):
            q = int(np.argmin(load))
            out[i] = q
            load[q] += c[i]
    else:
        raise ValueError(policy)
    return out


def schedule_critical_path(n_tasks: int, edges, n_queues: int,
                           costs=None) -> tuple[np.ndarray, int]:
    """HEFT-style dependency-aware list scheduling: tasks prioritized by
    upward rank (longest cost-weighted path to a sink), each placed on
    the queue with the earliest dependency-respecting start.

    Returns (queue_of_task, makespan). The makespan is a
    speed-of-light estimate of the fused step on ``n_queues``-way
    hardware — usable as a perf model for the mega graph. Raises on
    cycles. Native C++ with a bit-identical Python fallback.

    Costs must be >= 0 (zero is fine for free ops like reshapes; rank
    ties are broken in topological order so dependencies hold).
    """
    if costs is not None and int(np.min(np.asarray(costs))) < 0:
        raise ValueError("costs must be >= 0")
    edges = _i32(np.asarray(edges).reshape(-1, 2))
    lib = _load()
    if lib is not None:
        out = np.empty(n_tasks, np.int32)
        c = (np.ascontiguousarray(costs, np.int64)
             .ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
             if costs is not None else None)
        span = lib.tdt_schedule_critical_path(
            n_tasks, len(edges),
            edges.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            n_queues, c,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if span < 0:
            raise ValueError("task graph has a cycle")
        return out, int(span)
    return _schedule_critical_path_py(n_tasks, edges, n_queues, costs)


def _schedule_critical_path_py(n_tasks, edges, n_queues,
                               costs=None) -> tuple[np.ndarray, int]:
    c = (np.asarray(costs, np.int64) if costs is not None
         else np.ones(n_tasks, np.int64))
    children = [[] for _ in range(n_tasks)]
    parents = [[] for _ in range(n_tasks)]
    for s, d in edges:
        children[s].append(int(d))
        parents[d].append(int(s))
    # upward ranks in reverse topological order
    order = _toposort_py(n_tasks, edges)
    pos = np.empty(n_tasks, np.int64)
    pos[order] = np.arange(n_tasks)
    rank = np.zeros(n_tasks, np.int64)
    for t in reversed(order):
        best = max((rank[ch] for ch in children[t]), default=0)
        rank[t] = c[t] + best
    # ties broken by topo position (zero-cost parents must precede)
    prio = sorted(range(n_tasks), key=lambda i: (-rank[i], pos[i]))
    queue_free = np.zeros(n_queues, np.int64)
    finish = np.zeros(n_tasks, np.int64)
    out = np.empty(n_tasks, np.int32)
    makespan = 0
    for t in prio:
        ready = max((finish[p] for p in parents[t]), default=0)
        starts = np.maximum(queue_free, ready)
        q = int(np.argmin(starts))
        out[t] = q
        finish[t] = starts[q] + c[t]
        queue_free[q] = finish[t]
        makespan = max(makespan, int(finish[t]))
    return out, makespan


def priority_order(n_tasks: int, edges, costs=None) -> np.ndarray:
    """HEFT priority linearization: task ids in (descending upward
    rank, ties by topological position) — the visit order of
    :func:`schedule_critical_path`, and itself a valid topological
    order (a parent's rank exceeds any child's by >= its own cost;
    zero-cost ties fall back to topo position).

    This is the schedule's RUNTIME hook: the mega executor emits tasks
    in this order, which biases XLA's buffer-liveness/latency-hiding
    scheduling toward the critical path (bench.py's mega part measures
    the peak-temp-memory effect; VERDICT r3 weak-4 wiring)."""
    edges = _i32(np.asarray(edges).reshape(-1, 2))
    lib = _load()
    if lib is not None:
        out = np.empty(n_tasks, np.int32)
        c = (np.ascontiguousarray(costs, np.int64)
             .ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
             if costs is not None else None)
        rc = lib.tdt_priority_order(
            n_tasks, len(edges),
            edges.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            c, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if rc != 0:
            raise ValueError("task graph has a cycle")
        return out
    return _priority_order_py(n_tasks, edges, costs)


def _priority_order_py(n_tasks, edges, costs=None) -> np.ndarray:
    c = (np.asarray(costs, np.int64) if costs is not None
         else np.ones(n_tasks, np.int64))
    children = [[] for _ in range(n_tasks)]
    for s, d in edges:
        children[s].append(int(d))
    order = _toposort_py(n_tasks, edges)
    pos = np.empty(n_tasks, np.int64)
    pos[order] = np.arange(n_tasks)
    rank = np.zeros(n_tasks, np.int64)
    for t in reversed(order):
        best = max((rank[ch] for ch in children[t]), default=0)
        rank[t] = c[t] + best
    return np.asarray(
        sorted(range(n_tasks), key=lambda i: (-rank[i], pos[i])),
        np.int32)


def toposort(n_tasks: int, edges) -> np.ndarray:
    """Stable topological order (ties by task id). edges: (E, 2) int
    (src, dst). Raises on cycles."""
    edges = _i32(np.asarray(edges).reshape(-1, 2))
    lib = _load()
    if lib is not None:
        out = np.empty(n_tasks, np.int32)
        rc = lib.tdt_toposort(
            n_tasks, len(edges),
            edges.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if rc != 0:
            raise ValueError("task graph has a cycle")
        return out
    return _toposort_py(n_tasks, edges)


def _toposort_py(n_tasks, edges) -> np.ndarray:
    import heapq
    adj = [[] for _ in range(n_tasks)]
    indeg = [0] * n_tasks
    for s, d in edges:
        adj[s].append(int(d))
        indeg[d] += 1
    ready = [i for i in range(n_tasks) if indeg[i] == 0]
    heapq.heapify(ready)
    order = []
    while ready:
        t = heapq.heappop(ready)
        order.append(t)
        for d in adj[t]:
            indeg[d] -= 1
            if indeg[d] == 0:
                heapq.heappush(ready, d)
    if len(order) != n_tasks:
        raise ValueError("task graph has a cycle")
    return np.asarray(order, np.int32)


def wavefronts(n_tasks: int, edges) -> tuple[int, np.ndarray]:
    """(n_waves, wave_of_task): longest-path depth partition — fusion
    groups for the jit executor (scoreboard-phase analog)."""
    edges = _i32(np.asarray(edges).reshape(-1, 2))
    lib = _load()
    if lib is not None:
        out = np.empty(n_tasks, np.int32)
        n = lib.tdt_wavefronts(
            n_tasks, len(edges),
            edges.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if n < 0:
            raise ValueError("task graph has a cycle")
        return int(n), out
    return _wavefronts_py(n_tasks, edges)


def _wavefronts_py(n_tasks, edges) -> tuple[int, np.ndarray]:
    order = _toposort_py(n_tasks, edges)
    depth = np.zeros(n_tasks, np.int32)
    adj = [[] for _ in range(n_tasks)]
    for s, d in edges:
        adj[s].append(int(d))
    for t in order:
        for d in adj[t]:
            depth[d] = max(depth[d], depth[t] + 1)
    return int(depth.max(initial=0)) + 1, depth
