"""ctypes bindings for the native scheduler (csrc/scheduler/scheduler.cc).

Reference analog: the mega runtime's scheduler + ModelBuilder dependency
resolution (mega_triton_kernel/core/scheduler.py:40-95,
models/model_builder.py) — kept native like the reference's csrc/
components. Falls back to pure-Python implementations when no compiler is
available (results are bit-identical; tests assert so).
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "..", "csrc", "scheduler",
                    "scheduler.cc")
_SO = os.path.join(os.path.dirname(_SRC), "libtdtsched.so")
_LIB = None
_TRIED = False


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    src, so = os.path.abspath(_SRC), os.path.abspath(_SO)
    try:
        if (not os.path.exists(so)
                or os.path.getmtime(so) < os.path.getmtime(src)):
            subprocess.run(
                ["g++", "-shared", "-fPIC", "-O2", "-o", so, src],
                check=True, capture_output=True)
        lib = ctypes.CDLL(so)
        lib.tdt_toposort.restype = ctypes.c_int32
        lib.tdt_wavefronts.restype = ctypes.c_int32
        _LIB = lib
    except (OSError, subprocess.CalledProcessError):
        _LIB = None
    return _LIB


def have_native() -> bool:
    return _load() is not None


def _i32(a):
    return np.ascontiguousarray(a, np.int32)


def schedule(n_tasks: int, n_queues: int, policy: str = "round_robin",
             costs=None) -> np.ndarray:
    """Assign tasks to queues. Policies: round_robin | zigzag |
    least_loaded (reference ROUND_ROBIN / ZIG_ZAG, scheduler.py:86)."""
    lib = _load()
    out = np.empty(n_tasks, np.int32)
    if lib is not None:
        p = out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        if policy == "round_robin":
            lib.tdt_schedule_round_robin(n_tasks, n_queues, p)
        elif policy == "zigzag":
            lib.tdt_schedule_zigzag(n_tasks, n_queues, p)
        elif policy == "least_loaded":
            c = (np.ascontiguousarray(costs, np.int64)
                 .ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
                 if costs is not None else None)
            lib.tdt_schedule_least_loaded(n_tasks, n_queues, c, p)
        else:
            raise ValueError(policy)
        return out
    return _schedule_py(n_tasks, n_queues, policy, costs)


def _schedule_py(n_tasks, n_queues, policy, costs=None) -> np.ndarray:
    out = np.empty(n_tasks, np.int32)
    if policy == "round_robin":
        out[:] = np.arange(n_tasks) % n_queues
    elif policy == "zigzag":
        r = np.arange(n_tasks) % (2 * n_queues)
        out[:] = np.where(r < n_queues, r, 2 * n_queues - 1 - r)
    elif policy == "least_loaded":
        load = np.zeros(n_queues, np.int64)
        c = (np.asarray(costs, np.int64) if costs is not None
             else np.ones(n_tasks, np.int64))
        for i in range(n_tasks):
            q = int(np.argmin(load))
            out[i] = q
            load[q] += c[i]
    else:
        raise ValueError(policy)
    return out


def toposort(n_tasks: int, edges) -> np.ndarray:
    """Stable topological order (ties by task id). edges: (E, 2) int
    (src, dst). Raises on cycles."""
    edges = _i32(np.asarray(edges).reshape(-1, 2))
    lib = _load()
    if lib is not None:
        out = np.empty(n_tasks, np.int32)
        rc = lib.tdt_toposort(
            n_tasks, len(edges),
            edges.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if rc != 0:
            raise ValueError("task graph has a cycle")
        return out
    return _toposort_py(n_tasks, edges)


def _toposort_py(n_tasks, edges) -> np.ndarray:
    import heapq
    adj = [[] for _ in range(n_tasks)]
    indeg = [0] * n_tasks
    for s, d in edges:
        adj[s].append(int(d))
        indeg[d] += 1
    ready = [i for i in range(n_tasks) if indeg[i] == 0]
    heapq.heapify(ready)
    order = []
    while ready:
        t = heapq.heappop(ready)
        order.append(t)
        for d in adj[t]:
            indeg[d] -= 1
            if indeg[d] == 0:
                heapq.heappush(ready, d)
    if len(order) != n_tasks:
        raise ValueError("task graph has a cycle")
    return np.asarray(order, np.int32)


def wavefronts(n_tasks: int, edges) -> tuple[int, np.ndarray]:
    """(n_waves, wave_of_task): longest-path depth partition — fusion
    groups for the jit executor (scoreboard-phase analog)."""
    edges = _i32(np.asarray(edges).reshape(-1, 2))
    lib = _load()
    if lib is not None:
        out = np.empty(n_tasks, np.int32)
        n = lib.tdt_wavefronts(
            n_tasks, len(edges),
            edges.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if n < 0:
            raise ValueError("task graph has a cycle")
        return int(n), out
    return _wavefronts_py(n_tasks, edges)


def _wavefronts_py(n_tasks, edges) -> tuple[int, np.ndarray]:
    order = _toposort_py(n_tasks, edges)
    depth = np.zeros(n_tasks, np.int32)
    adj = [[] for _ in range(n_tasks)]
    for s, d in edges:
        adj[s].append(int(d))
    for t in order:
        for d in adj[t]:
            depth[d] = max(depth[d], depth[t] + 1)
    return int(depth.max(initial=0)) + 1, depth
