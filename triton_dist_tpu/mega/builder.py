"""ModelBuilder: records decoder ops layer-by-layer into a TaskGraph.

TPU-native redesign of the reference's ``ModelBuilder``
(python/triton_dist/mega_triton_kernel/models/model_builder.py:408:
``make_linear / make_rms_norm / make_activation / make_flash_decode /
make_allreduce ...`` task builders, tasks/{linear,attn,norm,activation,
elementwise,allreduce}.py) — the recorded graph compiles to ONE jitted
program per step instead of one persistent interpreted kernel.

Ops carry the same roles as the reference task kinds: linear (TP
col/row), rmsnorm, activation (silu·mul), elementwise add, attention
(cached GQA decode), allreduce epilogue (fused gemm_ar). The barrier /
prefetch task kinds collapse: XLA inserts synchronization and HBM→VMEM
prefetch itself.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
from jax.sharding import Mesh

from triton_dist_tpu.layers.common import (
    col_parallel_matmul, rms_norm, row_parallel_matmul_ar)
from triton_dist_tpu.mega.task_graph import TaskGraph
from triton_dist_tpu.ops.gemm_reduce_scatter import (
    create_gemm_rs_context, gemm_ar)


class ModelBuilder:
    """Record ops into a TaskGraph with TP-aware linear tasks."""

    def __init__(self, mesh: Mesh | None = None, axis: str = "tp",
                 impl: str = "pallas", rms_eps: float = 1e-6):
        if mesh is None:
            from triton_dist_tpu.runtime.dist import get_mesh
            mesh = get_mesh()
        self.mesh, self.axis = mesh, axis
        self.impl = impl
        self.rms_eps = rms_eps
        self.graph = TaskGraph()
        self.rs_ctx = create_gemm_rs_context(mesh, axis)

    # -- task builders (reference tasks/*.py) ------------------------------
    def make_rms_norm(self, x: str, w: str, out: str, name=None) -> str:
        fn = functools.partial(rms_norm, eps=self.rms_eps)
        return self.graph.add("rmsnorm", fn, [x, w], [out], name=name)[0]

    def make_linear_col(self, x: str, w: str, out: str, name=None) -> str:
        """Column-parallel GEMM: replicated (M,K) @ col-sharded (K,N/w)."""
        fn = functools.partial(col_parallel_matmul, mesh=self.mesh,
                               axis=self.axis)
        return self.graph.add("linear", fn, [x, w], [out], name=name,
                              cost=4)[0]

    def make_linear_ar(self, x: str, w: str, out: str, name=None) -> str:
        """Row-parallel GEMM + AllReduce epilogue (reference allreduce
        task over symm ptrs ≙ fused gemm_ar kernel)."""
        if self.impl == "xla":
            fn = functools.partial(row_parallel_matmul_ar, mesh=self.mesh,
                                   axis=self.axis)
        else:
            def fn(xv, wv):
                return gemm_ar(xv, wv, self.rs_ctx, impl=self.impl)
        return self.graph.add("linear_ar", fn, [x, w], [out], name=name,
                              cost=6)[0]

    def make_silu_mul(self, gate: str, up: str, out: str, name=None) -> str:
        def fn(g, u):
            import jax
            return (jax.nn.silu(g.astype(jnp.float32)) *
                    u.astype(jnp.float32)).astype(g.dtype)
        return self.graph.add("activation", fn, [gate, up], [out],
                              name=name)[0]

    def make_add(self, a: str, b: str, out: str, name=None) -> str:
        return self.graph.add("elementwise", lambda x, y: x + y, [a, b],
                              [out], name=name)[0]

    def make_attention(self, attn_module, qkv_norm_x: str, attn_params: str,
                       position_ids: str, rope: str, cache_k: str,
                       cache_v: str, offset: str, kv_start: str, out: str,
                       new_k: str, new_v: str, name=None):
        """Cached GQA decode attention task (reference flash_attn paged
        decode task, tasks/attn.py) — wraps the TP attention module's
        projections + core in one task; returns out + updated cache.

        ``offset`` may be a scalar OR a (B,) per-row vector (continuous
        batching: every row decodes at its own cache position) and
        ``kv_start`` carries the (B,) left-pad boundaries of ragged
        batches — both thread straight into ``_attention_core``'s
        scatter/mask path, so the mega graph serves the same batch
        shapes the plain forward does (ISSUE 11)."""
        def fn(x, p, pos, rc, ck, cv, off, ks):
            o, (nk, nv) = attn_module(p, x, pos, rc, (ck, cv), off,
                                      mode=attn_module.fwd_mode,
                                      kv_start=ks)
            return o, nk, nv
        return self.graph.add(
            "attention", fn,
            [qkv_norm_x, attn_params, position_ids, rope, cache_k, cache_v,
             offset, kv_start], [out, new_k, new_v], name=name, cost=8)

    def make_attention_sp(self, model, qkv_norm_x: str, attn_params: str,
                          position_ids: str, rope: str, cache_k: str,
                          cache_v: str, offset: str, out: str, new_k: str,
                          new_v: str, table: str | None = None, name=None):
        """Sequence-parallel DECODE attention task: the seq-sharded
        contiguous cache (``table=None``) or the paged pools (``table``
        names the block-table buffer).

        Mirrors ``dense.forward_sp``'s decode layer attention op for op
        — same projections, per-head norms, rope, scalar/per-row KV
        scatter through ``PagedKVCacheManager``'s one address-math home
        (``position_to_slot`` / ``position_to_slot_rows``), and the
        distributed split-KV flash decode — so the mega graph's greedy
        outputs match the plain stream step bit for bit
        (tests/test_scheduler.py). Frozen rows keep the plain path's
        safety story untouched: their writes land on the sentinel page
        (paged) or a lane the next admission overwrites."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from triton_dist_tpu.layers.common import apply_rope
        from triton_dist_tpu.models.kv_cache import PagedKVCacheManager
        from triton_dist_tpu.ops.flash_decode import (
            gqa_fwd_batch_decode, gqa_fwd_batch_decode_paged)

        ap = model.attn
        hq, hkv, d = ap.num_heads, ap.num_kv_heads, ap.head_dim
        eps = model.config.rms_norm_eps
        mesh, sp = model.mesh, model.sp_axis
        world = mesh.shape[sp]
        fd_ctx, fd_impl = model.fd_ctx, model.fd_impl

        def constrain(t):
            # decode keeps everything replicated (forward_sp: hsh/csh/
            # xsh all collapse to P() at S == 1)
            return jax.lax.with_sharding_constraint(
                t, NamedSharding(mesh, P()))

        def fn(x, a, pos, rc, ck, cv, off, *rest):
            tb = rest[0] if rest else None
            b, s = pos.shape
            cos, sin = rc
            q = constrain((x @ a["w_q"]).reshape(b, s, hq, d))
            k = constrain((x @ a["w_k"]).reshape(b, s, hkv, d))
            v = constrain((x @ a["w_v"]).reshape(b, s, hkv, d))
            if ap.qk_norm:
                q = rms_norm(q, a["q_norm"], eps)
                k = rms_norm(k, a["k_norm"], eps)
            q = apply_rope(q, cos, sin, pos)
            k = apply_rope(k, cos, sin, pos)
            kc = constrain(k).astype(ck.dtype)
            vc = constrain(v).astype(cv.dtype)
            if tb is None:
                if off.ndim:
                    rows = jnp.arange(b)
                    ck = ck.at[rows, off].set(kc[:, 0])
                    cv = cv.at[rows, off].set(vc[:, 0])
                else:
                    import jax.lax as lax
                    ck = lax.dynamic_update_slice(ck, kc, (0, off, 0, 0))
                    cv = lax.dynamic_update_slice(cv, vc, (0, off, 0, 0))
                att = gqa_fwd_batch_decode(q[:, 0], ck, cv, off + 1,
                                           fd_ctx, impl=fd_impl)
            else:
                spd = ck.shape[0] // world
                if off.ndim:
                    g, ip = PagedKVCacheManager.position_to_slot_rows(
                        tb, off, ck.shape[1], spd)
                else:
                    g, ip = PagedKVCacheManager.position_to_slot(
                        tb, off, ck.shape[1], spd)
                ck = ck.at[g, ip].set(kc[:, 0])
                cv = cv.at[g, ip].set(vc[:, 0])
                att = gqa_fwd_batch_decode_paged(q[:, 0], ck, cv, tb,
                                                 off + 1, fd_ctx,
                                                 impl=fd_impl)
            att = att[:, None].reshape(b, s, hq * d)
            o = constrain((att @ a["w_o"]).astype(x.dtype))
            return o, ck, cv

        inputs = [qkv_norm_x, attn_params, position_ids, rope, cache_k,
                  cache_v, offset]
        if table is not None:
            inputs.append(table)
        return self.graph.add("attention", fn, inputs,
                              [out, new_k, new_v], name=name, cost=8)

    def make_embedding(self, table: str, ids: str, out: str, name=None):
        def fn(t, i):
            b, s = i.shape
            return t[i].reshape(b * s, t.shape[-1])
        return self.graph.add("embedding", fn, [table, ids], [out],
                              name=name)[0]

    # -- sp-family tasks (forward_sp decode parity, ISSUE 11) --------------
    # The sp/paged engines keep (B, S, H) activations and plain
    # XLA-sharded matmuls (the weight shardings drive the collectives),
    # so their mega graph records forward_sp's exact decode ops rather
    # than the TP fused-op tasks above — op-for-op parity is what makes
    # mega-in-scheduler greedy outputs bit-identical to the plain path.

    def _constrain_replicated(self, t):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        return jax.lax.with_sharding_constraint(
            t, NamedSharding(self.mesh, P()))

    def make_embedding_sp(self, table: str, ids: str, out: str, name=None):
        """(B, S, H) embedding lookup with forward_sp's decode
        activation constraint (xsh = P() at S == 1)."""
        def fn(t, i):
            return self._constrain_replicated(t[i])
        return self.graph.add("embedding", fn, [table, ids], [out],
                              name=name)[0]

    def make_linear_sp(self, x: str, w: str, out: str, name=None) -> str:
        """Plain XLA-sharded linear on (B, S, H) activations —
        forward_sp's gate/up projections."""
        return self.graph.add("linear", lambda xv, wv: xv @ wv, [x, w],
                              [out], name=name, cost=4)[0]

    def make_silu_mul_sp(self, gate: str, up: str, out: str,
                         name=None) -> str:
        """``_sp_ffn``'s activation: silu in f32 cast back BEFORE the
        multiply. (:meth:`make_silu_mul` multiplies in f32 — a
        different rounding under bf16; sp parity needs this exact op
        order.)"""
        def fn(g, u):
            import jax
            return jax.nn.silu(g.astype(jnp.float32)).astype(g.dtype) * u
        return self.graph.add("activation", fn, [gate, up], [out],
                              name=name)[0]

    def make_linear_down_sp(self, x: str, w: str, out: str,
                            name=None) -> str:
        """``_sp_ffn``'s down projection with its replicated-output
        constraint (decode xsh = P())."""
        def fn(xv, wv):
            return self._constrain_replicated((xv @ wv).astype(xv.dtype))
        return self.graph.add("linear", fn, [x, w], [out], name=name,
                              cost=6)[0]

    def make_lm_head_sp(self, x: str, w: str, out: str, name=None):
        """forward_sp's LM head: einsum over (B, S, H) in f32."""
        def fn(xv, wv):
            return jnp.einsum("bsh,vh->bsv", xv.astype(jnp.float32),
                              wv.astype(jnp.float32))
        return self.graph.add("linear", fn, [x, w], [out], name=name,
                              cost=4)[0]

    def make_lm_head(self, x: str, w: str, out: str, name=None):
        def fn(xv, wv):
            return jnp.dot(xv.astype(jnp.float32),
                           wv.T.astype(jnp.float32))
        return self.graph.add("linear", fn, [x, w], [out], name=name,
                              cost=4)[0]

    # -- finalize ----------------------------------------------------------
    def compile(self, input_names, output_names, jit: bool = True,
                order_policy: str = "topo"):
        """Resolve deps and emit the step executor (reference
        ``ModelBuilder.compile`` building queues + codegen'ing the
        persistent kernel, model_builder.py / code_generator.py:153).
        ``order_policy="heft"`` emits in critical-path priority order
        (TaskGraph.priority_order)."""
        import jax
        run = self.graph.make_executor(input_names, output_names,
                                       order_policy=order_policy)
        return jax.jit(run) if jit else run
