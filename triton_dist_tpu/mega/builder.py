"""ModelBuilder: records decoder ops layer-by-layer into a TaskGraph.

TPU-native redesign of the reference's ``ModelBuilder``
(python/triton_dist/mega_triton_kernel/models/model_builder.py:408:
``make_linear / make_rms_norm / make_activation / make_flash_decode /
make_allreduce ...`` task builders, tasks/{linear,attn,norm,activation,
elementwise,allreduce}.py) — the recorded graph compiles to ONE jitted
program per step instead of one persistent interpreted kernel.

Ops carry the same roles as the reference task kinds: linear (TP
col/row), rmsnorm, activation (silu·mul), elementwise add, attention
(cached GQA decode), allreduce epilogue (fused gemm_ar). The barrier /
prefetch task kinds collapse: XLA inserts synchronization and HBM→VMEM
prefetch itself.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
from jax.sharding import Mesh

from triton_dist_tpu.layers.common import (
    col_parallel_matmul, rms_norm, row_parallel_matmul_ar)
from triton_dist_tpu.mega.task_graph import TaskGraph
from triton_dist_tpu.ops.gemm_reduce_scatter import (
    create_gemm_rs_context, gemm_ar)


class ModelBuilder:
    """Record ops into a TaskGraph with TP-aware linear tasks."""

    def __init__(self, mesh: Mesh | None = None, axis: str = "tp",
                 impl: str = "pallas", rms_eps: float = 1e-6):
        if mesh is None:
            from triton_dist_tpu.runtime.dist import get_mesh
            mesh = get_mesh()
        self.mesh, self.axis = mesh, axis
        self.impl = impl
        self.rms_eps = rms_eps
        self.graph = TaskGraph()
        self.rs_ctx = create_gemm_rs_context(mesh, axis)

    # -- task builders (reference tasks/*.py) ------------------------------
    def make_rms_norm(self, x: str, w: str, out: str, name=None) -> str:
        fn = functools.partial(rms_norm, eps=self.rms_eps)
        return self.graph.add("rmsnorm", fn, [x, w], [out], name=name)[0]

    def make_linear_col(self, x: str, w: str, out: str, name=None) -> str:
        """Column-parallel GEMM: replicated (M,K) @ col-sharded (K,N/w)."""
        fn = functools.partial(col_parallel_matmul, mesh=self.mesh,
                               axis=self.axis)
        return self.graph.add("linear", fn, [x, w], [out], name=name,
                              cost=4)[0]

    def make_linear_ar(self, x: str, w: str, out: str, name=None) -> str:
        """Row-parallel GEMM + AllReduce epilogue (reference allreduce
        task over symm ptrs ≙ fused gemm_ar kernel)."""
        if self.impl == "xla":
            fn = functools.partial(row_parallel_matmul_ar, mesh=self.mesh,
                                   axis=self.axis)
        else:
            def fn(xv, wv):
                return gemm_ar(xv, wv, self.rs_ctx, impl=self.impl)
        return self.graph.add("linear_ar", fn, [x, w], [out], name=name,
                              cost=6)[0]

    def make_silu_mul(self, gate: str, up: str, out: str, name=None) -> str:
        def fn(g, u):
            import jax
            return (jax.nn.silu(g.astype(jnp.float32)) *
                    u.astype(jnp.float32)).astype(g.dtype)
        return self.graph.add("activation", fn, [gate, up], [out],
                              name=name)[0]

    def make_add(self, a: str, b: str, out: str, name=None) -> str:
        return self.graph.add("elementwise", lambda x, y: x + y, [a, b],
                              [out], name=name)[0]

    def make_attention(self, attn_module, qkv_norm_x: str, attn_params: str,
                       position_ids: str, rope: str, cache_k: str,
                       cache_v: str, offset: str, out: str, new_k: str,
                       new_v: str, name=None):
        """Cached GQA decode attention task (reference flash_attn paged
        decode task, tasks/attn.py) — wraps the TP attention module's
        projections + core in one task; returns out + updated cache."""
        def fn(x, p, pos, rc, ck, cv, off):
            o, (nk, nv) = attn_module(p, x, pos, rc, (ck, cv), off,
                                      mode=attn_module.fwd_mode)
            return o, nk, nv
        return self.graph.add(
            "attention", fn,
            [qkv_norm_x, attn_params, position_ids, rope, cache_k, cache_v,
             offset], [out, new_k, new_v], name=name, cost=8)

    def make_embedding(self, table: str, ids: str, out: str, name=None):
        def fn(t, i):
            b, s = i.shape
            return t[i].reshape(b * s, t.shape[-1])
        return self.graph.add("embedding", fn, [table, ids], [out],
                              name=name)[0]

    def make_lm_head(self, x: str, w: str, out: str, name=None):
        def fn(xv, wv):
            return jnp.dot(xv.astype(jnp.float32),
                           wv.T.astype(jnp.float32))
        return self.graph.add("linear", fn, [x, w], [out], name=name,
                              cost=4)[0]

    # -- finalize ----------------------------------------------------------
    def compile(self, input_names, output_names, jit: bool = True,
                order_policy: str = "topo"):
        """Resolve deps and emit the step executor (reference
        ``ModelBuilder.compile`` building queues + codegen'ing the
        persistent kernel, model_builder.py / code_generator.py:153).
        ``order_policy="heft"`` emits in critical-path priority order
        (TaskGraph.priority_order)."""
        import jax
        run = self.graph.make_executor(input_names, output_names,
                                       order_policy=order_policy)
        return jax.jit(run) if jit else run
