"""Qwen3-class dense decoder under tensor parallelism.

TPU-native redesign of the reference's ``DenseLLM``
(python/triton_dist/models/dense.py:117-241: HF-weight-loading TP model,
per-layer ``set_fwd(mode)``, ``init_triton_dist_ctx`` allocating the fused
op contexts). Model math follows HF Qwen3: pre-norm decoder blocks with
GQA attention (per-head q/k RMSNorm) + SwiGLU MLP, rotary embeddings,
tied/untied LM head.

Functional shape: the module owns config + layer objects (which own the
fused-op contexts); parameters are a pytree; ``forward`` threads the KV
cache through. ``jax.jit`` of ``forward`` is the CUDA-graph analog
(SURVEY.md §7 stage 7).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu.layers.common import (
    apply_rope, precompute_rope_cache, rms_norm, shard_param)
from triton_dist_tpu.layers.tp_attn import TPAttn
from triton_dist_tpu.layers.tp_mlp import TPMLP
from triton_dist_tpu.models.config import ModelConfig


class DenseLLM:
    """TP Qwen3 decoder (reference models/dense.py:117)."""

    def __init__(self, config: ModelConfig, mesh: Mesh | None = None,
                 axis: str = "tp", fwd_mode: str = "ag_rs",
                 impl: str = "pallas", sp_axis: str | None = None):
        if mesh is None:
            from triton_dist_tpu.runtime.dist import get_mesh
            mesh = get_mesh()
        self.config = config
        self.mesh, self.axis = mesh, axis
        self.fwd_mode = fwd_mode
        self.sp_axis = sp_axis
        if sp_axis is not None:
            # Sequence-parallel contexts (mode="sp"): ring attention for
            # prefill/training, distributed split-KV flash decode over
            # the sequence-sharded cache. With tp > 1 this is a 2-D
            # tp×sp model: heads shard over tp inside the ring
            # (head_axis), weight collectives come from XLA shardings;
            # decode keeps the cache head-replicated (flash decode runs
            # per sp-rank on full heads).
            tp_world = mesh.shape[axis]
            from triton_dist_tpu.ops.flash_decode import (
                create_flash_decode_context)
            from triton_dist_tpu.ops.sp_attention import (
                create_sp_attention_context)
            self.sp_ctx = create_sp_attention_context(
                mesh, sp_axis, causal=True,
                head_axis=axis if tp_world > 1 else None)
            self.fd_ctx = create_flash_decode_context(mesh, sp_axis)
            self.sp_impl = "ring" if impl == "pallas" else "xla"
            self.fd_impl = impl
        c = config
        # One module per role, reused across layers (all layers share
        # shapes; params differ per layer).
        self.attn = TPAttn(c.hidden_size, c.num_attention_heads,
                           c.num_key_value_heads, c.head_dim, mesh=mesh,
                           axis=axis, dtype=c.dtype, fwd_mode=fwd_mode,
                           impl=impl, rms_eps=c.rms_norm_eps,
                           qk_norm=c.qk_norm)
        self.mlp = TPMLP(c.hidden_size, c.intermediate_size, mesh=mesh,
                         axis=axis, dtype=c.dtype, fwd_mode=fwd_mode,
                         impl=impl)
        self.rope_cache = precompute_rope_cache(
            c.head_dim, c.max_position_embeddings, c.rope_theta)

    def set_fwd(self, mode: str):
        """Switch all layers' forward mode (reference per-layer set_fwd,
        models/dense.py:216)."""
        self.fwd_mode = mode
        self.attn.set_fwd(mode)
        self.mlp.set_fwd(mode)

    # -- params ------------------------------------------------------------
    def init(self, key: jax.Array) -> dict:
        c = self.config
        keys = jax.random.split(key, c.num_hidden_layers + 2)
        layers = []
        for i in range(c.num_hidden_layers):
            ka, km = jax.random.split(keys[i])
            layers.append({
                "attn": self.attn.init(ka),
                "mlp": self.mlp.init(km),
                "ln_attn": jnp.ones((c.hidden_size,), c.dtype),
                "ln_mlp": jnp.ones((c.hidden_size,), c.dtype),
            })
        embed = (jax.random.normal(keys[-2], (c.vocab_size, c.hidden_size),
                                   c.dtype) * 0.02)
        params = {
            "embed": embed,
            "layers": layers,
            "final_norm": jnp.ones((c.hidden_size,), c.dtype),
            "lm_head": (embed if c.tie_word_embeddings else
                        jax.random.normal(keys[-1],
                                          (c.vocab_size, c.hidden_size),
                                          c.dtype) * 0.02),
        }
        return self.shard_params(params)

    def shard_params(self, params: dict) -> dict:
        m = self.mesh
        out = {
            "embed": shard_param(params["embed"], m, P()),
            "final_norm": shard_param(params["final_norm"], m, P()),
            "lm_head": shard_param(params["lm_head"], m, P()),
            "layers": [],
        }
        for lp in params["layers"]:
            out["layers"].append({
                "attn": self.attn.shard_params(lp["attn"]),
                "mlp": self.mlp.shard_params(lp["mlp"]),
                "ln_attn": shard_param(lp["ln_attn"], m, P()),
                "ln_mlp": shard_param(lp["ln_mlp"], m, P()),
            })
        return out

    # -- forward -----------------------------------------------------------
    def forward(self, params: dict, input_ids: jax.Array, kv_caches,
                offset, mode: str | None = None, kv_start=None,
                remat: bool = False, block_table=None):
        """input_ids: (B, S) int32; kv_caches: [(k, v)] * L; offset: scalar
        write position. Returns (logits (B, S, V), new_caches).

        The reference's ``inference`` (dense.py:200-241). Activation
        layout: row-sharded (M=B*S over tp) for {xla, ag_rs} — requires
        B*S % world == 0; replicated for {xla_ar, gemm_ar} (decode).

        ``kv_start``: optional (B,) left-pad boundaries for ragged
        batches — rope positions count from each row's first real token
        and attention never sees the pad prefix (Engine.serve_ragged).

        ``remat``: checkpoint each decoder layer — activations are
        recomputed in the backward pass instead of stored, trading
        FLOPs for HBM so long-sequence training fits (models/train.py).
        """
        c = self.config
        mode = mode or self.fwd_mode
        if mode == "sp":
            assert kv_start is None, "mode='sp' has no ragged support yet"
            return self.forward_sp(params, input_ids, kv_caches, offset,
                                   remat=remat, block_table=block_table)
        assert block_table is None, "paged caches need mode='sp'"
        b, s = input_ids.shape
        offset = jnp.asarray(offset, jnp.int32)
        # offset may be a (B,) vector (per-row decode positions —
        # continuous batching, Engine.serve_stream — with S == 1, or
        # the S == k+1 speculative-decoding verify window: the
        # attention core scatters row b's K/V at offset[b]+[0, S) and
        # masks each query position causally at its own absolute
        # position).
        off2d = offset[:, None] if offset.ndim else offset
        position_ids = off2d + jnp.tile(
            jnp.arange(s, dtype=jnp.int32)[None], (b, 1))
        if kv_start is not None:
            position_ids = jnp.maximum(
                position_ids - jnp.asarray(kv_start, jnp.int32)[:, None], 0)

        def layer_body(x, lp, cache):
            h = rms_norm(x, lp["ln_attn"], c.rms_norm_eps)
            a, cache = self.attn(lp["attn"], h, position_ids,
                                 self.rope_cache, cache, offset, mode=mode,
                                 kv_start=kv_start)
            x = x + a
            h = rms_norm(x, lp["ln_mlp"], c.rms_norm_eps)
            x = x + self.mlp(lp["mlp"], h, mode=mode)
            return x, cache

        body = jax.checkpoint(layer_body) if remat else layer_body
        x = params["embed"][input_ids].reshape(b * s, c.hidden_size)
        new_caches = []
        for lp, cache in zip(params["layers"], kv_caches):
            x, cache = body(x, lp, cache)
            new_caches.append(cache)

        x = rms_norm(x, params["final_norm"], c.rms_norm_eps)
        logits = jnp.dot(x.astype(jnp.float32),
                         params["lm_head"].T.astype(jnp.float32))
        return logits.reshape(b, s, c.vocab_size), new_caches

    # -- sequence-parallel forward (long-context path) ---------------------
    def forward_sp(self, params: dict, input_ids: jax.Array, kv_caches,
                   offset, remat: bool = False, block_table=None):
        """Sequence-parallel forward: the long-context path the reference
        serves with ``SpFlashDecodeLayer`` + AG-attention
        (sp_ag_attention_inter_node.py:504, sp_flash_decode_layer.py),
        lifted to the whole model.

        Activations stay (B, S, H) with S sharded over ``sp_axis`` —
        each device holds S/w positions, so max context scales with the
        mesh. With tp > 1 this is a 2-D tp×sp model: projections keep
        their column/row TP shardings (XLA inserts the psums) and the
        ring attention runs on the head-local slice
        (``SpAttentionContext.head_axis``). Prefill/training (S > 1,
        offset must be 0) runs ring SP attention on the
        freshly-projected K/V; decode (S == 1) runs the distributed
        split-KV flash decode over the sequence-sharded,
        head-replicated cache. The cache must be allocated with
        ``KVCacheManager(seq_shard=True, axis=sp_axis)``.

        Differentiable end-to-end in the prefill shape (ring attention
        carries native transpose rules), so ``make_train_step(
        mode="sp")`` trains long sequences with S/w activation memory
        per device on top of the remat option.

        ``block_table``: switches the caches to PAGED pools
        (``PagedKVCacheManager`` layout: per-layer (pool_k, pool_v) of
        (w·slots, page, Hkv, D) dim-0-sharded physical pages plus this
        (w, B, n_pages) table) — prefill scatters the projected K/V
        into the allocated pages, decode writes one position and runs
        the paged distributed flash decode. vLLM-style slot reuse at
        the whole-model level (Engine(paged=True)).
        """
        from jax.sharding import NamedSharding
        from triton_dist_tpu.ops.flash_decode import (
            gqa_fwd_batch_decode, gqa_fwd_batch_decode_paged)
        from triton_dist_tpu.ops.sp_attention import sp_ag_attention
        from triton_dist_tpu.ops.common import nestable_shard_map

        assert self.sp_axis is not None, (
            "build the model with sp_axis=... to use mode='sp' "
            "(DenseLLM and Qwen3MoE share this forward)")
        c = self.config
        b, s = input_ids.shape
        sp = self.sp_axis
        decode = s == 1
        # Chunked prefill (S > 1, offset > 0): the chunk's K/V are
        # written into the cache, then ring attention runs with the
        # CACHE as the rotating KV — q positions offset+[0, S), live KV
        # limited to offset+S (sp_ag_attention q_offset/kv_len). A
        # traced offset conservatively selects the chunked path.
        chunked = (s > 1 and getattr(offset, "ndim", 0) == 0
                   and (isinstance(offset, jax.core.Tracer)
                        or int(offset) != 0))
        offset = jnp.asarray(offset, jnp.int32)
        # (B,) per-row offsets supported for decode (continuous
        # batching, Engine.serve_stream — same contract as the dense tp
        # forward): per-row cache writes, masks, and rope positions.
        # With S > 1 a vector offset is the speculative-decoding verify
        # window (Engine spec steps): row b's S tokens sit at absolute
        # positions offset[b]+[0, S), each scoring against its own
        # causal prefix — a burst of S decode steps in one program.
        burst = offset.ndim == 1 and s > 1
        off2d = offset[:, None] if offset.ndim else offset
        pos = off2d + jnp.tile(jnp.arange(s, dtype=jnp.int32)[None],
                               (b, 1))
        tp = self.sp_ctx.head_axis  # single source of truth (ctor)
        # Burst windows are decode-shaped work (S = k+1 small): keep
        # activations replicated like the decode step, not S-sharded.
        xsh = P() if decode or burst else P(None, sp, None)
        hsh = P() if decode or burst else P(None, sp, tp, None)

        def constrain(t, spec):
            return jax.lax.with_sharding_constraint(
                t, NamedSharding(self.mesh, spec))

        ap = self.attn  # head geometry + qk-norm config live there
        hq, hkv, d = ap.num_heads, ap.num_kv_heads, ap.head_dim
        cos, sin = self.rope_cache
        eps = c.rms_norm_eps

        def layer_body(x, lp, cache):
            a = lp["attn"]
            h = rms_norm(x, lp["ln_attn"], eps)
            q = constrain((h @ a["w_q"]).reshape(b, s, hq, d), hsh)
            k = constrain((h @ a["w_k"]).reshape(b, s, hkv, d), hsh)
            v = constrain((h @ a["w_v"]).reshape(b, s, hkv, d), hsh)
            if ap.qk_norm:
                q = rms_norm(q, a["q_norm"], eps)
                k = rms_norm(k, a["k_norm"], eps)
            q = apply_rope(q, cos, sin, pos)
            k = apply_rope(k, cos, sin, pos)
            ck, cv = cache
            # Align to the cache layout (seq-sharded, head-replicated)
            # BEFORE the write: updating with head-sharded operands
            # forces SPMD into an involuntary full rematerialization.
            # (Training discards new_caches, so XLA dead-code-eliminates
            # this whole write chain — prefill attention reads the
            # just-projected k/v, not the cache.)
            csh = P() if decode or burst else P(None, sp, None, None)
            kc = constrain(k, csh).astype(ck.dtype)
            vc = constrain(v, csh).astype(cv.dtype)
            if block_table is None:
                if burst:
                    # Per-row burst (spec verify window): row b's S
                    # tokens scatter at offset[b]+[0, S); out-of-range
                    # positions (frozen rows) drop out of the scatter.
                    rows = jnp.arange(b)
                    posb = offset[:, None] + jnp.arange(
                        s, dtype=jnp.int32)[None]
                    ck = ck.at[rows[:, None], posb].set(kc)
                    cv = cv.at[rows[:, None], posb].set(vc)
                elif offset.ndim:
                    # Per-row decode positions: scatter one position
                    # per row into its own lane.
                    rows = jnp.arange(b)
                    ck = ck.at[rows, offset].set(kc[:, 0])
                    cv = cv.at[rows, offset].set(vc[:, 0])
                else:
                    ck = jax.lax.dynamic_update_slice(ck, kc,
                                                      (0, offset, 0, 0))
                    cv = jax.lax.dynamic_update_slice(cv, vc,
                                                      (0, offset, 0, 0))
            elif decode or burst:
                # Single-position (or per-row burst) paged write — the
                # address math lives in ONE place
                # (PagedKVCacheManager.position_to_slot*).
                from triton_dist_tpu.models.kv_cache import (
                    PagedKVCacheManager)
                spd = ck.shape[0] // self.mesh.shape[sp]
                if burst:
                    # Spec verify window: position j of row b is
                    # offset[b]+j. Positions past max_seq (frozen rows
                    # at stale offsets, or a live row padded past its
                    # own clamp by a wider batchmate) reroute to the
                    # device-0 SENTINEL page instead of wrapping the
                    # address math into a live block.
                    t_total = ck.shape[1] * block_table.shape[2] \
                        * self.mesh.shape[sp]
                    for j in range(s):
                        posj = offset + j
                        ok = posj < t_total
                        g, ip = \
                            PagedKVCacheManager.position_to_slot_rows(
                                block_table,
                                jnp.minimum(posj, t_total - 1),
                                ck.shape[1], spd)
                        g = jnp.where(ok, g, spd - 1)
                        ck = ck.at[g, ip].set(kc[:, j])
                        cv = cv.at[g, ip].set(vc[:, j])
                elif offset.ndim:
                    g, ip = PagedKVCacheManager.position_to_slot_rows(
                        block_table, offset, ck.shape[1], spd)
                    ck = ck.at[g, ip].set(kc[:, 0])
                    cv = cv.at[g, ip].set(vc[:, 0])
                else:
                    g, ip = PagedKVCacheManager.position_to_slot(
                        block_table, offset, ck.shape[1], spd)
                    ck = ck.at[g, ip].set(kc[:, 0])
                    cv = cv.at[g, ip].set(vc[:, 0])
            elif chunked:
                # Paged chunked prefill (prefix-cache suffix admission,
                # ISSUE 6): scatter ONLY positions offset+[0, S) into
                # the row's private pages — a full-table scatter here
                # would zero the shared cached-prefix blocks out from
                # under every other request referencing them.
                from triton_dist_tpu.models.kv_cache import (
                    PagedKVCacheManager)
                spd = ck.shape[0] // self.mesh.shape[sp]
                posn = offset + jnp.arange(s, dtype=jnp.int32)
                g, ip = PagedKVCacheManager.position_to_slot(
                    block_table, posn, ck.shape[1], spd)   # (S, B), (S,)
                ck = ck.at[g, ip[:, None]].set(kc.swapaxes(0, 1))
                cv = cv.at[g, ip[:, None]].set(vc.swapaxes(0, 1))
            else:
                ck = self._paged_scatter(ck, kc, block_table,
                                         nestable_shard_map)
                cv = self._paged_scatter(cv, vc, block_table,
                                         nestable_shard_map)
            if decode:
                if block_table is None:
                    att = gqa_fwd_batch_decode(q[:, 0], ck, cv,
                                               offset + 1, self.fd_ctx,
                                               impl=self.fd_impl)
                else:
                    att = gqa_fwd_batch_decode_paged(
                        q[:, 0], ck, cv, block_table, offset + 1,
                        self.fd_ctx, impl=self.fd_impl)
                att = att[:, None]
            elif burst:
                # Spec verify window: query position j runs the SAME
                # per-row flash decode the sequential stream step runs
                # — kv_len = offset+j+1 masks every later window
                # position, so logits are bit-identical to S sequential
                # decode steps (the spec acceptance contract,
                # docs/serving.md "Speculative decoding"). S = k+1 is
                # small, so the unrolled loop stays one program.
                atts = []
                for j in range(s):
                    if block_table is None:
                        atts.append(gqa_fwd_batch_decode(
                            q[:, j], ck, cv, offset + j + 1,
                            self.fd_ctx, impl=self.fd_impl))
                    else:
                        atts.append(gqa_fwd_batch_decode_paged(
                            q[:, j], ck, cv, block_table,
                            offset + j + 1, self.fd_ctx,
                            impl=self.fd_impl))
                att = jnp.stack(atts, axis=1)
            elif chunked:
                # Cache-aware chunk: attend over the updated cache
                # (prefix [0, offset) + this chunk), ring or xla. With a
                # STATIC offset (the scheduler's common case) the
                # rotated KV is sliced to the world-aligned live prefix
                # — a 512-token chunk at the front of a 64k cache must
                # not ppermute 64k mostly-masked positions per layer.
                if block_table is not None:
                    # Paged: reconstruct the contiguous per-row view —
                    # shared prefix blocks and this chunk's fresh
                    # writes land in one (B, T, Hkv, D) tensor; the
                    # kv_len mask below hides positions past the live
                    # length (gathered_view's docstring has the cost
                    # story).
                    from triton_dist_tpu.models.kv_cache import (
                        PagedKVCacheManager)
                    w = self.mesh.shape[sp]
                    csh = P(None, sp, None, None)
                    ck_att = constrain(PagedKVCacheManager.gathered_view(
                        ck, block_table, w), csh)
                    cv_att = constrain(PagedKVCacheManager.gathered_view(
                        cv, block_table, w), csh)
                else:
                    ck_att, cv_att = ck, cv
                if (block_table is None
                        and not isinstance(offset, jax.core.Tracer)):
                    # Slice the cache to the live prefix, rounded up to
                    # a length sp_ag_attention accepts: a multiple of
                    # BOTH the cache shard size (so the slice lands on
                    # shard boundaries) and world (its t % world == 0
                    # contract — advisor r3: per alone breaks when
                    # t_cache//world is not itself a world multiple).
                    # The sliced tensor is re-partitioned over the sp
                    # axis by the shard_map in_specs (data movement
                    # proportional to t_live, still far cheaper than
                    # ring-attending the full mostly-masked cache).
                    import math
                    world_sp = self.mesh.shape[sp]
                    t_cache = ck.shape[1]
                    if t_cache % world_sp == 0:
                        per = t_cache // world_sp
                        step = math.lcm(per, world_sp)
                        t_live = -(-(int(offset) + s) // step) * step
                        if t_live < t_cache:
                            ck_att = ck[:, :t_live]
                            cv_att = cv[:, :t_live]
                att = sp_ag_attention(
                    q, ck_att, cv_att, self.sp_ctx,
                    impl=("xla" if self.sp_impl == "xla" else "ring"),
                    q_offset=offset, kv_len=offset + s)
            else:
                # Ring attention over the JUST-projected K/V (single-
                # shot prefill from offset 0 — the Engine's fast path).
                att = sp_ag_attention(q, k, v, self.sp_ctx,
                                      impl=self.sp_impl)
            att = att.reshape(b, s, hq * d)
            x = x + constrain((att @ a["w_o"]).astype(x.dtype), xsh)
            h = rms_norm(x, lp["ln_mlp"], eps)
            x = x + self._sp_ffn(lp, h, constrain, xsh)
            return x, (ck, cv)

        body = jax.checkpoint(layer_body) if remat else layer_body
        x = constrain(params["embed"][input_ids], xsh)
        new_caches = []
        for lp, cache in zip(params["layers"], kv_caches):
            x, cache = body(x, lp, cache)
            new_caches.append(cache)

        x = rms_norm(x, params["final_norm"], eps)
        logits = jnp.einsum("bsh,vh->bsv", x.astype(jnp.float32),
                            params["lm_head"].astype(jnp.float32))
        return logits, new_caches

    def _sp_ffn(self, lp, h, constrain, xsh):
        """FFN block of the sp forward on (B, S, H) activations — the
        hook Qwen3MoE overrides with its row-local MoE (the rest of
        forward_sp is model-agnostic and shared)."""
        m = lp["mlp"]
        gate = h @ m["w_gate"]
        up = h @ m["w_up"]
        act = jax.nn.silu(gate.astype(jnp.float32)).astype(h.dtype) * up
        return constrain((act @ m["w_down"]).astype(h.dtype), xsh)

    def _paged_scatter(self, pool, kv, table, shard_map_fn):
        """Scatter a (B, S, Hkv, D) seq-sharded prefill K/V into the
        paged pool: stage into the cache's position space (zeros past
        S), then each device moves its t_loc positions into its
        allocated page slots — a purely local scatter (the allocator
        guarantees distinct (row, page) → distinct slots).

        Known cost: staging + scatter are O(max_seq) per layer, not
        O(S) — a short prompt in a large-capacity engine rewrites the
        zero tail of every allocated page. Acceptable while prefill is
        single-shot (one scatter per serve); a page-granular scatter
        bounded by ceil(S/page) needs per-device drop-masked indices
        (the position spaces of K (S/w blocks) and the cache (t_loc
        blocks) disagree when S < capacity) — optimization candidate.
        """
        sp = self.sp_axis
        world = self.mesh.shape[sp]
        b, s = kv.shape[0], kv.shape[1]
        page, hkv, d = pool.shape[1], pool.shape[2], pool.shape[3]
        n_pages = table.shape[2]
        t_total = page * n_pages * world
        assert s <= t_total, f"prefill {s} > paged capacity {t_total}"
        staged = jnp.zeros((b, t_total, hkv, d), pool.dtype)
        staged = jax.lax.with_sharding_constraint(
            staged, jax.sharding.NamedSharding(self.mesh,
                                               P(None, sp, None, None)))
        staged = jax.lax.dynamic_update_slice(staged, kv, (0, 0, 0, 0))

        def local(pool_l, st_l, tb_l):
            pages = st_l.reshape(b, n_pages, page, hkv, d)
            return pool_l.at[tb_l.reshape(-1)].set(
                pages.reshape(b * n_pages, page, hkv, d))

        return shard_map_fn(
            local, mesh=self.mesh,
            in_specs=(P(sp), P(None, sp), P(sp)),
            out_specs=P(sp), check_vma=False)(pool, staged, table)

    # -- HF weights --------------------------------------------------------
    def load_hf_state_dict(self, state: dict) -> dict:
        """Map a HF Qwen3 state dict (name → array) to our params pytree
        and shard (the reference shards at load, dense.py:150-168,
        tp_mlp.py:72-96). Accepts numpy/jnp arrays or anything
        np.asarray-able (torch tensors via ``.numpy()``)."""
        c = self.config

        def get(name):
            a = state[name]
            if hasattr(a, "detach"):
                a = a.detach().cpu().numpy()
            return jnp.asarray(np.asarray(a), c.dtype)

        def lin(name):
            # HF nn.Linear keeps (out, in); we use (in, out).
            return get(name).T

        layers = []
        for i in range(c.num_hidden_layers):
            p = f"model.layers.{i}."
            attn = {
                "w_q": lin(p + "self_attn.q_proj.weight"),
                "w_k": lin(p + "self_attn.k_proj.weight"),
                "w_v": lin(p + "self_attn.v_proj.weight"),
                "w_o": lin(p + "self_attn.o_proj.weight"),
            }
            if c.qk_norm:  # absent in Llama-3 / Seed-OSS checkpoints
                attn["q_norm"] = get(p + "self_attn.q_norm.weight")
                attn["k_norm"] = get(p + "self_attn.k_norm.weight")
            layers.append({
                "attn": attn,
                "mlp": {
                    "w_gate": lin(p + "mlp.gate_proj.weight"),
                    "w_up": lin(p + "mlp.up_proj.weight"),
                    "w_down": lin(p + "mlp.down_proj.weight"),
                },
                "ln_attn": get(p + "input_layernorm.weight"),
                "ln_mlp": get(p + "post_attention_layernorm.weight"),
            })
        embed = get("model.embed_tokens.weight")
        params = {
            "embed": embed,
            "layers": layers,
            "final_norm": get("model.norm.weight"),
            "lm_head": (embed if c.tie_word_embeddings else
                        get("lm_head.weight")),
        }
        return self.shard_params(params)
